#!/usr/bin/env bash
# Build the native engine and copy its binaries to ./bin — entry-point
# parity with the reference's install.sh (reference install.sh:1-27):
#   ./install.sh [dev|fast]     (default: fast)
set -euo pipefail

flavor="${1:-fast}"
case "$flavor" in
  dev|fast) ;;
  *) echo "usage: $0 [dev|fast]" >&2; exit 2 ;;
esac

cd "$(dirname "$0")"
make -C native "$flavor" -j"$(nproc)"
mkdir -p bin
for prog in make_cpd_auto gen_distribute_conf fifo_auto; do
  cp "native/build/$flavor/bin/$prog" bin/
done
echo "installed $flavor binaries to ./bin"
