"""Wire-format round-trips and the head-side transfer script.

The FIFO wire schema is the reference's de-facto RPC contract
(``process_query.py:66-111``); these tests pin it.
"""

import numpy as np
import pytest

from distributed_oracle_search_tpu.transport.wire import (
    ENGINE_STAT_FIELDS, Request, RuntimeConfig, StatsRow,
    read_query_file, read_results_file, results_file_for,
    write_query_file, write_results_file,
)
from distributed_oracle_search_tpu.transport.fifo import make_script


def test_runtime_config_roundtrip():
    rc = RuntimeConfig(hscale=1.5, fscale=0.2, time=123456789, itrs=3,
                       k_moves=7, threads=4, verbose=2, debug=True,
                       thread_alloc=1, no_cache=True)
    assert RuntimeConfig.from_json(rc.to_json()) == rc


def test_runtime_config_ignores_unknown_keys():
    rc = RuntimeConfig.from_json('{"hscale": 2.0, "future_knob": 1}')
    assert rc.hscale == 2.0


def test_runtime_config_trace_id_wire_extension():
    """trace_id rides the wire like extract does: preserved by a new
    peer, defaulted when an old-schema peer omits it (the symmetric
    unknown-key filter keeps both directions compatible)."""
    rc = RuntimeConfig(trace_id="deadbeef/w1.d0")
    assert RuntimeConfig.from_json(rc.to_json()).trace_id == \
        "deadbeef/w1.d0"
    assert RuntimeConfig.from_json('{"hscale": 1.0}').trace_id == ""


def test_runtime_config_results_wire_extension():
    """``results`` (the serving per-query-answers sidecar ask) follows
    the same compat contract as ``extract``/``trace_id``: preserved by a
    new peer, defaulted False when an old-schema peer omits it."""
    rc = RuntimeConfig(results=True)
    assert RuntimeConfig.from_json(rc.to_json()).results is True
    assert RuntimeConfig.from_json('{"hscale": 1.0}').results is False


def test_results_file_roundtrip(tmp_path):
    path = results_file_for(str(tmp_path / "query.host0"))
    assert path.endswith(".results")
    cost = np.array([0, 7, 123456], np.int64)
    plen = np.array([0, 3, 41], np.int64)
    fin = np.array([True, True, False])
    write_results_file(path, cost, plen, fin)
    rc, rp, rf = read_results_file(path)
    assert (rc == cost).all() and (rp == plen).all() and (rf == fin).all()
    assert rf.dtype == bool


def test_results_file_roundtrip_empty(tmp_path):
    path = str(tmp_path / "query.empty.results")
    write_results_file(path, np.zeros(0, np.int64), np.zeros(0, np.int64),
                       np.zeros(0, bool))
    rc, rp, rf = read_results_file(path)
    assert len(rc) == len(rp) == len(rf) == 0


def test_results_file_rejects_truncated(tmp_path):
    path = str(tmp_path / "query.bad.results")
    with open(path, "w") as f:
        f.write("3\n1 2 1\n")
    with pytest.raises(ValueError, match="header says"):
        read_results_file(path)


def test_request_roundtrip():
    req = Request(RuntimeConfig(), "/nfs/query.host3", "/nfs/answer.host3",
                  "/data/melb.diff")
    back = Request.decode(req.encode())
    assert back == req
    assert req.encode().count("\n") == 2  # exactly two wire lines


def test_request_decode_rejects_short():
    with pytest.raises(ValueError):
        Request.decode("{}")


def test_stats_row_roundtrip():
    row = StatsRow(n_expanded=10, n_inserted=1, n_touched=5, n_updated=2,
                   n_surplus=0, plen=42, finished=5, t_receive=0.25,
                   t_astar=1.5, t_search=1.75)
    back = StatsRow.decode(row.encode())
    assert back == row
    assert len(row.encode().split(",")) == len(ENGINE_STAT_FIELDS)


def test_stats_row_decode_rejects_bad_width():
    with pytest.raises(ValueError):
        StatsRow.decode("1,2,3")


def test_stats_as_list_appends_head_fields():
    row = StatsRow(plen=9, finished=3)
    full = row.as_list(t_prepare=0.1, t_partition=0.2, size=3)
    assert full[-3:] == [0.1, 0.2, 3]
    assert len(full) == len(ENGINE_STAT_FIELDS) + 3


def test_query_file_roundtrip(tmp_path):
    q = np.array([[1, 2], [3, 4], [100000, 7]], np.int64)
    path = str(tmp_path / "query.host0")
    write_query_file(path, q)
    assert (read_query_file(path) == q).all()
    # header line = count (reference process_query.py:93-96)
    assert open(path).readline().strip() == "3"


def test_query_file_empty(tmp_path):
    path = str(tmp_path / "query.empty")
    write_query_file(path, np.zeros((0, 2), np.int64))
    assert read_query_file(path).shape == (0, 2)


def test_query_file_count_mismatch(tmp_path):
    path = str(tmp_path / "query.bad")
    with open(path, "w") as f:
        f.write("2\n1 2\n")
    with pytest.raises(ValueError):
        read_query_file(path)


def test_make_script_shape():
    req = Request(RuntimeConfig(), "/nfs/q", "/nfs/a", "-")
    script = make_script(req, "/tmp/worker0.fifo")
    # mkfifo answer; heredoc into command fifo; cat answer; rm answer —
    # the reference's transfer script shape (process_query.py:71-77)
    assert "mkfifo /nfs/a" in script
    assert "cat > /tmp/worker0.fifo" in script
    assert "cat /nfs/a" in script
    assert "rm -f /nfs/a" in script
    assert "/nfs/q /nfs/a -" in script


def test_fail_sentinel_roundtrip():
    row = StatsRow.failed()
    assert row.encode_wire() == "FAIL"
    back = StatsRow.decode(row.encode_wire())
    assert not back.ok


def test_success_row_encode_wire_is_csv():
    row = StatsRow(plen=5, finished=2)
    assert row.encode_wire() == row.encode()
    assert StatsRow.decode(row.encode_wire()).ok


def test_send_fails_fast_without_resident_worker(tmp_path):
    """No server on the command FIFO -> failure row, no hang (the script's
    [ -p ] guard)."""
    from distributed_oracle_search_tpu.transport.fifo import send
    req = Request(RuntimeConfig(), str(tmp_path / "q"),
                  str(tmp_path / "a"), "-")
    row = send("localhost", req, str(tmp_path / "no-such.fifo"), timeout=10)
    assert not row.ok
