"""Worker-mesh parity suite: one worker, one mesh (DOS_MESH_DEVICES).

The mesh engine must be invisible in the answers: every lane count in
{1, 2, 4, 8} (the conftest's 8 virtual CPU devices) must produce
BIT-identical results to the single-device engine across the walk
(both kernels — XLA and the Pallas-fused one in interpret mode), the
lane-parallel CPD build (same block bytes, same digests), and the
``mat`` family's on-mesh collective join. ``DOS_MESH_DEVICES`` unset
or 1 is the legacy path — no mesh object, no mesh counters moving.
"""

import glob
import hashlib
import json
import os
import types

import jax
import numpy as np
import pytest

from distributed_oracle_search_tpu.data import synth_diff, synth_scenario
from distributed_oracle_search_tpu.data.formats import write_diff
from distributed_oracle_search_tpu.models.cpd import (
    CPDOracle, build_worker_shard,
)
from distributed_oracle_search_tpu.obs import fleet
from distributed_oracle_search_tpu.obs import metrics as obs_metrics
from distributed_oracle_search_tpu.parallel.mesh import (
    LANE_AXIS, make_mesh, make_worker_mesh, mesh_devices,
)
from distributed_oracle_search_tpu.parallel.partition import (
    DistributionController,
)
from distributed_oracle_search_tpu.traffic.families import QueryFamilies
from distributed_oracle_search_tpu.transport.wire import RuntimeConfig
from distributed_oracle_search_tpu.worker.engine import ShardEngine

pytestmark = pytest.mark.mesh

LANES = (1, 2, 4, 8)


@pytest.fixture(scope="module")
def dc1(toy_graph):
    return DistributionController("tpu", None, 1, toy_graph.n)


@pytest.fixture(scope="module")
def shard_dir(toy_graph, dc1, tmp_path_factory):
    d = str(tmp_path_factory.mktemp("mesh-shard"))
    build_worker_shard(toy_graph, dc1, 0, d, chunk=16)
    return d


@pytest.fixture(scope="module")
def diff_file(toy_graph, tmp_path_factory):
    d = tmp_path_factory.mktemp("mesh-diff")
    path = str(d / "t.diff")
    write_diff(path, *synth_diff(toy_graph, frac=0.3, seed=3))
    return path


@pytest.fixture(scope="module")
def walk_queries(toy_graph, toy_queries):
    """Scenario plus the awkward rows: zero-length (s==t) and
    duplicate pairs — the dedup/unsort machinery must survive lanes."""
    q = np.asarray(toy_queries, np.int64)
    extra = np.array([[3, 3], [0, 0], q[0].tolist(), q[0].tolist(),
                      q[5].tolist()], np.int64)
    return np.concatenate([q, extra], axis=0)


@pytest.fixture(scope="module")
def baseline(toy_graph, dc1, shard_dir, walk_queries, diff_file):
    """Single-device engine answers: free-flow and diffed."""
    eng = ShardEngine(toy_graph, dc1, 0, shard_dir)
    assert eng.mesh is None        # conftest env carries no mesh knob
    rc = RuntimeConfig()
    free = eng.answer(walk_queries, rc)[:3]
    diffed = eng.answer(walk_queries, rc, diff_file)[:3]
    return free, diffed


def _lane_engine(monkeypatch, lanes, *args, **kwargs):
    monkeypatch.setenv("DOS_MESH_DEVICES", str(lanes))
    eng = ShardEngine(*args, **kwargs)
    assert eng.n_lanes == lanes
    assert (eng.mesh is None) == (lanes == 1)
    return eng


# ------------------------------------------------------ knob resolution

def test_mesh_devices_resolution(monkeypatch):
    monkeypatch.delenv("DOS_MESH_DEVICES", raising=False)
    assert mesh_devices() == 1
    for raw, want in (("1", 1), ("0", 1), ("-3", 1), ("bogus", 1),
                      ("2", 2), ("3", 2), ("8", 8), ("64", 8)):
        monkeypatch.setenv("DOS_MESH_DEVICES", raw)
        assert mesh_devices() == want, (raw, want)


def test_make_worker_mesh_legacy_is_none(monkeypatch):
    monkeypatch.delenv("DOS_MESH_DEVICES", raising=False)
    assert make_worker_mesh() is None
    monkeypatch.setenv("DOS_MESH_DEVICES", "1")
    assert make_worker_mesh() is None
    monkeypatch.setenv("DOS_MESH_DEVICES", "4")
    mesh = make_worker_mesh()
    assert mesh is not None and mesh.shape[LANE_AXIS] == 4


# -------------------------------------------------------- walk parity

@pytest.mark.parametrize("lanes", LANES)
def test_walk_parity_xla(monkeypatch, toy_graph, dc1, shard_dir,
                         walk_queries, diff_file, baseline, lanes):
    """Mesh sizes 1/2/4/8 bit-identical to the single-device engine,
    free-flow AND diffed, duplicates/zero-length included."""
    eng = _lane_engine(monkeypatch, lanes, toy_graph, dc1, 0, shard_dir)
    rc = RuntimeConfig()
    free, diffed = baseline
    for want, got in ((free, eng.answer(walk_queries, rc)[:3]),
                      (diffed,
                       eng.answer(walk_queries, rc, diff_file)[:3])):
        for a, b in zip(want, got):
            assert np.asarray(a).dtype == np.asarray(b).dtype
            np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize("lanes", (2, 8))
def test_walk_parity_pallas_interpret(monkeypatch, toy_graph, dc1,
                                      shard_dir, walk_queries,
                                      baseline, lanes):
    """The Pallas-fused kernel runs per lane unchanged (interpret mode
    on CPU) — still bit-identical to the XLA single-device answers."""
    monkeypatch.setenv("DOS_WALK_KERNEL", "pallas")
    eng = _lane_engine(monkeypatch, lanes, toy_graph, dc1, 0, shard_dir)
    got = eng.answer(walk_queries, RuntimeConfig())[:3]
    for a, b in zip(baseline[0], got):
        np.testing.assert_array_equal(a, b)


def test_walk_tiny_batch_pads_to_lanes(monkeypatch, toy_graph, dc1,
                                       shard_dir, walk_queries,
                                       baseline):
    """A batch smaller than the lane count pads up (valid=False lanes)
    instead of falling off the mesh path or crashing."""
    eng = _lane_engine(monkeypatch, 8, toy_graph, dc1, 0, shard_dir)
    base = ShardEngine(toy_graph, dc1, 0, shard_dir)
    rc = RuntimeConfig()
    for a, b in zip(base.answer(walk_queries[:2], rc)[:3],
                    eng.answer(walk_queries[:2], rc)[:3]):
        np.testing.assert_array_equal(a, b)


def test_walk_deadline_chunked_under_lanes(monkeypatch, toy_graph, dc1,
                                           shard_dir, walk_queries):
    """The ns-budget chunked path splits each chunk over lanes; a
    generous budget answers everything, bit-identical."""
    base = ShardEngine(toy_graph, dc1, 0, shard_dir)
    eng = _lane_engine(monkeypatch, 4, toy_graph, dc1, 0, shard_dir)
    base.astar_chunk = eng.astar_chunk = 16       # force chunking
    rc = RuntimeConfig(time=10**13)
    for a, b in zip(base.answer(walk_queries, rc)[:3],
                    eng.answer(walk_queries, rc)[:3]):
        np.testing.assert_array_equal(a, b)


def test_extract_and_sig_under_lanes(monkeypatch, toy_graph, dc1,
                                     shard_dir, walk_queries):
    """--extract path prefixes and sig_k signatures are unchanged by
    the lane split (extraction runs on the lane-replicated table)."""
    base = ShardEngine(toy_graph, dc1, 0, shard_dir)
    eng = _lane_engine(monkeypatch, 4, toy_graph, dc1, 0, shard_dir)
    rc = RuntimeConfig(extract=True, k_moves=6)
    for a, b in zip(base.answer(walk_queries, rc)[:3],
                    eng.answer(walk_queries, rc)[:3]):
        np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(base.last_paths[0], eng.last_paths[0])
    np.testing.assert_array_equal(base.last_paths[1], eng.last_paths[1])


def test_mesh_metrics_move_only_on_mesh(monkeypatch, toy_graph, dc1,
                                        shard_dir, walk_queries):
    def _counters():
        snap = obs_metrics.REGISTRY.snapshot()
        return (snap["counters"].get("mesh_walk_batches_total", 0),
                snap["gauges"].get("mesh_devices", 0))

    monkeypatch.delenv("DOS_MESH_DEVICES", raising=False)
    legacy = ShardEngine(toy_graph, dc1, 0, shard_dir)
    before, gauge = _counters()
    assert gauge == 1                       # legacy engine reports 1
    legacy.answer(walk_queries, RuntimeConfig())
    assert _counters()[0] == before         # no mesh batches booked
    eng = _lane_engine(monkeypatch, 4, toy_graph, dc1, 0, shard_dir)
    eng.answer(walk_queries, RuntimeConfig())
    after, gauge = _counters()
    assert after > before and gauge == 4


# -------------------------------------------------------- build parity

def _digests(d):
    return {os.path.basename(p):
            hashlib.md5(open(p, "rb").read()).hexdigest()
            for p in glob.glob(os.path.join(d, "*.npy"))}


@pytest.mark.parametrize("lanes", (2, 4, 8))
def test_build_parity(monkeypatch, toy_graph, dc1, shard_dir, tmp_path,
                      lanes):
    """Lane-parallel build chunks write byte-identical block files."""
    monkeypatch.setenv("DOS_MESH_DEVICES", str(lanes))
    d = str(tmp_path / f"lanes{lanes}")
    build_worker_shard(toy_graph, dc1, 0, d, chunk=16)
    assert _digests(d) == _digests(shard_dir)


def test_build_indivisible_chunk_degrades(monkeypatch, toy_graph, dc1,
                                          shard_dir, tmp_path):
    """A chunk the lane count does not divide falls back to the
    single-device compute — same bytes, no crash."""
    monkeypatch.setenv("DOS_MESH_DEVICES", "8")
    d = str(tmp_path / "odd")
    build_worker_shard(toy_graph, dc1, 0, d, chunk=12)   # 12 % 8 != 0
    d_ref = str(tmp_path / "odd-ref")
    monkeypatch.delenv("DOS_MESH_DEVICES")
    build_worker_shard(toy_graph, dc1, 0, d_ref, chunk=12)
    assert _digests(d) == _digests(d_ref)


def test_build_ctx_reuse(monkeypatch, toy_graph, dc1, tmp_path):
    """The shared compute ctx (bench hoist) caches the DeviceGraph and
    kernel pick across calls — and a second build through the same ctx
    still writes identical blocks."""
    ctx = {}
    d1 = str(tmp_path / "c1")
    build_worker_shard(toy_graph, dc1, 0, d1, chunk=16, ctx=ctx)
    dg_first = ctx["dg"]
    d2 = str(tmp_path / "c2")
    build_worker_shard(toy_graph, dc1, 0, d2, chunk=16, ctx=ctx)
    assert ctx["dg"] is dg_first
    assert _digests(d1) == _digests(d2)


# ------------------------------------------------------- replica lanes

def test_replica_lane_pinning(monkeypatch, toy_graph, dc1, shard_dir,
                              walk_queries, baseline):
    """Replica rank r pins to mesh lane r % L: its table lives on a
    DIFFERENT device than the primary's lane 0, and answers are
    unchanged (the replica falls back to the primary block set on a
    shared filesystem)."""
    monkeypatch.setenv("DOS_MESH_DEVICES", "4")
    for rank in (1, 2):
        eng = ShardEngine(toy_graph, dc1, 0, shard_dir, replica=rank)
        assert set(eng.fm.devices()) == {jax.devices()[rank % 4]}
        got = eng.answer(walk_queries, RuntimeConfig())[:3]
        for a, b in zip(baseline[0], got):
            np.testing.assert_array_equal(a, b)


# ------------------------------------------------------- mat collective

@pytest.mark.parametrize("workers", LANES)
def test_query_mat_parity(toy_graph, workers):
    """The on-mesh collective mat row equals per-pair query answers at
    every mesh size — duplicates and out-of-range targets included."""
    dc = DistributionController("tpu", None, workers, toy_graph.n)
    o = CPDOracle(toy_graph, dc,
                  mesh=make_mesh(n_workers=workers)).build(chunk=16)
    tg = np.concatenate([np.arange(0, toy_graph.n, 3), [7, 7]])
    cost, fin = o.query_mat(5, tg)
    pc, _pl, pf = o.query(
        np.stack([np.full(len(tg), 5), tg], axis=1))
    np.testing.assert_array_equal(cost, pc)
    np.testing.assert_array_equal(fin, pf)
    # out-of-range / negative targets come back unfinished, in place
    cost2, fin2 = o.query_mat(5, [3, toy_graph.n + 9, -2, 8])
    assert list(fin2) == [True, False, False, True]
    # out-of-range source: whole row unanswered, no crash
    cost3, fin3 = o.query_mat(toy_graph.n + 1, [3, 8])
    assert not fin3.any()


def test_query_mat_diffed(toy_graph):
    dc = DistributionController("tpu", None, 4, toy_graph.n)
    o = CPDOracle(toy_graph, dc,
                  mesh=make_mesh(n_workers=4)).build(chunk=16)
    w = toy_graph.weights_with_diff(synth_diff(toy_graph, frac=0.3,
                                               seed=5))
    tg = np.arange(0, toy_graph.n, 4)
    cost, fin = o.query_mat(2, tg, w_query=w)
    pc, _pl, pf = o.query(np.stack([np.full(len(tg), 2), tg], axis=1),
                          w_query=w)
    np.testing.assert_array_equal(cost, pc)
    np.testing.assert_array_equal(fin, pf)


def test_families_matrix_mesh_path(toy_graph, diff_file):
    """QueryFamilies with an oracle answers ``mat`` via the collective
    — the encoded MAT sentence matches the per-pair answers, free-flow
    and under the frontend's diff, and no frontend submit happens."""
    dc = DistributionController("tpu", None, 2, toy_graph.n)
    o = CPDOracle(toy_graph, dc, mesh=make_mesh(n_workers=2)).build(
        chunk=16)

    def _boom(*a, **k):                     # the fan-out path is dead
        raise AssertionError("mesh mat must not submit futures")

    frontend = types.SimpleNamespace(diff="-", submit=_boom)
    fam = QueryFamilies(frontend, oracle=o)
    tg = [3, 9, 14, 9]
    res = fam.matrix(5, tg).result(timeout=1.0)
    pc, _pl, pf = o.query(np.stack([np.full(len(tg), 5), tg], axis=1))
    want = [int(c) if f else -1 for c, f in zip(pc, pf)]
    assert res.costs == want
    assert res.encode() == " ".join(
        ["MAT", "5", str(len(tg))] + [str(c) for c in want])
    # under a diff: weights re-read per diff change
    frontend.diff = diff_file
    res2 = fam.matrix(5, tg).result(timeout=1.0)
    from distributed_oracle_search_tpu.data.formats import read_diff
    w = toy_graph.weights_with_diff(read_diff(diff_file))
    pc2, _pl2, pf2 = o.query(
        np.stack([np.full(len(tg), 5), tg], axis=1), w_query=w)
    assert res2.costs == [int(c) if f else -1
                          for c, f in zip(pc2, pf2)]


def test_query_mat_row_width_pads_pow2(toy_graph):
    """The mat row's compiled width buckets at powers of two: k is
    client-controlled, and an unpadded width would cache one XLA
    program per distinct k forever."""
    from distributed_oracle_search_tpu.parallel import sharded

    dc = DistributionController("tpu", None, 2, toy_graph.n)
    o = CPDOracle(toy_graph, dc, mesh=make_mesh(n_workers=2)).build(
        chunk=16)
    o.query_mat(1, list(range(5)))
    size0 = sharded._mat_fn.cache_info().currsize
    for k in (5, 6, 7, 8):              # all in the width-8 bucket
        cost, fin = o.query_mat(1, list(range(k)))
        pc, _pl, pf = o.query(
            np.stack([np.full(k, 1), np.arange(k)], axis=1))
        np.testing.assert_array_equal(cost, pc)
        np.testing.assert_array_equal(fin, pf)
    assert sharded._mat_fn.cache_info().currsize == size0


def test_query_mat_weight_buffer_cached_by_key(toy_graph):
    """With a w_key, the padded device weights upload once per diff,
    not once per row."""
    dc = DistributionController("tpu", None, 2, toy_graph.n)
    o = CPDOracle(toy_graph, dc, mesh=make_mesh(n_workers=2)).build(
        chunk=16)
    w = toy_graph.weights_with_diff(synth_diff(toy_graph, frac=0.2,
                                               seed=9))
    o.query_mat(1, [2, 4], w_query=w, w_key="d1")
    buf = o._mat_weights["d1"]
    o.query_mat(1, [3, 5, 6], w_query=w, w_key="d1")
    assert o._mat_weights["d1"] is buf          # no re-upload
    # keyless calls never populate the cache
    o.query_mat(1, [2], w_query=w)
    assert set(o._mat_weights) == {"d1"}


def test_mesh_mat_oracle_refused_under_traffic(monkeypatch):
    """DOS_MESH_MAT + --traffic-dir: the mesh oracle would serve
    stale tables across epoch promotions, so serve wiring refuses it
    (mat degrades to fan-out) instead of silently diverging."""
    from distributed_oracle_search_tpu.cli.serve import _mesh_mat_oracle

    monkeypatch.setenv("DOS_MESH_MAT", "1")
    assert _mesh_mat_oracle(None, None, traffic=object()) is None
    monkeypatch.setenv("DOS_MESH_MAT", "0")
    assert _mesh_mat_oracle(None, None, traffic=None) is None


# ----------------------------------------------- obs / gate satellites

def test_bench_diff_mesh_directions():
    """The mesh_* family's directions are explicit, pinned — and the
    multichip smoke gates at tolerance 0 (any 1 -> 0 drop)."""
    for key in ("mesh_build_rows_per_sec_d8",
                "mesh_walk_queries_per_sec_d8",
                "mesh_mat_rows_per_sec_d8",
                "shard_strong_scaling_rows_per_sec_w1",
                "shard_strong_scaling_rows_per_sec_w8",
                "multichip_smoke_ok"):
        assert fleet._KEY_DIRECTIONS[key] == "higher", key
    assert fleet._KEY_DIRECTIONS[
        "shard_strong_scaling_overhead_w8_seconds"] == "lower"
    assert fleet._KEY_TOLERANCES["multichip_smoke_ok"] == 0.0


def test_bench_diff_gates_mesh_regression(tmp_path):
    """End-to-end through compare_bench: a mesh rate drop and a
    multichip 1 -> 0 flip both gate; overhead seconds gate UPWARD."""
    def _rec(name, headline):
        p = tmp_path / name
        p.write_text(json.dumps(
            {"parsed": {"metric": "m", "value": 1.0,
                        "headline": headline}}))
        return str(p)

    old = _rec("BENCH_r01.json", {"mesh_walk_queries_per_sec_d8": 1000,
                                  "multichip_smoke_ok": 1,
                                  "shard_strong_scaling_overhead_w8_seconds": 0.2})
    new = _rec("BENCH_r02.json", {"mesh_walk_queries_per_sec_d8": 500,
                                  "multichip_smoke_ok": 0,
                                  "shard_strong_scaling_overhead_w8_seconds": 0.5})
    out = fleet.compare_bench(old, new)
    bad = {e["key"] for e in out["regressions"]}
    assert bad == {"mesh_walk_queries_per_sec_d8",
                   "multichip_smoke_ok",
                   "shard_strong_scaling_overhead_w8_seconds"}


def test_top_renders_mesh_column_blank_tolerantly():
    """`dos-obs top` shows the lane count when a worker exports it and
    blanks (not crashes) for older workers / odd types."""
    newer = {"worker": {"mesh": {"devices": 4, "axis": "lane"}}}
    older = {"worker": {"batches": 3}}
    weird = {"worker": {"mesh": {"devices": None}}}
    assert fleet._summarize(newer)["mesh"] == 4
    assert "mesh" not in fleet._summarize(older)
    assert "mesh" not in fleet._summarize(weird)
    table = fleet.render_top({"a:1": newer, "b:2": older, "c:3": weird})
    assert "mesh" in table.splitlines()[0]


def test_metrics_registered_in_obs_map():
    """New series documented in the obs/__init__ metric map (the
    dos-lint metric-registry contract)."""
    import distributed_oracle_search_tpu.obs as obs

    for name in ("mesh_devices", "mesh_walk_batches_total",
                 "mesh_collective_seconds"):
        assert name in obs.__doc__, name
