"""End-to-end driver tests: shard build -> resident servers -> campaign.

The no-cluster analog of the reference's ``-t`` smoke mode (N workers on
localhost, SURVEY.md §4): host-mode runs the real FIFO wire protocol against
resident servers in background threads (no ssh — the local bash path), and
TPU-mode runs the whole campaign in-process on the virtual 8-device mesh.
"""

import csv
import json
import os
import threading

import numpy as np
import pytest

from distributed_oracle_search_tpu.cli.args import parse_args
from distributed_oracle_search_tpu.cli import process_query as pq
from distributed_oracle_search_tpu.cli import offline as offline_mod
from distributed_oracle_search_tpu.cli.make_cpds import run_host, run_tpu
from distributed_oracle_search_tpu.data import (
    Graph, ensure_synth_dataset, read_diff, read_scen,
)
from distributed_oracle_search_tpu.models.cpd import write_index_manifest
from distributed_oracle_search_tpu.models.reference import dist_to_target
from distributed_oracle_search_tpu.parallel.partition import (
    DistributionController,
)
from distributed_oracle_search_tpu.transport.wire import STATS_HEADER
from distributed_oracle_search_tpu.utils.config import ClusterConfig
from distributed_oracle_search_tpu.worker import (
    FifoServer, ShardEngine, stop_server,
)
from distributed_oracle_search_tpu.worker.build import main as build_main


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    datadir = str(tmp_path_factory.mktemp("data"))
    paths = ensure_synth_dataset(datadir, width=10, height=8, n_queries=96,
                                 seed=13)
    return datadir, paths


@pytest.fixture(scope="module")
def host_conf(dataset):
    datadir, paths = dataset
    conf = ClusterConfig(
        workers=["localhost", "localhost"],
        partmethod="mod", partkey=2,
        outdir=os.path.join(datadir, "index"),
        xy_file=paths["xy"], scenfile=paths["scen"],
        diffs=["-", paths["diff"]],
        nfs=datadir,
    ).validate()
    path = os.path.join(datadir, "conf.json")
    conf.save(path)
    return conf, path


@pytest.fixture(scope="module")
def built_index(host_conf):
    conf, _ = host_conf
    # the make_cpd_auto-equivalent CLI, one invocation per worker
    for wid in range(conf.maxworker):
        build_main(["--input", conf.xy_file, "--partmethod", conf.partmethod,
                    "--partkey", str(conf.partkey),
                    "--workerid", str(wid),
                    "--maxworker", str(conf.maxworker),
                    "--outdir", conf.outdir])
    g = Graph.from_xy(conf.xy_file)
    dc = DistributionController(conf.partmethod, conf.partkey,
                                conf.maxworker, g.n)
    write_index_manifest(conf.outdir, dc)
    return g, dc


def test_shard_engine_matches_cpu_oracle(host_conf, built_index):
    conf, _ = host_conf
    g, dc = built_index
    queries = read_scen(conf.scenfile)
    eng = ShardEngine(g, dc, wid=1, outdir=conf.outdir)
    mine = queries[dc.worker_of(queries[:, 1]) == 1][:16]
    cost, plen, fin, stats = eng.answer(
        mine, pq.runtime_config(parse_args([])))
    assert fin.all() and stats.finished == len(mine)
    for (s, t), c in zip(mine, cost):
        assert c == dist_to_target(g, int(t))[int(s)]


def test_shard_engine_applies_diff(host_conf, built_index):
    conf, _ = host_conf
    g, dc = built_index
    diff = conf.diffs[1]
    queries = read_scen(conf.scenfile)
    mine = queries[dc.worker_of(queries[:, 1]) == 0][:8]
    eng = ShardEngine(g, dc, wid=0, outdir=conf.outdir)
    cost, plen, fin, _ = eng.answer(
        mine, pq.runtime_config(parse_args([])), difffile=diff)
    # costs accumulate on perturbed weights while moves follow free-flow
    # first moves (reference semantics, SURVEY.md §0)
    w_diff = g.weights_with_diff(read_diff(diff))
    free_cost, _, _, _ = eng.answer(mine, pq.runtime_config(parse_args([])))
    assert (cost >= free_cost).all() and (cost > free_cost).any()
    assert fin.all()
    del w_diff


def test_shard_engine_rejects_misrouted(host_conf, built_index):
    conf, _ = host_conf
    g, dc = built_index
    queries = read_scen(conf.scenfile)
    other = queries[dc.worker_of(queries[:, 1]) == 0][:4]
    eng = ShardEngine(g, dc, wid=1, outdir=conf.outdir)
    with pytest.raises(ValueError, match="routing invariant"):
        eng.answer(other, pq.runtime_config(parse_args([])))


def test_shard_engine_owner_check_precedes_row_lookup(
        host_conf, built_index, monkeypatch):
    """Regression: the routing-invariant diagnostic must fire BEFORE the
    shard-local row lookup — a misrouted query used to crash inside
    ``owned_index_of`` with an opaque index error instead."""
    conf, _ = host_conf
    g, dc = built_index
    queries = read_scen(conf.scenfile)
    other = queries[dc.worker_of(queries[:, 1]) == 0][:4]
    eng = ShardEngine(g, dc, wid=1, outdir=conf.outdir)

    def boom(nodes):
        raise AssertionError("row lookup ran before the owner check")

    monkeypatch.setattr(eng.dc, "owned_index_of", boom)
    with pytest.raises(ValueError, match="routing invariant"):
        eng.answer(other, pq.runtime_config(parse_args([])))


def test_shard_engine_dedups_duplicates_and_zero_length(
        host_conf, built_index):
    """Sort/unsort path under duplicate and ``s == t`` queries: answers
    stay element-wise equal to the reference CPU oracle, stats counters
    (``finished``, ``plen``, ``n_touched``) count per ORIGINAL query,
    and the dedup counter books the kernel's saved work."""
    from distributed_oracle_search_tpu.models.reference import (
        first_move_to_target, table_search_walk,
    )
    from distributed_oracle_search_tpu.obs import metrics as obs_metrics

    conf, _ = host_conf
    g, dc = built_index
    queries = read_scen(conf.scenfile)
    mine = queries[dc.worker_of(queries[:, 1]) == 1][:12]
    own = dc.owned(1)[:3]
    batch = np.concatenate([mine, mine[:5],                 # duplicates
                            np.stack([own, own], axis=1)])  # s == t
    batch = batch[np.random.default_rng(3).permutation(len(batch))]
    eng = ShardEngine(g, dc, wid=1, outdir=conf.outdir)
    dup0 = obs_metrics.REGISTRY.snapshot()["counters"][
        "worker_duplicate_queries_total"]
    cost, plen, fin, stats = eng.answer(
        batch, pq.runtime_config(parse_args([])))
    fm_cols = {int(t): first_move_to_target(g, int(t))
               for t in set(batch[:, 1].tolist())}
    for (s, t), c, p, f in zip(batch, cost, plen, fin):
        gc, gp, gf, _path = table_search_walk(
            g, lambda x, tt: fm_cols[int(tt)][x], int(s), int(t))
        assert (c, p, f) == (gc, gp, gf), (s, t)
        if s == t:
            assert p == 0 and f
    assert fin.all()
    # per-original-query stats despite the kernel answering dedup'd
    assert stats.finished == len(batch)
    assert stats.n_touched == len(batch)
    assert stats.plen == int(plen.sum())
    dup1 = obs_metrics.REGISTRY.snapshot()["counters"][
        "worker_duplicate_queries_total"]
    n_dup = len(batch) - len(np.unique(batch, axis=0))
    assert n_dup >= 5 and dup1 - dup0 == n_dup


def test_host_campaign_over_fifo(host_conf, built_index, monkeypatch,
                                 tmp_path):
    """Full host-mode campaign through the real FIFO wire protocol."""
    conf, _ = host_conf
    fifos = {wid: str(tmp_path / f"worker{wid}.fifo")
             for wid in range(conf.maxworker)}
    monkeypatch.setattr(pq, "command_fifo_path", lambda wid: fifos[wid])

    servers = [FifoServer(conf, wid, command_fifo=fifos[wid])
               for wid in range(conf.maxworker)]
    threads = [threading.Thread(target=s.serve_forever, daemon=True)
               for s in servers]
    for t in threads:
        t.start()
    try:
        args = parse_args(["--backend", "host"])
        data, stats, _paths = pq.run(conf, args)
    finally:
        for wid in fifos:
            try:
                stop_server(fifos[wid])
            except OSError:
                pass
        for t in threads:
            t.join(timeout=10)

    queries = read_scen(conf.scenfile)
    assert data["num_queries"] == len(queries)
    assert len(stats) == len(conf.diffs)          # one round per diff
    for expe in stats:
        assert len(expe) == conf.maxworker
        total = sum(row[-1] for row in expe)       # size column
        finished = sum(row[6] for row in expe)     # finished column
        assert total == len(queries)
        assert finished == len(queries)


def test_tpu_campaign_and_artifacts(dataset, tmp_path):
    """TPU-mode: in-process sharded campaign + artifact trio."""
    datadir, paths = dataset
    conf = ClusterConfig(
        workers=[f"tpu:{i}" for i in range(8)],
        partmethod="tpu", partkey=8,
        outdir=str(tmp_path / "index"),
        xy_file=paths["xy"], scenfile=paths["scen"],
        diffs=["-", paths["diff"]],
    ).validate()
    out = str(tmp_path / "artifacts")
    args = parse_args(["-o", out])
    data, stats, _paths = pq.run(conf, args)
    pq.output(data, stats, args)

    queries = read_scen(conf.scenfile)
    for expe in stats:
        assert sum(row[-1] for row in expe) == len(queries)
        assert sum(row[6] for row in expe) == len(queries)

    with open(os.path.join(out, "parts.csv")) as f:
        rows = list(csv.reader(f))
    assert rows[0] == STATS_HEADER
    # every data row: expe index + full stats width (the reference's CSV
    # writer crashed for != 2 workers; ours must not)
    assert all(len(r) == len(STATS_HEADER) for r in rows[1:])
    assert {r[0] for r in rows[1:]} == {"0", "1"}
    metrics = json.load(open(os.path.join(out, "metrics.json")))
    assert metrics["num_queries"] == len(queries)
    assert json.load(open(os.path.join(out, "data.json")))["output"] == out


def test_tpu_streamed_serve_fallback(dataset, tmp_path, monkeypatch):
    """When the resident shard exceeds DOS_FM_BUDGET_GB (forced here via
    DOS_SERVE_STREAMED=1), the TPU campaign serves from the on-disk
    index via the streamed oracle — same per-round counters as the
    resident path, including fused multi-diff rounds, -w filtering, and
    --extract path prefixes (per-chunk scans of the uploaded fm rows).
    """
    datadir, paths = dataset
    conf = ClusterConfig(
        workers=[f"tpu:{i}" for i in range(4)],
        partmethod="tpu", partkey=4,
        outdir=str(tmp_path / "index"),
        xy_file=paths["xy"], scenfile=paths["scen"],
        diffs=["-", paths["diff"]],
    ).validate()
    g = Graph.from_xy(paths["xy"])
    dc = DistributionController("tpu", None, 4, g.n)
    queries = read_scen(conf.scenfile)[:40]
    stats_res, _ = pq.run_tpu(conf, parse_args([]), queries, dc,
                              conf.diffs)
    monkeypatch.setenv("DOS_SERVE_STREAMED", "1")
    stats_str, _ = pq.run_tpu(conf, parse_args([]), queries, dc,
                              conf.diffs)
    for rows_r, rows_s in zip(stats_res, stats_str):
        for rr, rs in zip(rows_r, rows_s):
            assert rr[:7] == rs[:7] and rr[-1] == rs[-1]
    # -w filter parity: one streamed run, one resident run
    s_w, _ = pq.run_tpu(conf, parse_args(["-w", "1"]), queries, dc,
                        conf.diffs)
    monkeypatch.delenv("DOS_SERVE_STREAMED")
    r_w, _ = pq.run_tpu(conf, parse_args(["-w", "1"]), queries, dc,
                        conf.diffs)
    for rows_r, rows_s in zip(r_w, s_w):
        for rr, rs in zip(rows_r, rows_s):
            assert rr[:7] == rs[:7] and rr[-1] == rs[-1]
    # --extract under the streamed plan: prefixes must equal the
    # resident oracle's (same fm rows, same scan, different memory plan)
    _, paths_res = pq.run_tpu(conf, parse_args(["--extract", "-k", "3"]),
                              queries, dc, ["-"])
    monkeypatch.setenv("DOS_SERVE_STREAMED", "1")
    _, paths_str = pq.run_tpu(conf, parse_args(["--extract", "-k", "3"]),
                              queries, dc, ["-"])
    assert paths_res is not None and paths_str is not None
    np.testing.assert_array_equal(paths_str, paths_res)


def test_tpu_fused_diff_rounds_match_sequential(dataset, tmp_path):
    """A multi-diff TPU campaign runs fused (one walk, all rounds); its
    per-round stats rows must carry the same counts as sequential
    rounds (a huge --k-moves forces the sequential path — the budget is
    never binding, so answers are identical; only timers may differ)."""
    datadir, paths = dataset
    conf = ClusterConfig(
        workers=[f"tpu:{i}" for i in range(4)],
        partmethod="tpu", partkey=4,
        outdir=str(tmp_path / "index"),
        xy_file=paths["xy"], scenfile=paths["scen"],
        diffs=["-", paths["diff"]],
    ).validate()
    g = Graph.from_xy(paths["xy"])
    dc = DistributionController("tpu", None, 4, g.n)
    queries = read_scen(conf.scenfile)[:40]
    stats_f, _ = pq.run_tpu(conf, parse_args([]), queries, dc, conf.diffs)
    stats_s, _ = pq.run_tpu(conf, parse_args(["--k-moves", "1000000"]),
                            queries, dc, conf.diffs)
    assert len(stats_f) == len(stats_s) == 2       # one round per diff
    for rows_f, rows_s in zip(stats_f, stats_s):
        assert len(rows_f) == len(rows_s)
        for rf, rs in zip(rows_f, rows_s):
            assert rf[:7] == rs[:7]                # counters columns
            assert rf[-1] == rs[-1]                # size column


def test_tpu_campaign_matches_cpu_oracle(dataset, tmp_path):
    datadir, paths = dataset
    conf = ClusterConfig(
        workers=[f"tpu:{i}" for i in range(4)],
        partmethod="tpu", partkey=4,
        outdir=str(tmp_path / "index"),
        xy_file=paths["xy"], scenfile=paths["scen"], diffs=["-"],
    ).validate()
    g = Graph.from_xy(paths["xy"])
    dc = DistributionController("tpu", None, 4, g.n)
    args = parse_args([])
    queries = read_scen(conf.scenfile)[:24]
    stats, _ = pq.run_tpu(conf, args, queries, dc, ["-"])
    assert sum(r[6] for r in stats[0]) == len(queries)
    # independently verify via the saved index + a fresh engine
    eng = ShardEngine(g, dc, wid=0, outdir=conf.outdir)
    mine = queries[dc.worker_of(queries[:, 1]) == 0]
    cost, _, fin, _ = eng.answer(mine, pq.runtime_config(args))
    for (s, t), c in zip(mine, cost):
        assert c == dist_to_target(g, int(t))[int(s)]


def test_worker_select_flag(dataset, tmp_path):
    """-w restricts the campaign to one worker (reference -w filter)."""
    datadir, paths = dataset
    conf = ClusterConfig(
        workers=[f"tpu:{i}" for i in range(4)],
        partmethod="tpu", partkey=4,
        outdir=str(tmp_path / "index"),
        xy_file=paths["xy"], scenfile=paths["scen"], diffs=["-"],
    ).validate()
    args = parse_args(["-w", "2"])
    data, stats, _paths = pq.run(conf, args)
    g_n = Graph.from_xy(paths["xy"]).n
    dc = DistributionController("tpu", None, 4, g_n)
    queries = read_scen(conf.scenfile)
    expect = int((dc.worker_of(queries[:, 1]) == 2).sum())
    assert len(stats[0]) == 1
    assert stats[0][0][-1] == expect


def _golden_path_prefix(g, s, t, k):
    """First k+1 nodes of the CPU oracle's walk, last node repeated."""
    from distributed_oracle_search_tpu.models.reference import (
        first_move_to_target, table_search_walk,
    )
    fm_col = first_move_to_target(g, int(t))
    _, moves, _, path = table_search_walk(
        g, lambda x, _t: fm_col[x], int(s), int(t), k_moves=k)
    path = path + [path[-1]] * (k + 1 - len(path))
    return path[:k + 1], min(moves, k)


def test_tpu_campaign_extracts_path_prefixes(dataset, tmp_path):
    """--extract -k 8: paths.csv rows match the CPU oracle's walk."""
    datadir, paths_d = dataset
    conf = ClusterConfig(
        workers=[f"tpu:{i}" for i in range(4)],
        partmethod="tpu", partkey=4,
        outdir=str(tmp_path / "index"),
        xy_file=paths_d["xy"], scenfile=paths_d["scen"],
        diffs=["-", paths_d["diff"]],
    ).validate()
    out = str(tmp_path / "artifacts")
    args = parse_args(["-o", out, "--extract", "-k", "8"])
    data, stats, paths = pq.run(conf, args)
    pq.output(data, stats, args, paths)
    queries = read_scen(conf.scenfile)
    assert paths is not None and paths.shape == (len(queries), 3 + 9)
    g = Graph.from_xy(paths_d["xy"])
    for row in paths[:20]:
        s, t, moves = int(row[0]), int(row[1]), int(row[2])
        golden_nodes, golden_moves = _golden_path_prefix(g, s, t, 8)
        assert moves == golden_moves
        assert list(row[3:]) == golden_nodes
    with open(os.path.join(out, "paths.csv")) as f:
        rows = list(csv.reader(f))
    assert rows[0][:3] == ["s", "t", "moves"] and len(rows) == len(queries) + 1


def test_host_campaign_time_budget_truncates_batch(host_conf, built_index,
                                                   monkeypatch, tmp_path):
    """A tiny ``--ns-lim`` budget cuts searches short INSIDE a batch
    (reference semantics, reference ``args.py:30-57``): partial
    ``finished`` counts come back through the full FIFO wire — at least
    the first chunk answered, the rest left unfinished."""
    conf, _ = host_conf
    fifos = {wid: str(tmp_path / f"worker{wid}.fifo")
             for wid in range(conf.maxworker)}
    monkeypatch.setattr(pq, "command_fifo_path", lambda wid: fifos[wid])
    servers = [FifoServer(conf, wid, command_fifo=fifos[wid])
               for wid in range(conf.maxworker)]
    for s in servers:
        # shrink the truncation chunk far below the batch so the tiny
        # budget bites mid-batch (production chunk is 1024 rows)
        s.engine.astar_chunk = 4
    threads = [threading.Thread(target=s.serve_forever, daemon=True)
               for s in servers]
    for t in threads:
        t.start()
    try:
        args = parse_args(["--backend", "host", "--ns-lim", "1"])
        data, stats, _paths = pq.run(conf, args)
        queries = read_scen(conf.scenfile)
        for expe in stats:
            finished = sum(r[6] for r in expe)
            # first chunk per worker always answers; the expired budget
            # leaves the rest unfinished
            assert conf.maxworker <= finished < len(queries), finished
    finally:
        for wid in fifos:
            try:
                stop_server(fifos[wid])
            except OSError:
                pass
        for t in threads:
            t.join(timeout=10)
    # no budget -> every query finishes (the truncation is budget-gated)
    servers2 = [FifoServer(conf, wid, command_fifo=fifos[wid])
                for wid in range(conf.maxworker)]
    threads2 = [threading.Thread(target=s.serve_forever, daemon=True)
                for s in servers2]
    for t in threads2:
        t.start()
    try:
        data, stats, _paths = pq.run(conf, parse_args(["--backend",
                                                       "host"]))
        for expe in stats:
            assert sum(r[6] for r in expe) == len(read_scen(conf.scenfile))
    finally:
        for wid in fifos:
            try:
                stop_server(fifos[wid])
            except OSError:
                pass
        for t in threads2:
            t.join(timeout=10)


def test_host_campaign_extracts_path_prefixes(host_conf, built_index,
                                              monkeypatch, tmp_path):
    """The wire extension end-to-end: servers write .paths files, the
    head collects them; golden-tested vs the CPU oracle."""
    conf, _ = host_conf
    fifos = {wid: str(tmp_path / f"worker{wid}.fifo")
             for wid in range(conf.maxworker)}
    monkeypatch.setattr(pq, "command_fifo_path", lambda wid: fifos[wid])
    servers = [FifoServer(conf, wid, command_fifo=fifos[wid])
               for wid in range(conf.maxworker)]
    threads = [threading.Thread(target=s.serve_forever, daemon=True)
               for s in servers]
    for t in threads:
        t.start()
    try:
        args = parse_args(["--backend", "host", "--extract", "-k", "5"])
        data, stats, paths = pq.run(conf, args)
    finally:
        for wid in fifos:
            try:
                stop_server(fifos[wid])
            except OSError:
                pass
        for t in threads:
            t.join(timeout=10)
    queries = read_scen(conf.scenfile)
    assert paths is not None and len(paths) == len(queries)
    g, _dc = built_index
    for row in paths[:15]:
        s, t, moves = int(row[0]), int(row[1]), int(row[2])
        golden_nodes, golden_moves = _golden_path_prefix(g, s, t, 5)
        assert moves == golden_moves and list(row[3:]) == golden_nodes


def test_extract_requires_positive_k():
    with pytest.raises(SystemExit, match="k-moves"):
        pq.runtime_config(parse_args(["--extract"]))


def test_tpu_per_worker_times_sum_to_campaign(dataset, tmp_path):
    """Apportioned per-worker t_search rows must sum to the measured
    round interval (VERDICT: no fabricated per-worker wall clocks)."""
    datadir, paths_d = dataset
    conf = ClusterConfig(
        workers=[f"tpu:{i}" for i in range(4)],
        partmethod="tpu", partkey=4,
        outdir=str(tmp_path / "index"),
        xy_file=paths_d["xy"], scenfile=paths_d["scen"], diffs=["-"],
    ).validate()
    args = parse_args([])
    g_n = Graph.from_xy(paths_d["xy"]).n
    dc = DistributionController("tpu", None, 4, g_n)
    queries = read_scen(conf.scenfile)
    stats, _ = pq.run_tpu(conf, args, queries, dc, ["-"])
    idx = STATS_HEADER.index("t_search") - 1   # rows lack the expe column
    total = sum(row[idx] for row in stats[0])
    # rows are shares of one measured interval: their sum IS the interval
    assert total > 0
    shares = [row[idx] / total for row in stats[0]]
    moves_idx = STATS_HEADER.index("plen") - 1
    all_moves = sum(row[moves_idx] for row in stats[0])
    for row, share in zip(stats[0], shares):
        assert abs(share - row[moves_idx] / all_moves) < 1e-9


# ------------------------------------------------------------- make_parts

def _reqs(n=50, seed=3, n_nodes=200):
    rng = np.random.default_rng(seed)
    return np.stack([rng.integers(0, n_nodes, n),
                     rng.integers(0, n_nodes, n)], axis=1)


def _covers_exactly(parts, reqs):
    got = np.concatenate(parts) if parts else np.zeros((0, 2), np.int64)
    a = sorted(map(tuple, got))
    b = sorted(map(tuple, reqs))
    assert a == b


@pytest.mark.parametrize("argv", [
    [], ["--group", "all"], ["--group", "mod"], ["--group", "div"],
    ["--alloc", "50", "120", "200"], ["--sort"],
    ["--group", "all", "--sort"],
])
def test_make_parts_partitions_exactly(argv):
    args = parse_args(argv)
    reqs = _reqs()
    parts = offline_mod.make_parts(reqs, args, num_parts=4)
    _covers_exactly(parts, reqs)


def test_make_parts_all_keeps_target_groups_whole():
    args = parse_args(["--group", "all"])
    reqs = _reqs(80)
    parts = offline_mod.make_parts(reqs, args, num_parts=5)
    seen = {}
    for i, p in enumerate(parts):
        for t in np.unique(p[:, 1]):
            assert seen.setdefault(int(t), i) == i, \
                "a destination group was split across parts"


def test_make_parts_sort_orders_by_target():
    args = parse_args(["--sort"])
    parts = offline_mod.make_parts(_reqs(), args, num_parts=3)
    for p in parts:
        assert (np.diff(p[:, 1]) >= 0).all()


def test_build_resume_computes_only_missing_blocks(dataset, tmp_path):
    """Deleting one block file and re-running rebuilds exactly that block."""
    from distributed_oracle_search_tpu.models.cpd import (
        build_worker_shard, shard_block_name,
    )
    datadir, paths = dataset
    g = Graph.from_xy(paths["xy"])
    dc = DistributionController("mod", 2, 2, g.n, block_size=16)
    out = str(tmp_path / "idx")
    first = build_worker_shard(g, dc, 0, out, chunk=16)
    assert len(first) == (dc.n_owned(0) + 15) // 16
    again = build_worker_shard(g, dc, 0, out, chunk=16)
    assert again == []          # everything on disk -> nothing recomputed
    victim = shard_block_name(0, 1)
    os.remove(os.path.join(out, victim))
    redo = build_worker_shard(g, dc, 0, out, chunk=16)
    assert redo == [victim]

    # and the rebuilt index still matches the CPU oracle
    eng = ShardEngine(g, dc, wid=0, outdir=out)
    queries = read_scen(paths["scen"])
    mine = queries[dc.worker_of(queries[:, 1]) == 0][:8]
    cost, _, fin, _ = eng.answer(mine, pq.runtime_config(parse_args([])))
    assert fin.all()
    for (s, t), c in zip(mine, cost):
        assert c == dist_to_target(g, int(t))[int(s)]


def test_server_answers_malformed_request(host_conf, built_index, tmp_path):
    """A corrupt request must not leave the head blocked: the server sends
    the FAIL sentinel to the answer FIFO recovered from line 2."""
    conf, _ = host_conf
    fifo = str(tmp_path / "wm.fifo")
    server = FifoServer(conf, 0, command_fifo=fifo)
    answer = str(tmp_path / "ans.fifo")
    os.mkfifo(answer)
    th = threading.Thread(target=server.serve_forever, daemon=True)
    th.start()
    import time
    for _ in range(100):
        if os.path.exists(fifo):
            break
        time.sleep(0.05)
    try:
        with open(fifo, "w") as f:
            f.write("this is not json\nqueryfile %s -\n" % answer)
        with open(answer) as f:          # blocks until the server answers
            line = f.read().strip()
        assert line == "FAIL"
    finally:
        stop_server(fifo)
        th.join(timeout=10)


def test_make_cpds_test_mode_bootstraps_dataset(tmp_path, monkeypatch):
    """-t must work in a fresh directory: it generates the canned synth
    dataset itself (regression: it used to assume process_query -t had
    already run)."""
    from distributed_oracle_search_tpu.cli.make_cpds import main as cpds_main
    monkeypatch.chdir(tmp_path)
    assert cpds_main(["-t"]) == 0
    assert os.path.exists("data/synth-city.xy")
    assert os.path.exists("data/index/index.json")


def test_python_server_back_to_back_writers(host_conf, built_index,
                                            tmp_path):
    """N separate writers in quick succession must each get a reply (same
    coalescing-race regression test as the native server's)."""
    from distributed_oracle_search_tpu.transport.wire import (
        write_query_file,
    )

    conf, _ = host_conf
    g, dc = built_index
    queries = read_scen(conf.scenfile)
    mine = queries[dc.worker_of(queries[:, 1]) == 0][:4]
    fifo = str(tmp_path / "pb2b.fifo")
    server = FifoServer(conf, 0, command_fifo=fifo)
    th = threading.Thread(target=server.serve_forever, daemon=True)
    th.start()
    import time
    for _ in range(100):
        if os.path.exists(fifo):
            break
        time.sleep(0.05)
    else:
        pytest.fail("server fifo never appeared")
    n = 8
    try:
        afifos = []
        for k in range(n):
            qfile = str(tmp_path / f"pb2b{k}.query")
            afifo = str(tmp_path / f"pb2b{k}.answer")
            write_query_file(qfile, mine)
            os.mkfifo(afifo)
            afifos.append(afifo)
            with open(fifo, "w") as f:
                f.write('{"itrs": 1, "threads": 1}\n'
                        f"{qfile} {afifo} -\n")
        for afifo in afifos:
            with open(afifo) as f:
                reply = f.readline().strip()
            assert reply != "FAIL"
            assert int(reply.split(",")[6]) == len(mine)
    finally:
        stop_server(fifo)
        th.join(timeout=10)


def test_tpu_campaign_astar(dataset, tmp_path, monkeypatch):
    """TPU-mode --alg astar serves from the CPU heap engine by DEFAULT
    (the fast index-free backend; the dense device kernel measured
    ~160x slower and must be an explicit opt-in, VERDICT r4 weak-#5);
    DOS_ASTAR_DEVICE=1 selects the batched device kernel, and both
    engines finish every query with full priority-queue telemetry."""
    datadir, paths = dataset
    conf = ClusterConfig(
        workers=[f"tpu:{i}" for i in range(8)],
        partmethod="tpu", partkey=8,
        outdir=str(tmp_path / "index"),
        xy_file=paths["xy"], scenfile=paths["scen"],
        diffs=["-", paths["diff"]],
    ).validate()
    queries = read_scen(conf.scenfile)
    monkeypatch.delenv("DOS_ASTAR_DEVICE", raising=False)
    by_engine = {}
    for env in (None, "1"):
        if env is not None:
            monkeypatch.setenv("DOS_ASTAR_DEVICE", env)
        data, stats, _paths = pq.run(conf, parse_args(["--alg", "astar"]))
        by_engine[env] = stats
        for expe in stats:
            assert sum(row[-1] for row in expe) == len(queries)
            assert sum(row[6] for row in expe) == len(queries)  # finished
            # telemetry columns carry the search counters
            assert sum(row[0] for row in expe) > 0           # n_expanded
            assert sum(row[1] for row in expe) > 0           # n_inserted
            assert len(expe[0]) == len(STATS_HEADER) - 1
    # both engines answer the same campaign (finished/size per worker)
    for expe_h, expe_d in zip(by_engine[None], by_engine["1"]):
        for rh, rd in zip(expe_h, expe_d):
            assert rh[6] == rd[6] and rh[-1] == rd[-1]
    # ch is native-only; TPU mode must say so loudly
    with pytest.raises(SystemExit, match="native"):
        pq.run(conf, parse_args(["--alg", "ch", "--backend", "tpu"]))


def test_dimacs_gr_co_pipeline_end_to_end(tmp_path):
    """The DIMACS road pipeline as the reference's scale-up flow runs
    it (BASELINE.md configs[5]), end to end on a real ``.gr``/``.co``
    artifact: convert -> RCM reorder (graph + scen together) -> build a
    sharded index -> answer a campaign -> costs equal the CPU oracle on
    the ORIGINAL ids. The reference's actual NY files are stripped from
    its snapshot, so the artifact is a synthetic road network written in
    the real interchange format — every downstream step consumes only
    the files."""
    from distributed_oracle_search_tpu.cli.reorder import main as rmain
    from distributed_oracle_search_tpu.data import synth_road_network
    from distributed_oracle_search_tpu.data.dimacs import main as dmain
    from distributed_oracle_search_tpu.data.formats import write_scen
    from distributed_oracle_search_tpu.models.reference import (
        dist_to_target,
    )

    g = synth_road_network(900, seed=11)
    gr, co = str(tmp_path / "r.gr"), str(tmp_path / "r.co")
    with open(gr, "w") as f:
        f.write(f"c synthetic road, DIMACS format\np sp {g.n} {g.m}\n")
        for u, v, w in zip(g.src, g.dst, g.w):
            f.write(f"a {u + 1} {v + 1} {w}\n")
    with open(co, "w") as f:
        f.write(f"p aux sp co {g.n}\n")
        for i, (x, y) in enumerate(zip(g.xs, g.ys)):
            f.write(f"v {i + 1} {x} {y}\n")
    rng = np.random.default_rng(12)
    q_orig = np.stack([rng.integers(0, g.n, 64),
                       rng.integers(0, g.n, 64)], axis=1)
    scen0 = str(tmp_path / "r.scen")
    write_scen(scen0, q_orig)

    xy0 = str(tmp_path / "road.xy")
    assert dmain(["--gr", gr, "--co", co, "-o", xy0]) == 0
    xy1 = str(tmp_path / "road-rcm.xy")
    scen1 = str(tmp_path / "r-rcm.scen")
    assert rmain(["--input", xy0, "--order", "rcm", "-o", xy1,
                  "--scen", scen0, scen1]) == 0

    conf = ClusterConfig(
        workers=[f"tpu:{i}" for i in range(4)],
        partmethod="tpu", partkey=4,
        outdir=str(tmp_path / "index"),
        xy_file=xy1, scenfile=scen1, diffs=["-"],
    ).validate()
    data, stats, _ = pq.run(conf, parse_args([]))
    for expe in stats:
        assert sum(r[6] for r in expe) == len(q_orig)

    # cost parity back on ORIGINAL ids: the .order sidecar maps new->old
    order = np.loadtxt(xy1 + ".order", dtype=np.int64)
    g1 = Graph.from_xy(xy1)
    from distributed_oracle_search_tpu.models.cpd import CPDOracle
    from distributed_oracle_search_tpu.parallel.mesh import make_mesh
    dc = DistributionController("tpu", 4, 4, g1.n)
    o = CPDOracle(g1, dc, mesh=make_mesh(n_workers=4))
    o.load(conf.outdir)
    q1 = read_scen(scen1)
    cost, _, fin = o.query(q1)
    assert bool(np.asarray(fin).all())
    for i in (0, 7, 33, 63):
        s_new, t_new = int(q1[i, 0]), int(q1[i, 1])
        assert int(order[s_new]) == q_orig[i, 0]
        assert int(order[t_new]) == q_orig[i, 1]
        golden = dist_to_target(g, int(q_orig[i, 1]))[q_orig[i, 0]]
        assert int(cost[i]) == int(golden), i


def test_order_flag_points_to_reorder_tool(dataset, tmp_path):
    """--order on a campaign fails fast with the dataset-prep guidance
    (reordering per campaign would desync from the on-disk index)."""
    datadir, paths = dataset
    conf = ClusterConfig(
        workers=["tpu"], partmethod="tpu", partkey=1,
        outdir=str(tmp_path / "index"),
        xy_file=paths["xy"], scenfile=paths["scen"], diffs=["-"],
    ).validate()
    with pytest.raises(SystemExit, match="cli.reorder"):
        pq.run(conf, parse_args(["--order", "rcm"]))
