"""dos-lint fixture: silent-except."""

import logging

log = logging.getLogger(__name__)


def _risky():
    raise RuntimeError("fixture")


def bad_swallow():
    try:
        _risky()
    except Exception:
        return None


def suppressed_swallow():
    try:
        _risky()
    except Exception:  # dos-lint: disable=silent-except -- fixture:
        # exercising the suppression path of the checker itself
        pass


def clean_logged():
    try:
        _risky()
    except Exception as e:
        log.warning("risky failed: %s", e)
        return None


def clean_error_as_data():
    try:
        _risky()
    except Exception as e:
        return {"error": str(e)}
