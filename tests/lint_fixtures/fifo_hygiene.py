"""dos-lint fixture: fifo-hygiene."""

import os


def bad_blocking_open(fifo_path):
    return os.open(fifo_path, os.O_WRONLY)


def bad_bare_recv(sock):
    # the socket half: a bare recv outside transport/frames.py can
    # return a partial frame and desync the stream
    return sock.recv(4096)


def bad_bare_sendall(sock, payload):
    sock.sendall(payload)


def suppressed_blocking_open(fifo_path):
    # dos-lint: disable=fifo-hygiene -- fixture: peer lifetime pinned
    #   by the test harness, open cannot wedge
    return open(fifo_path, "r")


def suppressed_bare_recv_into(sock, buf):
    # dos-lint: disable=fifo-hygiene -- fixture: a raw-byte diagnostic
    #   probe that never parses frames off this socket
    return sock.recv_into(buf)


def clean_bounded_open(fifo_path):
    return os.open(fifo_path, os.O_WRONLY | os.O_NONBLOCK)


def clean_framed_wire(sock, frame_writer, frame_reader):
    # wire IO through the frame codec's reader/writer is the pattern
    frame_writer.send({"kind": "ping"})
    sock.close()
    return frame_reader
