"""dos-lint fixture: fifo-hygiene."""

import os


def bad_blocking_open(fifo_path):
    return os.open(fifo_path, os.O_WRONLY)


def suppressed_blocking_open(fifo_path):
    # dos-lint: disable=fifo-hygiene -- fixture: peer lifetime pinned
    #   by the test harness, open cannot wedge
    return open(fifo_path, "r")


def clean_bounded_open(fifo_path):
    return os.open(fifo_path, os.O_WRONLY | os.O_NONBLOCK)
