"""dos-lint fixture: lock-scope."""

import threading
import time

_lock = threading.Lock()


def bad_sleep_under_lock():
    with _lock:
        time.sleep(0.01)


def suppressed_sleep_under_lock():
    with _lock:
        # dos-lint: disable=lock-scope -- fixture: bounded pause held
        #   deliberately to exercise the suppression path
        time.sleep(0.01)


def clean_sleep_outside():
    with _lock:
        x = 1 + 1
    time.sleep(0.01)
    return x
