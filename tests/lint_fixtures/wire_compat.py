"""dos-lint fixture: wire-compat."""

import dataclasses
import json

VERSION = 2


@dataclasses.dataclass
class Msg:
    a: int = 0


def bad_from_json(line):
    d = json.loads(line)
    return Msg(**d)


def bad_version_gate(d):
    def parse_header(h):
        if h["version"] != VERSION:
            raise ValueError("unsupported")
        return h
    return parse_header(d)


def suppressed_from_json(line):
    d = json.loads(line)
    # dos-lint: disable=wire-compat -- fixture: strict legacy codec
    #   kept for byte-parity tests
    return Msg(**d)


def clean_from_json(line):
    d = json.loads(line)
    if d.get("version", 1) > VERSION:
        raise ValueError("newer than this reader; refusing to misread")
    known = {f.name for f in dataclasses.fields(Msg)}
    return Msg(**{k: v for k, v in d.items() if k in known})
