"""dos-lint fixture: atomic-writes."""

import json

from distributed_oracle_search_tpu.utils.atomicio import atomic_write_json


def bad_manifest_write(dirname, manifest):
    path = dirname + "/index-manifest.json"
    with open(path, "w") as f:
        json.dump(manifest, f)


def suppressed_write(dirname):
    # dos-lint: disable=atomic-writes -- fixture: scratch file, a torn
    #   write is rebuilt from source on the next run
    with open(dirname + "/scratch.json", "w") as f:
        f.write("{}")


def clean_write(dirname, manifest):
    atomic_write_json(dirname + "/index-manifest.json", manifest)
    with open(dirname + "/notes.txt", "w") as f:
        f.write("non-durable: no artifact suffix, plain open is fine")
