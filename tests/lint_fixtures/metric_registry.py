"""dos-lint fixture: metric-registry."""

from distributed_oracle_search_tpu.obs import metrics as obs_metrics

M_BAD = obs_metrics.counter(
    "fixture_lonely_total", "counter missing from the obs metric map")

# dos-lint: disable=metric-registry -- fixture: exercising the
#   suppression path of the checker itself
M_SUPPRESSED = obs_metrics.counter(
    "fixture_suppressed_total", "suppressed undocumented counter")

M_CLEAN = obs_metrics.counter(
    "serve_requests_total", "documented name, correct suffix")
