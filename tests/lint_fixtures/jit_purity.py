"""dos-lint fixture: jit-purity."""

import time

import jax

_captured = []


@jax.jit
def bad_traced_sleep(x):
    time.sleep(0.001)
    return x + 1


@jax.jit
def bad_captured_mutation(x):
    _captured.append(x)
    return x + 1


@jax.jit
def suppressed_sleep(x):
    # dos-lint: disable=jit-purity -- fixture: trace-time delay wanted
    #   to exercise the suppression path
    time.sleep(0.001)
    return x + 1


@jax.jit
def clean_pure(x):
    y = x * 2
    local = [y]
    local.append(y + 1)
    return local[0] + local[1]
