"""dos-lint fixture: jit-purity."""

import time

import jax

_captured = []


@jax.jit
def bad_traced_sleep(x):
    time.sleep(0.001)
    return x + 1


@jax.jit
def bad_captured_mutation(x):
    _captured.append(x)
    return x + 1


@jax.jit
def suppressed_sleep(x):
    # dos-lint: disable=jit-purity -- fixture: trace-time delay wanted
    #   to exercise the suppression path
    time.sleep(0.001)
    return x + 1


def bad_pallas_traced_clock(x_ref, o_ref):
    time.time()
    o_ref[...] = x_ref[...] * 2


def bad_pallas_captured_mutation(x_ref, o_ref):
    _captured.append(x_ref)
    o_ref[...] = x_ref[...]


def suppressed_pallas_print(x_ref, o_ref):
    # dos-lint: disable=jit-purity -- fixture: trace-time print wanted
    #   to exercise pallas_call suppression
    print("tracing")
    o_ref[...] = x_ref[...]


def _invoke_pallas(pallas_call, x):
    # marks the kernels above as pallas_call-wrapped (the rule's
    # _wrapped_names path — same mechanism as jax.jit(fn))
    pallas_call(bad_pallas_traced_clock)(x)
    pallas_call(bad_pallas_captured_mutation)(x)
    pallas_call(suppressed_pallas_print)(x)


@jax.jit
def clean_pure(x):
    y = x * 2
    local = [y]
    local.append(y + 1)
    return local[0] + local[1]


def clean_pallas_kernel(x_ref, o_ref):
    scratch = x_ref[...] * 2
    o_ref[...] = scratch + 1


def _invoke_clean_pallas(pallas_call, x):
    pallas_call(clean_pallas_kernel)(x)
