"""dos-lint fixture: a disable comment without a justification is
itself a finding and silences nothing."""

import os


def bad_unjustified(fifo_path):
    # dos-lint: disable=fifo-hygiene
    return open(fifo_path, "r")
