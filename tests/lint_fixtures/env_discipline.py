"""dos-lint fixture: env-discipline."""

import os

from distributed_oracle_search_tpu.utils.env import env_cast


def bad_direct_read():
    return os.environ.get("DOS_FIXTURE_KNOB", "1")


def suppressed_read():
    # dos-lint: disable=env-discipline -- fixture: exercising the
    #   suppression path of the checker itself
    return os.getenv("DOS_FIXTURE_KNOB")


def clean_read():
    return env_cast("DOS_FIXTURE_KNOB", 1, int)
