"""Gather-free shift relaxation: equivalence with the ELL gather path.

The shift path must be a pure optimization — bit-identical distances and
first moves on any graph, with automatic fallback when the node-id layout
gives poor shift coverage.
"""

import numpy as np
import pytest

from distributed_oracle_search_tpu.data import Graph, synth_city_graph
from distributed_oracle_search_tpu.data.graph import INF
from distributed_oracle_search_tpu.models.cpd import pick_build_kernel
from distributed_oracle_search_tpu.models.reference import dist_to_target
from distributed_oracle_search_tpu.ops import DeviceGraph
from distributed_oracle_search_tpu.ops.bellman_ford import dist_to_targets
from distributed_oracle_search_tpu.ops.shift_relax import (
    ShiftGraph, build_fm_columns_shift, dist_to_targets_shift,
)


def _shuffled(graph: Graph, seed=5) -> Graph:
    """Same graph, node ids randomly permuted — destroys shift locality."""
    rng = np.random.default_rng(seed)
    perm = rng.permutation(graph.n)
    return Graph(graph.xs[np.argsort(perm)], graph.ys[np.argsort(perm)],
                 perm[graph.src], perm[graph.dst], graph.w)


def test_shift_split_partitions_edges(toy_graph):
    shifts, w_shift, nbr_left, w_left = toy_graph.shift_split()
    on_shift = int((w_shift < int(INF)).sum())
    left = int((w_left < int(INF)).sum()) if w_left.size else 0
    # parallel (same src, same delta) edges collapse to their min in
    # w_shift, so covered slots <= covered edges; total never exceeds m
    assert on_shift + left <= toy_graph.m
    assert on_shift > 0


def test_shift_split_takes_min_of_parallel_edges():
    # two parallel edges 0->1 with different weights: shift slot = min
    g = Graph([0, 1], [0, 0], [0, 0], [1, 1], [7, 3])
    shifts, w_shift, _, _ = g.shift_split()
    si = shifts.index(1)
    assert w_shift[si][0] == 3


@pytest.mark.parametrize("batch", [1, 7, 32])
def test_shift_dist_matches_ell(toy_graph, batch):
    dg = DeviceGraph.from_graph(toy_graph)
    sg = ShiftGraph.from_graph(toy_graph)
    tg = np.arange(batch, dtype=np.int32)
    a = np.asarray(dist_to_targets(dg, tg))
    b = np.asarray(dist_to_targets_shift(sg, tg))
    assert (a == b).all()


def test_shift_dist_matches_on_shuffled_ids(toy_graph):
    """Poor locality -> big leftover ELL; results must still be exact."""
    g = _shuffled(toy_graph)
    dg = DeviceGraph.from_graph(g)
    sg = ShiftGraph.from_graph(g, max_shifts=4)
    assert sg.k_left > 0  # the fallback path is actually exercised
    tg = np.arange(10, dtype=np.int32)
    a = np.asarray(dist_to_targets(dg, tg))
    b = np.asarray(dist_to_targets_shift(sg, tg))
    assert (a == b).all()
    # and both agree with the CPU oracle
    for t in range(5):
        assert (a[t] == dist_to_target(g, t)).all()


def test_shift_fm_matches_ell(toy_graph):
    from distributed_oracle_search_tpu.ops import build_fm_columns

    dg = DeviceGraph.from_graph(toy_graph)
    sg = ShiftGraph.from_graph(toy_graph)
    tg = np.arange(12, dtype=np.int32)
    a = np.asarray(build_fm_columns(dg, tg))
    b = np.asarray(build_fm_columns_shift(dg, sg, tg))
    assert (a == b).all()


def test_shift_handles_padding_targets(toy_graph):
    sg = ShiftGraph.from_graph(toy_graph)
    tg = np.array([3, -1, 5], np.int32)
    d = np.asarray(dist_to_targets_shift(sg, tg))
    assert (d[1] >= int(INF)).all()          # padding row all-INF
    assert d[0][3] == 0 and d[2][5] == 0


def test_auto_method_selection(toy_graph):
    kind, st = pick_build_kernel(toy_graph, "ell")
    assert kind == "ell" and st is None
    kind, st = pick_build_kernel(toy_graph, "shift")
    assert kind == "shift" and st is not None
    with pytest.raises(ValueError, match="unknown build method"):
        pick_build_kernel(toy_graph, "bogus")


def test_oracle_build_methods_agree(toy_graph, toy_queries):
    from distributed_oracle_search_tpu.models.cpd import CPDOracle
    from distributed_oracle_search_tpu.parallel import DistributionController
    from distributed_oracle_search_tpu.parallel.mesh import make_mesh

    dc = DistributionController("tpu", None, 4, toy_graph.n)
    o1 = CPDOracle(toy_graph, dc, mesh=make_mesh(n_workers=4))
    o1.build(method="ell")
    o2 = CPDOracle(toy_graph, dc, mesh=make_mesh(n_workers=4))
    o2.build(method="shift")
    assert (np.asarray(o1.fm) == np.asarray(o2.fm)).all()
    c1, _, f1 = o1.query(toy_queries)
    c2, _, f2 = o2.query(toy_queries)
    assert (c1 == c2).all() and (f1 == f2).all() and f1.all()
