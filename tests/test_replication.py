"""Shard replication: placement, wire round-trip, replica builds +
anti-entropy, failover routing, and hedged dispatch.

Tier-1 gates: R=1 stays byte-identical to the unreplicated system
(placement, wire format, routing); an R=2 serve world with one breaker
forced open answers every request via failover (zero degraded); a
campaign with a crashed primary exits 0 with ``failover_total > 0`` and
answer columns identical to a fault-free run; hedges win under an
injected delay within the configured rate budget. The mid-run
kill-the-primary chaos drill stays behind ``slow``.
"""

import csv
import json
import os
import threading
import time

import numpy as np
import pytest

from distributed_oracle_search_tpu.cli import process_query as pq
from distributed_oracle_search_tpu.cli.gen_distribute_conf import (
    main as gen_conf_main,
)
from distributed_oracle_search_tpu.data import (
    ensure_synth_dataset, read_scen,
)
from distributed_oracle_search_tpu.data.graph import Graph
from distributed_oracle_search_tpu.models.cpd import (
    anti_entropy, build_replica_shards, read_manifest, shard_block_name,
    verify_exit_code, verify_index, write_index_manifest,
)
from distributed_oracle_search_tpu.obs import metrics as obs_metrics
from distributed_oracle_search_tpu.parallel.partition import (
    DistributionController, UNROUTABLE, parse_conf,
)
from distributed_oracle_search_tpu.serving import (
    EngineDispatcher, HedgeConfig, HedgeTracker, ServeConfig,
    ServingFrontend,
)
from distributed_oracle_search_tpu.testing import faults
from distributed_oracle_search_tpu.transport import resilience
from distributed_oracle_search_tpu.transport.wire import RuntimeConfig
from distributed_oracle_search_tpu.utils.config import ClusterConfig
from distributed_oracle_search_tpu.worker import FifoServer, stop_server
from distributed_oracle_search_tpu.worker.build import main as build_main
from distributed_oracle_search_tpu.worker.engine import ShardEngine

pytestmark = pytest.mark.replication

N_WORKERS = 3


def _counter(name: str) -> float:
    return obs_metrics.REGISTRY.snapshot()["counters"].get(name, 0)


# ------------------------------------------------------------ placement

def test_replica_placement_distinct_workers():
    dc = DistributionController("mod", 5, 5, 100, replication=3)
    for wid in range(5):
        hosts = dc.replica_workers(wid)
        assert hosts[0] == wid                      # rank 0 = primary
        assert len(set(hosts)) == 3                 # distinct workers
        for r, h in enumerate(hosts):
            assert dc.replica_rank(wid, h) == r
        # replica_shards is the exact inverse
        for h in hosts:
            assert wid in dc.replica_shards(h)


def test_replication_one_is_identity():
    dc = DistributionController("mod", 4, 4, 64)
    assert dc.replication == 1
    assert dc.replica_workers(2) == [2]
    assert dc.replica_shards(2) == [2]
    with pytest.raises(ValueError):
        dc.replica_rank(2, 3)


def test_replication_bounds_validated():
    with pytest.raises(ValueError):
        DistributionController("mod", 4, 4, 64, replication=5)
    with pytest.raises(ValueError):
        DistributionController("mod", 4, 4, 64, replication=0)


# ------------------------------------------------------- wire round-trip

def test_format_conf_r1_byte_identical_legacy():
    """The R=1 wire format must not move: legacy consumers parse by
    position."""
    dc = DistributionController("mod", 4, 4, 12, block_size=2)
    lines = dc.format_conf().split("\n")
    assert lines[0] == "node,wid,bid,bidx"
    assert all(len(ln.split(",")) == 4 for ln in lines[1:])


def test_parse_format_round_trip_replicated():
    dc = DistributionController("mod", 4, 4, 32, block_size=4,
                                replication=3)
    p = parse_conf(dc.format_conf())
    assert p["replication"] == 3
    tab = dc.table()
    np.testing.assert_array_equal(p["node"], tab[:, 0])
    np.testing.assert_array_equal(p["wid"], tab[:, 1])
    np.testing.assert_array_equal(p["bid"], tab[:, 2])
    np.testing.assert_array_equal(p["bidx"], tab[:, 3])
    np.testing.assert_array_equal(p["replicas"], dc.replica_table())


def test_parse_conf_legacy_and_unknown_columns():
    # legacy 4-column format -> replication 1, no replica columns
    legacy = "node,wid,bid,bidx\n0,0,0,0\n1,1,0,0"
    p = parse_conf(legacy)
    assert p["replication"] == 1 and p["replicas"].shape == (2, 0)
    # unknown columns are tolerated wherever they appear (compat
    # contract shared with the wire codecs)
    future = ("node,wid,future_key,bid,bidx,rep1,another\n"
              "0,0,99,0,0,1,7\n1,1,99,0,0,2,7")
    p2 = parse_conf(future)
    assert p2["replication"] == 2
    np.testing.assert_array_equal(p2["replicas"][:, 0], [1, 2])
    np.testing.assert_array_equal(p2["bid"], [0, 0])
    with pytest.raises(ValueError):
        parse_conf("node,wid,bid\n0,0,0")            # missing bidx


def test_gen_distribute_conf_cli_emits_replica_table(capsys):
    gen_conf_main(["--nodenum", "8", "--maxworker", "4",
                   "--partmethod", "mod", "--partkey", "4",
                   "--replication", "2"])
    out = capsys.readouterr().out
    p = parse_conf(out)
    assert p["replication"] == 2
    np.testing.assert_array_equal(
        p["replicas"][:, 0], (np.arange(8) % 4 + 1) % 4)


# --------------------------------------------------- replica-aware routing

def test_group_queries_r1_byte_identical():
    """Pinned: with no dead set, routing is identical whatever R is —
    and identical to the pre-replication controller."""
    rng = np.random.default_rng(7)
    qs = rng.integers(0, 100, size=(50, 2))
    base = DistributionController("mod", 4, 4, 100)
    repl = DistributionController("mod", 4, 4, 100, replication=3)
    g1, g2 = base.group_queries(qs), repl.group_queries(qs)
    assert list(g1) == list(g2)
    for wid in g1:
        np.testing.assert_array_equal(g1[wid], g2[wid])


def test_group_queries_routes_around_dead_workers():
    dc = DistributionController("mod", 4, 4, 100, replication=2)
    qs = np.stack([np.zeros(100, np.int64), np.arange(100)], axis=1)
    groups = dc.group_queries(qs, dead={1})
    assert 1 not in groups
    # shard 1's queries moved to its rank-1 replica host (worker 2)
    moved = groups[2]
    assert (dc.worker_of(moved[:, 1]) != 2).any()   # some are shard 1's
    total = sum(len(p) for p in groups.values())
    assert total == len(qs)                          # nothing dropped


def test_group_queries_all_replicas_dead_is_unroutable():
    """All replicas of a node dead => the query comes back in the
    UNROUTABLE bucket immediately — never silently dropped, never
    routed to a dead worker (the caller sheds it UNAVAILABLE)."""
    dc = DistributionController("mod", 4, 4, 100, replication=2)
    qs = np.array([[0, 1], [0, 2]])     # targets owned by shards 1, 2
    groups = dc.group_queries(qs, dead={1, 2})
    assert UNROUTABLE in groups
    np.testing.assert_array_equal(groups[UNROUTABLE], [[0, 1]])
    np.testing.assert_array_equal(groups[3], [[0, 2]])  # 2's replica


def test_group_queries_active_worker_with_replicas():
    dc = DistributionController("mod", 4, 4, 100, replication=2)
    qs = np.stack([np.zeros(16, np.int64), np.arange(16)], axis=1)
    # restricting to worker 2 keeps what ROUTES to 2: its own shard
    # plus shard 1's failover traffic when 1 is dead
    only2 = dc.group_queries(qs, active_worker=2)
    assert list(only2) == [2]
    only2_dead = dc.group_queries(qs, active_worker=2, dead={1})
    assert set(only2_dead) == {2}
    assert len(only2_dead[2]) == len(only2[2]) + 4   # + shard 1's


# -------------------------------------------------------- build fixtures

@pytest.fixture(scope="module")
def repl_world(tmp_path_factory):
    """3-worker world with a replicated (R=2) CPD index: primary block
    sets + replica block sets + a manifest recording both."""
    datadir = str(tmp_path_factory.mktemp("repl-data"))
    paths = ensure_synth_dataset(datadir, width=8, height=6,
                                 n_queries=45, seed=23)
    outdir = os.path.join(datadir, "index")
    for wid in range(N_WORKERS):
        build_main(["--input", paths["xy"], "--partmethod", "mod",
                    "--partkey", str(N_WORKERS), "--workerid", str(wid),
                    "--maxworker", str(N_WORKERS), "--outdir", outdir,
                    "--replication", "2"])
    g = Graph.from_xy(paths["xy"])
    dc = DistributionController("mod", N_WORKERS, N_WORKERS, g.n,
                                replication=2)
    write_index_manifest(outdir, dc)
    return datadir, paths, outdir, g, dc


def _repl_conf(repl_world, name, diffs):
    datadir, paths, outdir, g, dc = repl_world
    conf = ClusterConfig(
        workers=["localhost"] * N_WORKERS,
        partmethod="mod", partkey=N_WORKERS,
        outdir=outdir, xy_file=paths["xy"], scenfile=paths["scen"],
        diffs=diffs, nfs=datadir, replication=2,
    ).validate()
    path = os.path.join(datadir, name)
    conf.save(path)
    return conf, path


# ------------------------------------------------- build + anti-entropy

def test_replicated_manifest_and_digests(repl_world):
    datadir, paths, outdir, g, dc = repl_world
    man = read_manifest(outdir)
    assert man["replication"] == 2
    assert len(man["replica_files"]) == N_WORKERS
    for rf in man["replica_files"]:
        prim = rf.replace("-r01", "")
        assert prim in man["files"]
        # a copied/recomputed replica is bit-identical to its primary
        assert man["blocks"][rf]["digest"] == man["blocks"][prim]["digest"]
    # verify covers replicas too
    rep = verify_index(outdir, dc=dc)
    assert rep["total"] == 2 * N_WORKERS
    assert rep["ok"] == 2 * N_WORKERS
    assert verify_exit_code(rep) == 0


def test_anti_entropy_detects_and_heals(repl_world):
    datadir, paths, outdir, g, dc = repl_world
    clean = anti_entropy(outdir, dc, graph=g)
    assert clean["checked"] == N_WORKERS and not clean["mismatched"]
    man = read_manifest(outdir)
    victim = man["replica_files"][0]
    with open(os.path.join(outdir, victim), "r+b") as f:
        f.seek(96)
        f.write(b"\x7f" * 8)
    m0 = _counter("replica_digest_mismatches_total")
    report = anti_entropy(outdir, dc, graph=g, manifest=man)
    assert [e["file"] for e in report["mismatched"]] == [victim]
    assert report["healed"] == [victim]
    assert _counter("replica_digest_mismatches_total") - m0 == 1
    # healed in place: a second pass is clean, and the digest matches
    # the primary again
    again = anti_entropy(outdir, dc, graph=g)
    assert not again["mismatched"]
    man2 = read_manifest(outdir)
    assert (man2["blocks"][victim]["digest"]
            == man2["blocks"][victim.replace("-r01", "")]["digest"])


def test_missing_replica_set_materializes_by_copy(repl_world, tmp_path):
    """build_replica_shards on an index with only primaries copies the
    digest-valid primary bytes instead of recomputing."""
    datadir, paths, outdir, g, dc = repl_world
    alt = str(tmp_path / "prim-only")
    os.makedirs(alt)
    import shutil
    for wid in range(N_WORKERS):
        fname = shard_block_name(wid, 0)
        shutil.copy(os.path.join(outdir, fname), os.path.join(alt, fname))
    c0 = _counter("replica_blocks_copied_total")
    out = build_replica_shards(g, dc, 2, alt)
    # worker 2 hosts the rank-1 replica of shard 1
    assert out == {1: [shard_block_name(1, 0, 1)]}
    assert _counter("replica_blocks_copied_total") - c0 == 1
    assert os.path.exists(os.path.join(alt, shard_block_name(1, 0, 1)))


# ------------------------------------------------- engines and servers

def test_replica_engine_answers_identical(repl_world):
    datadir, paths, outdir, g, dc = repl_world
    qs = read_scen(paths["scen"])
    shard1 = qs[dc.worker_of(qs[:, 1]) == 1]
    prim = ShardEngine(g, dc, 1, outdir)
    repl = ShardEngine(g, dc, 2, outdir, shard=1)     # host 2, shard 1
    assert repl.shard == 1 and repl.replica == 1
    c_a, p_a, f_a, _ = prim.answer(shard1, RuntimeConfig())
    c_b, p_b, f_b, _ = repl.answer(shard1, RuntimeConfig())
    np.testing.assert_array_equal(c_a, c_b)
    np.testing.assert_array_equal(p_a, p_b)
    np.testing.assert_array_equal(f_a, f_b)
    # a replica engine still enforces ITS shard's routing invariant
    other = qs[dc.worker_of(qs[:, 1]) == 0][:2]
    with pytest.raises(ValueError, match="routing invariant"):
        repl.answer(other, RuntimeConfig())


def test_fifo_server_serves_hosted_replica_batch(repl_world, tmp_path):
    """A worker's server answers a batch targeting a shard it hosts as
    a replica (the wire half of failover), and books the replica
    counter; a batch for an un-hosted shard still fails loudly."""
    datadir, paths, outdir, g, dc = repl_world
    conf, _ = _repl_conf(repl_world, "conf-server.json", ["-"])
    server = FifoServer(conf, 2,
                        command_fifo=str(tmp_path / "w2.fifo"))
    qs = read_scen(paths["scen"])
    shard1 = qs[dc.worker_of(qs[:, 1]) == 1][:6]
    from distributed_oracle_search_tpu.transport.wire import (
        Request, write_query_file,
    )
    qfile = str(tmp_path / "query.test")
    write_query_file(qfile, shard1)
    r0 = _counter("server_replica_batches_total")
    row = server._handle(Request(RuntimeConfig(), qfile,
                                 str(tmp_path / "ans"), "-"))
    assert row.finished == len(shard1)
    assert _counter("server_replica_batches_total") - r0 == 1
    # shard 0 is NOT hosted by worker 2 at R=2 (hosted: {2, 1})
    shard0 = qs[dc.worker_of(qs[:, 1]) == 0][:2]
    write_query_file(qfile, shard0)
    with pytest.raises(ValueError, match="routing invariant"):
        server._handle(Request(RuntimeConfig(), qfile,
                               str(tmp_path / "ans"), "-"))


# ---------------------------------------------------- serve: failover

def test_serve_failover_smoke_zero_degraded(repl_world):
    """The tier-1 replication smoke: R=2 in-process serving with the
    primary's breaker forced open — every request is answered via the
    replica (zero degraded answers), failover_total moves."""
    datadir, paths, outdir, g, dc = repl_world
    conf, _ = _repl_conf(repl_world, "conf-serve.json", ["-"])
    dispatcher = EngineDispatcher(conf, graph=g, dc=dc)
    registry = resilience.BreakerRegistry(threshold=1, cooldown_s=600.0,
                                          enabled=True)
    registry.record(0, ok=False)               # shard 0's primary: OPEN
    f0 = _counter("failover_total")
    fe = ServingFrontend(dc, dispatcher,
                         sconf=ServeConfig(max_wait_ms=2.0,
                                           cache_bytes=0),
                         registry=registry,
                         hconf=HedgeConfig(enabled=False))
    fe.start()
    try:
        qs = read_scen(paths["scen"])
        shard0 = qs[dc.worker_of(qs[:, 1]) == 0][:8]
        res = [fe.query(int(s), int(t), timeout=60) for s, t in shard0]
        assert all(r.ok for r in res), [r.status for r in res]
        # answers match the primary engine's (replica rows identical)
        c, p, f, _ = dispatcher._engine_for(0).answer(
            shard0, RuntimeConfig())
        assert [r.cost for r in res] == c.tolist()
        assert [r.plen for r in res] == p.tolist()
    finally:
        fe.stop()
        registry.shutdown()
    assert _counter("failover_total") - f0 > 0


def test_serve_all_replicas_dead_sheds_unavailable(repl_world):
    """All replicas of the target shard breaker-dead => immediate
    UNAVAILABLE at admission, not a hang or a deadline'd timeout."""
    datadir, paths, outdir, g, dc = repl_world
    conf, _ = _repl_conf(repl_world, "conf-dead.json", ["-"])
    registry = resilience.BreakerRegistry(threshold=1, cooldown_s=600.0,
                                          enabled=True)
    registry.record(0, ok=False)     # shard 0's primary
    registry.record(1, ok=False)     # shard 0's rank-1 replica host
    fe = ServingFrontend(dc, EngineDispatcher(conf, graph=g, dc=dc),
                         sconf=ServeConfig(cache_bytes=0),
                         registry=registry,
                         hconf=HedgeConfig(enabled=False))
    fe.start()
    try:
        qs = read_scen(paths["scen"])
        s, t = (int(v) for v in qs[dc.worker_of(qs[:, 1]) == 0][0])
        t0 = time.monotonic()
        res = fe.query(s, t, timeout=5)
        assert res.status == "UNAVAILABLE"
        assert res.detail == "no-live-replica"
        assert time.monotonic() - t0 < 1.0
    finally:
        fe.stop()
        registry.shutdown()


def test_engine_dispatcher_builds_missing_replica_lazily(tmp_path):
    """Satellite: a bare --test-style world needs no pre-build step —
    the dispatcher materializes missing primary AND replica shards on
    first use."""
    datadir = str(tmp_path / "lazy")
    paths = ensure_synth_dataset(datadir, width=8, height=6,
                                 n_queries=16, seed=9)
    conf = ClusterConfig(
        workers=["localhost"] * 2, partmethod="mod", partkey=2,
        outdir=os.path.join(datadir, "idx"), xy_file=paths["xy"],
        scenfile=paths["scen"], nfs=datadir, replication=2,
    ).validate()
    g = Graph.from_xy(conf.xy_file)
    dc = DistributionController("mod", 2, 2, g.n, replication=2)
    disp = EngineDispatcher(conf, graph=g, dc=dc, build_missing=True)
    qs = read_scen(conf.scenfile)
    shard1 = qs[dc.worker_of(qs[:, 1]) == 1][:4]
    # replica route first: nothing on disk, so the replica block set of
    # shard 1 (hosted by worker 0) is built lazily
    c, p, f = disp.answer_batch(1, shard1, RuntimeConfig(), "-", via=0)
    assert os.path.exists(os.path.join(
        conf.outdir, shard_block_name(1, 0, 1)))
    c2, p2, f2 = disp.answer_batch(1, shard1, RuntimeConfig(), "-")
    np.testing.assert_array_equal(c, c2)
    np.testing.assert_array_equal(p, p2)


# ------------------------------------------------------- serve: hedging

class _SlowVia:
    """Via-aware dispatcher wrapper: dispatches through ``slow_wid``
    sleep ``delay_s`` before answering (the injected `delay` fault's
    in-process analog)."""

    def __init__(self, inner, slow_wid, delay_s):
        self.inner = inner
        self.slow_wid = slow_wid
        self.delay_s = delay_s

    def answer_batch(self, wid, queries, rconf, diff, via=None):
        v = wid if via is None else via
        if v == self.slow_wid:
            time.sleep(self.delay_s)
        return self.inner.answer_batch(wid, queries, rconf, diff,
                                       via=via)


def test_hedge_wins_under_delay_within_budget(repl_world):
    """Serve smoke: a slow primary loses to the hedge (hedges_won > 0)
    and the hedge rate stays within the configured budget."""
    datadir, paths, outdir, g, dc = repl_world
    conf, _ = _repl_conf(repl_world, "conf-hedge.json", ["-"])
    inner = EngineDispatcher(conf, graph=g, dc=dc)
    qs = read_scen(paths["scen"])
    shard0 = qs[dc.worker_of(qs[:, 1]) == 0][:8]
    # warm both engines off the clock (first-call JIT must not count
    # as "slow primary")
    inner.answer_batch(0, shard0, RuntimeConfig(), "-")
    inner.answer_batch(0, shard0, RuntimeConfig(), "-", via=1)
    hconf = HedgeConfig(enabled=True, min_delay_ms=25.0, budget=1.0)
    i0, w0 = (_counter("hedges_issued_total"),
              _counter("hedges_won_total"))
    fe = ServingFrontend(dc, _SlowVia(inner, 0, 0.4),
                         sconf=ServeConfig(max_wait_ms=1.0,
                                           cache_bytes=0, max_batch=8),
                         hconf=hconf)
    fe.start()
    try:
        res = [fe.query(int(s), int(t), timeout=60) for s, t in shard0]
        assert all(r.ok for r in res), [r.status for r in res]
    finally:
        fe.stop()
    issued = _counter("hedges_issued_total") - i0
    assert issued > 0
    assert _counter("hedges_won_total") - w0 > 0
    assert fe.hedge.hedge_rate() <= hconf.budget + 1e-9
    time.sleep(0.5)          # let loser primary threads drain


def test_hedge_budget_caps_rate(repl_world):
    datadir, paths, outdir, g, dc = repl_world
    conf, _ = _repl_conf(repl_world, "conf-budget.json", ["-"])
    inner = EngineDispatcher(conf, graph=g, dc=dc)
    qs = read_scen(paths["scen"])
    shard0 = qs[dc.worker_of(qs[:, 1]) == 0][:12]
    inner.answer_batch(0, shard0, RuntimeConfig(), "-")
    inner.answer_batch(0, shard0, RuntimeConfig(), "-", via=1)
    hconf = HedgeConfig(enabled=True, min_delay_ms=10.0, budget=0.25)
    d0 = _counter("hedges_budget_denied_total")
    fe = ServingFrontend(dc, _SlowVia(inner, 0, 0.2),
                         sconf=ServeConfig(max_wait_ms=1.0,
                                           cache_bytes=0, max_batch=1),
                         hconf=hconf)
    fe.start()
    try:
        futs = []
        for s, t in shard0:           # one at a time: many batches
            futs.append(fe.submit(int(s), int(t)))
        res = [f.result(60) for f in futs]
        assert all(r.ok for r in res)
    finally:
        fe.stop()
    tr = fe.hedge
    # the budget held: hedges <= grace + budget * dispatches
    assert tr._hedges <= tr.BUDGET_GRACE + hconf.budget * tr._dispatches
    assert _counter("hedges_budget_denied_total") - d0 > 0
    time.sleep(0.5)


class _FailingPrimary:
    """Via-aware dispatcher: the primary lane of ``bad_wid`` raises
    after ``delay_s`` (a wedged-then-erroring worker); replicas answer
    instantly."""

    def __init__(self, inner, bad_wid, delay_s):
        self.inner = inner
        self.bad_wid = bad_wid
        self.delay_s = delay_s

    def answer_batch(self, wid, queries, rconf, diff, via=None):
        v = wid if via is None else via
        if v == self.bad_wid:
            time.sleep(self.delay_s)
            raise RuntimeError("primary wedged")
        return self.inner.answer_batch(wid, queries, rconf, diff,
                                       via=via)


def test_hedge_win_still_opens_wedged_primary_breaker(repl_world):
    """A hedge win must NOT book a breaker success for the primary
    lane: the losing primary's own (eventual) failure records on ITS
    breaker, which OPENs after the threshold — so later batches stop
    waiting on the wedged worker instead of hedging forever."""
    datadir, paths, outdir, g, dc = repl_world
    conf, _ = _repl_conf(repl_world, "conf-wedge.json", ["-"])
    inner = EngineDispatcher(conf, graph=g, dc=dc)
    qs = read_scen(paths["scen"])
    shard0 = qs[dc.worker_of(qs[:, 1]) == 0][:6]
    inner.answer_batch(0, shard0, RuntimeConfig(), "-", via=1)  # warm
    registry = resilience.BreakerRegistry(threshold=2, cooldown_s=600.0,
                                          enabled=True)
    fe = ServingFrontend(dc, _FailingPrimary(inner, 0, 0.05),
                         sconf=ServeConfig(max_wait_ms=1.0,
                                           cache_bytes=0, max_batch=2),
                         registry=registry,
                         hconf=HedgeConfig(enabled=True,
                                           min_delay_ms=10.0,
                                           budget=1.0))
    fe.start()
    try:
        res = [fe.query(int(s), int(t), timeout=60) for s, t in shard0]
        assert all(r.ok for r in res)          # hedges answered them
        time.sleep(0.5)                        # losers record failures
        assert registry.get(0).state == resilience.OPEN, \
            "wedged primary's breaker never opened"
        # with the breaker OPEN the next batch skips the primary
        # entirely (failover, no hedge wait) and still answers
        s, t = (int(v) for v in shard0[0])
        assert fe.query(s, t, timeout=60).ok
    finally:
        fe.stop()
        registry.shutdown()
    time.sleep(0.3)


def test_hedge_tracker_adaptive_delay():
    tr = HedgeTracker(HedgeConfig(min_delay_ms=5.0, quantile=0.5))
    assert tr.delay_s(0) == pytest.approx(0.005)     # cold: the floor
    for v in (0.01, 0.02, 0.03, 0.04, 0.05, 0.06, 0.07, 0.08):
        tr.observe(0, v)
    # median of the window, floored
    assert tr.delay_s(0) == pytest.approx(0.04)
    assert tr.delay_s(1) == pytest.approx(0.005)     # other shard: cold


# -------------------------------------------------- campaign: failover

def _thread_servers(conf, fifo_dir, monkeypatch):
    os.makedirs(fifo_dir, exist_ok=True)
    fifos = {wid: os.path.join(fifo_dir, f"worker{wid}.fifo")
             for wid in range(conf.maxworker)}
    monkeypatch.setattr(pq, "command_fifo_path", lambda wid: fifos[wid])
    servers = [FifoServer(conf, wid, command_fifo=fifos[wid])
               for wid in range(conf.maxworker)]
    threads = [threading.Thread(target=s.serve_forever, daemon=True)
               for s in servers]
    for t in threads:
        t.start()
    for fifo in fifos.values():
        for _ in range(100):
            if os.path.exists(fifo):
                break
            time.sleep(0.02)
    return fifos, threads


def _stop_all(fifos, threads):
    for fifo in fifos.values():
        stop_server(fifo, deadline_s=5.0)
    for t in threads:
        t.join(timeout=15)


def _answer_columns(outdir):
    """parts.csv minus the timing columns — the deterministic answer
    payload of a campaign."""
    with open(os.path.join(outdir, "parts.csv")) as fh:
        rows = list(csv.reader(fh))
    hdr = rows[0]
    keep = [hdr.index(k) for k in
            ("expe", "n_expanded", "n_touched", "plen", "finished",
             "size")]
    return [[r[i] for i in keep] for r in rows[1:]]


def test_campaign_failover_clean_exit(repl_world, tmp_path,
                                      monkeypatch):
    """A campaign whose worker-1 engine crashes on every batch still
    exits 0: each shard-1 batch fails over to worker 2's replica, no
    degraded.json, answers bit-identical to a fault-free run."""
    datadir = repl_world[0]
    conf, conf_path = _repl_conf(repl_world, "conf-campaign.json",
                                 ["-", "-"])
    monkeypatch.setenv("DOS_RETRY_MAX", "0")
    # fault-free golden run
    faults.reset()
    monkeypatch.delenv("DOS_FAULTS", raising=False)
    fifos, threads = _thread_servers(conf, str(tmp_path / "f0"),
                                     monkeypatch)
    out0 = str(tmp_path / "artifacts-clean")
    try:
        rc = pq.main(["-c", conf_path, "--backend", "host", "-o", out0])
    finally:
        _stop_all(fifos, threads)
    assert rc == pq.EXIT_CLEAN

    # faulted run: worker 1's engine crashes every batch
    faults.reset()
    monkeypatch.setenv("DOS_FAULTS", "crash-engine;wid=1;times=inf")
    f0 = _counter("failover_total")
    fifos, threads = _thread_servers(conf, str(tmp_path / "f1"),
                                     monkeypatch)
    out1 = str(tmp_path / "artifacts-faulted")
    try:
        rc = pq.main(["-c", conf_path, "--backend", "host", "-o", out1])
    finally:
        _stop_all(fifos, threads)
    assert rc == pq.EXIT_CLEAN                       # exit 0, not 3
    assert not os.path.exists(os.path.join(out1, "degraded.json"))
    assert _counter("failover_total") - f0 >= 2      # both rounds
    assert _answer_columns(out0) == _answer_columns(out1)


def test_campaign_all_replicas_down_books_degraded(repl_world,
                                                   tmp_path,
                                                   monkeypatch):
    """When a shard's primary AND replica both fail, the batch books
    degraded with the replica trail recorded — failover widens
    availability, it never hides a real loss."""
    datadir = repl_world[0]
    conf, conf_path = _repl_conf(repl_world, "conf-bothdown.json", ["-"])
    faults.reset()
    # shard 1's primary (w1) and its replica host (w2) both crash
    monkeypatch.setenv("DOS_FAULTS",
                       "crash-engine;wid=1;times=inf,"
                       "crash-engine;wid=2;times=inf")
    monkeypatch.setenv("DOS_RETRY_MAX", "0")
    fifos, threads = _thread_servers(conf, str(tmp_path / "fifos"),
                                     monkeypatch)
    out = str(tmp_path / "artifacts")
    try:
        rc = pq.main(["-c", conf_path, "--backend", "host", "-o", out])
    finally:
        _stop_all(fifos, threads)
    assert rc == pq.EXIT_DEGRADED
    man = json.load(open(os.path.join(out, "degraded.json")))
    # shard 2's batch failed over to worker 0 and survived; shards 1
    # and 2 both crashed as PRIMARIES, but only shard 1 lost both
    # replicas (w1 + w2); shard 2's replica is healthy w0
    assert man["failed_workers"] == [1]
    trail = man["failed_batches"][0]["replicas_tried"]
    assert [e["wid"] for e in trail] == [1, 2]
    assert all(e["reason"] == "send-failed" for e in trail)


# ------------------------------------------------------ the chaos drill

@pytest.mark.slow
def test_chaos_kill_primary_mid_campaign(repl_world, tmp_path,
                                         monkeypatch):
    """The acceptance drill: worker 1's server process dies MID-RUN
    (kill-mid-batch after it already served round 0). The campaign
    completes clean — exit 0, failover_total > 0, zero degraded
    entries — and every answer column is bit-identical to a fault-free
    run."""
    datadir = repl_world[0]
    conf, conf_path = _repl_conf(repl_world, "conf-chaos.json",
                                 ["-", "-", "-"])
    monkeypatch.setenv("DOS_RETRY_MAX", "0")
    monkeypatch.setenv("DOS_SEND_TIMEOUT_S", "15")

    faults.reset()
    monkeypatch.delenv("DOS_FAULTS", raising=False)
    fifos, threads = _thread_servers(conf, str(tmp_path / "f0"),
                                     monkeypatch)
    out0 = str(tmp_path / "clean")
    try:
        rc = pq.main(["-c", conf_path, "--backend", "host", "-o", out0])
    finally:
        _stop_all(fifos, threads)
    assert rc == pq.EXIT_CLEAN

    faults.reset()
    # the in-thread analog of a hard crash: the server thread reads
    # round 1's request for worker 1 and dies (mode=raise returns from
    # the serve loop, tearing down its command FIFO like a dead
    # process's would be); the head's send times out, the next rounds
    # fail fast on the missing FIFO, and every shard-1 batch from
    # round 1 on fails over to worker 2's replica
    monkeypatch.setenv("DOS_FAULTS",
                       "kill-mid-batch;wid=1;mode=raise;after=1")
    f0 = _counter("failover_total")
    fifos, threads = _thread_servers(conf, str(tmp_path / "f1"),
                                     monkeypatch)
    out1 = str(tmp_path / "chaos")
    try:
        rc = pq.main(["-c", conf_path, "--backend", "host", "-o", out1])
    finally:
        _stop_all(fifos, threads)
    assert rc == pq.EXIT_CLEAN, "campaign must survive the kill"
    assert not os.path.exists(os.path.join(out1, "degraded.json"))
    assert _counter("failover_total") - f0 >= 1
    assert _answer_columns(out0) == _answer_columns(out1)
