"""Subprocess body for the 2-process SHARDED STREAMED serving test (not
a pytest file).

Each controller serves only its own workers' queries, streaming only
those workers' rows onto its own devices; the disjoint partials merge
via allgather (``cli.process_query._StreamedServe``). Prints the merged
cost checksum and this process's streamed byte count so the test can
assert (a) every controller sees the full merged answer and (b) the
upload work actually split.

Usage: multihost_streamed_worker.py <pid> <nproc> <coord> <xy> <index>
       <scen>
"""

import sys

pid, nproc, coord, xy, index, scen = (
    int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4],
    sys.argv[5], sys.argv[6])

import os  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from distributed_oracle_search_tpu.parallel.multihost import (  # noqa: E402
    initialize,
)

initialize(coordinator=coord, num_processes=nproc, process_id=pid,
           cpu_devices_per_process=4)

import numpy as np  # noqa: E402

from distributed_oracle_search_tpu.cli.process_query import (  # noqa: E402
    _StreamedServe,
)
from distributed_oracle_search_tpu.data import (  # noqa: E402
    Graph, read_scen,
)
from distributed_oracle_search_tpu.parallel import (  # noqa: E402
    DistributionController,
)

g = Graph.from_xy(xy)
dc = DistributionController("mod", 4, 4, g.n)
queries = read_scen(scen)
serve = _StreamedServe(g, dc, index, chunk=64)
assert serve.pcount == nproc and serve.pidx == pid
cost, plen, fin = serve.query(queries)
assert bool(np.asarray(fin).all()), "merged campaign left queries behind"
stats = serve.st.last_stats
print(f"STREAMED_OK process={pid} nproc={nproc} "
      f"cost_sum={int(np.asarray(cost).sum())} "
      f"bytes={stats['bytes_streamed']} "
      f"chunks={stats['row_chunks']}")
