"""Streamed serving: disk-index row-streaming must match the resident
oracle exactly (same walk kernel, different memory plan)."""

import numpy as np
import pytest

from distributed_oracle_search_tpu.data import (
    synth_city_graph, synth_scenario, synth_diff,
)
from distributed_oracle_search_tpu.models.cpd import (
    CPDOracle, build_worker_shard, write_index_manifest,
)
from distributed_oracle_search_tpu.models.streamed import StreamedCPDOracle
from distributed_oracle_search_tpu.parallel import DistributionController
from distributed_oracle_search_tpu.parallel.mesh import make_mesh


@pytest.fixture(scope="module")
def stream_setup(tmp_path_factory):
    outdir = str(tmp_path_factory.mktemp("cpd-index"))
    g = synth_city_graph(16, 12, seed=5)
    dc = DistributionController("mod", 4, 4, g.n)
    for wid in range(4):
        build_worker_shard(g, dc, wid, outdir, chunk=64)
    write_index_manifest(outdir, dc)
    queries = synth_scenario(g.n, 300, seed=6)
    resident = CPDOracle(g, dc, mesh=make_mesh(n_workers=4)).load(outdir)
    return g, dc, outdir, queries, resident


def test_streamed_matches_resident_free_flow(stream_setup, monkeypatch):
    g, dc, outdir, queries, resident = stream_setup
    monkeypatch.delenv("DOS_STREAM_PACK4", raising=False)
    st = StreamedCPDOracle(g, dc, outdir, row_chunk=37)  # force many chunks
    c_r, p_r, f_r = resident.query(queries)
    c_s, p_s, f_s = st.query(queries)
    np.testing.assert_array_equal(c_s, c_r)
    np.testing.assert_array_equal(p_s, p_r)
    np.testing.assert_array_equal(f_s, f_r)
    stats = st.last_stats
    assert stats["n_queries"] == len(queries)
    if stats["mode"] == "compacted":
        assert stats["row_chunks"] == -(-stats["distinct_targets"] // 37)
    else:
        # range chunks cover gaps too, so there are at least as many
        assert stats["row_chunks"] >= -(-stats["distinct_targets"] // 37)
    # both modes upload whole [C, N] chunks (range mode covers gap rows,
    # compacted mode pads the tail chunk); 4-bit packing roughly halves
    # the wire bytes (nibbles + a tiny exception list per chunk)
    assert st.pack4
    assert stats["bytes_raw"] == stats["row_chunks"] * 37 * g.n
    assert stats["bytes_streamed"] < 0.55 * stats["bytes_raw"]


def test_streamed_matches_resident_diffed(stream_setup):
    g, dc, outdir, queries, resident = stream_setup
    w_diff = g.weights_with_diff(synth_diff(g, frac=0.2, seed=7))
    st = StreamedCPDOracle(g, dc, outdir, row_chunk=64)
    c_r, p_r, f_r = resident.query(queries, w_query=w_diff)
    c_s, p_s, f_s = st.query(queries, w_query=w_diff)
    np.testing.assert_array_equal(c_s, c_r)
    np.testing.assert_array_equal(p_s, p_r)
    np.testing.assert_array_equal(f_s, f_r)


def test_streamed_k_moves_budget(stream_setup):
    g, dc, outdir, queries, resident = stream_setup
    st = StreamedCPDOracle(g, dc, outdir, row_chunk=128)
    c_r, p_r, f_r = resident.query(queries, k_moves=3)
    c_s, p_s, f_s = st.query(queries, k_moves=3)
    np.testing.assert_array_equal(c_s, c_r)
    np.testing.assert_array_equal(p_s, p_r)
    np.testing.assert_array_equal(f_s, f_r)
    assert (np.asarray(p_s) <= 3).all()


def test_streamed_query_paths_matches_resident(stream_setup):
    """Path-prefix extraction from the streamed index must equal the
    resident oracle's rows exactly (same fm, same scan kernel)."""
    g, dc, outdir, queries, resident = stream_setup
    st = StreamedCPDOracle(g, dc, outdir, row_chunk=37)
    n_r, m_r = resident.query_paths(queries, k=5)
    n_s, m_s = st.query_paths(queries, k=5)
    np.testing.assert_array_equal(n_s, n_r)
    np.testing.assert_array_equal(m_s, m_r)
    with pytest.raises(ValueError, match="positive"):
        st.query_paths(queries, k=0)


def test_streamed_rejects_mismatched_controller(stream_setup):
    g, dc, outdir, _, _ = stream_setup
    other = DistributionController("mod", 2, 2, g.n)
    with pytest.raises(ValueError, match="was built with"):
        StreamedCPDOracle(g, other, outdir)


def test_streamed_chunk_cache_round2_streams_zero(stream_setup,
                                                  monkeypatch):
    """The device LRU makes round 2 of an overlapping campaign stream
    ZERO bytes, and a diff round reuses the SAME chunks (fm rows hold
    free-flow moves; diffs only change cost accumulation)."""
    g, dc, outdir, queries, resident = stream_setup
    monkeypatch.setenv("DOS_STREAM_RANGE_DENSITY", "0.0")   # force range
    st = StreamedCPDOracle(g, dc, outdir, row_chunk=37)
    c1, p1, f1 = st.query(queries)
    assert st.last_stats["cache_misses"] == st.last_stats["row_chunks"]
    assert st.last_stats["bytes_streamed"] > 0
    c2, p2, f2 = st.query(queries)
    assert st.last_stats["bytes_streamed"] == 0
    assert st.last_stats["cache_hits"] == st.last_stats["row_chunks"]
    np.testing.assert_array_equal(c1, c2)
    np.testing.assert_array_equal(p1, p2)
    np.testing.assert_array_equal(f1, f2)
    w_diff = g.weights_with_diff(synth_diff(g, frac=0.2, seed=9))
    c_d, p_d, f_d = st.query(queries, w_query=w_diff)
    assert st.last_stats["bytes_streamed"] == 0    # diff round: all hits
    c_r, p_r, f_r = resident.query(queries, w_query=w_diff)
    np.testing.assert_array_equal(c_d, c_r)
    np.testing.assert_array_equal(p_d, p_r)
    np.testing.assert_array_equal(f_d, f_r)
    # compacted mode: identical replayed campaign is content-addressed
    monkeypatch.setenv("DOS_STREAM_RANGE_DENSITY", "2.0")
    st_c = StreamedCPDOracle(g, dc, outdir, row_chunk=37)
    c_c1, _, _ = st_c.query(queries)
    assert st_c.last_stats["mode"] == "compacted"
    assert st_c.last_stats["bytes_streamed"] > 0
    c_c2, _, _ = st_c.query(queries)
    assert st_c.last_stats["bytes_streamed"] == 0
    np.testing.assert_array_equal(c_c1, c_c2)


def test_streamed_query_multi_matches_sequential(stream_setup):
    """The fused multi-diff streamed campaign must equal per-diff
    sequential streamed rounds, and a warm fused campaign streams
    nothing (one walk AND zero upload)."""
    g, dc, outdir, queries, resident = stream_setup
    w_list = [None,
              g.weights_with_diff(synth_diff(g, frac=0.2, seed=13)),
              g.weights_with_diff(synth_diff(g, frac=0.4, seed=14))]
    st = StreamedCPDOracle(g, dc, outdir, row_chunk=37)
    cm, pm, fm = st.query_multi(queries, w_list)
    assert cm.shape == (3, len(queries))
    for di, w in enumerate(w_list):
        c1, p1, f1 = st.query(queries, w_query=w)
        np.testing.assert_array_equal(cm[di], c1)
        np.testing.assert_array_equal(pm, p1)
        np.testing.assert_array_equal(fm, f1)
    c2, p2, f2 = st.query_multi(queries, w_list)   # warm replay
    assert st.last_stats["bytes_streamed"] == 0
    np.testing.assert_array_equal(c2, cm)
    with pytest.raises(ValueError, match="at least one"):
        st.query_multi(queries, [])


def test_streamed_cache_budget_and_disable(stream_setup, monkeypatch):
    """Residency never exceeds cache_bytes (LRU evicts); 0 disables."""
    g, dc, outdir, queries, resident = stream_setup
    monkeypatch.setenv("DOS_STREAM_RANGE_DENSITY", "0.0")   # force range
    two_chunks = 2 * 37 * g.n
    st = StreamedCPDOracle(g, dc, outdir, row_chunk=37,
                           cache_bytes=two_chunks)
    c_s, p_s, f_s = st.query(queries)
    assert st.last_stats["row_chunks"] > 2         # forced eviction
    held = sum(v.nbytes for v in st._chunk_cache.values())
    assert 0 < held <= two_chunks
    c_r, p_r, f_r = resident.query(queries)
    np.testing.assert_array_equal(c_s, c_r)

    st0 = StreamedCPDOracle(g, dc, outdir, row_chunk=37, cache_bytes=0)
    st0.query(queries)
    c0, p0, f0 = st0.query(queries)
    assert st0.last_stats["cache_hits"] == 0
    assert st0.last_stats["bytes_streamed"] > 0
    np.testing.assert_array_equal(c0, c_r)


def test_streamed_pack4_roundtrip_and_disable(stream_setup, monkeypatch):
    """4-bit packed uploads must answer identically to unpacked ones,
    and DOS_STREAM_PACK4=0 falls back to raw int8 chunks."""
    import numpy as np

    from distributed_oracle_search_tpu.models.streamed import (
        _pack4, _unpack4,
    )

    g, dc, outdir, queries, resident = stream_setup
    monkeypatch.delenv("DOS_STREAM_PACK4", raising=False)
    # kernel-level roundtrip incl. odd N, the -1 marker, AND escape
    # slots (>= 14 — hub-degree entries carried by the exception list)
    jnp = __import__("jax").numpy
    rng = np.random.default_rng(3)
    fm = rng.integers(-1, 14, (5, 33)).astype(np.int8)
    fm[0, 0] = 17                      # (0,0) itself an escape entry
    fm[2, 31] = 14                     # the escape boundary value
    fm[4, 5] = 20                      # hub-degree slot
    packed, er, ec, ev = _pack4(fm)
    got = np.asarray(_unpack4(jnp.asarray(packed), 33, jnp.asarray(er),
                              jnp.asarray(ec), jnp.asarray(ev)))
    np.testing.assert_array_equal(got, fm)
    # no-escape input: pad triple is the (0,0) identity write
    fm2 = rng.integers(-1, 14, (4, 10)).astype(np.int8)
    packed2, er2, ec2, ev2 = _pack4(fm2)
    got2 = np.asarray(_unpack4(jnp.asarray(packed2), 10,
                               jnp.asarray(er2), jnp.asarray(ec2),
                               jnp.asarray(ev2)))
    np.testing.assert_array_equal(got2, fm2)
    # degenerate (mostly-escape) input refuses to pack
    assert _pack4(np.full((3, 8), 20, np.int8)) is None
    # a chunk taller than uint16 escape-row range must refuse too (the
    # scatter indices would silently wrap and corrupt unpacked moves)
    tall = np.zeros((65537, 2), np.int8)
    tall[65536 - 1, 0] = 20
    assert _pack4(tall) is None
    # pack4-vs-raw comparison with the (better) RLE coder held off so
    # the pack4 fallback path is what actually streams
    monkeypatch.setenv("DOS_STREAM_RLE", "0")
    st_p = StreamedCPDOracle(g, dc, outdir, row_chunk=37)
    assert st_p.pack4
    c_p, p_p, f_p = st_p.query(queries)
    assert st_p.last_stats["chunks_packed"] > 0
    monkeypatch.setenv("DOS_STREAM_PACK4", "0")
    st_r = StreamedCPDOracle(g, dc, outdir, row_chunk=37)
    assert not st_r.pack4
    c_r, p_r, f_r = st_r.query(queries)
    np.testing.assert_array_equal(c_p, c_r)
    np.testing.assert_array_equal(p_p, p_r)
    np.testing.assert_array_equal(f_p, f_r)
    assert st_p.last_stats["bytes_streamed"] < \
        st_r.last_stats["bytes_streamed"]


def test_streamed_rle_roundtrip_and_disable(stream_setup, monkeypatch):
    """Transposed target-axis RLE uploads must answer identically to
    dense ones, fall back when runs are short, and DOS_STREAM_RLE=0
    disables the coder."""
    import jax.numpy as jnp

    from distributed_oracle_search_tpu.models.streamed import (
        _pack_rle, _unpack_rle,
    )

    g, dc, outdir, queries, resident = stream_setup
    rng = np.random.default_rng(11)
    # blocky columns: runs of ~16 consecutive target rows per column
    fm = np.repeat(rng.integers(-1, 6, (4, 50)).astype(np.int8),
                   16, axis=0)[:60]
    enc = _pack_rle(fm, pack4_viable=True)
    assert enc is not None
    plen, pval, counts = enc
    assert plen.dtype == np.uint8 and counts.sum() <= len(plen)
    got = np.asarray(_unpack_rle(jnp.asarray(plen), jnp.asarray(pval),
                                 jnp.asarray(counts), c=fm.shape[0]))
    np.testing.assert_array_equal(got, fm)
    # runs longer than 255 must split into uint8 pieces and still decode
    tall = np.tile(rng.integers(-1, 6, (1, 8)).astype(np.int8), (600, 1))
    enc_t = _pack_rle(tall, pack4_viable=True)
    assert enc_t is not None
    pl_t, pv_t, ct_t = enc_t
    got_t = np.asarray(_unpack_rle(jnp.asarray(pl_t), jnp.asarray(pv_t),
                                   jnp.asarray(ct_t), c=600))
    np.testing.assert_array_equal(got_t, tall)
    # incompressible input (every row distinct from its neighbor in
    # every column) must refuse — the dense upload is cheaper
    noise = np.arange(64 * 32, dtype=np.int64).reshape(64, 32)
    noise = ((noise % 13) - 1).astype(np.int8)
    assert (noise[1:] != noise[:-1]).all()
    assert _pack_rle(noise, pack4_viable=True) is None
    assert _pack_rle(np.zeros((1, 5), np.int8), True) is None  # c < 2

    # integration: RLE on vs off answer identically; when the coder
    # runs it beats the dense wire byte count
    monkeypatch.delenv("DOS_STREAM_RLE", raising=False)
    st_on = StreamedCPDOracle(g, dc, outdir, row_chunk=64)
    assert st_on.rle
    c_on, p_on, f_on = st_on.query(queries)
    stats_on = dict(st_on.last_stats)
    monkeypatch.setenv("DOS_STREAM_RLE", "0")
    st_off = StreamedCPDOracle(g, dc, outdir, row_chunk=64)
    assert not st_off.rle
    c_off, p_off, f_off = st_off.query(queries)
    np.testing.assert_array_equal(c_on, c_off)
    np.testing.assert_array_equal(p_on, p_off)
    np.testing.assert_array_equal(f_on, f_off)
    if stats_on["chunks_rle"] > 0:
        assert stats_on["bytes_streamed"] < \
            st_off.last_stats["bytes_streamed"]


def test_streamed_rle_sidecar_persistence(stream_setup, monkeypatch,
                                          tmp_path):
    """First cold round writes rle-*.npz sidecars; a fresh oracle's cold
    round hits them (no raw block read), answers stay identical, and a
    rebuilt (touched) index invalidates the fingerprint."""
    import os
    import shutil

    g, dc, outdir, queries, resident = stream_setup
    monkeypatch.delenv("DOS_STREAM_RLE", raising=False)
    monkeypatch.delenv("DOS_STREAM_RLE_SIDECAR", raising=False)
    # private index copy: sidecar files written here must not leak into
    # the shared fixture dir other tests assert against
    priv = str(tmp_path / "idx")
    shutil.copytree(outdir, priv,
                    ignore=shutil.ignore_patterns("rle-*"))
    st1 = StreamedCPDOracle(g, dc, priv, row_chunk=64)
    c1, p1, f1 = st1.query(queries)
    s1 = dict(st1.last_stats)
    # every miss persists SOMETHING: the encoding, or a negative marker
    # so incompressible chunks never re-pay the encode attempt
    sidecars = [f for f in os.listdir(priv) if f.startswith("rle-")]
    assert len(sidecars) == s1["cache_misses"]
    assert s1["sidecar_hits"] == 0
    if s1["chunks_rle"] == 0:       # coder fell back: markers only
        st2 = StreamedCPDOracle(g, dc, priv, row_chunk=64)
        c2, _, _ = st2.query(queries)
        assert st2.last_stats["sidecar_hits"] == \
            st2.last_stats["cache_misses"]      # markers were consulted
        assert st2.last_stats["chunks_rle"] == 0
        np.testing.assert_array_equal(c1, c2)
        return
    st2 = StreamedCPDOracle(g, dc, priv, row_chunk=64)
    c2, p2, f2 = st2.query(queries)
    s2 = dict(st2.last_stats)
    assert s2["sidecar_hits"] == s2["chunks_rle"] == s1["chunks_rle"]
    assert s2["bytes_streamed"] == s1["bytes_streamed"]
    np.testing.assert_array_equal(c1, c2)
    np.testing.assert_array_equal(p1, p2)
    np.testing.assert_array_equal(f1, f2)
    # stale sidecar: touching a block file changes the fingerprint
    for f in os.listdir(priv):
        if f.startswith("cpd-"):
            os.utime(os.path.join(priv, f),
                     ns=(1, 1))
    st3 = StreamedCPDOracle(g, dc, priv, row_chunk=64)
    c3, _, _ = st3.query(queries)
    assert st3.last_stats["sidecar_hits"] == 0   # all invalidated
    np.testing.assert_array_equal(c3, c1)


def test_streamed_modes_agree(stream_setup, monkeypatch):
    """Range and compacted chunking must produce identical answers."""
    g, dc, outdir, queries, resident = stream_setup
    monkeypatch.setenv("DOS_STREAM_RANGE_DENSITY", "0.0")   # force range
    st_r = StreamedCPDOracle(g, dc, outdir, row_chunk=37)
    c_r, p_r, f_r = st_r.query(queries)
    assert st_r.last_stats["mode"] == "range"
    monkeypatch.setenv("DOS_STREAM_RANGE_DENSITY", "2.0")   # force compact
    st_c = StreamedCPDOracle(g, dc, outdir, row_chunk=37)
    c_c, p_c, f_c = st_c.query(queries)
    assert st_c.last_stats["mode"] == "compacted"
    np.testing.assert_array_equal(c_r, c_c)
    np.testing.assert_array_equal(p_r, p_c)
    np.testing.assert_array_equal(f_r, f_c)
