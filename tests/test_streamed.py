"""Streamed serving: disk-index row-streaming must match the resident
oracle exactly (same walk kernel, different memory plan)."""

import numpy as np
import pytest

from distributed_oracle_search_tpu.data import (
    synth_city_graph, synth_scenario, synth_diff,
)
from distributed_oracle_search_tpu.models.cpd import (
    CPDOracle, build_worker_shard, write_index_manifest,
)
from distributed_oracle_search_tpu.models.streamed import StreamedCPDOracle
from distributed_oracle_search_tpu.parallel import DistributionController
from distributed_oracle_search_tpu.parallel.mesh import make_mesh


@pytest.fixture(scope="module")
def stream_setup(tmp_path_factory):
    outdir = str(tmp_path_factory.mktemp("cpd-index"))
    g = synth_city_graph(16, 12, seed=5)
    dc = DistributionController("mod", 4, 4, g.n)
    for wid in range(4):
        build_worker_shard(g, dc, wid, outdir, chunk=64)
    write_index_manifest(outdir, dc)
    queries = synth_scenario(g.n, 300, seed=6)
    resident = CPDOracle(g, dc, mesh=make_mesh(n_workers=4)).load(outdir)
    return g, dc, outdir, queries, resident


def test_streamed_matches_resident_free_flow(stream_setup):
    g, dc, outdir, queries, resident = stream_setup
    st = StreamedCPDOracle(g, dc, outdir, row_chunk=37)  # force many chunks
    c_r, p_r, f_r = resident.query(queries)
    c_s, p_s, f_s = st.query(queries)
    np.testing.assert_array_equal(c_s, c_r)
    np.testing.assert_array_equal(p_s, p_r)
    np.testing.assert_array_equal(f_s, f_r)
    stats = st.last_stats
    assert stats["n_queries"] == len(queries)
    if stats["mode"] == "compacted":
        assert stats["row_chunks"] == -(-stats["distinct_targets"] // 37)
    else:
        # range chunks cover gaps too, so there are at least as many
        assert stats["row_chunks"] >= -(-stats["distinct_targets"] // 37)
    # both modes upload whole [C, N] chunks (range mode covers gap rows,
    # compacted mode pads the tail chunk)
    assert stats["bytes_streamed"] == stats["row_chunks"] * 37 * g.n


def test_streamed_matches_resident_diffed(stream_setup):
    g, dc, outdir, queries, resident = stream_setup
    w_diff = g.weights_with_diff(synth_diff(g, frac=0.2, seed=7))
    st = StreamedCPDOracle(g, dc, outdir, row_chunk=64)
    c_r, p_r, f_r = resident.query(queries, w_query=w_diff)
    c_s, p_s, f_s = st.query(queries, w_query=w_diff)
    np.testing.assert_array_equal(c_s, c_r)
    np.testing.assert_array_equal(p_s, p_r)
    np.testing.assert_array_equal(f_s, f_r)


def test_streamed_k_moves_budget(stream_setup):
    g, dc, outdir, queries, resident = stream_setup
    st = StreamedCPDOracle(g, dc, outdir, row_chunk=128)
    c_r, p_r, f_r = resident.query(queries, k_moves=3)
    c_s, p_s, f_s = st.query(queries, k_moves=3)
    np.testing.assert_array_equal(c_s, c_r)
    np.testing.assert_array_equal(p_s, p_r)
    np.testing.assert_array_equal(f_s, f_r)
    assert (np.asarray(p_s) <= 3).all()


def test_streamed_rejects_mismatched_controller(stream_setup):
    g, dc, outdir, _, _ = stream_setup
    other = DistributionController("mod", 2, 2, g.n)
    with pytest.raises(ValueError, match="was built with"):
        StreamedCPDOracle(g, other, outdir)


def test_streamed_modes_agree(stream_setup, monkeypatch):
    """Range and compacted chunking must produce identical answers."""
    g, dc, outdir, queries, resident = stream_setup
    monkeypatch.setenv("DOS_STREAM_RANGE_DENSITY", "0.0")   # force range
    st_r = StreamedCPDOracle(g, dc, outdir, row_chunk=37)
    c_r, p_r, f_r = st_r.query(queries)
    assert st_r.last_stats["mode"] == "range"
    monkeypatch.setenv("DOS_STREAM_RANGE_DENSITY", "2.0")   # force compact
    st_c = StreamedCPDOracle(g, dc, outdir, row_chunk=37)
    c_c, p_c, f_c = st_c.query(queries)
    assert st_c.last_stats["mode"] == "compacted"
    np.testing.assert_array_equal(c_r, c_c)
    np.testing.assert_array_equal(p_r, p_c)
    np.testing.assert_array_equal(f_r, f_c)
