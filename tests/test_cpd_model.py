"""CPDOracle on an 8-virtual-device mesh: sharded build == CPU oracle,
routed queries in input order, save/load round-trip, partition-mismatch
guard."""

import numpy as np
import pytest
import jax

from distributed_oracle_search_tpu.data import synth_diff
from distributed_oracle_search_tpu.models import first_move_matrix, dist_to_target
from distributed_oracle_search_tpu.models.cpd import CPDOracle
from distributed_oracle_search_tpu.parallel import DistributionController
from distributed_oracle_search_tpu.parallel.mesh import make_mesh, WORKER_AXIS


@pytest.fixture(scope="module", params=["tpu", "mod"])
def oracle(request, toy_graph):
    dc = DistributionController(request.param,
                                8 if request.param == "mod" else None,
                                8, toy_graph.n, block_size=4)
    return CPDOracle(toy_graph, dc).build(chunk=3)


def test_fetch_fm_rle_roundtrip(monkeypatch):
    """The RLE-compressed device->host fm fetch must be bit-identical to
    a plain fetch — blocky, incompressible, and tiny inputs, with the
    size gate forced off so the compressed path actually runs."""
    import jax.numpy as jnp

    from distributed_oracle_search_tpu.models import cpd as cpd_mod
    from distributed_oracle_search_tpu.models.cpd import fetch_fm

    monkeypatch.setattr(cpd_mod, "FETCH_RLE_MIN_BYTES", 0)
    rng = np.random.default_rng(7)
    blocky = np.repeat(rng.integers(-1, 6, (5, 40)).astype(np.int8),
                       13, axis=0)[:60]
    np.testing.assert_array_equal(fetch_fm(jnp.asarray(blocky)), blocky)
    noise = ((np.arange(64 * 32).reshape(64, 32) % 13) - 1).astype(np.int8)
    np.testing.assert_array_equal(fetch_fm(jnp.asarray(noise)), noise)
    tiny = np.zeros((1, 5), np.int8)       # c < 2: plain path
    np.testing.assert_array_equal(fetch_fm(jnp.asarray(tiny)), tiny)
    monkeypatch.setenv("DOS_FETCH_RLE", "0")
    np.testing.assert_array_equal(fetch_fm(jnp.asarray(blocky)), blocky)


def test_sharded_build_matches_cpu_oracle(toy_graph, oracle):
    fm = np.asarray(oracle.fm)
    dc = oracle.dc
    for wid in range(dc.maxworker):
        owned = dc.owned(wid)
        golden = first_move_matrix(toy_graph, owned)
        np.testing.assert_array_equal(fm[wid, :len(owned)], golden,
                                      err_msg=f"worker {wid}")
        # padding rows all -1
        assert np.all(fm[wid, len(owned):] == -1)


def test_fm_is_sharded_over_workers(oracle):
    shard_devices = {d for s in oracle.fm.addressable_shards
                     for d in [s.device]}
    assert len(shard_devices) == 8
    # each shard holds exactly its row slice
    for s in oracle.fm.addressable_shards:
        assert s.data.shape[0] == 1


def test_query_input_order_and_correctness(toy_graph, oracle, toy_queries):
    cost, plen, fin = oracle.query(toy_queries)
    assert fin.all()
    for i, (s, t) in enumerate(toy_queries):
        assert cost[i] == dist_to_target(toy_graph, int(t))[s], (s, t)


def test_query_with_diff_and_kmoves(toy_graph, oracle, toy_queries):
    w_query = toy_graph.weights_with_diff(synth_diff(toy_graph, 0.3, seed=21))
    c0, p0, f0 = oracle.query(toy_queries)
    c1, p1, f1 = oracle.query(toy_queries, w_query=w_query)
    np.testing.assert_array_equal(p0, p1)
    assert np.all(c1 >= c0)
    c2, p2, f2 = oracle.query(toy_queries, k_moves=1)
    assert np.all(p2 <= 1)


def test_query_multi_matches_sequential_rounds(toy_graph, oracle,
                                               toy_queries):
    """The fused multi-diff campaign must reproduce the reference shape
    of one-round-per-diff exactly: cost row d == a sequential round on
    diff d; plen/finished shared (trajectories are diff-independent)."""
    w_list = [None,
              toy_graph.weights_with_diff(
                  synth_diff(toy_graph, 0.3, seed=31)),
              toy_graph.weights_with_diff(
                  synth_diff(toy_graph, 0.6, seed=32))]
    cost, plen, fin = oracle.query_multi(toy_queries, w_list)
    assert cost.shape == (3, len(toy_queries))
    assert fin.all()
    for di, w in enumerate(w_list):
        c1, p1, f1 = oracle.query(toy_queries, w_query=w)
        np.testing.assert_array_equal(cost[di], c1)
        np.testing.assert_array_equal(plen, p1)
        np.testing.assert_array_equal(fin, f1)
    import pytest

    with pytest.raises(ValueError, match="at least one"):
        oracle.query_multi(toy_queries, [])


def test_query_multi_active_worker(toy_graph, oracle, toy_queries):
    """-w filtering drops other workers' queries like query() does."""
    wid = 2
    w_list = [None, toy_graph.weights_with_diff(
        synth_diff(toy_graph, 0.4, seed=33))]
    cost_all, _, _ = oracle.query_multi(toy_queries, w_list)
    cost_w, _, fin_w = oracle.query_multi(toy_queries, w_list,
                                          active_worker=wid)
    mine = oracle.dc.worker_of(toy_queries[:, 1]) == wid
    np.testing.assert_array_equal(cost_w[:, mine], cost_all[:, mine])
    assert fin_w[mine].all() and not fin_w[~mine].any()
    assert np.all(cost_w[:, ~mine] == 0)


def test_active_worker_filter(toy_graph, oracle, toy_queries):
    dc = oracle.dc
    wid = 3
    cost_all, _, fin_all = oracle.query(toy_queries)
    cost_w, _, fin_w = oracle.query(toy_queries, active_worker=wid)
    mine = dc.worker_of(toy_queries[:, 1]) == wid
    np.testing.assert_array_equal(cost_w[mine], cost_all[mine])
    assert fin_w[mine].all()
    assert not fin_w[~mine].any()
    assert np.all(cost_w[~mine] == 0)


def test_save_load_roundtrip(tmp_path, toy_graph, oracle, toy_queries):
    outdir = str(tmp_path / "index")
    oracle.save(outdir)
    import os
    import json
    with open(os.path.join(outdir, "index.json")) as f:
        manifest = json.load(f)
    # block files per worker: ceil(owned / block_size)
    dc = oracle.dc
    expect = sum(-(-dc.n_owned(w) // dc.block_size)
                 for w in range(dc.maxworker))
    assert len(manifest["files"]) == expect

    fresh = CPDOracle(toy_graph, dc).load(outdir)
    np.testing.assert_array_equal(np.asarray(fresh.fm),
                                  np.asarray(oracle.fm))
    c0, _, f0 = oracle.query(toy_queries)
    c1, _, f1 = fresh.query(toy_queries)
    np.testing.assert_array_equal(c0, c1)


def test_load_rejects_mismatched_partition(tmp_path, toy_graph, oracle):
    outdir = str(tmp_path / "index2")
    oracle.save(outdir)
    other = DistributionController("div", -(-toy_graph.n // 8), 8,
                                   toy_graph.n, block_size=4)
    with pytest.raises(ValueError, match="partmethod"):
        CPDOracle(toy_graph, other).load(outdir)


def test_load_rejects_same_method_different_partkey(tmp_path, toy_graph):
    # same partmethod, different partkey must be refused: rows would land
    # under the wrong owners and queries would silently go wrong
    dc6 = DistributionController("div", 7, 8, toy_graph.n, block_size=4)
    o = CPDOracle(toy_graph, dc6).build()
    outdir = str(tmp_path / "index3")
    o.save(outdir)
    dc7 = DistributionController("div", 8, 8, toy_graph.n, block_size=4)
    with pytest.raises(ValueError, match="partkey"):
        CPDOracle(toy_graph, dc7).load(outdir)


def test_mesh_worker_mismatch_rejected(toy_graph):
    dc = DistributionController("mod", 3, 3, toy_graph.n)
    mesh = make_mesh(n_workers=8)
    with pytest.raises(ValueError, match="worker axis"):
        CPDOracle(toy_graph, dc, mesh=mesh)


def test_data_axis_mesh(toy_graph, toy_queries):
    # 2x4 mesh: data parallelism over query batches x worker sharding
    dc = DistributionController("tpu", None, 4, toy_graph.n)
    mesh = make_mesh(n_workers=4, n_data=2)
    o = CPDOracle(toy_graph, dc, mesh=mesh).build()
    cost, plen, fin = o.query(toy_queries)
    assert fin.all()
    for i in range(0, len(toy_queries), 9):
        s, t = map(int, toy_queries[i])
        assert cost[i] == dist_to_target(toy_graph, t)[s]


def test_query_dist_fast_path(toy_graph, toy_queries):
    """build(store_dists=True) -> free-flow answers by one gather, equal
    to the walked costs and the CPU oracle."""
    from distributed_oracle_search_tpu.models.cpd import CPDOracle
    from distributed_oracle_search_tpu.models.reference import dist_to_target
    from distributed_oracle_search_tpu.parallel import DistributionController
    from distributed_oracle_search_tpu.parallel.mesh import make_mesh

    dc = DistributionController("tpu", None, 4, toy_graph.n)
    oracle = CPDOracle(toy_graph, dc, mesh=make_mesh(n_workers=4))
    oracle.build(store_dists=True)
    cost_d, fin_d = oracle.query_dist(toy_queries)
    cost_w, _, fin_w = oracle.query(toy_queries)
    assert fin_d.all() and (fin_d == fin_w).all()
    assert (cost_d == cost_w).all()
    s0, t0 = map(int, toy_queries[3])
    assert cost_d[3] == dist_to_target(toy_graph, t0)[s0]


def test_query_dist_requires_store(toy_graph, toy_queries):
    import pytest as _pytest

    from distributed_oracle_search_tpu.models.cpd import CPDOracle
    from distributed_oracle_search_tpu.parallel import DistributionController
    from distributed_oracle_search_tpu.parallel.mesh import make_mesh

    dc = DistributionController("tpu", None, 2, toy_graph.n)
    oracle = CPDOracle(toy_graph, dc,
                       mesh=make_mesh(n_workers=2)).build()
    with _pytest.raises(RuntimeError, match="store_dists"):
        oracle.query_dist(toy_queries)


def test_build_program_has_no_collectives(toy_graph):
    """The sharded build must be embarrassingly parallel: per-shard
    while_loop convergence, ZERO cross-shard traffic. A GSPMD-jit build
    once carried a global convergence flag — an all-reduce per sweep and
    slowest-shard coupling (the round-2 weak-scaling regression). Pin the
    property in the compiled HLO."""
    from distributed_oracle_search_tpu.ops import DeviceGraph
    from distributed_oracle_search_tpu.parallel.sharded import (
        _build_fn, pad_targets,
    )

    g = toy_graph
    dc = DistributionController("tpu", None, 8, g.n)
    mesh = make_mesh(n_workers=8)
    dg = DeviceGraph.from_graph(g)
    tgt = pad_targets(dc)
    import jax.numpy as jnp
    fn = _build_fn(mesh, 8, 0, False)
    compiled = fn.lower(dg, jnp.asarray(tgt.T)).compile()
    hlo = compiled.as_text()
    for op in ("all-reduce", "all-gather", "collective-permute",
               "all-to-all", "reduce-scatter"):
        assert op not in hlo, f"build program contains a {op} collective"


def test_mesh_from_config(toy_graph):
    """mesh_shape/mesh_axes config keys drive the campaign mesh; the
    worker axis must match maxworker (one shard per worker)."""
    from distributed_oracle_search_tpu.parallel.mesh import (
        DATA_AXIS, mesh_from_config,
    )
    from distributed_oracle_search_tpu.utils.config import ClusterConfig

    base = dict(workers=["localhost"] * 4, partmethod="tpu", partkey=0,
                outdir="x", xy_file="x.xy", scenfile="x.scen")
    conf = ClusterConfig(**base)
    m = mesh_from_config(conf)
    assert m.shape[WORKER_AXIS] == 4 and m.shape[DATA_AXIS] == 1

    conf = ClusterConfig(**base, mesh_shape=[2, 4],
                         mesh_axes=["data", "worker"])
    m = mesh_from_config(conf)
    assert m.shape[DATA_AXIS] == 2 and m.shape[WORKER_AXIS] == 4

    conf = ClusterConfig(**base, mesh_shape=[2, 2],
                         mesh_axes=["data", "worker"])
    with pytest.raises(ValueError, match="maxworker"):
        mesh_from_config(conf)


def test_ellsplit_build_matches_plain_ell(toy_graph):
    """The ELL+COO split relaxation must produce bit-identical first
    moves to the plain padded-ELL kernel (same tie-breaks)."""
    import jax.numpy as jnp

    from distributed_oracle_search_tpu.data import synth_road_network
    from distributed_oracle_search_tpu.ops import (
        DeviceGraph, build_fm_columns,
    )
    from distributed_oracle_search_tpu.ops.ell_split import (
        build_fm_columns_ellsplit, ell_split_graph,
    )

    for g in (toy_graph, synth_road_network(600, seed=2)):
        dg = DeviceGraph.from_graph(g)
        sg = ell_split_graph(g)
        assert sg.k0 <= g.max_out_degree
        tgts = np.arange(0, g.n, 3, dtype=np.int32)
        ref = np.asarray(build_fm_columns(dg, jnp.asarray(tgts)))
        got = np.asarray(build_fm_columns_ellsplit(dg, sg, tgts))
        np.testing.assert_array_equal(got, ref)


def test_auto_picks_ellsplit_for_degree_skewed(toy_graph):
    """auto resolves to the split kernel on the road synthetic (grid and
    shift gates fail, degree skew makes the split worthwhile) and the
    sharded build path runs it with matching results."""
    from distributed_oracle_search_tpu.data import synth_road_network
    from distributed_oracle_search_tpu.models.cpd import (
        CPDOracle, pick_build_kernel,
    )
    from distributed_oracle_search_tpu.models.reference import (
        dist_to_target,
    )

    g = synth_road_network(800, seed=5)
    kind, st = pick_build_kernel(g, "auto")
    assert kind == "ellsplit"
    dc = DistributionController("tpu", None, 8, g.n)
    o = CPDOracle(g, dc, mesh=make_mesh(n_workers=8)).build(method="auto")
    rng = np.random.default_rng(0)
    q = np.stack([rng.integers(0, g.n, 32), rng.integers(0, g.n, 32)],
                 axis=1)
    c, p, f = o.query(q)
    for (s, t), cc, ff in zip(q, c, f):
        d = dist_to_target(g, int(t))[int(s)]
        assert (cc == d) if ff else d >= 10**9


def test_frontier_build_matches_plain_ell(toy_graph):
    """The delta-stepping frontier relaxation must produce bit-identical
    first moves to the plain padded-ELL kernel (same fixed point, same
    tie-breaks) — including with a tiny pop capacity F that forces queue
    overflow every iteration."""
    import jax.numpy as jnp

    from distributed_oracle_search_tpu.data import synth_road_network
    from distributed_oracle_search_tpu.ops import (
        DeviceGraph, build_fm_columns,
    )
    from distributed_oracle_search_tpu.ops.frontier_relax import (
        build_fm_columns_frontier, frontier_graph,
    )

    for g, f in ((toy_graph, None), (synth_road_network(600, seed=2), None),
                 (synth_road_network(600, seed=2), 32)):
        dg = DeviceGraph.from_graph(g)
        fg = frontier_graph(g, f=f)
        tgts = np.arange(0, g.n, 3, dtype=np.int32)
        ref = np.asarray(build_fm_columns(dg, jnp.asarray(tgts)))
        got = np.asarray(build_fm_columns_frontier(dg, fg, tgts))
        np.testing.assert_array_equal(got, ref)
    # padded target rows stay all -1
    g = synth_road_network(600, seed=2)
    dg = DeviceGraph.from_graph(g)
    fg = frontier_graph(g)
    tg2 = np.asarray([5, -1, 77, -1], np.int32)
    ref = np.asarray(build_fm_columns(dg, jnp.asarray(tg2)))
    got = np.asarray(build_fm_columns_frontier(dg, fg, tg2))
    np.testing.assert_array_equal(got, ref)


def test_frontier_near_inf_weights_terminate(toy_graph):
    """Regression: weights large enough that theta = prio.min() + delta
    crosses JINF must not pop idle (prio == JINF) nodes — an unmasked
    pop starved armed high-id nodes forever (livelock to the iteration
    backstop). Legal inputs: dimacs accepts any weight < 1e9."""
    import jax.numpy as jnp

    from distributed_oracle_search_tpu.data.graph import Graph
    from distributed_oracle_search_tpu.ops import (
        DeviceGraph, build_fm_columns,
    )
    from distributed_oracle_search_tpu.ops.frontier_relax import (
        build_fm_columns_frontier, frontier_graph,
    )

    g0 = toy_graph
    g = Graph(g0.xs, g0.ys, g0.src, g0.dst,
              np.full(g0.m, 500_000_000, np.int32))
    dg = DeviceGraph.from_graph(g)
    fg = frontier_graph(g)        # pick_delta clamps delta to 2^29
    assert fg.delta == 1 << 29
    tgts = np.arange(0, g.n, 2, dtype=np.int32)
    ref = np.asarray(build_fm_columns(dg, jnp.asarray(tgts)))
    got = np.asarray(build_fm_columns_frontier(dg, fg, tgts))
    np.testing.assert_array_equal(got, ref)


def test_frontier_auto_gate():
    """auto picks the frontier queue only for big graphs whose ids have
    locality (post-RCM road nets); shuffled ids of the SAME graph fall
    back to the dense split kernel (the union wavefront would span the
    whole graph), and small graphs stay dense."""
    from distributed_oracle_search_tpu.data import synth_road_network
    from distributed_oracle_search_tpu.models.cpd import (
        FRONTIER_MIN_NODES, pick_build_kernel,
    )
    from distributed_oracle_search_tpu.ops.frontier_relax import (
        locality_fraction,
    )

    g = synth_road_network(FRONTIER_MIN_NODES, seed=1)
    g_rcm = g.reorder(g.rcm_order())
    assert locality_fraction(g_rcm) > locality_fraction(g)
    kind, st = pick_build_kernel(g_rcm, "auto")
    assert kind == "frontier"
    assert st.in_nbr.shape[0] == g.n
    # same graph, shuffled ids -> dense fallback
    kind2, _ = pick_build_kernel(g, "auto")
    assert kind2 == "ellsplit"
    # small irregular graph -> dense regardless of locality
    small = synth_road_network(800, seed=5)
    kind3, _ = pick_build_kernel(small.reorder(small.rcm_order()), "auto")
    assert kind3 == "ellsplit"


def test_frontier_sharded_build_matches_cpu_oracle(toy_graph):
    """method='frontier' through the sharded build path (shard_map)
    answers queries identically to the CPU oracle."""
    from distributed_oracle_search_tpu.data import synth_road_network
    from distributed_oracle_search_tpu.models.reference import (
        dist_to_target,
    )

    g = synth_road_network(800, seed=5)
    dc = DistributionController("tpu", None, 8, g.n)
    o = CPDOracle(g, dc, mesh=make_mesh(n_workers=8)).build(
        method="frontier")
    rng = np.random.default_rng(1)
    q = np.stack([rng.integers(0, g.n, 32), rng.integers(0, g.n, 32)],
                 axis=1)
    c, p, f = o.query(q)
    for (s, t), cc, ff in zip(q, c, f):
        d = dist_to_target(g, int(t))[int(s)]
        assert (cc == d) if ff else d >= 10**9


def test_frontier_build_program_has_no_collectives(toy_graph):
    """The frontier build under shard_map must stay embarrassingly
    parallel: per-shard queue convergence, ZERO cross-shard traffic
    (same property the dense kernels pin)."""
    import jax.numpy as jnp

    from distributed_oracle_search_tpu.data import synth_road_network
    from distributed_oracle_search_tpu.ops import DeviceGraph
    from distributed_oracle_search_tpu.ops.frontier_relax import (
        frontier_graph,
    )
    from distributed_oracle_search_tpu.parallel.sharded import (
        _build_fn, pad_targets,
    )

    g = synth_road_network(800, seed=5)
    fg = frontier_graph(g)
    dc = DistributionController("tpu", None, 8, g.n)
    mesh = make_mesh(n_workers=8)
    dg = DeviceGraph.from_graph(g)
    tgt = pad_targets(dc)
    fn = _build_fn(mesh, 8, 0, False, kind="frontier",
                   kernel_sig=(fg.n, fg.f, fg.delta, fg.s_unroll))
    compiled = fn.lower(dg, jnp.asarray(fg.in_nbr),
                        jnp.asarray(tgt.T)).compile()
    hlo = compiled.as_text()
    for op in ("all-reduce", "all-gather", "collective-permute",
               "all-to-all", "reduce-scatter"):
        assert op not in hlo, f"frontier build contains a {op} collective"
