"""Closed-loop control plane: policy daemon, brownout ladder,
quarantine, elastic repair.

Policy arms are unit-tested against stubs (decisions stay deterministic
without a fleet); the actuation seams (breaker pin, supervisor kick,
plan_leave live-set, frontend brownout knobs, family shed) run against
the real subsystems; the non-slow core drill closes the loop end to end
over a real :class:`WorkerSupervisor` with dummy subprocess workers.
The full drill — supervised worker subprocesses killed mid-campaign,
healed with zero operator action — is the slow daemon variant in
test_chaos.py."""

import subprocess
import sys
import threading
import time

import pytest

from distributed_oracle_search_tpu.control import (
    ControlConfig, ControlDaemon, maybe_daemon,
)
from distributed_oracle_search_tpu.control import daemon as daemon_mod
from distributed_oracle_search_tpu.control.actuators import Actuators
from distributed_oracle_search_tpu.control.policy import (
    BROWNOUT_SHED_FAMILIES, ActionBudget, BrownoutLadder,
    HysteresisRule, QuarantineManager, RepairScaler,
)
from distributed_oracle_search_tpu.control.signals import ControlSignals
from distributed_oracle_search_tpu.obs import fleet as obs_fleet
from distributed_oracle_search_tpu.obs import metrics as obs_metrics
from distributed_oracle_search_tpu.obs import recorder as obs_recorder
from distributed_oracle_search_tpu.parallel import membership as fleet
from distributed_oracle_search_tpu.parallel.partition import (
    DistributionController,
)
from distributed_oracle_search_tpu.transport.resilience import (
    OPEN, BreakerRegistry, CircuitBreaker,
)
from distributed_oracle_search_tpu.transport.wire import HealthStatus
from distributed_oracle_search_tpu.utils.config import ClusterConfig
from distributed_oracle_search_tpu.worker import supervisor as sup_mod
from distributed_oracle_search_tpu.worker.supervisor import (
    WorkerSupervisor,
)

pytestmark = pytest.mark.control


def _counter(name):
    return obs_metrics.REGISTRY.snapshot()["counters"].get(name, 0)


def _sig(now=0.0, **kw):
    return ControlSignals(now=now, **kw)


def _cfg(**kw):
    kw.setdefault("enabled", True)
    kw.setdefault("hold_ticks", 1)
    kw.setdefault("cooldown_s", 0.0)
    kw.setdefault("clean_probes", 1)
    return ControlConfig(**kw)


def _drain_control_events():
    return [e for e in obs_recorder.drain_pending()
            if e["kind"].startswith("control_")]


# ------------------------------------------------------- policy units

def test_hysteresis_rule_trips_clears_and_never_flaps():
    r = HysteresisRule("x", trip=10.0, clear_frac=0.5, hold_ticks=2,
                       cooldown_s=5.0)
    # one over-threshold tick is not enough (hold_ticks=2)
    assert r.observe(12.0, now=0.0) is None
    assert r.observe(12.0, now=1.0) == "trip"
    assert r.tripped
    # still over: no re-fire
    assert r.observe(15.0, now=2.0) is None
    # between clear (5.0) and trip: holds tripped
    assert r.observe(7.0, now=3.0) is None
    # clearing needs hold_ticks consecutive under-clear observations
    assert r.observe(4.0, now=4.0) is None
    assert r.observe(4.0, now=5.0) == "clear"
    assert not r.tripped
    # cooldown gates the next trip even with sustained overload
    assert r.observe(12.0, now=5.5) is None
    assert r.observe(12.0, now=5.6) is None     # hold met, cooldown not
    assert r.observe(12.0, now=11.0) == "trip"  # 5s after last fire


def test_hysteresis_rule_oscillating_signal_bounded_actions():
    """A signal oscillating across the trip threshold every tick
    produces ZERO trips: the hold counter resets on every dip."""
    r = HysteresisRule("x", trip=10.0, hold_ticks=2, cooldown_s=0.0)
    edges = [r.observe(v, now=i)
             for i, v in enumerate([12, 4, 13, 3, 14, 2, 15, 1] * 4)]
    assert edges.count("trip") == 0
    # and None (sensor absent) holds state rather than clearing
    r2 = HysteresisRule("y", trip=10.0, hold_ticks=1)
    assert r2.observe(12.0, now=0.0) == "trip"
    assert r2.observe(None, now=1.0) is None
    assert r2.tripped


def test_action_budget_sliding_window():
    b = ActionBudget(2, window_s=10.0)
    assert b.allow(0.0)
    b.book(0.0)
    b.book(1.0)
    assert not b.allow(2.0)                    # exhausted
    assert b.allow(10.5)                       # first booking aged out
    assert b.statusz(10.5) == {"budget": 2, "window_s": 10.0, "used": 1}


def test_brownout_ladder_escalates_and_clears_to_zero():
    lad = BrownoutLadder(burn_trip=10.0, clear_frac=0.5, hold_ticks=1,
                         cooldown_s=0.0)
    # sustained over-threshold burn walks the whole ladder...
    assert lad.decide(20.0, now=0.0) == 1
    lad.level = 1
    assert lad.decide(20.0, now=1.0) == 2
    lad.level = 2
    assert lad.decide(20.0, now=2.0) == 3
    lad.level = 3
    assert lad.decide(20.0, now=3.0) is None   # already at max
    # ...but between trip and clear thresholds it holds, not escalates
    assert lad.decide(7.0, now=4.0) is None
    # clear steps ALL the way down, not one rung at a time
    assert lad.decide(3.0, now=5.0) == 0
    lad.level = 0
    assert lad.decide(3.0, now=6.0) is None


def test_brownout_ladder_cooldown_spaces_escalation_steps():
    lad = BrownoutLadder(burn_trip=10.0, clear_frac=0.5, hold_ticks=1,
                         cooldown_s=5.0)
    assert lad.decide(20.0, now=0.0) == 1
    lad.level = 1
    assert lad.decide(20.0, now=1.0) is None   # inside cooldown
    assert lad.decide(20.0, now=4.9) is None
    assert lad.decide(20.0, now=5.0) == 2      # cooldown elapsed
    lad.level = 2
    assert lad.decide(None, now=11.0) is None  # missing data holds


def test_quarantine_manager_state_machine():
    qm = QuarantineManager(unhealthy_pings=2, clean_probes=2,
                           dead_after_s=100.0, telemetry_lag_s=30.0,
                           readmit_grace_s=5.0)
    sick = _sig(worker_running={0: True, 1: True},
                ping_failures={0: 0, 1: 3})
    assert qm.decide(sick, now=0.0) == [
        ("quarantine", 1, "3 consecutive ping failures")]
    assert qm.quarantined() == [1]
    assert qm.decide(sick, now=1.0) == []        # already quarantined
    # probation: clean probes must be consecutive
    assert not qm.probe_result(1, True)
    assert not qm.probe_result(1, False)         # resets the streak
    assert not qm.probe_result(1, True)
    assert qm.probe_result(1, True)
    qm.readmitted(1, now=2.0)
    assert qm.quarantined() == []
    # grace window: the stale ping-failure echo must not re-quarantine
    assert qm.decide(sick, now=3.0) == []
    assert qm.decide(sick, now=8.0) == [
        ("quarantine", 1, "3 consecutive ping failures")]


def test_quarantine_manager_dead_worker_escalates_to_leave():
    qm = QuarantineManager(unhealthy_pings=2, clean_probes=1,
                           dead_after_s=10.0, telemetry_lag_s=30.0)
    dead = _sig(worker_running={0: False})
    assert qm.decide(dead, now=0.0) == [("quarantine", 0,
                                         "process dead")]
    assert qm.decide(dead, now=5.0) == []
    out = qm.decide(dead, now=10.0)
    assert out == [("leave", 0, "unhealthy 10s")]
    assert qm.quarantined() == []                # left, not quarantined


def test_quarantine_manager_telemetry_lag_is_a_failure_signal():
    qm = QuarantineManager(unhealthy_pings=5, clean_probes=1,
                           dead_after_s=100.0, telemetry_lag_s=30.0)
    lagging = _sig(worker_running={2: True}, telemetry_lag_s={2: 45.0})
    assert qm.decide(lagging, now=0.0) == [
        ("quarantine", 2, "telemetry silent 45s")]


def test_repair_scaler_starvation_and_hot_shard():
    rs = RepairScaler(starve_frac=0.9, hot_frac=0.6, clear_frac=0.5,
                      hold_ticks=1, cooldown_s=0.0, join_host="")
    # an absent frontend (no queue_depths) holds state — never trips
    assert rs.decide(_sig(queue_frac=0.99), now=0.0) == []
    starved = _sig(queue_frac=0.95, queue_depths={0: 95, 1: 90})
    assert rs.decide(starved, now=1.0) == [("scale_advise",)]
    rs2 = RepairScaler(starve_frac=0.9, hot_frac=0.6, clear_frac=0.5,
                       hold_ticks=1, cooldown_s=0.0, join_host="h9")
    assert rs2.decide(starved, now=0.0) == [("join", "h9")]
    # hot shard: one shard holds > hot_frac of queued work
    hot = _sig(queue_depths={0: 9, 1: 1}, hot_shard=0, hot_frac=0.9)
    assert ("replicate", 0) in rs2.decide(hot, now=1.0)
    # a drained fleet (shards present, empty) observes 0.0 and clears
    rs3 = RepairScaler(starve_frac=0.9, hot_frac=0.6, clear_frac=0.5,
                       hold_ticks=1, cooldown_s=0.0)
    assert rs3.decide(starved, now=0.0) == [("scale_advise",)]
    assert rs3._starve.tripped
    rs3.decide(_sig(queue_frac=0.0, queue_depths={0: 0, 1: 0}),
               now=1.0)
    assert not rs3._starve.tripped


# ---------------------------------------------------------- config

def test_control_config_env_and_validation(monkeypatch):
    monkeypatch.setenv("DOS_CONTROL", "1")
    monkeypatch.setenv("DOS_CONTROL_INTERVAL_S", "0.5")
    monkeypatch.setenv("DOS_CONTROL_DRY_RUN", "1")
    monkeypatch.setenv("DOS_CONTROL_BUDGET", "3")
    monkeypatch.setenv("DOS_CONTROL_JOIN_HOST", "spare-host")
    cfg = ControlConfig.from_env()
    assert cfg.enabled and cfg.dry_run
    assert cfg.interval_s == 0.5 and cfg.budget == 3
    assert cfg.join_host == "spare-host"
    # impossible combinations disable the daemon instead of crashing
    # the CLI that embeds it
    monkeypatch.setenv("DOS_CONTROL_BUDGET", "0")
    assert not ControlConfig.from_env().enabled
    with pytest.raises(ValueError, match="budget"):
        ControlConfig(budget=0).validate()


def test_maybe_daemon_off_by_default(monkeypatch):
    monkeypatch.delenv("DOS_CONTROL", raising=False)
    assert maybe_daemon() is None
    monkeypatch.setenv("DOS_CONTROL", "0")
    assert maybe_daemon() is None


# ----------------------------------------------------- decision seam

class _SpyRegistry:
    """Breaker-registry stand-in recording pin/release calls."""

    def __init__(self):
        self.forced, self.released = [], []

    def force_open(self, key, why="quarantine"):
        self.forced.append((key, why))
        return True

    def release(self, key, close=True, why=""):
        self.released.append((key, close))

    def get(self, key):
        return None


class _SpySupervisor:
    def __init__(self, workers):
        self._workers = workers
        self.kicked = []

    def statusz(self):
        return {"workers": {str(w): dict(st)
                            for w, st in self._workers.items()}}

    def kick(self, wid):
        self.kicked.append(wid)
        return True


def _mk_daemon(**kw):
    kw.setdefault("config", _cfg())
    cfg = kw.pop("config")
    return ControlDaemon(cfg, **kw)


def test_dry_run_books_every_decision_and_executes_nothing():
    reg = _SpyRegistry()
    sup = _SpySupervisor({0: {"running": False, "ping_failures": 0}})
    d = _mk_daemon(config=_cfg(dry_run=True), supervisor=sup,
                   registry=reg, breaker_key=lambda w: w,
                   clock=lambda: 100.0)
    obs_recorder.drain_pending()
    decisions0 = daemon_mod.M_DECISIONS.value
    actions0 = daemon_mod.M_ACTIONS.value
    d.tick()
    # the decision is booked: counter + recorder event, state advanced
    assert daemon_mod.M_DECISIONS.value > decisions0
    evs = _drain_control_events()
    assert any(e["kind"] == "control_quarantine"
               and e["mode"] == "dry-run"
               and e["executed"] is False for e in evs)
    assert d.quarantine.quarantined() == [0]
    assert "quarantine(dry-run)" in d.last_action
    # ...but NOTHING was executed
    assert daemon_mod.M_ACTIONS.value == actions0
    assert reg.forced == [] and reg.released == []
    assert sup.kicked == []


def test_quarantine_executes_pin_and_kick_then_readmits():
    reg = _SpyRegistry()
    sup = _SpySupervisor({0: {"running": True, "ping_failures": 0},
                          1: {"running": False, "ping_failures": 0}})
    probe_ok = {"ok": False}
    d = _mk_daemon(config=_cfg(clean_probes=2), supervisor=sup,
                   registry=reg, breaker_key=lambda w: ("h", w),
                   probe_fn=lambda w: probe_ok["ok"],
                   clock=lambda: 100.0)
    q0 = daemon_mod.M_QUARANTINES.value
    r0 = daemon_mod.M_READMISSIONS.value
    d.tick()
    assert reg.forced == [(("h", 1), "process dead")]
    assert sup.kicked == [1]
    assert daemon_mod.M_QUARANTINES.value == q0 + 1
    assert d.statusz()["quarantined"] == [1]
    # probation: failing probes keep it quarantined
    d.tick()
    assert d.quarantine.quarantined() == [1]
    # two consecutive clean probes earn re-admission (breaker CLOSEs)
    probe_ok["ok"] = True
    sup._workers[1] = {"running": True, "ping_failures": 0}
    d.tick()
    assert d.quarantine.quarantined() == [1]    # 1 of 2 clean
    d.tick()
    assert d.quarantine.quarantined() == []
    assert reg.released == [(("h", 1), True)]
    assert daemon_mod.M_READMISSIONS.value == r0 + 1


def test_budget_denied_books_the_decision_without_acting():
    reg = _SpyRegistry()
    sup = _SpySupervisor({0: {"running": False, "ping_failures": 0},
                          1: {"running": False, "ping_failures": 0}})
    d = _mk_daemon(config=_cfg(budget=1), supervisor=sup,
                   registry=reg, breaker_key=lambda w: w,
                   probe_fn=lambda w: False, clock=lambda: 100.0)
    denied0 = daemon_mod.M_BUDGET_DENIED.value
    obs_recorder.drain_pending()
    d.tick()
    # two sick workers, budget for one: the second books budget-denied
    assert len(reg.forced) == 1
    assert daemon_mod.M_BUDGET_DENIED.value == denied0 + 1
    modes = [e["mode"] for e in _drain_control_events()
             if e["kind"] == "control_quarantine"]
    assert sorted(modes) == ["budget-denied", "executed"]


def test_actuator_error_is_counted_and_loop_survives():
    # a daemon with NO actuators: the quarantine decision books an
    # error (wiring bug made visible) and the tick completes
    sup = _SpySupervisor({0: {"running": False, "ping_failures": 0}})

    class _NoActSup(_SpySupervisor):
        def kick(self, wid):
            raise RuntimeError("kick transport down")

    sup = _NoActSup({0: {"running": False, "ping_failures": 0}})
    d = _mk_daemon(supervisor=sup, clock=lambda: 100.0)
    e0 = daemon_mod.M_ERRORS.value
    d.tick()
    assert daemon_mod.M_ERRORS.value == e0 + 1
    assert "quarantine(error)" in d.last_action


def test_warm_bypasses_action_budget():
    warmed = []
    d = _mk_daemon(config=_cfg(budget=1), warm_fns=[lambda:
                                                    warmed.append(1)],
                   clock=lambda: 100.0)
    d.budget.book(100.0)                 # budget already exhausted
    w0 = daemon_mod.M_WARMS.value
    d.tick()
    assert warmed == [1]                 # warm still ran
    assert daemon_mod.M_WARMS.value == w0 + 1
    # cooldown spaces warms (cooldown_s=0 here: every tick re-warms)
    d.tick()
    assert warmed == [1, 1]


def test_repair_decisions_route_to_actuators():
    calls = {"join": [], "repl": []}

    class _MC:
        def join(self, host):
            calls["join"].append(host)

        def leave(self, wid, live=None):
            pass

    class _FE:
        _breaker_key = staticmethod(lambda wid: wid)

        def statusz(self):
            return {"shards": {
                "0": {"queue_depth": 97, "queue_bound": 100},
                "1": {"queue_depth": 3, "queue_bound": 100}}}

    d = _mk_daemon(config=_cfg(join_host="spare"), frontend=_FE(),
                   membership=_MC(),
                   replicate_fn=lambda s: calls["repl"].append(s),
                   clock=lambda: 100.0)
    d.tick()
    d.actuators.stop()                   # join runs on a worker thread
    assert calls["join"] == ["spare"]
    assert calls["repl"] == [0]          # shard 0 holds 97% of queue


def test_scale_advise_books_without_budget():
    class _FE:
        _breaker_key = staticmethod(lambda wid: wid)

        def statusz(self):
            return {"shards": {
                "0": {"queue_depth": 95, "queue_bound": 100}}}

    d = _mk_daemon(config=_cfg(budget=1), frontend=_FE(),
                   clock=lambda: 100.0)
    d.budget.book(100.0)
    a0 = daemon_mod.M_SCALE_ADVISED.value
    obs_recorder.drain_pending()
    d.tick()
    assert daemon_mod.M_SCALE_ADVISED.value == a0 + 1
    assert any(e["kind"] == "control_scale_advise"
               for e in _drain_control_events())


# ------------------------------------------------- breaker pin seam

def test_breaker_force_open_pins_against_healing():
    br = CircuitBreaker(("h", 0), threshold=3, cooldown_s=0.01,
                        clock=time.monotonic)
    open0 = _counter("head_circuit_open_total")
    br.force_open("quarantine test")
    assert br.state == OPEN and br.pinned
    assert _counter("head_circuit_open_total") == open0 + 1
    assert not br.allow() and not br.would_allow()
    # a success result cannot heal a pinned breaker
    br.record(True)
    assert br.state == OPEN
    # cooldown elapsed: half-open trial still refused
    time.sleep(0.02)
    assert not br.allow()
    br.force_open("again")               # idempotent
    br.release(close=True)
    assert br.state != OPEN and not br.pinned
    assert br.allow()


def test_registry_force_open_release_and_disabled_noop():
    reg = BreakerRegistry(threshold=3, cooldown_s=60.0, enabled=True)
    assert reg.force_open(("h", 1), why="t")
    assert not reg.allow(("h", 1))
    assert reg.snapshot()[str(("h", 1))]["pinned"]
    reg.release(("h", 1), close=True)
    assert reg.allow(("h", 1))
    assert not reg.snapshot()[str(("h", 1))]["pinned"]
    off = BreakerRegistry(enabled=False)
    assert off.force_open(("h", 1)) is False
    assert off.allow(("h", 1))
    reg.shutdown()
    off.shutdown()


# ------------------------------------------------ supervisor kick seam

def _conf(n=2):
    return ClusterConfig(workers=["localhost"] * n, partmethod="mod",
                         partkey=n)


def _dummy_spawn(w):
    return subprocess.Popen([sys.executable, "-c",
                             "import time; time.sleep(600)"],
                            start_new_session=True)


def _alive_probe(w):
    if w.proc is not None and w.proc.poll() is None:
        return HealthStatus(ok=True, wid=w.wid)
    return None


def test_kick_schedules_immediate_respawn_past_backoff():
    """kick() must overwrite an already-scheduled exponential backoff
    wait: with a 5 s base the respawn would otherwise be unobservable
    in this test's 2 s window."""
    sup = WorkerSupervisor(_conf(1), conf_path=None,
                           spawn_fn=_dummy_spawn, probe_fn=_alive_probe,
                           ping_interval_s=0.05, backoff_base_s=5.0,
                           backoff_cap_s=10.0)
    sup.start(wait_ready_s=10)
    try:
        w = sup.workers[0]
        w.proc.kill()
        w.proc.wait()
        # let the monitor OBSERVE the death and schedule the 5 s wait
        deadline = time.monotonic() + 5
        while w.next_spawn_at == 0.0 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert w.next_spawn_at > 0.0
        assert sup.kick(0) is True       # dead: immediate respawn
        deadline = time.monotonic() + 2
        while w.respawns == 0 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert w.respawns == 1
        assert w.proc.poll() is None
        assert sup.kick(0) is False      # alive now: nothing to do
        assert sup.kick(99) is False     # unknown wid: no-op
    finally:
        sup.stop()


# ------------------------------ satellite: hung worker, opt-in respawn

def test_hung_worker_respawn_driven_through_quarantine_decision(
        monkeypatch, tmp_path):
    """The opt-in hung-worker path end to end: a delay-faulted worker
    stays ping-ALIVE as a process but unhealthy on the wire; the
    policy's quarantine decision fires on its ping failures and kicks,
    while the supervisor's DOS_SUPERVISOR_UNHEALTHY_PINGS escalation
    kills and respawns it; the fault budget then runs dry, probes come
    back clean, and the daemon re-admits — zero operator action."""
    from distributed_oracle_search_tpu.testing import faults

    faults.reset()
    monkeypatch.setenv("DOS_FAULTS", "delay;wid=0;times=6;delay=9")
    monkeypatch.setenv("DOS_FAULTS_STATE",
                       str(tmp_path / "faults.json"))
    monkeypatch.setenv("DOS_SUPERVISOR_UNHEALTHY_PINGS", "3")

    def probe(w):
        # the delay fault models a hung server: the process is alive
        # but a ping would block past its timeout -> failure
        if faults.inject("delay", w.wid) is not None:
            return None
        return _alive_probe(w)

    sup = WorkerSupervisor(_conf(1), conf_path=None,
                           spawn_fn=_dummy_spawn, probe_fn=probe,
                           ping_interval_s=0.05, backoff_base_s=0.05,
                           backoff_cap_s=0.2)
    assert sup.unhealthy_pings == 3      # env knob armed
    reg = _SpyRegistry()
    d = _mk_daemon(config=_cfg(unhealthy_pings=2, clean_probes=2),
                   supervisor=sup, registry=reg,
                   breaker_key=lambda w: ("localhost", w))
    w = sup.workers[0]
    w.proc = _dummy_spawn(w)
    w.healthy_once = True
    first_pid = w.proc.pid
    t = threading.Thread(target=sup._monitor, daemon=True,
                         name="dos-supervisor")
    t.start()
    try:
        # tick until the daemon quarantines on the ping-failure signal
        deadline = time.monotonic() + 10
        while not reg.forced and time.monotonic() < deadline:
            d.tick()
            time.sleep(0.05)
        assert reg.forced and reg.forced[0][0] == ("localhost", 0)
        assert "ping failures" in reg.forced[0][1]
        # the supervisor's own opt-in escalation kills the hung proc
        # and respawns it
        deadline = time.monotonic() + 10
        while w.respawns == 0 and time.monotonic() < deadline:
            d.tick()
            time.sleep(0.05)
        assert w.respawns >= 1
        assert w.proc.pid != first_pid
        # fault budget (times=6) exhausts; pings heal; the daemon's
        # probation probes run clean and re-admit
        deadline = time.monotonic() + 15
        while not reg.released and time.monotonic() < deadline:
            d.tick()
            time.sleep(0.05)
        assert reg.released == [(("localhost", 0), True)]
        assert d.quarantine.quarantined() == []
    finally:
        sup._stop.set()
        t.join(timeout=5)
        if w.proc is not None and w.proc.poll() is None:
            w.proc.kill()
            w.proc.wait()
        sup_mod.G_ALIVE.set(0)
        faults.reset()


# --------------------------------------- plan_leave live-set semantics

def _mc(tmp_path, n=3, nodes=9, replication=1):
    import types

    dc = DistributionController("mod", n, n, nodes,
                                replication=replication)
    conf = types.SimpleNamespace(workers=[f"h{i}" for i in range(n)],
                                 outdir=str(tmp_path))
    return fleet.MembershipController(conf, dc)


def test_plan_leave_refuses_sole_owner_with_no_live_chain(tmp_path):
    """R=1: the leaver is each of its shards' ONLY replica-chain host.
    With a live set (leaver presumed dead — catch-up cannot copy from
    a corpse) the plan must refuse with a per-shard diagnostic and a
    counter, leaving membership untouched."""
    mc = _mc(tmp_path, replication=1)
    owners0 = list(mc.state.owners)
    refused0 = _counter("reshard_leave_refused_total")
    with pytest.raises(ValueError, match=r"refusing leave of worker 1"):
        mc.plan_leave(1, live={0, 2})
    with pytest.raises(ValueError, match=r"shard 1: .*no live host"):
        mc.plan_leave(1, live={0, 2})
    with pytest.raises(ValueError, match=r"sole owner at R=1"):
        mc.plan_leave(1, live={0, 2})
    assert _counter("reshard_leave_refused_total") == refused0 + 3
    assert mc.state.owners == owners0            # nothing committed
    # legacy live=None path still round-robins onto surviving owners
    mig = mc.plan_leave(1)
    assert mig.moves and all(to in (0, 2) for _s, _f, to in mig.moves)


def test_plan_leave_live_set_adopts_only_live_replica_hosts(tmp_path):
    mc = _mc(tmp_path, replication=2)
    dc = mc.dc_view()
    mig = mc.plan_leave(1, live={0, 2})
    assert mig.moves
    for shard, frm, to in mig.moves:
        assert frm == 1 and to in (0, 2)
        assert to in dc.replica_workers(shard)   # already holds rows
    # same fleet, but the only replica host is itself dead: refuse
    chain_hosts = {h for s, _f, _t in mig.moves
                   for h in dc.replica_workers(s) if h != 1}
    dead_live = {0, 2} - chain_hosts
    if chain_hosts != {0, 2}:
        with pytest.raises(ValueError):
            mc.plan_leave(1, live=dead_live)


def test_plan_leave_refuses_when_no_live_owner_remains(tmp_path):
    mc = _mc(tmp_path, replication=1)
    refused0 = _counter("reshard_leave_refused_total")
    with pytest.raises(ValueError, match="last shard-owning"):
        mc.plan_leave(1, live=set())
    assert _counter("reshard_leave_refused_total") == refused0 + 1


# ------------------------------------------- frontend brownout seams

def _fe(n=1, **sconf_kw):
    import numpy as np

    from distributed_oracle_search_tpu.serving import (
        CallableDispatcher, ServeConfig, ServingFrontend,
    )
    from distributed_oracle_search_tpu.serving.hedge import HedgeConfig

    dc = DistributionController("mod", n, n, 8 * n)

    def fn(wid, q, rconf, diff):
        k = len(q)
        return (np.zeros(k, np.int64), np.zeros(k, np.int64),
                np.ones(k, bool))

    sconf_kw.setdefault("max_batch", 8)
    sconf_kw.setdefault("max_wait_ms", 1.0)
    sconf_kw.setdefault("deadline_ms", 8000.0)
    return ServingFrontend(dc, CallableDispatcher(fn),
                           sconf=ServeConfig(**sconf_kw).validate(),
                           hconf=HedgeConfig(enabled=True, budget=0.2))


def test_brownout_ladder_applies_and_restores_pristine_knobs():
    fe = _fe()
    act = Actuators(frontend=fe)
    budget0 = fe.hedge.config.budget
    deadline0 = fe.sconf.deadline_ms
    act.apply_brownout(1)
    assert fe.hedge.config.budget == pytest.approx(budget0 * 0.25)
    assert fe.shed_families == frozenset()
    assert fe.sconf.deadline_ms == deadline0
    act.apply_brownout(2)
    assert fe.shed_families == frozenset(BROWNOUT_SHED_FAMILIES)
    assert fe.sconf.deadline_ms == deadline0
    act.apply_brownout(3)
    assert fe.sconf.deadline_ms == pytest.approx(deadline0 * 0.25)
    assert fe.statusz()["shed_families"] == ["alt", "mat"]
    # stepping down restores EXACTLY the pristine values
    act.apply_brownout(0)
    assert fe.hedge.config.budget == budget0
    assert fe.sconf.deadline_ms == deadline0
    assert fe.shed_families == frozenset()
    assert "shed_families" not in fe.statusz()   # legacy body restored


def test_family_shed_answers_busy_while_pairs_flow():
    from distributed_oracle_search_tpu.serving import BUSY
    from distributed_oracle_search_tpu.traffic.families import (
        QueryFamilies,
    )

    fe = _fe()
    fe.start()
    try:
        fam = QueryFamilies(fe)
        shed0 = _counter("serve_shed_family_total")
        fe.set_family_shed(("mat", "alt"))
        f = fam.submit_line("mat", [0, [1, 2]])
        assert f.done()                          # shed in-order, now
        r = f.result(0)
        assert r.status == BUSY and r.detail == "brownout-shed"
        assert _counter("serve_shed_family_total") == shed0 + 1
        # plain reverse queries keep flowing
        rr = fam.submit_line("rev", [1, 2]).result(10)
        assert rr.ok
        # and clearing the shed restores the family
        fe.set_family_shed(())
        assert fam.submit_line("mat", [0, [1]]).result(10).ok
    finally:
        fe.stop()


def test_control_off_frontend_statusz_byte_identical():
    fe = _fe()
    assert "shed_families" not in fe.statusz()
    assert fe.shed_families == frozenset()


# -------------------------------------------- obs: columns, directions

def test_fleet_summary_control_columns_blank_tolerant():
    row = obs_fleet._summarize({
        "control": {"brownout_level": 2, "dry_run": True,
                    "last_action": "quarantine(executed) wid=1",
                    "quarantined": [1, 3]},
        "worker": {"batches": 1}})
    assert row["policy"] == "dry:L2"
    assert row["last action"] == "quarantine(executed)"
    assert row["quarantined"] == "1,3"
    live = obs_fleet._summarize({"control": {"brownout_level": 0,
                                             "dry_run": False}})
    assert live["policy"] == "L0"
    # endpoints without the section (or with garbage) show no columns
    for status in ({}, {"control": {}}, {"control": "nope"},
                   {"control": {"brownout_level": True,
                                "last_action": 7,
                                "quarantined": "x"}}):
        row = obs_fleet._summarize(status)
        assert "policy" not in row
        assert "last action" not in row
        assert "quarantined" not in row


def test_bench_directions_and_tolerances_cover_control_family():
    for k in ("control_recover_seconds", "control_shed_rate",
              "control_p99_ms", "control_off_recover_seconds",
              "control_off_shed_rate", "control_off_p99_ms"):
        assert obs_fleet._KEY_DIRECTIONS.get(k) == "lower", k
        assert k in obs_fleet._KEY_TOLERANCES, k
    # the suffix heuristic alone would misread the _rate keys as
    # higher-is-better — that is WHY they are pinned here
    assert not k.endswith(("_ms", "_seconds", "_s")) or True


def test_daemon_statusz_shape():
    d = _mk_daemon(clock=lambda: 50.0)
    st = d.statusz()
    assert st["enabled"] is True and st["dry_run"] is False
    assert st["brownout_level"] == 0 and st["quarantined"] == []
    assert st["budget"]["used"] == 0


# --------------------------------------------- core drill (non-slow)

def test_core_drill_kill_quarantine_respawn_readmit(tmp_path):
    """The closed loop end to end against a real supervisor + real
    breaker registry: a worker is killed, the daemon quarantines it
    (breaker pinned, kick scheduled), the supervisor respawns it, the
    probation probes run clean, and the daemon re-admits — with the
    whole causal chain on the flight recorder."""
    rec = obs_recorder.FlightRecorder(str(tmp_path / "tape"),
                                      flush_every=1)
    obs_recorder.set_recorder(rec)
    reg = BreakerRegistry(threshold=3, cooldown_s=60.0, enabled=True)
    sup = WorkerSupervisor(_conf(2), conf_path=None,
                           spawn_fn=_dummy_spawn, probe_fn=_alive_probe,
                           ping_interval_s=0.05, backoff_base_s=5.0,
                           backoff_cap_s=10.0)
    d = _mk_daemon(config=_cfg(clean_probes=2), supervisor=sup,
                   registry=reg, breaker_key=lambda w: ("localhost", w))
    actions0 = daemon_mod.M_ACTIONS.value
    sup.start(wait_ready_s=10)
    try:
        w = sup.workers[0]
        w.proc.kill()
        w.proc.wait()
        deadline = time.monotonic() + 10
        while (d.quarantine.quarantined() != [0]
               and time.monotonic() < deadline):
            d.tick()
            time.sleep(0.05)
        assert d.quarantine.quarantined() == [0]
        assert not reg.allow(("localhost", 0))   # routed around
        # kick beat the 5 s backoff: the respawn lands fast
        deadline = time.monotonic() + 5
        while w.respawns == 0 and time.monotonic() < deadline:
            d.tick()
            time.sleep(0.05)
        assert w.respawns == 1
        deadline = time.monotonic() + 10
        while (d.quarantine.quarantined()
               and time.monotonic() < deadline):
            d.tick()
            time.sleep(0.05)
        assert d.quarantine.quarantined() == []
        assert reg.allow(("localhost", 0))       # breaker released
        assert sup.workers[1].respawns == 0      # survivor untouched
        assert daemon_mod.M_ACTIONS.value > actions0
    finally:
        sup.stop()
        reg.shutdown()
        obs_recorder.set_recorder(None)
    rec.close()
    # satellite: dos-obs replay renders the causal incident timeline
    records = obs_recorder.replay(str(tmp_path / "tape"))
    kinds = [r["kind"] for r in records if r.get("rec") == "event"]
    assert "control_quarantine" in kinds and "control_readmit" in kinds
    assert (kinds.index("control_quarantine")
            < kinds.index("control_readmit"))
    text = obs_recorder.render_timeline(records)
    assert "control_quarantine" in text and "control_readmit" in text


def test_daemon_thread_lifecycle():
    d = ControlDaemon(_cfg(interval_s=0.05), clock=time.monotonic)
    t0 = daemon_mod.M_TICKS.value
    d.start()
    try:
        assert "dos-control" in [t.name for t in threading.enumerate()]
        deadline = time.monotonic() + 5
        while (daemon_mod.M_TICKS.value == t0
               and time.monotonic() < deadline):
            time.sleep(0.02)
        assert daemon_mod.M_TICKS.value > t0
    finally:
        d.stop()
    assert "dos-control" not in [t.name for t in threading.enumerate()
                                 if t.is_alive()]
