"""Subprocess body for the 2-process multi-host test (not a pytest file).

Each process owns 4 virtual CPU devices; together they form one 8-device
worker mesh spanning both processes — the single-machine stand-in for a
multi-host TPU pod (DCN between hosts). Builds a sharded CPD on the global
mesh, allgathers it, and checks it against the CPU oracle.

Usage: multihost_worker.py <process_id> <num_processes> <coordinator>
"""

import os
import sys

pid, nproc, coord = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_oracle_search_tpu.parallel.multihost import (  # noqa: E402
    gather_to_host, initialize,
)

# config-level CPU override: the host may pin another platform via
# sitecustomize, which trumps JAX_PLATFORMS env vars
initialize(coordinator=coord, num_processes=nproc, process_id=pid,
           cpu_devices_per_process=4)

import jax  # noqa: E402
import numpy as np  # noqa: E402

assert jax.process_count() == nproc, jax.process_count()
assert len(jax.devices()) == 4 * nproc, jax.devices()

from distributed_oracle_search_tpu.data import synth_city_graph  # noqa: E402
from distributed_oracle_search_tpu.models.cpd import CPDOracle  # noqa: E402
from distributed_oracle_search_tpu.models.reference import (  # noqa: E402
    first_move_matrix,
)
from distributed_oracle_search_tpu.parallel import (  # noqa: E402
    DistributionController,
)
from distributed_oracle_search_tpu.parallel.mesh import make_mesh  # noqa: E402

n_workers = 4 * nproc
g = synth_city_graph(8, 6, seed=7)
dc = DistributionController("tpu", None, n_workers, g.n)
mesh = make_mesh(n_workers=n_workers)  # spans BOTH processes' devices
oracle = CPDOracle(g, dc, mesh=mesh)
oracle.build()

fm_global = gather_to_host(oracle.fm)  # [W, R, N] on every process
golden = first_move_matrix(g, np.arange(g.n))
for wid in range(n_workers):
    owned = dc.owned(wid)
    got = fm_global[wid, :len(owned)]
    assert (got == golden[owned]).all(), f"worker {wid} rows differ"

# fused multi-diff campaign on the cross-process mesh: both rounds of
# one walk must match per-round sequential queries (every process
# participates in the same SPMD program)
from distributed_oracle_search_tpu.data import (  # noqa: E402
    synth_diff, synth_scenario,
)

queries = synth_scenario(g.n, 24, seed=8)
w_diff = g.weights_with_diff(synth_diff(g, frac=0.3, seed=9))
cm, pm, fm_ = oracle.query_multi(queries, [None, w_diff])
assert fm_.all(), "multihost fused campaign left queries unfinished"
c0, p0, f0 = oracle.query(queries)
c1, p1, f1 = oracle.query(queries, w_query=w_diff)
assert (cm[0] == c0).all() and (cm[1] == c1).all(), "fused != sequential"
assert (pm == p0).all() and (pm == p1).all()

print(f"MULTIHOST_OK process={pid} devices={len(jax.devices())}")
