"""TPU ops vs CPU oracle: golden equality on distances, first moves, walks."""

import numpy as np
import jax.numpy as jnp
import pytest

from distributed_oracle_search_tpu.data import synth_diff
from distributed_oracle_search_tpu.data.graph import Graph, INF
from distributed_oracle_search_tpu.models import (
    dist_to_target, first_move_matrix, table_search_walk,
)
from distributed_oracle_search_tpu.ops import (
    DeviceGraph, dist_to_targets, first_move_from_dist, build_fm_columns,
    table_search_batch,
)


@pytest.fixture(scope="module")
def dg(toy_graph):
    return DeviceGraph.from_graph(toy_graph)


def test_dist_matches_dijkstra(toy_graph, dg):
    g = toy_graph
    targets = np.array([0, 5, g.n // 2, g.n - 1], np.int32)
    dist = np.asarray(dist_to_targets(dg, jnp.asarray(targets)))
    for b, t in enumerate(targets):
        golden = dist_to_target(g, int(t))
        np.testing.assert_array_equal(dist[b], golden)


def test_first_move_matches_oracle_exactly(toy_graph, dg):
    # Equality includes tie-breaking: both sides take the first minimal slot.
    g = toy_graph
    targets = np.arange(g.n, dtype=np.int32)
    fm_tpu = np.asarray(build_fm_columns(dg, jnp.asarray(targets)))
    fm_cpu = first_move_matrix(g, targets)
    np.testing.assert_array_equal(fm_tpu, fm_cpu)


def test_padding_rows_are_inert(toy_graph, dg):
    targets = jnp.asarray([3, -1, 7, -1], jnp.int32)
    dist = dist_to_targets(dg, targets)
    fm = first_move_from_dist(dg, targets, dist)
    assert np.all(np.asarray(dist)[1] == INF)
    assert np.all(np.asarray(fm)[1] == -1)
    assert np.all(np.asarray(fm)[3] == -1)
    # real rows unaffected by the padding rows
    np.testing.assert_array_equal(
        np.asarray(fm)[0], first_move_matrix(toy_graph, np.array([3]))[0])


def test_batch_walk_matches_reference_walk(toy_graph, dg, toy_queries):
    g = toy_graph
    targets = np.arange(g.n, dtype=np.int32)
    fm = build_fm_columns(dg, jnp.asarray(targets))

    s = toy_queries[:, 0].astype(np.int32)
    t = toy_queries[:, 1].astype(np.int32)
    cost, plen, fin = table_search_batch(
        dg, fm, jnp.asarray(t), jnp.asarray(s), jnp.asarray(t), dg.w_pad)
    cost, plen, fin = map(np.asarray, (cost, plen, fin))

    fm_np = np.asarray(fm)
    for i, (si, ti) in enumerate(toy_queries):
        c, p, f, _ = table_search_walk(
            g, lambda x, tt: fm_np[tt, x], int(si), int(ti))
        assert (cost[i], plen[i], fin[i]) == (c, p, f), f"query {si}->{ti}"
        # and the walk cost is the true shortest distance
        assert cost[i] == dist_to_target(g, int(ti))[si]


def test_batch_walk_with_diff(toy_graph, dg, toy_queries):
    g = toy_graph
    w_query = g.weights_with_diff(synth_diff(g, frac=0.3, seed=13))
    w_query_pad = jnp.asarray(g.padded_weights(w_query))
    targets = np.arange(g.n, dtype=np.int32)
    fm = build_fm_columns(dg, jnp.asarray(targets))
    s = jnp.asarray(toy_queries[:, 0], jnp.int32)
    t = jnp.asarray(toy_queries[:, 1], jnp.int32)

    c_free, p_free, f_free = table_search_batch(dg, fm, t, s, t, dg.w_pad)
    c_diff, p_diff, f_diff = table_search_batch(dg, fm, t, s, t, w_query_pad)
    # same routes (free-flow first moves), higher-or-equal cost, same plen
    np.testing.assert_array_equal(np.asarray(p_free), np.asarray(p_diff))
    np.testing.assert_array_equal(np.asarray(f_free), np.asarray(f_diff))
    assert np.all(np.asarray(c_diff) >= np.asarray(c_free))

    fm_np = np.asarray(fm)
    for i in range(0, len(toy_queries), 7):
        si, ti = map(int, toy_queries[i])
        c, p, f, _ = table_search_walk(
            g, lambda x, tt: fm_np[tt, x], si, ti, w_query=w_query)
        assert np.asarray(c_diff)[i] == c


def test_k_moves_budget(toy_graph, dg, toy_queries):
    targets = np.arange(toy_graph.n, dtype=np.int32)
    fm = build_fm_columns(dg, jnp.asarray(targets))
    s = jnp.asarray(toy_queries[:, 0], jnp.int32)
    t = jnp.asarray(toy_queries[:, 1], jnp.int32)
    _, plen_all, fin_all = table_search_batch(dg, fm, t, s, t, dg.w_pad)
    _, plen2, fin2 = table_search_batch(dg, fm, t, s, t, dg.w_pad, k_moves=2)
    # k_moves is a STATIC argname: the unlimited default (-1) compiles a
    # program with NO per-step budget compare — pin that the budgeted
    # lowering is strictly larger, so the specialization cannot silently
    # regress to a traced operand again (advisor r4 found exactly that)
    hlo_unl = table_search_batch.lower(
        dg, fm, t, s, t, dg.w_pad, k_moves=-1).as_text()
    hlo_bud = table_search_batch.lower(
        dg, fm, t, s, t, dg.w_pad, k_moves=2).as_text()
    assert len(hlo_bud) > len(hlo_unl)
    plen_all, fin_all, plen2, fin2 = map(
        np.asarray, (plen_all, fin_all, plen2, fin2))
    assert np.all(plen2 <= 2)
    long_ones = plen_all > 2
    assert not np.any(fin2[long_ones])
    short_ones = (plen_all <= 2) & fin_all
    np.testing.assert_array_equal(fin2[short_ones],
                                  np.ones(short_ones.sum(), bool))


def test_valid_mask_padding(toy_graph, dg):
    targets = np.arange(toy_graph.n, dtype=np.int32)
    fm = build_fm_columns(dg, jnp.asarray(targets))
    s = jnp.asarray([1, 0, 2], jnp.int32)
    t = jnp.asarray([5, 0, 9], jnp.int32)
    valid = jnp.asarray([True, False, True])
    cost, plen, fin = table_search_batch(dg, fm, t, s, t, dg.w_pad, valid=valid)
    assert not np.asarray(fin)[1] and np.asarray(cost)[1] == 0
    assert np.asarray(fin)[0] and np.asarray(fin)[2]


def test_unreachable_batch():
    g = Graph(xs=[0, 1, 5, 6], ys=[0, 0, 0, 0],
              src=[0, 1, 2, 3], dst=[1, 0, 3, 2], w=[1, 1, 1, 1])
    dg = DeviceGraph.from_graph(g)
    fm = build_fm_columns(dg, jnp.asarray([3], jnp.int32))
    cost, plen, fin = table_search_batch(
        dg, fm, jnp.asarray([0]), jnp.asarray([0]), jnp.asarray([3]),
        dg.w_pad)
    assert not np.asarray(fin)[0] and np.asarray(plen)[0] == 0


def test_bucketed_walk_invariant(toy_graph, dg, toy_queries):
    """n_buckets must never change answers — same results for 1, explicit
    B, auto, with k_moves budgets and valid padding, odd batch sizes."""
    from distributed_oracle_search_tpu.ops.table_search import pick_buckets

    g = toy_graph
    targets = np.arange(g.n, dtype=np.int32)
    fm = build_fm_columns(dg, jnp.asarray(targets))
    # replicate queries to a biggish batch with an odd size
    q = np.tile(toy_queries, (41, 1))[:257]
    s = jnp.asarray(q[:, 0], jnp.int32)
    t = jnp.asarray(q[:, 1], jnp.int32)
    valid = jnp.asarray(np.arange(len(q)) % 5 != 3)
    for k_moves in (-1, 2):
        ref = table_search_batch(dg, fm, t, s, t, dg.w_pad, valid=valid,
                                 k_moves=k_moves, n_buckets=1)
        for b in (0, 2, 4, 16):
            got = table_search_batch(dg, fm, t, s, t, dg.w_pad,
                                     valid=valid, k_moves=k_moves,
                                     n_buckets=b)
            for a, r in zip(got, ref):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(r))
    # odd sizes fall back to a divisor (257 is prime -> 1 bucket)
    assert pick_buckets(257, 0) == 1
    assert pick_buckets(65536, 0) == 64
    assert pick_buckets(8192, 0) == 8
    assert pick_buckets(100, 6) == 5


def test_multi_diff_fused_walk_matches_sequential(toy_graph, dg,
                                                  toy_queries):
    """One fused walk under D diffs must equal D sequential single-diff
    walks exactly — costs per diff, shared plen/finished — across bucket
    counts and with valid padding."""
    from distributed_oracle_search_tpu.data import synth_diff
    from distributed_oracle_search_tpu.ops.table_search import (
        table_search_multi,
    )

    g = toy_graph
    targets = np.arange(g.n, dtype=np.int32)
    fm = build_fm_columns(dg, jnp.asarray(targets))
    q = np.tile(toy_queries, (23, 1))[:144]
    s = jnp.asarray(q[:, 0], jnp.int32)
    t = jnp.asarray(q[:, 1], jnp.int32)
    valid = jnp.asarray(np.arange(len(q)) % 7 != 2)
    w_list = [None,
              g.weights_with_diff(synth_diff(g, frac=0.3, seed=11)),
              g.weights_with_diff(synth_diff(g, frac=0.5, seed=12))]
    w_pads = jnp.asarray(np.stack([
        g.padded_weights(g.w if w is None else w) for w in w_list]),
        jnp.int32)
    for b in (0, 1, 4):
        cost, plen, fin = table_search_multi(dg, fm, t, s, t, w_pads,
                                             valid=valid, n_buckets=b)
        assert cost.shape == (3, len(q))
        for di, w in enumerate(w_list):
            wp = dg.w_pad if w is None else jnp.asarray(
                g.padded_weights(w), jnp.int32)
            c1, p1, f1 = table_search_batch(dg, fm, t, s, t, wp,
                                            valid=valid, n_buckets=b)
            np.testing.assert_array_equal(np.asarray(cost[di]),
                                          np.asarray(c1))
            np.testing.assert_array_equal(np.asarray(plen),
                                          np.asarray(p1))
            np.testing.assert_array_equal(np.asarray(fin),
                                          np.asarray(f1))
    # max_steps truncates EXACTLY like the single-diff kernel
    # (regression: the while cond alone overshot by up to unroll-1)
    cm, pm, fmm = table_search_multi(dg, fm, t, s, t, w_pads,
                                     valid=valid, max_steps=3)
    c3, p3, f3 = table_search_batch(dg, fm, t, s, t, w_pads[0],
                                    valid=valid, max_steps=3)
    assert int(np.asarray(pm).max()) <= 3
    np.testing.assert_array_equal(np.asarray(cm[0]), np.asarray(c3))
    np.testing.assert_array_equal(np.asarray(pm), np.asarray(p3))
    np.testing.assert_array_equal(np.asarray(fmm), np.asarray(f3))


def test_route_sorts_by_length_estimate(toy_graph):
    """route() orders each worker group by the coordinate-distance
    estimate (slot_q ascends with expected walk length) and still
    scatters answers back to input order."""
    from distributed_oracle_search_tpu.models.cpd import CPDOracle
    from distributed_oracle_search_tpu.parallel import (
        DistributionController,
    )
    from distributed_oracle_search_tpu.parallel.mesh import make_mesh

    g = toy_graph
    dc = DistributionController("mod", 1, 1, g.n)
    o = CPDOracle(g, dc, mesh=make_mesh(n_workers=1))
    rng = np.random.default_rng(0)
    q = np.stack([rng.integers(0, g.n, 64), rng.integers(0, g.n, 64)],
                 axis=1)
    r_arr, s_arr, t_arr, valid, scatter = o.route(q)
    est = o._length_estimate(q)
    active, sd, sw, sq = scatter
    # same (d) lane: higher slot_q => est must not decrease
    for d in range(r_arr.shape[0]):
        lane = np.nonzero((sd == d) & active)[0]
        order = np.argsort(sq[lane])
        assert (np.diff(est[lane][order]) >= 0).all()
