"""Frame-codec compat suite: the streaming wire's version of the
manifest/membership/segment codec contracts.

Unknown header keys are tolerated, ONLY newer frame-schema versions are
refused, arrays round-trip as zero-copy views, and every torn/truncated
frame on a dead socket surfaces as a typed retryable transport error —
never a hang, never a crash three layers up."""

import socket

import numpy as np
import pytest

from distributed_oracle_search_tpu.transport import frames
from distributed_oracle_search_tpu.transport.frames import (
    FRAME_SCHEMA_VERSION, FrameReader, FrameSchemaError, FrameWriter,
    TornFrame, TransportError, decode_header, encode_frame,
)


@pytest.fixture
def pair():
    a, b = socket.socketpair()
    yield a, b
    a.close()
    b.close()


def _rt(pair, header, arrays=()):
    a, b = pair
    FrameWriter(a).send(header, arrays)
    return FrameReader(b).read()


# ------------------------------------------------------------ round trips

def test_frame_roundtrip_header_and_arrays(pair):
    q = np.arange(12, dtype=np.int64).reshape(6, 2)
    fin = np.array([1, 0, 1], np.uint8)
    fr = _rt(pair, {"kind": "req", "config": {"hscale": 1.5},
                    "diff": "-"}, [q, fin])
    assert fr.kind == "req"
    assert fr.header["config"] == {"hscale": 1.5}
    assert (fr.arrays[0] == q).all() and fr.arrays[0].dtype == np.int64
    assert (fr.arrays[1] == fin).all() and fr.arrays[1].dtype == np.uint8


def test_frame_arrays_are_zero_copy_views(pair):
    q = np.arange(64, dtype=np.int64)
    fr = _rt(pair, {"kind": "rep"}, [q])
    # decoded arrays are frombuffer views into the one receive buffer,
    # not parsed copies — the no-savetxt-on-the-hot-path contract
    assert fr.arrays[0].base is not None


def test_unaligned_segment_still_decodes_aligned(pair):
    # a uint8 segment between two int64 ones: the 8-byte segment
    # padding keeps every view aligned
    fr = _rt(pair, {"kind": "rep"},
             [np.arange(4, dtype=np.int64), np.array([1, 0, 1], np.uint8),
              np.arange(6, dtype=np.int64).reshape(2, 3)])
    assert (fr.arrays[2] == np.arange(6).reshape(2, 3)).all()


def test_empty_payload_and_multiple_frames(pair):
    a, b = pair
    w, r = FrameWriter(a), FrameReader(b)
    w.send({"kind": "ping"})
    w.send({"kind": "ping", "n": 2})
    f1, f2 = r.read(), r.read()
    assert f1.kind == f2.kind == "ping"
    assert f2.header["n"] == 2 and f1.arrays == []


def test_clean_eof_between_frames_is_none(pair):
    a, b = pair
    FrameWriter(a).send({"kind": "ping"})
    a.close()
    r = FrameReader(b)
    assert r.read().kind == "ping"
    assert r.read() is None        # peer closed AT a frame boundary


# -------------------------------------------------------- compat contract

def test_unknown_header_keys_tolerated(pair):
    fr = _rt(pair, {"kind": "req", "future_knob": {"deep": [1, 2]}})
    assert fr.header["future_knob"] == {"deep": [1, 2]}


def test_unknown_frame_kind_decodes(pair):
    # receivers skip unknown kinds; the codec itself must not refuse
    fr = _rt(pair, {"kind": "gossip", "payload": 1})
    assert fr.kind == "gossip"


def test_older_and_absent_version_tolerated(pair):
    a, b = pair
    w, r = FrameWriter(a), FrameReader(b)
    w.send({"kind": "req", "v": 0})
    assert r.read().kind == "req"
    assert decode_header(b'{"kind": "req"}')["kind"] == "req"


def test_newer_version_refused(pair):
    a, b = pair
    FrameWriter(a).send({"kind": "req",
                         "v": FRAME_SCHEMA_VERSION + 1})
    with pytest.raises(FrameSchemaError, match="newer"):
        FrameReader(b).read()


def test_schema_error_is_not_retryable_transport_error():
    # the dispatcher retries TransportError; a schema gate must NOT
    # loop forever on a reconnect that meets the same peer
    assert not issubclass(FrameSchemaError, TransportError)
    assert issubclass(TornFrame, TransportError)


# ------------------------------------------------------- torn-frame paths

def _raw(header, arrays=()):
    return b"".join(bytes(x) for x in encode_frame(header, arrays))


def test_peer_death_mid_frame_is_torn(pair):
    a, b = pair
    raw = _raw({"kind": "req"}, [np.arange(32, dtype=np.int64)])
    a.sendall(raw[: len(raw) // 2])
    a.close()
    with pytest.raises(TornFrame):
        FrameReader(b).read()


def test_bad_magic_is_torn(pair):
    a, b = pair
    a.sendall(b"GARBAGEGARBAGEGARBAGE")
    a.close()
    with pytest.raises(TornFrame, match="magic"):
        FrameReader(b).read()


def test_implausible_lengths_are_torn_not_alloc(pair):
    import struct

    a, b = pair
    a.sendall(frames.MAGIC + struct.pack("<IQ", 16, 1 << 62))
    a.close()
    with pytest.raises(TornFrame, match="implausible"):
        FrameReader(b).read()


def test_undecodable_header_is_torn(pair):
    import struct

    a, b = pair
    hdr = b"not json at all!"
    a.sendall(frames.MAGIC + struct.pack("<IQ", len(hdr), 0) + hdr)
    with pytest.raises(TornFrame, match="undecodable"):
        FrameReader(b).read()


def test_truncated_payload_vs_segs_is_torn(pair):
    import struct
    import json as _json

    a, b = pair
    # header promises a 256-byte segment; payload carries 8 bytes
    hdr = _json.dumps({"kind": "rep", "v": 1,
                       "segs": [{"dtype": "<i8",
                                 "shape": [32]}]}).encode()
    a.sendall(frames.MAGIC + struct.pack("<IQ", len(hdr), 8) + hdr
              + b"\x00" * 8)
    with pytest.raises(TornFrame, match="truncated"):
        FrameReader(b).read()


def test_send_on_dead_socket_is_transport_error(pair):
    a, b = pair
    b.close()
    a.close()
    with pytest.raises(TransportError):
        FrameWriter(a).send({"kind": "ping"})


def test_bounded_read_on_timeout_socket(pair):
    # a socket carrying a timeout never hangs the reader: the timeout
    # surfaces as a retryable transport error
    a, b = pair
    b.settimeout(0.1)
    with pytest.raises(TornFrame):
        FrameReader(b).read()
