"""Worker supervisor: launch, monitor, respawn with capped backoff.

Real ``worker.server`` subprocesses cost a JAX import + engine load
each, so these tests supervise cheap dummy processes through the
injectable ``spawn_fn``/``probe_fn`` seams; the full stack (subprocess
servers, real pings, a mid-campaign kill) runs in the slow chaos test
(test_chaos.py)."""

import subprocess
import sys
import threading
import time

import pytest

from distributed_oracle_search_tpu.transport.wire import HealthStatus
from distributed_oracle_search_tpu.utils.config import ClusterConfig
from distributed_oracle_search_tpu.worker import supervisor as sup_mod
from distributed_oracle_search_tpu.worker.supervisor import (
    WorkerSupervisor,
)


def _conf(n=2):
    return ClusterConfig(workers=["localhost"] * n, partmethod="mod",
                         partkey=n)


def _dummy_spawn(w):
    return subprocess.Popen([sys.executable, "-c",
                             "import time; time.sleep(600)"],
                            start_new_session=True)


def _alive_probe(w):
    if w.proc is not None and w.proc.poll() is None:
        return HealthStatus(ok=True, wid=w.wid)
    return None


def _mk(n=2, **kw):
    kw.setdefault("spawn_fn", _dummy_spawn)
    kw.setdefault("probe_fn", _alive_probe)
    kw.setdefault("ping_interval_s", 0.05)
    kw.setdefault("backoff_base_s", 0.05)
    kw.setdefault("backoff_cap_s", 0.2)
    return WorkerSupervisor(_conf(n), conf_path=None, **kw)


def test_supervisor_starts_monitors_and_stops():
    sup = _mk(2)
    sup.start(wait_ready_s=10)
    try:
        assert all(w.proc.poll() is None for w in sup.workers.values())
        assert sup_mod.G_ALIVE.value == 2
        names = [t.name for t in threading.enumerate()]
        assert "dos-supervisor" in names
    finally:
        sup.stop()
    assert all(w.proc.poll() is not None for w in sup.workers.values())
    assert sup_mod.G_ALIVE.value == 0
    assert "dos-supervisor" not in [t.name for t in
                                    threading.enumerate()
                                    if t.is_alive()]


def test_supervisor_respawns_dead_worker_with_backoff():
    respawns_before = sup_mod.M_RESPAWNS.value
    sup = _mk(2)
    sup.start(wait_ready_s=10)
    try:
        victim = sup.workers[0]
        old_pid = victim.proc.pid
        victim.proc.kill()
        deadline = time.monotonic() + 10
        while (victim.respawns == 0
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert victim.respawns == 1
        assert victim.proc.pid != old_pid
        assert victim.proc.poll() is None        # replacement running
        assert sup_mod.M_RESPAWNS.value == respawns_before + 1
        # the survivor was never touched
        assert sup.workers[1].respawns == 0
        # a good ping resets the backoff step for the next crash
        deadline = time.monotonic() + 5
        while (victim.backoff_k != 0
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert victim.backoff_k == 0
    finally:
        sup.stop()


def test_supervisor_backoff_caps():
    """A worker that dies instantly on every spawn backs off
    exponentially and the delay never exceeds the cap."""
    def doomed_spawn(w):
        return subprocess.Popen([sys.executable, "-c", "pass"])

    # probe never succeeds, so the backoff step is never reset by a
    # "came up healthy" observation racing the instant death
    sup = _mk(1, backoff_base_s=0.05, backoff_cap_s=0.15,
              probe_fn=lambda w: None)
    # bypass start(): install the doomed worker and run the monitor
    sup.spawn_fn = doomed_spawn
    w = sup.workers[0]
    w.proc = doomed_spawn(w)
    w.proc.wait()
    t = threading.Thread(target=sup._monitor, daemon=True,
                         name="dos-supervisor")
    t.start()
    try:
        deadline = time.monotonic() + 10
        while w.respawns < 4 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert w.respawns >= 4
        assert sup._backoff_s(w) == 0.15         # capped
    finally:
        sup._stop.set()
        t.join(timeout=5)
        if w.proc is not None and w.proc.poll() is None:
            w.proc.kill()
            w.proc.wait()
        sup_mod.G_ALIVE.set(0)


def test_supervisor_hung_worker_optin_respawn():
    """Ping-based respawn is opt-in (unhealthy_pings): a live process
    whose pings keep failing is killed and relaunched."""
    sup = _mk(1, unhealthy_pings=3,
              probe_fn=lambda w: None)           # every ping fails
    w = sup.workers[0]
    w.proc = _dummy_spawn(w)
    w.healthy_once = True
    t = threading.Thread(target=sup._monitor, daemon=True,
                         name="dos-supervisor")
    t.start()
    try:
        deadline = time.monotonic() + 10
        while w.respawns == 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert w.respawns >= 1
    finally:
        sup._stop.set()
        t.join(timeout=5)
        if w.proc is not None and w.proc.poll() is None:
            w.proc.kill()
            w.proc.wait()
        sup_mod.G_ALIVE.set(0)


def test_supervisor_start_fails_loudly_when_worker_never_ready():
    sup = _mk(1, probe_fn=lambda w: None)
    with pytest.raises(RuntimeError, match="not ready"):
        sup.start(wait_ready_s=0.5)
    sup.stop()


def test_supervisor_env_knobs(monkeypatch):
    monkeypatch.setenv("DOS_SUPERVISOR_PING_S", "9")
    monkeypatch.setenv("DOS_SUPERVISOR_BACKOFF_BASE_S", "0.25")
    monkeypatch.setenv("DOS_SUPERVISOR_BACKOFF_CAP_S", "3")
    monkeypatch.setenv("DOS_SUPERVISOR_UNHEALTHY_PINGS", "5")
    sup = WorkerSupervisor(_conf(1), conf_path=None,
                           spawn_fn=_dummy_spawn,
                           probe_fn=_alive_probe)
    assert sup.ping_interval_s == 9
    assert sup.backoff_base_s == 0.25
    assert sup.backoff_cap_s == 3
    assert sup.unhealthy_pings == 5


def test_supervisor_add_and_remove_worker():
    """Elastic membership support: a worker joins the supervised set
    without touching the running fleet, and a leave drains it clean —
    monitor keeps running throughout, never respawns the leaver."""
    sup = _mk(2)
    sup.start(wait_ready_s=10)
    try:
        w = sup.add_worker(2)
        assert w.proc.poll() is None and w.healthy_once
        assert sup.health() == {"ok": True, "alive": 3, "workers": 3}
        assert "2" in sup.statusz()["workers"]
        with pytest.raises(ValueError, match="already supervised"):
            sup.add_worker(2)
        # leave: unsupervised first, then stopped (dummies have no
        # stop-token reader, so the drain escalates to SIGTERM — the
        # seam under test is supervision, not the server's drain)
        assert 2 in sup.workers
        sup.remove_worker(2, join_s=1.0)
        assert 2 not in sup.workers
        assert w.proc.poll() is not None
        time.sleep(0.2)          # monitor ticks: no respawn of a leaver
        assert sup.health()["workers"] == 2
        assert sup.remove_worker(7) is False     # unknown wid: no-op
    finally:
        sup.stop()


def test_add_worker_unwinds_on_raising_probe():
    """A probe that RAISES during the readiness poll (an anticipated
    mode — the monitor wraps the same call) must not strand a
    half-joined worker supervised: the joiner is fully unwound so the
    caller can retry."""
    def boom(w):
        raise OSError("probe transport down")

    sup = _mk(2, probe_fn=boom)
    # no start(): the seam under test is add_worker's own cleanup
    with pytest.raises(OSError, match="probe transport down"):
        sup.add_worker(2)
    assert 2 not in sup.workers
    sup2 = _mk(2)
    sup2.probe_fn = boom
    try:
        with pytest.raises(OSError):
            sup2.add_worker(2)
        sup2.probe_fn = _alive_probe
        w = sup2.add_worker(2, wait_ready_s=10)   # retry succeeds
        assert w.healthy_once
    finally:
        sup2.stop()
