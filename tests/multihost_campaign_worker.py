"""Subprocess body for the 2-process conf-driven campaign test (not a
pytest file).

Unlike ``multihost_worker.py`` (which drives the builder directly), this
exercises the actual driver: ``cli.process_query.main`` with a cluster conf
whose ``multihost`` key wires the processes into one mesh — proving the
drivers themselves, not just the kernels, run multi-controller (SURVEY.md
§7 stage 6).

Usage: multihost_campaign_worker.py <process_id> <conf_path> <out_dir>
"""

import os
import sys

pid, conf_path, out_dir = int(sys.argv[1]), sys.argv[2], sys.argv[3]

# the driver resolves the process id from $DOS_PROCESS_ID
# (parallel/multihost.py initialize_from_conf)
os.environ["DOS_PROCESS_ID"] = str(pid)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_oracle_search_tpu.cli import process_query  # noqa: E402

rc = process_query.main(["-c", conf_path, "-o", out_dir])
assert rc == 0, rc

import jax  # noqa: E402

print(f"CAMPAIGN_OK process={pid} nproc={jax.process_count()} "
      f"devices={len(jax.devices())}")
