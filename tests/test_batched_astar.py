"""Batched device A* (``ops.batched_astar``): optimality, pruning,
weighted bound, engine serving path, deadline truncation.

The serving-path counterpart of the per-query CPU heap oracle
(``models.astar``) — same knobs (reference ``args.py:30-57``), lock-step
dense sweeps instead of a priority queue.
"""

import time

import numpy as np
import pytest

from distributed_oracle_search_tpu.cli import process_query as pq
from distributed_oracle_search_tpu.cli.args import parse_args
from distributed_oracle_search_tpu.data import (
    Graph, ensure_synth_dataset, read_scen, synth_city_graph, synth_scenario,
)
from distributed_oracle_search_tpu.models.reference import dist_to_target
from distributed_oracle_search_tpu.ops import astar_batch_np


@pytest.fixture(scope="module")
def graph():
    return synth_city_graph(9, 7, seed=41)


@pytest.fixture(scope="module")
def queries(graph):
    return synth_scenario(graph.n, 48, seed=5)


@pytest.fixture(scope="module")
def opt(graph, queries):
    """Golden optimal costs via the CPU Dijkstra oracle, per target."""
    cost = np.zeros(len(queries), np.int64)
    for i, (s, t) in enumerate(queries):
        cost[i] = dist_to_target(graph, int(t))[int(s)]
    return cost


def test_admissible_is_exactly_optimal(graph, queries, opt):
    cost, plen, fin, counters = astar_batch_np(graph, queries, hscale=1.0)
    assert fin.all()
    np.testing.assert_array_equal(cost, opt)
    assert (plen >= (opt > 0)).all() and (plen < graph.n).all()
    assert counters["n_expanded"] > 0 and counters["n_inserted"] > 0
    assert counters["n_touched"] >= counters["n_expanded"]


def test_chunking_is_transparent(graph, queries, opt):
    cost, _, fin, _ = astar_batch_np(graph, queries, hscale=1.0, chunk=7)
    assert fin.all()
    np.testing.assert_array_equal(cost, opt)


def test_diffed_weights_optimal(graph, queries):
    rng = np.random.default_rng(3)
    w = graph.w.copy()
    bump = rng.integers(0, 2, graph.m).astype(bool)
    w[bump] = w[bump] * 3
    cost, _, fin, _ = astar_batch_np(graph, queries, w=w, hscale=1.0)
    assert fin.all()
    for i, (s, t) in enumerate(queries):
        assert cost[i] == dist_to_target(graph, int(t), w=w)[int(s)]


def test_weighted_bound_and_pruning(graph, queries, opt):
    """hscale > 1: costs bounded by hscale x optimal (weighted-A* bound),
    and the aggressive prune does strictly less edge work."""
    c1, _, f1, k1 = astar_batch_np(graph, queries, hscale=1.0)
    c3, _, f3, k3 = astar_batch_np(graph, queries, hscale=3.0)
    assert f3.all()
    assert (c3 >= opt).all()
    assert (c3 <= 3.0 * opt + 1e-9).all()
    assert k3["n_touched"] < k1["n_touched"]


def test_fscale_keeps_optimality(graph, queries, opt):
    """fscale loosens the incumbent prune — admissible search stays
    optimal (CPU-oracle parity: models/astar.py fscale semantics)."""
    cost, _, fin, _ = astar_batch_np(graph, queries, hscale=1.0, fscale=0.5)
    assert fin.all()
    np.testing.assert_array_equal(cost, opt)


def test_past_deadline_still_answers_first_chunk(graph, queries):
    """An already-expired budget must still produce a minimal answer —
    the first chunk runs, later chunks stay unfinished (the per-query
    CPU oracle's at-least-one-query behavior, chunk-granular)."""
    cost, plen, fin, counters = astar_batch_np(
        graph, queries, chunk=4, deadline=time.perf_counter() - 1.0)
    assert fin[:4].all()
    assert not fin[4:].any()
    assert (cost[4:] == 0).all() and (plen[4:] == 0).all()
    assert counters["n_expanded"] > 0


def test_engine_astar_deadline_truncates_batch(tmp_path):
    """A 1 ns budget cuts the campaign short with finished < size and
    correct partial stats (reference args.py:38-57 time-budget teeth)."""
    from distributed_oracle_search_tpu.parallel.partition import (
        DistributionController,
    )
    from distributed_oracle_search_tpu.worker import ShardEngine

    dataset = ensure_synth_dataset(str(tmp_path), width=9, height=7,
                                   n_queries=48, seed=41)
    graph = Graph.from_xy(dataset["xy"])
    dc = DistributionController("mod", 1, 1, graph.n)
    eng = ShardEngine(graph, dc, wid=0, outdir=str(tmp_path), alg="astar")
    eng.astar_chunk = 4      # several chunks so truncation is observable
    qs = read_scen(dataset["scen"])[:16]
    args = parse_args(["--ns-lim", "1"])
    cfg = pq.runtime_config(args)
    assert cfg.time == 1
    cost, plen, fin, stats = eng.answer(qs, cfg)
    # first chunk answered (minimal progress), later chunks truncated
    assert 0 < stats.finished == int(fin.sum()) < len(qs)


def test_engine_debug_uses_heap_oracle(tmp_path):
    """config.debug routes to the per-query CPU heap oracle; costs agree
    with the batched kernel (both optimal at hscale=1)."""
    from distributed_oracle_search_tpu.parallel.partition import (
        DistributionController,
    )
    from distributed_oracle_search_tpu.worker import ShardEngine

    dataset = ensure_synth_dataset(str(tmp_path), width=9, height=7,
                                   n_queries=48, seed=41)
    graph = Graph.from_xy(dataset["xy"])
    dc = DistributionController("mod", 1, 1, graph.n)
    eng = ShardEngine(graph, dc, wid=0, outdir=str(tmp_path), alg="astar")
    qs = read_scen(dataset["scen"])[:12]
    fast = eng.answer(qs, pq.runtime_config(parse_args([])))
    dbg = eng.answer(qs, pq.runtime_config(parse_args(["--debug"])))
    np.testing.assert_array_equal(fast[0], dbg[0])
    assert dbg[3].finished == fast[3].finished == len(qs)
