"""DistributionController: property tests against the executable spec
(reference offline.py:50-63) and the wire format (process_query.py:46-53)."""

import numpy as np
import pytest

from distributed_oracle_search_tpu.parallel import DistributionController


def spec_wid(node, method, key):
    # transliteration of the reference's Python partition spec semantics
    if method == "div":
        return node // key
    if method == "mod":
        return node % key
    if method == "alloc":
        return next(i for i, bound in enumerate(key) if bound > node)
    raise ValueError(method)


@pytest.mark.parametrize("method,key,maxworker", [
    ("mod", 8, 8),
    ("mod", 3, 8),
    ("div", 13, 8),   # 13*8 >= 100
    ("alloc", [10, 25, 60, 100], 4),
])
def test_matches_spec(method, key, maxworker):
    n = 100
    dc = DistributionController(method, key, maxworker, n)
    for node in range(n):
        assert dc.worker_of([node])[0] == spec_wid(node, method, key)


def test_div_out_of_range_raises():
    with pytest.raises(ValueError):
        DistributionController("div", 10, 4, 100)  # node 99 -> wid 9 >= 4


def test_tpu_contiguous_chunks():
    dc = DistributionController("tpu", None, 4, 103)
    wids = dc.worker_of(np.arange(103))
    # contiguous, ascending, covers all workers, balanced to +-1 chunk
    assert np.all(np.diff(wids) >= 0)
    assert wids.max() == 3
    chunk = -(-103 // 4)
    assert np.all(wids == np.arange(103) // chunk)


@pytest.mark.parametrize("method,key", [("mod", 8), ("div", 13), ("tpu", None)])
def test_owned_index_dense(method, key):
    n = 100
    dc = DistributionController(method, key, 8, n)
    for wid in range(8):
        owned = dc.owned(wid)
        assert dc.n_owned(wid) == len(owned)
        # ascending node order, dense owned indices 0..k-1
        assert np.all(np.diff(owned) > 0)
        np.testing.assert_array_equal(
            dc.owned_index_of(owned), np.arange(len(owned)))
    # every node owned exactly once
    total = sum(dc.n_owned(w) for w in range(8))
    assert total == n


def test_table_and_wire_format():
    dc = DistributionController("mod", 4, 4, 12, block_size=2)
    tab = dc.table()
    assert tab.shape == (12, 4)
    # bid/bidx consistent with owned index and block size
    np.testing.assert_array_equal(
        tab[:, 2] * 2 + tab[:, 3], dc.owned_index_of(np.arange(12)))
    # wire format: header + one CSV row per node, parseable the way the
    # reference driver parses gen_distribute_conf output
    lines = dc.format_conf().split("\n")
    assert len(lines) == 13
    node2worker = {}
    for l in lines[1:]:
        node, wid, bid, bidx = map(int, l.split(","))
        node2worker[node] = wid
    assert node2worker == {i: i % 4 for i in range(12)}


def test_group_queries_by_target_owner():
    dc = DistributionController("mod", 4, 4, 100)
    qs = np.array([[1, 2], [3, 6], [5, 2], [0, 7], [9, 11]])
    groups = dc.group_queries(qs)
    # invariant: every query lands on the worker owning its *target*
    for wid, part in groups.items():
        assert np.all(dc.worker_of(part[:, 1]) == wid)
    assert sum(len(p) for p in groups.values()) == len(qs)
    # active-worker restriction (-w flag semantics)
    only2 = dc.group_queries(qs, active_worker=2)
    assert list(only2) == [2]
    np.testing.assert_array_equal(only2[2], [[1, 2], [3, 6], [5, 2]])


def test_balanced_partitions_mod_vs_tpu():
    n = 1000
    for method, key in [("mod", 8), ("tpu", None)]:
        dc = DistributionController(method, key, 8, n)
        counts = [dc.n_owned(w) for w in range(8)]
        assert max(counts) - min(counts) <= -(-n // 8)
