"""``utils.env`` knob-parsing policy and the non-durable atomic-replace
variant the wire sidecars use."""

import os

import pytest

from distributed_oracle_search_tpu.utils.atomicio import (
    atomic_replace_bytes,
)
from distributed_oracle_search_tpu.utils.env import (
    env_cast, env_flag, env_str,
)


@pytest.fixture
def knob(monkeypatch):
    def set_(val):
        if val is None:
            monkeypatch.delenv("DOS_TEST_KNOB", raising=False)
        else:
            monkeypatch.setenv("DOS_TEST_KNOB", val)
    return set_


def test_env_flag_spellings(knob):
    for raw, want in [("1", True), ("true", True), ("YES", True),
                      ("on", True), ("0", False), ("false", False),
                      ("No", False), ("off", False)]:
        knob(raw)
        assert env_flag("DOS_TEST_KNOB", not want) is want, raw


@pytest.mark.parametrize("default", [True, False])
def test_env_flag_absent_and_empty_take_default(knob, default):
    """FLAG=${UNSET_VAR} interpolation yields an EMPTY value: it must
    behave like absence, never silently flip a default-on knob off."""
    for raw in (None, "", "   "):
        knob(raw)
        assert env_flag("DOS_TEST_KNOB", default) is default


def test_env_flag_malformed_degrades_to_default(knob):
    knob("maybe")
    assert env_flag("DOS_TEST_KNOB", True) is True
    assert env_flag("DOS_TEST_KNOB", False) is False


def test_env_cast_and_str(knob):
    knob("17")
    assert env_cast("DOS_TEST_KNOB", 3, int) == 17
    knob("banana")
    assert env_cast("DOS_TEST_KNOB", 3, int) == 3
    knob("x")
    assert env_str("DOS_TEST_KNOB") == "x"
    knob(None)
    assert env_str("DOS_TEST_KNOB") is None
    assert env_str("DOS_TEST_KNOB", "d") == "d"


def test_atomic_writer_streams_and_cleans_up(tmp_path):
    from distributed_oracle_search_tpu.utils.atomicio import atomic_writer
    p = tmp_path / "parts.csv"
    with atomic_writer(str(p)) as f:
        f.write("wid,cost\n")
        f.write("0,42\n")
    assert p.read_text() == "wid,cost\n0,42\n"
    with pytest.raises(RuntimeError):
        with atomic_writer(str(tmp_path / "doomed.csv")) as f:
            f.write("partial")
            raise RuntimeError("mid-write crash")
    assert not (tmp_path / "doomed.csv").exists()
    assert [x for x in os.listdir(tmp_path) if ".tmp." in x] == []


def test_atomic_replace_is_rename_based(tmp_path):
    """Readers of a transient wire sidecar see old bytes or new bytes,
    never a prefix — and no tmp debris survives the replace."""
    p = tmp_path / "query.results"
    p.write_bytes(b"old")
    atomic_replace_bytes(str(p), b"new contents")
    assert p.read_bytes() == b"new contents"
    assert [f for f in os.listdir(tmp_path) if ".tmp." in f] == []
