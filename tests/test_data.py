"""Data layer: formats round-trip, graph invariants, synth generators."""

import numpy as np
import pytest

from distributed_oracle_search_tpu.data import (
    Graph, read_xy, write_xy, read_scen, write_scen, read_diff, write_diff,
    xy_node_count, synth_city_graph, synth_scenario, synth_diff,
)
from distributed_oracle_search_tpu.data.graph import INF


def test_xy_roundtrip(tmp_path, toy_graph):
    p = str(tmp_path / "g.xy")
    g = toy_graph
    write_xy(p, g.xs, g.ys, g.src, g.dst, g.w)
    xs, ys, src, dst, w = read_xy(p)
    np.testing.assert_array_equal(xs, g.xs)
    np.testing.assert_array_equal(ys, g.ys)
    np.testing.assert_array_equal(src, g.src)
    np.testing.assert_array_equal(dst, g.dst)
    np.testing.assert_array_equal(w, g.w)


def test_xy_node_count_contract(tmp_path, toy_graph):
    # The one structural fact the reference driver relies on: 4th line,
    # 2nd whitespace token = node count (process_query.py:126-130).
    p = str(tmp_path / "g.xy")
    g = toy_graph
    write_xy(p, g.xs, g.ys, g.src, g.dst, g.w)
    assert xy_node_count(p) == g.n
    with open(p) as f:
        line4 = f.read().split("\n")[3]
    assert int(line4.split(" ")[1]) == g.n


def test_scen_roundtrip(tmp_path):
    qs = synth_scenario(100, 37, seed=3)
    p = str(tmp_path / "a.scen")
    write_scen(p, qs, comment="test")
    back = read_scen(p)
    np.testing.assert_array_equal(back, qs)
    assert np.all(back[:, 0] != back[:, 1])


def test_scen_ignores_non_q_lines(tmp_path):
    p = str(tmp_path / "b.scen")
    with open(p, "w") as f:
        f.write("c header\nversion 1\n\nq 3 5\nx 9 9\nq 1 2\n")
    np.testing.assert_array_equal(read_scen(p), [[3, 5], [1, 2]])


def test_diff_roundtrip_and_apply(tmp_path, toy_graph):
    g = toy_graph
    ds, dd, dw = synth_diff(g, frac=0.25, seed=5)
    p = str(tmp_path / "g.xy.diff")
    write_diff(p, ds, dd, dw)
    rs, rd, rw = read_diff(p)
    np.testing.assert_array_equal(rs, ds)
    np.testing.assert_array_equal(rw, dw)

    w2 = g.weights_with_diff(p)
    eids = g.edge_ids(ds, dd)
    np.testing.assert_array_equal(w2[eids], dw)
    mask = np.ones(g.m, bool)
    mask[eids] = False
    np.testing.assert_array_equal(w2[mask], g.w[mask])


def test_no_diff_dash():
    g = synth_city_graph(3, 3, seed=0)
    np.testing.assert_array_equal(g.weights_with_diff("-"), g.w)


def test_graph_csr_and_ell(toy_graph):
    g = toy_graph
    # CSR partitions the edge set by src / dst
    assert g.out_ptr[-1] == g.m and g.in_ptr[-1] == g.m
    for u in [0, 1, g.n // 2, g.n - 1]:
        nbrs, eids = g.out_edges(u)
        np.testing.assert_array_equal(g.src[eids], u)
        np.testing.assert_array_equal(g.dst[eids], nbrs)

    nbr, eid = g.ell("out")
    assert nbr.shape == eid.shape == (g.n, g.max_out_degree)
    w_pad = g.padded_weights()
    assert w_pad[-1] == INF
    # every real edge appears exactly once in the ELL table
    real = eid[eid < g.m]
    assert len(real) == g.m and len(np.unique(real)) == g.m
    # padded slots point at self with INF weight
    pad_rows, pad_cols = np.nonzero(eid == g.m)
    np.testing.assert_array_equal(nbr[pad_rows, pad_cols], pad_rows)
    # slot order is ascending edge id per row
    for u in range(min(g.n, 20)):
        row = eid[u][eid[u] < g.m]
        assert np.all(np.diff(row) > 0)


def test_synth_city_strongly_connected_small():
    from distributed_oracle_search_tpu.models import dijkstra
    g = synth_city_graph(5, 4, seed=1)
    d = dijkstra(g, 0)
    assert d.max() < INF  # reachable from 0
    dr = dijkstra(g, 0, reverse=True)
    assert dr.max() < INF  # 0 reachable from all
