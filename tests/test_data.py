"""Data layer: formats round-trip, graph invariants, synth generators."""

import numpy as np
import pytest

from distributed_oracle_search_tpu.data import (
    Graph, read_xy, write_xy, read_scen, write_scen, read_diff, write_diff,
    xy_node_count, synth_city_graph, synth_scenario, synth_diff,
)
from distributed_oracle_search_tpu.data.graph import INF


def test_xy_roundtrip(tmp_path, toy_graph):
    p = str(tmp_path / "g.xy")
    g = toy_graph
    write_xy(p, g.xs, g.ys, g.src, g.dst, g.w)
    xs, ys, src, dst, w = read_xy(p)
    np.testing.assert_array_equal(xs, g.xs)
    np.testing.assert_array_equal(ys, g.ys)
    np.testing.assert_array_equal(src, g.src)
    np.testing.assert_array_equal(dst, g.dst)
    np.testing.assert_array_equal(w, g.w)


def test_xy_node_count_contract(tmp_path, toy_graph):
    # The one structural fact the reference driver relies on: 4th line,
    # 2nd whitespace token = node count (process_query.py:126-130).
    p = str(tmp_path / "g.xy")
    g = toy_graph
    write_xy(p, g.xs, g.ys, g.src, g.dst, g.w)
    assert xy_node_count(p) == g.n
    with open(p) as f:
        line4 = f.read().split("\n")[3]
    assert int(line4.split(" ")[1]) == g.n


def test_scen_roundtrip(tmp_path):
    qs = synth_scenario(100, 37, seed=3)
    p = str(tmp_path / "a.scen")
    write_scen(p, qs, comment="test")
    back = read_scen(p)
    np.testing.assert_array_equal(back, qs)
    assert np.all(back[:, 0] != back[:, 1])


def test_scen_ignores_non_q_lines(tmp_path):
    p = str(tmp_path / "b.scen")
    with open(p, "w") as f:
        f.write("c header\nversion 1\n\nq 3 5\nx 9 9\nq 1 2\n")
    np.testing.assert_array_equal(read_scen(p), [[3, 5], [1, 2]])


def test_diff_roundtrip_and_apply(tmp_path, toy_graph):
    g = toy_graph
    ds, dd, dw = synth_diff(g, frac=0.25, seed=5)
    p = str(tmp_path / "g.xy.diff")
    write_diff(p, ds, dd, dw)
    rs, rd, rw = read_diff(p)
    np.testing.assert_array_equal(rs, ds)
    np.testing.assert_array_equal(rw, dw)

    w2 = g.weights_with_diff(p)
    eids = g.edge_ids(ds, dd)
    np.testing.assert_array_equal(w2[eids], dw)
    mask = np.ones(g.m, bool)
    mask[eids] = False
    np.testing.assert_array_equal(w2[mask], g.w[mask])


def test_no_diff_dash():
    g = synth_city_graph(3, 3, seed=0)
    np.testing.assert_array_equal(g.weights_with_diff("-"), g.w)


def test_graph_csr_and_ell(toy_graph):
    g = toy_graph
    # CSR partitions the edge set by src / dst
    assert g.out_ptr[-1] == g.m and g.in_ptr[-1] == g.m
    for u in [0, 1, g.n // 2, g.n - 1]:
        nbrs, eids = g.out_edges(u)
        np.testing.assert_array_equal(g.src[eids], u)
        np.testing.assert_array_equal(g.dst[eids], nbrs)

    nbr, eid = g.ell("out")
    assert nbr.shape == eid.shape == (g.n, g.max_out_degree)
    w_pad = g.padded_weights()
    assert w_pad[-1] == INF
    # every real edge appears exactly once in the ELL table
    real = eid[eid < g.m]
    assert len(real) == g.m and len(np.unique(real)) == g.m
    # padded slots point at self with INF weight
    pad_rows, pad_cols = np.nonzero(eid == g.m)
    np.testing.assert_array_equal(nbr[pad_rows, pad_cols], pad_rows)
    # slot order is ascending edge id per row
    for u in range(min(g.n, 20)):
        row = eid[u][eid[u] < g.m]
        assert np.all(np.diff(row) > 0)


def test_synth_city_strongly_connected_small():
    from distributed_oracle_search_tpu.models import dijkstra
    g = synth_city_graph(5, 4, seed=1)
    d = dijkstra(g, 0)
    assert d.max() < INF  # reachable from 0
    dr = dijkstra(g, 0, reverse=True)
    assert dr.max() < INF  # 0 reachable from all


# ------------------------------------------------------------- DIMACS

def _write_dimacs(tmp_path, g):
    gr = str(tmp_path / "t.gr")
    co = str(tmp_path / "t.co")
    with open(gr, "w") as f:
        f.write("c test graph\n")
        f.write(f"p sp {g.n} {g.m}\n")
        for u, v, w in zip(g.src, g.dst, g.w):
            f.write(f"a {u + 1} {v + 1} {w}\n")
    with open(co, "w") as f:
        f.write(f"p aux sp co {g.n}\n")
        for i, (x, y) in enumerate(zip(g.xs, g.ys)):
            f.write(f"v {i + 1} {x} {y}\n")
    return gr, co


def test_dimacs_roundtrip(tmp_path, toy_graph):
    from distributed_oracle_search_tpu.data import graph_from_dimacs

    g = toy_graph
    gr, co = _write_dimacs(tmp_path, g)
    g2 = graph_from_dimacs(gr, co)
    assert g2.n == g.n and g2.m == g.m
    np.testing.assert_array_equal(g2.xs, g.xs)
    np.testing.assert_array_equal(g2.ys, g.ys)
    # same edge multiset (construction may reorder)
    k1 = np.sort(g.src * g.n + g.dst)
    k2 = np.sort(g2.src * g2.n + g2.dst)
    np.testing.assert_array_equal(k1, k2)


def test_dimacs_converter_cli(tmp_path, toy_graph):
    from distributed_oracle_search_tpu.data import Graph
    from distributed_oracle_search_tpu.data.dimacs import main as dmain

    g = toy_graph
    gr, co = _write_dimacs(tmp_path, g)
    out = str(tmp_path / "conv.xy")
    assert dmain(["--gr", gr, "--co", co, "-o", out]) == 0
    g2 = Graph.from_xy(out)
    assert g2.n == g.n and g2.m == g.m


def test_dimacs_without_coordinates(tmp_path, toy_graph):
    from distributed_oracle_search_tpu.data import graph_from_dimacs

    gr, _ = _write_dimacs(tmp_path, toy_graph)
    g2 = graph_from_dimacs(gr)
    assert (g2.xs == 0).all() and g2.m == toy_graph.m


# ----------------------------------------------------------- reordering

def test_reorder_preserves_shortest_paths(toy_graph):
    from distributed_oracle_search_tpu.models.reference import (
        dist_to_target,
    )

    g = toy_graph
    rng = np.random.default_rng(3)
    perm = rng.permutation(g.n)
    g2 = g.reorder(perm)
    inv = np.empty(g.n, np.int64)
    inv[perm] = np.arange(g.n)
    for t in (0, 7, g.n - 1):
        d1 = dist_to_target(g, t)
        d2 = dist_to_target(g2, int(inv[t]))
        np.testing.assert_array_equal(d1, d2[inv])


def test_orders_are_permutations_and_rcm_reduces_bandwidth(toy_graph):
    g0 = toy_graph
    rng = np.random.default_rng(9)
    g = g0.reorder(rng.permutation(g0.n))   # destroy locality
    for perm in (g.bfs_order(), g.rcm_order()):
        assert np.array_equal(np.sort(perm), np.arange(g.n))

    def bandwidth(gg):
        return int(np.abs(gg.src - gg.dst).max())

    g_rcm = g.reorder(g.rcm_order())
    assert bandwidth(g_rcm) < bandwidth(g)


def test_reorder_cli_remaps_dataset(tmp_path, toy_graph):
    from distributed_oracle_search_tpu.cli.reorder import main as rmain
    from distributed_oracle_search_tpu.data import (
        Graph, read_scen, write_scen, write_xy,
    )
    from distributed_oracle_search_tpu.models.reference import (
        dist_to_target, first_move_to_target, table_search_walk,
    )

    g = toy_graph
    xy = str(tmp_path / "g.xy")
    write_xy(xy, g.xs, g.ys, g.src, g.dst, g.w)
    scen_in = str(tmp_path / "in.scen")
    rng = np.random.default_rng(1)
    q = np.stack([rng.integers(0, g.n, 16), rng.integers(0, g.n, 16)],
                 axis=1)
    write_scen(scen_in, q)
    out = str(tmp_path / "g-rcm.xy")
    scen_out = str(tmp_path / "out.scen")
    assert rmain(["--input", xy, "--order", "rcm", "-o", out,
                  "--scen", scen_in, scen_out]) == 0
    g2 = Graph.from_xy(out)
    q2 = read_scen(scen_out)
    perm = np.loadtxt(out + ".order", dtype=np.int64)
    assert np.array_equal(np.sort(perm), np.arange(g.n))
    # remapped queries answer with the SAME costs as the originals
    for (s, t), (s2, t2) in zip(q[:6], q2[:6]):
        assert dist_to_target(g, int(t))[s] == \
            dist_to_target(g2, int(t2))[s2]


def test_synth_road_network_properties():
    from distributed_oracle_search_tpu.data import synth_road_network

    g = synth_road_network(4000, seed=0)
    assert g.grid_split() is None           # non-grid by construction
    deg = np.diff(g.out_ptr)
    assert deg.max() >= 10                  # degree-skewed (hubs)
    assert np.percentile(deg, 50) <= 6
    # single strongly-connected-ish component: BFS from node 0 reaches all
    ptr, nbr = g._undirected_csr()
    seen = np.zeros(g.n, bool)
    seen[0] = True
    frontier = np.array([0])
    while len(frontier):
        nxt = g.frontier_neighbors(ptr, nbr, frontier)
        nxt = nxt[~seen[nxt]]
        seen[nxt] = True
        frontier = nxt
    assert seen.all(), "road network must be connected"


def test_dimacs_rejects_out_of_range_weight(tmp_path):
    """A .gr arc weight >= INF (or negative) must be rejected up front:
    the int32 min-plus relaxation relies on INF+INF < int32 max, which
    an ingested giant weight would silently wrap."""
    import pytest

    from distributed_oracle_search_tpu.data.dimacs import read_gr

    for bad in (10**9, -5):
        p = tmp_path / f"bad{bad}.gr"
        p.write_text("p sp 2 1\n" f"a 1 2 {bad}\n")
        with pytest.raises(ValueError, match="weight"):
            read_gr(str(p))
    ok = tmp_path / "ok.gr"
    ok.write_text("p sp 2 1\na 1 2 999999999\n")
    n, src, dst, w = read_gr(str(ok))
    assert w[0] == 999999999
