"""Streaming RPC data plane, end to end.

One fleet of in-thread workers serves BOTH transports from the same
engines (the FIFO serve loop and the socket accept loop share each
``FifoServer``); the suite pins parity with the FIFO wire, multiplexed
in-flight batches on one socket, explicit BUSY backpressure, the
membership + diff epoch gates over sockets, the hedged-dispatch
query-file reuse on the FIFO backend, and the acceptance chaos drill:
kill-mid-batch + drop-reply over sockets completing degraded-not-wedged
with answers bit-identical to the fault-free FIFO run."""

import glob
import os
import threading
import time

import numpy as np
import pytest

import distributed_oracle_search_tpu.serving.dispatch as dmod
from distributed_oracle_search_tpu.cli import process_query as pq
from distributed_oracle_search_tpu.data import (
    ensure_synth_dataset, read_scen,
)
from distributed_oracle_search_tpu.data.graph import Graph
from distributed_oracle_search_tpu.models.cpd import (
    build_replica_shards, build_worker_shard, write_index_manifest,
)
from distributed_oracle_search_tpu.obs import metrics as obs_metrics
from distributed_oracle_search_tpu.parallel.partition import (
    DistributionController,
)
from distributed_oracle_search_tpu.serving import (
    AutoDispatcher, DispatchError, FifoDispatcher, HedgeConfig,
    RpcDispatcher, ServeConfig, ServingFrontend,
)
from distributed_oracle_search_tpu.testing import faults
from distributed_oracle_search_tpu.transport import resilience
from distributed_oracle_search_tpu.transport import rpc as rpc_transport
from distributed_oracle_search_tpu.transport.frames import TransportError
from distributed_oracle_search_tpu.transport.wire import RuntimeConfig
from distributed_oracle_search_tpu.utils.config import ClusterConfig
from distributed_oracle_search_tpu.worker import FifoServer, stop_server
from distributed_oracle_search_tpu.worker import supervisor as sup_mod
from distributed_oracle_search_tpu.worker.build import main as build_main
from distributed_oracle_search_tpu.worker.server import RpcServeLoop

pytestmark = pytest.mark.rpc

N_WORKERS = 2


def _counter(name: str) -> float:
    return obs_metrics.REGISTRY.snapshot()["counters"].get(name, 0)


# -------------------------------------------------------------- fixtures

@pytest.fixture(scope="module")
def rpc_world(tmp_path_factory):
    """2-shard R=2 world with primary + replica CPD shards built (the
    bench repl-section pattern), so failover and hedging have a live
    second lane."""
    datadir = str(tmp_path_factory.mktemp("rpc-world"))
    paths = ensure_synth_dataset(datadir, width=10, height=8,
                                 n_queries=96, seed=29)
    conf = ClusterConfig(
        workers=["localhost"] * N_WORKERS,
        partmethod="mod", partkey=N_WORKERS,
        outdir=os.path.join(datadir, "index"),
        xy_file=paths["xy"], scenfile=paths["scen"],
        nfs=datadir, replication=2,
    ).validate()
    g = Graph.from_xy(conf.xy_file)
    dc = DistributionController("mod", N_WORKERS, N_WORKERS, g.n,
                                replication=2)
    for wid in range(N_WORKERS):
        build_worker_shard(g, dc, wid, conf.outdir)
        build_replica_shards(g, dc, wid, conf.outdir)
    write_index_manifest(conf.outdir, dc)
    queries = read_scen(conf.scenfile)
    return conf, g, dc, queries


class _Fleet:
    """Both workers serving both transports, restartable per lane."""

    def __init__(self, conf, sockdir):
        self.conf = conf
        self.sockdir = sockdir
        self.servers = {}
        self.threads = {}
        self.loops = {}
        for wid in range(N_WORKERS):
            srv = FifoServer(conf, wid, command_fifo=self.fifo_of(wid))
            th = threading.Thread(target=srv.serve_forever, daemon=True)
            th.start()
            self.servers[wid] = srv
            self.threads[wid] = th
            self.loops[wid] = RpcServeLoop(
                srv, socket_path=self.sock_of(wid)).start()
        for wid in range(N_WORKERS):
            for _ in range(200):
                if os.path.exists(self.fifo_of(wid)):
                    break
                time.sleep(0.02)

    def fifo_of(self, wid: int) -> str:
        return os.path.join(self.sockdir, f"worker{wid}.fifo")

    def sock_of(self, wid: int) -> str:
        return os.path.join(self.sockdir, f"dos-rpc-worker{wid}.sock")

    def restart_rpc(self, wid: int) -> None:
        """Bring a torn-down accept loop back on the SAME endpoint (the
        in-thread analog of a supervisor respawn)."""
        self.loops[wid].stop(join_s=2.0)
        self.loops[wid] = RpcServeLoop(
            self.servers[wid], socket_path=self.sock_of(wid)).start()

    def stop(self) -> None:
        for wid in range(N_WORKERS):
            stop_server(self.fifo_of(wid), deadline_s=5.0)
        for th in self.threads.values():
            th.join(timeout=15)
        for loop in self.loops.values():
            loop.stop()


@pytest.fixture(scope="module")
def rpc_fleet(rpc_world, tmp_path_factory):
    conf, g, dc, queries = rpc_world
    sockdir = str(tmp_path_factory.mktemp("rpc-socks"))
    old = os.environ.get("DOS_RPC_SOCKET_DIR")
    os.environ["DOS_RPC_SOCKET_DIR"] = sockdir
    fleet = _Fleet(conf, sockdir)
    yield conf, g, dc, queries, fleet
    fleet.stop()
    if old is None:
        os.environ.pop("DOS_RPC_SOCKET_DIR", None)
    else:
        os.environ["DOS_RPC_SOCKET_DIR"] = old


def _frontend(dc, dispatcher, registry=None, hedge_enabled=False,
              **hedge_kw):
    return ServingFrontend(
        dc, dispatcher,
        sconf=ServeConfig(max_batch=8, max_wait_ms=2.0,
                          queue_depth=1024, cache_bytes=0,
                          deadline_ms=60_000.0),
        registry=registry,
        hconf=HedgeConfig(enabled=hedge_enabled, **hedge_kw))


def _run_pool(fe, pool):
    fe.start()
    try:
        futs = [fe.submit(int(s), int(t)) for s, t in pool]
        return [f.result(60) for f in futs]
    finally:
        fe.stop()


# --------------------------------------------------------------- parity

def test_transport_knob_defaults_to_fifo_legacy(monkeypatch):
    """DOS_TRANSPORT unset (or malformed) is the byte-identical legacy
    path: every pre-existing suite runs it, and the knob degrades
    instead of crashing (the utils.env policy)."""
    monkeypatch.delenv("DOS_TRANSPORT", raising=False)
    assert rpc_transport.resolve_transport() == "fifo"
    monkeypatch.setenv("DOS_TRANSPORT", "bogus")
    assert rpc_transport.resolve_transport() == "fifo"
    monkeypatch.setenv("DOS_TRANSPORT", " RPC ")
    assert rpc_transport.resolve_transport() == "rpc"
    monkeypatch.setenv("DOS_TRANSPORT", "auto")
    assert rpc_transport.resolve_transport() == "auto"

def test_rpc_dispatch_matches_engine(rpc_fleet):
    conf, g, dc, queries, fleet = rpc_fleet
    faults.reset()
    mine = queries[dc.worker_of(queries[:, 1]) == 1][:8]
    disp = RpcDispatcher(conf, timeout=60.0)
    try:
        cost, plen, fin = disp.answer_batch(1, mine, RuntimeConfig(),
                                            "-")
        c2, p2, f2, _ = fleet.servers[1].engine.answer(
            mine, RuntimeConfig())
        assert (cost == c2).all() and (plen == p2).all()
        assert (fin == np.asarray(f2)).all()
    finally:
        disp.close()


def test_rpc_paths_segments_match_engine_capture(rpc_fleet):
    conf, g, dc, queries, fleet = rpc_fleet
    faults.reset()
    mine = queries[dc.worker_of(queries[:, 1]) == 0][:6]
    rc = RuntimeConfig(sig_k=4)
    disp = RpcDispatcher(conf, timeout=60.0)
    try:
        cost, plen, fin, nodes, moves = disp.answer_batch_paths(
            0, mine, rc, "-")
        assert nodes is not None and moves is not None
        eng = fleet.servers[0].engine
        with fleet.servers[0].answer_lock:
            c2, p2, f2, _ = eng.answer(mine, rc)
            n2, m2 = eng.last_paths
        assert (cost == c2).all()
        assert (nodes == np.asarray(n2)).all()
        assert (moves == np.asarray(m2)).all()
    finally:
        disp.close()


def test_rpc_frontend_bit_identical_to_fifo_frontend(rpc_fleet,
                                                     monkeypatch):
    """The serving acceptance: the same pool through the FIFO wire and
    the socket wire answers identically (cache off, so every answer is
    a live dispatch)."""
    conf, g, dc, queries, fleet = rpc_fleet
    faults.reset()
    monkeypatch.setattr(dmod, "command_fifo_path", fleet.fifo_of)
    pool = queries[:40]
    fifo_res = _run_pool(_frontend(dc, FifoDispatcher(
        conf, timeout=60.0)), pool)
    rpc_res = _run_pool(_frontend(dc, RpcDispatcher(
        conf, timeout=60.0)), pool)
    assert all(r.ok for r in fifo_res) and all(r.ok for r in rpc_res)
    assert [(r.cost, r.plen, r.finished) for r in rpc_res] == \
        [(r.cost, r.plen, r.finished) for r in fifo_res]


# --------------------------------------------- multiplexing/backpressure

def test_multiplexed_batches_share_one_connection(rpc_fleet):
    conf, g, dc, queries, fleet = rpc_fleet
    faults.reset()
    mine = queries[dc.worker_of(queries[:, 1]) == 1][:8]
    disp = RpcDispatcher(conf, timeout=60.0)
    c0 = _counter("rpc_connects_total")
    try:
        golden = disp.answer_batch(1, mine, RuntimeConfig(), "-")
        outs = {}

        def go(i):
            outs[i] = disp.answer_batch(1, mine, RuntimeConfig(), "-")

        ths = [threading.Thread(target=go, args=(i,)) for i in range(4)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(timeout=60)
        assert all((outs[i][0] == golden[0]).all() for i in range(4))
        st = disp.statusz()
        assert st["mode"] == "rpc"
        assert st["connections"]["1"]["connected"] is True
        assert st["connections"]["1"]["connects"] == 1
        # 4 concurrent batches never opened a second connection
        assert _counter("rpc_connects_total") - c0 == 1
    finally:
        disp.close()


def test_busy_frame_is_explicit_backpressure(rpc_fleet, tmp_path,
                                             monkeypatch):
    """A request past the server's credit window answers an explicit
    BUSY frame — booked on rpc_busy_frames_total, surfaced as RpcBusy —
    instead of queueing into a timeout."""
    conf, g, dc, queries, fleet = rpc_fleet
    faults.reset()
    monkeypatch.setenv("DOS_FAULTS", "delay;wid=1;delay=0.8;times=1")
    sock = str(tmp_path / "busy.sock")
    loop = RpcServeLoop(fleet.servers[1], socket_path=sock,
                        credit=1).start()
    mine = np.ascontiguousarray(
        queries[dc.worker_of(queries[:, 1]) == 1][:4], np.int64)
    ca = rpc_transport.RpcClient(("unix", sock, None), timeout_s=30.0)
    cb = rpc_transport.RpcClient(("unix", sock, None), timeout_s=30.0)
    busy0 = _counter("rpc_busy_frames_total")
    hdr = {"kind": "req",
           "config": {"results": True}, "diff": "-"}
    got = {}

    def slow():
        got["a"] = ca.call(dict(hdr), [mine])

    th = threading.Thread(target=slow)
    try:
        th.start()
        time.sleep(0.25)        # inside worker 1's injected delay
        with pytest.raises(rpc_transport.RpcBusy):
            cb.call(dict(hdr), [mine])
        th.join(timeout=30)
        assert got["a"].header.get("res")
        assert _counter("rpc_busy_frames_total") - busy0 >= 2
    finally:
        th.join(timeout=5)
        ca.close()
        cb.close()
        loop.stop()


# ------------------------------------------------------------ the gates

def test_stale_epoch_gate_over_sockets(rpc_fleet):
    conf, g, dc, queries, fleet = rpc_fleet
    faults.reset()
    mine = queries[dc.worker_of(queries[:, 1]) == 1][:4]
    disp = RpcDispatcher(conf, timeout=30.0)
    s0 = _counter("server_stale_epoch_total")
    try:
        # tolerate-older: epoch 0 (and the worker's own epoch) serves
        disp.answer_batch(1, mine, RuntimeConfig(epoch=0), "-")
        # gate-newer: a NEWER table version refuses with the sentinel
        with pytest.raises(DispatchError, match="STALE_EPOCH"):
            disp.answer_batch(1, mine, RuntimeConfig(epoch=99), "-")
        assert _counter("server_stale_epoch_total") - s0 == 1
    finally:
        disp.close()


def test_stale_diff_gate_over_sockets(rpc_world, tmp_path, monkeypatch):
    conf, g, dc, queries = rpc_world
    faults.reset()
    monkeypatch.setenv("DOS_RPC_SOCKET_DIR", str(tmp_path))
    stream = tmp_path / "stream"
    stream.mkdir()
    srv = FifoServer(conf, 1,
                     command_fifo=str(tmp_path / "w1.fifo"),
                     traffic_dir=str(stream))
    loop = RpcServeLoop(srv).start()
    mine = queries[dc.worker_of(queries[:, 1]) == 1][:4]
    disp = RpcDispatcher(conf, timeout=30.0)
    d0 = _counter("server_stale_diff_total")
    try:
        disp.answer_batch(1, mine, RuntimeConfig(diff_epoch=0), "-")
        with pytest.raises(DispatchError, match="STALE_DIFF"):
            disp.answer_batch(1, mine, RuntimeConfig(diff_epoch=7), "-")
        assert _counter("server_stale_diff_total") - d0 == 1
    finally:
        disp.close()
        loop.stop()


# ------------------------------------------------------------- liveness

def test_rpc_probe_rides_health_vocabulary(rpc_fleet):
    conf, g, dc, queries, fleet = rpc_fleet
    faults.reset()
    st = rpc_transport.probe(1)
    assert st is not None and st.ok and st.wid == 1
    # no listener -> None, never a hang (the fifo probe contract)
    assert rpc_transport.probe(57, timeout=3.0) is None


def test_malformed_config_answers_fail_not_wedge(rpc_fleet):
    conf, g, dc, queries, fleet = rpc_fleet
    faults.reset()
    client = rpc_transport.RpcClient(
        ("unix", fleet.sock_of(1), None), timeout_s=15.0)
    m0 = _counter("rpc_server_frames_malformed_total")
    try:
        fr = client.call({"kind": "req", "config": "CORRUPT {",
                          "diff": "-"},
                         [np.zeros((1, 2), np.int64)])
        assert fr.header["stats"] == "FAIL"
        assert _counter("rpc_server_frames_malformed_total") - m0 == 1
    finally:
        client.close()


# ------------------------------------------------------ the chaos drill

def test_rpc_chaos_drill_degraded_not_wedged(rpc_fleet, monkeypatch):
    """The acceptance drill: kill-mid-batch on worker 0 and drop-reply
    on worker 1 (the existing testing/faults hooks) over sockets. Every
    request still answers OK — failover walks to the replica, breakers
    open and short-circuit, transport errors are typed and retryable —
    and the answers are bit-identical to the fault-free FIFO run over
    the same pool."""
    conf, g, dc, queries, fleet = rpc_fleet
    faults.reset()
    monkeypatch.delenv("DOS_FAULTS", raising=False)
    monkeypatch.setattr(dmod, "command_fifo_path", fleet.fifo_of)
    pool = queries[:40]

    # golden: the fault-free FIFO run (the compat backend, unchanged)
    golden = _run_pool(_frontend(dc, FifoDispatcher(
        conf, timeout=60.0)), pool)
    assert all(r.ok for r in golden)
    gold = [(r.cost, r.plen, r.finished) for r in golden]

    # phase 1: kill-mid-batch tears worker 0's transport mid-batch;
    # the batch fails over to worker 1's replica, the breaker opens
    # after threshold-1 failures and later shard-0 batches skip the
    # corpse without a connect attempt
    faults.reset()
    monkeypatch.setenv("DOS_FAULTS", "kill-mid-batch;wid=0;mode=raise")
    fo0 = _counter("failover_total")
    te0 = _counter("rpc_transport_errors_total")
    op0 = _counter("head_circuit_open_total")
    reg = resilience.BreakerRegistry(threshold=1, cooldown_s=600.0,
                                     enabled=True)
    res1 = _run_pool(_frontend(dc, RpcDispatcher(conf, timeout=10.0),
                               registry=reg), pool)
    reg.shutdown()
    assert all(r.ok for r in res1), [r.detail for r in res1
                                     if not r.ok]
    assert [(r.cost, r.plen, r.finished) for r in res1] == gold
    assert _counter("failover_total") - fo0 >= 1
    assert _counter("rpc_transport_errors_total") - te0 >= 1
    assert _counter("head_circuit_open_total") - op0 >= 1

    # phase 2: worker 0 "respawns" on the same endpoint; worker 1
    # drops one reply — the client times out (typed, retryable), the
    # batch fails over to worker 0's replica, nothing wedges
    faults.reset()
    monkeypatch.setenv("DOS_FAULTS", "drop-reply;wid=1;times=1")
    fleet.restart_rpc(0)
    dr0 = _counter("rpc_server_replies_dropped_total")
    reg2 = resilience.BreakerRegistry(threshold=3, cooldown_s=600.0,
                                      enabled=True)
    res2 = _run_pool(_frontend(dc, RpcDispatcher(conf, timeout=3.0),
                               registry=reg2), pool)
    reg2.shutdown()
    assert all(r.ok for r in res2), [r.detail for r in res2
                                     if not r.ok]
    assert [(r.cost, r.plen, r.finished) for r in res2] == gold
    assert _counter("rpc_server_replies_dropped_total") - dr0 == 1


def test_hedge_over_rpc_wins_against_slow_primary(rpc_fleet,
                                                  monkeypatch):
    """Hedged dispatch over sockets: the duplicate shares the replica's
    persistent connection and beats a delay-faulted primary."""
    conf, g, dc, queries, fleet = rpc_fleet
    faults.reset()
    monkeypatch.setenv("DOS_FAULTS",
                       "delay;wid=0;delay=0.3;times=inf")
    mine = queries[dc.worker_of(queries[:, 1]) == 0][:6]
    hi0 = _counter("hedges_issued_total")
    hw0 = _counter("hedges_won_total")
    fe = _frontend(dc, RpcDispatcher(conf, timeout=30.0),
                   hedge_enabled=True, min_delay_ms=5.0, budget=1.0)
    fe.start()
    try:
        res = [fe.query(int(s), int(t), timeout=60) for s, t in mine]
    finally:
        fe.stop()
        time.sleep(0.5)     # drain delayed loser replies
    assert all(r.ok for r in res)
    assert _counter("hedges_issued_total") - hi0 >= 1
    assert _counter("hedges_won_total") - hw0 >= 1


# ------------------------------------------- fifo hedge satellite + auto

def test_hedged_fifo_dispatch_reuses_primary_query_file(rpc_fleet,
                                                        monkeypatch):
    """The ROADMAP item-3 callout: a hedge duplicate on the FIFO
    backend reuses the primary attempt's already-written query file
    instead of paying a second filesystem round-trip per candidate."""
    conf, g, dc, queries, fleet = rpc_fleet
    faults.reset()
    monkeypatch.setenv("DOS_FAULTS",
                       "delay;wid=0;delay=0.3;times=inf")
    monkeypatch.setattr(dmod, "command_fifo_path", fleet.fifo_of)
    mine = queries[dc.worker_of(queries[:, 1]) == 0][:6]
    r0 = _counter("serve_hedge_qfile_reused_total")
    fe = _frontend(dc, FifoDispatcher(conf, timeout=60.0),
                   hedge_enabled=True, min_delay_ms=5.0, budget=1.0)
    fe.start()
    try:
        res = [fe.query(int(s), int(t), timeout=60) for s, t in mine]
    finally:
        fe.stop()
        time.sleep(0.5)     # drain delayed loser replies
    assert all(r.ok for r in res)
    assert _counter("serve_hedge_qfile_reused_total") - r0 >= 1


def test_sweep_defers_unlink_while_shared_qfile_in_flight(rpc_world,
                                                          tmp_path):
    """The cross-lane race the reuse refcount exists for: the writer
    lane's NEXT dispatch sweeps its previous batch while a hedge on
    another lane still has the shared query file in flight — the
    physical unlink must defer to the last reference's release, never
    tear the in-flight attempt's read. (White-box: the interleaving
    cannot be scheduled reliably over the real wire.)"""
    conf, g, dc, queries = rpc_world
    disp = FifoDispatcher(conf)
    qfile = str(tmp_path / "query.serve.shared")
    open(qfile, "w").write("0\n")
    qkey = (0, 1, 123, "-")
    disp._shared_q[qkey] = [qfile, 1, False, b"x"]   # hedge in flight
    disp._prev[(0, 0)] = (qfile, str(tmp_path / "answer.base"))
    disp._sweep_prev((0, 0))
    assert os.path.exists(qfile), "sweep tore an in-flight shared file"
    assert disp._shared_q[qkey][2] is True           # orphaned
    # the last reference's release unlinks it (the _dispatch finally)
    ent = disp._shared_q.pop(qkey)
    ent[1] -= 1
    assert ent[1] == 0 and ent[2]
    disp._unlink_batch_files(ent[0])
    assert not os.path.exists(qfile)


def test_auto_dispatcher_sticky_fifo_fallback(rpc_world, rpc_fleet,
                                              tmp_path, monkeypatch):
    """DOS_TRANSPORT=auto on a mixed fleet: worker 1 has a listener
    (rpc), worker 0 does not (fifo fallback), and the lane choice is
    sticky + visible in statusz."""
    conf, g, dc, queries, fleet = rpc_fleet
    faults.reset()
    # a socket dir where ONLY worker 1 listens
    monkeypatch.setenv("DOS_RPC_SOCKET_DIR", str(tmp_path))
    monkeypatch.setattr(dmod, "command_fifo_path", fleet.fifo_of)
    loop1 = RpcServeLoop(fleet.servers[1],
                         socket_path=rpc_transport.rpc_socket_path(1)
                         ).start()
    disp = AutoDispatcher(conf, timeout=60.0)
    try:
        rc = RuntimeConfig()
        m0 = queries[dc.worker_of(queries[:, 1]) == 0][:4]
        m1 = queries[dc.worker_of(queries[:, 1]) == 1][:4]
        c0, _, _ = disp.answer_batch(0, m0, rc, "-")
        c1, _, _ = disp.answer_batch(1, m1, rc, "-")
        ce0, _, _, _ = fleet.servers[0].engine.answer(m0, rc)
        ce1, _, _, _ = fleet.servers[1].engine.answer(m1, rc)
        assert (c0 == ce0).all() and (c1 == ce1).all()
        st = disp.statusz()
        assert st["mode"] == "auto"
        assert st["fifo_fallback_lanes"] == [0]
        assert st["connections"]["1"]["connected"] is True
    finally:
        disp.close()
        loop1.stop()


# -------------------------------------------------------- campaign lane

def test_campaign_over_rpc_writes_no_query_files(rpc_world, rpc_fleet,
                                                 tmp_path, monkeypatch):
    """The campaign CLI on DOS_TRANSPORT=rpc: clean exit, parts.csv,
    and ZERO per-batch query files on the shared dir — the hot path
    really stopped touching the filesystem."""
    conf, g, dc, queries, fleet = rpc_fleet
    faults.reset()
    monkeypatch.delenv("DOS_FAULTS", raising=False)
    monkeypatch.setenv("DOS_TRANSPORT", "rpc")
    conf_path = os.path.join(fleet.sockdir, "conf-rpc-campaign.json")
    conf.save(conf_path)
    monkeypatch.setattr(pq, "command_fifo_path", fleet.fifo_of)
    before = set(glob.glob(os.path.join(conf.nfs, "query.*")))
    f0 = _counter("rpc_frames_sent_total")
    outdir = str(tmp_path / "artifacts")
    rc = pq.main(["-c", conf_path, "--backend", "host", "-o", outdir])
    assert rc == pq.EXIT_CLEAN
    assert os.path.exists(os.path.join(outdir, "parts.csv"))
    after = set(glob.glob(os.path.join(conf.nfs, "query.*")))
    assert after <= before, f"rpc campaign wrote query files: " \
        f"{sorted(after - before)}"
    assert _counter("rpc_frames_sent_total") - f0 >= 2 * N_WORKERS


def test_supervisor_spawns_rpc_endpoint(rpc_world, tmp_path,
                                        monkeypatch):
    conf, g, dc, queries = rpc_world
    conf_path = str(tmp_path / "conf.json")
    conf.save(conf_path)
    spawned = {}

    class _FakeProc:
        def poll(self):
            return None

    def fake_popen(cmd, **kw):
        spawned["cmd"] = cmd
        return _FakeProc()

    monkeypatch.setattr(sup_mod.subprocess, "Popen", fake_popen)
    rpc_dir = str(tmp_path / "socks")
    sup = sup_mod.WorkerSupervisor(conf, conf_path,
                                   fifo_dir=str(tmp_path),
                                   rpc_dir=rpc_dir)
    sup._spawn_server(sup.workers[0])
    assert "--rpc-socket" in spawned["cmd"]
    idx = spawned["cmd"].index("--rpc-socket")
    assert spawned["cmd"][idx + 1] == os.path.join(
        rpc_dir, "dos-rpc-worker0.sock")
    # default fleet (DOS_TRANSPORT unset, no rpc_dir): no endpoint flag
    monkeypatch.delenv("DOS_TRANSPORT", raising=False)
    sup2 = sup_mod.WorkerSupervisor(conf, conf_path,
                                    fifo_dir=str(tmp_path))
    sup2._spawn_server(sup2.workers[0])
    assert "--rpc-socket" not in spawned["cmd"]


# ------------------------------------------------------- obs satellites

def test_statusz_transport_sections(rpc_fleet):
    conf, g, dc, queries, fleet = rpc_fleet
    faults.reset()
    wstat = fleet.servers[1].statusz()
    assert wstat["transport"]["credit"] >= 1
    assert "connections" in wstat["transport"]
    disp = RpcDispatcher(conf, timeout=30.0)
    fe = _frontend(dc, disp)
    fe.start()
    try:
        mine = queries[dc.worker_of(queries[:, 1]) == 1][:2]
        assert fe.query(int(mine[0][0]), int(mine[0][1]),
                        timeout=30).ok
        tstat = fe.statusz()["transport"]
        assert tstat["mode"] == "rpc"
        assert tstat["connections"]["1"]["connected"] is True
    finally:
        fe.stop()


def test_top_renders_transport_blank_tolerantly():
    from distributed_oracle_search_tpu.obs import fleet as obs_fleet

    # worker-style section
    row = obs_fleet._summarize(
        {"worker": {"transport": {"connections": 2, "inflight": 1,
                                  "credit": 8}}})
    assert (row["conns"], row["inflight"], row["credit"]) == (2, 1, 8)
    # head-style per-worker connection table
    row = obs_fleet._summarize(
        {"serving": {"transport": {
            "mode": "rpc",
            "connections": {"0": {"inflight": 3}, "1": {"inflight": 1}},
        }}})
    assert (row["conns"], row["inflight"]) == (2, 4)
    # pre-RPC endpoints: no section (or garbage) -> blanks, no crash
    assert "conns" not in obs_fleet._summarize({"worker": {"batches": 1}})
    assert "conns" not in obs_fleet._summarize(
        {"worker": {"transport": "garbage"}})
    table = obs_fleet.render_top({
        "new": {"worker": {"transport": {"connections": 1,
                                         "inflight": 0, "credit": 8}}},
        "old": {"worker": {"batches": 3}},
    })
    lines = table.splitlines()
    assert "conns" in lines[0]
    assert "-" in lines[-1] or "-" in lines[-2]


def test_bench_diff_directions_cover_transport_family():
    from distributed_oracle_search_tpu.obs import fleet as obs_fleet

    for key in ("serve_rpc_vs_fifo_dispatch_ratio",
                "serve_rpc_queries_per_sec",
                "serve_fifo_queries_per_sec"):
        assert obs_fleet._KEY_DIRECTIONS[key] == "higher", key
    for key in ("serve_rpc_dispatch_ms", "serve_fifo_dispatch_ms",
                "serve_rpc_p99_ms", "serve_fifo_p99_ms"):
        assert obs_fleet._KEY_DIRECTIONS[key] == "lower", key
    assert obs_fleet._KEY_TOLERANCES[
        "serve_rpc_vs_fifo_dispatch_ratio"] == 0.5


def test_rpc_metrics_registered_in_obs_map():
    import distributed_oracle_search_tpu.obs as obs

    for name in ("rpc_frames_sent_total", "rpc_frames_received_total",
                 "rpc_frames_torn_total", "rpc_connects_total",
                 "rpc_reconnects_total", "rpc_transport_errors_total",
                 "rpc_busy_frames_total", "rpc_heartbeats_total",
                 "rpc_dispatch_seconds", "rpc_server_connections",
                 "rpc_server_batches_total",
                 "rpc_server_replies_dropped_total",
                 "rpc_server_frames_malformed_total",
                 "serve_hedge_qfile_reused_total"):
        assert name in obs.__doc__, name
