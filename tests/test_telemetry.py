"""Fleet telemetry bus, SLO burn rates, and the flight recorder.

The acceptance drill at the bottom runs a served workload over BOTH
transports (FIFO sidecar lane + RPC ``telemetry`` frames) into one
head-side store, checks the fleet-merged latency window against the
worker's own snapshot, trips the fast-burn SLO with an injected delay
fault (and clears it by hysteresis), and replays the tape to the
incident's event sequence: fault fired -> burn alert -> breaker open.

Everything above it is the unit ladder: tick codec compat (unknown
keys pass, only NEWER versions refuse), delta encoding with full-tick
resync, counter-reset clamping across a worker respawn (no negative
rates, ever), the byte-budgeted timeseries rings, burn-rate math with
hysteresis, and the bounded on-disk ring with torn-tail-vs-corrupt
replay semantics."""

import json
import os
import threading
import time

import numpy as np
import pytest

import distributed_oracle_search_tpu.serving.dispatch as dmod
from distributed_oracle_search_tpu.data import (
    ensure_synth_dataset, read_scen,
)
from distributed_oracle_search_tpu.data.graph import Graph
from distributed_oracle_search_tpu.models.cpd import (
    build_worker_shard, write_index_manifest,
)
from distributed_oracle_search_tpu.obs import metrics as obs_metrics
from distributed_oracle_search_tpu.obs import quantiles as obs_quantiles
from distributed_oracle_search_tpu.obs import recorder as obs_recorder
from distributed_oracle_search_tpu.obs import slo as slo_mod
from distributed_oracle_search_tpu.obs import telemetry
from distributed_oracle_search_tpu.obs import timeseries as tts
from distributed_oracle_search_tpu.parallel.partition import (
    DistributionController,
)
from distributed_oracle_search_tpu.serving import (
    FifoDispatcher, HedgeConfig, RpcDispatcher, ServeConfig,
    ServingFrontend,
)
from distributed_oracle_search_tpu.testing import faults
from distributed_oracle_search_tpu.transport import resilience
from distributed_oracle_search_tpu.transport import rpc as rpc_transport
from distributed_oracle_search_tpu.transport.wire import RuntimeConfig
from distributed_oracle_search_tpu.utils.config import ClusterConfig
from distributed_oracle_search_tpu.worker import FifoServer, stop_server
from distributed_oracle_search_tpu.worker.server import RpcServeLoop

pytestmark = pytest.mark.telemetry


def _counter(name: str) -> float:
    return obs_metrics.REGISTRY.snapshot()["counters"].get(name, 0)


class _Clock:
    """Deterministic injectable clock."""

    def __init__(self, t0: float = 1000.0):
        self.t = float(t0)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += dt
        return self.t


# -------------------------------------------------------------- fixtures

@pytest.fixture(scope="module")
def tele_world(tmp_path_factory):
    """One-worker world: the telemetry drill needs a live fleet, not a
    big one."""
    datadir = str(tmp_path_factory.mktemp("tele-world"))
    paths = ensure_synth_dataset(datadir, width=10, height=8,
                                 n_queries=64, seed=31)
    conf = ClusterConfig(
        workers=["localhost"], partmethod="mod", partkey=1,
        outdir=os.path.join(datadir, "index"),
        xy_file=paths["xy"], scenfile=paths["scen"], nfs=datadir,
    ).validate()
    g = Graph.from_xy(conf.xy_file)
    dc = DistributionController("mod", 1, 1, g.n)
    build_worker_shard(g, dc, 0, conf.outdir)
    write_index_manifest(conf.outdir, dc)
    return conf, g, dc, read_scen(conf.scenfile)


class _Fleet:
    """One worker serving both transports (the test_rpc pattern)."""

    def __init__(self, conf, sockdir):
        self.conf = conf
        self.sockdir = sockdir
        self.server = FifoServer(conf, 0, command_fifo=self.fifo_of(0))
        self.thread = threading.Thread(target=self.server.serve_forever,
                                       daemon=True)
        self.thread.start()
        self.loop = RpcServeLoop(
            self.server, socket_path=self.sock_of(0)).start()
        for _ in range(200):
            if os.path.exists(self.fifo_of(0)):
                break
            time.sleep(0.02)

    def fifo_of(self, wid: int) -> str:
        return os.path.join(self.sockdir, f"worker{wid}.fifo")

    def sock_of(self, wid: int) -> str:
        return os.path.join(self.sockdir, f"dos-rpc-worker{wid}.sock")

    def stop(self) -> None:
        stop_server(self.fifo_of(0), deadline_s=5.0)
        self.thread.join(timeout=15)
        self.loop.stop()


@pytest.fixture(scope="module")
def tele_fleet(tele_world, tmp_path_factory):
    conf, g, dc, queries = tele_world
    sockdir = str(tmp_path_factory.mktemp("tele-socks"))
    old = os.environ.get("DOS_RPC_SOCKET_DIR")
    os.environ["DOS_RPC_SOCKET_DIR"] = sockdir
    fleet = _Fleet(conf, sockdir)
    yield conf, g, dc, queries, fleet
    fleet.stop()
    if old is None:
        os.environ.pop("DOS_RPC_SOCKET_DIR", None)
    else:
        os.environ["DOS_RPC_SOCKET_DIR"] = old


def _frontend(dc, dispatcher):
    return ServingFrontend(
        dc, dispatcher,
        sconf=ServeConfig(max_batch=8, max_wait_ms=2.0,
                          queue_depth=1024, cache_bytes=0,
                          deadline_ms=60_000.0),
        hconf=HedgeConfig(enabled=False))


def _run_pool(fe, pool):
    fe.start()
    try:
        futs = [fe.submit(int(s), int(t)) for s, t in pool]
        return [f.result(60) for f in futs]
    finally:
        fe.stop()


# ------------------------------------------------------------ tick codec

def test_tick_codec_tolerates_unknown_keys_and_old_versions():
    tick = {"v": 1, "source": "w0", "seq": 3, "ts": 12.0,
            "counters": {"serve_requests_total": 7},
            "some_future_key": {"nested": True}}
    out = telemetry.decode_tick(telemetry.encode_tick(tick))
    assert out["some_future_key"] == {"nested": True}
    assert out["counters"]["serve_requests_total"] == 7
    # a tick with no version (or garbage) decodes — annotation, not gate
    assert telemetry.decode_tick({"source": "w0"})["source"] == "w0"
    assert telemetry.decode_tick({"v": "x", "source": "w0"})
    assert telemetry.decode_tick({"v": True, "source": "w0"})


def test_tick_codec_refuses_newer_schema_only():
    with pytest.raises(telemetry.TelemetrySchemaError, match="newer"):
        telemetry.decode_tick(
            {"v": telemetry.TELEMETRY_SCHEMA_VERSION + 1})
    with pytest.raises(ValueError):
        telemetry.decode_tick(b"not json {")
    with pytest.raises(ValueError):
        telemetry.decode_tick([1, 2, 3])


def test_sidecar_torn_tail_skipped_midfile_raises(tmp_path):
    path = str(tmp_path / "w0.fifo") + telemetry.SIDECAR_SUFFIX
    assert telemetry.read_sidecar(path) == []     # missing: no ticks
    ticks = [{"v": 1, "source": "w0", "seq": i, "ts": float(i)}
             for i in range(3)]
    telemetry.write_sidecar(path, ticks)
    assert [t["seq"] for t in telemetry.read_sidecar(path)] == [0, 1, 2]
    # a torn TAIL line (reader racing a non-atomic copy) is skipped
    with open(path, "ab") as f:
        f.write(b'{"v": 1, "seq": 3, "trunc')
    assert [t["seq"] for t in telemetry.read_sidecar(path)] == [0, 1, 2]
    # garbage MID-file is corruption and must raise
    lines = [telemetry.encode_tick(ticks[0]), b"garbage {",
             telemetry.encode_tick(ticks[1])]
    with open(path, "wb") as f:
        f.write(b"\n".join(lines) + b"\n")
    with pytest.raises(ValueError, match="mid-file"):
        telemetry.read_sidecar(path)
    # a NEWER tick raises wherever it sits — even at the tail
    telemetry.write_sidecar(path, ticks + [{"v": 99, "source": "w0"}])
    with pytest.raises(telemetry.TelemetrySchemaError, match="newer"):
        telemetry.read_sidecar(path)


# ------------------------------------------------------------- publisher

def test_publisher_delta_encoding_and_full_resync():
    clock = _Clock()
    reg = obs_metrics.MetricsRegistry()
    c = reg.counter("serve_requests_total")
    win = obs_quantiles.QuantileWindows()
    got = []
    pub = telemetry.TelemetryPublisher(
        "wX", sinks=[got.append], interval=0.0, registry=reg,
        windows=win, full_every=3, clock=clock)
    obs_recorder.drain_pending()
    t0 = pub.tick_once()
    assert t0["full"] and t0["seq"] == 0
    assert t0["counters"].get("serve_requests_total") == 0.0
    assert t0["v"] == telemetry.TELEMETRY_SCHEMA_VERSION
    c.inc(5)
    win.observe("serve_request_seconds", 0.25)
    obs_recorder.emit("drill_probe", x=1)
    t1 = pub.tick_once()
    assert not t1["full"]
    assert t1["counters"] == {"serve_requests_total": 5.0}
    assert t1["windows"]["serve_request_seconds"]["count"] == 1
    assert any(e["kind"] == "drill_probe" for e in t1["events"])
    t2 = pub.tick_once()    # nothing changed: delta tick is empty
    assert t2["counters"] == {} and t2["events"] == []
    t3 = pub.tick_once()    # seq 3 % full_every == 0: full resync
    assert t3["full"]
    assert t3["counters"].get("serve_requests_total") == 5.0
    assert got == [t0, t1, t2, t3]
    # a raising sink loses its lane only; publishing keeps going
    def bad_sink(tick):
        raise RuntimeError("lane down")
    errs0 = _counter("telemetry_publish_errors_total")
    pub.add_sink(bad_sink)
    t4 = pub.tick_once()
    assert got[-1] is t4
    assert _counter("telemetry_publish_errors_total") == errs0 + 1


def test_ingest_counter_deltas_survive_worker_respawn():
    """Satellite: a respawned worker restarts its counters at zero —
    the head must clamp the reset (book from zero), never a negative
    rate."""
    clock = _Clock()
    store = tts.TimeseriesStore(bucket_s=5.0, clock=clock)
    ing = telemetry.TelemetryIngest(store, clock=clock)
    resets0 = _counter("telemetry_counter_resets_total")

    reg1 = obs_metrics.MetricsRegistry()
    c1 = reg1.counter("serve_requests_total")
    c1.inc(10)
    pub1 = telemetry.TelemetryPublisher(
        "w7", registry=reg1, windows=obs_quantiles.QuantileWindows(),
        full_every=1, clock=clock)
    assert ing.ingest(pub1.tick_once())
    clock.advance(5.0)
    c1.inc(15)
    assert ing.ingest(pub1.tick_once())

    # incarnation stamps have millisecond resolution: keep them apart
    time.sleep(0.005)
    reg2 = obs_metrics.MetricsRegistry()
    c2 = reg2.counter("serve_requests_total")
    c2.inc(4)
    pub2 = telemetry.TelemetryPublisher(
        "w7", registry=reg2, windows=obs_quantiles.QuantileWindows(),
        full_every=1, clock=clock)
    assert pub2.incarnation != pub1.incarnation
    clock.advance(5.0)
    # seq restarts at 0 too — the new incarnation must not be deduped
    assert ing.ingest(pub2.tick_once())

    pts = store.query("serve_requests_total", worker="w7")["w7"]
    vals = [v for _, v in pts]
    assert all(v >= 0 for v in vals), vals
    assert sum(vals) == pytest.approx(10 + 15 + 4)
    assert store.rate("serve_requests_total", 60.0,
                      now=clock()) >= 0.0
    assert _counter("telemetry_counter_resets_total") > resets0
    assert ing.statusz()["sources"]["w7"]["incarnation"] \
        == pub2.incarnation


def test_ingest_dedupes_replayed_ticks_and_drops_garbage():
    clock = _Clock()
    store = tts.TimeseriesStore(bucket_s=5.0, clock=clock)
    ing = telemetry.TelemetryIngest(store, clock=clock)
    tick = {"v": 1, "source": "w3", "incarnation": "abc", "seq": 0,
            "ts": clock(), "counters": {"serve_requests_total": 2}}
    raw = telemetry.encode_tick(tick)
    dropped0 = _counter("telemetry_ticks_dropped_total")
    assert ing.ingest(raw)
    assert not ing.ingest(raw)          # sidecar re-read: silent drop
    assert not ing.ingest(b"nope {")    # malformed: drop, don't raise
    assert not ing.ingest(telemetry.encode_tick(
        {"v": 1, "seq": 1, "ts": clock()}))   # no source
    assert _counter("telemetry_ticks_dropped_total") == dropped0 + 3
    pts = store.query("serve_requests_total", worker="w3")["w3"]
    assert sum(v for _, v in pts) == pytest.approx(2.0)


# ------------------------------------------------------------- the store

def test_store_buckets_merge_and_rates():
    clock = _Clock(t0=100.0)
    store = tts.TimeseriesStore(bucket_s=5.0, clock=clock)
    for ts in (100.0, 101.0, 104.0):     # one bucket
        store.append("w0", "serve_requests_total", ts, 1.0,
                     kind="delta")
    store.append("w0", "serve_requests_total", 107.0, 1.0, kind="delta")
    pts = store.query("serve_requests_total", worker="w0")["w0"]
    assert pts == [(100.0, 3.0), (105.0, 1.0)]
    # gauges overwrite within a bucket instead of summing
    store.append("w0", "queue_depth", 100.0, 5.0, kind="gauge")
    store.append("w0", "queue_depth", 104.0, 2.0, kind="gauge")
    assert store.query("queue_depth", worker="w0")["w0"] == [(100.0, 2.0)]
    clock.t = 110.0
    assert store.rate("serve_requests_total", 20.0,
                      now=clock()) == pytest.approx(4.0 / 20.0)


def test_store_byte_budget_evicts_oldest_series():
    probe = tts.SeriesRing(16)
    store = tts.TimeseriesStore(max_bytes=3 * probe.nbytes + 1,
                                capacity=16, bucket_s=5.0)
    evicted0 = _counter("telemetry_series_evicted_total")
    for i in range(8):
        store.append(f"w{i}", "serve_requests_total", float(i), 1.0,
                     kind="delta")
    st = store.statusz()
    assert st["series"] <= 3
    assert st["bytes"] <= st["max_bytes"]
    assert _counter("telemetry_series_evicted_total") >= evicted0 + 5
    # the most recently written series survive
    assert "w7" in store.query("serve_requests_total")


def test_store_fleet_window_merges_worst_case_and_ages_out():
    clock = _Clock(t0=500.0)
    store = tts.TimeseriesStore(bucket_s=5.0, clock=clock)
    snap = {"window_s": 60.0, "count": 10,
            "quantiles": {"p50": 0.01, "p95": 0.1, "p99": 0.2}}
    store.put_window("w0", "serve_request_seconds", 500.0, snap)
    store.put_window("w1", "serve_request_seconds", 501.0,
                     {"window_s": 60.0, "count": 5,
                      "quantiles": {"p50": 0.02, "p95": 0.3,
                                    "p99": 0.5}})
    store.put_window("w2", "serve_request_seconds", 501.0,
                     {"window_s": 60.0, "count": 0, "quantiles": {}})
    fw = store.fleet_window("serve_request_seconds", now=clock())
    assert fw["count"] == 15 and fw["workers"] == 2
    # conservative merge: every quantile takes the fleet-worst value
    assert fw["quantiles"] == {"p50": 0.02, "p95": 0.3, "p99": 0.5}
    # p99/count trend series rode along
    assert store.query("serve_request_seconds:p99", worker="w1")
    # stale snapshots age out of the merged view entirely
    assert store.fleet_window("serve_request_seconds", max_age_s=30.0,
                              now=600.0) is None


# ------------------------------------------------------------ burn rates

def test_bad_fraction_quantile_ladder():
    snap = {"quantiles": {"p50": 0.01, "p95": 0.1, "p99": 0.4}}
    f = slo_mod._bad_fraction_from_window
    assert f(snap, 0.5) == 0.0       # above p99: within a 99% budget
    assert f(snap, 0.2) == 0.01      # between p95 and p99
    assert f(snap, 0.05) == 0.05     # between p50 and p95
    assert f(snap, 0.001) == 0.75    # below p50: most of the window
    assert f({"quantiles": {}}, 0.1) == 0.0


def test_slo_engine_trips_and_clears_with_hysteresis():
    clock = _Clock(t0=10_000.0)
    store = tts.TimeseriesStore(bucket_s=5.0, clock=clock)
    spec = slo_mod.SLOSpec(name="drill_avail", kind="availability",
                           objective=0.999)
    eng = slo_mod.SLOEngine(store, specs=[spec], fast_s=60.0,
                            slow_s=120.0, fast_threshold=10.0,
                            slow_threshold=5.0, clear_frac=0.5,
                            clock=clock)
    alerts0 = _counter("slo_alerts_total")
    # no data at all: burn is None, nothing trips
    out = eng.evaluate()
    assert out["drill_avail"]["fast_burn"] is None
    assert not out["drill_avail"]["alerting"]
    # 30% shed rate against a 0.1% budget: burn 300 >> threshold 10
    for dt in range(0, 30, 5):
        ts = clock() + dt - 30.0
        store.append("w0", "serve_requests_total", ts, 10.0,
                     kind="delta")
        store.append("w0", "serve_shed_busy_total", ts, 3.0,
                     kind="delta")
    obs_recorder.drain_pending()
    out = eng.evaluate()
    assert out["drill_avail"]["alerting"]
    assert out["drill_avail"]["fast_burn"] == pytest.approx(300.0)
    assert _counter("slo_alerts_total") == alerts0 + 1
    assert eng.alerting() == ["drill_avail"]
    gauges = obs_metrics.REGISTRY.snapshot()["gauges"]
    assert gauges["slo_alerting_drill_avail"] == 1.0
    assert gauges["slo_fast_burn_drill_avail"] == pytest.approx(300.0)
    # still above threshold/2 -> hysteresis holds the alert
    evs = [e["kind"] for e in obs_recorder.drain_pending()]
    assert "slo_alert" in evs
    out = eng.evaluate()
    assert out["drill_avail"]["alerting"]
    # a clean stretch clears it (burn falls to 0 <= threshold/2)
    clock.advance(120.0)
    for dt in range(0, 60, 5):
        store.append("w0", "serve_requests_total",
                     clock() - 60.0 + dt, 10.0, kind="delta")
    out = eng.evaluate()
    assert not out["drill_avail"]["alerting"]
    assert eng.alerting() == []
    gauges = obs_metrics.REGISTRY.snapshot()["gauges"]
    assert gauges["slo_alerting_drill_avail"] == 0.0
    assert any(e["kind"] == "slo_clear"
               for e in obs_recorder.drain_pending())
    # statusz mirrors the last evaluation
    st = eng.statusz()
    assert st["alerting"] == []
    assert st["burn"]["drill_avail"]["fast"] == pytest.approx(0.0)


def test_slo_specs_parse_tolerantly(tmp_path, monkeypatch):
    doc = [
        {"name": "lat", "kind": "latency", "objective": 0.99,
         "threshold_s": 0.25, "future_key": True},
        {"kind": "availability"},                   # nameless: skipped
        "garbage",                                  # wrong type: skipped
        {"name": "avail", "bad": ["serve_errors_total"]},
    ]
    specs = slo_mod.parse_specs(doc)
    assert [s.name for s in specs] == ["lat", "avail"]
    assert specs[0].threshold_s == 0.25
    assert specs[1].bad == ("serve_errors_total",)
    with pytest.raises(ValueError):
        slo_mod.parse_specs({"not": "a list"})
    # the env knob degrades to defaults on an unreadable file
    monkeypatch.setenv("DOS_SLO_SPECS", str(tmp_path / "missing.json"))
    assert [s.name for s in slo_mod.load_specs()] \
        == [s.name for s in slo_mod.default_specs()]
    path = tmp_path / "specs.json"
    path.write_text(json.dumps(doc))
    monkeypatch.setenv("DOS_SLO_SPECS", str(path))
    assert [s.name for s in slo_mod.load_specs()] == ["lat", "avail"]


# -------------------------------------------------------------- the tape

def test_recorder_ring_rotates_evicts_and_replays(tmp_path):
    clock = _Clock(t0=50.0)
    d = str(tmp_path / "tape")
    rec = obs_recorder.FlightRecorder(d, max_bytes=4096,
                                      segment_bytes=512, flush_every=4,
                                      clock=clock)
    pad = "x" * 64
    for i in range(120):
        rec.record_event({"ts": 50.0 + i, "kind": "beat", "i": i,
                          "pad": pad})
    rec.close()
    segs = obs_recorder.segment_paths(d)
    assert len(segs) > 1                     # rotated
    assert sum(os.path.getsize(p) for p in segs) <= 4096 + 512
    records = obs_recorder.replay(d)
    ts = [r["ts"] for r in records]
    assert ts == sorted(ts)
    assert records[0]["i"] > 0               # oldest segments evicted
    assert records[-1]["i"] == 119
    # ticks drop their window payloads on the tape
    rec2 = obs_recorder.FlightRecorder(d, max_bytes=4096,
                                       segment_bytes=512, flush_every=1)
    rec2.record_tick({"v": 1, "source": "w0", "seq": 7, "ts": 500.0,
                      "counters": {"a": 1},
                      "windows": {"serve_request_seconds": {}}})
    rec2.close()
    tick = [r for r in obs_recorder.replay(d) if r.get("rec") == "tick"]
    assert tick and "windows" not in tick[0]
    assert tick[0]["seq"] == 7


def test_recorder_replay_torn_tail_vs_corruption(tmp_path):
    d = str(tmp_path / "tape")
    rec = obs_recorder.FlightRecorder(d, flush_every=1)
    rec.record_event({"ts": 1.0, "kind": "a"})
    rec.record_event({"ts": 2.0, "kind": "b"})
    rec.close()
    seg = obs_recorder.segment_paths(d)[-1]
    torn0 = _counter("recorder_torn_lines_total")
    with open(seg, "ab") as f:
        f.write(b'{"ts": 3.0, "kind": "tor')       # crash mid-flush
    assert [r["kind"] for r in obs_recorder.replay(d)] == ["a", "b"]
    assert _counter("recorder_torn_lines_total") == torn0 + 1
    # the same garbage MID-segment is corruption and must raise
    with open(seg, "rb") as f:
        lines = f.read().splitlines()
    lines.insert(1, b"garbage {")
    with open(seg, "wb") as f:
        f.write(b"\n".join(lines) + b"\n")
    with pytest.raises(ValueError, match="mid-segment"):
        obs_recorder.replay(d)


def test_render_timeline_relative_timestamps(tmp_path):
    assert obs_recorder.render_timeline([]) == "(empty tape)"
    out = obs_recorder.render_timeline([
        {"rec": "event", "ts": 100.0, "kind": "fault", "wid": 0},
        {"rec": "event", "ts": 101.5, "kind": "slo_alert",
         "slo": "lat", "burn": 75.0},
    ])
    l0, l1 = out.splitlines()
    assert l0.startswith("+    0.000s") and "fault" in l0
    assert l1.startswith("+    1.500s") and "slo_alert" in l1
    assert "slo=lat" in l1 and "burn=75.0" in l1


def test_event_bus_bounded_and_drained():
    obs_recorder.drain_pending()
    for i in range(obs_recorder._PENDING_MAX + 10):
        obs_recorder.emit("spam", i=i)
    evs = obs_recorder.drain_pending()
    assert len(evs) == obs_recorder._PENDING_MAX    # bounded ring
    assert evs[-1]["i"] == obs_recorder._PENDING_MAX + 9
    assert obs_recorder.drain_pending() == []


# ----------------------------------------------------- acceptance drill

def test_e2e_fleet_telemetry_drill(tele_fleet, tmp_path, monkeypatch,
                                   capsys):
    """The ISSUE's pinned drill: a served workload over BOTH transports
    streams telemetry into one head store; the fleet-merged window
    matches the worker's own; a delay fault trips the fast-burn SLO
    (hysteresis clears it); the tape replays the incident in order."""
    from distributed_oracle_search_tpu.cli import obs as obs_cli

    conf, g, dc, queries, fleet = tele_fleet
    faults.reset()
    obs_recorder.drain_pending()
    tape = str(tmp_path / "tape")
    store = tts.TimeseriesStore(bucket_s=1.0)
    rec = obs_recorder.FlightRecorder(tape, flush_every=1)
    ingest = telemetry.TelemetryIngest(store, recorder=rec)
    sidecar = fleet.fifo_of(0) + telemetry.SIDECAR_SUFFIX
    pub = telemetry.TelemetryPublisher(
        "w0", sinks=[telemetry.sidecar_sink(sidecar)], interval=0.05,
        full_every=4)
    poller = telemetry.SidecarPoller(fleet.sockdir, ingest,
                                     interval=0.05)
    disp = None
    breakers = None
    try:
        # ---- act 1: the workload, with the delay fault armed
        monkeypatch.setenv("DOS_FAULTS",
                           "delay;wid=0;delay=0.05;times=2")
        monkeypatch.setattr(dmod, "command_fifo_path", fleet.fifo_of)
        res = _run_pool(
            _frontend(dc, FifoDispatcher(conf, timeout=60.0)),
            queries[:16])
        assert all(r.ok for r in res)
        disp = RpcDispatcher(conf, timeout=60.0)
        cost, plen, fin = disp.answer_batch(0, queries[:8],
                                            RuntimeConfig(), "-")
        assert cost.shape == (8,)

        # ---- act 2, lane A: the FIFO sidecar carries ticks
        t1 = pub.tick_once()
        assert any(e["kind"] == "fault" for e in t1["events"]), \
            "the armed delay fault must land on the event bus"
        assert poller.poll_once() >= 1
        assert "w0" in ingest.statusz()["sources"]

        # ---- act 2, lane B: `telemetry` frames on the live RPC conn
        rpc_transport.set_telemetry_sink(ingest.ingest)
        pub.add_sink(fleet.loop.broadcast)
        seq0 = ingest.statusz()["sources"]["w0"]["seq"]
        t2 = pub.tick_once()
        deadline = time.time() + 5.0
        while time.time() < deadline and \
                ingest.statusz()["sources"]["w0"]["seq"] <= seq0:
            time.sleep(0.02)
        assert ingest.statusz()["sources"]["w0"]["seq"] > seq0, \
            "the RPC push lane never delivered the tick"

        # ---- agreement: fleet-merged window == the worker's own snap
        snap = t2["windows"].get("serve_request_seconds") \
            or t1["windows"].get("serve_request_seconds")
        assert snap, "serving must populate the latency window"
        fw = store.fleet_window("serve_request_seconds")
        assert fw is not None
        assert fw["quantiles"]["p99"] == pytest.approx(
            snap["quantiles"]["p99"])
        assert fw["count"] == snap["count"]
        booked = sum(v for _, v in store.query(
            "serve_requests_total", worker="w0").get("w0", []))
        assert booked == pytest.approx(_counter("serve_requests_total"))

        # ---- act 3: the fast-burn SLO trips on the slow window
        eng = slo_mod.SLOEngine(
            store,
            specs=[slo_mod.SLOSpec(
                name="drill_latency", kind="latency", objective=0.99,
                window="serve_request_seconds", threshold_s=0.0)],
            fast_s=60.0, slow_s=120.0, fast_threshold=14.4)
        out = eng.evaluate()
        assert out["drill_latency"]["alerting"]
        gauges = obs_metrics.REGISTRY.snapshot()["gauges"]
        assert gauges["slo_alerting_drill_latency"] == 1.0
        assert gauges["slo_fast_burn_drill_latency"] >= 14.4

        # ---- act 4: the breaker opens (the incident's third beat)
        breakers = resilience.BreakerRegistry(threshold=1,
                                              cooldown_s=600.0,
                                              enabled=True)
        breakers.record(("localhost", 0), False)
        assert not breakers.available(("localhost", 0))
        # drain alert + breaker events onto the tape via a tick; the
        # RPC broadcast lane may beat the direct ingest to it (seq
        # dedupe makes the loser a no-op) — wait for either to land
        tick3 = pub.tick_once()
        ingest.ingest(tick3)
        deadline = time.time() + 5.0
        while time.time() < deadline and \
                ingest.statusz()["sources"]["w0"]["seq"] \
                < tick3["seq"]:
            time.sleep(0.02)
        assert ingest.statusz()["sources"]["w0"]["seq"] >= tick3["seq"]

        # ---- hysteresis: an aged-out window clears the alert
        out = eng.evaluate(now=time.time() + 3600.0)
        assert not out["drill_latency"]["alerting"]
        assert obs_metrics.REGISTRY.snapshot()["gauges"][
            "slo_alerting_drill_latency"] == 0.0
        rec.flush()

        # ---- act 5: the tape replays the incident in order
        records = obs_recorder.replay(tape)
        kinds = [r.get("kind") for r in records
                 if r.get("rec") == "event"]
        assert kinds.index("fault") < kinds.index("slo_alert") \
            < kinds.index("breaker_open")
        assert obs_cli.main(["record", "--dir", tape]) == 0
        summary = capsys.readouterr().out
        assert "segment(s)" in summary and "event(s)" in summary
        assert obs_cli.main(["replay", "--dir", tape,
                             "--events-only"]) == 0
        out_text = capsys.readouterr().out
        assert " tick " not in out_text
        i_fault = out_text.find("fault")
        i_alert = out_text.find("slo_alert")
        i_open = out_text.find("breaker_open")
        assert 0 <= i_fault < i_alert < i_open, out_text
    finally:
        rpc_transport.set_telemetry_sink(None)
        if disp is not None:
            disp.close()
        if breakers is not None:
            breakers.shutdown()
        pub.stop()
        poller.stop()
        rec.close()
        faults.reset()
        obs_recorder.drain_pending()


# ------------------------------------------------------------ satellites

def test_rpc_heartbeat_feeds_quantile_window(tele_fleet, monkeypatch):
    """Heartbeat RTTs land in the fleet + per-worker sliding windows
    (the SLO engine's liveness signal)."""
    conf, g, dc, queries, fleet = tele_fleet
    monkeypatch.setenv("DOS_RPC_HEARTBEAT_S", "0.05")
    client = rpc_transport.RpcClient(
        rpc_transport.endpoint_for(0), wid=0)
    try:
        client.probe(timeout=10.0)
        deadline = time.time() + 5.0
        while time.time() < deadline:
            snap = obs_quantiles.WINDOWS.snapshot()
            if snap.get("rpc_heartbeat_seconds", {}).get("count") and \
                    snap.get("rpc_heartbeat_seconds_w0", {}).get("count"):
                break
            time.sleep(0.02)
    finally:
        client.close()
    snap = obs_quantiles.WINDOWS.snapshot()
    assert snap["rpc_heartbeat_seconds"]["count"] >= 1
    assert snap["rpc_heartbeat_seconds_w0"]["count"] >= 1
    assert snap["rpc_heartbeat_seconds"]["quantiles"]["p99"] > 0


def test_lane_split_engine_still_captures_device_costs(
        monkeypatch, toy_graph, tmp_path):
    """Satellite: meshed workers lower the ACTUAL lane-split shard_map
    program for the roofline gauges (they used to go dark under
    DOS_MESH_DEVICES > 1)."""
    from distributed_oracle_search_tpu.obs import device as obs_device
    from distributed_oracle_search_tpu.worker.engine import ShardEngine

    d = str(tmp_path / "shard")
    dc = DistributionController("tpu", None, 1, toy_graph.n)
    build_worker_shard(toy_graph, dc, 0, d, chunk=16)
    monkeypatch.setenv("DOS_MESH_DEVICES", "2")
    obs_device.reset()
    eng = ShardEngine(toy_graph, dc, 0, d)
    assert eng.n_lanes == 2
    queries = np.array([[0, 5], [3, 9], [1, 7], [2, 8]], np.int64)
    eng.answer(queries, RuntimeConfig())
    snap = obs_device.snapshot()
    lane_keys = [k for k in snap if "[lanes2]" in k]
    assert lane_keys, f"no lane-split program captured: {list(snap)}"
    entry = snap[lane_keys[0]]
    assert entry["bytes_accessed"] > 0
    # steady state: a second batch adds no new program
    eng.answer(queries, RuntimeConfig())
    assert len(obs_device.snapshot()) == len(snap)
    obs_device.reset()


def test_telemetry_metrics_registered_in_obs_map():
    import distributed_oracle_search_tpu.obs as obs

    for name in ("telemetry_ticks_published_total",
                 "telemetry_publish_errors_total",
                 "telemetry_publish_seconds",
                 "telemetry_ticks_ingested_total",
                 "telemetry_ticks_dropped_total",
                 "telemetry_counter_resets_total",
                 "telemetry_points_total",
                 "telemetry_series_evicted_total",
                 "telemetry_series", "telemetry_store_bytes",
                 "rpc_heartbeat_seconds",
                 "slo_evaluations_total", "slo_alerts_total",
                 "slo_fast_burn_", "slo_slow_burn_", "slo_alerting_",
                 "recorder_events_total", "recorder_records_total",
                 "recorder_segments_total", "recorder_torn_lines_total",
                 "recorder_ring_bytes"):
        assert name in obs.__doc__, name


def test_bench_diff_directions_cover_telemetry_family():
    from distributed_oracle_search_tpu.obs import fleet as obs_fleet

    assert obs_fleet._KEY_DIRECTIONS[
        "telemetry_head_ingest_per_sec"] == "higher"
    for key in ("telemetry_publish_p99_ms",
                "telemetry_publish_overhead_frac"):
        assert obs_fleet._KEY_DIRECTIONS[key] == "lower", key
    for key in ("telemetry_head_ingest_per_sec",
                "telemetry_publish_p99_ms",
                "telemetry_publish_overhead_frac"):
        assert obs_fleet._KEY_TOLERANCES[key] == 0.5, key


def test_top_renders_slo_and_telemetry_blank_tolerantly():
    from distributed_oracle_search_tpu.obs import fleet as obs_fleet

    row = obs_fleet._summarize({
        "slo": {"alerting": ["serve_latency"],
                "burn": {"serve_latency": {"fast": 21.37, "slow": 2.0},
                         "serve_availability": {"fast": 0.1}}},
        "telemetry": {"sources": {"w0": {"lag_s": 1.25},
                                  "w1": {"lag_s": 7.5}}},
    })
    assert row["slo burn"] == 21.37
    assert row["tel lag"] == 7.5
    assert row["state"] == "SLO:serve_latency"
    # pre-telemetry statusz (or garbage sections): blanks, no crash
    row = obs_fleet._summarize({"worker": {"batches": 3}})
    assert "slo burn" not in row and "tel lag" not in row
    assert "slo burn" not in obs_fleet._summarize(
        {"slo": "garbage", "telemetry": {"sources": "garbage"}})
    table = obs_fleet.render_top({
        "head": {"slo": {"burn": {"s": {"fast": 1.0}}},
                 "telemetry": {"sources": {"w0": {"lag_s": 0.5}}}},
        "w0": {"worker": {"batches": 3}},
    })
    assert "slo burn" in table.splitlines()[0]
