"""Pipelined CPD builds and epoch-keyed delta rebuilds.

Non-slow: the pipelined-vs-serial parity smoke (bit-identical blocks on
the tier-1 grid, staging counters moved), HBM-budget chunk sizing,
epoch-keyed ledger invalidation, the delta-build correctness suite
(bit-identical to a from-scratch build on the retimed graph, including
the empty-delta copy-everything and whole-shard-dirty degrade-to-full
edges, plus a crash-mid-delta resume drill on the ``crash-build`` fault
point), engine index promotion, the DiffEpochManager's retime→rebuild
hook, and the bench-diff direction table for the ``build_*`` keys.
"""

import json
import os

import numpy as np
import pytest

from distributed_oracle_search_tpu.data.graph import Graph
from distributed_oracle_search_tpu.models.cpd import (
    BuildLedger, build_chunk_rows, build_worker_shard,
    delta_affected_targets, delta_build_index, diff_epoch_of,
    epoch_index_dir, read_manifest, write_index_manifest,
)
from distributed_oracle_search_tpu.obs import metrics as obs_metrics
from distributed_oracle_search_tpu.parallel.partition import (
    DistributionController,
)
from distributed_oracle_search_tpu.utils import atomicio

pytestmark = pytest.mark.build

N_WORKERS = 8
BLOCK_SIZE = 4


@pytest.fixture()
def toy_dc(toy_graph):
    return DistributionController("tpu", N_WORKERS, N_WORKERS,
                                  toy_graph.n, block_size=BLOCK_SIZE)


def _build_all(graph, dc, outdir, **kw):
    for wid in range(dc.maxworker):
        build_worker_shard(graph, dc, wid, outdir, **kw)
    write_index_manifest(outdir, dc)


def _block_bytes(outdir):
    return {f: open(os.path.join(outdir, f), "rb").read()
            for f in sorted(os.listdir(outdir)) if f.startswith("cpd-")}


def _counter(snap, name):
    return snap["counters"].get(name, 0)


def _retimed(graph, difffile):
    return Graph(graph.xs, graph.ys, graph.src, graph.dst,
                 graph.weights_with_diff(difffile))


def _hot_diff(tmp_path, graph, eids, mult=3):
    """A fused-diff file multiplying the named edges' weights."""
    from distributed_oracle_search_tpu.data.formats import write_diff

    path = str(tmp_path / "fused-e000005.diff")
    eids = np.asarray(eids)
    write_diff(path, graph.src[eids], graph.dst[eids],
               graph.w[eids].astype(np.int64) * mult)
    return path


# ------------------------------------------------------ pipeline parity

def test_pipelined_build_bit_identical_to_serial(tmp_path, toy_graph,
                                                 toy_dc, monkeypatch):
    """The tier-1 parity smoke: the pipelined loop (background stager,
    pre-opened writers, device-staged targets) must produce byte-
    identical block files to the serial reference loop — staging moves
    WHEN inputs are prepared, never what the kernels compute."""
    snap0 = obs_metrics.REGISTRY.snapshot()
    d_pipe = str(tmp_path / "pipe")
    monkeypatch.setenv("DOS_BUILD_PIPELINE", "1")
    _build_all(toy_graph, toy_dc, d_pipe)
    snap1 = obs_metrics.REGISTRY.snapshot()
    d_serial = str(tmp_path / "serial")
    monkeypatch.setenv("DOS_BUILD_PIPELINE", "0")
    _build_all(toy_graph, toy_dc, d_serial)
    assert _block_bytes(d_pipe) == _block_bytes(d_serial)
    # the stager actually ran and counted its rows
    assert (_counter(snap1, "build_rows_staged_total")
            - _counter(snap0, "build_rows_staged_total")) == toy_graph.n


def test_pipeline_small_chunk_parity(tmp_path, toy_graph, toy_dc):
    """Multi-chunk blocks (chunk < block size) keep parity through the
    pipeline — the chunked staging path, not just one pad per block."""
    d1 = str(tmp_path / "c2")
    d2 = str(tmp_path / "whole")
    _build_all(toy_graph, toy_dc, d1, chunk=2)
    _build_all(toy_graph, toy_dc, d2)
    assert _block_bytes(d1) == _block_bytes(d2)


def test_pipeline_resume_recomputes_only_missing(tmp_path, toy_graph,
                                                 toy_dc):
    d = str(tmp_path / "idx")
    build_worker_shard(toy_graph, toy_dc, 0, d)
    victim = "cpd-w00000-b00001.npy"
    os.unlink(os.path.join(d, victim))
    written = build_worker_shard(toy_graph, toy_dc, 0, d)
    assert written == [victim]


def test_build_chunk_rows_budget(toy_graph, monkeypatch):
    n_owned = 512
    # explicit chunk always wins
    assert build_chunk_rows(toy_graph, 64, n_owned) == 64
    # unset budget keeps the legacy whole-shard batch
    monkeypatch.delenv("DOS_BUILD_HBM_MB", raising=False)
    assert build_chunk_rows(toy_graph, 0, n_owned) == n_owned
    # a budget sizes the chunk: rows = budget // per-row bytes, pow2
    k = max(toy_graph.max_out_degree, 1)
    per_row = toy_graph.n * (k + 2) * 4
    monkeypatch.setenv("DOS_BUILD_HBM_MB", str(100 * per_row / 1e6))
    got = build_chunk_rows(toy_graph, 0, n_owned, kind="ell")
    assert got == 64          # pow2 floor of 100
    # budget larger than the shard clamps to the shard
    monkeypatch.setenv("DOS_BUILD_HBM_MB", "1e9")
    assert build_chunk_rows(toy_graph, 0, 48) <= 48
    # malformed degrades to the default (no crash)
    monkeypatch.setenv("DOS_BUILD_HBM_MB", "not-a-number")
    assert build_chunk_rows(toy_graph, 0, n_owned) == n_owned


def test_atomic_npy_writer_and_copy(tmp_path):
    p = str(tmp_path / "b.npy")
    w = atomicio.AtomicNpyWriter(p)
    arr = np.arange(12, dtype=np.int8).reshape(3, 4)
    digest = w.commit(arr)
    assert (np.load(p) == arr).all()
    assert digest == atomicio.digest_file(p)
    # abort leaves nothing behind
    w2 = atomicio.AtomicNpyWriter(str(tmp_path / "c.npy"))
    w2.abort()
    assert os.listdir(tmp_path) == ["b.npy"]
    # atomic copy returns the copied digest
    q = str(tmp_path / "copy.npy")
    assert atomicio.atomic_copy_file(p, q) == digest
    assert open(q, "rb").read() == open(p, "rb").read()


# ------------------------------------------------- epoch-keyed ledger

def test_epoch_keyed_ledger_invalidation(tmp_path, toy_graph, toy_dc):
    """A parseable block journaled under ANOTHER epoch (or none) must
    not satisfy an epoch-keyed resume — stale weight regimes are
    invalidated, not adopted."""
    d = str(tmp_path / "idx")
    build_worker_shard(toy_graph, toy_dc, 0, d, epoch=1)
    ledger = BuildLedger(d, 0)
    assert all(e.get("epoch") == 1 for e in ledger.entries().values())
    # same epoch: everything resumes
    assert build_worker_shard(toy_graph, toy_dc, 0, d, epoch=1) == []
    # different epoch: every block is rebuilt
    written = build_worker_shard(toy_graph, toy_dc, 0, d, epoch=2)
    assert len(written) == 2
    # un-keyed build over epoch-keyed ledger keeps legacy semantics
    assert build_worker_shard(toy_graph, toy_dc, 0, d) == []


# ------------------------------------------------------- delta builds

def test_delta_build_bit_identical_and_skips(tmp_path, toy_graph,
                                             toy_dc):
    """The core delta contract: old index + fused diff must reproduce
    a from-scratch build on the retimed graph bit-for-bit, while
    recomputing only the dirty rows and byte-copying clean blocks."""
    old = str(tmp_path / "old")
    _build_all(toy_graph, toy_dc, old)
    # one increased + one mildly decreased edge (both tense directions)
    # with SMALL dirty cones — on a 48-node graph most edges sit on
    # co-optimal paths to over half the targets (whole-graph dirty is
    # the degrade test's regime, not this one's); edges 26/41 measured
    # 1-row cones under these perturbations
    from distributed_oracle_search_tpu.data.formats import write_diff
    fused = str(tmp_path / "fused-e000005.diff")
    e1, e2 = 26, 41
    write_diff(fused,
               toy_graph.src[[e1, e2]], toy_graph.dst[[e1, e2]],
               np.asarray([int(toy_graph.w[e1]) * 7,
                           max(int(toy_graph.w[e2]) - 1, 1)]))
    snap0 = obs_metrics.REGISTRY.snapshot()
    rep = delta_build_index(toy_graph, toy_dc, old, fused)
    snap1 = obs_metrics.REGISTRY.snapshot()
    assert rep["epoch"] == 5                  # parsed from the name
    assert rep["outdir"] == epoch_index_dir(old, 5)
    scratch = str(tmp_path / "scratch")
    _build_all(_retimed(toy_graph, fused), toy_dc, scratch)
    assert _block_bytes(rep["outdir"]) == _block_bytes(scratch)
    # real sparsity on the toy graph: some rows skipped, some redone
    assert 0 < rep["affected_rows"] < toy_graph.n
    assert rep["rows_recomputed"] < toy_graph.n
    assert rep["blocks_skipped"] > 0
    assert not rep["degraded_full"]
    assert (_counter(snap1, "build_delta_rows_recomputed_total")
            - _counter(snap0, "build_delta_rows_recomputed_total")
            ) == rep["rows_recomputed"]
    assert (_counter(snap1, "build_delta_skipped_blocks_total")
            - _counter(snap0, "build_delta_skipped_blocks_total")
            ) == rep["blocks_skipped"]
    # the new manifest is a valid epoch-tagged index
    man = read_manifest(rep["outdir"])
    assert man["diff_epoch"] == 5
    assert man["diff_file"] == os.path.abspath(fused)


def test_delta_empty_diff_copies_everything(tmp_path, toy_graph,
                                            toy_dc):
    old = str(tmp_path / "old")
    _build_all(toy_graph, toy_dc, old)
    # a "retime" to the weights already in force: zero changed edges
    from distributed_oracle_search_tpu.data.formats import write_diff
    fused = str(tmp_path / "fused-e000002.diff")
    write_diff(fused, toy_graph.src[:3], toy_graph.dst[:3],
               toy_graph.w[:3])
    rep = delta_build_index(toy_graph, toy_dc, old, fused)
    assert rep["changed_edges"] == 0
    assert rep["rows_recomputed"] == 0
    assert rep["affected_rows"] == 0
    n_blocks = sum(-(-toy_dc.n_owned(w) // BLOCK_SIZE)
                   for w in range(N_WORKERS))
    assert rep["blocks_skipped"] == n_blocks
    assert _block_bytes(rep["outdir"]) == _block_bytes(old)


def test_delta_whole_shard_dirty_degrades_to_full(tmp_path, toy_graph,
                                                  toy_dc, monkeypatch):
    """Past the seed bound the dirty pass is inconclusive and the delta
    degrades to a full (pipelined) rebuild — still bit-identical."""
    old = str(tmp_path / "old")
    _build_all(toy_graph, toy_dc, old)
    fused = _hot_diff(tmp_path, toy_graph, [1, 5, 9], mult=4)
    monkeypatch.setenv("DOS_BUILD_DELTA_MAX_SEEDS", "2")
    rep = delta_build_index(toy_graph, toy_dc, old, fused)
    assert rep["degraded_full"]
    assert rep["blocks_skipped"] == 0
    scratch = str(tmp_path / "scratch")
    _build_all(_retimed(toy_graph, fused), toy_dc, scratch)
    assert _block_bytes(rep["outdir"]) == _block_bytes(scratch)


def test_delta_affected_targets_bound_and_empty(toy_graph):
    assert len(delta_affected_targets(
        toy_graph, np.zeros(0, np.int64), toy_graph.w, toy_graph.w)) == 0
    w2 = toy_graph.w.copy()
    w2[:8] = w2[:8] * 2
    assert delta_affected_targets(
        toy_graph, np.arange(8), toy_graph.w, w2, max_seeds=3) is None


def test_delta_crash_mid_build_resumes(tmp_path, toy_graph, toy_dc,
                                       monkeypatch):
    """crash-build fires between delta block flushes; the rerun resumes
    off the epoch-keyed ledger and the finished index is bit-identical
    to an uninterrupted delta (and therefore to a scratch build)."""
    from distributed_oracle_search_tpu.testing import faults

    old = str(tmp_path / "old")
    _build_all(toy_graph, toy_dc, old)
    fused = _hot_diff(tmp_path, toy_graph, [2], mult=9)
    monkeypatch.setenv("DOS_FAULTS",
                       "crash-build;mode=raise;after=3;times=1")
    faults.reset()
    with pytest.raises(RuntimeError, match="crash-build"):
        delta_build_index(toy_graph, toy_dc, old, fused)
    monkeypatch.delenv("DOS_FAULTS")
    faults.reset()
    snap0 = obs_metrics.REGISTRY.snapshot()
    rep = delta_build_index(toy_graph, toy_dc, old, fused)
    snap1 = obs_metrics.REGISTRY.snapshot()
    assert rep["blocks_resumed"] > 0
    assert (_counter(snap1, "build_blocks_resumed_total")
            > _counter(snap0, "build_blocks_resumed_total"))
    scratch = str(tmp_path / "scratch")
    _build_all(_retimed(toy_graph, fused), toy_dc, scratch)
    assert _block_bytes(rep["outdir"]) == _block_bytes(scratch)


def test_diff_epoch_of():
    assert diff_epoch_of("spool/fused-e000042.diff") == 42
    assert diff_epoch_of("road.xy.diff") is None
    assert diff_epoch_of("") is None


# --------------------------------------------------- index promotion

def test_engine_promotes_epoch_index(tmp_path, toy_graph, toy_dc):
    """A promoted delta index serves the fused epoch with OPTIMAL
    routes: the promoted engine's answers equal a fresh engine loaded
    from a scratch build on the retimed graph."""
    from distributed_oracle_search_tpu.transport.wire import (
        RuntimeConfig,
    )
    from distributed_oracle_search_tpu.worker.engine import ShardEngine

    old = str(tmp_path / "old")
    _build_all(toy_graph, toy_dc, old)
    fused = _hot_diff(tmp_path, toy_graph, [4], mult=11)
    rep = delta_build_index(toy_graph, toy_dc, old, fused)
    wid = 0
    rng = np.random.default_rng(3)
    owned = toy_dc.owned(wid)
    queries = np.stack([rng.integers(0, toy_graph.n, 32),
                        rng.choice(owned, 32)], axis=1)
    eng = ShardEngine(toy_graph, toy_dc, wid, old)
    assert eng.index_epoch == 0
    t = eng.promote_index_async(rep["outdir"], rep["epoch"])
    t.join(timeout=30)
    assert eng.index_epoch == rep["epoch"]
    scratch = str(tmp_path / "scratch")
    _build_all(_retimed(toy_graph, fused), toy_dc, scratch)
    ref = ShardEngine(toy_graph, toy_dc, wid, scratch)
    got = eng.answer(queries, RuntimeConfig(), difffile=fused)
    want = ref.answer(queries, RuntimeConfig(), difffile=fused)
    for a, b in zip(got[:3], want[:3]):
        assert (np.asarray(a) == np.asarray(b)).all()
    # the epoch GATE: a batch naming any other diff (here free flow)
    # still walks the BASE table after promotion — a promoted epoch
    # must never leak new-regime moves into older-epoch or free-flow
    # traffic priced under its own weights
    base = ShardEngine(toy_graph, toy_dc, wid, old)
    got_ff = eng.answer(queries, RuntimeConfig())
    want_ff = base.answer(queries, RuntimeConfig())
    for a, b in zip(got_ff[:3], want_ff[:3]):
        assert (np.asarray(a) == np.asarray(b)).all()


def test_engine_promotion_failure_keeps_old_table(tmp_path, toy_graph,
                                                  toy_dc):
    from distributed_oracle_search_tpu.worker.engine import ShardEngine

    old = str(tmp_path / "old")
    _build_all(toy_graph, toy_dc, old)
    eng = ShardEngine(toy_graph, toy_dc, 0, old)
    fm_before = eng.fm
    assert not eng.promote_index(str(tmp_path / "nope"), 3)
    assert eng.index_epoch == 0
    assert eng.fm is fm_before


def test_engine_promotion_is_monotone(tmp_path, toy_graph, toy_dc):
    """Out-of-order async promotions must not regress the gate: an
    older epoch finishing after a newer one is refused, so current-
    epoch traffic keeps the newest promoted table."""
    from distributed_oracle_search_tpu.worker.engine import ShardEngine

    old = str(tmp_path / "old")
    _build_all(toy_graph, toy_dc, old)
    fused5 = _hot_diff(tmp_path, toy_graph, [26], mult=7)
    rep5 = delta_build_index(toy_graph, toy_dc, old, fused5)
    eng = ShardEngine(toy_graph, toy_dc, 0, old)
    assert eng.promote_index(rep5["outdir"], rep5["epoch"])
    # the slower, older promotion loses
    assert not eng.promote_index(rep5["outdir"], rep5["epoch"] - 1)
    assert not eng.promote_index(rep5["outdir"], rep5["epoch"])
    assert eng.index_epoch == rep5["epoch"]


def test_engine_promotion_never_heals_with_freeflow_graph(
        tmp_path, toy_graph, toy_dc):
    """A corrupt epoch-index block must FAIL the promotion (base table
    stays), never self-heal — the engine's heal path rebuilds from its
    free-flow graph, which would persist wrong-regime rows into the
    epoch index under valid digests."""
    from distributed_oracle_search_tpu.worker.engine import ShardEngine

    old = str(tmp_path / "old")
    _build_all(toy_graph, toy_dc, old)
    fused = _hot_diff(tmp_path, toy_graph, [26], mult=7)
    rep = delta_build_index(toy_graph, toy_dc, old, fused)
    victim = os.path.join(rep["outdir"], "cpd-w00000-b00000.npy")
    raw = bytearray(open(victim, "rb").read())
    raw[-3] ^= 0xFF
    open(victim, "wb").write(bytes(raw))
    eng = ShardEngine(toy_graph, toy_dc, 0, old)
    assert not eng.promote_index(rep["outdir"], rep["epoch"])
    assert eng.index_epoch == 0
    # not quarantined, not rebuilt: the bad bytes are untouched
    assert open(victim, "rb").read() == bytes(raw)
    assert not os.path.exists(victim + ".quarantined")


def _promoted_world(tmp_path, toy_graph, toy_dc):
    from distributed_oracle_search_tpu.worker.engine import ShardEngine

    old = str(tmp_path / "old")
    _build_all(toy_graph, toy_dc, old)
    fused = _hot_diff(tmp_path, toy_graph, [26], mult=7)
    rep = delta_build_index(toy_graph, toy_dc, old, fused)
    eng = ShardEngine(toy_graph, toy_dc, 0, old)
    assert eng.promote_index(rep["outdir"], rep["epoch"])
    rng = np.random.default_rng(5)
    owned = toy_dc.owned(0)
    queries = np.stack([rng.integers(0, toy_graph.n, 16),
                        rng.choice(owned, 16)], axis=1)
    return eng, old, fused, rep, queries


def test_scrub_rebind_under_serve_never_tears_epoch_gate(
        tmp_path, toy_graph, toy_dc):
    """Heal-under-serve: a scrubber rebinding BOTH tables in a tight
    loop while a serving thread answers epoch and free-flow batches —
    every answer stays bit-correct for its regime (the ``(epoch,
    table)`` gate pair never tears) and the promotion survives."""
    import threading

    from distributed_oracle_search_tpu.integrity.scrub import _rebind
    from distributed_oracle_search_tpu.transport.wire import (
        RuntimeConfig,
    )
    from distributed_oracle_search_tpu.worker.engine import ShardEngine

    eng, old, fused, rep, queries = _promoted_world(
        tmp_path, toy_graph, toy_dc)
    scratch = str(tmp_path / "scratch")
    _build_all(_retimed(toy_graph, fused), toy_dc, scratch)
    ref = ShardEngine(toy_graph, toy_dc, 0, scratch)
    want = [np.asarray(a) for a in
            ref.answer(queries, RuntimeConfig(), difffile=fused)[:3]]
    base = ShardEngine(toy_graph, toy_dc, 0, old)
    want_ff = [np.asarray(a) for a in
               base.answer(queries, RuntimeConfig())[:3]]
    stop = threading.Event()
    bad = []

    def serve():
        while not stop.is_set():
            got = eng.answer(queries, RuntimeConfig(), difffile=fused)
            if not all((np.asarray(a) == b).all()
                       for a, b in zip(got[:3], want)):
                bad.append("epoch answers tore")
                return
            got_ff = eng.answer(queries, RuntimeConfig())
            if not all((np.asarray(a) == b).all()
                       for a, b in zip(got_ff[:3], want_ff)):
                bad.append("free-flow leaked epoch moves")
                return

    t = threading.Thread(target=serve)
    t.start()
    try:
        for _ in range(25):
            assert _rebind(eng, None)
            assert _rebind(eng, rep["epoch"])
    finally:
        stop.set()
        t.join(timeout=30)
    assert not bad
    assert eng.index_epoch == rep["epoch"]


def test_scrubber_heals_corrupted_promoted_resident_same_epoch(
        tmp_path, toy_graph, toy_dc):
    """Resident rot in a PROMOTED table heals from the epoch index
    itself (promote_index's no-freeflow-heal rule), same epoch, serving
    uninterrupted — never by dropping back to the base regime."""
    from distributed_oracle_search_tpu.integrity.scrub import (
        TableScrubber,
    )
    from distributed_oracle_search_tpu.transport.wire import (
        RuntimeConfig,
    )
    from distributed_oracle_search_tpu.worker.engine import ShardEngine

    eng, old, fused, rep, queries = _promoted_world(
        tmp_path, toy_graph, toy_dc)
    want = [np.asarray(a) for a in
            eng.answer(queries, RuntimeConfig(), difffile=fused)[:3]]
    epoch, table = eng._fm_promoted
    rotted = np.array(np.asarray(table), np.int8, copy=True)
    rotted[0, :] = np.where(rotted[0, :] <= 0, 1, 0)
    eng._fm_promoted = (epoch, rotted)
    scr = TableScrubber(lambda: [eng], interval_s=3600.0)
    scr.run_pass()
    assert scr.corrupt_blocks >= 1
    assert eng._fm_promoted is not None
    assert eng._fm_promoted[0] == epoch     # healed IN regime
    got = eng.answer(queries, RuntimeConfig(), difffile=fused)
    for a, b in zip(got[:3], want):
        assert (np.asarray(a) == b).all()


def test_scrub_unreloadable_epoch_drops_promotion_to_clean_base(
        tmp_path, toy_graph, toy_dc):
    """Rotted promoted resident whose epoch index is ALSO damaged
    (manifest and a block lost): the rebind drops the promotion (an
    epoch index must never heal from the free-flow graph) and epoch
    traffic degrades to the clean base table instead of serving rot."""
    from distributed_oracle_search_tpu.integrity.scrub import (
        TableScrubber,
    )
    from distributed_oracle_search_tpu.transport.wire import (
        RuntimeConfig,
    )
    from distributed_oracle_search_tpu.worker.engine import ShardEngine

    eng, old, fused, rep, queries = _promoted_world(
        tmp_path, toy_graph, toy_dc)
    epoch, table = eng._fm_promoted
    rotted = np.array(np.asarray(table), np.int8, copy=True)
    rotted[0, :] = np.where(rotted[0, :] <= 0, 1, 0)
    eng._fm_promoted = (epoch, rotted)
    # the digests went with the manifest: detection falls back to the
    # resident-vs-disk compare, and the reload cannot reassemble the
    # shard (a whole block is gone)
    os.unlink(os.path.join(rep["outdir"], "index.json"))
    os.unlink(os.path.join(rep["outdir"], "cpd-w00000-b00001.npy"))
    scr = TableScrubber(lambda: [eng], interval_s=3600.0)
    scr.run_pass()
    assert scr.corrupt_blocks >= 1
    assert eng._fm_promoted is None
    base = ShardEngine(toy_graph, toy_dc, 0, old)
    want = base.answer(queries, RuntimeConfig(), difffile=fused)
    got = eng.answer(queries, RuntimeConfig(), difffile=fused)
    for a, b in zip(got[:3], want[:3]):
        assert (np.asarray(a) == np.asarray(b)).all()


def test_delta_pruned_old_diff_degrades_to_full(tmp_path, toy_graph,
                                                toy_dc):
    """Delta-on-delta chaining when the old index's recorded fused
    diff was pruned from the spool: the changed set is unknowable, so
    the delta degrades to a full rebuild — still a correct, bit-
    identical epoch index, never a failed chain link."""
    old = str(tmp_path / "old")
    _build_all(toy_graph, toy_dc, old)
    fused2 = _hot_diff(tmp_path, toy_graph, [26], mult=7)
    os.rename(fused2, str(tmp_path / "fused-e000002.diff"))
    fused2 = str(tmp_path / "fused-e000002.diff")
    rep2 = delta_build_index(toy_graph, toy_dc, old, fused2)
    os.unlink(fused2)                      # the spool pruned it
    fused3 = _hot_diff(tmp_path, toy_graph, [41], mult=9)
    os.rename(fused3, str(tmp_path / "fused-e000003.diff"))
    fused3 = str(tmp_path / "fused-e000003.diff")
    rep3 = delta_build_index(toy_graph, toy_dc, rep2["outdir"], fused3)
    assert rep3["degraded_full"]
    scratch = str(tmp_path / "scratch")
    _build_all(_retimed(toy_graph, fused3), toy_dc, scratch)
    assert _block_bytes(rep3["outdir"]) == _block_bytes(scratch)


# ------------------------------------------------- retime→rebuild hook

def test_epoch_manager_on_swap_hook(tmp_path):
    from distributed_oracle_search_tpu.traffic import (
        DiffEpochManager, write_segment,
    )

    d = str(tmp_path / "stream")
    os.makedirs(d)
    calls = []
    m = DiffEpochManager(
        d, on_swap=lambda e, f, aff: calls.append((e, f, set(aff))))
    write_segment(d, 1, [0, 1], [1, 2], [50, 60])
    assert m.refresh()
    assert calls == [(1, m.fused_path(1), {(0, 1), (1, 2)})]
    # a raising hook is logged, never unwinds the swap
    m.on_swap = lambda *a: (_ for _ in ()).throw(ValueError("boom"))
    write_segment(d, 2, [0], [1], [70])
    assert m.refresh()
    assert m.epoch == 2


# ----------------------------------------------------- bench-diff gate

def _bench_record(tmp_path, name, headline):
    p = tmp_path / name
    p.write_text(json.dumps({
        "metric": "scenario_queries_per_sec", "value": 100000.0,
        "headline": headline}))
    return str(p)


def test_bench_diff_knows_build_key_directions(tmp_path):
    """build_* headline keys gate with the right directions: build
    rates and the delta-vs-full ratio are higher-is-better, pipeline
    stall lower-is-better — and staging OVERLAP higher-is-better
    despite its _seconds suffix (the heuristic-defeating case the
    explicit table exists for)."""
    from distributed_oracle_search_tpu.obs import fleet

    old = _bench_record(tmp_path, "BENCH_r01.json", {
        "scale_build_rows_per_sec": 300.0,
        "road_tpu_build_rows_per_sec": 42.0,
        "build_delta_vs_full_ratio": 10.0,
        "build_pipeline_stall_seconds": 0.5,
        "build_stage_overlap_seconds": 2.0,
    })
    bad = _bench_record(tmp_path, "BENCH_r02.json", {
        "scale_build_rows_per_sec": 100.0,       # drop: regression
        "road_tpu_build_rows_per_sec": 12.0,     # drop: regression
        "build_delta_vs_full_ratio": 7.5,        # -25% > 20% tol
        "build_pipeline_stall_seconds": 2.0,     # rise: regression
        "build_stage_overlap_seconds": 0.2,      # DROP: regression
    })
    out = fleet.compare_bench(old, bad)
    by_key = {e["key"]: e for e in out["regressions"]}
    assert by_key["scale_build_rows_per_sec"]["direction"] == "higher"
    assert by_key["road_tpu_build_rows_per_sec"]["direction"] == "higher"
    assert by_key["build_delta_vs_full_ratio"]["direction"] == "higher"
    assert by_key["build_delta_vs_full_ratio"]["tolerance"] == \
        pytest.approx(0.2)
    assert by_key["build_pipeline_stall_seconds"]["direction"] == "lower"
    assert by_key["build_stage_overlap_seconds"]["direction"] == "higher"

    ok = _bench_record(tmp_path, "BENCH_r03.json", {
        "scale_build_rows_per_sec": 320.0,
        "road_tpu_build_rows_per_sec": 210.0,
        "build_delta_vs_full_ratio": 12.0,
        "build_pipeline_stall_seconds": 0.1,
        "build_stage_overlap_seconds": 2.4,
    })
    assert fleet.compare_bench(old, ok)["regressions"] == []


# ---------------------------------------------------------- CLI drive

def test_make_cpds_delta_from_cli(tmp_path, toy_graph, toy_dc,
                                  capsys):
    """``dos-make-cpds --delta-from OLD --diff FUSED`` end to end."""
    from distributed_oracle_search_tpu.cli.make_cpds import main
    from distributed_oracle_search_tpu.data import write_xy

    old = str(tmp_path / "old")
    _build_all(toy_graph, toy_dc, old)
    fused = _hot_diff(tmp_path, toy_graph, [6], mult=5)
    xy = str(tmp_path / "g.xy")
    write_xy(xy, toy_graph.xs, toy_graph.ys, toy_graph.src,
             toy_graph.dst, toy_graph.w)
    conf = str(tmp_path / "conf.json")
    with open(conf, "w") as f:
        json.dump({"workers": [f"tpu:{i}" for i in range(N_WORKERS)],
                   "partmethod": "tpu", "partkey": N_WORKERS,
                   "outdir": old, "xy_file": xy}, f)
    rc = main(["-c", conf, "--delta-from", old, "--diff", fused])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["exit_code"] == 0
    assert out["epoch"] == 5
    scratch = str(tmp_path / "scratch")
    _build_all(_retimed(toy_graph, fused), toy_dc, scratch)
    assert _block_bytes(out["outdir"]) == _block_bytes(scratch)
