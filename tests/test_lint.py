"""``dos-lint`` analyzer suite: the fixture corpus proves every rule
fires (positive + suppressed + clean per rule), the self-check proves
the real package passes ``--strict`` with zero unsuppressed findings,
and the CLI tests pin the bench-diff exit-code convention."""

import json
import os
import subprocess
import sys

import pytest

import distributed_oracle_search_tpu
from distributed_oracle_search_tpu.analysis import (
    ALL_RULES, BAD_SUPPRESSION, LintConfig, render_json, run_paths,
)

pytestmark = pytest.mark.lint

FIXTURES = os.path.join(os.path.dirname(__file__), "lint_fixtures")
PACKAGE = os.path.dirname(
    os.path.abspath(distributed_oracle_search_tpu.__file__))
REPO = os.path.dirname(PACKAGE)

RULE_NAMES = [r.name for r in ALL_RULES]


def lint(paths, **cfg):
    findings, n = run_paths(paths, ALL_RULES, LintConfig(**cfg))
    return findings


def _clean_line(path) -> int:
    """Line of the first ``clean``-prefixed def/assign in a fixture —
    findings at or after it would be false positives."""
    with open(path) as f:
        for i, line in enumerate(f, start=1):
            if line.startswith(("def clean", "M_CLEAN")):
                return i
    raise AssertionError(f"no clean case in {path}")


# ------------------------------------------------------- fixture corpus

@pytest.mark.parametrize("rule", [r for r in RULE_NAMES])
def test_rule_fires_and_suppresses(rule):
    path = os.path.join(FIXTURES, rule.replace("-", "_") + ".py")
    findings = [f for f in lint([path], select=(rule,))
                if f.rule == rule]
    active = [f for f in findings if not f.suppressed]
    suppressed = [f for f in findings if f.suppressed]
    assert active, f"{rule}: positive case did not fire"
    assert suppressed, f"{rule}: suppressed case did not register"
    for f in suppressed:
        assert f.justification, f"{rule}: suppression lost its reason"
    clean_at = _clean_line(path)
    late = [f for f in findings if f.line >= clean_at]
    assert late == [], f"{rule}: clean case flagged: {late}"


def test_corpus_strict_fails_with_every_rule():
    findings = lint([FIXTURES])
    fired = {f.rule for f in findings if not f.suppressed}
    assert set(RULE_NAMES) <= fired, sorted(set(RULE_NAMES) - fired)
    assert BAD_SUPPRESSION in fired


def test_bad_suppression_is_finding_and_does_not_silence():
    path = os.path.join(FIXTURES, "bad_suppression.py")
    findings = lint([path])
    rules = {f.rule: f.suppressed for f in findings}
    assert rules.get(BAD_SUPPRESSION) is False
    # the justification-less disable silenced nothing
    assert rules.get("fifo-hygiene") is False


def test_suppression_needs_matching_rule(tmp_path):
    p = tmp_path / "wrong_rule.py"
    p.write_text(
        "import os\n\n\n"
        "def f():\n"
        "    # dos-lint: disable=lock-scope -- wrong rule named here\n"
        "    return os.getenv(\"DOS_X\")\n")
    findings = lint([str(p)])
    env = [f for f in findings if f.rule == "env-discipline"]
    assert env and not env[0].suppressed


def test_syntax_error_is_a_finding(tmp_path):
    p = tmp_path / "broken.py"
    p.write_text("def f(:\n")
    findings = lint([str(p)])
    assert [f.rule for f in findings] == ["syntax-error"]


def test_null_byte_file_is_a_finding_not_a_crash(tmp_path):
    """ast.parse raises ValueError (not SyntaxError) on a null byte;
    one corrupt file must not take down the whole gate."""
    p = tmp_path / "stray.py"
    p.write_bytes(b"x = 1\x00")
    findings = lint([str(p)])
    assert [f.rule for f in findings] == ["syntax-error"]


def test_trailing_suppression_covers_multiline_statement(tmp_path):
    """A finding anchors to a multi-line statement's FIRST line; a
    trailing disable comment on a later physical line must still
    cover it."""
    p = tmp_path / "multiline.py"
    p.write_text(
        "import os\n\n"
        "x = os.environ.get(\n"
        "    \"DOS_X\")  # dos-lint: disable=env-discipline -- why not\n")
    findings = lint([str(p)])
    env = [f for f in findings if f.rule == "env-discipline"]
    assert env and env[0].suppressed and env[0].justification


def test_suppression_inside_body_cannot_reach_the_header(tmp_path):
    """A disable trailing a line INSIDE a with/if body must not silence
    a finding anchored at the compound statement's header."""
    p = tmp_path / "scoped.py"
    p.write_text(
        "import os\n\n"
        "def write_out(d):\n"
        "    with open(d + \"/outer.json\", \"w\") as f:\n"
        "        x = 1  # dos-lint: disable=atomic-writes -- unrelated\n"
        "        f.write(str(x))\n")
    findings = lint([str(p)])
    aw = [f for f in findings if f.rule == "atomic-writes"]
    assert aw and not aw[0].suppressed


def test_stacked_disable_lines_both_apply(tmp_path):
    p = tmp_path / "stacked.py"
    p.write_text(
        "import os\n\n\n"
        "def write_out(d):\n"
        "    # dos-lint: disable=env-discipline -- reason one\n"
        "    # dos-lint: disable=atomic-writes -- reason two\n"
        "    open(d + \"/out.json\", \"w\").write("
        "os.environ.get(\"DOS_Y\", \"\"))\n")
    findings = lint([str(p)])
    by_rule = {f.rule: f for f in findings}
    assert by_rule["env-discipline"].suppressed
    assert by_rule["atomic-writes"].suppressed


# ----------------------------------------------------------- self-check

def test_package_is_lint_clean():
    """THE gate: zero unsuppressed findings on the real package, and
    every suppression carries a justification."""
    findings = lint([PACKAGE])
    active = [f for f in findings if not f.suppressed]
    assert active == [], "\n".join(f.render() for f in active)
    suppressed = [f for f in findings if f.suppressed]
    assert suppressed, "expected the documented real-code suppressions"
    for f in suppressed:
        assert f.justification.strip(), f.render()


def test_console_script_strict_exits_zero_on_package():
    proc = subprocess.run(
        [sys.executable, "-m",
         "distributed_oracle_search_tpu.cli.lint", "--strict", PACKAGE],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_console_script_strict_fails_on_corpus():
    proc = subprocess.run(
        [sys.executable, "-m",
         "distributed_oracle_search_tpu.cli.lint", "--strict", FIXTURES],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert proc.returncode == 1, proc.stdout + proc.stderr


# -------------------------------------------- bench-diff gate convention

def test_json_report_gate_convention():
    """``--json`` mirrors ``dos-obs bench-diff``: ok/exit_code in the
    doc, process exit 1 on findings / 0 clean — the two gates compose
    in one pipeline."""
    proc = subprocess.run(
        [sys.executable, "-m",
         "distributed_oracle_search_tpu.cli.lint", "--json", FIXTURES],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    doc = json.loads(proc.stdout)
    assert proc.returncode == 1
    assert doc["ok"] is False and doc["exit_code"] == 1
    assert set(RULE_NAMES) <= set(doc["counts"])
    assert doc["suppressed"] > 0

    proc = subprocess.run(
        [sys.executable, "-m",
         "distributed_oracle_search_tpu.cli.lint", "--json", PACKAGE],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    doc = json.loads(proc.stdout)
    assert proc.returncode == 0
    assert doc["ok"] is True and doc["exit_code"] == 0
    assert doc["counts"] == {}


def test_render_json_matches_cli_fields():
    findings = lint([os.path.join(FIXTURES, "env_discipline.py")])
    doc = render_json(findings, 1)
    assert {"ok", "exit_code", "files", "counts", "suppressed",
            "findings"} <= set(doc)


def test_unknown_rule_is_usage_error():
    proc = subprocess.run(
        [sys.executable, "-m",
         "distributed_oracle_search_tpu.cli.lint", "--select", "bogus"],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert proc.returncode == 2
    assert "unknown rule" in proc.stderr


def test_select_and_disable_scope_rules():
    path = os.path.join(FIXTURES, "env_discipline.py")
    only_lock = lint([path], select=("lock-scope",))
    assert [f for f in only_lock if f.rule == "env-discipline"] == []
    disabled = lint([path], disable=("env-discipline",))
    assert [f for f in disabled if f.rule == "env-discipline"] == []
