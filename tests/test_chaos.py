"""Fault-tolerant campaign execution, end to end.

Non-slow: real host-mode campaigns through in-thread ``FifoServer``
instances with injected engine crashes — graceful degradation
(``degraded.json``, distinct exit codes) and circuit breaking, asserted
through the obs counters.

Slow: the full chaos drill — 3 supervised worker SUBPROCESSES, one
killed mid-round by the fault harness (twice: once per send attempt, the
budget shared across respawns via ``DOS_FAULTS_STATE``), one dropping a
reply that the head's retry recovers. The campaign must complete
degraded, the supervisor must respawn the dead worker within its backoff
cap, and every recovery path must show in its counter.
"""

import json
import os
import threading
import time

import pytest

from distributed_oracle_search_tpu.cli import process_query as pq
from distributed_oracle_search_tpu.data import (
    Graph, ensure_synth_dataset,
)
from distributed_oracle_search_tpu.models.cpd import write_index_manifest
from distributed_oracle_search_tpu.obs import metrics as obs_metrics
from distributed_oracle_search_tpu.parallel.partition import (
    DistributionController,
)
from distributed_oracle_search_tpu.testing import faults
from distributed_oracle_search_tpu.transport import fifo as fifo_mod
from distributed_oracle_search_tpu.utils.config import ClusterConfig
from distributed_oracle_search_tpu.worker import FifoServer, stop_server
from distributed_oracle_search_tpu.worker import server as server_mod
from distributed_oracle_search_tpu.worker import supervisor as sup_mod
from distributed_oracle_search_tpu.worker.build import main as build_main
from distributed_oracle_search_tpu.worker.supervisor import (
    WorkerSupervisor,
)

N_WORKERS = 3


@pytest.fixture(scope="module")
def chaos_cluster(tmp_path_factory):
    """Tiny dataset + built 3-worker index; tests derive their own conf
    files (round counts differ)."""
    datadir = str(tmp_path_factory.mktemp("chaosdata"))
    paths = ensure_synth_dataset(datadir, width=8, height=6,
                                 n_queries=45, seed=23)
    outdir = os.path.join(datadir, "index")
    for wid in range(N_WORKERS):
        build_main(["--input", paths["xy"], "--partmethod", "mod",
                    "--partkey", str(N_WORKERS), "--workerid", str(wid),
                    "--maxworker", str(N_WORKERS), "--outdir", outdir])
    g = Graph.from_xy(paths["xy"])
    dc = DistributionController("mod", N_WORKERS, N_WORKERS, g.n)
    write_index_manifest(outdir, dc)
    return datadir, paths, outdir


def _conf(chaos_cluster, name, diffs):
    datadir, paths, outdir = chaos_cluster
    conf = ClusterConfig(
        workers=["localhost"] * N_WORKERS,
        partmethod="mod", partkey=N_WORKERS,
        outdir=outdir, xy_file=paths["xy"], scenfile=paths["scen"],
        diffs=diffs, nfs=datadir,
    ).validate()
    path = os.path.join(datadir, name)
    conf.save(path)
    return conf, path


def _thread_servers(conf, tmp_path, monkeypatch):
    fifos = {wid: str(tmp_path / f"worker{wid}.fifo")
             for wid in range(conf.maxworker)}
    monkeypatch.setattr(pq, "command_fifo_path", lambda wid: fifos[wid])
    servers = [FifoServer(conf, wid, command_fifo=fifos[wid])
               for wid in range(conf.maxworker)]
    threads = [threading.Thread(target=s.serve_forever, daemon=True)
               for s in servers]
    for t in threads:
        t.start()
    for fifo in fifos.values():
        for _ in range(100):
            if os.path.exists(fifo):
                break
            time.sleep(0.02)
    return fifos, threads


def _stop_all(fifos, threads):
    for fifo in fifos.values():
        stop_server(fifo, deadline_s=5.0)
    for t in threads:
        t.join(timeout=15)


def _counter(name):
    return obs_metrics.REGISTRY.snapshot()["counters"].get(name, 0)


def test_degraded_campaign_exit_code_and_manifest(
        chaos_cluster, tmp_path, monkeypatch):
    """One worker's engine crashes on every batch: the campaign finishes
    with partial results, exit code EXIT_DEGRADED, a degraded.json
    naming the worker, and — once its failures pass the circuit
    threshold — short-circuited batches instead of futile sends."""
    faults.reset()
    monkeypatch.setenv("DOS_FAULTS", "crash-engine;wid=1;times=inf")
    monkeypatch.setenv("DOS_RETRY_MAX", "1")
    monkeypatch.setenv("DOS_RETRY_BASE_S", "0.05")
    monkeypatch.setenv("DOS_RETRY_JITTER", "0")
    monkeypatch.setenv("DOS_CIRCUIT_THRESHOLD", "2")
    monkeypatch.setenv("DOS_CIRCUIT_COOLDOWN_S", "300")
    conf, conf_path = _conf(chaos_cluster, "conf-degraded.json",
                            diffs=["-", "-", "-", "-"])
    fifos, threads = _thread_servers(conf, tmp_path, monkeypatch)
    outdir = str(tmp_path / "artifacts")
    retries0 = _counter("head_retries_total")
    opened0 = _counter("head_circuit_open_total")
    rejected0 = _counter("head_circuit_rejected_total")
    try:
        rc = pq.main(["-c", conf_path, "--backend", "host",
                      "-o", outdir])
    finally:
        _stop_all(fifos, threads)
    assert rc == pq.EXIT_DEGRADED
    man = json.load(open(os.path.join(outdir, "degraded.json")))
    assert man["exit_code"] == pq.EXIT_DEGRADED
    assert man["failed_workers"] == [1]
    assert man["total_batches"] == 4 * N_WORKERS
    assert man["failed_count"] == 4
    reasons = [f["reason"] for f in man["failed_batches"]]
    # rounds 0-1 fail on the wire (retried), 2-3 are short-circuited by
    # the breaker that OPENed after 2 consecutive failures
    assert reasons == ["send-failed", "send-failed",
                       "circuit-open", "circuit-open"]
    assert _counter("head_retries_total") - retries0 == 2
    assert _counter("head_circuit_open_total") - opened0 == 1
    assert _counter("head_circuit_rejected_total") - rejected0 == 2
    # partial results made it out: parts.csv holds every batch row
    assert os.path.exists(os.path.join(outdir, "parts.csv"))
    assert os.path.exists(os.path.join(outdir, "obs_metrics.json"))


def test_all_failed_campaign_exit_code(chaos_cluster, tmp_path,
                                       monkeypatch):
    faults.reset()
    monkeypatch.setenv("DOS_FAULTS", "crash-engine;times=inf")
    monkeypatch.setenv("DOS_RETRY_MAX", "0")
    conf, conf_path = _conf(chaos_cluster, "conf-allfail.json",
                            diffs=["-"])
    fifos, threads = _thread_servers(conf, tmp_path, monkeypatch)
    outdir = str(tmp_path / "artifacts-allfail")
    try:
        rc = pq.main(["-c", conf_path, "--backend", "host",
                      "-o", outdir])
    finally:
        _stop_all(fifos, threads)
    assert rc == pq.EXIT_FAILED
    man = json.load(open(os.path.join(outdir, "degraded.json")))
    assert man["failed_workers"] == list(range(N_WORKERS))
    assert man["failed_count"] == N_WORKERS


def test_clean_campaign_exit_code_and_no_manifest(chaos_cluster,
                                                  tmp_path, monkeypatch):
    faults.reset()
    monkeypatch.delenv("DOS_FAULTS", raising=False)
    conf, conf_path = _conf(chaos_cluster, "conf-clean.json",
                            diffs=["-"])
    fifos, threads = _thread_servers(conf, tmp_path, monkeypatch)
    outdir = str(tmp_path / "artifacts-clean")
    try:
        rc = pq.main(["-c", conf_path, "--backend", "host",
                      "-o", outdir])
    finally:
        _stop_all(fifos, threads)
    assert rc == pq.EXIT_CLEAN
    assert not os.path.exists(os.path.join(outdir, "degraded.json"))


def test_campaign_sweeps_stale_answer_fifos(chaos_cluster, tmp_path,
                                            monkeypatch):
    """Satellite: FIFOs orphaned by a crashed earlier run are removed at
    campaign start, counted on head_stale_fifos_cleaned_total."""
    faults.reset()
    monkeypatch.delenv("DOS_FAULTS", raising=False)
    datadir = chaos_cluster[0]
    stale = [os.path.join(datadir, "answer.localhost9.a0"),
             os.path.join(datadir, "answer.deadhost0")]
    for p in stale:
        os.mkfifo(p)
    before = _counter("head_stale_fifos_cleaned_total")
    conf, conf_path = _conf(chaos_cluster, "conf-sweep.json",
                            diffs=["-"])
    fifos, threads = _thread_servers(conf, tmp_path, monkeypatch)
    try:
        rc = pq.main(["-c", conf_path, "--backend", "host"])
    finally:
        _stop_all(fifos, threads)
    assert rc == pq.EXIT_CLEAN
    assert not any(os.path.exists(p) for p in stale)
    assert _counter("head_stale_fifos_cleaned_total") - before >= 2


# ---------------------------------------------------------- the chaos drill

@pytest.mark.slow
def test_chaos_kill_worker_mid_round_supervised(chaos_cluster, tmp_path,
                                                monkeypatch):
    """3 supervised worker subprocesses; worker 1 is killed mid-batch on
    both send attempts of round 0 (fault budget shared across its
    respawn via DOS_FAULTS_STATE), worker 2 drops one reply that the
    retry recovers. The campaign completes DEGRADED with worker 1 the
    only loss, the supervisor respawns it (twice) within the backoff
    cap, and the counters match the injected faults."""
    faults.reset()
    datadir = chaos_cluster[0]
    state = str(tmp_path / "faults-state.json")
    monkeypatch.setenv("DOS_FAULTS",
                       "kill-mid-batch;wid=1;times=2,"
                       "drop-reply;wid=2;times=1")
    monkeypatch.setenv("DOS_FAULTS_STATE", state)
    # the timeout must outlive a worker respawn (jax import + engine
    # load in the fresh subprocess), so the retry meets the REPLACEMENT
    # server — whose read of the retry request triggers kill #2
    monkeypatch.setenv("DOS_SEND_TIMEOUT_S", "90")
    monkeypatch.setenv("DOS_RETRY_MAX", "1")
    monkeypatch.setenv("DOS_RETRY_BASE_S", "0.2")
    monkeypatch.setenv("DOS_RETRY_JITTER", "0")
    conf, conf_path = _conf(chaos_cluster, "conf-chaos.json",
                            diffs=["-", "-"])
    fifo_dir = str(tmp_path / "fifos")
    os.makedirs(fifo_dir)
    monkeypatch.setattr(
        pq, "command_fifo_path",
        lambda wid: os.path.join(fifo_dir, f"worker{wid}.fifo"))
    sup = WorkerSupervisor(conf, conf_path, fifo_dir=fifo_dir,
                           logdir=str(tmp_path / "logs"),
                           ping_interval_s=1.0, backoff_base_s=0.2,
                           backoff_cap_s=5.0, probe_timeout_s=5.0)
    respawns0 = sup_mod.M_RESPAWNS.value
    retries0 = fifo_mod.M_RETRIES.value
    outdir = str(tmp_path / "artifacts-chaos")
    sup.start(wait_ready_s=300)
    try:
        rc = pq.main(["-c", conf_path, "--backend", "host",
                      "-o", outdir])
        assert rc == pq.EXIT_DEGRADED
        man = json.load(open(os.path.join(outdir, "degraded.json")))
        # worker 1 lost exactly its round-0 batch (both attempts
        # killed); worker 2's drop was recovered by the retry and must
        # NOT appear
        assert man["failed_workers"] == [1]
        assert [(f["wid"], f["round"]) for f in man["failed_batches"]] \
            == [(1, 0)]
        assert man["total_batches"] == 2 * N_WORKERS
        # retries: worker 1 round 0 (+1) and worker 2's dropped reply
        # (+1) — both booked on head_retries_total
        assert fifo_mod.M_RETRIES.value - retries0 == 2
        # the supervisor respawned worker 1 once per kill, within the
        # backoff cap: the respawned server answered round 1 (otherwise
        # (1, 1) would be in the failure list)
        assert sup.workers[1].respawns == 2
        assert sup_mod.M_RESPAWNS.value - respawns0 == 2
        assert sup.workers[0].respawns == 0
        assert sup.workers[2].respawns == 0
        # worker 2 really dropped one (and only one) data reply: read
        # its counter over the liveness wire
        st = fifo_mod.probe(
            "localhost", 2,
            command_fifo=os.path.join(fifo_dir, "worker2.fifo"),
            nfs=datadir, timeout=10.0)
        assert st is not None and st.ok
        assert st.dropped == 1
        # the injected kill consumed its full cross-process budget
        counts = json.load(open(state))
        kill_counts = counts["0"]
        assert kill_counts["fired"] == 2
        # respawned worker 1 is healthy again and served round 1
        st1 = fifo_mod.probe(
            "localhost", 1,
            command_fifo=os.path.join(fifo_dir, "worker1.fifo"),
            nfs=datadir, timeout=10.0)
        assert st1 is not None and st1.ok and st1.batches >= 1
    finally:
        sup.stop()
    assert all(w.proc.poll() is not None for w in sup.workers.values())


@pytest.mark.slow
@pytest.mark.control
def test_chaos_control_daemon_self_heals_killed_worker(
        chaos_cluster, tmp_path, monkeypatch):
    """The closed-loop drill: with the policy daemon attached, a worker
    killed mid-campaign is quarantined, kick-respawned past the
    supervisor's backoff, probed clean and re-admitted with ZERO
    operator action; the campaign completes CLEAN (the retry meets the
    replacement) with answers bit-identical to the fault-free run, and
    the flight recorder shows the causal detect -> quarantine ->
    recover timeline."""
    import csv

    from distributed_oracle_search_tpu.control import daemon as dmod
    from distributed_oracle_search_tpu.control import maybe_daemon
    from distributed_oracle_search_tpu.obs import recorder as obs_rec

    def _answers(outdir):
        with open(os.path.join(outdir, "parts.csv")) as fh:
            rows = list(csv.reader(fh))
        keep = [rows[0].index(k) for k in
                ("expe", "n_expanded", "n_touched", "plen", "finished",
                 "size")]
        return [[r[i] for i in keep] for r in rows[1:]]

    faults.reset()
    state = str(tmp_path / "faults-state.json")
    # the kill is armed from the START (supervised workers inherit env
    # at spawn) but ``after=2`` skips worker 1's two fault-free batches
    # of the reference run — the cross-process state file keeps the
    # skip-count true across both campaigns and the respawn
    monkeypatch.setenv("DOS_FAULTS",
                       "kill-mid-batch;wid=1;times=1;after=2")
    monkeypatch.setenv("DOS_FAULTS_STATE", state)
    monkeypatch.setenv("DOS_SEND_TIMEOUT_S", "120")
    monkeypatch.setenv("DOS_RETRY_MAX", "1")
    monkeypatch.setenv("DOS_RETRY_BASE_S", "0.2")
    monkeypatch.setenv("DOS_RETRY_JITTER", "0")
    conf, conf_path = _conf(chaos_cluster, "conf-control.json",
                            diffs=["-", "-"])
    fifo_dir = str(tmp_path / "fifos")
    os.makedirs(fifo_dir)
    monkeypatch.setattr(
        pq, "command_fifo_path",
        lambda wid: os.path.join(fifo_dir, f"worker{wid}.fifo"))
    sup = WorkerSupervisor(conf, conf_path, fifo_dir=fifo_dir,
                           logdir=str(tmp_path / "logs"),
                           ping_interval_s=0.5, backoff_base_s=5.0,
                           backoff_cap_s=20.0, probe_timeout_s=5.0)
    rec = obs_rec.FlightRecorder(str(tmp_path / "tape"), flush_every=1)
    out0 = str(tmp_path / "artifacts-ref")
    out1 = str(tmp_path / "artifacts-healed")
    monkeypatch.setenv("DOS_CONTROL", "1")
    monkeypatch.setenv("DOS_CONTROL_INTERVAL_S", "0.25")
    monkeypatch.setenv("DOS_CONTROL_CLEAN_PROBES", "2")
    actions0 = dmod.M_ACTIONS.value
    quar0 = dmod.M_QUARANTINES.value
    readmit0 = dmod.M_READMISSIONS.value
    sup.start(wait_ready_s=300)
    daemon = None
    try:
        # fault-free reference run (fault budget skips its batches)
        rc = pq.main(["-c", conf_path, "--backend", "host",
                      "-o", out0])
        assert rc == pq.EXIT_CLEAN
        assert sup.workers[1].respawns == 0
        # arm the tape + the daemon, then the incident run
        obs_rec.set_recorder(rec)
        daemon = maybe_daemon(supervisor=sup)
        assert daemon is not None
        rc = pq.main(["-c", conf_path, "--backend", "host",
                      "-o", out1])
        assert rc == pq.EXIT_CLEAN               # retry met replacement
        assert not os.path.exists(os.path.join(out1, "degraded.json"))
        assert sup.workers[1].respawns == 1
        assert _answers(out0) == _answers(out1)  # bit-identical
        # the daemon acted (quarantine at least; kick rode along)
        assert dmod.M_ACTIONS.value > actions0
        assert dmod.M_QUARANTINES.value >= quar0 + 1
        # probation completes: the healed worker is re-admitted
        deadline = time.monotonic() + 60
        while (daemon.quarantine.quarantined()
               and time.monotonic() < deadline):
            time.sleep(0.25)
        assert daemon.quarantine.quarantined() == []
        assert dmod.M_READMISSIONS.value >= readmit0 + 1
    finally:
        if daemon is not None:
            daemon.stop()
        obs_rec.set_recorder(None)
        sup.stop()
        faults.reset()
    rec.close()
    records = obs_rec.replay(str(tmp_path / "tape"))
    kinds = [r["kind"] for r in records if r.get("rec") == "event"]
    assert "control_quarantine" in kinds
    assert "control_readmit" in kinds
    assert (kinds.index("control_quarantine")
            < kinds.index("control_readmit"))
    text = obs_rec.render_timeline(records)
    assert "control_quarantine" in text and "control_readmit" in text
