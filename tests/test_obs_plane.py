"""Live observability plane: sliding-window quantiles + exemplars, the
scrape endpoints against a live frontend, fleet snapshot/trace merging,
per-worker Prometheus labels, trace ids in log records, XLA program-cost
capture, and the bench-diff regression gate.

The endpoint round-trip test is the acceptance gate for the plane: a
running frontend with ``--obs-port``-style wiring must answer
``/metrics`` with live p50/p95/p99 gauges that move under load, and
``/statusz`` must report breaker + queue + replica state.
"""

import json
import logging
import os
import threading
import time
import urllib.request

import numpy as np
import pytest

from distributed_oracle_search_tpu.obs import device as obs_device
from distributed_oracle_search_tpu.obs import fleet as obs_fleet
from distributed_oracle_search_tpu.obs import metrics as obs_metrics
from distributed_oracle_search_tpu.obs import quantiles as obs_quantiles
from distributed_oracle_search_tpu.obs import trace as obs_trace
from distributed_oracle_search_tpu.obs.http import (
    ObsServer, resolve_obs_port, start_obs_server,
)
from distributed_oracle_search_tpu.obs.quantiles import (
    QuantileWindows, SlidingQuantiles,
)
from distributed_oracle_search_tpu.parallel.partition import (
    DistributionController,
)
from distributed_oracle_search_tpu.serving import (
    CallableDispatcher, ServeConfig, ServingFrontend,
)
from distributed_oracle_search_tpu.transport import resilience
from distributed_oracle_search_tpu.utils.log import (
    get_logger, set_verbosity, set_worker_id,
)


@pytest.fixture(autouse=True)
def _clean_obs_state():
    yield
    obs_trace.enable(False)
    obs_trace.clear()
    obs_trace.set_trace_id(None)


# ------------------------------------------------------ quantile windows

def _nearest_rank(data, q):
    data = sorted(data)
    import math
    return data[max(0, min(len(data) - 1, math.ceil(q * len(data)) - 1))]


def test_window_quantiles_match_sorted_reference():
    w = SlidingQuantiles(window_s=60, buckets=6, max_samples=10_000)
    rng = np.random.default_rng(3)
    vals = rng.gamma(2.0, 0.01, size=2000).tolist()
    for v in vals:
        w.observe(v, now=100.0)
    qs = w.quantiles(now=100.0)
    for q in (0.5, 0.95, 0.99):
        assert qs[q] == pytest.approx(_nearest_rank(vals, q))
    assert w.count(now=100.0) == 2000


def test_window_rotation_drops_old_samples():
    w = SlidingQuantiles(window_s=6, buckets=3, max_samples=100)
    w.observe(5.0, trace_id="old", now=0.5)     # bucket epoch 0
    w.observe(1.0, now=3.0)                     # bucket epoch 1
    qs = w.quantiles(now=4.0)
    assert qs[0.99] == 5.0                      # both in window
    # advance past the first bucket's window: only the 1.0 remains
    assert w.quantiles(now=7.9)[0.99] == 1.0
    assert w.worst(now=7.9) == (1.0, "")
    # advance past everything: empty window
    assert w.quantiles(now=60.0) is None
    assert w.worst(now=60.0) is None
    assert w.count(now=60.0) == 0


def test_window_bucket_reuse_after_wraparound():
    """A slot recycled after a full ring rotation must not resurrect
    its previous epoch's samples."""
    w = SlidingQuantiles(window_s=3, buckets=3, max_samples=10)
    w.observe(9.0, now=0.1)
    # same ring slot (epoch 0 and epoch 3 share index 0), later window
    w.observe(1.0, now=3.1)
    assert w.quantiles(now=3.5)[0.99] == 1.0


def test_window_reservoir_stays_bounded_and_worst_exact():
    w = SlidingQuantiles(window_s=60, buckets=1, max_samples=32)
    for i in range(1000):
        w.observe(float(i), trace_id=f"t{i}", now=1.0)
    assert len(w._ring[0].samples) == 32        # bounded memory
    assert w.count(now=1.0) == 1000             # true volume kept
    # the exemplar is exact even when its sample was reservoir-evicted
    assert w.worst(now=1.0) == (999.0, "t999")


def test_windows_registry_prometheus_and_snapshot():
    wins = QuantileWindows(window_s=60, buckets=6)
    wins.observe("x_seconds", 0.2, trace_id="deadbeef")
    wins.observe("x_seconds", 0.4, trace_id="cafe0001")
    text = wins.to_prometheus()
    assert '# TYPE x_seconds_window gauge' in text
    assert 'x_seconds_window{quantile="0.99"} 0.4' in text
    assert 'x_seconds_window_worst{trace_id="cafe0001"} 0.4' in text
    assert "x_seconds_window_count 2" in text
    snap = wins.snapshot()
    assert snap["x_seconds"]["count"] == 2
    assert snap["x_seconds"]["worst"]["trace_id"] == "cafe0001"
    assert snap["x_seconds"]["quantiles"]["p50"] == 0.2


# ---------------------------------------------- per-worker label folding

def test_prometheus_folds_worker_suffix_into_label():
    reg = obs_metrics.MetricsRegistry()
    reg.gauge("serve_queue_depth").set(7)
    reg.gauge("serve_queue_depth_w0").set(3)
    reg.gauge("serve_queue_depth_w12").set(4)
    reg.counter("other_total").inc()
    text = reg.to_prometheus()
    assert 'serve_queue_depth{worker="0"} 3' in text
    assert 'serve_queue_depth{worker="12"} 4' in text
    assert "serve_queue_depth_w0" not in text   # folded, not flat
    # exactly one TYPE line for the folded family
    assert text.count("# TYPE serve_queue_depth gauge") == 1
    # JSON snapshots keep the flat names (backward compatibility)
    snap = reg.snapshot()
    assert snap["gauges"]["serve_queue_depth_w0"] == 3
    assert "serve_queue_depth{" not in json.dumps(snap)


def test_prometheus_fold_skips_mixed_kind_collisions():
    reg = obs_metrics.MetricsRegistry()
    reg.counter("thing").inc(2)
    reg.gauge("thing_w1").set(5)    # would fold into a counter family
    text = reg.to_prometheus()
    assert "thing 2" in text
    assert "thing_w1 5" in text     # kept flat instead of mislabeled


# ----------------------------------------------------- atomic obs writes

def test_metrics_dump_and_trace_writes_are_atomic(tmp_path):
    reg = obs_metrics.MetricsRegistry()
    reg.counter("c_total").inc(3)
    path = str(tmp_path / "snap.json")
    reg.dump_json(path)
    assert json.load(open(path))["counters"]["c_total"] == 3
    sidecar = str(tmp_path / "q.trace")
    obs_trace.write_events(sidecar, [{"name": "a", "ts": 1}])
    assert obs_trace.read_events(sidecar) == [{"name": "a", "ts": 1}]
    merged = str(tmp_path / "trace.json")
    obs_trace.write_trace(merged, extra_events=[{"name": "b", "ts": 2}])
    assert {e["name"] for e in
            json.load(open(merged))["traceEvents"]} >= {"b"}
    # the atomic-write protocol leaves no temp debris behind
    assert not [p for p in os.listdir(tmp_path) if ".tmp." in p]


# ------------------------------------------------- trace ids in logging

def test_log_records_carry_trace_id_next_to_worker_id():
    set_verbosity(1)
    root = get_logger()
    records = []

    class Sink(logging.Handler):
        def emit(self, record):
            records.append(self.format(record))

    sink = Sink()
    sink.setFormatter(root.handlers[0].formatter)
    for f in root.handlers[0].filters:
        sink.addFilter(f)
    root.addHandler(sink)
    try:
        log = get_logger("plane.test")
        set_worker_id(4)
        obs_trace.set_trace_id("feedc0de")
        log.info("traced record")
        obs_trace.set_trace_id(None)
        log.info("untraced record")
    finally:
        root.removeHandler(sink)
        set_verbosity(0)
        set_worker_id(None)
    assert "[w4 t:feedc0de]" in records[0]
    assert "[w4]" in records[1] and "t:" not in records[1]


# --------------------------------------- endpoints against a live frontend

def _ok_dispatcher(delay_s=0.0):
    def fn(wid, q, rconf, diff):
        if delay_s:
            time.sleep(delay_s)
        n = len(q)
        return (np.arange(n, dtype=np.int64), np.ones(n, np.int64),
                np.ones(n, bool))
    return CallableDispatcher(fn)


def test_endpoints_roundtrip_against_live_frontend():
    """/metrics serves live quantiles that move under load; /healthz
    follows the frontend's lifecycle; /statusz reports breaker + queue
    + replica state."""
    dc = DistributionController("mod", 2, 2, 64, replication=2)
    registry = resilience.BreakerRegistry(enabled=True)
    fe = ServingFrontend(
        dc, _ok_dispatcher(),
        sconf=ServeConfig(queue_depth=32, max_batch=8, max_wait_ms=1.0,
                          cache_bytes=0),
        registry=registry, breaker_key=lambda wid: ("h", wid))
    fe.start()
    srv = start_obs_server(
        0,
        health_fn=lambda: {"ok": fe._started and not fe._closed},
        status_providers={"serving": fe.statusz,
                          "device_programs": obs_device.snapshot})
    assert srv is not None
    base = f"http://127.0.0.1:{srv.port}"
    try:
        n0 = obs_quantiles.WINDOWS.window(
            "serve_request_seconds").count()
        for i in range(24):
            assert fe.query(i % 64, (i + 1) % 64, timeout=30).ok
        body = urllib.request.urlopen(base + "/metrics").read().decode()
        assert 'serve_request_seconds_window{quantile="0.5"}' in body
        assert 'serve_request_seconds_window{quantile="0.99"}' in body
        assert "serve_request_seconds_window_count" in body
        count1 = obs_quantiles.WINDOWS.window(
            "serve_request_seconds").count()
        assert count1 >= n0 + 24            # the window moved under load
        # cumulative registry rides the same scrape
        assert "serve_requests_total" in body
        h = urllib.request.urlopen(base + "/healthz")
        assert h.status == 200 and json.loads(h.read())["ok"]
        sz = json.loads(
            urllib.request.urlopen(base + "/statusz").read())
        serving = sz["serving"]
        assert serving["serving"] is True
        assert serving["replication"] == 2
        # per-shard queue depth + replica chain (the failover map)
        assert set(serving["shards"]) == {"0", "1"}
        assert serving["shards"]["0"]["replicas"] == [0, 1]
        assert "queue_depth" in serving["shards"]["0"]
        assert "breakers" in serving and "open" in serving["breakers"]
        assert "hedge" in serving and "rate" in serving["hedge"]
    finally:
        fe.stop()
        srv.close()
        registry.shutdown()
    # stopped frontend -> healthz goes 503 (probe semantics, no parsing)
    srv2 = start_obs_server(
        0, health_fn=lambda: {"ok": fe._started and not fe._closed})
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv2.port}/healthz")
        assert ei.value.code == 503
    finally:
        srv2.close()


def test_resolve_obs_port_flag_env_and_off(monkeypatch):
    monkeypatch.delenv("DOS_OBS_PORT", raising=False)
    assert resolve_obs_port(None) == (None, "off")   # default: off
    assert resolve_obs_port(-1) == (None, "off")     # negative: off
    assert resolve_obs_port(9100) == (9100, "flag")
    monkeypatch.setenv("DOS_OBS_PORT", "9200")
    assert resolve_obs_port(None) == (9200, "env")
    assert resolve_obs_port(9100) == (9100, "flag")  # flag wins
    monkeypatch.setenv("DOS_OBS_PORT", "junk")
    assert resolve_obs_port(None) == (None, "off")   # malformed:
    # degrade


def test_env_port_bind_failure_degrades_flag_port_raises(monkeypatch):
    """An unbindable DOS_OBS_PORT (e.g. inherited by every process of
    a fleet) disables endpoints with a warning; an explicit flag for
    the same port still raises — the operator named it."""
    holder = start_obs_server(0)
    try:
        taken = holder.port
        monkeypatch.setenv("DOS_OBS_PORT", str(taken))
        assert start_obs_server(None) is None      # env: degrade
        with pytest.raises(OSError):
            start_obs_server(taken)                # flag: raise
    finally:
        holder.close()


def test_supervisor_spawn_strips_obs_port_from_child_env(monkeypatch):
    """Supervised workers must not inherit the supervisor's
    DOS_OBS_PORT — N children contending for one socket is a
    crash-respawn loop."""
    import subprocess
    from distributed_oracle_search_tpu.utils.config import ClusterConfig
    from distributed_oracle_search_tpu.worker.supervisor import (
        SupervisedWorker, WorkerSupervisor,
    )

    monkeypatch.setenv("DOS_OBS_PORT", "9300")
    captured = {}

    def fake_popen(cmd, **kw):
        captured.update(kw)
        raise RuntimeError("stop before spawning anything")

    monkeypatch.setattr(subprocess, "Popen", fake_popen)
    conf = ClusterConfig(workers=["localhost"]).validate()
    sup = WorkerSupervisor(conf, conf_path="conf.json")
    with pytest.raises(RuntimeError):
        sup._spawn_server(SupervisedWorker(0, "/tmp/x.fifo"))
    assert "DOS_OBS_PORT" not in captured["env"]


def test_bench_numbers_survives_null_tail(tmp_path):
    p = str(tmp_path / "BENCH_r09.json")
    json.dump({"parsed": None, "tail": None}, open(p, "w"))
    assert obs_fleet.bench_numbers(p) == {}      # degrade, not crash


def test_exemplar_trace_id_propagates_from_traced_dispatch():
    """With tracing on, every dispatched batch gets a trace id; the
    window's worst request exposes it — the p99 -> Perfetto link."""
    obs_quantiles.WINDOWS.reset()
    obs_trace.enable()
    seen_rconf_ids = []

    def fn(wid, q, rconf, diff):
        seen_rconf_ids.append(rconf.trace_id)
        n = len(q)
        return (np.zeros(n, np.int64), np.zeros(n, np.int64),
                np.ones(n, bool))

    dc = DistributionController("mod", 1, 1, 64)
    fe = ServingFrontend(
        dc, CallableDispatcher(fn),
        sconf=ServeConfig(queue_depth=16, max_batch=4, max_wait_ms=1.0,
                          cache_bytes=0))
    fe.start()
    try:
        for i in range(8):
            assert fe.query(i, i + 1, timeout=30).ok
    finally:
        fe.stop()
        obs_trace.enable(False)
    # the wire saw per-batch ids (the worker would capture spans under
    # them) ...
    assert seen_rconf_ids and all(seen_rconf_ids)
    worst = obs_quantiles.WINDOWS.window("serve_request_seconds").worst()
    # ... and the window's exemplar is one of those SAME ids
    assert worst is not None and worst[1] in set(seen_rconf_ids)


# ---------------------------------------------------------- fleet merge

def _snap(counters=None, gauges=None, hists=None):
    return {"counters": counters or {}, "gauges": gauges or {},
            "histograms": hists or {}}


def test_fleet_merge_sums_and_keeps_workers():
    h = {"count": 2, "sum": 0.5, "buckets": {"0.1": 1, "1.0": 2}}
    doc = obs_fleet.merge_snapshots([
        ("w0", _snap(counters={"a_total": 3}, gauges={"g": 1.0},
                     hists={"lat": h})),
        ("w1", _snap(counters={"a_total": 4, "b_total": 1},
                     gauges={"g": 2.0}, hists={"lat": h})),
    ])
    assert doc["n_workers"] == 2
    assert doc["fleet"]["counters"] == {"a_total": 7, "b_total": 1}
    assert doc["fleet"]["gauges"]["g"] == 3.0
    merged = doc["fleet"]["histograms"]["lat"]
    assert merged["count"] == 4 and merged["sum"] == 1.0
    assert merged["buckets"] == {"0.1": 2, "1.0": 4}
    assert set(doc["workers"]) == {"w0", "w1"}


def test_fleet_merge_disambiguates_conflicting_labels():
    doc = obs_fleet.merge_snapshots([
        ("w0", _snap(counters={"a": 1})),
        ("w0", _snap(counters={"a": 2})),
        ("w0", _snap(counters={"a": 4})),
    ])
    assert set(doc["workers"]) == {"w0", "w0#2", "w0#3"}
    # nothing was silently overwritten: the sum sees all three
    assert doc["fleet"]["counters"]["a"] == 7


def test_fleet_merge_histogram_bucket_mismatch_degrades():
    doc = obs_fleet.merge_snapshots([
        ("a", _snap(hists={"h": {"count": 1, "sum": 1.0,
                                 "buckets": {"1.0": 1}}})),
        ("b", _snap(hists={"h": {"count": 2, "sum": 2.0,
                                 "buckets": {"2.0": 2}}})),
    ])
    h = doc["fleet"]["histograms"]["h"]
    assert h["count"] == 3 and h["sum"] == 3.0
    assert h["buckets"] == {}      # count+sum kept, buckets dropped


def test_merge_traces_produces_one_perfetto_doc(tmp_path):
    head = str(tmp_path / "campaign.trace.json")
    json.dump({"traceEvents": [
        {"name": "head.send", "ts": 10, "ph": "X",
         "args": {"trace_id": "t1"}}]}, open(head, "w"))
    sidecar_dir = tmp_path / "nfs"
    sidecar_dir.mkdir()
    obs_trace.write_events(
        str(sidecar_dir / "q.host0.trace"),
        [{"name": "worker.search", "ts": 12, "ph": "X",
          "args": {"trace_id": "t1"}}])
    obs_trace.write_events(
        str(sidecar_dir / "q.host1.trace"),
        [{"name": "worker.search", "ts": 11, "ph": "X",
          "args": {"trace_id": "t2"}}])
    out = str(tmp_path / "merged.json")
    n = obs_fleet.merge_traces([head, str(sidecar_dir)], out)
    assert n == 3
    doc = json.load(open(out))
    assert "traceEvents" in doc and len(doc["traceEvents"]) == 3
    # sorted by ts so Perfetto streams it in timeline order
    assert [e["ts"] for e in doc["traceEvents"]] == [10, 11, 12]
    # head and worker spans of one batch still join on trace_id
    ids = {e["args"]["trace_id"] for e in doc["traceEvents"]}
    assert "t1" in ids and "t2" in ids


def test_dos_obs_cli_merge_commands(tmp_path, capsys):
    from distributed_oracle_search_tpu.cli.obs import main as obs_main

    s0 = str(tmp_path / "w0" / "obs_metrics.json")
    s1 = str(tmp_path / "w1" / "obs_metrics.json")
    for p, n in ((s0, 1), (s1, 2)):
        os.makedirs(os.path.dirname(p))
        json.dump(_snap(counters={"x_total": n}), open(p, "w"))
    out = str(tmp_path / "fleet_metrics.json")
    assert obs_main(["merge-metrics", "-o", out, s0, s1,
                     "--label", "w0", "--label", "w1"]) == 0
    doc = json.load(open(out))
    assert doc["fleet"]["counters"]["x_total"] == 3
    assert set(doc["workers"]) == {"w0", "w1"}


def test_top_renders_fleet_table_live_and_unreachable():
    # the REAL dos-serve shape: breakers nested under the "serving"
    # section (frontend.statusz), not a top-level provider
    srv = ObsServer(0, status_providers={
        "serving": lambda: {"serving": True, "shards": {
            "0": {"queue_depth": 3}, "1": {"queue_depth": 1}},
            "hedge": {"rate": 0.05},
            "breakers": {"open": 1, "breakers": {
                "('h', 0)": {"state": "open"},
                "('h', 1)": {"state": "closed"}}}},
    }).start()
    try:
        eps = {f"127.0.0.1:{srv.port}":
               obs_fleet.fetch_statusz(f"127.0.0.1:{srv.port}"),
               "127.0.0.1:1": obs_fleet.fetch_statusz("127.0.0.1:1",
                                                      timeout_s=0.2)}
        table = obs_fleet.render_top(eps)
    finally:
        srv.close()
    lines = table.splitlines()
    assert lines[0].startswith("endpoint")
    assert "queued" in lines[0] and "breakers_open" in lines[0]
    live = next(l for l in lines if f":{srv.port}" in l)
    assert " 4 " in live + " "      # 3 + 1 queued
    assert "UNREACHABLE" in table   # the dead endpoint is a row, not a
    # crash


# ------------------------------------------------------- device costs

def test_device_cost_capture_on_host_backend():
    import jax
    import jax.numpy as jnp

    obs_device.reset()

    @jax.jit
    def f(x):
        return (x @ x).sum()

    x = jnp.ones((32, 32), jnp.float32)
    entry = obs_device.capture("test/matmul32", f, x)
    assert entry is not None and entry["flops"] > 0
    assert entry["bytes_accessed"] > 0
    snap = obs_device.snapshot()
    assert snap["test/matmul32"]["flops"] == entry["flops"]
    # second capture under the same key is a no-op cache hit
    assert obs_device.capture("test/matmul32", f, x) == entry
    text = obs_device.to_prometheus()
    assert 'device_program_flops{program="test/matmul32"}' in text
    gauge = obs_metrics.REGISTRY.snapshot()["gauges"]
    assert gauge["device_programs_analyzed"] == 1
    obs_device.reset()


def test_engine_captures_cost_per_program_key(tmp_path):
    """ShardEngine's first call at a new program key lands one entry in
    the device-cost store (FLOPs/bytes for the compiled walk program)."""
    from distributed_oracle_search_tpu.data import (
        Graph, ensure_synth_dataset, read_scen,
    )
    from distributed_oracle_search_tpu.worker.build import (
        main as build_main,
    )
    from distributed_oracle_search_tpu.worker.engine import ShardEngine
    from distributed_oracle_search_tpu.transport.wire import RuntimeConfig

    obs_device.reset()
    datadir = str(tmp_path / "data")
    paths = ensure_synth_dataset(datadir, width=6, height=5,
                                 n_queries=16, seed=9)
    outdir = os.path.join(datadir, "index")
    build_main(["--input", paths["xy"], "--partmethod", "mod",
                "--partkey", "1", "--workerid", "0", "--maxworker", "1",
                "--outdir", outdir])
    g = Graph.from_xy(paths["xy"])
    dc = DistributionController("mod", 1, 1, g.n)
    eng = ShardEngine(g, dc, 0, outdir)
    queries = read_scen(paths["scen"])[:8]
    eng.answer(queries, RuntimeConfig())
    snap = obs_device.snapshot()
    assert len(snap) == 1
    (key, entry), = snap.items()
    assert key.startswith("table-search/q")
    assert entry.get("flops", 0) >= 0
    assert entry["bytes_accessed"] > 0
    # steady-state repeat at the same key adds nothing
    eng.answer(queries, RuntimeConfig())
    assert len(obs_device.snapshot()) == 1
    # the chunked deadline path captures the CHUNK-wide program it
    # actually ran (even under --extract, where the jit bookkeeping
    # key stays batch-wide), never a never-executed full-batch shape
    eng.astar_chunk = 4
    eng.answer(queries, RuntimeConfig(time=10**12, extract=True,
                                      k_moves=4))
    assert any(k.startswith("table-search/q4/")
               for k in obs_device.snapshot()), obs_device.snapshot()
    obs_device.reset()


# ----------------------------------------------------------- bench gate

def _bench_record(path, headline, value=100.0):
    json.dump({"parsed": {"metric": "scenario_queries_per_sec",
                          "value": value, "unit": "queries/s",
                          "headline": headline}}, open(path, "w"))


def test_bench_diff_gates_regressions(tmp_path):
    from distributed_oracle_search_tpu.cli.obs import main as obs_main

    old = str(tmp_path / "BENCH_r01.json")
    new = str(tmp_path / "BENCH_r02.json")
    _bench_record(old, {"road_resident_queries_per_sec": 60000,
                        "serve_p99_ms": 10.0, "devices": 1})
    # clean round: small wobble inside tolerance + an improvement
    _bench_record(new, {"road_resident_queries_per_sec": 55000,
                        "serve_p99_ms": 8.0, "devices": 1})
    assert obs_main(["bench-diff", "--dir", str(tmp_path)]) == 0
    # regression round: throughput halves
    _bench_record(new, {"road_resident_queries_per_sec": 25000,
                        "serve_p99_ms": 10.0, "devices": 1})
    assert obs_main(["bench-diff", "--dir", str(tmp_path)]) == 1
    # latency-like keys gate in the OTHER direction
    _bench_record(new, {"road_resident_queries_per_sec": 60000,
                        "serve_p99_ms": 25.0, "devices": 1})
    assert obs_main(["bench-diff", "--dir", str(tmp_path)]) == 1
    # per-key tolerance overrides the default
    assert obs_main(["bench-diff", "--dir", str(tmp_path),
                     "--key-tolerance", "serve_p99_ms=2.0"]) == 0
    # value key (the headline scenario rate) is compared too
    _bench_record(new, {"devices": 1}, value=10.0)
    assert obs_main(["bench-diff", "--dir", str(tmp_path)]) == 1


def test_bench_diff_with_fewer_than_two_records(tmp_path):
    from distributed_oracle_search_tpu.cli.obs import main as obs_main

    assert obs_main(["bench-diff", "--dir", str(tmp_path)]) == 0


def test_bench_diff_reads_the_repo_records():
    """The real BENCH_r*.json trajectory parses and compares (the gate
    must work on the driver's record format, not just synthetic
    fixtures)."""
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    records = obs_fleet.find_bench_records(here)
    parseable = [p for p in records if obs_fleet.bench_numbers(p)]
    if len(parseable) < 2:
        pytest.skip("repo carries fewer than two parseable records")
    out = obs_fleet.compare_bench(parseable[-2], parseable[-1],
                                  tolerance=1e9)  # parse check only
    assert out["checked"] > 0
