"""Gateway high availability: the leased endpoint registry (lease
lifecycle, schema compat, --join fid claims), client discovery +
failover with safe resubmission (dedup replay, exactly-once
accounting), per-request deadlines across failovers, the L2
admit-on-second-miss doorkeeper, the control-loop gateway sensor /
policy / actuator arm, the obs satellites (top columns, bench key
pins), and the kill + blackhole partition chaos drill.
"""

import collections
import os
import socket
import threading
import time
import types

import numpy as np
import pytest

from distributed_oracle_search_tpu.gateway import (
    DosClient, GatewayConfig, GatewayServer, GatewayTier,
    GATEWAY_REGISTRY_VERSION, GatewayLease, GatewayRegistry,
    GatewayRegistrySchemaError, RegistryState, live_endpoints,
    load_registry, save_registry,
)
from distributed_oracle_search_tpu.gateway import protocol
from distributed_oracle_search_tpu.gateway.client import pair_rows
from distributed_oracle_search_tpu.gateway.registry import registry_path
from distributed_oracle_search_tpu.obs import metrics as obs_metrics
from distributed_oracle_search_tpu.obs import recorder as obs_recorder
from distributed_oracle_search_tpu.parallel.partition import (
    DistributionController,
)
from distributed_oracle_search_tpu.serving import (
    CallableDispatcher, ServeConfig, ServingFrontend,
)
from distributed_oracle_search_tpu.testing import faults
from distributed_oracle_search_tpu.transport.frames import (
    FrameReader, FrameWriter, TransportError,
)
from distributed_oracle_search_tpu.utils.locks import OrderedLock

pytestmark = pytest.mark.gateway


def _counter(name: str) -> float:
    return obs_metrics.REGISTRY.snapshot()["counters"].get(name, 0)


# ------------------------------------------------------------- helpers

def _answer(wid, q, rconf, diff):
    q = np.asarray(q)
    return (np.abs(q[:, 0] - q[:, 1]).astype(np.int64),
            np.ones(len(q), np.int64), np.ones(len(q), bool))


def _frontend(n=64, fn=_answer, **kw):
    dc = DistributionController("mod", 1, 1, n)
    sconf = ServeConfig(**{"queue_depth": 1024, "max_wait_ms": 1.0,
                           "cache_bytes": 0, **kw}).validate()
    fe = ServingFrontend(dc, CallableDispatcher(fn), sconf=sconf)
    fe.start()
    return fe


def _gconf(tmp_path, **kw):
    return GatewayConfig(**{"replicas": 1,
                            "socket_dir": str(tmp_path),
                            "credit": 32,
                            "deadline_ms": 60_000.0, **kw}).validate()


# ----------------------------------------------------- lease lifecycle

def test_registry_lease_lifecycle(tmp_path):
    """Register -> live; let the lease age past TTL -> dead (no crash
    signal needed); renew resurrects; unregister leaves NEITHER list
    (clean drain is not a death)."""
    reg = GatewayRegistry(str(tmp_path), lease_s=5.0)
    reg.register(0, "/tmp/f0.sock", now=100.0)
    reg.register(1, "/tmp/f1.sock", now=100.0)
    assert [x.fid for x in reg.live(now=101.0)] == [0, 1]
    assert reg.dead(now=101.0) == []
    # f1 stops renewing: past the TTL it is dead, f0 renewed on time
    r0 = _counter("gateway_lease_renewals_total")
    assert reg.renew(0, "/tmp/f0.sock", now=104.0)
    assert _counter("gateway_lease_renewals_total") - r0 == 1
    assert [x.fid for x in reg.live(now=107.0)] == [0]
    assert [x.fid for x in reg.dead(now=107.0)] == [1]
    # renewing a vanished row reports False so the caller re-registers
    reg.unregister(1, "/tmp/f1.sock")
    assert not reg.renew(1, "/tmp/f1.sock", now=107.0)
    assert reg.dead(now=107.0) == []          # drained, not dead
    snap = reg.snapshot(now=107.5)
    assert [r["fid"] for r in snap["live"]] == [0]
    assert snap["dead"] == [] and snap["lease_s"] == 5.0
    assert snap["live"][0]["stale_s"] == pytest.approx(3.5)


def test_registry_claim_allocates_above_everything_seen(tmp_path):
    """--join claims stack: each block starts above every fid the
    registry has EVER seen (live or expired) so ids stay unique across
    respawns, and racing joiners can't collide."""
    reg = GatewayRegistry(str(tmp_path), lease_s=0.5)
    assert reg.claim(2, lambda f: f"/tmp/f{f}.sock", now=100.0) == 0
    assert reg.claim(2, lambda f: f"/tmp/f{f}.sock", now=100.0) == 2
    # even once the first block's leases expire, the ids stay burned
    assert reg.claim(1, lambda f: f"/tmp/f{f}.sock", now=200.0) == 4
    assert sorted(x.fid for x in reg.leases()) == [0, 1, 2, 3, 4]


# -------------------------------------------------------- schema compat

def test_registry_unknown_keys_and_older_version_tolerated(tmp_path):
    """Future fields ride along (row and top level); an OLDER file
    loads; only NEWER refuses — typed."""
    save_registry(str(tmp_path), RegistryState(
        leases=[{**GatewayLease(fid=3, endpoint="/tmp/f3.sock",
                                renewed=time.time(),
                                lease_s=60.0).to_dict(),
                 "shiny_future_field": {"nested": True}}],
        version=GATEWAY_REGISTRY_VERSION))
    with open(registry_path(str(tmp_path))) as f:
        import json
        doc = json.load(f)
    doc["future_top_level"] = [1, 2, 3]
    doc["version"] = 0                        # older build's file
    with open(registry_path(str(tmp_path)), "w") as f:
        json.dump(doc, f)
    state = load_registry(str(tmp_path))
    assert [x.fid for x in state.lease_objs()] == [3]
    assert live_endpoints(str(tmp_path)) == ["/tmp/f3.sock"]
    doc["version"] = GATEWAY_REGISTRY_VERSION + 1
    with open(registry_path(str(tmp_path)), "w") as f:
        json.dump(doc, f)
    with pytest.raises(GatewayRegistrySchemaError):
        load_registry(str(tmp_path))


def test_registry_newer_file_never_clobbered(tmp_path):
    """A writer facing a NEWER fleet's registry refuses (typed) instead
    of downgrading the file under the fleet's feet."""
    save_registry(str(tmp_path), RegistryState(
        leases=[], version=GATEWAY_REGISTRY_VERSION + 1))
    reg = GatewayRegistry(str(tmp_path), lease_s=5.0)
    with pytest.raises(GatewayRegistrySchemaError):
        reg.register(0, "/tmp/f0.sock")
    with open(registry_path(str(tmp_path))) as f:
        assert f'"version": {GATEWAY_REGISTRY_VERSION + 1}' in f.read()


def test_registry_torn_file_degrades_to_seeds(tmp_path):
    """A torn gateway.json: discovery degrades to the seed endpoints
    (never a crash), tolerant readers report empty, and the next
    writer resets the file."""
    with open(registry_path(str(tmp_path)), "w") as f:
        f.write('{"version": 1, "leases": [{"fid"')   # torn mid-write
    assert live_endpoints(str(tmp_path),
                          seeds=("/tmp/seed.sock",)) == ["/tmp/seed.sock"]
    reg = GatewayRegistry(str(tmp_path), lease_s=5.0)
    assert reg.leases() == []
    reg.register(0, "/tmp/f0.sock")           # reset + re-register
    assert [x.fid for x in reg.live()] == [0]
    # missing directory: seeds, quietly
    assert live_endpoints(str(tmp_path / "nope"),
                          seeds=("/tmp/seed.sock",)) == ["/tmp/seed.sock"]


# ------------------------------------------- discovery + live failover

def test_client_discovers_registry_and_fails_over(tmp_path):
    """DosClient(registry_dir=...) finds the tier with no seed
    endpoint; an abrupt frontend death (lease left to expire) moves it
    to the next live lease and the in-flight frames are resubmitted —
    zero lost, zero duplicates."""
    fes = [_frontend() for _ in range(2)]
    reg = GatewayRegistry(str(tmp_path / "reg"), lease_s=0.5)
    tier = GatewayTier([(fe, None) for fe in fes],
                       gconf=_gconf(tmp_path, replicas=2, lease_s=0.5),
                       registry=reg).start()
    c = None
    try:
        c = DosClient(registry_dir=reg.dir)
        assert c.endpoint == tier.endpoints[0]    # ascending fid
        batch = [(i % 11 + 1, (i * 7) % 13 + 1) for i in range(8)]
        want = [(("OK"), abs(s - t), 1, True, False)
                for s, t in batch]
        assert c.query_batch(batch, timeout=30.0) == want
        f0 = _counter("gateway_client_failovers_total")
        tier.servers[0].stop(graceful=False)      # crash stand-in
        assert c.query_batch(batch, timeout=30.0) == want
        assert c.endpoint == tier.endpoints[1]
        assert c.failovers >= 1 and c.unmatched == 0
        assert _counter("gateway_client_failovers_total") > f0
    finally:
        if c is not None:
            c.close()
        tier.stop()
        for fe in fes:
            fe.stop()


def test_multi_tier_join_serves_one_pool_bit_identically(tmp_path):
    """Two tiers --join one registry: claimed fid blocks are disjoint,
    discovery sees all replicas, and every replica answers the same
    pool identically."""
    fes = [_frontend() for _ in range(3)]
    regdir = str(tmp_path / "reg")
    reg_a = GatewayRegistry(regdir, lease_s=30.0)
    reg_b = GatewayRegistry(regdir, lease_s=30.0)
    gconf = _gconf(tmp_path, lease_s=30.0)
    base_a = reg_a.claim(2, endpoint_of=gconf.socket_of)
    base_b = reg_b.claim(1, endpoint_of=gconf.socket_of)
    assert (base_a, base_b) == (0, 2)
    tier_a = GatewayTier([(fes[0], None), (fes[1], None)], gconf=gconf,
                         registry=reg_a, fid_base=base_a).start()
    tier_b = GatewayTier([(fes[2], None)], gconf=gconf,
                         registry=reg_b, fid_base=base_b).start()
    clients = []
    try:
        eps = live_endpoints(regdir)
        assert eps == [gconf.socket_of(f) for f in (0, 1, 2)]
        batch = [(i % 17 + 1, (i * 5) % 23 + 1) for i in range(12)]
        clients = [DosClient(ep) for ep in eps]
        rows = [c.query_batch(batch, timeout=30.0) for c in clients]
        assert rows[0] == rows[1] == rows[2]
        assert sorted(c.frontend for c in clients) == [0, 1, 2]
    finally:
        for c in clients:
            c.close()
        tier_a.stop()
        tier_b.stop()
        for fe in fes:
            fe.stop()


# ------------------------------------------- resubmission dedup replay

def test_resubmit_dedup_replays_answered_frames(tmp_path):
    """An already-answered resubmitted frame gets the memoized reply
    REPLAYED: same bytes back, no second execution, requests/queries
    counters untouched. A genuinely unanswered resubmission (server
    never saw it) re-executes and is booked as a failover frame."""
    fe = _frontend()
    srv = GatewayServer(fe, fid=0, gconf=_gconf(tmp_path)).start()
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    try:
        sock.connect(srv.socket_path)
        reader, writer = FrameReader(sock), FrameWriter(sock)
        reader.read()                               # hello
        h, a = protocol.encode_pairs(5, [(3, 9), (1, 8)], cid="c" * 16)
        writer.send(h, a)
        r1 = reader.read()
        reqs0 = _counter("gateway_requests_total")
        qs0 = _counter("gateway_queries_total")
        d0 = _counter("gateway_resubmits_deduped_total")
        h2 = dict(h)
        h2["resubmit"] = True
        writer.send(h2, a)
        r2 = reader.read()
        assert pair_rows(r2) == pair_rows(r1)       # replayed verbatim
        assert _counter("gateway_resubmits_deduped_total") - d0 == 1
        assert _counter("gateway_requests_total") == reqs0
        assert _counter("gateway_queries_total") == qs0
        assert srv.statusz()["resubmits_deduped"] == 1
        # unanswered resubmission: this server never saw id 6 — it
        # executes (at-least-once) and books the failover frame
        f0 = _counter("gateway_failover_frames_total")
        h3, a3 = protocol.encode_pairs(6, [(3, 9)], cid="c" * 16)
        h3["resubmit"] = True
        writer.send(h3, a3)
        r3 = reader.read()
        assert pair_rows(r3)[0][1] == 6             # |3-9|, re-executed
        assert _counter("gateway_failover_frames_total") - f0 == 1
        assert srv.statusz()["failovers"] == 1
        assert "lease" not in srv.statusz()         # no registry wired
    finally:
        sock.close()
        srv.stop()
        fe.stop()


def test_clean_disconnect_purges_dedup_memo(tmp_path):
    """Memo hygiene: a TORN connection keeps its dedup entries (the
    client will reconnect and resubmit — the replay guarantee), but an
    orderly EOF at a frame boundary purges them (that client is done;
    nothing will ever resubmit those ids). ``/statusz`` exposes the
    memo occupancy so a leak is visible, not silent."""
    fe = _frontend()
    srv = GatewayServer(fe, fid=0, gconf=_gconf(tmp_path)).start()
    try:
        assert srv.statusz()["memo"] == {"entries": 0, "cap": 4096}
        s1 = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s1.connect(srv.socket_path)
        r1, w1 = FrameReader(s1), FrameWriter(s1)
        r1.read()                                   # hello
        h, a = protocol.encode_pairs(5, [(3, 9)], cid="a" * 16)
        w1.send(h, a)
        first = r1.read()
        assert srv.statusz()["memo"]["entries"] == 1
        # die mid-frame: half a header, then gone — a torn transport,
        # not a clean goodbye
        s1.sendall(b"\x00\x01")
        s1.close()
        # the entry survived: the reconnect replays it verbatim
        d0 = _counter("gateway_resubmits_deduped_total")
        s2 = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s2.connect(srv.socket_path)
        r2, w2 = FrameReader(s2), FrameWriter(s2)
        r2.read()                                   # hello
        h2 = dict(h)
        h2["resubmit"] = True
        w2.send(h2, a)
        assert pair_rows(r2.read()) == pair_rows(first)
        assert _counter("gateway_resubmits_deduped_total") - d0 == 1
        assert srv.statusz()["memo"]["entries"] == 1
        # orderly EOF at a frame boundary: the server forgets the
        # connection's ids once the writer drains
        s2.close()
        deadline = time.monotonic() + 10
        while (srv.statusz()["memo"]["entries"]
               and time.monotonic() < deadline):
            time.sleep(0.02)
        assert srv.statusz()["memo"]["entries"] == 0
    finally:
        srv.stop()
        fe.stop()


# -------------------------------------- per-request deadline from submit

def test_wait_honors_deadline_from_submit_time(tmp_path):
    """wait() never blocks past the frame's own deadline_ms measured
    from SUBMIT — even when called with a huge timeout — but a reply
    that already landed is returned past a spent deadline."""
    release = threading.Event()

    def slow(wid, q, rconf, diff):
        release.wait(30.0)
        return _answer(wid, q, rconf, diff)

    fe = _frontend(fn=slow)
    srv = GatewayServer(fe, fid=0, gconf=_gconf(tmp_path)).start()
    c = DosClient(srv.socket_path)
    try:
        fid = c.submit_pairs([(1, 5)], deadline_ms=300.0, timeout=5.0)
        t0 = time.monotonic()
        with pytest.raises(TimeoutError):
            c.wait(fid, timeout=30.0)
        assert time.monotonic() - t0 < 5.0          # deadline won
        release.set()
        fid2 = c.submit_pairs([(1, 5)], deadline_ms=250.0, timeout=5.0)
        time.sleep(0.4)                             # reply lands, then
        assert pair_rows(c.wait(fid2, timeout=5.0))[0][1] == 4
    finally:
        release.set()
        c.close()
        srv.stop()
        fe.stop()


# --------------------------------------------- L2 doorkeeper satellite

def test_l2_second_hit_doorkeeper():
    """second-hit: the first miss is ghosted + denied (booked), the
    second admits; the ghost list is bounded; the default policy
    admits everything and books nothing."""
    from distributed_oracle_search_tpu.worker.server import FifoServer

    ns = types.SimpleNamespace(
        _l2_admit="second-hit",
        l2=types.SimpleNamespace(max_bytes=1 << 20),
        _l2_seen=collections.OrderedDict(),
        _l2_seen_lock=OrderedLock("worker.FifoServer.l2_admit"))
    admit = FifoServer._l2_admit_key
    d0 = _counter("gateway_l2_admit_denied_total")
    assert admit(ns, ("k1", 0)) is False            # ghosted
    assert _counter("gateway_l2_admit_denied_total") - d0 == 1
    assert admit(ns, ("k1", 0)) is True             # second miss admits
    assert admit(ns, ("k1", 0)) is False            # ghost was consumed
    cap = max(1024, ns.l2.max_bytes // 256)
    for i in range(cap + 10):
        admit(ns, ("churn", i))
    assert len(ns._l2_seen) <= cap                  # bounded
    ns._l2_admit = "all"
    d1 = _counter("gateway_l2_admit_denied_total")
    assert admit(ns, ("anything", 1)) is True
    assert _counter("gateway_l2_admit_denied_total") == d1


def test_l2_admit_env_knob(monkeypatch):
    monkeypatch.setenv("DOS_GATEWAY_L2_ADMIT", "second-hit")
    assert GatewayConfig.from_env().l2_admit == "second-hit"
    monkeypatch.setenv("DOS_GATEWAY_L2_ADMIT", "zorp")
    assert GatewayConfig.from_env().l2_admit == "all"   # degrades
    with pytest.raises(ValueError):
        GatewayConfig(l2_admit="zorp").validate()       # explicit raises


# ------------------------------------------------ control-loop gateway arm

def test_signal_reader_gateway_sensor():
    from distributed_oracle_search_tpu.control.signals import SignalReader

    reg = types.SimpleNamespace(snapshot=lambda now=None: {
        "lease_s": 1.0,
        "live": [{"fid": 0, "stale_s": 0.2}],
        "dead": [{"fid": 2, "stale_s": 7.5}, {"fid": 1, "stale_s": 3.0}],
    })
    sig = SignalReader(gateway=reg).read(now=1.0)
    assert sig.gateway_live == 1
    assert sig.gateway_dead == (1, 2)
    assert sig.gateway_lease_stale_s == {0: 0.2, 1: 3.0, 2: 7.5}
    # no registry wired / a broken one: the sensor stays quiet
    sig = SignalReader().read(now=1.0)
    assert sig.gateway_live is None and sig.gateway_dead == ()
    boom = types.SimpleNamespace(
        snapshot=lambda now=None: (_ for _ in ()).throw(OSError("x")))
    sig = SignalReader(gateway=boom).read(now=1.0)
    assert sig.gateway_live is None


def test_gateway_watch_cooldown():
    from distributed_oracle_search_tpu.control.policy import GatewayWatch
    from distributed_oracle_search_tpu.control.signals import (
        ControlSignals,
    )

    gw = GatewayWatch(cooldown_s=10.0)
    sig = ControlSignals(now=0.0, gateway_dead=(1,),
                         gateway_lease_stale_s={1: 2.5})
    assert gw.decide(sig, 0.0) == [
        ("kick", 1, "endpoint lease stale 2.5s")]
    assert gw.decide(sig, 5.0) == []            # cooldown holds
    assert gw.decide(sig, 11.0) == [            # one kick per window
        ("kick", 1, "endpoint lease stale 2.5s")]
    assert gw.decide(ControlSignals(now=12.0), 12.0) == []


def test_actuator_kick_frontend_prefers_respawn_fn():
    from distributed_oracle_search_tpu.control.actuators import Actuators

    kicked = []
    a = Actuators(gateway_respawn_fn=kicked.append)
    a.kick_frontend(3)
    assert kicked == [3]
    sup = types.SimpleNamespace(kick=kicked.append)
    a = Actuators(supervisor=sup)
    a.kick_frontend(4)
    assert kicked == [3, 4]
    with pytest.raises(RuntimeError):
        Actuators().kick_frontend(5)


# ------------------------------------------------------- obs satellites

def test_fleet_columns_render_ha_and_blanks():
    from distributed_oracle_search_tpu.obs import fleet as obs_fleet

    row = obs_fleet._summarize({
        "gateway": {"replicas": 2, "peers": 5, "lease_age_s": 0.42,
                    "failovers": 3},
    })
    assert row["peers"] == 5 and row["lease s"] == 0.4
    assert row["failover"] == 3
    # pre-HA statusz and garbage values render blanks, never a crash
    old = obs_fleet._summarize({"gateway": {"replicas": 2}})
    assert "peers" not in old and "lease s" not in old
    weird = obs_fleet._summarize({
        "gateway": {"peers": "many", "lease_age_s": None,
                    "failovers": True},
    })
    assert ("peers" not in weird and "lease s" not in weird
            and "failover" not in weird)


def test_bench_gateway_ha_keys_pinned():
    """The chaos-drill bench keys gate at ZERO tolerance for lost and
    duplicated requests — a regression there is a correctness bug, not
    a perf drift."""
    from distributed_oracle_search_tpu.obs import fleet as obs_fleet

    for key in ("gateway_ha_lost_requests",
                "gateway_ha_duplicate_answers",
                "gateway_ha_failover_p99_ms"):
        assert obs_fleet._KEY_DIRECTIONS.get(key) == "lower", key
        assert key in obs_fleet._KEY_TOLERANCES, key
    assert obs_fleet._KEY_TOLERANCES["gateway_ha_lost_requests"] == 0.0
    assert obs_fleet._KEY_TOLERANCES[
        "gateway_ha_duplicate_answers"] == 0.0


# ---------------------------------------------------------- chaos drill

def test_chaos_drill_kill_and_blackhole(tmp_path, monkeypatch):
    """The PR's acceptance drill: one frontend killed abruptly (lease
    left to expire) and a second blackholed (accepts frames, never
    replies) mid open-loop burst. Zero lost accepted requests, zero
    duplicate answers, rows bit-identical to the fault-free run, the
    control loop kicks a respawn for the dead frontend, and the tape
    replays the causal chain register -> failover -> kick ->
    re-register."""
    from distributed_oracle_search_tpu.control.config import ControlConfig
    from distributed_oracle_search_tpu.control.daemon import ControlDaemon

    rec = obs_recorder.FlightRecorder(str(tmp_path / "tape"),
                                      flush_every=1)
    obs_recorder.set_recorder(rec)
    faults.reset()
    fes = [_frontend() for _ in range(3)]
    reg = GatewayRegistry(str(tmp_path / "reg"), lease_s=0.4)
    gconf = _gconf(tmp_path, replicas=3, lease_s=0.4)
    tier = GatewayTier([(fe, None) for fe in fes], gconf=gconf,
                       registry=reg).start()
    respawned = []

    def respawn(fid):
        srv = GatewayServer(fes[fid], fid=fid, gconf=gconf,
                            registry=reg).start()
        respawned.append(srv)

    d = ControlDaemon(
        ControlConfig(enabled=True, cooldown_s=60.0, budget=4),
        gateway=reg, gateway_respawn_fn=respawn)
    batches = [[(i % 11 + 1, (i * 7 + b) % 13 + 1) for i in range(8)]
               for b in range(12)]
    base = None
    client = None
    try:
        base = DosClient(tier.endpoints[2])       # fault-free lane
        want = [base.query_batch(b, timeout=30.0) for b in batches]

        client = DosClient(registry_dir=reg.dir)
        fids = [client.submit_pairs(b, timeout=30.0)
                for b in batches[:4]]
        tier.servers[0].stop(graceful=False)      # CRASH: lease ages
        deadline = time.monotonic() + 5.0
        while client.failovers == 0 and time.monotonic() < deadline:
            time.sleep(0.01)                      # reader notices EOF
        fids += [client.submit_pairs(b, timeout=30.0)
                 for b in batches[4:8]]
        # half-open partition on the frontend we failed over to
        monkeypatch.setenv("DOS_FAULTS", "blackhole-conn;wid=1;times=inf")
        faults.reset()
        fids += [client.submit_pairs(b, timeout=30.0)
                 for b in batches[8:]]
        got = []
        for fid in fids:
            give_up = time.monotonic() + 30.0
            while True:
                d.tick()                          # the healing loop
                try:
                    got.append(pair_rows(client.wait(fid, timeout=1.0)))
                    break
                except TimeoutError:
                    # wait already failed the client over + resubmitted;
                    # the re-wait collects the (replayed) answer
                    assert time.monotonic() < give_up, f"lost frame {fid}"
        monkeypatch.delenv("DOS_FAULTS")
        faults.reset()
        assert got == want                        # bit-identical, 0 lost
        assert client.unmatched == 0              # 0 duplicate answers
        assert client.failovers >= 2              # kill + blackhole
        # the dead frontend was kicked and re-registered
        deadline = time.monotonic() + 5.0
        while (not any(r["fid"] == 0 for r in reg.snapshot()["live"])
               and time.monotonic() < deadline):
            d.tick()
            time.sleep(0.05)
        assert any(r["fid"] == 0 for r in reg.snapshot()["live"])
        assert len(respawned) == 1
    finally:
        monkeypatch.delenv("DOS_FAULTS", raising=False)
        faults.reset()
        if client is not None:
            client.close()
        if base is not None:
            base.close()
        for srv in respawned:
            srv.stop()
        tier.stop()
        for fe in fes:
            fe.stop()
        obs_recorder.set_recorder(None)
    rec.close()
    # dos-obs replay renders the causal incident timeline
    records = obs_recorder.replay(str(tmp_path / "tape"))
    kinds = [r["kind"] for r in records if r.get("rec") == "event"]
    assert "fault" in kinds                       # the blackhole firing
    first_reg = kinds.index("gateway_register")
    failover = kinds.index("gateway_failover")
    kick = kinds.index("control_gateway_kick")
    re_reg = len(kinds) - 1 - kinds[::-1].index("gateway_register")
    # the kick event books the COMPLETED decision, so the respawn's
    # re-register (emitted inside the actuator) lands just before it
    assert first_reg < failover < re_reg
    assert failover < kick
    text = obs_recorder.render_timeline(records)
    assert "gateway_failover" in text and "control_gateway_kick" in text
