"""Observability layer: metrics registry, span tracing, worker-id logs,
server failure-path counters, and the end-to-end traced campaign.

The integration test at the bottom is the acceptance gate for the obs
subsystem: a host-mode campaign through real ``FifoServer`` instances
with ``--trace`` and ``--metrics-dump`` set must produce a Chrome trace
whose head-side and worker-side spans share a ``trace_id``, and a
metrics snapshot carrying the serve-loop health counters and per-phase
histograms.
"""

import json
import logging
import os
import threading
import time

import numpy as np
import pytest

from distributed_oracle_search_tpu.obs import metrics as obs_metrics
from distributed_oracle_search_tpu.obs import trace as obs_trace
from distributed_oracle_search_tpu.obs.metrics import MetricsRegistry
from distributed_oracle_search_tpu.transport.wire import RuntimeConfig
from distributed_oracle_search_tpu.utils.log import (
    get_logger, set_verbosity, set_worker_id,
)
from distributed_oracle_search_tpu.utils.timer import Timer


@pytest.fixture(autouse=True)
def _clean_trace_state():
    """Tracing is process-global: leave it as we found it."""
    yield
    obs_trace.enable(False)
    obs_trace.clear()
    obs_trace.set_trace_id(None)


# ------------------------------------------------------------------ metrics

def test_counter_gauge_histogram_snapshot():
    reg = MetricsRegistry()
    reg.counter("c_total").inc()
    reg.counter("c_total").inc(4)
    reg.gauge("g").set(2.5)
    reg.histogram("h_seconds").observe(0.005)
    reg.histogram("h_seconds").observe(2.0)
    snap = reg.snapshot()
    assert set(snap) == {"counters", "gauges", "histograms"}
    assert snap["counters"]["c_total"] == 5
    assert snap["gauges"]["g"] == 2.5
    h = snap["histograms"]["h_seconds"]
    assert h["count"] == 2 and abs(h["sum"] - 2.005) < 1e-9
    # buckets are cumulative (Prometheus semantics)
    assert h["buckets"]["0.01"] == 1
    assert h["buckets"]["5.0"] == 2


def test_histogram_overflow_lands_in_inf_only():
    reg = MetricsRegistry()
    h = reg.histogram("h", buckets=(0.1, 1.0))
    h.observe(50.0)
    d = h.as_dict()
    assert d["count"] == 1 and all(v == 0 for v in d["buckets"].values())


def test_registry_reset_zeroes_in_place_keeping_handles():
    """reset() must not orphan handles held from import time: after a
    reset, existing Counter/Histogram objects keep feeding snapshots."""
    reg = MetricsRegistry()
    c = reg.counter("kept_total")
    h = reg.histogram("kept_seconds")
    c.inc(5)
    h.observe(1.0)
    reg.reset()
    snap = reg.snapshot()
    assert snap["counters"]["kept_total"] == 0
    assert snap["histograms"]["kept_seconds"]["count"] == 0
    c.inc()                     # the ORIGINAL handle, post-reset
    h.observe(0.5)
    snap = reg.snapshot()
    assert snap["counters"]["kept_total"] == 1
    assert snap["histograms"]["kept_seconds"]["count"] == 1


def test_registry_get_or_create_is_idempotent_and_kind_checked():
    reg = MetricsRegistry()
    assert reg.counter("x") is reg.counter("x")
    with pytest.raises(TypeError):
        reg.gauge("x")


def test_prometheus_exposition():
    reg = MetricsRegistry()
    reg.counter("frames_total", help="frames").inc(3)
    reg.histogram("lat_seconds", buckets=(0.1, 1.0)).observe(0.05)
    text = reg.to_prometheus()
    assert "# TYPE frames_total counter" in text
    assert "frames_total 3" in text
    assert '# HELP frames_total frames' in text
    assert 'lat_seconds_bucket{le="0.1"} 1' in text
    assert 'lat_seconds_bucket{le="+Inf"} 1' in text
    assert "lat_seconds_count 1" in text


def test_registry_dump_json_is_valid(tmp_path):
    reg = MetricsRegistry()
    reg.counter("c").inc()
    path = str(tmp_path / "snap.json")
    reg.dump_json(path)
    snap = json.load(open(path))
    assert set(snap) == {"counters", "gauges", "histograms"}
    assert snap["counters"]["c"] == 1


def test_counter_thread_safety():
    reg = MetricsRegistry()
    c = reg.counter("n")
    threads = [threading.Thread(target=lambda: [c.inc() for _ in
                                                range(1000)])
               for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 8000


# -------------------------------------------------------------------- trace

def test_span_disabled_is_shared_noop():
    assert not obs_trace.enabled()
    s1 = obs_trace.span("a", k=1)
    s2 = obs_trace.span("b")
    assert s1 is s2                 # one shared null object, no allocs
    with s1:
        pass
    obs_trace.add_span("c", 0.5)
    assert obs_trace.events() == []


def test_span_records_chrome_events_with_trace_id():
    obs_trace.enable()
    obs_trace.set_trace_id("tid-1")
    with obs_trace.span("outer", wid=3):
        with obs_trace.span("inner"):
            time.sleep(0.002)
    obs_trace.add_span("measured", 0.25, wid=3)
    evs = obs_trace.events()
    assert [e["name"] for e in evs] == ["inner", "outer", "measured"]
    for e in evs:
        assert e["ph"] == "X" and e["pid"] == os.getpid()
        assert e["args"]["trace_id"] == "tid-1"
    inner, outer, measured = evs
    assert inner["dur"] >= 2000          # us
    assert outer["dur"] >= inner["dur"]
    assert measured["dur"] == 250000
    # explicit trace_id overrides the thread's
    with obs_trace.span("explicit", trace_id="other"):
        pass
    assert obs_trace.events()[-1]["args"]["trace_id"] == "other"


def test_capture_diverts_this_threads_spans():
    with obs_trace.capture("batch-7") as cap:
        with obs_trace.span("worker.search"):
            pass
    assert len(cap.events) == 1
    assert cap.events[0]["args"]["trace_id"] == "batch-7"
    # nothing leaked to the global buffer, and tracing stayed off
    assert obs_trace.events() == []
    assert not obs_trace.enabled()
    assert obs_trace.current_trace_id() is None


def test_capture_does_not_steal_other_threads_events():
    obs_trace.enable()
    release = threading.Event()
    started = threading.Event()

    def other():
        started.wait(5)
        with obs_trace.span("other.thread"):
            pass
        release.set()

    th = threading.Thread(target=other)
    th.start()
    with obs_trace.capture("mine") as cap:
        started.set()
        release.wait(5)
        with obs_trace.span("mine.span"):
            pass
    th.join()
    assert [e["name"] for e in cap.events] == ["mine.span"]
    assert [e["name"] for e in obs_trace.events()] == ["other.thread"]


def test_write_trace_and_sidecar_roundtrip(tmp_path):
    obs_trace.enable()
    with obs_trace.span("head.send", trace_id="t"):
        pass
    sidecar = str(tmp_path / "q.trace")
    obs_trace.write_events(sidecar, [{"name": "worker.search", "ph": "X",
                                      "ts": 1, "dur": 2, "pid": 9,
                                      "tid": 9, "args": {"trace_id": "t"}}])
    obs_trace.ingest(obs_trace.read_events(sidecar))
    out = str(tmp_path / "trace.json")
    obs_trace.write_trace(out)
    doc = json.load(open(out))
    names = {e["name"] for e in doc["traceEvents"]}
    assert names == {"head.send", "worker.search"}


def test_trace_sidecar_path_convention():
    assert obs_trace.trace_sidecar_for("/nfs/query.host0") == \
        "/nfs/query.host0.trace"


# -------------------------------------------------------------------- timer

def test_timer_elapsed_works_mid_block():
    with Timer() as t:
        assert t.interval == 0.0          # documented mid-block reading
        time.sleep(0.02)
        mid = t.elapsed
        assert mid >= 0.015
    assert t.interval >= mid              # exit keeps interval semantics
    assert t.elapsed == t.interval        # after exit they agree


def test_timer_elapsed_before_any_block():
    t = Timer(1.5)
    assert t.elapsed == 1.5


# ------------------------------------------------------------------ logging

def test_log_records_carry_worker_id():
    set_verbosity(1)
    root = get_logger()
    records = []

    class Sink(logging.Handler):
        def emit(self, record):
            records.append(self.format(record))

    sink = Sink()
    sink.setFormatter(root.handlers[0].formatter)
    for f in root.handlers[0].filters:
        sink.addFilter(f)
    root.addHandler(sink)
    try:
        log = get_logger("worker.test")
        set_worker_id(3)
        log.info("from the worker")
        set_worker_id(None)
        log.info("from the head")
        in_thread = []

        def other():
            set_worker_id(5)
            log.info("thread-local")
            in_thread.append(True)
        th = threading.Thread(target=other)
        th.start()
        th.join()
    finally:
        root.removeHandler(sink)
        set_verbosity(0)
    assert "[w3]" in records[0]
    assert "[w-]" in records[1]
    assert "[w5]" in records[2] and in_thread


# ------------------------------------------- server failure-path counters

from distributed_oracle_search_tpu.worker import server as server_mod
from distributed_oracle_search_tpu.worker.server import FifoServer


def _bare_server(tmp_path, name, frame_timeout=0.3):
    """A FifoServer with no engine/index: enough for every failure path
    (only a successfully decoded request ever touches the engine)."""
    s = FifoServer.__new__(FifoServer)
    s.wid = 0
    s.command_fifo = str(tmp_path / f"{name}.fifo")
    s.FRAME_TIMEOUT_S = frame_timeout
    return s


def _serve(server):
    th = threading.Thread(target=server.serve_forever, daemon=True)
    th.start()
    for _ in range(100):
        if os.path.exists(server.command_fifo):
            break
        time.sleep(0.02)
    else:
        pytest.fail("server fifo never appeared")
    return th


def _counters():
    return {k: v.value for k, v in [
        ("frames", server_mod.M_FRAMES),
        ("malformed", server_mod.M_MALFORMED),
        ("half", server_mod.M_HALF),
        ("dropped", server_mod.M_DROPPED),
        ("replies", server_mod.M_REPLIES),
    ]}


def test_server_counts_malformed_stray_line(tmp_path):
    s = _bare_server(tmp_path, "stray")
    answer = str(tmp_path / "stray.answer")
    os.mkfifo(answer)
    before = _counters()
    th = _serve(s)
    try:
        with open(s.command_fifo, "w") as f:
            f.write(f"this is not a frame {answer} -\n")
        with open(answer) as f:           # server FAILs the named fifo
            assert f.readline().strip() == "FAIL"
    finally:
        server_mod.stop_server(s.command_fifo)
        th.join(timeout=10)
    after = _counters()
    assert after["frames"] == before["frames"] + 1
    assert after["malformed"] == before["malformed"] + 1


def test_server_counts_undecodable_request(tmp_path):
    s = _bare_server(tmp_path, "badreq")
    answer = str(tmp_path / "badreq.answer")
    os.mkfifo(answer)
    before = _counters()
    th = _serve(s)
    try:
        # valid JSON config line, but line 2 has 2 tokens instead of 3
        with open(s.command_fifo, "w") as f:
            f.write('{"itrs": 1}\n' + f"queryfile {answer}\n")
        with open(answer) as f:
            assert f.readline().strip() == "FAIL"
    finally:
        server_mod.stop_server(s.command_fifo)
        th.join(timeout=10)
    after = _counters()
    assert after["malformed"] == before["malformed"] + 1


def test_server_counts_config_only_half_frame(tmp_path):
    s = _bare_server(tmp_path, "cfgonly")
    before = _counters()
    th = _serve(s)
    try:
        # two consecutive config lines: the second is pushed back as the
        # next frame's start, the first counts as a half frame; the stop
        # token then pairs with the pushed-back line and still wins
        with open(s.command_fifo, "w") as f:
            f.write('{"itrs": 1}\n{"itrs": 2}\n')
        time.sleep(0.2)
    finally:
        server_mod.stop_server(s.command_fifo)
        th.join(timeout=10)
    after = _counters()
    assert after["half"] == before["half"] + 1


def test_server_counts_timed_out_half_frame(tmp_path):
    s = _bare_server(tmp_path, "halftime", frame_timeout=0.15)
    before = _counters()
    th = _serve(s)
    try:
        with open(s.command_fifo, "w") as f:
            f.write('{"itrs": 1}\n')      # line 2 never arrives
        time.sleep(0.5)                   # > FRAME_TIMEOUT_S
    finally:
        server_mod.stop_server(s.command_fifo)
        th.join(timeout=10)
    after = _counters()
    assert after["half"] == before["half"] + 1


def test_server_counts_dropped_reply_when_reader_never_opens(tmp_path):
    s = _bare_server(tmp_path, "drop")
    fifo = str(tmp_path / "nobody-reads.fifo")
    os.mkfifo(fifo)
    before = _counters()
    s._reply(fifo, "1,2\n", deadline_s=0.15)      # no reader -> dropped
    after = _counters()
    assert after["dropped"] == before["dropped"] + 1
    assert after["replies"] == before["replies"]


def test_server_reply_wait_histogram_on_success(tmp_path):
    s = _bare_server(tmp_path, "ok")
    fifo = str(tmp_path / "read.fifo")
    os.mkfifo(fifo)
    got = []

    def reader():
        with open(fifo) as f:
            got.append(f.readline())
    th = threading.Thread(target=reader)
    th.start()
    before_count = server_mod.M_REPLY_WAIT.count
    before = _counters()
    s._reply(fifo, "payload\n", deadline_s=5.0)
    th.join(timeout=5)
    assert got == ["payload\n"]
    assert server_mod.M_REPLY_WAIT.count == before_count + 1
    assert _counters()["replies"] == before["replies"] + 1


# ----------------------------------------------- wire compat (trace_id)

def test_runtime_config_trace_id_roundtrip_and_old_peer_compat():
    rc = RuntimeConfig(trace_id="abc123/w0.d0")
    # new peer: preserved through the wire
    assert RuntimeConfig.from_json(rc.to_json()).trace_id == "abc123/w0.d0"
    # old-schema peer line (no trace_id key): default applies
    old = json.loads(rc.to_json())
    del old["trace_id"]
    assert RuntimeConfig.from_json(json.dumps(old)).trace_id == ""
    # symmetric: an old peer's from_json filter would drop the key, and
    # OUR filter drops keys from a future schema without complaint
    future = dict(json.loads(rc.to_json()), some_future_knob=7)
    back = RuntimeConfig.from_json(json.dumps(future))
    assert back.trace_id == "abc123/w0.d0"


# ------------------------------------------------- end-to-end integration

@pytest.fixture(scope="module")
def obs_cluster(tmp_path_factory):
    """Small built index + host conf (the test_drivers pattern, sized
    down: the obs integration test needs a real FIFO campaign, not a
    big one)."""
    from distributed_oracle_search_tpu.data import (
        Graph, ensure_synth_dataset,
    )
    from distributed_oracle_search_tpu.models.cpd import (
        write_index_manifest,
    )
    from distributed_oracle_search_tpu.parallel.partition import (
        DistributionController,
    )
    from distributed_oracle_search_tpu.utils.config import ClusterConfig
    from distributed_oracle_search_tpu.worker.build import main as build_main

    datadir = str(tmp_path_factory.mktemp("obsdata"))
    paths = ensure_synth_dataset(datadir, width=8, height=6, n_queries=48,
                                 seed=5)
    conf = ClusterConfig(
        workers=["localhost", "localhost"],
        partmethod="mod", partkey=2,
        outdir=os.path.join(datadir, "index"),
        xy_file=paths["xy"], scenfile=paths["scen"],
        diffs=["-", paths["diff"]],
        nfs=datadir,
    ).validate()
    for wid in range(conf.maxworker):
        build_main(["--input", conf.xy_file, "--partmethod",
                    conf.partmethod, "--partkey", str(conf.partkey),
                    "--workerid", str(wid),
                    "--maxworker", str(conf.maxworker),
                    "--outdir", conf.outdir])
    g = Graph.from_xy(conf.xy_file)
    dc = DistributionController(conf.partmethod, conf.partkey,
                                conf.maxworker, g.n)
    write_index_manifest(conf.outdir, dc)
    conf_path = os.path.join(datadir, "conf.json")
    conf.save(conf_path)
    return conf, conf_path


def test_engine_jit_split_keys_on_program_shape(obs_cluster):
    """The compile/steady split must key on the compiled program's
    shape: under a time budget the chunked table-search path reuses one
    chunk-wide program across batch sizes, so a bigger qpad alone must
    NOT book a steady-state batch as a compile."""
    from distributed_oracle_search_tpu.data import Graph, read_scen
    from distributed_oracle_search_tpu.parallel.partition import (
        DistributionController,
    )
    from distributed_oracle_search_tpu.worker.engine import (
        M_JIT, M_SEARCH, ShardEngine,
    )

    conf, _ = obs_cluster
    g = Graph.from_xy(conf.xy_file)
    dc = DistributionController("mod", 2, 2, g.n)
    eng = ShardEngine(g, dc, 0, conf.outdir)
    eng.astar_chunk = 4
    queries = read_scen(conf.scenfile)
    mine = queries[dc.worker_of(queries[:, 1]) == 0]
    assert len(mine) >= 12
    rc = RuntimeConfig(time=10**12)       # deadline set, never binding
    j0, s0 = M_JIT.count, M_SEARCH.count
    eng.answer(mine[:6], rc)    # qpad 8 > chunk 4: chunked, compiles
    eng.answer(mine[:12], rc)   # qpad 16: same chunk-wide program
    assert M_JIT.count - j0 == 1
    assert M_SEARCH.count - s0 == 1
    # astar never consumes k_moves (reference args.py:28): a new value
    # on a resident server is NOT a recompile
    eng_a = ShardEngine(g, dc, 0, conf.outdir, alg="astar")
    eng_a.astar_chunk = 4
    j0, s0 = M_JIT.count, M_SEARCH.count
    eng_a.answer(mine[:6], RuntimeConfig(k_moves=-1))
    eng_a.answer(mine[:6], RuntimeConfig(k_moves=8))
    assert M_JIT.count - j0 == 1
    assert M_SEARCH.count - s0 == 1


def test_traced_campaign_end_to_end(obs_cluster, tmp_path, monkeypatch):
    """--trace + --metrics-dump through a real FifoServer campaign:
    merged trace joins head and worker spans on one trace_id; the
    snapshot carries the health counters and phase histograms; the
    artifact dir gains obs_metrics.json next to parts.csv."""
    from distributed_oracle_search_tpu.cli import process_query as pq
    from distributed_oracle_search_tpu.worker import (
        FifoServer, stop_server,
    )

    conf, conf_path = obs_cluster
    fifos = {wid: str(tmp_path / f"worker{wid}.fifo")
             for wid in range(conf.maxworker)}
    monkeypatch.setattr(pq, "command_fifo_path", lambda wid: fifos[wid])
    servers = [FifoServer(conf, wid, command_fifo=fifos[wid])
               for wid in range(conf.maxworker)]
    threads = [threading.Thread(target=s.serve_forever, daemon=True)
               for s in servers]
    for t in threads:
        t.start()
    trace_path = str(tmp_path / "campaign.trace.json")
    dump_path = str(tmp_path / "metrics.json")
    outdir = str(tmp_path / "artifacts")
    before_frames = server_mod.M_FRAMES.value
    try:
        rc = pq.main(["-c", conf_path, "--backend", "host",
                      "--trace", trace_path, "--metrics-dump", dump_path,
                      "-o", outdir])
        assert rc == 0
    finally:
        for wid in fifos:
            try:
                stop_server(fifos[wid])
            except OSError:
                pass
        for t in threads:
            t.join(timeout=10)

    # (a) the merged Chrome trace: head + worker spans, joined on one id
    doc = json.load(open(trace_path))
    evs = doc["traceEvents"]
    names = {e["name"] for e in evs}
    assert {"head.read", "head.partition", "head.prepare", "head.send",
            "worker.receive", "worker.weights",
            "worker.search"} <= names
    sends = {e["args"]["trace_id"]: e for e in evs
             if e["name"] == "head.send"}
    searches = {e["args"]["trace_id"]: e for e in evs
                if e["name"] == "worker.search"}
    shared = set(sends) & set(searches)
    # every batch (2 workers x 2 diff rounds) joined head<->worker
    assert len(shared) == conf.maxworker * len(conf.diffs)
    for tid in shared:
        # the worker's search happened INSIDE the head's send window
        assert sends[tid]["ts"] <= searches[tid]["ts"]

    # (b) the metrics snapshot: health counters + phase histograms
    snap = json.load(open(dump_path))
    assert set(snap) == {"counters", "gauges", "histograms"}
    c, h = snap["counters"], snap["histograms"]
    assert c["server_frames_received_total"] - before_frames >= 4
    # failure-path counters are PRESENT (zero here) — dashboards can
    # alert on them without waiting for the first failure
    assert "server_frames_malformed_total" in c
    assert "server_replies_dropped_total" in c
    for name in ("worker_receive_seconds", "worker_weights_load_seconds",
                 "head_prepare_seconds", "head_send_seconds",
                 "server_reply_open_wait_seconds"):
        assert h[name]["count"] > 0, name
    # compile time split from steady state: first call per program key
    # landed in the jit histogram
    assert h["worker_jit_compile_seconds"]["count"] > 0

    # (c) snapshot also written next to the stats CSV
    side = json.load(open(os.path.join(outdir, "obs_metrics.json")))
    assert set(side) == {"counters", "gauges", "histograms"}
    assert os.path.exists(os.path.join(outdir, "parts.csv"))
