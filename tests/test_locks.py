"""``utils.locks`` runtime lock-order detector suite.

The unit tests drive a private witness graph so deliberate cycles never
pollute the process-wide one (the session fixture asserts THAT graph
stays clean — tier-1's threaded serving/replication/obs tests run with
``DOS_LOCK_CHECK=1`` and double as the continuous regression check).
"""

import threading

import pytest

from distributed_oracle_search_tpu.utils import locks
from distributed_oracle_search_tpu.utils.locks import (
    LockOrderError, OrderedLock, _WitnessGraph,
)

pytestmark = pytest.mark.lint


@pytest.fixture
def graph():
    return _WitnessGraph()


@pytest.fixture(autouse=True)
def _checking():
    """Force raise-mode for these tests regardless of the env, and
    restore afterwards."""
    prev = locks.set_checking("raise")
    yield
    locks.set_checking(prev)


def test_consistent_order_is_silent(graph):
    a = OrderedLock("t.A", graph)
    b = OrderedLock("t.B", graph)
    for _ in range(3):
        with a:
            with b:
                pass
    assert graph.violations() == []
    assert "t.B" in graph.edges()["t.A"]


def test_abba_cycle_raises_without_deadlocking(graph):
    """The witness property: one thread exercising A->B then B->A is
    enough — no adversarial interleaving needed."""
    a = OrderedLock("t.A", graph)
    b = OrderedLock("t.B", graph)
    with a:
        with b:
            pass
    with pytest.raises(LockOrderError, match="cycle"):
        with b:
            with a:
                pass
    assert graph.violations()


def test_longer_cycle_detected_through_the_graph(graph):
    a, b, c = (OrderedLock(n, graph) for n in ("t.A", "t.B", "t.C"))
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    with pytest.raises(LockOrderError, match="t.A"):
        with c:
            with a:
                pass


def test_self_deadlock_raises(graph):
    a = OrderedLock("t.A", graph)
    with a:
        with pytest.raises(LockOrderError, match="self-deadlock"):
            a.acquire()


def test_same_name_different_instances_flagged(graph):
    """Two locks of the same CLASS nested = instance-order ambiguity,
    the ABBA seed the per-name graph cannot prove safe."""
    a1 = OrderedLock("t.Peer", graph)
    a2 = OrderedLock("t.Peer", graph)
    with a1:
        with pytest.raises(LockOrderError):
            a2.acquire()


def test_warn_mode_self_deadlock_still_raises(graph):
    """warn downgrades ORDER cycles only: a same-instance re-acquire
    is deadlock CERTAIN — proceeding would block the thread forever
    with one log line as evidence, so it raises in every mode."""
    locks.set_checking("warn")
    a = OrderedLock("t.A", graph)
    with a:
        with pytest.raises(LockOrderError, match="self-deadlock"):
            a.acquire()


def test_warn_mode_records_without_raising(graph):
    locks.set_checking("warn")
    a = OrderedLock("t.A", graph)
    b = OrderedLock("t.B", graph)
    with a:
        with b:
            pass
    with b:
        with a:
            pass        # no raise
    assert any("cycle" in v for v in graph.violations())


def test_off_mode_is_a_plain_lock(graph):
    locks.set_checking(False)
    a = OrderedLock("t.A", graph)
    b = OrderedLock("t.B", graph)
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    assert graph.violations() == []
    assert graph.edges() == {}


def test_mode_flip_mid_hold_does_not_strand_stack(graph):
    """set_checking() flipped between a thread's acquire and release
    must not leave a stale held-stack entry that later reads as a
    false self-deadlock."""
    a = OrderedLock("t.Flip", graph)
    a.acquire()
    locks.set_checking(False)
    a.release()                 # mode off: pop must still happen
    locks.set_checking("raise")
    with a:                     # would raise self-deadlock if stranded
        pass
    assert graph.violations() == []


def test_out_of_order_release_is_fine(graph):
    a = OrderedLock("t.A", graph)
    b = OrderedLock("t.B", graph)
    a.acquire()
    b.acquire()
    a.release()
    b.release()
    assert graph.violations() == []


def test_nonblocking_acquire_contended():
    lock = OrderedLock("t.NB", _WitnessGraph())
    got = lock.acquire(blocking=False)
    assert got
    holder = {}

    def try_other():
        holder["got"] = lock.acquire(blocking=False)

    t = threading.Thread(target=try_other)
    t.start()
    t.join()
    assert holder["got"] is False
    lock.release()


def test_ordered_condition_wait_notify(graph):
    """Condition integration: wait() releases through OrderedLock (the
    held stack stays truthful), _is_owned answers from the stack, and
    no violation is recorded."""
    cond = threading.Condition(OrderedLock("t.Cond", graph))
    state = {"ready": False, "seen": False}

    def waiter():
        with cond:
            while not state["ready"]:
                cond.wait(timeout=5.0)
            state["seen"] = True

    t = threading.Thread(target=waiter)
    t.start()
    with cond:
        state["ready"] = True
        cond.notify()
    t.join(timeout=5.0)
    assert state["seen"]
    assert graph.violations() == []


def test_detector_is_live_in_the_real_stack():
    """Regression guard for the adopted lock sites: drive the serving
    queue (condition -> metrics-gauge edge) and the breaker registry
    and assert the PROCESS-WIDE witness graph saw those edges — proof
    tier-1's threaded tests are actually running under the detector,
    not silently in no-op mode."""
    from distributed_oracle_search_tpu.serving.queue import ShardQueue
    from distributed_oracle_search_tpu.serving.request import ServeRequest
    from distributed_oracle_search_tpu.transport.resilience import (
        BreakerRegistry,
    )

    q = ShardQueue(4)
    q.try_put(ServeRequest(s=0, t=1, wid=0, key=(0, 1, "-", ()),
                           t_submit=0.0, deadline=1e9))
    q.get_batch(4, 0.0, threading.Event())
    reg = BreakerRegistry(threshold=1, enabled=True)
    reg.record((0, "h"), True)
    edges = locks.GRAPH.edges()
    assert "metrics.Gauge" in edges.get("serving.ShardQueue", set())
    assert locks.violations() == []


def test_hedge_breaker_lane_interaction_acyclic():
    """The ISSUE's prime suspect: hedge-tracker vs breaker-registry vs
    dispatcher lane locks. Exercise the same nesting the frontend's
    hedged dispatch path uses and assert the witness graph stays
    acyclic (the runtime detector found NO real ordering cycle in the
    adopted sites — this pins that)."""
    from distributed_oracle_search_tpu.serving.hedge import (
        HedgeConfig, HedgeTracker,
    )
    from distributed_oracle_search_tpu.transport.resilience import (
        BreakerRegistry,
    )

    tracker = HedgeTracker(HedgeConfig(enabled=True, budget=1.0))
    reg = BreakerRegistry(threshold=1, enabled=True)
    for wid in (0, 1):
        key = (wid, "h")
        assert reg.allow(key)
        tracker.observe(wid, 0.01)
        tracker.try_issue()
        reg.record(key, wid == 0)   # one success, one failure -> OPEN
    assert reg.available((1, "h")) in (True, False)
    reg.shutdown()
    assert locks.violations() == []
