"""Pallas-fused walk kernel: parity + selection suite.

The fused kernel (``ops.pallas_walk``) must answer exactly like the
two references it shadows: element-wise equal to the CPU oracle
(``models.reference.table_search_walk``) and BIT-identical to the XLA
walk (``ops.table_search.table_search_batch``). Everything here runs
the kernel in Pallas interpret mode so the whole suite executes in the
CPU tier-1 run; the compiled real-chip run sits behind ``slow``.
``conftest.py`` pins ``DOS_WALK_KERNEL=xla`` for the rest of the suite
— these tests opt into pallas explicitly, so the fused path cannot
silently stop being exercised on CPU-only containers.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_oracle_search_tpu.data import synth_diff, synth_scenario
from distributed_oracle_search_tpu.data.graph import Graph
from distributed_oracle_search_tpu.models import table_search_walk
from distributed_oracle_search_tpu.models.cpd import build_worker_shard
from distributed_oracle_search_tpu.obs import fleet
from distributed_oracle_search_tpu.obs import metrics as obs_metrics
from distributed_oracle_search_tpu.ops import (
    DeviceGraph, build_fm_columns, pallas_walk_batch, pallas_walk_fits,
    resolve_walk_kernel, table_search_batch,
)
from distributed_oracle_search_tpu.ops.table_search import (
    BUCKET_MAX, pick_buckets,
)
from distributed_oracle_search_tpu.parallel.partition import (
    DistributionController,
)
from distributed_oracle_search_tpu.worker.engine import ShardEngine


@pytest.fixture(scope="module")
def dg(toy_graph):
    return DeviceGraph.from_graph(toy_graph)


@pytest.fixture(scope="module")
def fm(toy_graph, dg):
    targets = np.arange(toy_graph.n, dtype=np.int32)
    return build_fm_columns(dg, jnp.asarray(targets))


@pytest.fixture(scope="module")
def walk_queries(toy_graph, toy_queries):
    """The scenario plus the awkward rows: zero-length (s==t) and
    duplicate pairs."""
    q = np.asarray(toy_queries, np.int64)
    extra = np.array([[3, 3], [0, 0],              # zero-length
                      q[0].tolist(), q[0].tolist(),  # duplicates
                      q[5].tolist()], np.int64)
    return np.concatenate([q, extra], axis=0)


def _both_kernels(dg, fm, queries, w_pad, **kw):
    """Run XLA and Pallas (interpret) on identical inputs."""
    s = jnp.asarray(queries[:, 0], jnp.int32)
    t = jnp.asarray(queries[:, 1], jnp.int32)
    rows = jnp.asarray(queries[:, 1], jnp.int32)
    a = table_search_batch(dg, fm, rows, s, t, w_pad, **kw)
    b = pallas_walk_batch(dg, fm, rows, s, t, w_pad, **kw)
    return a, b


def _assert_bit_identical(a, b):
    for x, y in zip(a, b):
        x, y = np.asarray(x), np.asarray(y)
        assert x.dtype == y.dtype, (x.dtype, y.dtype)
        np.testing.assert_array_equal(x, y)


# ------------------------------------------------- pick_buckets edges

@pytest.mark.parametrize("q", [0, 1, 2, 3, 7, 97, 4099, 9973, 65536])
@pytest.mark.parametrize("n_buckets", [0, 1, 3, 64, 1000])
def test_pick_buckets_never_zero_never_uneven(q, n_buckets):
    """The kernel's grid resolver: q=0 and prime q must degrade to 1,
    never return 0 or a non-divisor (a 0 grid or ragged bucket would
    fault the pallas_call)."""
    b = pick_buckets(q, n_buckets)
    assert b >= 1
    if q > 0:
        assert q % b == 0
        assert b <= max(q, 1)


def test_pick_buckets_prime_degrades_to_one():
    for prime in (4099, 9973):
        assert pick_buckets(prime, 0) == 1
        assert pick_buckets(prime, 7) == 1


def test_pick_buckets_auto_cap():
    assert pick_buckets(1 << 20, 0) == BUCKET_MAX


# ------------------------------------------------------ kernel parity

def test_parity_vs_cpu_reference(toy_graph, dg, fm, walk_queries):
    """Element-wise vs models.reference.table_search_walk, free-flow
    and diffed — moves on free-flow first moves, costs on query-time
    weights."""
    g = toy_graph
    fm_np = np.asarray(fm)
    w_diff = g.weights_with_diff(synth_diff(g, frac=0.2, seed=3))
    for w_query in (None, w_diff):
        w_pad = jnp.asarray(g.padded_weights(w_query), jnp.int32)
        s = jnp.asarray(walk_queries[:, 0], jnp.int32)
        t = jnp.asarray(walk_queries[:, 1], jnp.int32)
        rows = jnp.asarray(walk_queries[:, 1], jnp.int32)
        cost, plen, fin = pallas_walk_batch(dg, fm, rows, s, t, w_pad)
        for i, (sq, tq) in enumerate(walk_queries):
            c, p, f, _ = table_search_walk(
                g, lambda x, tt: fm_np[tt, x], int(sq), int(tq),
                w_query=w_query)
            assert (int(cost[i]), int(plen[i]), bool(fin[i])) == \
                (c, p, f), f"query {i} ({sq}->{tq})"


@pytest.mark.parametrize("k_moves", [-1, 0, 1, 3])
@pytest.mark.parametrize("n_buckets", [0, 1, 2, 4])
def test_bit_identical_vs_xla(toy_graph, dg, fm, walk_queries,
                              k_moves, n_buckets):
    g = toy_graph
    w_diff = g.weights_with_diff(synth_diff(g, frac=0.2, seed=3))
    for w in (dg.w_pad, jnp.asarray(g.padded_weights(w_diff),
                                    jnp.int32)):
        a, b = _both_kernels(dg, fm, walk_queries, w,
                             k_moves=k_moves, n_buckets=n_buckets)
        _assert_bit_identical(a, b)


def test_bit_identical_with_pad_lanes_and_max_steps(dg, fm,
                                                    walk_queries):
    nq = len(walk_queries)
    valid = np.ones(nq, bool)
    valid[nq - 6:] = False
    a, b = _both_kernels(dg, fm, walk_queries, dg.w_pad,
                         valid=jnp.asarray(valid), max_steps=5)
    _assert_bit_identical(a, b)
    # pad lanes come back zero / unfinished from BOTH kernels
    for arr in (a[0], a[1], a[2], b[0], b[1], b[2]):
        assert not np.asarray(arr)[nq - 6:].any()


def test_k_moves_budget_exhaustion(toy_graph, dg, fm):
    """A budget smaller than the walk truncates at EXACTLY k moves,
    unfinished — pinned against the reference and the XLA path."""
    g = toy_graph
    fm_np = np.asarray(fm)
    # corner-to-corner queries are longer than 2 moves on an 8x6 grid
    queries = np.array([[0, g.n - 1], [g.n - 1, 0], [1, g.n - 2],
                        [2, 2]], np.int64)
    a, b = _both_kernels(dg, fm, queries, dg.w_pad, k_moves=2)
    _assert_bit_identical(a, b)
    cost, plen, fin = b
    for i, (sq, tq) in enumerate(queries):
        c, p, f, _ = table_search_walk(
            g, lambda x, tt: fm_np[tt, x], int(sq), int(tq), k_moves=2)
        assert (int(cost[i]), int(plen[i]), bool(fin[i])) == (c, p, f)
    assert int(plen[0]) == 2 and not bool(fin[0])
    assert bool(fin[3]) and int(plen[3]) == 0      # s==t inside budget


def test_unreachable_minus_one_rows():
    """Two directed 4-cycles, no edges between them: cross-component
    queries sit on -1 first-move rows and must halt at birth."""
    n = 8
    src = np.array([0, 1, 2, 3, 4, 5, 6, 7])
    dst = np.array([1, 2, 3, 0, 5, 6, 7, 4])
    w = np.full(8, 10, np.int32)
    g = Graph(np.arange(n), np.zeros(n), src, dst, w)
    dg2 = DeviceGraph.from_graph(g)
    fm2 = build_fm_columns(dg2, jnp.asarray(np.arange(n, dtype=np.int32)))
    fm_np = np.asarray(fm2)
    assert (fm_np[0, 4:] == -1).all()      # cross-component rows
    queries = np.array([[0, 5], [6, 2], [0, 3], [4, 7], [5, 5]],
                       np.int64)
    a, b = _both_kernels(dg2, fm2, queries, dg2.w_pad)
    _assert_bit_identical(a, b)
    cost, plen, fin = b
    for i, (sq, tq) in enumerate(queries):
        c, p, f, _ = table_search_walk(
            g, lambda x, tt: fm_np[tt, x], int(sq), int(tq))
        assert (int(cost[i]), int(plen[i]), bool(fin[i])) == (c, p, f)
    assert not bool(fin[0]) and int(plen[0]) == 0   # unreachable
    assert bool(fin[2]) and int(cost[2]) == 30      # in-component


def test_empty_batch():
    g = Graph(np.arange(2), np.zeros(2), [0, 1], [1, 0], [1, 1])
    dg2 = DeviceGraph.from_graph(g)
    fm2 = build_fm_columns(dg2, jnp.asarray(np.arange(2, dtype=np.int32)))
    z = np.zeros((0,), np.int32)
    cost, plen, fin = pallas_walk_batch(
        dg2, fm2, jnp.asarray(z), jnp.asarray(z), jnp.asarray(z),
        dg2.w_pad)
    assert cost.shape == plen.shape == fin.shape == (0,)


# ------------------------------------------------- knob + fit policy

def test_conftest_pins_xla_for_tier1():
    """The suite-wide default is the XLA reference path; this file's
    pallas coverage is explicit opt-in (the pin is what keeps a
    container env from flipping the whole tier-1 run to interpret
    speed)."""
    assert os.environ.get("DOS_WALK_KERNEL") == "xla"
    assert resolve_walk_kernel() == "xla"


def test_knob_resolution(monkeypatch):
    monkeypatch.setenv("DOS_WALK_KERNEL", "auto")
    assert resolve_walk_kernel("cpu") == "xla"
    assert resolve_walk_kernel("tpu") == "pallas"
    monkeypatch.setenv("DOS_WALK_KERNEL", "pallas")
    assert resolve_walk_kernel("cpu") == "pallas"
    monkeypatch.setenv("DOS_WALK_KERNEL", "XLA")       # case-tolerant
    assert resolve_walk_kernel("tpu") == "xla"
    monkeypatch.setenv("DOS_WALK_KERNEL", "bogus")     # degrade, not crash
    assert resolve_walk_kernel("cpu") == "xla"
    assert resolve_walk_kernel("tpu") == "pallas"


def test_vmem_fit_check(monkeypatch):
    ok, why = pallas_walk_fits(48, 4, 164, 1024)
    assert ok and why == ""
    ok, why = pallas_walk_fits(5_000_000, 8, 20_000_000, 65536)
    assert not ok and "VMEM budget" in why
    monkeypatch.setenv("DOS_WALK_VMEM_MB", "0.001")
    ok, why = pallas_walk_fits(48, 4, 164, 1024)
    assert not ok
    monkeypatch.setenv("DOS_WALK_VMEM_MB", "junk")     # degrade to default
    ok, _ = pallas_walk_fits(48, 4, 164, 1024)
    assert ok
    assert pallas_walk_fits(48, 4, 164, 0)[0]          # empty batch


# ------------------------------------------- engine dedup/unsort path

@pytest.fixture(scope="module")
def shard_setup(toy_graph, tmp_path_factory):
    outdir = str(tmp_path_factory.mktemp("pallas-shard"))
    dc = DistributionController("mod", 2, 2, toy_graph.n)
    build_worker_shard(toy_graph, dc, 0, outdir, chunk=16)
    return dc, outdir


def _engine_config():
    from distributed_oracle_search_tpu.cli import process_query as pq
    from distributed_oracle_search_tpu.cli.args import parse_args
    return pq.runtime_config(parse_args([]))


def test_engine_duplicates_unsort_pallas(toy_graph, shard_setup,
                                         monkeypatch):
    """The fused kernel through ShardEngine's dedup/unsort machinery:
    duplicate (s, t) pairs, zero-length queries, answers element-wise
    equal to the CPU reference AND bit-identical to the XLA engine,
    with the pallas selection booked on its counter."""
    g = toy_graph
    dc, outdir = shard_setup
    rng = np.random.default_rng(5)
    nodes = np.arange(g.n)
    owned0 = nodes[dc.worker_of(nodes) == 0]
    t = rng.choice(owned0, 24)
    s = rng.choice(nodes, 24)
    queries = np.stack([s, t], axis=1).astype(np.int64)
    queries[3] = queries[0]                     # duplicates
    queries[7] = queries[0]
    queries[9] = (queries[9][1], queries[9][1])  # zero-length s==t
    config = _engine_config()

    monkeypatch.setenv("DOS_WALK_KERNEL", "xla")
    eng_x = ShardEngine(g, dc, wid=0, outdir=outdir)
    cost_x, plen_x, fin_x, stats_x = eng_x.answer(queries, config)

    snap0 = obs_metrics.REGISTRY.snapshot()["counters"]
    monkeypatch.setenv("DOS_WALK_KERNEL", "pallas")
    eng_p = ShardEngine(g, dc, wid=0, outdir=outdir)
    cost_p, plen_p, fin_p, stats_p = eng_p.answer(queries, config)
    snap1 = obs_metrics.REGISTRY.snapshot()["counters"]
    assert snap1.get("walk_pallas_batches_total", 0) \
        == snap0.get("walk_pallas_batches_total", 0) + 1

    _assert_bit_identical((cost_x, plen_x, fin_x),
                          (cost_p, plen_p, fin_p))
    assert fin_p.all()
    # stats count per ORIGINAL query, duplicates included
    assert stats_p.finished == len(queries) == stats_x.finished
    fm_np = np.asarray(eng_p.fm)
    rows = dc.owned_index_of(queries[:, 1])
    for i, (sq, tq) in enumerate(queries):
        c, p, f, _ = table_search_walk(
            g, lambda x, tt, r=rows[i]: fm_np[r, x], int(sq), int(tq))
        assert (int(cost_p[i]), int(plen_p[i]), bool(fin_p[i])) == \
            (c, p, f)
    # duplicates fanned back out identically
    assert cost_p[3] == cost_p[0] == cost_p[7]
    assert plen_p[9] == 0 and fin_p[9]


def test_engine_diffed_weights_pallas(toy_graph, shard_setup, tmp_path,
                                      monkeypatch):
    """Diff applied at query time through the fused kernel: moves stay
    free-flow, costs dominate free flow, bit-identical to XLA."""
    from distributed_oracle_search_tpu.data.formats import write_diff

    g = toy_graph
    dc, outdir = shard_setup
    dsrc, ddst, dw = synth_diff(g, frac=0.3, seed=9)
    difffile = str(tmp_path / "q.diff")
    write_diff(difffile, dsrc, ddst, dw)
    nodes = np.arange(g.n)
    owned0 = nodes[dc.worker_of(nodes) == 0]
    queries = np.stack([nodes[:16], np.resize(owned0, 16)],
                       axis=1).astype(np.int64)
    config = _engine_config()

    monkeypatch.setenv("DOS_WALK_KERNEL", "pallas")
    eng_p = ShardEngine(g, dc, wid=0, outdir=outdir)
    free = eng_p.answer(queries, config)
    diffed = eng_p.answer(queries, config, difffile=difffile)
    monkeypatch.setenv("DOS_WALK_KERNEL", "xla")
    eng_x = ShardEngine(g, dc, wid=0, outdir=outdir)
    diffed_x = eng_x.answer(queries, config, difffile=difffile)
    _assert_bit_identical(diffed[:3], diffed_x[:3])
    assert (diffed[0] >= free[0]).all()          # diff only raises cost
    assert (diffed[1] == free[1]).all()          # trajectory unchanged


def test_engine_vmem_fallback_books_xla(toy_graph, shard_setup,
                                        monkeypatch):
    """A pallas-requested batch over the VMEM budget degrades to the
    XLA walk (correct answers, xla counter booked) instead of faulting."""
    g = toy_graph
    dc, outdir = shard_setup
    nodes = np.arange(g.n)
    owned0 = nodes[dc.worker_of(nodes) == 0]
    queries = np.stack([nodes[:8], np.resize(owned0, 8)],
                       axis=1).astype(np.int64)
    monkeypatch.setenv("DOS_WALK_KERNEL", "pallas")
    monkeypatch.setenv("DOS_WALK_VMEM_MB", "0.0001")
    snap0 = obs_metrics.REGISTRY.snapshot()["counters"]
    eng = ShardEngine(g, dc, wid=0, outdir=outdir)
    cost, plen, fin, _ = eng.answer(queries, _engine_config())
    snap1 = obs_metrics.REGISTRY.snapshot()["counters"]
    assert fin.all()
    assert snap1.get("walk_xla_batches_total", 0) \
        == snap0.get("walk_xla_batches_total", 0) + 1
    assert snap1.get("walk_pallas_batches_total", 0) \
        == snap0.get("walk_pallas_batches_total", 0)


# ------------------------------------------------- bench-diff gate

def _bench_record(tmp_path, name, headline):
    p = tmp_path / name
    p.write_text(json.dumps({
        "metric": "scenario_queries_per_sec", "value": 100000.0,
        "headline": headline}))
    return str(p)


def test_bench_diff_knows_walk_key_directions(tmp_path):
    """walk_* headline keys gate with the right direction: q/s and
    lane fractions are higher-is-better (a drop regresses), stall is
    lower-is-better (a rise regresses), and the lane fraction uses the
    tighter per-key tolerance."""
    old = _bench_record(tmp_path, "BENCH_r01.json", {
        "walk_pallas_queries_per_sec": 500000.0,
        "walk_pallas_stall_p99_ms": 2.0,
        "walk_useful_lane_fraction": 0.5,
        "walk_pallas_speedup": 2.0,
    })
    bad = _bench_record(tmp_path, "BENCH_r02.json", {
        "walk_pallas_queries_per_sec": 200000.0,   # drop: regression
        "walk_pallas_stall_p99_ms": 9.0,           # rise: regression
        "walk_useful_lane_fraction": 0.4,          # -20% > 15% tol
        "walk_pallas_speedup": 2.1,
    })
    out = fleet.compare_bench(old, bad)
    by_key = {e["key"]: e for e in out["regressions"]}
    assert by_key["walk_pallas_queries_per_sec"]["direction"] == "higher"
    assert by_key["walk_pallas_stall_p99_ms"]["direction"] == "lower"
    assert by_key["walk_useful_lane_fraction"]["tolerance"] == \
        pytest.approx(0.15)
    assert "walk_pallas_speedup" not in by_key

    ok = _bench_record(tmp_path, "BENCH_r03.json", {
        "walk_pallas_queries_per_sec": 520000.0,
        "walk_pallas_stall_p99_ms": 1.5,
        "walk_useful_lane_fraction": 0.47,         # -6%: inside tol
        "walk_pallas_speedup": 2.4,
    })
    out = fleet.compare_bench(old, ok)
    assert out["regressions"] == []


# --------------------------------------------------- real chip (slow)

@pytest.mark.slow
@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="compiled fused kernel needs a real TPU")
def test_compiled_kernel_parity_on_tpu(toy_graph, dg, fm, walk_queries):
    """interpret=False: the Mosaic-compiled kernel (double-buffered DMA
    loader) against the XLA walk on hardware."""
    s = jnp.asarray(walk_queries[:, 0], jnp.int32)
    t = jnp.asarray(walk_queries[:, 1], jnp.int32)
    rows = jnp.asarray(walk_queries[:, 1], jnp.int32)
    a = table_search_batch(dg, fm, rows, s, t, dg.w_pad)
    b = pallas_walk_batch(dg, fm, rows, s, t, dg.w_pad,
                          interpret=False)
    _assert_bit_identical(a, b)
