"""Elastic fleet membership: epoch-versioned partition tables,
drain-free join/leave, and the reconfiguration controller.

Tier-1 gates: epoch-0 identity tables stay byte-identical to the
pre-elastic system (conf wire, routing, wire knobs); epoch/owner
columns round-trip under the unknown-column compat contract; the
server's version gate refuses only NEWER epochs (after a membership
refresh) and always serves older ones; join/leave commit atomically
with crash-resumable catch-up; the serving frontend dual-reads a
moving shard; ``dos-obs top`` tolerates mixed statusz schemas. The
mid-campaign join+leave chaos drill stays behind ``slow``.
"""

import csv
import json
import os
import shutil
import threading
import time

import numpy as np
import pytest

from distributed_oracle_search_tpu.cli import process_query as pq
from distributed_oracle_search_tpu.data import (
    ensure_synth_dataset, read_scen,
)
from distributed_oracle_search_tpu.data.graph import Graph
from distributed_oracle_search_tpu.models.cpd import (
    adopt_shard_blocks, build_replica_shards, build_worker_shard,
    shard_block_name, write_index_manifest,
)
from distributed_oracle_search_tpu.obs import fleet as obs_fleet
from distributed_oracle_search_tpu.obs import metrics as obs_metrics
from distributed_oracle_search_tpu.parallel import membership as fleet
from distributed_oracle_search_tpu.parallel.partition import (
    DistributionController, parse_conf,
)
from distributed_oracle_search_tpu.serving import (
    EngineDispatcher, HedgeConfig, ServeConfig, ServingFrontend,
)
from distributed_oracle_search_tpu.testing import faults
from distributed_oracle_search_tpu.transport import (
    fifo as fifo_transport,
)
from distributed_oracle_search_tpu.transport.wire import (
    RuntimeConfig, STALE_EPOCH_LINE, StatsRow,
)
from distributed_oracle_search_tpu.utils.config import ClusterConfig
from distributed_oracle_search_tpu.worker import FifoServer, stop_server

pytestmark = pytest.mark.membership

N_WORKERS = 3


def _counter(name: str) -> float:
    return obs_metrics.REGISTRY.snapshot()["counters"].get(name, 0)


def _gauge(name: str) -> float:
    return obs_metrics.REGISTRY.snapshot()["gauges"].get(name, 0)


# ------------------------------------------------- conf wire round-trips

def test_epoch0_identity_conf_byte_identical():
    """The legacy wire format must not move: an epoch-0 identity table
    (R=1 AND R=2) emits no epoch/owner columns."""
    dc = DistributionController("mod", 4, 4, 12, block_size=2)
    lines = dc.format_conf().split("\n")
    assert lines[0] == "node,wid,bid,bidx"
    assert all(len(ln.split(",")) == 4 for ln in lines[1:])
    dc2 = DistributionController("mod", 4, 4, 12, block_size=2,
                                 replication=2)
    assert dc2.format_conf().split("\n")[0] == "node,wid,bid,bidx,rep1"


def test_epoch_conf_round_trip():
    owners = [0, 5, 2, 3]
    dc = DistributionController("mod", 4, 4, 32, block_size=4,
                                epoch=7, owners=owners)
    text = dc.format_conf()
    assert text.split("\n")[0] == "node,wid,bid,bidx,epoch,owner"
    p = parse_conf(text)
    assert p["epoch"] == 7
    np.testing.assert_array_equal(
        p["owner"], np.asarray(owners)[p["wid"]])
    # the first four columns are untouched — a legacy positional
    # consumer still routes on the primary shard
    tab = dc.table()
    for i, k in enumerate(("node", "wid", "bid", "bidx")):
        np.testing.assert_array_equal(p[k], tab[:, i])


def test_parse_conf_legacy_is_epoch0():
    legacy = "node,wid,bid,bidx\n0,0,0,0\n1,1,0,0"
    p = parse_conf(legacy)
    assert p["epoch"] == 0
    np.testing.assert_array_equal(p["owner"], p["wid"])


def test_parse_conf_unknown_columns_and_mixed_epochs():
    # unknown columns tolerated wherever they appear
    text = ("node,future,wid,bid,bidx,epoch,owner\n"
            "0,9,0,0,0,3,2\n1,9,1,0,0,3,1")
    p = parse_conf(text)
    assert p["epoch"] == 3 and list(p["owner"]) == [2, 1]
    # a table mixing epochs is torn state, not tolerable ambiguity
    torn = ("node,wid,bid,bidx,epoch,owner\n"
            "0,0,0,0,3,0\n1,1,0,0,4,1")
    with pytest.raises(ValueError, match="mixes epochs"):
        parse_conf(torn)


def test_owner_validation():
    with pytest.raises(ValueError, match="owners"):
        DistributionController("mod", 4, 4, 16, owners=[0, 1])
    with pytest.raises(ValueError, match="epoch"):
        DistributionController("mod", 4, 4, 16, epoch=-1)


# --------------------------------------------------- wire knob + sentinel

def test_runtime_config_epoch_wire_compat():
    rc = RuntimeConfig(epoch=4)
    assert RuntimeConfig.from_json(rc.to_json()).epoch == 4
    # an old peer's payload has no epoch key -> default 0; a new
    # payload read by old-style filtering keeps working (unknown keys
    # dropped symmetrically)
    assert RuntimeConfig.from_json('{"itrs": 2}').epoch == 0
    d = json.loads(rc.to_json())
    d["some_future_knob"] = True
    assert RuntimeConfig.from_json(json.dumps(d)).epoch == 4


def test_stale_epoch_stats_sentinel():
    row = StatsRow(ok=False, stale_epoch=True)
    assert row.encode_wire() == STALE_EPOCH_LINE
    back = StatsRow.decode(STALE_EPOCH_LINE)
    assert not back.ok and back.stale_epoch
    # an annotated sentinel ("STALE_EPOCH 3") still decodes
    back2 = StatsRow.decode(STALE_EPOCH_LINE + " 3")
    assert not back2.ok and back2.stale_epoch
    # a normal failure row stays FAIL
    assert StatsRow.failed().encode_wire() == "FAIL"


# ----------------------------------------------- owner-aware routing

def test_owner_aware_replica_routing():
    dc = DistributionController("mod", 4, 4, 64, replication=2,
                                epoch=1, owners=[4, 1, 2, 3])
    # shard 0's chain slots are shards {0, 1}; their owners host it
    assert dc.replica_workers(0) == [4, 1]
    assert dc.replica_rank(0, 4) == 0 and dc.replica_rank(0, 1) == 1
    with pytest.raises(ValueError):
        dc.replica_rank(0, 2)
    # worker 4 hosts exactly the shards whose chain slots it owns:
    # shard 0 (owner) and shard 3 (its rank-1 slot is shard 0)
    assert dc.replica_shards(4) == [0, 3]
    assert 0 in dc.replica_shards(1)
    # the dead-remap routes around the dead OWNER to the live host
    qs = np.stack([np.zeros(8, np.int64),
                   np.arange(8, dtype=np.int64)], axis=1)
    groups = dc.group_queries(qs, dead={4})
    shard0 = qs[dc.worker_of(qs[:, 1]) == 0]
    assert len(groups[1]) >= len(shard0)     # shard 0 fell to worker 1


# ----------------------------------------------- membership state file

def test_state_round_trip_and_compat(tmp_path):
    outdir = str(tmp_path)
    assert fleet.load_state(outdir) is None
    assert fleet.current_epoch(outdir) == 0
    st = fleet.MembershipState(epoch=2, workers=["a", "b"],
                               owners=[1, 0])
    fleet.save_state(outdir, st)
    back = fleet.load_state(outdir)
    assert back.epoch == 2 and back.owners == [1, 0]
    assert fleet.current_epoch(outdir) == 2
    # unknown keys tolerated (future fields cannot break this reader)
    raw = json.load(open(fleet.state_path(outdir)))
    raw["future_key"] = {"x": 1}
    json.dump(raw, open(fleet.state_path(outdir), "w"))
    assert fleet.load_state(outdir).epoch == 2
    # only NEWER schema versions reject (the manifest-compat contract)
    raw["version"] = fleet.MEMBERSHIP_VERSION + 1
    json.dump(raw, open(fleet.state_path(outdir), "w"))
    with pytest.raises(ValueError, match="schema"):
        fleet.load_state(outdir)


# ------------------------------------------------------ built world

@pytest.fixture(scope="module")
def world(tmp_path_factory):
    """3-worker world, R=2 replicated index + manifest (the replica
    chains are what leave transfers ownership onto)."""
    datadir = str(tmp_path_factory.mktemp("membership-data"))
    paths = ensure_synth_dataset(datadir, width=8, height=6,
                                 n_queries=45, seed=29)
    outdir = os.path.join(datadir, "index")
    g = Graph.from_xy(paths["xy"])
    dc = DistributionController("mod", N_WORKERS, N_WORKERS, g.n,
                                replication=2)
    for wid in range(N_WORKERS):
        build_worker_shard(g, dc, wid, outdir)
        build_replica_shards(g, dc, wid, outdir)
    write_index_manifest(outdir, dc)
    return datadir, paths, outdir, g, dc


def _fresh_world(world, tmp_path, name, diffs=("-",), replication=2):
    """A per-test copy of the built index (membership state mutates the
    index dir; tests must not see each other's epochs)."""
    datadir, paths, outdir, g, dc = world
    my_out = str(tmp_path / f"index-{name}")
    shutil.copytree(outdir, my_out)
    conf = ClusterConfig(
        workers=["localhost"] * N_WORKERS,
        partmethod="mod", partkey=N_WORKERS,
        outdir=my_out, xy_file=paths["xy"], scenfile=paths["scen"],
        diffs=list(diffs), nfs=str(tmp_path), replication=replication,
    ).validate()
    my_dc = DistributionController("mod", N_WORKERS, N_WORKERS, g.n,
                                   replication=replication)
    return conf, g, my_dc, my_out


# ------------------------------------------------- controller: join

def test_join_window_and_commit(world, tmp_path):
    conf, g, dc, outdir = _fresh_world(world, tmp_path, "join")
    mc = fleet.MembershipController(conf, dc, graph=g)
    assert mc.epoch == 0
    m0 = _counter("reshard_migrations_total")
    s0 = _counter("reshard_shards_moved_total")
    mig = mc.begin(mc.plan_join("localhost"), host="localhost")
    assert mig.worker == N_WORKERS and len(mig.moves) == 1
    moved = mig.moves[0][0]
    # dual-read window: old owner authoritative, adopter second
    cands = mc.candidates_for(moved)
    assert cands[0] == moved and cands[1] == N_WORKERS
    # epoch does NOT bump at begin
    assert fleet.current_epoch(outdir) == 0
    a0 = _counter("reshard_blocks_adopted_total")
    mc.catch_up(mig)
    assert _counter("reshard_blocks_adopted_total") > a0
    state = mc.commit(mig)
    assert state.epoch == 1
    assert state.owners[moved] == N_WORKERS
    assert fleet.current_epoch(outdir) == 1
    assert _gauge("reshard_epoch") == 1
    assert _counter("reshard_migrations_total") - m0 == 1
    assert _counter("reshard_shards_moved_total") - s0 == 1
    # post-commit routing leads with the adopter
    assert mc.candidates_for(moved)[0] == N_WORKERS
    # a fresh reader derives the same view
    dc2 = fleet.apply_state(dc, fleet.load_state(outdir))
    assert dc2.epoch == 1 and dc2.owner_of(moved) == N_WORKERS


def test_leave_transfers_to_replica_first(world, tmp_path):
    conf, g, dc, outdir = _fresh_world(world, tmp_path, "leave")
    mc = fleet.MembershipController(conf, dc, graph=g)
    mig = mc.begin(mc.plan_leave(1))
    # shard 1's replica chain is (1, 2) at R=2: worker 2 already holds
    # the rows — ownership transfers to the replica first
    assert mig.moves == [[1, 1, 2]]
    mc.catch_up(mig)
    state = mc.commit(mig)
    assert state.epoch == 1 and state.owners == [0, 2, 2]
    # the leaver now owns nothing; its former shard routes to worker 2
    dc2 = fleet.apply_state(dc, state)
    assert dc2.replica_workers(1)[0] == 2


def test_commit_requires_catchup_and_abort_restores(world, tmp_path):
    conf, g, dc, outdir = _fresh_world(world, tmp_path, "abort")
    mc = fleet.MembershipController(conf, dc, graph=g)
    mig = mc.begin(mc.plan_join("localhost"), host="localhost")
    with pytest.raises(ValueError, match="catch-up"):
        mc.commit(mig)
    ab0 = _counter("reshard_aborted_total")
    st = mc.abort(mig)
    assert st.epoch == 0 and st.migration is None
    assert len(st.workers) == N_WORKERS      # roster entry dropped
    assert _counter("reshard_aborted_total") - ab0 == 1
    # double begin is refused while a window is open
    mig2 = mc.begin(mc.plan_join("localhost"), host="localhost")
    with pytest.raises(ValueError, match="in flight"):
        mc.begin(mc.plan_join("x"))
    mc.abort(mig2)


def test_catch_up_crash_resume(world, tmp_path, monkeypatch):
    """kill-during-reshard between moves: the journal keeps the done
    list, a fresh controller resumes exactly the tail and commits."""
    conf, g, dc, outdir = _fresh_world(world, tmp_path, "crash")
    mc = fleet.MembershipController(conf, dc, graph=g)
    # force a 2-move migration: leave moves BOTH of worker 0's and 1's
    # shards? leave(0) moves one shard; craft a join with 2 moves
    mig = fleet.Migration(epoch=1, kind="join", worker=N_WORKERS,
                          moves=[[0, 0, N_WORKERS], [1, 1, N_WORKERS]])
    mc.begin(mig, host="localhost")
    faults.reset()
    monkeypatch.setenv("DOS_FAULTS",
                       "kill-during-reshard;mode=raise;times=1")
    with pytest.raises(RuntimeError, match="kill-during-reshard"):
        mc.catch_up(mig)
    monkeypatch.delenv("DOS_FAULTS")
    faults.reset()
    # the first move is journaled; the window is still open
    st = fleet.load_state(outdir)
    assert st.epoch == 0
    assert st.live_migration.done == [0]
    # a brand-new controller (the restarted process) resumes the tail
    mc2 = fleet.MembershipController(conf, dc, graph=g)
    state = mc2.resume()
    assert state.epoch == 1
    assert state.owners[0] == N_WORKERS
    assert state.owners[1] == N_WORKERS


def test_adopt_heals_corrupt_block(world, tmp_path):
    conf, g, dc, outdir = _fresh_world(world, tmp_path, "heal")
    victim = shard_block_name(2, 0)
    with open(os.path.join(outdir, victim), "r+b") as f:
        f.seek(64)
        f.write(b"\x55" * 16)
    report = adopt_shard_blocks(g, dc, 2, outdir)
    assert report["healed"] == [victim]
    # idempotent: a second pass verifies clean
    again = adopt_shard_blocks(g, dc, 2, outdir)
    assert again["healed"] == [] and again["ok"] == again["blocks"]


# --------------------------------------------------- server epoch gate

def test_server_epoch_gate(world, tmp_path, monkeypatch):
    conf, g, dc, outdir = _fresh_world(world, tmp_path, "gate")
    server = FifoServer(conf, 0, command_fifo=str(tmp_path / "w0.fifo"))
    assert server.epoch == 0
    # older/equal epochs always pass
    assert server._epoch_gate(RuntimeConfig()) is None
    assert server._epoch_gate(RuntimeConfig(epoch=0)) is None
    # newer epoch with no newer state on disk -> STALE_EPOCH
    g0 = _counter("server_stale_epoch_total")
    row = server._epoch_gate(RuntimeConfig(epoch=1))
    assert row is not None and row.stale_epoch and not row.ok
    assert _counter("server_stale_epoch_total") - g0 == 1
    # once the commit lands on disk the gate refreshes and serves
    st = fleet.MembershipState(epoch=1,
                               workers=["localhost"] * N_WORKERS,
                               owners=[0, 1, 2])
    fleet.save_state(outdir, st)
    assert server._epoch_gate(RuntimeConfig(epoch=1)) is None
    assert server.epoch == 1
    # and older-epoch traffic is STILL served after the bump (the
    # dual-read window depends on it)
    assert server._epoch_gate(RuntimeConfig(epoch=0)) is None


def test_stale_epoch_reply_fault(world, tmp_path, monkeypatch):
    conf, g, dc, outdir = _fresh_world(world, tmp_path, "gatefault")
    server = FifoServer(conf, 1, command_fifo=str(tmp_path / "w1.fifo"))
    faults.reset()
    monkeypatch.setenv("DOS_FAULTS", "stale-epoch-reply;wid=1;times=1")
    row = server._epoch_gate(RuntimeConfig())
    assert row is not None and row.stale_epoch
    # the rule fired once; the next frame serves normally
    assert server._epoch_gate(RuntimeConfig()) is None
    monkeypatch.delenv("DOS_FAULTS")
    faults.reset()


def test_server_serves_adopted_shard_after_commit(world, tmp_path):
    """The drain-free join, worker side: a server whose wid is outside
    the original roster owns nothing at epoch 0, then serves its
    adopted shard after the commit is visible."""
    conf, g, dc, outdir = _fresh_world(world, tmp_path, "adopt-serve")
    qs = read_scen(conf.scenfile)
    # commit an epoch moving shard 0 to the new worker 3
    mc = fleet.MembershipController(conf, dc, graph=g)
    mig = fleet.Migration(epoch=1, kind="join", worker=3,
                          moves=[[0, 0, 3]])
    mc.begin(mig, host="localhost")
    mc.catch_up(mig)
    mc.commit(mig)
    server = FifoServer(conf, 3, command_fifo=str(tmp_path / "w3.fifo"))
    assert server.engine is None or server.engine.shard == 0
    shard0 = qs[dc.worker_of(qs[:, 1]) == 0][:6]
    from distributed_oracle_search_tpu.transport.wire import (
        Request, write_query_file,
    )
    qfile = str(tmp_path / "query.adopt")
    write_query_file(qfile, shard0)
    row = server._handle(Request(RuntimeConfig(epoch=1), qfile,
                                 str(tmp_path / "ans"), "-"))
    assert row.ok and row.finished == len(shard0)


# ------------------------------------------------ frontend dual-read

class _FailingVia:
    """Dispatcher wrapper that fails every batch sent via one worker."""

    def __init__(self, inner, dead_via):
        self.inner = inner
        self.dead = dead_via

    def answer_batch(self, wid, queries, rconf, diff, via=None):
        if (wid if via is None else via) == self.dead:
            raise RuntimeError("injected: via-worker down")
        return self.inner.answer_batch(wid, queries, rconf, diff,
                                       via=via)


def test_frontend_dual_read_window(world, tmp_path):
    """During a migration window the frontend walks old-owner -> adopter:
    with the old owner down, every moving-shard request is answered by
    the adopter lane, zero sheds."""
    conf, g, dc, outdir = _fresh_world(world, tmp_path, "dualread",
                                       replication=1)
    qs = read_scen(conf.scenfile)
    mc = fleet.MembershipController(conf, dc, graph=g)
    mig = fleet.Migration(epoch=1, kind="join", worker=3,
                          moves=[[1, 1, 3]])
    mc.begin(mig, host="localhost")
    mc.catch_up(mig)                     # window open, NOT committed
    assert mc.candidates_for(1) == [1, 3]
    disp = EngineDispatcher(conf, graph=g, dc=dc)
    fe = ServingFrontend(
        mc.dc_view(), _FailingVia(disp, dead_via=1),
        sconf=ServeConfig(max_batch=16, max_wait_ms=2.0,
                          queue_depth=256, cache_bytes=0,
                          deadline_ms=60_000.0),
        hconf=HedgeConfig(enabled=False), membership=mc)
    fe.start()
    f0 = _counter("failover_total")
    try:
        shard1 = qs[dc.worker_of(qs[:, 1]) == 1][:8]
        res = [fe.query(int(s), int(t), timeout=60) for s, t in shard1]
    finally:
        fe.stop()
    assert all(r.ok for r in res)
    assert _counter("failover_total") - f0 >= 1
    # answers match the primary engine's
    eng_disp = EngineDispatcher(conf, graph=g, dc=dc)
    c, p, fin = eng_disp.answer_batch(1, shard1, RuntimeConfig(), "-")
    for i, r in enumerate(res):
        assert (r.cost, r.plen, r.finished) == (int(c[i]), int(p[i]),
                                                bool(fin[i]))


def test_frontend_r1_admission_sees_adopter(world, tmp_path):
    """R=1 admission during a dual-read window: the moving shard's old
    owner has an OPEN breaker, but the adopter is live — requests must
    pass admission and be served via failover, not shed circuit-open.
    A steady (single-candidate) shard with an open breaker still sheds,
    pinning the legacy R=1 trial semantics."""
    from distributed_oracle_search_tpu.transport import resilience

    conf, g, dc, outdir = _fresh_world(world, tmp_path, "r1admission",
                                       replication=1)
    qs = read_scen(conf.scenfile)
    mc = fleet.MembershipController(conf, dc, graph=g)
    mig = fleet.Migration(epoch=1, kind="join", worker=3,
                          moves=[[1, 1, 3]])
    mc.begin(mig, host="localhost")
    mc.catch_up(mig)                     # window open, NOT committed
    registry = resilience.BreakerRegistry(threshold=1, cooldown_s=600.0,
                                          enabled=True)
    registry.record(1, ok=False)         # old owner: OPEN
    registry.record(0, ok=False)         # a steady shard: OPEN
    fe = ServingFrontend(
        mc.dc_view(), EngineDispatcher(conf, graph=g, dc=dc),
        sconf=ServeConfig(max_batch=16, max_wait_ms=2.0,
                          queue_depth=256, cache_bytes=0,
                          deadline_ms=60_000.0),
        registry=registry, hconf=HedgeConfig(enabled=False),
        membership=mc)
    fe.start()
    try:
        shard1 = qs[dc.worker_of(qs[:, 1]) == 1][:6]
        res = [fe.query(int(s), int(t), timeout=60) for s, t in shard1]
        assert all(r.ok for r in res), [(r.status, r.detail)
                                        for r in res]
        s0, t0 = qs[dc.worker_of(qs[:, 1]) == 0][0]
        steady = fe.query(int(s0), int(t0), timeout=60)
        assert not steady.ok and steady.detail == "circuit-open"
    finally:
        fe.stop()


# --------------------------------------------------------- wire sweep

def test_clean_stale_epoch_files(tmp_path):
    nfs = str(tmp_path)
    old = ["query.localhost1.s0.e2", "answer.localhost1.s0.e2.a0",
           "query.localhost3.e1"]
    keep_young = "query.localhost1.s0.e3"
    keep_plain = ["query.localhost1", "answer.localhost1.a0"]
    for name in old + [keep_young] + keep_plain:
        with open(os.path.join(nfs, name), "w") as f:
            f.write("x")
    past = time.time() - 120
    for name in old + keep_plain:
        os.utime(os.path.join(nfs, name), (past, past))
    s0 = _counter("artifacts_swept_total")
    n = fifo_transport.clean_stale_epoch_files(nfs)
    assert n == len(old)
    assert _counter("artifacts_swept_total") - s0 == len(old)
    left = set(os.listdir(nfs))
    assert keep_young in left                 # age-gated
    assert all(k in left for k in keep_plain)  # non-epoch names kept
    assert not any(o in left for o in old)


# ------------------------------------------------------- dos-obs top

def test_top_tolerates_mixed_statusz_schemas():
    """A rolling upgrade mixes new workers (epoch/migration keys) with
    old ones (no such keys) and the odd garbage payload — every one is
    a row, never a crash."""
    statuses = {
        "new:1": {"serving": {"epoch": 3, "shards": {},
                              "migration": {"kind": "join", "epoch": 4,
                                            "moves": [[0, 0, 3]],
                                            "done": []}}},
        "newworker:2": {"worker": {"batches": 7, "batch_failures": 0,
                                   "epoch": 3}},
        "legacy:3": {"worker": {"batches": 5, "batch_failures": 1}},
        "garbage:4": {"serving": "not-a-dict", "worker": 17,
                      "breakers": ["weird"]},
        "dead:5": {"error": "ConnectionRefusedError: ..."},
        "nulls:6": {"serving": {"shards": {"w0": {"queue_depth": None},
                                           "w1": {"queue_depth": "?"}},
                                "hedge": {"rate": None}},
                    "worker": {"batches": None},
                    "supervisor": {"alive": "yes"}},
    }
    table = obs_fleet.render_top(statuses)
    lines = table.split("\n")
    assert len(lines) == len(statuses) + 2       # header + rule + rows
    assert "epoch" in lines[0] and "migration" in lines[0]
    row_new = next(ln for ln in lines if ln.startswith("new:1"))
    assert "join->e4 0/1" in row_new
    row_legacy = next(ln for ln in lines if ln.startswith("legacy:3"))
    assert " - " in row_legacy                   # blanks, not a crash
    row_dead = next(ln for ln in lines if ln.startswith("dead:5"))
    assert "UNREACHABLE" in row_dead
    row_nulls = next(ln for ln in lines if ln.startswith("nulls:6"))
    assert "up" in row_nulls                     # non-numeric scalars
    # render as defaults, not a TypeError out of the sum()


def test_replica_fast_path_ignores_out_of_range_joiner():
    """A fresh joiner's wid is past maxworker: under the identity
    assignment it hosts NOTHING — the identity modulo must not claim
    another worker's shard for it (that would make the server's
    routing-invariant check silently accept a misroute)."""
    dc = DistributionController("mod", 2, 2, 8, replication=2)
    assert dc.replica_shards(2) == []
    with pytest.raises(ValueError):
        dc.replica_rank(0, 2)


def test_plan_join_share_counts_live_owners(tmp_path):
    """The joiner's balanced share divides by workers that OWN shards,
    not roster slots — departed workers keep their positional roster
    entry and must not dilute the share."""
    import types

    dc = DistributionController("mod", 6, 6, 18)
    conf = types.SimpleNamespace(workers=[f"h{i}" for i in range(6)],
                                 outdir=str(tmp_path))
    mc = fleet.MembershipController(conf, dc)
    mc.state.owners = [0, 0, 0, 1, 1, 1]    # workers 2-5 departed
    mig = mc.plan_join("hnew")
    assert len(mig.moves) == 2              # 6 shards // (2 live + 1)
    assert all(to == 6 for _s, _f, to in mig.moves)


# --------------------------------------------- campaign (non-slow)

def _thread_servers(conf, fifo_dir, monkeypatch, wids):
    os.makedirs(fifo_dir, exist_ok=True)
    fifos = {wid: os.path.join(fifo_dir, f"worker{wid}.fifo")
             for wid in wids}
    monkeypatch.setattr(pq, "command_fifo_path", lambda wid: fifos[wid])
    servers = {wid: FifoServer(conf, wid, command_fifo=fifos[wid])
               for wid in wids}
    threads = {wid: threading.Thread(target=s.serve_forever,
                                     daemon=True)
               for wid, s in servers.items()}
    for t in threads.values():
        t.start()
    for fifo in fifos.values():
        for _ in range(100):
            if os.path.exists(fifo):
                break
            time.sleep(0.02)
    return fifos, threads


def _stop_all(fifos, threads):
    for fifo in fifos.values():
        stop_server(fifo, deadline_s=5.0)
    for t in threads.values():
        t.join(timeout=15)


def _answer_columns(outdir):
    """parts.csv minus the timing columns — the deterministic answer
    payload of a campaign."""
    with open(os.path.join(outdir, "parts.csv")) as fh:
        rows = list(csv.reader(fh))
    hdr = rows[0]
    keep = [hdr.index(k) for k in
            ("expe", "n_expanded", "n_touched", "plen", "finished",
             "size")]
    return [[r[i] for i in keep] for r in rows[1:]]


def test_campaign_routes_by_committed_epoch(world, tmp_path,
                                            monkeypatch):
    """A campaign under a committed epoch (shard 0 owned by the joined
    worker 3) exits 0 with answers bit-identical to the static-fleet
    run — ownership moved, answers did not."""
    monkeypatch.setenv("DOS_RETRY_MAX", "0")
    monkeypatch.setenv("DOS_SEND_TIMEOUT_S", "15")
    faults.reset()
    monkeypatch.delenv("DOS_FAULTS", raising=False)

    # static golden run
    conf_a, g, dc, _out_a = _fresh_world(world, tmp_path, "static",
                                         diffs=["-", "-"])
    conf_a_path = str(tmp_path / "conf-static.json")
    conf_a.save(conf_a_path)
    fifos, threads = _thread_servers(conf_a, str(tmp_path / "f0"),
                                     monkeypatch, range(N_WORKERS))
    out0 = str(tmp_path / "artifacts-static")
    try:
        rc = pq.main(["-c", conf_a_path, "--backend", "host",
                      "-o", out0])
    finally:
        _stop_all(fifos, threads)
    assert rc == pq.EXIT_CLEAN

    # elastic run: commit the join first, then serve with 4 workers
    conf_b, g, dc, out_b = _fresh_world(world, tmp_path, "elastic",
                                        diffs=["-", "-"])
    conf_b_path = str(tmp_path / "conf-elastic.json")
    conf_b.save(conf_b_path)
    mc = fleet.MembershipController(conf_b, dc, graph=g)
    mig = fleet.Migration(epoch=1, kind="join", worker=3,
                          moves=[[0, 0, 3]])
    mc.begin(mig, host="localhost")
    mc.catch_up(mig)
    mc.commit(mig)
    fifos, threads = _thread_servers(conf_b, str(tmp_path / "f1"),
                                     monkeypatch, range(N_WORKERS + 1))
    out1 = str(tmp_path / "artifacts-elastic")
    try:
        rc = pq.main(["-c", conf_b_path, "--backend", "host",
                      "-o", out1])
    finally:
        _stop_all(fifos, threads)
    assert rc == pq.EXIT_CLEAN
    assert not os.path.exists(os.path.join(out1, "degraded.json"))
    assert _answer_columns(out0) == _answer_columns(out1)


# ------------------------------------------------- the chaos drill

@pytest.mark.slow
def test_chaos_join_and_leave_mid_campaign(world, tmp_path,
                                           monkeypatch):
    """The acceptance drill: a worker JOIN and a worker LEAVE are both
    injected while a campaign runs. The campaign exits 0, writes no
    degraded.json, its answer columns are bit-identical to the
    static-fleet run, and the reshard_epoch gauge shows the committed
    bumps (join -> 1, leave -> 2)."""
    monkeypatch.setenv("DOS_RETRY_MAX", "0")
    monkeypatch.setenv("DOS_SEND_TIMEOUT_S", "15")
    n_rounds = 8
    # identical reply-delay fault in BOTH runs: it paces the rounds so
    # the reconfigurations genuinely overlap the campaign, without
    # perturbing the (deterministic) answer payload
    pace = "delay;delay=0.12;times=inf"

    faults.reset()
    monkeypatch.setenv("DOS_FAULTS", pace)
    conf_a, g, dc, _ = _fresh_world(world, tmp_path, "chaos-static",
                                    diffs=["-"] * n_rounds)
    conf_a_path = str(tmp_path / "conf-cs.json")
    conf_a.save(conf_a_path)
    fifos, threads = _thread_servers(conf_a, str(tmp_path / "cf0"),
                                     monkeypatch, range(N_WORKERS))
    out0 = str(tmp_path / "chaos-golden")
    try:
        rc = pq.main(["-c", conf_a_path, "--backend", "host",
                      "-o", out0])
    finally:
        _stop_all(fifos, threads)
    assert rc == pq.EXIT_CLEAN

    faults.reset()
    monkeypatch.setenv("DOS_FAULTS", pace)
    conf_b, g, dc, out_b = _fresh_world(world, tmp_path, "chaos-live",
                                        diffs=["-"] * n_rounds)
    conf_b_path = str(tmp_path / "conf-cl.json")
    conf_b.save(conf_b_path)
    fifo_dir = str(tmp_path / "cf1")
    fifos, threads = _thread_servers(conf_b, fifo_dir, monkeypatch,
                                     range(N_WORKERS))
    # the joiner's server starts inside the drill, on the same fifo map
    fifos[3] = os.path.join(fifo_dir, "worker3.fifo")
    out1 = str(tmp_path / "chaos-answers")
    campaign_rc = {}

    def _campaign():
        campaign_rc["rc"] = pq.main(
            ["-c", conf_b_path, "--backend", "host", "-o", out1])

    th = threading.Thread(target=_campaign, daemon=True)
    th.start()
    try:
        time.sleep(0.4)                      # round 0 in flight
        mc = fleet.MembershipController(conf_b, dc, graph=g)
        # ---- JOIN: worker 3 adopts one shard, serving from the start
        mig = mc.begin(mc.plan_join("localhost"), host="localhost")
        joiner = FifoServer(conf_b, 3, command_fifo=fifos[3])
        jth = threading.Thread(target=joiner.serve_forever, daemon=True)
        jth.start()
        threads[3] = jth
        for _ in range(100):
            if os.path.exists(fifos[3]):
                break
            time.sleep(0.02)
        mc.catch_up(mig)
        mc.commit(mig)                       # epoch 1: routing flips
        time.sleep(0.4)                      # a round runs at epoch 1
        # ---- LEAVE: worker 1's shard transfers to its replica host,
        # then the worker drains and exits 0
        mig2 = mc.begin(mc.plan_leave(1))
        mc.catch_up(mig2)
        mc.commit(mig2)                      # epoch 2
        assert stop_server(fifos[1], deadline_s=5.0)
        threads[1].join(timeout=15)
        assert not threads[1].is_alive()     # drained clean
    finally:
        th.join(timeout=120)
        _stop_all({w: f for w, f in fifos.items() if w != 1}, {
            w: t for w, t in threads.items() if w != 1})
    assert not th.is_alive(), "campaign wedged"
    assert campaign_rc.get("rc") == pq.EXIT_CLEAN
    assert not os.path.exists(os.path.join(out1, "degraded.json"))
    assert _gauge("reshard_epoch") == 2
    assert fleet.current_epoch(out_b) == 2
    assert _answer_columns(out0) == _answer_columns(out1)
    faults.reset()


# ---------------------------------------------- review-hardening pins

def test_leave_fallback_never_targets_departed_worker(world, tmp_path):
    """R=1: after C leaves, its roster slot remains (ids are
    positional) — a later leave's round-robin fallback must pick from
    workers that still OWN shards, never the drained slot."""
    conf, g, dc1, outdir = _fresh_world(world, tmp_path, "leave-r1",
                                        replication=1)
    mc = fleet.MembershipController(conf, dc1, graph=g)
    mig = mc.begin(mc.plan_leave(2))
    mc.catch_up(mig)
    mc.commit(mig)                        # worker 2 drained, slot kept
    assert 2 not in mc.state.owners
    mig2 = mc.plan_leave(0)               # R=1: chains are the leaver
    targets = {to for _s, _f, to in mig2.moves}
    assert 2 not in targets               # never the departed worker
    assert targets <= set(mc.state.owners)


def test_reader_controller_observes_external_commit(world, tmp_path):
    """A long-lived serving-side controller must pick up commits made
    by ANOTHER process (throttled re-read of membership.json)."""
    conf, g, dc1, outdir = _fresh_world(world, tmp_path, "xproc",
                                        replication=1)
    reader = fleet.MembershipController(conf, dc1, graph=g)
    assert reader.candidates_for(0) == [0]
    writer = fleet.MembershipController(conf, dc1, graph=g)
    mig = fleet.Migration(epoch=1, kind="join", worker=3,
                          moves=[[0, 0, 3]])
    writer.begin(mig, host="localhost")
    writer.catch_up(mig)
    writer.commit(mig)
    reader._last_refresh = 0.0            # force the throttle window
    assert reader.candidates_for(0)[0] == 3
    assert reader.epoch == 1


def test_server_learns_window_on_hosted_miss(world, tmp_path):
    """A worker started BEFORE a migration window opens (no epoch bump
    at begin) must refresh on the first dual-read batch instead of
    refusing it — 'no query is shed during handoff'."""
    conf, g, dc1, outdir = _fresh_world(world, tmp_path, "window-miss",
                                        replication=1)
    qs = read_scen(conf.scenfile)
    # worker 2's server starts under the static epoch-0 table
    server = FifoServer(conf, 2, command_fifo=str(tmp_path / "wm.fifo"))
    # another process opens a window adopting shard 0 ONTO worker 2
    mc = fleet.MembershipController(conf, dc1, graph=g)
    mig = fleet.Migration(epoch=1, kind="leave", worker=0,
                          moves=[[0, 0, 2]])
    mc.begin(mig)
    mc.catch_up(mig)                      # window open, not committed
    shard0 = qs[dc1.worker_of(qs[:, 1]) == 0][:4]
    from distributed_oracle_search_tpu.transport.wire import (
        Request, write_query_file,
    )
    qfile = str(tmp_path / "query.window")
    write_query_file(qfile, shard0)
    row = server._handle(Request(RuntimeConfig(), qfile,
                                 str(tmp_path / "ans"), "-"))
    assert row.ok and row.finished == len(shard0)


def test_refresh_never_rolls_epoch_back(world, tmp_path):
    """A lagging read (NFS cache, a restored stale file) must not roll
    a controller's routing back to a drained owner: refresh ignores an
    OLDER on-disk epoch; same-epoch content (a window opened without a
    bump) still applies."""
    conf, g, dc1, outdir = _fresh_world(world, tmp_path, "rollback",
                                        replication=1)
    mc = fleet.MembershipController(conf, dc1, graph=g)
    mig = fleet.Migration(epoch=1, kind="join", worker=3,
                          moves=[[0, 0, 3]])
    mc.begin(mig, host="localhost")
    mc.catch_up(mig)
    committed = mc.commit(mig)
    assert committed.epoch == 1 and mc.candidates_for(0)[0] == 3
    # an operator restores yesterday's epoch-0 state file
    fleet.save_state(outdir, fleet.MembershipState(
        epoch=0, workers=["localhost"] * N_WORKERS,
        owners=list(range(N_WORKERS))))
    mc.refresh()
    assert mc.epoch == 1                  # older state ignored
    assert mc.candidates_for(0)[0] == 3   # routing did not roll back
    # same-epoch content changes still apply (window without a bump)
    newer = fleet.MembershipState(
        epoch=1, workers=committed.workers, owners=committed.owners,
        migration=fleet.Migration(epoch=2, kind="leave", worker=1,
                                  moves=[[1, 1, 2]]).to_dict())
    fleet.save_state(outdir, newer)
    mc.refresh()
    assert mc.state.migration is not None


def test_dc_view_cache_invalidated_across_mutations(world, tmp_path):
    """dc_view's per-generation cache must never pin a pre-commit
    controller: every mutation point bumps the generation, so a cache
    entry built from pre-mutation state can't be mistaken for current
    (the reader-preempted-across-a-commit race)."""
    conf, g, dc1, outdir = _fresh_world(world, tmp_path, "dcgen",
                                        replication=1)
    mc = fleet.MembershipController(conf, dc1, graph=g)
    gen0 = mc._state_gen
    before = mc.dc_view()
    assert before.owner_of(0) == 0
    mig = fleet.Migration(epoch=1, kind="join", worker=3,
                          moves=[[0, 0, 3]])
    mc.begin(mig, host="localhost")
    mc.catch_up(mig)
    mc.commit(mig)
    assert mc._state_gen > gen0
    # a racing reader stuffing the PRE-commit controller back into the
    # cache under the OLD generation must not survive the next view
    mc._dc_cache = (gen0, before)
    assert mc.dc_view().owner_of(0) == 3


def test_round_membership_degrades_to_last_good_pair(world, tmp_path):
    """The campaign's per-round membership re-read must degrade to the
    last-good (table, roster) PAIR: an elastic owner table whose joined
    worker ids are past the static conf roster would otherwise wrap
    onto the wrong hosts."""
    conf, g, dc1, outdir = _fresh_world(world, tmp_path, "lastgood",
                                        replication=1)
    mc = fleet.MembershipController(conf, dc1, graph=g)
    mig = fleet.Migration(epoch=1, kind="join", worker=3,
                          moves=[[0, 0, 3]])
    mc.begin(mig, host="joiner-host")
    mc.catch_up(mig)
    mc.commit(mig)
    mview, dc_r, hosts = pq._round_membership(conf, dc1)
    assert dc_r.owner_of(0) == 3 and hosts[3] == "joiner-host"
    last = (mview, dc_r, hosts)
    # the state file becomes unreadable mid-campaign
    with open(fleet.state_path(outdir), "w") as fh:
        fh.write("{torn")
    assert pq._round_membership(conf, dc1, last=last) == last
    # ... or vanishes entirely: same degrade, never a mixed pair
    os.remove(fleet.state_path(outdir))
    assert pq._round_membership(conf, dc1, last=last) == last
    # a static fleet (no state, no last-good) keeps the static pair
    mview2, dc2, hosts2 = pq._round_membership(conf, dc1)
    assert mview2 is None and dc2 is dc1
    assert hosts2 == list(conf.workers)


def test_frontend_statusz_reports_live_chains(world, tmp_path):
    """/statusz replica chains must be the LIVE candidate chains
    dispatch walks, not the construction-time static ones — during a
    migration window they are exactly what an operator is debugging."""
    conf, g, dc1, outdir = _fresh_world(world, tmp_path, "statusz-live",
                                        replication=1)
    mc = fleet.MembershipController(conf, dc1, graph=g)
    mig = fleet.Migration(epoch=1, kind="join", worker=3,
                          moves=[[0, 0, 3]])
    mc.begin(mig, host="localhost")
    disp = EngineDispatcher(conf, g, dc1)
    fe = ServingFrontend(
        dc1, disp,
        sconf=ServeConfig(max_batch=16, max_wait_ms=2.0,
                          queue_depth=64, cache_bytes=0,
                          deadline_ms=5_000.0),
        hconf=HedgeConfig(enabled=False), membership=mc)
    try:
        fe.start()
        chains = {int(w): s["replicas"]
                  for w, s in fe.statusz()["shards"].items()}
        # dual-read window: old owner authoritative, adopter second
        assert chains[0] == [0, 3]
        assert chains[1] == [1]
    finally:
        fe.stop()


def test_group_queries_dead_remap_reaches_joined_worker():
    """The dead-remap buckets over the ids actually present: an owner
    table naming a JOINED worker (wid >= maxworker) must receive its
    queries, not have them silently vanish outside a fixed
    range(maxworker) walk."""
    dc = DistributionController("mod", 4, 4, 100, epoch=1,
                                owners=[0, 4, 2, 3])
    qs = np.stack([np.zeros(12, np.int64),
                   np.arange(12, dtype=np.int64)], axis=1)
    groups = dc.group_queries(qs, dead=[2])
    assert sum(len(g) for g in groups.values()) == len(qs)
    assert 4 in groups and len(groups[4]) == 3      # shard 1 -> w4
    from distributed_oracle_search_tpu.parallel.partition import (
        UNROUTABLE,
    )
    assert UNROUTABLE in groups                     # shard 2: chain dead
    assert list(groups) == sorted(groups)           # -1 first, ascending


def test_plan_join_records_host(world, tmp_path):
    """plan_join's host rides the Migration record, so begin rosters
    the host the plan was made for without the caller passing it
    twice (an explicit begin(host=...) still wins)."""
    conf, g, dc1, outdir = _fresh_world(world, tmp_path, "planhost",
                                        replication=1)
    mc = fleet.MembershipController(conf, dc1, graph=g)
    mig = mc.plan_join("joiner-host")
    assert mig.host == "joiner-host"
    mc.begin(mig)
    assert mc.state.workers[-1] == "joiner-host"
    mc.abort(mig)
    mig2 = mc.plan_join("planned-host")
    mc.begin(mig2, host="explicit-host")
    assert mc.state.workers[-1] == "explicit-host"


def test_refresh_keeps_dc_cache_on_unchanged_state(world, tmp_path):
    """Steady-state refresh (same on-disk content) must not invalidate
    the dc_view cache: the admission hot path would otherwise re-run
    the O(N) node assignment once per refresh interval."""
    conf, g, dc1, outdir = _fresh_world(world, tmp_path, "steadyref",
                                        replication=1)
    mc = fleet.MembershipController(conf, dc1, graph=g)
    fleet.save_state(outdir, mc.state)
    view = mc.dc_view()
    gen = mc._state_gen
    mc.refresh()
    assert mc._state_gen == gen
    assert mc.dc_view() is view


def test_round_membership_stale_epoch_and_bad_owners(world, tmp_path):
    """The campaign's per-round re-read carries the other read paths'
    guards: an OLDER on-disk epoch never rolls the round's routing
    back, unchanged content reuses the previous round's controller
    (no per-round O(N) rebuild), and a state whose owners do not fit
    the partition degrades instead of crashing the round."""
    conf, g, dc1, outdir = _fresh_world(world, tmp_path, "roundguards",
                                        replication=1)
    mc = fleet.MembershipController(conf, dc1, graph=g)
    mig = fleet.Migration(epoch=1, kind="join", worker=3,
                          moves=[[0, 0, 3]], host="joiner-host")
    mc.begin(mig)
    mc.catch_up(mig)
    mc.commit(mig)
    last = pq._round_membership(conf, dc1)
    assert last[1].owner_of(0) == 3
    # unchanged content: the very same triple comes back (identity —
    # the controller is reused, not rebuilt)
    assert pq._round_membership(conf, dc1, last=last) == last
    # an operator restores yesterday's epoch-0 file mid-campaign
    fleet.save_state(outdir, fleet.MembershipState(
        epoch=0, workers=["localhost"] * N_WORKERS,
        owners=list(range(N_WORKERS))))
    assert pq._round_membership(conf, dc1, last=last) == last
    # owners that do not fit this partition degrade, not crash
    fleet.save_state(outdir, fleet.MembershipState(
        epoch=2, workers=["localhost"] * N_WORKERS, owners=[0, 1]))
    assert pq._round_membership(conf, dc1, last=last) == last
    # ... and with no last-good either, the static pair survives
    mview, dc_r, hosts = pq._round_membership(conf, dc1)
    assert mview is None and dc_r is dc1 and hosts == list(conf.workers)
