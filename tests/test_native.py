"""Native (C++) engine: parity with the Python/JAX side.

The native engine plays warthog's role (SURVEY.md §2.2): same partition
policy, same CPD block files, same FIFO wire protocol. These tests build it
with the real Makefile and cross-check every shared contract:

* ``gen_distribute_conf`` stdout byte-identical to the Python CLI,
* ``make_cpd_auto`` block files byte-identical to the JAX builder
  (Dijkstra vs batched min-plus must agree bit-for-bit, including
  tie-breaks),
* ``fifo_auto`` serving a real campaign over the FIFO wire (raw and
  RLE-compressed shards), interchangeable with the Python server.
"""

import os
import shutil
import subprocess
import time

import numpy as np
import pytest

from distributed_oracle_search_tpu.cli import process_query as pq
from distributed_oracle_search_tpu.cli.args import parse_args
from distributed_oracle_search_tpu.data import ensure_synth_dataset, read_scen
from distributed_oracle_search_tpu.utils.config import ClusterConfig

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.skipif(shutil.which("g++") is None,
                                reason="no C++ toolchain")


@pytest.fixture(scope="module")
def bins():
    """Build the native engine (fast flavor) via the real Makefile."""
    subprocess.run(["make", "-C", os.path.join(REPO, "native"), "fast",
                    "-j4"], check=True, capture_output=True)
    bindir = os.path.join(REPO, "native", "build", "fast", "bin")
    return {name: os.path.join(bindir, name)
            for name in ("make_cpd_auto", "gen_distribute_conf",
                         "fifo_auto")}


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    datadir = str(tmp_path_factory.mktemp("ndata"))
    return datadir, ensure_synth_dataset(datadir, width=10, height=8,
                                         n_queries=96, seed=29)


@pytest.mark.parametrize("method,key", [
    ("mod", ["3"]), ("div", ["27"]), ("tpu", ["0"]),
    ("alloc", ["20", "50", "80"]),
])
def test_gen_distribute_conf_parity(bins, method, key):
    native = subprocess.run(
        [bins["gen_distribute_conf"], "--nodenum", "80", "--maxworker", "3",
         "--partmethod", method, "--partkey", *key],
        capture_output=True, text=True, check=True).stdout
    from distributed_oracle_search_tpu.parallel.partition import (
        DistributionController,
    )
    pk = [int(k) for k in key] if method == "alloc" else int(key[0])
    dc = DistributionController(method, pk, 3, 80)
    assert native.strip() == dc.format_conf().strip()


def test_make_cpd_auto_blocks_match_jax_builder(bins, dataset, tmp_path):
    datadir, paths = dataset
    nidx, pidx = str(tmp_path / "n"), str(tmp_path / "p")
    for wid in range(2):
        subprocess.run(
            [bins["make_cpd_auto"], "--input", paths["xy"],
             "--partmethod", "mod", "--partkey", "2",
             "--workerid", str(wid), "--maxworker", "2",
             "--outdir", nidx, "--block-size", "16"],
            check=True, capture_output=True)
    from distributed_oracle_search_tpu.data import Graph
    from distributed_oracle_search_tpu.models.cpd import build_worker_shard
    from distributed_oracle_search_tpu.parallel.partition import (
        DistributionController,
    )
    g = Graph.from_xy(paths["xy"])
    dc = DistributionController("mod", 2, 2, g.n, block_size=16)
    for wid in range(2):
        build_worker_shard(g, dc, wid, pidx)
    for fname in sorted(os.listdir(nidx)):
        a = np.load(os.path.join(nidx, fname))
        b = np.load(os.path.join(pidx, fname))
        assert a.dtype == b.dtype == np.int8
        assert (a == b).all(), f"{fname}: native vs JAX CPD rows differ"


@pytest.mark.parametrize("compress", [False, True])
def test_fifo_auto_campaign(bins, dataset, tmp_path, monkeypatch, compress):
    """Full host-mode campaign against native resident servers."""
    datadir, paths = dataset
    idx = str(tmp_path / "index")
    for wid in range(2):
        subprocess.run(
            [bins["make_cpd_auto"], "--input", paths["xy"],
             "--partmethod", "mod", "--partkey", "2",
             "--workerid", str(wid), "--maxworker", "2", "--outdir", idx],
            check=True, capture_output=True)
    conf = ClusterConfig(
        workers=["localhost"] * 2, partmethod="mod", partkey=2,
        outdir=idx, xy_file=paths["xy"], scenfile=paths["scen"],
        diffs=["-", paths["diff"]], nfs=str(tmp_path),
    ).validate()

    fifos = {w: str(tmp_path / f"w{w}.fifo") for w in range(2)}
    monkeypatch.setattr(pq, "command_fifo_path", lambda wid: fifos[wid])
    procs = []
    try:
        for wid in range(2):
            cmd = [bins["fifo_auto"], "--input", paths["xy"], paths["diff"],
                   "--partmethod", "mod", "--partkey", "2",
                   "--workerid", str(wid), "--maxworker", "2",
                   "--outdir", idx, "--alg", "table-search",
                   "--fifo", fifos[wid]]
            if compress:
                cmd.append("--compress")
            procs.append(subprocess.Popen(cmd, stderr=subprocess.DEVNULL))
        deadline = time.time() + 15
        while not all(os.path.exists(f) for f in fifos.values()):
            assert time.time() < deadline, "fifo_auto never came up"
            time.sleep(0.05)

        data, stats = pq.run(conf, parse_args(["--backend", "host"]))
        queries = read_scen(conf.scenfile)
        assert data["num_queries"] == len(queries)
        for expe in stats:
            assert sum(r[-1] for r in expe) == len(queries)
            assert sum(r[6] for r in expe) == len(queries)
    finally:
        for f in fifos.values():
            if os.path.exists(f):
                with open(f, "w") as fh:
                    fh.write("__DOS_STOP__\n")
        for p in procs:
            p.wait(timeout=10)


def test_native_and_python_servers_interoperable(bins, dataset, tmp_path,
                                                 monkeypatch):
    """One native worker + one Python worker serving the same campaign:
    the head cannot tell them apart (same wire, same index files)."""
    import threading

    from distributed_oracle_search_tpu.worker import FifoServer, stop_server

    datadir, paths = dataset
    idx = str(tmp_path / "index")
    for wid in range(2):
        subprocess.run(
            [bins["make_cpd_auto"], "--input", paths["xy"],
             "--partmethod", "mod", "--partkey", "2",
             "--workerid", str(wid), "--maxworker", "2", "--outdir", idx],
            check=True, capture_output=True)
    conf = ClusterConfig(
        workers=["localhost"] * 2, partmethod="mod", partkey=2,
        outdir=idx, xy_file=paths["xy"], scenfile=paths["scen"],
        diffs=["-"], nfs=str(tmp_path),
    ).validate()
    fifos = {w: str(tmp_path / f"mix{w}.fifo") for w in range(2)}
    monkeypatch.setattr(pq, "command_fifo_path", lambda wid: fifos[wid])

    native = subprocess.Popen(
        [bins["fifo_auto"], "--input", paths["xy"], "--partmethod", "mod",
         "--partkey", "2", "--workerid", "0", "--maxworker", "2",
         "--outdir", idx, "--alg", "table-search", "--fifo", fifos[0]],
        stderr=subprocess.DEVNULL)
    server = FifoServer(conf, 1, command_fifo=fifos[1])
    th = threading.Thread(target=server.serve_forever, daemon=True)
    th.start()
    try:
        deadline = time.time() + 15
        while not all(os.path.exists(f) for f in fifos.values()):
            assert time.time() < deadline
            time.sleep(0.05)
        data, stats = pq.run(conf, parse_args(["--backend", "host"]))
        queries = read_scen(conf.scenfile)
        assert sum(r[6] for r in stats[0]) == len(queries)
    finally:
        with open(fifos[0], "w") as fh:
            fh.write("__DOS_STOP__\n")
        native.wait(timeout=10)
        stop_server(fifos[1])
        th.join(timeout=10)


def test_gen_distribute_conf_parity_beyond_block_size(bins):
    """bid/bidx must agree past one block (native and Python default block
    sizes must be the same constant)."""
    native = subprocess.run(
        [bins["gen_distribute_conf"], "--nodenum", "40000",
         "--maxworker", "2", "--partmethod", "div", "--partkey", "20000"],
        capture_output=True, text=True, check=True).stdout
    from distributed_oracle_search_tpu.parallel.partition import (
        DistributionController,
    )
    dc = DistributionController("div", 20000, 2, 40000)
    assert native.strip() == dc.format_conf().strip()


def test_fifo_auto_survives_bad_request(bins, dataset, tmp_path):
    """A request naming a nonexistent diff must get a FAIL answer and leave
    the native server resident (not exit), matching the Python server."""
    from distributed_oracle_search_tpu.transport.fifo import send
    from distributed_oracle_search_tpu.transport.wire import (
        Request, RuntimeConfig, write_query_file,
    )

    datadir, paths = dataset
    idx = str(tmp_path / "index")
    subprocess.run(
        [bins["make_cpd_auto"], "--input", paths["xy"], "--partmethod",
         "mod", "--partkey", "1", "--workerid", "0", "--maxworker", "1",
         "--outdir", idx], check=True, capture_output=True)
    fifo = str(tmp_path / "bad.fifo")
    proc = subprocess.Popen(
        [bins["fifo_auto"], "--input", paths["xy"], "--partmethod", "mod",
         "--partkey", "1", "--workerid", "0", "--maxworker", "1",
         "--outdir", idx, "--alg", "table-search", "--fifo", fifo],
        stderr=subprocess.DEVNULL)
    try:
        deadline = time.time() + 15
        while not os.path.exists(fifo):
            assert time.time() < deadline
            time.sleep(0.05)
        qfile = str(tmp_path / "q")
        write_query_file(qfile, np.array([[0, 1]]))
        bad = Request(RuntimeConfig(), qfile, str(tmp_path / "a1.fifo"),
                      "/no/such/diff")
        row = send("localhost", bad, fifo, timeout=30)
        assert not row.ok                      # FAIL sentinel came back
        assert proc.poll() is None             # ...and the server lives
        good = Request(RuntimeConfig(), qfile, str(tmp_path / "a2.fifo"))
        row = send("localhost", good, fifo, timeout=30)
        assert row.ok and row.finished == 1    # still serving correctly
    finally:
        with open(fifo, "w") as fh:
            fh.write("__DOS_STOP__\n")
        proc.wait(timeout=10)


def test_fifo_auto_rejects_misrouted(bins, dataset, tmp_path):
    """Misrouted queries (partition mismatch) answer FAIL loudly instead of
    silently undercounting (Python ShardEngine parity)."""
    from distributed_oracle_search_tpu.parallel.partition import (
        DistributionController,
    )
    from distributed_oracle_search_tpu.transport.fifo import send
    from distributed_oracle_search_tpu.transport.wire import (
        Request, RuntimeConfig, write_query_file,
    )
    from distributed_oracle_search_tpu.data import Graph

    datadir, paths = dataset
    idx = str(tmp_path / "index")
    subprocess.run(
        [bins["make_cpd_auto"], "--input", paths["xy"], "--partmethod",
         "mod", "--partkey", "2", "--workerid", "0", "--maxworker", "2",
         "--outdir", idx], check=True, capture_output=True)
    fifo = str(tmp_path / "mis.fifo")
    proc = subprocess.Popen(
        [bins["fifo_auto"], "--input", paths["xy"], "--partmethod", "mod",
         "--partkey", "2", "--workerid", "0", "--maxworker", "2",
         "--outdir", idx, "--alg", "table-search", "--fifo", fifo],
        stderr=subprocess.DEVNULL)
    try:
        deadline = time.time() + 15
        while not os.path.exists(fifo):
            assert time.time() < deadline
            time.sleep(0.05)
        g = Graph.from_xy(paths["xy"])
        dc = DistributionController("mod", 2, 2, g.n)
        t_other = int(np.nonzero(dc.worker_of(np.arange(g.n)) == 1)[0][0])
        qfile = str(tmp_path / "qm")
        write_query_file(qfile, np.array([[0, t_other]]))
        req = Request(RuntimeConfig(), qfile, str(tmp_path / "am.fifo"))
        row = send("localhost", req, fifo, timeout=30)
        assert not row.ok
    finally:
        with open(fifo, "w") as fh:
            fh.write("__DOS_STOP__\n")
        proc.wait(timeout=10)


def test_fifo_auto_astar(bins, dataset, tmp_path):
    """--alg astar answers optimally (hscale=1 euclidean heuristic is
    admissible) with live priority-queue counters."""
    from distributed_oracle_search_tpu.data import Graph
    from distributed_oracle_search_tpu.models.reference import dist_to_target
    from distributed_oracle_search_tpu.transport.fifo import send
    from distributed_oracle_search_tpu.transport.wire import (
        Request, RuntimeConfig, write_query_file,
    )

    datadir, paths = dataset
    fifo = str(tmp_path / "astar.fifo")
    proc = subprocess.Popen(
        [bins["fifo_auto"], "--input", paths["xy"], "--partmethod", "mod",
         "--partkey", "1", "--workerid", "0", "--maxworker", "1",
         "--outdir", str(tmp_path), "--alg", "astar", "--fifo", fifo],
        stderr=subprocess.DEVNULL)
    try:
        deadline = time.time() + 15
        while not os.path.exists(fifo):
            assert time.time() < deadline
            time.sleep(0.05)
        g = Graph.from_xy(paths["xy"])
        queries = read_scen(paths["scen"])[:16]
        qfile = str(tmp_path / "qa")
        write_query_file(qfile, queries)
        req = Request(RuntimeConfig(hscale=1.0), qfile,
                      str(tmp_path / "aa.fifo"))
        row = send("localhost", req, fifo, timeout=60)
        assert row.ok
        assert row.finished == len(queries)
        assert row.n_expanded > 0 and row.n_inserted > 0
        # optimal path lengths: plen sum must equal the oracle's hop counts
        # is not guaranteed (ties), but costs are checked via plen>0 and
        # the finished count; cost itself is not on the stats wire.
    finally:
        with open(fifo, "w") as fh:
            fh.write("__DOS_STOP__\n")
        proc.wait(timeout=10)
