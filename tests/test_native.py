"""Native (C++) engine: parity with the Python/JAX side.

The native engine plays warthog's role (SURVEY.md §2.2): same partition
policy, same CPD block files, same FIFO wire protocol. These tests build it
with the real Makefile and cross-check every shared contract:

* ``gen_distribute_conf`` stdout byte-identical to the Python CLI,
* ``make_cpd_auto`` block files byte-identical to the JAX builder
  (Dijkstra vs batched min-plus must agree bit-for-bit, including
  tie-breaks),
* ``fifo_auto`` serving a real campaign over the FIFO wire (raw and
  RLE-compressed shards), interchangeable with the Python server.
"""

import os
import shutil
import subprocess
import time

import numpy as np
import pytest

from distributed_oracle_search_tpu.cli import process_query as pq
from distributed_oracle_search_tpu.cli.args import parse_args
from distributed_oracle_search_tpu.data import ensure_synth_dataset, read_scen
from distributed_oracle_search_tpu.utils.config import ClusterConfig

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.skipif(shutil.which("g++") is None,
                                reason="no C++ toolchain")


@pytest.fixture(scope="module")
def bins():
    """Build the native engine (fast flavor) via the real Makefile."""
    subprocess.run(["make", "-C", os.path.join(REPO, "native"), "fast",
                    "-j4"], check=True, capture_output=True)
    bindir = os.path.join(REPO, "native", "build", "fast", "bin")
    return {name: os.path.join(bindir, name)
            for name in ("make_cpd_auto", "gen_distribute_conf",
                         "fifo_auto", "ch_check")}


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    datadir = str(tmp_path_factory.mktemp("ndata"))
    return datadir, ensure_synth_dataset(datadir, width=10, height=8,
                                         n_queries=96, seed=29)


@pytest.mark.parametrize("method,key", [
    ("mod", ["3"]), ("div", ["27"]), ("tpu", ["0"]),
    ("alloc", ["20", "50", "80"]),
])
def test_gen_distribute_conf_parity(bins, method, key):
    native = subprocess.run(
        [bins["gen_distribute_conf"], "--nodenum", "80", "--maxworker", "3",
         "--partmethod", method, "--partkey", *key],
        capture_output=True, text=True, check=True).stdout
    from distributed_oracle_search_tpu.parallel.partition import (
        DistributionController,
    )
    pk = [int(k) for k in key] if method == "alloc" else int(key[0])
    dc = DistributionController(method, pk, 3, 80)
    assert native.strip() == dc.format_conf().strip()


def test_make_cpd_auto_blocks_match_jax_builder(bins, dataset, tmp_path):
    datadir, paths = dataset
    nidx, pidx = str(tmp_path / "n"), str(tmp_path / "p")
    for wid in range(2):
        subprocess.run(
            [bins["make_cpd_auto"], "--input", paths["xy"],
             "--partmethod", "mod", "--partkey", "2",
             "--workerid", str(wid), "--maxworker", "2",
             "--outdir", nidx, "--block-size", "16"],
            check=True, capture_output=True)
    from distributed_oracle_search_tpu.data import Graph
    from distributed_oracle_search_tpu.models.cpd import build_worker_shard
    from distributed_oracle_search_tpu.parallel.partition import (
        DistributionController,
    )
    g = Graph.from_xy(paths["xy"])
    dc = DistributionController("mod", 2, 2, g.n, block_size=16)
    for wid in range(2):
        build_worker_shard(g, dc, wid, pidx)
    for fname in sorted(os.listdir(nidx)):
        a = np.load(os.path.join(nidx, fname))
        b = np.load(os.path.join(pidx, fname))
        assert a.dtype == b.dtype == np.int8
        assert (a == b).all(), f"{fname}: native vs JAX CPD rows differ"


@pytest.mark.parametrize("compress", [False, True])
def test_fifo_auto_campaign(bins, dataset, tmp_path, monkeypatch, compress):
    """Full host-mode campaign against native resident servers."""
    datadir, paths = dataset
    idx = str(tmp_path / "index")
    for wid in range(2):
        subprocess.run(
            [bins["make_cpd_auto"], "--input", paths["xy"],
             "--partmethod", "mod", "--partkey", "2",
             "--workerid", str(wid), "--maxworker", "2", "--outdir", idx],
            check=True, capture_output=True)
    conf = ClusterConfig(
        workers=["localhost"] * 2, partmethod="mod", partkey=2,
        outdir=idx, xy_file=paths["xy"], scenfile=paths["scen"],
        diffs=["-", paths["diff"]], nfs=str(tmp_path),
    ).validate()

    fifos = {w: str(tmp_path / f"w{w}.fifo") for w in range(2)}
    monkeypatch.setattr(pq, "command_fifo_path", lambda wid: fifos[wid])
    procs = []
    try:
        for wid in range(2):
            cmd = [bins["fifo_auto"], "--input", paths["xy"], paths["diff"],
                   "--partmethod", "mod", "--partkey", "2",
                   "--workerid", str(wid), "--maxworker", "2",
                   "--outdir", idx, "--alg", "table-search",
                   "--fifo", fifos[wid]]
            if compress:
                cmd.append("--compress")
            procs.append(subprocess.Popen(cmd, stderr=subprocess.DEVNULL))
        deadline = time.time() + 15
        while not all(os.path.exists(f) for f in fifos.values()):
            assert time.time() < deadline, "fifo_auto never came up"
            time.sleep(0.05)

        data, stats, _paths = pq.run(conf, parse_args(["--backend", "host"]))
        queries = read_scen(conf.scenfile)
        assert data["num_queries"] == len(queries)
        for expe in stats:
            assert sum(r[-1] for r in expe) == len(queries)
            assert sum(r[6] for r in expe) == len(queries)
    finally:
        for f in fifos.values():
            if os.path.exists(f):
                with open(f, "w") as fh:
                    fh.write("__DOS_STOP__\n")
        for p in procs:
            p.wait(timeout=10)


def test_fifo_auto_time_budget_truncates_batch(bins, dataset, tmp_path,
                                               monkeypatch):
    """A tiny ns budget truncates inside the native engine's batch too:
    partial ``finished`` counts through the full wire (reference
    semantics, reference ``args.py:30-57``); the first query always
    answers."""
    datadir, paths = dataset
    idx = str(tmp_path / "index")
    for wid in range(2):
        subprocess.run(
            [bins["make_cpd_auto"], "--input", paths["xy"],
             "--partmethod", "mod", "--partkey", "2",
             "--workerid", str(wid), "--maxworker", "2", "--outdir", idx],
            check=True, capture_output=True)
    conf = ClusterConfig(
        workers=["localhost"] * 2, partmethod="mod", partkey=2,
        outdir=idx, xy_file=paths["xy"], scenfile=paths["scen"],
        diffs=["-"], nfs=str(tmp_path),
    ).validate()
    fifos = {w: str(tmp_path / f"w{w}.fifo") for w in range(2)}
    monkeypatch.setattr(pq, "command_fifo_path", lambda wid: fifos[wid])
    procs = []
    try:
        for wid in range(2):
            procs.append(subprocess.Popen(
                [bins["fifo_auto"], "--input", paths["xy"],
                 "--partmethod", "mod", "--partkey", "2",
                 "--workerid", str(wid), "--maxworker", "2",
                 "--outdir", idx, "--alg", "table-search",
                 "--fifo", fifos[wid]], stderr=subprocess.DEVNULL))
        deadline = time.time() + 15
        while not all(os.path.exists(f) for f in fifos.values()):
            assert time.time() < deadline, "fifo_auto never came up"
            time.sleep(0.05)
        _, stats, _ = pq.run(conf, parse_args(["--backend", "host",
                                               "--ns-lim", "1"]))
        n = len(read_scen(conf.scenfile))
        for expe in stats:
            finished = sum(r[6] for r in expe)
            assert 2 <= finished < n, finished
        # no budget: every query finishes
        _, stats_full, _ = pq.run(conf, parse_args(["--backend", "host"]))
        for expe in stats_full:
            assert sum(r[6] for r in expe) == n
    finally:
        for f in fifos.values():
            if os.path.exists(f):
                with open(f, "w") as fh:
                    fh.write("__DOS_STOP__\n")
        for p in procs:
            p.wait(timeout=10)


def test_native_and_python_servers_interoperable(bins, dataset, tmp_path,
                                                 monkeypatch):
    """One native worker + one Python worker serving the same campaign:
    the head cannot tell them apart (same wire, same index files)."""
    import threading

    from distributed_oracle_search_tpu.worker import FifoServer, stop_server

    datadir, paths = dataset
    idx = str(tmp_path / "index")
    for wid in range(2):
        subprocess.run(
            [bins["make_cpd_auto"], "--input", paths["xy"],
             "--partmethod", "mod", "--partkey", "2",
             "--workerid", str(wid), "--maxworker", "2", "--outdir", idx],
            check=True, capture_output=True)
    conf = ClusterConfig(
        workers=["localhost"] * 2, partmethod="mod", partkey=2,
        outdir=idx, xy_file=paths["xy"], scenfile=paths["scen"],
        diffs=["-"], nfs=str(tmp_path),
    ).validate()
    fifos = {w: str(tmp_path / f"mix{w}.fifo") for w in range(2)}
    monkeypatch.setattr(pq, "command_fifo_path", lambda wid: fifos[wid])

    native = subprocess.Popen(
        [bins["fifo_auto"], "--input", paths["xy"], "--partmethod", "mod",
         "--partkey", "2", "--workerid", "0", "--maxworker", "2",
         "--outdir", idx, "--alg", "table-search", "--fifo", fifos[0]],
        stderr=subprocess.DEVNULL)
    server = FifoServer(conf, 1, command_fifo=fifos[1])
    th = threading.Thread(target=server.serve_forever, daemon=True)
    th.start()
    try:
        deadline = time.time() + 15
        while not all(os.path.exists(f) for f in fifos.values()):
            assert time.time() < deadline
            time.sleep(0.05)
        data, stats, _paths = pq.run(conf, parse_args(["--backend", "host"]))
        queries = read_scen(conf.scenfile)
        assert sum(r[6] for r in stats[0]) == len(queries)
    finally:
        with open(fifos[0], "w") as fh:
            fh.write("__DOS_STOP__\n")
        native.wait(timeout=10)
        stop_server(fifos[1])
        th.join(timeout=10)


def test_gen_distribute_conf_parity_beyond_block_size(bins):
    """bid/bidx must agree past one block (native and Python default block
    sizes must be the same constant)."""
    native = subprocess.run(
        [bins["gen_distribute_conf"], "--nodenum", "40000",
         "--maxworker", "2", "--partmethod", "div", "--partkey", "20000"],
        capture_output=True, text=True, check=True).stdout
    from distributed_oracle_search_tpu.parallel.partition import (
        DistributionController,
    )
    dc = DistributionController("div", 20000, 2, 40000)
    assert native.strip() == dc.format_conf().strip()


def test_fifo_auto_survives_bad_request(bins, dataset, tmp_path):
    """A request naming a nonexistent diff must get a FAIL answer and leave
    the native server resident (not exit), matching the Python server."""
    from distributed_oracle_search_tpu.transport.fifo import send
    from distributed_oracle_search_tpu.transport.wire import (
        Request, RuntimeConfig, write_query_file,
    )

    datadir, paths = dataset
    idx = str(tmp_path / "index")
    subprocess.run(
        [bins["make_cpd_auto"], "--input", paths["xy"], "--partmethod",
         "mod", "--partkey", "1", "--workerid", "0", "--maxworker", "1",
         "--outdir", idx], check=True, capture_output=True)
    fifo = str(tmp_path / "bad.fifo")
    proc = subprocess.Popen(
        [bins["fifo_auto"], "--input", paths["xy"], "--partmethod", "mod",
         "--partkey", "1", "--workerid", "0", "--maxworker", "1",
         "--outdir", idx, "--alg", "table-search", "--fifo", fifo],
        stderr=subprocess.DEVNULL)
    try:
        deadline = time.time() + 15
        while not os.path.exists(fifo):
            assert time.time() < deadline
            time.sleep(0.05)
        qfile = str(tmp_path / "q")
        write_query_file(qfile, np.array([[0, 1]]))
        bad = Request(RuntimeConfig(), qfile, str(tmp_path / "a1.fifo"),
                      "/no/such/diff")
        row = send("localhost", bad, fifo, timeout=30)
        assert not row.ok                      # FAIL sentinel came back
        assert proc.poll() is None             # ...and the server lives
        good = Request(RuntimeConfig(), qfile, str(tmp_path / "a2.fifo"))
        row = send("localhost", good, fifo, timeout=30)
        assert row.ok and row.finished == 1    # still serving correctly
    finally:
        with open(fifo, "w") as fh:
            fh.write("__DOS_STOP__\n")
        proc.wait(timeout=10)


def test_fifo_auto_rejects_misrouted(bins, dataset, tmp_path):
    """Misrouted queries (partition mismatch) answer FAIL loudly instead of
    silently undercounting (Python ShardEngine parity)."""
    from distributed_oracle_search_tpu.parallel.partition import (
        DistributionController,
    )
    from distributed_oracle_search_tpu.transport.fifo import send
    from distributed_oracle_search_tpu.transport.wire import (
        Request, RuntimeConfig, write_query_file,
    )
    from distributed_oracle_search_tpu.data import Graph

    datadir, paths = dataset
    idx = str(tmp_path / "index")
    subprocess.run(
        [bins["make_cpd_auto"], "--input", paths["xy"], "--partmethod",
         "mod", "--partkey", "2", "--workerid", "0", "--maxworker", "2",
         "--outdir", idx], check=True, capture_output=True)
    fifo = str(tmp_path / "mis.fifo")
    proc = subprocess.Popen(
        [bins["fifo_auto"], "--input", paths["xy"], "--partmethod", "mod",
         "--partkey", "2", "--workerid", "0", "--maxworker", "2",
         "--outdir", idx, "--alg", "table-search", "--fifo", fifo],
        stderr=subprocess.DEVNULL)
    try:
        deadline = time.time() + 15
        while not os.path.exists(fifo):
            assert time.time() < deadline
            time.sleep(0.05)
        g = Graph.from_xy(paths["xy"])
        dc = DistributionController("mod", 2, 2, g.n)
        t_other = int(np.nonzero(dc.worker_of(np.arange(g.n)) == 1)[0][0])
        qfile = str(tmp_path / "qm")
        write_query_file(qfile, np.array([[0, t_other]]))
        req = Request(RuntimeConfig(), qfile, str(tmp_path / "am.fifo"))
        row = send("localhost", req, fifo, timeout=30)
        assert not row.ok
    finally:
        with open(fifo, "w") as fh:
            fh.write("__DOS_STOP__\n")
        proc.wait(timeout=10)


def test_fifo_auto_astar(bins, dataset, tmp_path):
    """--alg astar answers optimally (hscale=1 euclidean heuristic is
    admissible) with live priority-queue counters."""
    from distributed_oracle_search_tpu.data import Graph
    from distributed_oracle_search_tpu.models.reference import dist_to_target
    from distributed_oracle_search_tpu.transport.fifo import send
    from distributed_oracle_search_tpu.transport.wire import (
        Request, RuntimeConfig, write_query_file,
    )

    datadir, paths = dataset
    fifo = str(tmp_path / "astar.fifo")
    proc = subprocess.Popen(
        [bins["fifo_auto"], "--input", paths["xy"], "--partmethod", "mod",
         "--partkey", "1", "--workerid", "0", "--maxworker", "1",
         "--outdir", str(tmp_path), "--alg", "astar", "--fifo", fifo],
        stderr=subprocess.DEVNULL)
    try:
        deadline = time.time() + 15
        while not os.path.exists(fifo):
            assert time.time() < deadline
            time.sleep(0.05)
        g = Graph.from_xy(paths["xy"])
        queries = read_scen(paths["scen"])[:16]
        qfile = str(tmp_path / "qa")
        write_query_file(qfile, queries)
        req = Request(RuntimeConfig(hscale=1.0), qfile,
                      str(tmp_path / "aa.fifo"))
        row = send("localhost", req, fifo, timeout=60)
        assert row.ok
        assert row.finished == len(queries)
        assert row.n_expanded > 0 and row.n_inserted > 0
        # optimal path lengths: plen sum must equal the oracle's hop counts
        # is not guaranteed (ties), but costs are checked via plen>0 and
        # the finished count; cost itself is not on the stats wire.
    finally:
        with open(fifo, "w") as fh:
            fh.write("__DOS_STOP__\n")
        proc.wait(timeout=10)


def test_ch_golden_vs_dijkstra(bins, dataset):
    """Contraction hierarchies (the reference's congestion-free TODO,
    reference README.md:133): every scen query's CH cost is bit-equal to
    Dijkstra's, and the hierarchy does strictly less expansion work —
    verified by the native self-check harness (ch_check.cpp)."""
    datadir, paths = dataset
    r = subprocess.run([bins["ch_check"], paths["xy"], paths["scen"]],
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr[-1000:]
    assert r.stdout.startswith("CH_OK"), r.stdout
    fields = dict(kv.split("=") for kv in r.stdout.split()[1:])
    assert int(fields["queries"]) == 96
    assert int(fields["ch_expanded"]) < int(fields["dijkstra_expanded"])


def test_fifo_auto_ch(bins, dataset, tmp_path):
    """--alg ch serves over the same FIFO wire; a congestion diff in the
    request is ignored with a warning (free-flow answers)."""
    from distributed_oracle_search_tpu.data import read_scen
    from distributed_oracle_search_tpu.transport.fifo import send
    from distributed_oracle_search_tpu.transport.wire import (
        Request, RuntimeConfig, write_query_file,
    )

    datadir, paths = dataset
    fifo = str(tmp_path / "ch.fifo")
    proc = subprocess.Popen(
        [bins["fifo_auto"], "--input", paths["xy"], "--partmethod", "mod",
         "--partkey", "1", "--workerid", "0", "--maxworker", "1",
         "--outdir", str(tmp_path), "--alg", "ch", "--fifo", fifo],
        stderr=subprocess.DEVNULL)
    try:
        deadline = time.time() + 30
        while not os.path.exists(fifo):
            assert time.time() < deadline
            time.sleep(0.05)
        queries = read_scen(paths["scen"])[:24]
        qfile = str(tmp_path / "qch")
        write_query_file(qfile, queries)
        row = send("localhost", Request(RuntimeConfig(), qfile,
                                        str(tmp_path / "ach.fifo")),
                   fifo, timeout=60)
        assert row.ok and row.finished == len(queries)
        assert row.n_expanded > 0 and row.plen > 0
        # diffed request: still answered (free-flow), not FAIL
        row2 = send("localhost", Request(RuntimeConfig(), qfile,
                                         str(tmp_path / "ach2.fifo"),
                                         paths["diff"]),
                    fifo, timeout=60)
        assert row2.ok and row2.finished == len(queries)
        assert row2.plen == row.plen
    finally:
        with open(fifo, "w") as fh:
            fh.write("__DOS_STOP__\n")
        proc.wait(timeout=10)


def _start_native_server(bins, paths, idx, fifo, extra=(), env=None):
    proc = subprocess.Popen(
        [bins["fifo_auto"], "--input", paths["xy"], "--partmethod", "mod",
         "--partkey", "2", "--workerid", "0", "--maxworker", "2",
         "--outdir", idx, "--alg", "table-search", "--fifo", fifo,
         *extra],
        stderr=subprocess.DEVNULL,
        env={**os.environ, **(env or {})})
    deadline = time.time() + 15
    while not os.path.exists(fifo):
        assert time.time() < deadline, "fifo_auto never came up"
        time.sleep(0.05)
    return proc


def _native_request(fifo, tmp_path, queries, cfg_json, tag="req"):
    """Push one raw 2-line request; returns the reply line."""
    from distributed_oracle_search_tpu.transport.wire import (
        write_query_file,
    )
    qfile = str(tmp_path / f"{tag}.query")
    afifo = str(tmp_path / f"{tag}.answer")
    write_query_file(qfile, queries)
    os.mkfifo(afifo)
    try:
        with open(fifo, "w") as f:
            f.write(cfg_json + "\n" + f"{qfile} {afifo} -\n")
        with open(afifo) as f:
            return f.readline().strip(), qfile
    finally:
        os.unlink(afifo)


@pytest.fixture(scope="module")
def native_index(bins, dataset, tmp_path_factory):
    datadir, paths = dataset
    idx = str(tmp_path_factory.mktemp("nidx"))
    for wid in range(2):
        subprocess.run(
            [bins["make_cpd_auto"], "--input", paths["xy"],
             "--partmethod", "mod", "--partkey", "2",
             "--workerid", str(wid), "--maxworker", "2", "--outdir", idx],
            check=True, capture_output=True)
    return paths, idx


def test_native_extract_paths_parity(bins, native_index, tmp_path):
    """Native --extract emits the same .paths file the Python engine
    produces (golden vs the CPU oracle walk)."""
    from distributed_oracle_search_tpu.data import Graph
    from distributed_oracle_search_tpu.models.reference import (
        first_move_to_target, table_search_walk,
    )
    from distributed_oracle_search_tpu.parallel import (
        DistributionController,
    )
    from distributed_oracle_search_tpu.transport.wire import (
        read_paths_file,
    )

    paths, idx = native_index
    g = Graph.from_xy(paths["xy"])
    dc = DistributionController("mod", 2, 2, g.n)
    scen = read_scen(paths["scen"])
    mine = scen[dc.worker_of(scen[:, 1]) == 0][:12]
    fifo = str(tmp_path / "ex.fifo")
    proc = _start_native_server(bins, paths, idx, fifo)
    try:
        cfg = ('{"hscale": 1.0, "fscale": 0.0, "time": 0, "itrs": 1, '
               '"k_moves": 6, "threads": 1, "verbose": 0, "debug": false, '
               '"thread_alloc": 0, "no_cache": false, "extract": true}')
        reply, qfile = _native_request(fifo, tmp_path, mine, cfg, "ex")
        assert reply != "FAIL"
        nodes, moves = read_paths_file(qfile + ".paths")
        assert nodes.shape == (len(mine), 7)
        for (s, t), nrow, m in zip(mine, nodes, moves):
            fm_col = first_move_to_target(g, int(t))
            _, gm, _, path = table_search_walk(
                g, lambda x, _t: fm_col[x], int(s), int(t), k_moves=6)
            path = path + [path[-1]] * (7 - len(path))
            assert m == min(gm, 6)
            assert list(nrow) == path[:7]
    finally:
        with open(fifo, "w") as fh:
            fh.write("__DOS_STOP__\n")
        proc.wait(timeout=10)


def test_native_json_parser_hardened(bins, native_index, tmp_path):
    """Valid-but-awkward JSON configs the Python side could legally emit:
    string values, scientific notation, key names inside strings, nested
    containers — none may corrupt the parsed knobs."""
    from distributed_oracle_search_tpu.data import Graph
    from distributed_oracle_search_tpu.parallel import (
        DistributionController,
    )

    paths, idx = native_index
    g = Graph.from_xy(paths["xy"])
    dc = DistributionController("mod", 2, 2, g.n)
    scen = read_scen(paths["scen"])
    mine = scen[dc.worker_of(scen[:, 1]) == 0][:8]
    fifo = str(tmp_path / "fz.fifo")
    proc = _start_native_server(bins, paths, idx, fifo)
    nasty = [
        # string value containing a known key name + escaped quote
        ('{"note": "k_moves\\" bogus: 99", "k_moves": -1, "itrs": 1, '
         '"threads": 1, "no_cache": false}'),
        # scientific notation and + signs
        '{"itrs": 1e0, "k_moves": -1, "time": 0E0, "threads": 1}',
        # nested container values (future extension) skipped balanced
        ('{"meta": {"k_moves": 77, "arr": [1, 2, "x]"]}, "k_moves": -1, '
         '"itrs": 1, "threads": 1}'),
        # null values and unicode escapes
        '{"extra": null, "tag": "\\u0041", "k_moves": -1, "threads": 1}',
    ]
    try:
        for i, cfg in enumerate(nasty):
            reply, _ = _native_request(fifo, tmp_path, mine, cfg, f"fz{i}")
            assert reply != "FAIL", f"config {i} failed: {cfg}"
            fields = reply.split(",")
            assert len(fields) == 10
            assert int(fields[6]) == len(mine), \
                f"config {i}: finished {fields[6]} != {len(mine)}"
    finally:
        with open(fifo, "w") as fh:
            fh.write("__DOS_STOP__\n")
        proc.wait(timeout=10)


def test_native_time_budget_bounds_itrs(bins, native_index, tmp_path):
    """`time` ns budget must break the itrs repetition loop (ADVICE wire-
    parity gap): 1000 itrs with a 1ns budget returns ~immediately."""
    from distributed_oracle_search_tpu.data import Graph
    from distributed_oracle_search_tpu.parallel import (
        DistributionController,
    )

    paths, idx = native_index
    g = Graph.from_xy(paths["xy"])
    dc = DistributionController("mod", 2, 2, g.n)
    scen = read_scen(paths["scen"])
    mine = scen[dc.worker_of(scen[:, 1]) == 0]
    fifo = str(tmp_path / "tb.fifo")
    proc = _start_native_server(bins, paths, idx, fifo)
    try:
        cfg = '{"itrs": 100000, "time": 1, "k_moves": -1, "threads": 1}'
        t0 = time.time()
        reply, _ = _native_request(fifo, tmp_path, mine, cfg, "tb")
        elapsed = time.time() - t0
        assert reply != "FAIL"
        assert elapsed < 30, "time budget did not bound the itrs loop"
    finally:
        with open(fifo, "w") as fh:
            fh.write("__DOS_STOP__\n")
        proc.wait(timeout=10)


def test_native_server_survives_dead_reader(bins, native_index, tmp_path):
    """A request whose answer FIFO never gets a reader (head died) must
    not wedge the server: the next request still gets served."""
    from distributed_oracle_search_tpu.data import Graph
    from distributed_oracle_search_tpu.parallel import (
        DistributionController,
    )
    from distributed_oracle_search_tpu.transport.wire import (
        write_query_file,
    )

    paths, idx = native_index
    g = Graph.from_xy(paths["xy"])
    dc = DistributionController("mod", 2, 2, g.n)
    scen = read_scen(paths["scen"])
    mine = scen[dc.worker_of(scen[:, 1]) == 0][:4]
    fifo = str(tmp_path / "dr.fifo")
    proc = _start_native_server(bins, paths, idx, fifo,
                                env={"DOS_REPLY_DEADLINE_S": "2"})
    try:
        # request 1: nonexistent answer fifo, nobody will ever read it.
        # The server waits its bounded deadline (2s here) then drops.
        qfile = str(tmp_path / "dead.query")
        write_query_file(qfile, mine)
        with open(fifo, "w") as f:
            f.write('{"itrs": 1, "threads": 1}\n'
                    f"{qfile} {tmp_path}/nonexistent.answer -\n")
        # request 2 must still be answered (within the drop deadline +
        # margin)
        t0 = time.time()
        reply, _ = _native_request(fifo, tmp_path, mine,
                                   '{"itrs": 1, "threads": 1}', "dr")
        assert reply != "FAIL"
        assert int(reply.split(",")[6]) == len(mine)
        assert time.time() - t0 < 60
    finally:
        with open(fifo, "w") as fh:
            fh.write("__DOS_STOP__\n")
        proc.wait(timeout=10)


def test_native_server_back_to_back_writers(bins, native_index, tmp_path):
    """N separate writers in quick succession must each get a reply — the
    reference's documented FIFO race (reference README.md:125-127): with an
    open-to-EOF session a second writer's request could land in the dying
    session and be silently dropped. The framed persistent-reader protocol
    must serve all N."""
    from distributed_oracle_search_tpu.data import Graph
    from distributed_oracle_search_tpu.parallel import (
        DistributionController,
    )
    from distributed_oracle_search_tpu.transport.wire import (
        write_query_file,
    )

    paths, idx = native_index
    g = Graph.from_xy(paths["xy"])
    dc = DistributionController("mod", 2, 2, g.n)
    scen = read_scen(paths["scen"])
    mine = scen[dc.worker_of(scen[:, 1]) == 0][:4]
    fifo = str(tmp_path / "b2b.fifo")
    proc = _start_native_server(bins, paths, idx, fifo)
    n = 8
    try:
        afifos = []
        for k in range(n):
            qfile = str(tmp_path / f"b2b{k}.query")
            afifo = str(tmp_path / f"b2b{k}.answer")
            write_query_file(qfile, mine)
            os.mkfifo(afifo)
            afifos.append(afifo)
            # fresh writer per request, no pause: the old protocol would
            # coalesce these into one session and drop all but the first
            with open(fifo, "w") as f:
                f.write('{"itrs": 1, "threads": 1}\n'
                        f"{qfile} {afifo} -\n")
        for afifo in afifos:
            with open(afifo) as f:
                reply = f.readline().strip()
            assert reply != "FAIL"
            assert int(reply.split(",")[6]) == len(mine)
    finally:
        with open(fifo, "w") as fh:
            fh.write("__DOS_STOP__\n")
        proc.wait(timeout=10)


def test_native_server_resyncs_after_half_frame(bins, native_index,
                                                tmp_path):
    """A 1-line garbage write must not desync the framed stream: after the
    frame timeout the server discards it and the next real request is
    served intact."""
    from distributed_oracle_search_tpu.data import Graph
    from distributed_oracle_search_tpu.parallel import (
        DistributionController,
    )

    paths, idx = native_index
    g = Graph.from_xy(paths["xy"])
    dc = DistributionController("mod", 2, 2, g.n)
    scen = read_scen(paths["scen"])
    mine = scen[dc.worker_of(scen[:, 1]) == 0][:4]
    fifo = str(tmp_path / "hf.fifo")
    proc = _start_native_server(bins, paths, idx, fifo)
    try:
        with open(fifo, "w") as f:
            f.write("this is not a frame\n")   # no line 2 will follow
        time.sleep(2.5)                        # > the 2s frame timeout
        reply, _ = _native_request(fifo, tmp_path, mine,
                                   '{"itrs": 1, "threads": 1}', "hf")
        assert reply != "FAIL"
        assert int(reply.split(",")[6]) == len(mine)
    finally:
        with open(fifo, "w") as fh:
            fh.write("__DOS_STOP__\n")
        proc.wait(timeout=10)


def test_native_server_garbage_then_immediate_request(bins, native_index,
                                                      tmp_path):
    """Garbage followed IMMEDIATELY by a real request (no quiet window):
    frame-start validation must handle the stray line standalone and serve
    the real request intact."""
    from distributed_oracle_search_tpu.data import Graph
    from distributed_oracle_search_tpu.parallel import (
        DistributionController,
    )
    from distributed_oracle_search_tpu.transport.wire import (
        write_query_file,
    )

    paths, idx = native_index
    g = Graph.from_xy(paths["xy"])
    dc = DistributionController("mod", 2, 2, g.n)
    scen = read_scen(paths["scen"])
    mine = scen[dc.worker_of(scen[:, 1]) == 0][:4]
    fifo = str(tmp_path / "gi.fifo")
    proc = _start_native_server(bins, paths, idx, fifo)
    try:
        qfile = str(tmp_path / "gi.query")
        afifo = str(tmp_path / "gi.answer")
        write_query_file(qfile, mine)
        os.mkfifo(afifo)
        with open(fifo, "w") as f:   # garbage + real frame, one write
            f.write("stray garbage line\n"
                    '{"itrs": 1, "threads": 1}\n'
                    f"{qfile} {afifo} -\n")
        with open(afifo) as f:
            reply = f.readline().strip()
        assert reply != "FAIL"
        assert int(reply.split(",")[6]) == len(mine)
    finally:
        with open(fifo, "w") as fh:
            fh.write("__DOS_STOP__\n")
        proc.wait(timeout=10)
