"""Multi-host (multi-process) mesh: the DCN-scale story, on one machine.

Spawns 2 separate Python processes, each owning 4 virtual CPU devices,
wired into ONE 8-shard worker mesh via ``jax.distributed`` + gloo
collectives — the single-machine analog of a multi-host TPU pod. Each
process runs the identical sharded CPD build; golden rows are checked
against the CPU oracle inside each process (``multihost_worker.py``).
"""

import os
import socket
import subprocess
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_mesh_build():
    coord = f"127.0.0.1:{_free_port()}"
    # scrub the single-process test env: the workers set their own
    # platform/device config (config-level, to beat any sitecustomize pin)
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    procs = [subprocess.Popen(
        [sys.executable, os.path.join(HERE, "multihost_worker.py"),
         str(pid), "2", coord],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for pid in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {pid} failed:\n{out[-2000:]}"
        assert f"MULTIHOST_OK process={pid} devices=8" in out, out[-2000:]


def test_two_process_conf_driven_campaign(tmp_path):
    """The DRIVERS run multi-controller: two processes execute
    ``cli.process_query`` against one cluster conf whose ``multihost`` key
    joins them into a single 8-device mesh; process 0 alone writes the
    artifact trio (VERDICT r1 next-#10)."""
    import csv
    import json

    import numpy as np

    from distributed_oracle_search_tpu.data import (
        Graph, ensure_synth_dataset, read_scen,
    )
    from distributed_oracle_search_tpu.models.cpd import CPDOracle
    from distributed_oracle_search_tpu.parallel import DistributionController
    from distributed_oracle_search_tpu.parallel.mesh import make_mesh

    datadir = str(tmp_path / "data")
    index = str(tmp_path / "index")
    out = str(tmp_path / "out")
    dataset = ensure_synth_dataset(datadir, width=10, height=8,
                                   n_queries=96, seed=13)
    n_queries = len(read_scen(dataset["scen"]))

    # prebuild the index in THIS process (8 virtual devices via conftest);
    # the two campaign controllers then oracle.load() it
    g = Graph.from_xy(dataset["xy"])
    dc = DistributionController("tpu", 8, 8, g.n)
    oracle = CPDOracle(g, dc, mesh=make_mesh(n_workers=8))
    oracle.build()
    oracle.save(index)

    coord = f"127.0.0.1:{_free_port()}"
    conf_path = str(tmp_path / "conf.json")
    with open(conf_path, "w") as f:
        json.dump({
            "workers": [f"tpu:{i}" for i in range(8)],
            "partmethod": "tpu", "partkey": 8,
            "outdir": index, "xy_file": dataset["xy"],
            "scenfile": dataset["scen"],
            "diffs": ["-", dataset["diff"]],
            "multihost": {"coordinator": coord, "num_processes": 2,
                          "cpu_devices_per_process": 4},
        }, f)

    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    procs = [subprocess.Popen(
        [sys.executable, os.path.join(HERE, "multihost_campaign_worker.py"),
         str(pid), conf_path, out],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for pid in range(2)]
    outs = []
    try:
        for p in procs:
            o, _ = p.communicate(timeout=240)
            outs.append(o)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for pid, (p, o) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {pid} failed:\n{o[-2000:]}"
        assert f"CAMPAIGN_OK process={pid} nproc=2 devices=8" in o, o[-2000:]

    # only process 0 wrote the artifacts; rows account for every query
    with open(os.path.join(out, "metrics.json")) as f:
        assert json.load(f)["num_queries"] == n_queries
    with open(os.path.join(out, "parts.csv")) as f:
        rows = list(csv.reader(f))[1:]
    by_round = {}
    for row in rows:
        by_round.setdefault(row[0], []).append(row)
    assert len(by_round) == 2                       # one per diff
    for rnd in by_round.values():
        finished = sum(int(float(r[7])) for r in rnd)
        assert finished == n_queries


def test_initialize_from_conf_noop_without_key():
    from distributed_oracle_search_tpu.parallel.multihost import (
        initialize_from_conf,
    )
    from distributed_oracle_search_tpu.utils.config import ClusterConfig

    conf = ClusterConfig(workers=["tpu:0"], partmethod="tpu")
    assert initialize_from_conf(conf) is False
    assert initialize_from_conf({"nfs": "/tmp"}) is False
