"""Multi-host (multi-process) mesh: the DCN-scale story, on one machine.

Spawns 2 separate Python processes, each owning 4 virtual CPU devices,
wired into ONE 8-shard worker mesh via ``jax.distributed`` + gloo
collectives — the single-machine analog of a multi-host TPU pod. Each
process runs the identical sharded CPD build; golden rows are checked
against the CPU oracle inside each process (``multihost_worker.py``).
"""

import os
import socket
import subprocess
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_mesh_build():
    coord = f"127.0.0.1:{_free_port()}"
    # scrub the single-process test env: the workers set their own
    # platform/device config (config-level, to beat any sitecustomize pin)
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    procs = [subprocess.Popen(
        [sys.executable, os.path.join(HERE, "multihost_worker.py"),
         str(pid), "2", coord],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for pid in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {pid} failed:\n{out[-2000:]}"
        assert f"MULTIHOST_OK process={pid} devices=8" in out, out[-2000:]


def test_initialize_from_conf_noop_without_key():
    from distributed_oracle_search_tpu.parallel.multihost import (
        initialize_from_conf,
    )
    from distributed_oracle_search_tpu.utils.config import ClusterConfig

    conf = ClusterConfig(workers=["tpu:0"], partmethod="tpu")
    assert initialize_from_conf(conf) is False
    assert initialize_from_conf({"nfs": "/tmp"}) is False
