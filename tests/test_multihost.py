"""Multi-host (multi-process) mesh: the DCN-scale story, on one machine.

Spawns 2 separate Python processes, each owning 4 virtual CPU devices,
wired into ONE 8-shard worker mesh via ``jax.distributed`` + gloo
collectives — the single-machine analog of a multi-host TPU pod. Each
process runs the identical sharded CPD build; golden rows are checked
against the CPU oracle inside each process (``multihost_worker.py``).
"""

import os
import socket
import subprocess
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_mesh_build():
    coord = f"127.0.0.1:{_free_port()}"
    # scrub the single-process test env: the workers set their own
    # platform/device config (config-level, to beat any sitecustomize pin)
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    procs = [subprocess.Popen(
        [sys.executable, os.path.join(HERE, "multihost_worker.py"),
         str(pid), "2", coord],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for pid in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {pid} failed:\n{out[-2000:]}"
        assert f"MULTIHOST_OK process={pid} devices=8" in out, out[-2000:]


@pytest.mark.parametrize("serve_streamed", [False, True],
                         ids=["resident", "streamed"])
def test_two_process_conf_driven_campaign(tmp_path, serve_streamed):
    """The DRIVERS run multi-controller: two processes execute
    ``cli.process_query`` against one cluster conf whose ``multihost`` key
    joins them into a single 8-device mesh; process 0 alone writes the
    artifact trio (VERDICT r1 next-#10). The streamed variant forces the
    streamed memory plan under the same two controllers — each process
    streams its own workers' rows and the merged rows still account for
    every query (VERDICT r4 weak-#7: streamed x multihost was untested).
    """
    import csv
    import json

    import numpy as np

    from distributed_oracle_search_tpu.data import (
        Graph, ensure_synth_dataset, read_scen,
    )
    from distributed_oracle_search_tpu.models.cpd import CPDOracle
    from distributed_oracle_search_tpu.parallel import DistributionController
    from distributed_oracle_search_tpu.parallel.mesh import make_mesh

    datadir = str(tmp_path / "data")
    index = str(tmp_path / "index")
    out = str(tmp_path / "out")
    dataset = ensure_synth_dataset(datadir, width=10, height=8,
                                   n_queries=96, seed=13)
    n_queries = len(read_scen(dataset["scen"]))

    # prebuild the index in THIS process (8 virtual devices via conftest);
    # the two campaign controllers then oracle.load() it
    g = Graph.from_xy(dataset["xy"])
    dc = DistributionController("tpu", 8, 8, g.n)
    oracle = CPDOracle(g, dc, mesh=make_mesh(n_workers=8))
    oracle.build()
    oracle.save(index)

    coord = f"127.0.0.1:{_free_port()}"
    conf_path = str(tmp_path / "conf.json")
    with open(conf_path, "w") as f:
        json.dump({
            "workers": [f"tpu:{i}" for i in range(8)],
            "partmethod": "tpu", "partkey": 8,
            "outdir": index, "xy_file": dataset["xy"],
            "scenfile": dataset["scen"],
            "diffs": ["-", dataset["diff"]],
            "multihost": {"coordinator": coord, "num_processes": 2,
                          "cpu_devices_per_process": 4},
        }, f)

    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    if serve_streamed:
        env["DOS_SERVE_STREAMED"] = "1"
    procs = [subprocess.Popen(
        [sys.executable, os.path.join(HERE, "multihost_campaign_worker.py"),
         str(pid), conf_path, out],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for pid in range(2)]
    outs = []
    try:
        for p in procs:
            o, _ = p.communicate(timeout=240)
            outs.append(o)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for pid, (p, o) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {pid} failed:\n{o[-2000:]}"
        assert f"CAMPAIGN_OK process={pid} nproc=2 devices=8" in o, o[-2000:]

    # only process 0 wrote the artifacts; rows account for every query
    with open(os.path.join(out, "metrics.json")) as f:
        assert json.load(f)["num_queries"] == n_queries
    with open(os.path.join(out, "parts.csv")) as f:
        rows = list(csv.reader(f))[1:]
    by_round = {}
    for row in rows:
        by_round.setdefault(row[0], []).append(row)
    assert len(by_round) == 2                       # one per diff
    for rnd in by_round.values():
        finished = sum(int(float(r[7])) for r in rnd)
        assert finished == n_queries


def test_two_process_sharded_streamed_campaign(tmp_path):
    """The streamed memory plan under multi-controller: each process
    streams ONLY its own workers' rows (per-process wire bytes sum to
    the single-process total, neither process re-streams the world) and
    every controller sees the full merged answer (VERDICT r4 missing-#1
    / weak-#7)."""
    import json  # noqa: F401  (parallel structure with sibling test)

    import numpy as np

    from distributed_oracle_search_tpu.data import (
        Graph, ensure_synth_dataset, read_scen,
    )
    from distributed_oracle_search_tpu.models.cpd import (
        build_worker_shard, write_index_manifest,
    )
    from distributed_oracle_search_tpu.models.streamed import (
        StreamedCPDOracle,
    )
    from distributed_oracle_search_tpu.parallel import DistributionController

    datadir = str(tmp_path / "data")
    index = str(tmp_path / "index")
    dataset = ensure_synth_dataset(datadir, width=10, height=8,
                                   n_queries=96, seed=17)
    g = Graph.from_xy(dataset["xy"])
    dc = DistributionController("mod", 4, 4, g.n)
    for wid in range(4):
        build_worker_shard(g, dc, wid, index, chunk=64)
    write_index_manifest(index, dc)
    queries = read_scen(dataset["scen"])

    # single-process baseline: total wire bytes + golden cost checksum.
    # Range mode + small row chunks so the two controllers' chunk SETS
    # exactly partition the single-process set (compacted chunks are
    # content-addressed per row set and would differ; pow2 padding
    # would quantize a one-chunk campaign to identical byte counts)
    os.environ["DOS_STREAM_RANGE_DENSITY"] = "0.0"
    os.environ["DOS_STREAM_ROW_CHUNK"] = "8"
    try:
        st = StreamedCPDOracle(g, dc, index, row_chunk=8)
        c_ref, _, f_ref = st.query(queries)
    finally:
        del os.environ["DOS_STREAM_RANGE_DENSITY"]
        del os.environ["DOS_STREAM_ROW_CHUNK"]
    assert bool(f_ref.all())
    total_bytes = st.last_stats["bytes_streamed"]
    ref_sum = int(np.asarray(c_ref).sum())

    coord = f"127.0.0.1:{_free_port()}"
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env["DOS_STREAM_RANGE_DENSITY"] = "0.0"
    env["DOS_STREAM_ROW_CHUNK"] = "8"
    procs = [subprocess.Popen(
        [sys.executable,
         os.path.join(HERE, "multihost_streamed_worker.py"),
         str(pid), "2", coord, dataset["xy"], index, dataset["scen"]],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for pid in range(2)]
    outs = []
    try:
        for p in procs:
            o, _ = p.communicate(timeout=240)
            outs.append(o)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    per_proc = {}
    for pid, (p, o) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {pid} failed:\n{o[-2000:]}"
        line = [ln for ln in o.splitlines()
                if ln.startswith(f"STREAMED_OK process={pid} ")]
        assert line, o[-2000:]
        per_proc[pid] = dict(kv.split("=") for kv in line[0].split()[1:])
    for pid in (0, 1):
        # every controller holds the full merged answer
        assert int(per_proc[pid]["cost_sum"]) == ref_sum
    b0, b1 = (int(per_proc[p]["bytes"]) for p in (0, 1))
    # the upload work split: the processes' disjoint chunk sets union to
    # exactly the single-process chunk set, and neither did it all
    assert b0 + b1 == total_bytes, (b0, b1, total_bytes)
    assert 0 < b0 < total_bytes and 0 < b1 < total_bytes


def test_initialize_from_conf_noop_without_key():
    from distributed_oracle_search_tpu.parallel.multihost import (
        initialize_from_conf,
    )
    from distributed_oracle_search_tpu.utils.config import ClusterConfig

    conf = ClusterConfig(workers=["tpu:0"], partmethod="tpu")
    assert initialize_from_conf(conf) is False
    assert initialize_from_conf({"nfs": "/tmp"}) is False
