"""Live traffic plane: segment codec compat, fused epoch swaps, scoped
cache invalidation, query families, and the live-swap serve smoke.

The tier-1 acceptance gate is ``test_live_swap_smoke``: a serving
frontend answers 100+ mixed-family queries across one LIVE diff epoch
swap with zero sheds, and every post-swap answer is bit-identical to a
frontend started fresh on the swapped fused diff. The rush-hour replay
drill (multiple epochs, answers pinned vs the CPU reference per epoch)
stays behind ``slow``.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from distributed_oracle_search_tpu.data import ensure_synth_dataset, read_scen
from distributed_oracle_search_tpu.data.formats import read_diff, write_diff
from distributed_oracle_search_tpu.data.graph import Graph
from distributed_oracle_search_tpu.models.cpd import write_index_manifest
from distributed_oracle_search_tpu.models.reference import (
    first_move_to_target, table_search_walk,
)
from distributed_oracle_search_tpu.obs import metrics as obs_metrics
from distributed_oracle_search_tpu.parallel.partition import (
    DistributionController,
)
from distributed_oracle_search_tpu.serving import (
    CallableDispatcher, EngineDispatcher, ResultCache, ServeConfig,
    ServingFrontend,
)
from distributed_oracle_search_tpu.serving import ingress
from distributed_oracle_search_tpu.traffic import (
    DiffEpochManager, DiffSegment, DiffStream, QueryFamilies,
    SEGMENT_SCHEMA, TailDiffStream, list_segments, parse_family_line,
    read_segment, segment_path, write_segment,
)
from distributed_oracle_search_tpu.traffic import scenarios
from distributed_oracle_search_tpu.traffic.segments import encode_segment
from distributed_oracle_search_tpu.transport.wire import (
    RuntimeConfig, STALE_DIFF_LINE, StatsRow,
)
from distributed_oracle_search_tpu.utils.config import ClusterConfig
from distributed_oracle_search_tpu.worker.build import main as build_main
from distributed_oracle_search_tpu.worker.server import FifoServer

pytestmark = pytest.mark.traffic


def _counter(name: str) -> float:
    return obs_metrics.REGISTRY.snapshot()["counters"].get(name, 0)


# ------------------------------------------------------------- fixtures

@pytest.fixture(scope="module")
def traffic_world(tmp_path_factory):
    """Small 2-shard world with a built CPD index (the test_serving
    pattern): graph, controller, conf, scenario queries."""
    datadir = str(tmp_path_factory.mktemp("traffic-data"))
    paths = ensure_synth_dataset(datadir, width=10, height=8,
                                 n_queries=96, seed=33)
    conf = ClusterConfig(
        workers=["localhost", "localhost"],
        partmethod="mod", partkey=2,
        outdir=os.path.join(datadir, "index"),
        xy_file=paths["xy"], scenfile=paths["scen"],
        diffs=["-", paths["diff"]],
        nfs=datadir,
    ).validate()
    for wid in range(conf.maxworker):
        build_main(["--input", conf.xy_file, "--partmethod",
                    conf.partmethod, "--partkey", str(conf.partkey),
                    "--workerid", str(wid),
                    "--maxworker", str(conf.maxworker),
                    "--outdir", conf.outdir])
    g = Graph.from_xy(conf.xy_file)
    dc = DistributionController(conf.partmethod, conf.partkey,
                                conf.maxworker, g.n)
    write_index_manifest(conf.outdir, dc)
    queries = read_scen(conf.scenfile)
    dispatcher = EngineDispatcher(conf, graph=g, dc=dc)
    return conf, g, dc, queries, dispatcher


def _reference_answers(g, queries, w_query):
    """CPU-oracle golden triple for (s, t) pairs under query weights."""
    fm_cache = {}

    def fm_of(x, t):
        if t not in fm_cache:
            fm_cache[t] = first_move_to_target(g, int(t))
        return fm_cache[t][int(x)]

    cost = np.zeros(len(queries), np.int64)
    plen = np.zeros(len(queries), np.int64)
    fin = np.zeros(len(queries), bool)
    for i, (s, t) in enumerate(queries):
        c, p, f, _path = table_search_walk(g, fm_of, int(s), int(t),
                                           w_query=w_query)
        cost[i], plen[i], fin[i] = c, p, f
    return cost, plen, fin


# ------------------------------------------- satellite: codec compat

def test_segment_roundtrip(tmp_path):
    d = str(tmp_path)
    p = write_segment(d, 3, [0, 1], [1, 2], [50, 60])
    assert p == segment_path(d, 3)
    seg = read_segment(p)
    assert seg.epoch == 3 and len(seg) == 2
    assert seg.pairs() == [(0, 1), (1, 2)]
    assert list(seg.w) == [50, 60]


def test_segment_unknown_keys_tolerated(tmp_path):
    d = str(tmp_path)
    write_segment(d, 1, [0], [1], [9],
                  extra={"producer": "sensor-fleet", "region": 7})
    seg = read_segment(segment_path(d, 1))
    assert seg.epoch == 1 and list(seg.w) == [9]


def test_segment_newer_schema_rejected(tmp_path):
    d = str(tmp_path)
    raw = encode_segment(1, [0], [1], [9]).decode()
    header = json.loads(raw.split("\n")[0])
    header["schema"] = SEGMENT_SCHEMA + 1
    body = "\n".join([json.dumps(header)] + raw.split("\n")[1:])
    p = segment_path(d, 1)
    os.makedirs(d, exist_ok=True)
    with open(p, "w") as f:
        f.write(body)
    with pytest.raises(ValueError, match="newer"):
        read_segment(p)


def test_segment_torn_tail_ignored(tmp_path):
    d = str(tmp_path)
    write_segment(d, 1, [0], [1], [9])
    # a torn TAIL (non-atomic producer mid-write) is skipped...
    with open(segment_path(d, 2), "w") as f:
        f.write(json.dumps({"kind": "dos-traffic-segment", "schema": 1,
                            "epoch": 2, "entries": 3}) + "\n0 1 5\n")
    segs = list_segments(d)
    assert [s.epoch for s in segs] == [1]
    # ...but a torn MID-stream segment is data loss and raises
    write_segment(d, 3, [2], [3], [7])
    with pytest.raises(ValueError, match="mid-stream"):
        list_segments(d)


def test_segment_filename_epoch_mismatch(tmp_path):
    d = str(tmp_path)
    write_segment(d, 1, [0], [1], [9])
    os.rename(segment_path(d, 1), segment_path(d, 4))
    with pytest.raises(ValueError, match="header says"):
        read_segment(segment_path(d, 4))


def test_tail_stream_torn_frame(tmp_path):
    spool = str(tmp_path / "spool.segs")
    ts = TailDiffStream(spool)
    assert ts.poll() == []                    # producer not started
    ts.append(encode_segment(1, [0], [1], [9]))
    ts.append(encode_segment(2, [1], [2], [8])[:-8])   # torn tail
    got = ts.poll()
    assert [s.epoch for s in got] == [1]
    with open(spool, "ab") as f:              # rest of frame 2 lands
        f.write(encode_segment(2, [1], [2], [8])[-8:])
    got = ts.poll()
    assert [s.epoch for s in got] == [2]
    assert list(got[0].w) == [8]


def test_tail_stream_multibyte_header_annotation(tmp_path):
    """Regression pin: the resume offset counts BYTES. A third-party
    producer may annotate headers with raw UTF-8 (our own encoder
    escapes, but the contract tolerates unknown keys as the producer
    wrote them); a multi-byte annotation used to desync the
    char-counted offset from the byte seek and stall the stream on the
    next frame."""
    spool = str(tmp_path / "spool.segs")
    ts = TailDiffStream(spool)
    raw = json.dumps({"kind": "dos-traffic-segment", "schema": 1,
                      "epoch": 1, "entries": 1,
                      "corridor": "Åsgatan–Brogränd"},
                     ensure_ascii=False)
    ts.append((raw + "\n0 1 9\n").encode())
    assert [s.epoch for s in ts.poll()] == [1]
    ts.append(encode_segment(2, [1], [2], [8]))
    got = ts.poll()
    assert [s.epoch for s in got] == [2]
    assert list(got[0].w) == [8]


def test_stream_holds_back_out_of_order_visibility(tmp_path):
    """Regression pin: on a shared filesystem a higher-numbered
    segment can become visible before a lower one; skipping the gap
    would omit the late segment's retimes from every later fusion
    forever. Held back until the gap fills; a late joiner still syncs
    to wherever the stream is."""
    d = str(tmp_path)
    ds = DiffStream(d)
    write_segment(d, 1, [0], [1], [9])
    assert [s.epoch for s in ds.poll()] == [1]
    write_segment(d, 3, [2], [3], [7])       # 3 visible before 2
    assert ds.poll() == []                   # held back
    write_segment(d, 2, [1], [2], [8])       # the gap fills
    assert [s.epoch for s in ds.poll()] == [2, 3]
    late = DiffStream(d)                     # late joiner: any start
    assert [s.epoch for s in late.poll()] == [1, 2, 3]


# ------------------------------------------------- epoch manager

def test_fused_multi_segment_swap(tmp_path):
    d = str(tmp_path / "stream")
    m = DiffEpochManager(d, keep_epochs=2)
    assert m.epoch == 0 and not m.refresh()
    write_segment(d, 1, [0, 1], [1, 2], [50, 60])
    write_segment(d, 2, [0, 5], [1, 6], [50, 70])   # (0,1) re-stated
    assert m.refresh()                   # BOTH segments fuse into one
    epoch, difffile, affected = m.active()
    assert epoch == 2
    # (0,1)=50 twice: changed once vs free flow; (1,2) and (5,6) new
    assert affected == {(0, 1), (1, 2), (5, 6)}
    src, dst, w = read_diff(difffile)
    fused = {(int(u), int(v)): int(ww) for u, v, ww in zip(src, dst, w)}
    assert fused == {(0, 1): 50, (1, 2): 60, (5, 6): 70}
    # a segment re-stating an ACTIVE weight affects nothing
    write_segment(d, 3, [0], [1], [50])
    assert m.refresh()
    _, _, affected = m.active()
    assert affected == set()
    # spool pruning keeps the keep window (>= 2: double buffer)
    write_segment(d, 4, [0], [1], [55])
    assert m.refresh()
    import glob as _glob
    kept = sorted(_glob.glob(os.path.join(m.spool, "fused-e*.diff")))
    assert len(kept) == 2
    assert kept[-1] == m.fused_path(4)


def test_refresh_retains_segments_when_materialize_fails(tmp_path):
    """Regression pin: the stream cursor advances inside poll(), so a
    failed fused-diff write must keep the polled segments pending — a
    drop would silently omit their retimes from every later epoch."""
    d = str(tmp_path / "stream")
    blocked = tmp_path / "spool"
    blocked.write_text("a FILE where the spool dir should be")
    m = DiffEpochManager(d, spool_dir=str(blocked))
    write_segment(d, 1, [0], [1], [9])
    assert not m.refresh()                   # makedirs fails: no swap
    assert m.epoch == 0 and m.weight_of(0, 1, 5) == 5
    os.remove(blocked)                       # the operator clears it
    assert m.refresh()                       # pending segments retried
    assert m.epoch == 1 and m.weight_of(0, 1, 5) == 9


def test_manager_base_diff_and_weight_of(tmp_path):
    base = str(tmp_path / "base.diff")
    write_diff(base, np.asarray([7]), np.asarray([8]),
               np.asarray([123]))
    d = str(tmp_path / "stream")
    m = DiffEpochManager(d, base_diff=base)
    assert m.weight_of(7, 8, 999) == 123        # base diff applies
    assert m.weight_of(1, 2, 42) == 42          # free-flow fallback
    write_segment(d, 1, [7], [8], [200])
    assert m.refresh()
    assert m.weight_of(7, 8, 999) == 200        # segment wins
    src, dst, w = read_diff(m.difffile)
    assert {(int(u), int(v)): int(ww)
            for u, v, ww in zip(src, dst, w)} == {(7, 8): 200}


# ------------------------------------- scoped cache invalidation

def _key(s, t, diff="-", fp=(), mep=0, dep=0):
    return (s, t, diff, fp, mep, dep)


def test_scoped_invalidation_rekeys_survivors():
    cache = ResultCache(1 << 20)
    # entry A: path avoids the updated edge; B: touches it; C: no sig
    cache.put(_key(1, 2), (10, 2, True), sig=frozenset({1, 5, 2}))
    cache.put(_key(3, 4), (20, 3, True), sig=frozenset({3, 8, 9, 4}))
    cache.put(_key(5, 6), (30, 4, True))            # signature-less
    s0 = _counter("serve_cache_invalidated_scoped_total")
    dropped, kept, reason = cache.invalidate_scoped(
        {(8, 9)}, "fused.diff", 1, max_edges=100,
        old_diff="-", old_depoch=0)
    assert (dropped, kept, reason) == (2, 1, "scoped")
    assert _counter("serve_cache_invalidated_scoped_total") - s0 == 2
    # the survivor was RE-KEYED to the new (diff, diff epoch): post-swap
    # traffic keeps hitting it, the old key is gone
    assert cache.get(_key(1, 2, "fused.diff", dep=1)) == (10, 2, True)
    assert cache.get(_key(1, 2)) is None
    assert cache.get(_key(3, 4, "fused.diff", dep=1)) is None


def test_scoped_invalidation_edge_midpath():
    # both endpoints on the path but NOT consecutive: conservative drop
    # is allowed; an entry whose nodes miss an endpoint must survive
    cache = ResultCache(1 << 20)
    cache.put(_key(1, 4), (5, 3, True), sig=frozenset({1, 2, 3, 4}))
    dropped, kept, _ = cache.invalidate_scoped(
        {(9, 2)}, "f.diff", 1, max_edges=100,
        old_diff="-", old_depoch=0)             # 9 not on the path
    assert (dropped, kept) == (0, 1)


def test_scoped_invalidation_drops_other_epoch_entries():
    """Regression pin: an entry cached under an OLDER epoch (a late
    put from a batch in flight across the previous swap) was never
    tested against the intermediate deltas — re-keying it on a later
    swap could resurrect a stale cost. Only entries keyed at exactly
    the fusion the swap replaced may survive."""
    cache = ResultCache(1 << 20)
    # late put: computed at epoch 0 while epoch 1 is already active;
    # its path DOES touch the edge the 0->1 swap updated
    cache.put(_key(1, 2, dep=0), (10, 2, True),
              sig=frozenset({1, 7, 2}))
    # a current-epoch entry, clean of the 1->2 delta
    cache.put(_key(3, 4, "f1.diff", dep=1), (20, 2, True),
              sig=frozenset({3, 9, 4}))
    # swap 1 -> 2 updates (5, 6): disjoint from BOTH signatures, but
    # only the epoch-1 entry is eligible to survive
    dropped, kept, reason = cache.invalidate_scoped(
        {(5, 6)}, "f2.diff", 2, max_edges=100,
        old_diff="f1.diff", old_depoch=1)
    assert (dropped, kept, reason) == (1, 1, "scoped")
    assert cache.get(_key(3, 4, "f2.diff", dep=2)) == (20, 2, True)
    assert cache.get(_key(1, 2, "f2.diff", dep=2)) is None


def test_scoped_full_flush_threshold():
    cache = ResultCache(1 << 20)
    for i in range(4):
        cache.put(_key(i, i + 1), (i, 1, True), sig=frozenset({i}))
    f0 = _counter("serve_cache_invalidated_full_total")
    dropped, kept, reason = cache.invalidate_scoped(
        {(i, i + 1) for i in range(10)}, "f.diff", 1, max_edges=5,
        old_diff="-", old_depoch=0)
    assert (dropped, kept, reason) == (4, 0, "full")
    assert _counter("serve_cache_invalidated_full_total") - f0 == 4
    assert len(cache) == 0


# -------------------------- satellite: epochs folded into the key

class _FakeMembership:
    def __init__(self):
        self.epoch = 0

    def candidates_for(self, wid):
        return [wid]

    def statusz(self):
        return {"epoch": self.epoch}


def test_cache_key_includes_membership_epoch():
    """Regression pin (PR 9 satellite): a post-reshard cache hit used
    to serve a result computed by a worker that no longer owns the
    shard — the membership epoch is now part of the key, so an epoch
    bump turns the stale entry into a miss."""
    dc = DistributionController("mod", 2, 2, 100)
    mem = _FakeMembership()
    calls = []

    def answer(wid, q, rconf, diff):
        calls.append(len(q))
        n = len(q)
        return (np.full(n, 7), np.ones(n, np.int64),
                np.ones(n, bool))

    fe = ServingFrontend(
        dc, CallableDispatcher(answer),
        sconf=ServeConfig(max_wait_ms=1.0).validate(),
        membership=mem)
    fe.start()
    try:
        assert fe.query(1, 2).ok
        r2 = fe.query(1, 2)
        assert r2.ok and r2.cached            # same epoch: cache hit
        mem.epoch = 1                          # reshard commits
        r3 = fe.query(1, 2)
        assert r3.ok and not r3.cached        # MISS: key re-derived
        assert len(calls) == 2
    finally:
        fe.stop()


def test_cache_key_includes_diff_epoch():
    dc = DistributionController("mod", 2, 2, 100)

    def answer(wid, q, rconf, diff):
        n = len(q)
        return (np.full(n, 7), np.ones(n, np.int64), np.ones(n, bool))

    fe = ServingFrontend(dc, CallableDispatcher(answer),
                         sconf=ServeConfig(max_wait_ms=1.0).validate())
    fe.start()
    try:
        assert fe.query(1, 2).ok
        assert fe.query(1, 2).cached
        fe._diff_epoch = 3                    # an epoch swap landed
        assert not fe.query(1, 2).cached
    finally:
        fe.stop()


# ----------------------------------------------------- wire compat

def test_diff_epoch_wire_roundtrip():
    rc = RuntimeConfig(diff_epoch=4, sig_k=32)
    back = RuntimeConfig.from_json(rc.to_json())
    assert back.diff_epoch == 4 and back.sig_k == 32
    # old peer's json (no new keys) -> defaults; unknown keys filtered
    legacy = json.dumps({"hscale": 1.0, "future_knob": 9})
    rc2 = RuntimeConfig.from_json(legacy)
    assert rc2.diff_epoch == 0 and rc2.sig_k == 0


def test_stale_diff_sentinel_roundtrip():
    row = StatsRow(ok=False, stale_diff=True)
    assert row.encode_wire() == STALE_DIFF_LINE
    back = StatsRow.decode(STALE_DIFF_LINE)
    assert not back.ok and back.stale_diff and not back.stale_epoch
    # a stale-EPOCH line still decodes to the membership flag only
    other = StatsRow.decode("STALE_EPOCH")
    assert other.stale_epoch and not other.stale_diff


def test_server_stale_diff_gate(tmp_path):
    d = str(tmp_path / "stream")
    srv = FifoServer.__new__(FifoServer)
    srv.wid = 0
    srv.traffic = DiffEpochManager(d, materialize=False)
    s0 = _counter("server_stale_diff_total")
    # older and equal diff epochs always serve
    assert srv._traffic_gate(RuntimeConfig()) is None
    assert srv._traffic_gate(RuntimeConfig(diff_epoch=0)) is None
    # newer than the stream shows, even after refresh: refuse
    row = srv._traffic_gate(RuntimeConfig(diff_epoch=5))
    assert row is not None and row.stale_diff and not row.ok
    assert _counter("server_stale_diff_total") - s0 == 1
    # the segment lands: the refresh inside the gate now sees it
    write_segment(d, 5, [0], [1], [9])
    assert srv._traffic_gate(RuntimeConfig(diff_epoch=5)) is None
    # a worker with no traffic manager never gates
    srv.traffic = None
    assert srv._traffic_gate(RuntimeConfig(diff_epoch=99)) is None


# --------------------------------------------------- query families

def test_family_line_parsing():
    assert parse_family_line("3 5") is None
    assert parse_family_line("mat 3 5 7 9") == ("mat", (3, [5, 7, 9]))
    assert parse_family_line("alt 3 5 2") == ("alt", (3, 5, 2))
    assert parse_family_line("rev 3 5") == ("rev", (3, 5))
    for bad in ("mat 3", "alt 3 5", "rev 3", "alt 3 5 2 9"):
        with pytest.raises(ValueError):
            parse_family_line(bad)


def test_families_match_reference(traffic_world):
    conf, g, dc, queries, dispatcher = traffic_world
    fe = ServingFrontend(
        dc, dispatcher,
        sconf=ServeConfig(queue_depth=1024, max_wait_ms=1.0,
                          cache_bytes=0).validate())
    fam = QueryFamilies(fe, graph=g)
    fe.start()
    try:
        s, t = int(queries[0][0]), int(queries[0][1])
        targets = [int(q[1]) for q in queries[:8]]
        # --- matrix: one cost per target, pinned element-wise
        mat = fam.matrix(s, targets).result(60)
        exp_c, _p, exp_f = _reference_answers(
            g, [(s, tt) for tt in targets], g.w)
        assert mat.encode().startswith(f"MAT {s} {len(targets)} ")
        for c, ec, ef in zip(mat.costs, exp_c, exp_f):
            assert c == (int(ec) if ef else -1)
        # --- alternatives: distinct first edges, ranked by total cost
        k = 3
        alt = fam.alternatives(s, t, k).result(60)
        nbrs, eids = g.out_edges(s)
        exp = []
        for v, e in zip(nbrs, eids):
            c, _pl, f = _reference_answers(g, [(int(v), t)], g.w)
            if f[0]:
                exp.append(int(g.w[e]) + int(c[0]))
        exp.sort()
        assert [c for c, _v in alt.alternatives] == exp[:k]
        # the best alternative IS the optimal route
        best, _pl, bf = _reference_answers(g, [(s, t)], g.w)
        assert bf[0] and alt.alternatives[0][0] == int(best[0])
        # --- reverse: the return trip, source-owner routed
        rev = fam.reverse(s, t).result(60)
        rc, rp, rf = _reference_answers(g, [(t, s)], g.w)
        assert rev.encode() == (
            f"REV {s} {t} {int(rc[0])} {int(rp[0])} {int(rf[0])}")
        m0 = (_counter("serve_matrix_requests_total"),
              _counter("serve_alt_requests_total"),
              _counter("serve_reverse_requests_total"))
        assert all(v >= 1 for v in m0)
    finally:
        fe.stop()


def test_alt_rejects_out_of_range_nodes(traffic_world):
    """Regression pin: ``alt`` indexes the graph before any submit —
    an out-of-range source used to crash the ingress session and a
    NEGATIVE source silently wrapped to another node's edges."""
    conf, g, dc, queries, dispatcher = traffic_world
    fe = ServingFrontend(
        dc, dispatcher,
        sconf=ServeConfig(queue_depth=64, max_wait_ms=1.0,
                          cache_bytes=0).validate())
    fam = QueryFamilies(fe, graph=g)
    for s, t in ((g.n + 7, 0), (-1, 0), (0, g.n), (0, -2)):
        with pytest.raises(ValueError, match="node-out-of-range"):
            fam.alternatives(s, t, 2)


def test_family_ingress_survives_bad_family_request(traffic_world):
    """A failing family submit answers ERROR in-order; the session
    keeps serving the lines after it."""
    import io

    conf, g, dc, queries, dispatcher = traffic_world
    fe = ServingFrontend(
        dc, dispatcher,
        sconf=ServeConfig(queue_depth=1024, max_wait_ms=1.0,
                          cache_bytes=0).validate())
    fam = QueryFamilies(fe, graph=g)
    fe.start()
    try:
        s, t = int(queries[0][0]), int(queries[0][1])
        lines = (f"alt {g.n + 99} {t} 2\nalt -1 {t} 2\n"
                 f"{s} {t}\nquit\n")
        out = io.StringIO()
        n = ingress.serve_stream(fe, io.StringIO(lines), out,
                                 families=fam)
        assert n == 1                      # only the pair counted
        got = out.getvalue().strip().split("\n")
        assert got[0].startswith("ERROR -1 -1 node-out-of-range")
        assert got[1].startswith("ERROR -1 -1 node-out-of-range")
        assert got[2].startswith(f"OK {s} {t} ")
    finally:
        fe.stop()


def test_cache_budget_charges_signatures():
    """Byte accounting: a signature-carrying entry costs its real
    size, so a budget that holds N signature-less entries holds FEWER
    once signatures ride along (the budget used to be a flat per-entry
    guess the signatures blew through)."""
    from distributed_oracle_search_tpu.serving.cache import (
        ENTRY_BYTES, SIG_NODE_BYTES,
    )

    budget = 4 * ENTRY_BYTES
    plain = ResultCache(budget)
    for i in range(4):
        plain.put(_key(i, i + 1), (i, 1, True))
    assert len(plain) == 4                 # flat entries: all fit
    sigged = ResultCache(budget)
    big = frozenset(range(ENTRY_BYTES // SIG_NODE_BYTES))  # 1 entry's
    for i in range(4):                     # worth of signature each
        sigged.put(_key(i, i + 1), (i, 1, True), sig=big)
    assert len(sigged) == 2                # charged 2x: half fit


def test_cache_refresh_with_signature_evicts():
    """Regression pin: attaching a signature to an EXISTING entry
    grows the footprint too — the refresh path must run the same
    eviction loop, or a stable hot pool re-answering with signatures
    pins far past the byte budget with no new key ever evicting."""
    from distributed_oracle_search_tpu.serving.cache import (
        ENTRY_BYTES, SIG_NODE_BYTES,
    )

    budget = 4 * ENTRY_BYTES
    cache = ResultCache(budget)
    for i in range(4):
        cache.put(_key(i, i + 1), (i, 1, True))    # at budget, sig-less
    big = frozenset(range(2 * ENTRY_BYTES // SIG_NODE_BYTES))
    for i in range(4):                             # re-answer with sigs
        cache.put(_key(i, i + 1), (i, 1, True), sig=big)
    assert cache._bytes <= budget
    assert len(cache) == 1                         # 3x-cost entries


def test_swap_ignores_manual_set_diff_entries(traffic_world, tmp_path):
    """Regression pin: scoped invalidation matches survivors against
    the previous FUSION, not ``frontend.diff`` — after a manual
    ``set_diff`` the live entries were computed under an unrelated
    diff the swap's affected set says nothing about, so re-keying one
    would serve its stale cost under the new epoch."""
    from distributed_oracle_search_tpu.data.formats import write_diff

    conf, g, dc, queries, dispatcher = traffic_world
    stream_dir = str(tmp_path / "stream")
    manager = DiffEpochManager(stream_dir, poll_ms=1e6)  # manual pump
    fe = ServingFrontend(
        dc, dispatcher,
        sconf=ServeConfig(queue_depth=256, max_wait_ms=1.0,
                          deadline_ms=60_000.0).validate(),
        traffic=manager)
    fe.start()
    try:
        s, t = int(queries[0][0]), int(queries[0][1])
        mdiff = str(tmp_path / "manual.diff")
        write_diff(mdiff, np.asarray([0]), np.asarray([1]),
                   np.asarray([12345]))
        fe.set_diff(mdiff)
        assert fe.submit(s, t).result(60).ok
        assert fe.submit(s, t).result(60).cached
        # update ONE edge provably off the cached walk, so only the
        # old-fusion match (not the signature check) can drop it
        fm = first_move_to_target(g, t)
        _c, _p, _f, path = table_search_walk(
            g, lambda x, _t: fm[int(x)], s, t, w_query=g.w)
        on_path = set(int(x) for x in path)
        eid = next(e for e in range(g.m)
                   if int(g.src[e]) not in on_path
                   and int(g.dst[e]) not in on_path)
        r0 = _counter("serve_cache_rekeyed_total")
        write_segment(stream_dir, 1, [int(g.src[eid])],
                      [int(g.dst[eid])], [int(g.w[eid]) * 2])
        assert fe.poll_traffic()
        assert _counter("serve_cache_rekeyed_total") == r0
        assert not fe.submit(s, t).result(60).cached
    finally:
        fe.stop()


def test_family_ingress_stream(traffic_world):
    import io

    conf, g, dc, queries, dispatcher = traffic_world
    fe = ServingFrontend(
        dc, dispatcher,
        sconf=ServeConfig(queue_depth=1024, max_wait_ms=1.0,
                          cache_bytes=0).validate())
    fam = QueryFamilies(fe, graph=g)
    fe.start()
    try:
        s, t = int(queries[0][0]), int(queries[0][1])
        lines = (f"{s} {t}\nmat {s} {t}\nrev {s} {t}\n"
                 f"alt {s} {t} 2\nmat nonsense\nquit\n")
        out = io.StringIO()
        n = ingress.serve_stream(fe, io.StringIO(lines), out,
                                 families=fam)
        assert n == 4
        got = out.getvalue().strip().split("\n")
        assert got[0].startswith(f"OK {s} {t} ")
        assert got[1].startswith(f"MAT {s} 1 ")
        assert got[2].startswith(f"REV {s} {t} ")
        assert got[3].startswith(f"ALT {s} {t} ")
        assert got[4].startswith("ERROR -1 -1 malformed-line")
    finally:
        fe.stop()


# ------------------------------------------- engine path signatures

def test_engine_sig_k_answers_unchanged(traffic_world):
    conf, g, dc, queries, dispatcher = traffic_world
    eng = dispatcher._engine_for(0)
    mine = queries[dc.worker_of(queries[:, 1]) == 0][:16]
    c0, p0, f0, _ = eng.answer(mine, RuntimeConfig())
    c1, p1, f1, _ = eng.answer(mine, RuntimeConfig(sig_k=64))
    np.testing.assert_array_equal(c0, c1)
    np.testing.assert_array_equal(p0, p1)
    np.testing.assert_array_equal(f0, f1)
    nodes, moves = eng.last_paths
    assert nodes.shape == (len(mine), 65)
    # a complete signature's moves equal the answered plen
    np.testing.assert_array_equal(moves, p1)


# --------------------------------------------- tier-1 live-swap smoke

def _pair_triples(results):
    return [(r.cost, r.plen, r.finished) for r in results]


def test_live_swap_smoke(traffic_world, tmp_path):
    """The acceptance gate: 100+ mixed-family queries across one LIVE
    epoch swap — zero sheds, post-swap answers bit-identical to a
    frontend started fresh on the swapped diff, scoped invalidation
    keeps unaffected entries hitting."""
    conf, g, dc, queries, dispatcher = traffic_world
    stream_dir = str(tmp_path / "stream")
    manager = DiffEpochManager(stream_dir, poll_ms=25.0)
    fe = ServingFrontend(
        dc, dispatcher,
        sconf=ServeConfig(queue_depth=2048, max_wait_ms=1.0,
                          deadline_ms=60_000.0).validate(),
        traffic=manager)
    fam = QueryFamilies(fe, graph=g, traffic=manager)
    shed0 = (_counter("serve_shed_busy_total"),
             _counter("serve_shed_unavailable_total"),
             _counter("serve_timeouts_total"),
             _counter("serve_errors_total"))
    fe.start()
    try:
        pool = [(int(s), int(t)) for s, t in queries[:60]]
        # --- pre-swap: pairs + every family (well over 100 sub-queries)
        pre = [fe.submit(s, t) for s, t in pool]
        fam_futs = [fam.matrix(pool[0][0], [t for _s, t in pool[:10]]),
                    fam.alternatives(pool[1][0], pool[1][1], 3),
                    fam.reverse(pool[2][0], pool[2][1])]
        pre_res = [f.result(60) for f in pre]
        for f in fam_futs:
            assert f.result(60) is not None
        assert all(r.ok for r in pre_res)

        # --- the swap: retime a handful of corridor edges, live
        eids = scenarios.pick_corridor(g, frac=0.01, seed=5)
        new_w = (g.w[eids].astype(np.int64) * 3).astype(np.int64)
        write_segment(stream_dir, 1, g.src[eids], g.dst[eids], new_w)
        deadline = time.monotonic() + 10.0
        while fe._diff_epoch != 1:
            assert time.monotonic() < deadline, "swap never applied"
            time.sleep(0.02)
        assert fe.diff == manager.fused_path(1)

        # --- post-swap: same mixed workload on the new epoch
        post = [fe.submit(s, t) for s, t in pool]
        post_res = [f.result(60) for f in post]
        assert all(r.ok for r in post_res)
        mat = fam.matrix(pool[0][0],
                         [t for _s, t in pool[:10]]).result(60)
        rev = fam.reverse(pool[2][0], pool[2][1]).result(60)
        assert mat.ok and rev.ok

        # zero sheds attributable to the swap
        assert (_counter("serve_shed_busy_total"),
                _counter("serve_shed_unavailable_total"),
                _counter("serve_timeouts_total"),
                _counter("serve_errors_total")) == shed0

        # scoped (not full) invalidation ran, and unaffected survivors
        # kept hitting after the swap
        assert _counter("serve_cache_invalidated_scoped_total") > 0
        hits_after = [r.cached for r in post_res]
        assert any(hits_after), "no re-keyed survivor ever hit"
    finally:
        fe.stop()

    # --- bit-identical to a serve started FRESH on the new diff
    fresh = ServingFrontend(
        dc, dispatcher,
        sconf=ServeConfig(queue_depth=2048, max_wait_ms=1.0,
                          cache_bytes=0,
                          deadline_ms=60_000.0).validate(),
        diff=manager.fused_path(1))
    fresh.start()
    try:
        fresh_res = [fresh.submit(s, t).result(60) for s, t in pool]
        assert _pair_triples(fresh_res) == _pair_triples(post_res)
    finally:
        fresh.stop()
    # and correct vs the CPU reference under the fused weights
    w_new = g.weights_with_diff(read_diff(manager.fused_path(1)))
    exp_c, exp_p, exp_f = _reference_answers(g, pool[:12], w_new)
    for r, ec, ep, ef in zip(post_res[:12], exp_c, exp_p, exp_f):
        assert (r.cost, r.plen, r.finished) == (int(ec), int(ep),
                                                bool(ef))


# -------------------------------------------------- scenario generator

def test_scenario_topologies():
    for kind in ("grid", "powerlaw"):
        g = scenarios.make_topology(kind, n=120, seed=3)
        assert g.n >= 100 and g.m > g.n        # connected-ish, 2-way
    q = scenarios.zipf_queries(100, 500, seed=4)
    assert q.shape == (500, 2)
    assert (q >= 0).all() and (q < 100).all()
    assert (q[:, 0] != q[:, 1]).all()
    # hotspots: the pool repeats pairs (what caches/dedup feed on)
    assert len(np.unique(q, axis=0)) < len(q)


def test_rush_hour_trace_profile():
    g = scenarios.make_topology("grid", n=100, seed=1)
    trace = scenarios.rush_hour_trace(g, epochs=5, frac=0.05,
                                      peak=3.0, seed=2)
    assert [seg["epoch"] for seg in trace] == [1, 2, 3, 4, 5]
    eids = scenarios.pick_corridor(g, frac=0.05, seed=2)
    base = g.w[eids].astype(np.int64)
    mid = trace[2]["w"]
    assert (mid >= base * 2.9).all()           # peak at the middle
    np.testing.assert_array_equal(trace[-1]["w"], base)  # ends at base


# ------------------------------------------ satellite: bench waivers

def _bench_record(path, headline):
    with open(path, "w") as f:
        json.dump({"parsed": {"metric": "scenario_queries_per_sec",
                              "value": headline.get(
                                  "scenario_queries_per_sec", 1.0),
                              "headline": headline}}, f)


def test_bench_diff_waiver_gate(tmp_path):
    from distributed_oracle_search_tpu.cli.obs import main as obs_main
    from distributed_oracle_search_tpu.obs import fleet

    d = str(tmp_path)
    _bench_record(os.path.join(d, "BENCH_r01.json"),
                  {"build_rows_per_sec": 300.0, "other_qps": 50.0})
    _bench_record(os.path.join(d, "BENCH_r02.json"),
                  {"build_rows_per_sec": 100.0, "other_qps": 60.0})
    # ungated: the regression exits 1
    assert obs_main(["bench-diff", "--dir", d]) == 1
    # a waiver for a round that is NOT the newest record is rejected
    # up front — it would be recorded but could never apply
    with pytest.raises(SystemExit, match="cannot apply"):
        obs_main(["bench-diff", "--dir", d, "--waive",
                  "build_rows_per_sec=r99"])
    # recording the waiver for THIS round passes, and is durable
    assert obs_main(["bench-diff", "--dir", d, "--waive",
                     "build_rows_per_sec=r02", "--waive-reason",
                     "accepted rebaseline"]) == 0
    assert obs_main(["bench-diff", "--dir", d]) == 0
    rec = fleet.load_waivers(d)["build_rows_per_sec"]
    assert rec["round"] == "r02"
    assert rec["reason"] == "accepted rebaseline"
    assert rec["old"] == 300.0 and rec["new"] == 100.0
    # the waiver is per-round: a FRESH regression in r03 gates again
    _bench_record(os.path.join(d, "BENCH_r03.json"),
                  {"build_rows_per_sec": 30.0, "other_qps": 60.0})
    assert obs_main(["bench-diff", "--dir", d]) == 1
    # a waiver recorded for the WRONG round does not apply
    out = fleet.compare_bench(
        os.path.join(d, "BENCH_r02.json"),
        os.path.join(d, "BENCH_r03.json"),
        waivers={"build_rows_per_sec": {"round": "r99"}})
    assert len(out["regressions"]) == 1 and not out["waived"]


def test_bench_waiver_file_unreadable_fails_closed(tmp_path):
    from distributed_oracle_search_tpu.obs import fleet

    d = str(tmp_path)
    with open(os.path.join(d, fleet.WAIVER_FILE), "w") as f:
        f.write("{not json")
    assert fleet.load_waivers(d) == {}         # no waivers -> gating


# --------------------------------------------------- slow: replay drill

@pytest.mark.slow
def test_rush_hour_replay_drill(traffic_world, tmp_path):
    """Multi-epoch rush-hour replay against a live frontend: every
    epoch's answers pinned element-wise vs the CPU reference under that
    epoch's fused weights; zero sheds across the whole rush."""
    conf, g, dc, queries, dispatcher = traffic_world
    stream_dir = str(tmp_path / "rush")
    # keep the whole rush's fused files: the drill reads each epoch's
    # fusion back for the reference pin AFTER serving on it, and the
    # first batch's JIT compile can outlast several replay intervals —
    # the default keep window would prune the file first (the keep
    # window's own behavior is pinned by test_fused_multi_segment_swap)
    manager = DiffEpochManager(stream_dir, poll_ms=25.0, keep_epochs=8)
    fe = ServingFrontend(
        dc, dispatcher,
        sconf=ServeConfig(queue_depth=2048, max_wait_ms=1.0,
                          deadline_ms=60_000.0).validate(),
        traffic=manager)
    shed0 = (_counter("serve_shed_busy_total"),
             _counter("serve_shed_unavailable_total"))
    trace = scenarios.rush_hour_trace(g, epochs=4, frac=0.03,
                                      peak=4.0, seed=9)
    pool = [(int(s), int(t)) for s, t in queries[:16]]
    fe.start()
    try:
        stop = threading.Event()
        writer = threading.Thread(
            target=scenarios.replay,
            args=(trace, stream_dir), kwargs={"interval_s": 0.3,
                                              "stop": stop},
            daemon=True)
        writer.start()
        seen = set()
        deadline = time.monotonic() + 60.0
        try:
            while len(seen) < 2 and time.monotonic() < deadline:
                ep = fe._diff_epoch
                if ep and ep not in seen:
                    seen.add(ep)
                    res = [fe.submit(s, t).result(60) for s, t in pool]
                    assert all(r.ok for r in res)
                    w_ep = g.weights_with_diff(
                        read_diff(manager.fused_path(ep)))
                    ec, ep_, ef = _reference_answers(g, pool, w_ep)
                    # pin only answers still computed under ep (a swap
                    # mid-collection is legal; skip if epoch moved)
                    if fe._diff_epoch == ep:
                        for r, c, p, f in zip(res, ec, ep_, ef):
                            assert (r.cost, r.plen, r.finished) == (
                                int(c), int(p), bool(f))
                time.sleep(0.05)
        finally:
            stop.set()
            writer.join(timeout=10)
        assert seen, "replay produced no epoch swaps"
        assert (_counter("serve_shed_busy_total"),
                _counter("serve_shed_unavailable_total")) == shed0
    finally:
        fe.stop()
