"""Deterministic fault-injection harness + server-side fault paths.

Every injection point is exercised against a real ``FifoServer`` serve
loop (the bare-server pattern from test_obs: no engine needed — only a
successfully decoded request touches it), and each recovery path is
asserted through its obs counter via a metrics snapshot, per the
fault-path smoke-job contract.
"""

import json
import os
import threading
import time

import pytest

from distributed_oracle_search_tpu.obs import metrics as obs_metrics
from distributed_oracle_search_tpu.testing import faults
from distributed_oracle_search_tpu.transport.wire import HealthStatus
from distributed_oracle_search_tpu.worker import server as server_mod
from distributed_oracle_search_tpu.worker.server import FifoServer


# ------------------------------------------------------------ spec parsing

def test_parse_faults_grammar():
    rules = faults.parse_faults(
        "drop-reply;wid=2;times=3,delay;delay=0.25;times=inf;after=1,"
        "kill-mid-batch;mode=raise")
    assert [r.point for r in rules] == ["drop-reply", "delay",
                                       "kill-mid-batch"]
    assert rules[0].wid == 2 and rules[0].times == 3
    assert rules[1].wid is None and rules[1].delay == 0.25
    assert rules[1].times == float("inf") and rules[1].after == 1
    assert rules[2].mode == "raise"
    assert [r.index for r in rules] == [0, 1, 2]


@pytest.mark.parametrize("bad", [
    "no-such-point", "drop-reply;times", "drop-reply;x=1",
    "kill-mid-batch;mode=explode",
])
def test_parse_faults_rejects_malformed(bad):
    with pytest.raises(ValueError):
        faults.parse_faults(bad)


def test_injector_counts_times_after_and_wid():
    inj = faults.FaultInjector(faults.parse_faults(
        "crash-engine;wid=1;times=2;after=1"))
    assert inj.fire("crash-engine", wid=0) is None       # wid filter
    assert inj.fire("drop-reply", wid=1) is None         # point filter
    assert inj.fire("crash-engine", wid=1) is None       # after=1 skip
    assert inj.fire("crash-engine", wid=1) is not None   # fire 1
    assert inj.fire("crash-engine", wid=1) is not None   # fire 2
    assert inj.fire("crash-engine", wid=1) is None       # times spent


def test_injector_shared_state_file_spans_processes(tmp_path):
    """Two injector instances (= two processes) sharing DOS_FAULTS_STATE
    consume ONE fire budget: the kill that must happen exactly once per
    campaign stays exactly-once across a supervisor respawn."""
    state = str(tmp_path / "faults.state.json")
    rules = "kill-mid-batch;times=1"
    a = faults.FaultInjector(faults.parse_faults(rules), state_path=state)
    b = faults.FaultInjector(faults.parse_faults(rules), state_path=state)
    assert a.fire("kill-mid-batch", wid=1) is not None
    assert b.fire("kill-mid-batch", wid=1) is None       # budget spent
    counts = json.load(open(state))
    assert counts["0"]["fired"] == 1 and counts["0"]["seen"] == 2


def test_inject_is_noop_without_env(monkeypatch):
    monkeypatch.delenv("DOS_FAULTS", raising=False)
    assert faults.inject("drop-reply", wid=0) is None


def test_inject_rearms_on_env_change(monkeypatch):
    faults.reset()
    monkeypatch.setenv("DOS_FAULTS", "delay;delay=0.1;times=1")
    assert faults.inject("delay").delay == 0.1
    assert faults.inject("delay") is None
    monkeypatch.setenv("DOS_FAULTS", "delay;delay=0.2;times=1")
    assert faults.inject("delay").delay == 0.2


# --------------------------------------------------- server fault paths

def _bare_server(tmp_path, name, wid=0):
    s = FifoServer.__new__(FifoServer)
    s.wid = wid
    s.command_fifo = str(tmp_path / f"{name}.fifo")
    return s


def _serve(server):
    th = threading.Thread(target=server.serve_forever, daemon=True)
    th.start()
    for _ in range(100):
        if os.path.exists(server.command_fifo):
            break
        time.sleep(0.02)
    else:
        pytest.fail("server fifo never appeared")
    return th


def _request_lines(answer):
    return '{"itrs": 1}\n' + f"/no/such/queryfile {answer} -\n"


def _counters():
    snap = obs_metrics.REGISTRY.snapshot()["counters"]
    return {
        "dropped": snap["server_replies_dropped_total"],
        "batch_fail": snap["server_batches_failed_total"],
        "replies": snap["server_replies_sent_total"],
        "injected": snap["faults_injected_total"],
    }


def test_server_crash_engine_fault_answers_fail(tmp_path, monkeypatch):
    """crash-engine: the batch is answered with FAIL (the head is never
    left blocked) and server_batches_failed_total books it."""
    faults.reset()
    monkeypatch.setenv("DOS_FAULTS", "crash-engine;wid=0;times=1")
    s = _bare_server(tmp_path, "crash")
    answer = str(tmp_path / "crash.answer")
    os.mkfifo(answer)
    before = _counters()
    th = _serve(s)
    try:
        with open(s.command_fifo, "w") as f:
            f.write(_request_lines(answer))
        with open(answer) as f:
            assert f.readline().strip() == "FAIL"
    finally:
        server_mod.stop_server(s.command_fifo)
        th.join(timeout=10)
    after = _counters()
    assert after["batch_fail"] == before["batch_fail"] + 1
    assert after["injected"] == before["injected"] + 1


def test_server_drop_reply_fault_counts_dropped(tmp_path, monkeypatch):
    """drop-reply: the server handles the batch but never answers; the
    drop is booked on server_replies_dropped_total and the NEXT request
    is answered normally (times=1 spent)."""
    faults.reset()
    monkeypatch.setenv("DOS_FAULTS", "drop-reply;wid=0;times=1")
    s = _bare_server(tmp_path, "drop")
    a0, a1 = str(tmp_path / "a0.fifo"), str(tmp_path / "a1.fifo")
    os.mkfifo(a0)
    os.mkfifo(a1)
    before = _counters()
    th = _serve(s)
    got = []

    def read_answer(path):
        fd = os.open(path, os.O_RDONLY | os.O_NONBLOCK)
        try:
            deadline = time.monotonic() + 5
            buf = b""
            while time.monotonic() < deadline and b"\n" not in buf:
                try:
                    chunk = os.read(fd, 4096)
                except BlockingIOError:
                    chunk = b""
                if chunk:
                    buf += chunk
                else:
                    time.sleep(0.02)
            got.append(buf.decode())
        finally:
            os.close(fd)

    try:
        with open(s.command_fifo, "w") as f:
            f.write(_request_lines(a0))
        t0 = threading.Thread(target=read_answer, args=(a0,))
        t0.start()
        t0.join()
        assert got == [""]                       # reply dropped
        with open(s.command_fifo, "w") as f:
            f.write(_request_lines(a1))
        t1 = threading.Thread(target=read_answer, args=(a1,))
        t1.start()
        t1.join()
        assert got[1].strip() == "FAIL"          # bare server: engine err
    finally:
        server_mod.stop_server(s.command_fifo)
        th.join(timeout=10)
    after = _counters()
    assert after["dropped"] == before["dropped"] + 1
    assert after["replies"] == before["replies"] + 1


def test_server_delay_fault_delays_reply(tmp_path, monkeypatch):
    faults.reset()
    monkeypatch.setenv("DOS_FAULTS", "delay;wid=0;delay=0.4;times=1")
    s = _bare_server(tmp_path, "slow")
    answer = str(tmp_path / "slow.answer")
    os.mkfifo(answer)
    th = _serve(s)
    try:
        t0 = time.monotonic()
        with open(s.command_fifo, "w") as f:
            f.write(_request_lines(answer))
        with open(answer) as f:
            assert f.readline().strip() == "FAIL"
        assert time.monotonic() - t0 >= 0.4
    finally:
        server_mod.stop_server(s.command_fifo)
        th.join(timeout=10)


def test_server_kill_mid_batch_raise_mode_dies_without_reply(
        tmp_path, monkeypatch):
    """kill-mid-batch (mode=raise, the in-thread variant): the serve
    loop dies after reading the request and before any reply — the
    injected analog of the reference's head-wedging worker crash."""
    faults.reset()
    monkeypatch.setenv("DOS_FAULTS",
                       "kill-mid-batch;wid=0;times=1;mode=raise")
    s = _bare_server(tmp_path, "kill")
    answer = str(tmp_path / "kill.answer")
    os.mkfifo(answer)
    before = _counters()
    th = _serve(s)
    with open(s.command_fifo, "w") as f:
        f.write(_request_lines(answer))
    th.join(timeout=10)
    assert not th.is_alive()                     # server died mid-batch
    after = _counters()
    assert after["replies"] == before["replies"]
    assert after["injected"] == before["injected"] + 1


def test_server_ping_health_line(tmp_path):
    """__DOS_PING__ control frame: one HealthStatus JSON line back, and
    data-plane counters untouched (pings are not frames)."""
    s = _bare_server(tmp_path, "ping", wid=7)
    answer = str(tmp_path / "ping.answer")
    os.mkfifo(answer)
    frames_before = server_mod.M_FRAMES.value
    th = _serve(s)
    try:
        with open(s.command_fifo, "w") as f:
            f.write(f"__DOS_PING__ {answer}\n")
        with open(answer) as f:
            st = HealthStatus.from_json(f.readline())
        assert st.ok and st.wid == 7 and st.pid == os.getpid()
        assert st.uptime_s >= 0 and st.batches == 0
    finally:
        server_mod.stop_server(s.command_fifo)
        th.join(timeout=10)
    assert server_mod.M_FRAMES.value == frames_before


def test_server_health_reflects_failures(tmp_path, monkeypatch):
    """batches / batch_failures / last_error in the health line move
    with the serve loop's actual outcomes."""
    faults.reset()
    monkeypatch.delenv("DOS_FAULTS", raising=False)
    s = _bare_server(tmp_path, "hstate")
    answer = str(tmp_path / "hstate.answer")
    os.mkfifo(answer)
    # the ping reply gets its OWN fifo, mirroring the production probe
    # protocol (transport.fifo.probe mints a unique answer fifo per
    # probe): re-opening a shared reply fifo races the server's
    # previous-reply writer close — the reader can connect to the old
    # fd and read EOF before the new reply's writer opens (the PR 2
    # stale-reply race class this test used to win by scheduler luck)
    ping_answer = str(tmp_path / "hstate.ping.answer")
    os.mkfifo(ping_answer)
    th = _serve(s)
    try:
        with open(s.command_fifo, "w") as f:     # bare server: FAILs
            f.write(_request_lines(answer))
        with open(answer) as f:
            assert f.readline().strip() == "FAIL"
        with open(s.command_fifo, "w") as f:
            f.write(f"__DOS_PING__ {ping_answer}\n")
        with open(ping_answer) as f:
            st = HealthStatus.from_json(f.readline())
        assert st.batches == 1 and st.batch_failures == 1
        assert st.last_error != ""
    finally:
        server_mod.stop_server(s.command_fifo)
        th.join(timeout=10)


# ------------------------------------------- DOS_FAULTS permutation smoke

@pytest.mark.parametrize("spec,expect", [
    ("crash-engine;times=2", {"server_batches_failed_total": 2}),
    ("drop-reply;times=1", {"server_replies_dropped_total": 1}),
    # request 0 crashes via injection, request 1 fails naturally (the
    # bare server has no engine) and its reply is dropped: 2 failed
    # batches, 1 dropped reply
    ("crash-engine;times=1,drop-reply;times=1;after=1",
     {"server_batches_failed_total": 2,
      "server_replies_dropped_total": 1}),
])
def test_fault_permutations_move_exactly_their_counters(
        tmp_path, monkeypatch, spec, expect):
    """The tier-1 fault-path smoke: each DOS_FAULTS permutation moves
    exactly the counters it should, asserted via a registry snapshot."""
    faults.reset()
    monkeypatch.setenv("DOS_FAULTS", spec)
    s = _bare_server(tmp_path, "perm")
    before = obs_metrics.REGISTRY.snapshot()["counters"]
    th = _serve(s)
    try:
        for i in range(2):
            answer = str(tmp_path / f"perm{i}.answer")
            os.mkfifo(answer)
            with open(s.command_fifo, "w") as f:
                f.write(_request_lines(answer))
            # drain the answer (or observe the drop) without blocking
            fd = os.open(answer, os.O_RDONLY | os.O_NONBLOCK)
            deadline = time.monotonic() + 5
            buf = b""
            while time.monotonic() < deadline and b"\n" not in buf:
                try:
                    buf += os.read(fd, 4096) or b""
                except BlockingIOError:
                    pass
                time.sleep(0.02)
            os.close(fd)
    finally:
        server_mod.stop_server(s.command_fifo)
        th.join(timeout=10)
    after = obs_metrics.REGISTRY.snapshot()["counters"]
    for name, delta in expect.items():
        assert after[name] - before[name] == delta, name
