"""Durable CPD builds: atomic artifacts, checksummed manifests,
crash-resume, and self-healing loads.

Non-slow: atomic-write/sweep units, manifest v2 digest round-trips, the
schema compat contract (unknown keys tolerated, v1 loads under v2 code,
newer majors rejected — the RuntimeConfig wire-codec rule applied to the
index manifest), corrupt-block detection + quarantine + in-place rebuild
at load (oracle and engine paths, counters asserted), verify exit codes,
and ``dos-serve`` draining cleanly on SIGTERM.

Slow: the kill-mid-build chaos drill — the build SUBPROCESS dies between
block flushes via the ``crash-build`` fault point, the rerun resumes off
the digest-verified ledger, and the completed index is bit-identical to
an uninterrupted build while only the missing tail was recomputed.
"""

import glob
import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

from distributed_oracle_search_tpu.data import (
    synth_city_graph, write_xy,
)
from distributed_oracle_search_tpu.models.cpd import (
    CPDOracle, INDEX_VERSION, M_BLOCKS_CORRUPT, M_BLOCKS_REBUILT,
    M_BLOCKS_RESUMED, M_BLOCKS_VERIFIED, BuildLedger, block_complete,
    build_worker_shard, ledger_path, read_manifest, shard_block_name,
    validate_manifest, verify_exit_code, verify_index,
    write_index_manifest,
)
from distributed_oracle_search_tpu.obs import metrics as obs_metrics
from distributed_oracle_search_tpu.parallel.partition import (
    DistributionController,
)
from distributed_oracle_search_tpu.utils import atomicio
from distributed_oracle_search_tpu.worker.engine import (
    ShardEngine, load_shard_rows,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N_WORKERS = 8
BLOCK_SIZE = 4          # several blocks per worker on the toy graph


@pytest.fixture()
def toy_dc(toy_graph):
    return DistributionController("tpu", N_WORKERS, N_WORKERS,
                                  toy_graph.n, block_size=BLOCK_SIZE)


@pytest.fixture()
def built_dir(tmp_path, toy_graph, toy_dc):
    """A complete per-block index with a v2 manifest."""
    d = str(tmp_path / "index")
    for wid in range(N_WORKERS):
        build_worker_shard(toy_graph, toy_dc, wid, d)
    write_index_manifest(d, toy_dc)
    return d


def _corrupt(path: str, flip_at: int = -3) -> None:
    raw = bytearray(open(path, "rb").read())
    raw[flip_at] ^= 0xFF
    with open(path, "wb") as f:
        f.write(bytes(raw))


# ------------------------------------------------------------- atomic IO

def test_atomic_write_bytes_roundtrip(tmp_path):
    p = str(tmp_path / "a.bin")
    atomicio.atomic_write_bytes(p, b"payload")
    assert open(p, "rb").read() == b"payload"
    # no tmp debris after a successful write
    assert not glob.glob(str(tmp_path / "*.tmp.*"))


def test_digest_is_algorithm_prefixed_and_stable(tmp_path):
    d1 = atomicio.digest_bytes(b"abc")
    assert d1.startswith("crc32:")
    p = str(tmp_path / "f")
    atomicio.atomic_write_bytes(p, b"abc")
    assert atomicio.digest_file(p) == d1


def test_sweep_removes_tmp_and_quarantine_debris(tmp_path):
    import time

    old = time.time() - 3600         # debris from a long-dead process
    for name in ("cpd-w00000-b00000.npy.tmp.123",
                 "cpd-w00000-b00001.npy.quarantined"):
        p = tmp_path / name
        p.write_bytes(b"stale")
        os.utime(p, (old, old))
    (tmp_path / "cpd-w00000-b00002.npy").write_bytes(b"keep")
    # a FRESH tmp file may be another live process's in-flight atomic
    # write (a resident server mid-heal) — the sweep must leave it alone
    (tmp_path / "cpd-w00000-b00003.npy.tmp.456").write_bytes(b"live")
    before = obs_metrics.REGISTRY.snapshot()["counters"].get(
        "artifacts_swept_total", 0)
    n = atomicio.sweep_stale_artifacts(str(tmp_path))
    after = obs_metrics.REGISTRY.snapshot()["counters"].get(
        "artifacts_swept_total", 0)
    assert n == 2 and after - before == 2
    assert sorted(os.listdir(tmp_path)) == [
        "cpd-w00000-b00002.npy", "cpd-w00000-b00003.npy.tmp.456"]


def test_atomic_npy_digest_matches_file(tmp_path):
    arr = np.arange(24, dtype=np.int8).reshape(4, 6)
    p = str(tmp_path / "b.npy")
    digest = atomicio.atomic_save_npy(p, arr)
    assert digest == atomicio.digest_file(p)
    assert (np.load(p) == arr).all()


# ------------------------------------------------- ledger + crash-resume

def test_ledger_records_and_verifies_blocks(tmp_path, toy_graph, toy_dc):
    d = str(tmp_path / "idx")
    build_worker_shard(toy_graph, toy_dc, 0, d)
    entries = BuildLedger(d, 0).entries()
    n_blocks = -(-toy_dc.n_owned(0) // BLOCK_SIZE)
    assert len(entries) == n_blocks
    fname = shard_block_name(0, 0)
    assert block_complete(d, fname, entries)
    # digest mismatch -> not complete -> the block would be recomputed
    _corrupt(os.path.join(d, fname))
    assert not block_complete(d, fname, entries)


def test_ledger_tolerates_torn_trailing_line(tmp_path, toy_graph, toy_dc):
    d = str(tmp_path / "idx")
    build_worker_shard(toy_graph, toy_dc, 0, d)
    with open(ledger_path(d, 0), "a") as f:
        f.write('{"file": "cpd-w00000-b9')     # crash mid-append
    entries = BuildLedger(d, 0).entries()
    assert shard_block_name(0, 0) in entries   # earlier lines intact


def test_resume_recomputes_only_invalid_blocks(tmp_path, toy_graph,
                                               toy_dc):
    d = str(tmp_path / "idx")
    ref = str(tmp_path / "ref")
    build_worker_shard(toy_graph, toy_dc, 0, ref)
    build_worker_shard(toy_graph, toy_dc, 0, d)
    # one block deleted, one corrupted: resume must redo exactly those
    gone = shard_block_name(0, 0)
    bad = shard_block_name(0, 1)
    os.remove(os.path.join(d, gone))
    _corrupt(os.path.join(d, bad))
    r0 = M_BLOCKS_RESUMED.value
    written = build_worker_shard(toy_graph, toy_dc, 0, d)
    assert sorted(written) == sorted([gone, bad])
    n_blocks = -(-toy_dc.n_owned(0) // BLOCK_SIZE)
    assert M_BLOCKS_RESUMED.value - r0 == n_blocks - 2
    for f in sorted(os.listdir(ref)):
        if f.endswith(".npy"):
            assert (open(os.path.join(d, f), "rb").read()
                    == open(os.path.join(ref, f), "rb").read()), f


def test_legacy_unledgered_blocks_resume(tmp_path, toy_graph, toy_dc):
    """Blocks from a pre-ledger build (no journal) still count as done
    when they parse; torn ones are rebuilt."""
    d = str(tmp_path / "idx")
    build_worker_shard(toy_graph, toy_dc, 0, d)
    os.remove(ledger_path(d, 0))
    assert build_worker_shard(toy_graph, toy_dc, 0, d) == []
    # truncate one block: unreadable npy -> recomputed
    bad = os.path.join(d, shard_block_name(0, 1))
    with open(bad, "wb") as f:
        f.write(b"\x93NUMPY")                  # torn header
    written = build_worker_shard(toy_graph, toy_dc, 0, d)
    assert written == [shard_block_name(0, 1)]


def test_build_sweeps_own_tmp_debris(tmp_path, toy_graph, toy_dc):
    import time

    d = str(tmp_path / "idx")
    os.makedirs(d)
    debris = os.path.join(d, shard_block_name(0, 0) + ".tmp.999")
    fresh = os.path.join(d, shard_block_name(0, 1) + ".tmp.888")
    other = os.path.join(d, shard_block_name(3, 0) + ".tmp.999")
    for p in (debris, fresh, other):
        with open(p, "wb") as f:
            f.write(b"torn")
    old = time.time() - 3600
    for p in (debris, other):
        os.utime(p, (old, old))
    build_worker_shard(toy_graph, toy_dc, 0, d)
    assert not os.path.exists(debris)    # mine + stale: swept
    assert os.path.exists(fresh)         # mine but YOUNG (possibly a
    #                                      live concurrent write): kept
    assert os.path.exists(other)         # another worker's: kept


# --------------------------------------------- manifest v2 + compat

def test_manifest_v2_records_digests(built_dir, toy_dc):
    m = read_manifest(built_dir)
    assert m["version"] == INDEX_VERSION == 2
    assert m["digest_algo"] == "crc32"
    assert set(m["blocks"]) == set(m["files"])
    ent = m["blocks"][m["files"][0]]
    assert ent["digest"].startswith("crc32:")
    assert ent["dtype"] == "int8" and len(ent["shape"]) == 2


def test_validate_manifest_compat_contract(built_dir, toy_dc):
    """The wire-codec rule applied to the manifest: unknown keys are
    tolerated, only a NEWER schema version rejects. A manifest missing
    a REQUIRED key raises ValueError (not KeyError), so verify_index
    books it fatal instead of crashing the --verify CLI. The engine
    load path applies the same version gate — a v3 manifest must not
    be misread into mass quarantine/rebuild."""
    m = read_manifest(built_dir)
    m["some_future_key"] = {"nested": True}
    validate_manifest(m, toy_dc, built_dir)            # no raise
    m2 = dict(m)
    del m2["nodenum"]
    with pytest.raises(ValueError, match="missing required key"):
        validate_manifest(m2, toy_dc, built_dir)
    m["version"] = INDEX_VERSION + 1
    with pytest.raises(ValueError, match="schema"):
        validate_manifest(m, toy_dc, built_dir)
    with open(os.path.join(built_dir, "index.json"), "w") as f:
        json.dump(m, f)
    with pytest.raises(ValueError, match="schema"):
        load_shard_rows(built_dir, 0)
    assert verify_exit_code(verify_index(built_dir, dc=toy_dc)) == 4


def test_v1_manifest_loads_under_v2_code(built_dir, toy_graph, toy_dc):
    """A pre-digest index keeps loading: v1 has no ``blocks``, so the
    load runs in unverified mode and still answers correctly."""
    m = read_manifest(built_dir)
    m.pop("blocks")
    m.pop("digest_algo")
    m["version"] = 1
    with open(os.path.join(built_dir, "index.json"), "w") as f:
        json.dump(m, f)
    oracle = CPDOracle(toy_graph, toy_dc).load(built_dir)
    queries = np.array([[1, 5], [7, 40], [3, 3]])
    cost, plen, fin = oracle.query(queries)
    assert bool(fin.all())
    rep = verify_index(built_dir, dc=toy_dc)
    assert verify_exit_code(rep) == 0          # unverified counts clean
    assert len(rep["unverified"]) == rep["total"]


# ------------------------------------- corrupt blocks: detect/quarantine

def test_load_detects_quarantines_and_rebuilds(built_dir, toy_graph,
                                               toy_dc):
    fname = shard_block_name(2, 1)
    path = os.path.join(built_dir, fname)
    _corrupt(path)
    v0, c0, r0 = (M_BLOCKS_VERIFIED.value, M_BLOCKS_CORRUPT.value,
                  M_BLOCKS_REBUILT.value)
    oracle = CPDOracle(toy_graph, toy_dc).load(built_dir)
    assert M_BLOCKS_CORRUPT.value - c0 == 1
    assert M_BLOCKS_REBUILT.value - r0 == 1
    assert M_BLOCKS_VERIFIED.value - v0 == len(
        read_manifest(built_dir)["files"]) - 1
    assert os.path.exists(path + ".quarantined")
    # healed in place: the index verifies clean again and answers match
    # a freshly built oracle
    assert verify_exit_code(verify_index(built_dir, dc=toy_dc)) == 0
    ref = CPDOracle(toy_graph, toy_dc).build()
    queries = np.stack(np.meshgrid(np.arange(0, toy_graph.n, 5),
                                   np.arange(0, toy_graph.n, 7)),
                       axis=-1).reshape(-1, 2)
    got = oracle.query(queries)
    want = ref.query(queries)
    for a, b in zip(got, want):
        assert (a == b).all()


def test_load_without_heal_raises_diagnostic(built_dir, toy_graph,
                                             toy_dc):
    fname = shard_block_name(1, 0)
    _corrupt(os.path.join(built_dir, fname))
    with pytest.raises(ValueError, match=fname):
        CPDOracle(toy_graph, toy_dc).load(built_dir, heal=False)


def test_load_missing_block_is_rebuilt(built_dir, toy_graph, toy_dc):
    """The manifest knows blocks the directory glob cannot see."""
    fname = shard_block_name(4, 0)
    os.remove(os.path.join(built_dir, fname))
    r0 = M_BLOCKS_REBUILT.value
    CPDOracle(toy_graph, toy_dc).load(built_dir)
    assert M_BLOCKS_REBUILT.value - r0 == 1
    assert os.path.exists(os.path.join(built_dir, fname))


def test_engine_load_self_heals(built_dir, toy_graph, toy_dc):
    fname = shard_block_name(3, 1)
    path = os.path.join(built_dir, fname)
    _corrupt(path)
    c0, r0 = M_BLOCKS_CORRUPT.value, M_BLOCKS_REBUILT.value
    eng = ShardEngine(toy_graph, toy_dc, 3, built_dir)
    assert M_BLOCKS_CORRUPT.value - c0 == 1
    assert M_BLOCKS_REBUILT.value - r0 == 1
    assert os.path.exists(path + ".quarantined")
    owned = toy_dc.owned(3)
    queries = np.stack([np.arange(len(owned)), owned], axis=1)
    from distributed_oracle_search_tpu.transport.wire import RuntimeConfig
    cost, plen, fin, _stats = eng.answer(queries, RuntimeConfig())
    assert bool(fin.all())


def test_engine_heal_refreshes_manifest_no_rebuild_churn(
        built_dir, toy_graph, toy_dc):
    """A rebuilt block whose digest differs from the manifest (index
    built by a different kernel) must refresh the manifest entry —
    otherwise every later load re-flags the healthy rebuild as corrupt
    and rebuilds it again, forever."""
    fname = shard_block_name(6, 0)
    m = read_manifest(built_dir)
    m["blocks"][fname]["digest"] = "crc32:00000000"   # foreign build
    with open(os.path.join(built_dir, "index.json"), "w") as f:
        json.dump(m, f)
    r0 = M_BLOCKS_REBUILT.value
    load_shard_rows(built_dir, 6, dc=toy_dc, graph=toy_graph)
    assert M_BLOCKS_REBUILT.value - r0 == 1
    # manifest refreshed with the rebuilt digest: the next load (either
    # path) finds the index clean — no rebuild churn
    assert verify_exit_code(verify_index(built_dir, dc=toy_dc)) == 0
    load_shard_rows(built_dir, 6, dc=toy_dc, graph=toy_graph)
    CPDOracle(toy_graph, toy_dc).load(built_dir)
    assert M_BLOCKS_REBUILT.value - r0 == 1


def test_engine_load_degrades_without_graph(built_dir):
    _corrupt(os.path.join(built_dir, shard_block_name(5, 0)))
    with pytest.raises(ValueError, match="degraded"):
        load_shard_rows(built_dir, 5)


# ------------------------------------------------------ verify exit codes

def test_verify_exit_codes(built_dir, toy_dc, tmp_path):
    # clean
    assert verify_exit_code(verify_index(built_dir, dc=toy_dc)) == 0
    # degraded: one bad block among many
    _corrupt(os.path.join(built_dir, shard_block_name(0, 0)))
    rep = verify_index(built_dir, dc=toy_dc)
    assert verify_exit_code(rep) == 3
    assert rep["corrupt"][0]["file"] == shard_block_name(0, 0)
    # corrupt: every block bad (a different byte than above, so the
    # already-corrupt block stays corrupt instead of un-flipping)
    for f in read_manifest(built_dir)["files"]:
        _corrupt(os.path.join(built_dir, f), flip_at=-5)
    assert verify_exit_code(verify_index(built_dir, dc=toy_dc)) == 4
    # fatal: no manifest at all
    rep = verify_index(str(tmp_path / "nowhere"))
    assert rep["fatal"] and verify_exit_code(rep) == 4
    # fatal: partition mismatch
    other = DistributionController("tpu", N_WORKERS, N_WORKERS,
                                   toy_dc.nodenum, block_size=64)
    rep = verify_index(built_dir, dc=other)
    assert rep["fatal"] and verify_exit_code(rep) == 4


def test_make_cpds_verify_cli(tmp_path, monkeypatch):
    """--verify exits 0/3 per the campaign convention, through main()."""
    from distributed_oracle_search_tpu.cli.make_cpds import main as cpds
    monkeypatch.chdir(tmp_path)
    assert cpds(["-t"]) == 0
    assert cpds(["-t", "--verify"]) == 0
    blocks = sorted(glob.glob("data/index/cpd-*.npy"))
    _corrupt(blocks[0])
    code = cpds(["-t", "--verify"])
    assert code == (3 if len(blocks) > 1 else 4)


# --------------------------------------------------- dos-serve drain

def test_serve_sigterm_drains_and_exits_clean(tmp_path):
    """SIGTERM stops ingress, answers/sheds every accepted request,
    writes the metrics dump, and exits 0 — never a silent drop."""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO_ROOT + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    proc = subprocess.Popen(
        [sys.executable, "-m", "distributed_oracle_search_tpu.cli.serve",
         "-t", "--ingress", "stdin", "--metrics-dump", "serve_obs.json"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True,
        cwd=str(tmp_path), env=env)
    try:
        for q in ("1 5", "2 9", "7 40"):
            proc.stdin.write(q + "\n")
        proc.stdin.flush()
        answers = [proc.stdout.readline().strip()]   # at least one served
        assert answers[0].startswith("OK ")
        proc.send_signal(signal.SIGTERM)
        # every accepted request still gets a response line before exit
        for line in proc.stdout:
            if line.strip():
                answers.append(line.strip())
        rc = proc.wait(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
    assert rc == 0
    assert len(answers) == 3
    assert all(a.split()[0] in ("OK", "BUSY", "UNAVAILABLE", "TIMEOUT",
                                "ERROR") for a in answers)
    assert os.path.exists(tmp_path / "serve_obs.json")


# -------------------------------------------------- chaos: kill-mid-build

@pytest.mark.slow
def test_kill_mid_build_resume_chaos(tmp_path, toy_graph):
    """The full drill: the build SUBPROCESS is killed by the fault
    harness between block flushes; the rerun (resume on by default)
    recomputes only the missing tail, the finished index is bit-identical
    to an uninterrupted build, and the resume proves itself through
    ``build_blocks_resumed_total``."""
    from distributed_oracle_search_tpu.testing.faults import KILL_EXIT_CODE

    xy = str(tmp_path / "g.xy")
    write_xy(xy, toy_graph.xs, toy_graph.ys, toy_graph.src,
             toy_graph.dst, toy_graph.w)
    outdir = str(tmp_path / "idx")
    refdir = str(tmp_path / "ref")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO_ROOT + os.pathsep
               + os.environ.get("PYTHONPATH", ""),
               DOS_FAULTS="crash-build;after=0;times=1;mode=exit")
    cmd = [sys.executable, "-m",
           "distributed_oracle_search_tpu.worker.build",
           "--input", xy, "--partmethod", "div", "--partkey", "24",
           "--workerid", "0", "--maxworker", "2",
           "--outdir", outdir, "--block-size", "8"]
    r = subprocess.run(cmd, env=env, capture_output=True, text=True,
                       timeout=300)
    assert r.returncode == KILL_EXIT_CODE, r.stderr[-2000:]
    survivors = sorted(f for f in os.listdir(outdir)
                       if f.endswith(".npy"))
    assert survivors == [shard_block_name(0, 0)]   # died after block 0

    # rerun in-process (counters observable) with resume on
    dc = DistributionController("div", 24, 2, toy_graph.n, block_size=8)
    r0 = M_BLOCKS_RESUMED.value
    written = build_worker_shard(toy_graph, dc, 0, outdir)
    assert M_BLOCKS_RESUMED.value - r0 > 0
    assert shard_block_name(0, 0) not in written   # only the tail
    build_worker_shard(toy_graph, dc, 0, refdir)
    idx_files = sorted(f for f in os.listdir(outdir)
                       if f.endswith(".npy"))
    ref_files = sorted(f for f in os.listdir(refdir)
                       if f.endswith(".npy"))
    assert idx_files == ref_files
    for f in idx_files:
        assert (open(os.path.join(outdir, f), "rb").read()
                == open(os.path.join(refdir, f), "rb").read()), f
    # the healed shard carries a digest-clean manifest
    write_index_manifest(outdir, dc, workers=[0])
    assert verify_exit_code(verify_index(outdir)) == 0
