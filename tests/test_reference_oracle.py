"""CPU reference oracle: Dijkstra / first-move / table-search invariants.

These are the golden semantics every other backend (TPU ops, native C++) is
tested against, so they get their own sanity checks: triangle inequality,
walk-cost == shortest-dist on free-flow weights, diff behavior.
"""

import numpy as np

from distributed_oracle_search_tpu.data import synth_diff
from distributed_oracle_search_tpu.data.graph import INF
from distributed_oracle_search_tpu.models import (
    dijkstra, dist_to_target, first_move_matrix, table_search_walk,
)


def test_dijkstra_forward_reverse_symmetry(toy_graph):
    g = toy_graph
    s, t = 3, g.n - 2
    assert dijkstra(g, s)[t] == dijkstra(g, t, reverse=True)[s]


def test_first_move_walk_reproduces_shortest_dist(toy_graph):
    g = toy_graph
    targets = np.arange(g.n)
    fm = first_move_matrix(g, targets)          # [T=N, N] slots
    assert fm.dtype == np.int8

    rng = np.random.default_rng(0)
    for _ in range(30):
        s, t = rng.integers(0, g.n, 2)
        d = dist_to_target(g, int(t))
        cost, plen, finished, path = table_search_walk(
            g, lambda x, tt: fm[tt, x], int(s), int(t))
        if s == t:
            assert cost == 0 and finished
            continue
        assert finished, f"walk {s}->{t} did not finish"
        assert cost == d[s], "free-flow walk cost must equal shortest dist"
        assert path[0] == s and path[-1] == t
        assert plen == len(path) - 1


def test_first_move_self_is_minus_one(toy_graph):
    g = toy_graph
    fm = first_move_matrix(g, np.arange(g.n))
    assert np.all(fm[np.arange(g.n), np.arange(g.n)] == -1)


def test_walk_on_perturbed_weights(toy_graph):
    # Diff changes query-time cost but not the route (reference semantics:
    # first moves stay free-flow, cost accumulates on perturbed weights).
    g = toy_graph
    ds, dd, dw = synth_diff(g, frac=0.3, seed=9)
    w_query = g.weights_with_diff((ds, dd, dw))
    fm = first_move_matrix(g, np.arange(g.n))

    rng = np.random.default_rng(1)
    for _ in range(10):
        s, t = rng.integers(0, g.n, 2)
        if s == t:
            continue
        c0, p0, f0, path0 = table_search_walk(
            g, lambda x, tt: fm[tt, x], int(s), int(t))
        c1, p1, f1, path1 = table_search_walk(
            g, lambda x, tt: fm[tt, x], int(s), int(t), w_query=w_query)
        assert path0 == path1          # same route
        assert f1 and p1 == p0
        assert c1 >= c0                # congestion only slows down


def test_k_moves_bounds_walk(toy_graph):
    g = toy_graph
    fm = first_move_matrix(g, np.arange(g.n))
    # find a pair with plen >= 3
    rng = np.random.default_rng(2)
    for _ in range(50):
        s, t = rng.integers(0, g.n, 2)
        _, plen, fin, _ = table_search_walk(g, lambda x, tt: fm[tt, x],
                                            int(s), int(t))
        if fin and plen >= 3:
            break
    c, p, fin, path = table_search_walk(g, lambda x, tt: fm[tt, x],
                                        int(s), int(t), k_moves=2)
    assert p == 2 and not fin and len(path) == 3


def test_unreachable_reports_inf():
    # two disconnected 2-node islands
    from distributed_oracle_search_tpu.data.graph import Graph
    g = Graph(xs=[0, 1, 5, 6], ys=[0, 0, 0, 0],
              src=[0, 1, 2, 3], dst=[1, 0, 3, 2], w=[1, 1, 1, 1])
    d = dist_to_target(g, 3)
    assert d[0] == INF and d[1] == INF and d[2] == 1 and d[3] == 0
    fm = first_move_matrix(g, np.array([3]))
    assert fm[0, 0] == -1 and fm[0, 1] == -1  # unreachable -> no move
    cost, plen, fin, _ = table_search_walk(
        g, lambda x, tt: fm[0, x], 0, 3)
    assert not fin and plen == 0
