"""Fast-sweeping build: bit-parity with the ELL relaxation, grid
detection, and the sharded build path (SURVEY.md §7 stage 3)."""

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_oracle_search_tpu.data import synth_city_graph
from distributed_oracle_search_tpu.data.graph import Graph
from distributed_oracle_search_tpu.models.cpd import (
    CPDOracle, pick_build_kernel,
)
from distributed_oracle_search_tpu.ops import DeviceGraph
from distributed_oracle_search_tpu.ops.bellman_ford import (
    build_fm_columns, dist_to_targets,
)
from distributed_oracle_search_tpu.ops.grid_sweep import (
    GridGraph, build_fm_columns_sweep, dist_to_targets_sweep,
)
from distributed_oracle_search_tpu.parallel import DistributionController
from distributed_oracle_search_tpu.parallel.mesh import make_mesh


@pytest.mark.parametrize("side,seed", [(8, 7), (16, 3), (24, 0)])
def test_sweep_dist_bit_identical(side, seed):
    g = synth_city_graph(side, side, seed=seed)
    gg = GridGraph.from_graph(g)
    assert gg is not None
    dg = DeviceGraph.from_graph(g)
    tg = jnp.asarray(np.r_[np.arange(min(48, g.n)), -1, -1], jnp.int32)
    np.testing.assert_array_equal(
        np.asarray(dist_to_targets_sweep(gg, tg)),
        np.asarray(dist_to_targets(dg, tg)))


def test_sweep_fm_matches_ell():
    g = synth_city_graph(12, 9, seed=11)
    gg = GridGraph.from_graph(g)
    dg = DeviceGraph.from_graph(g)
    tg = jnp.asarray(np.r_[np.arange(40), -1], jnp.int32)
    np.testing.assert_array_equal(
        np.asarray(build_fm_columns_sweep(dg, gg, tg)),
        np.asarray(build_fm_columns(dg, tg)))


def test_grid_split_coverage_and_stragglers():
    g = synth_city_graph(16, 16, seed=2)
    gg = GridGraph.from_graph(g)
    # synthetic city is grid + constant-offset shortcuts: near-total
    # coverage, stragglers only from border clipping
    assert gg.coverage() > 0.99
    n_struct = (int((np.asarray(gg.w_shift) < 10 ** 9).sum())
                + sum(int((np.asarray(a) < 10 ** 9).sum())
                      for a in (gg.wl, gg.wr, gg.wd, gg.wu)))
    assert n_struct + gg.n_left == g.m


def test_non_grid_graph_gets_low_coverage_not_wrong_answers():
    # a star graph has no lattice structure: the split still works (it is
    # permissive — stragglers keep correctness), but coverage is too low
    # for auto to ever pick sweep, and the sweep result stays exact
    n = 12
    src = np.r_[np.zeros(n - 1, np.int64), np.arange(1, n)]
    dst = np.r_[np.arange(1, n), np.zeros(n - 1, np.int64)]
    g = Graph(np.arange(n), np.arange(n), src, dst,
              np.full(2 * (n - 1), 5, np.int32))
    gg = GridGraph.from_graph(g)
    if gg is not None:
        from distributed_oracle_search_tpu.models.cpd import (
            SWEEP_COVERAGE_MIN,
        )
        assert gg.lattice_coverage() < SWEEP_COVERAGE_MIN
        dg = DeviceGraph.from_graph(g)
        tg = jnp.asarray(np.arange(n), jnp.int32)
        np.testing.assert_array_equal(
            np.asarray(dist_to_targets_sweep(gg, tg)),
            np.asarray(dist_to_targets(dg, tg)))


def test_pick_build_kernel_policies():
    g = synth_city_graph(10, 10, seed=4)
    kind, st = pick_build_kernel(g, "sweep")
    assert kind == "sweep" and isinstance(st, GridGraph)
    kind, _ = pick_build_kernel(g, "shift")
    assert kind == "shift"
    kind, st = pick_build_kernel(g, "ell")
    assert kind == "ell" and st is None
    # auto on a small grid stays with shift (sweep pays off above
    # SWEEP_MIN_NODES only)
    kind, _ = pick_build_kernel(g, "auto")
    assert kind == "shift"
    with pytest.raises(ValueError, match="unknown build method"):
        pick_build_kernel(g, "bogus")


def test_sharded_sweep_build_matches_auto(toy_graph):
    dc = DistributionController("tpu", None, 8, toy_graph.n)
    mesh = make_mesh(n_workers=8)
    a = CPDOracle(toy_graph, dc, mesh=mesh).build(chunk=16, method="sweep")
    b = CPDOracle(toy_graph, dc, mesh=mesh).build(chunk=16, method="ell")
    np.testing.assert_array_equal(np.asarray(a.fm), np.asarray(b.fm))
