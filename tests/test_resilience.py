"""Head-side resilience: retry policy, per-attempt answer FIFOs (the
stale-reply race fix), circuit breaker state machine, liveness probes,
non-wedging stop, and stale-FIFO cleanup."""

import os
import threading
import time

import pytest

from distributed_oracle_search_tpu.obs import metrics as obs_metrics
from distributed_oracle_search_tpu.testing import faults
from distributed_oracle_search_tpu.transport import fifo as fifo_mod
from distributed_oracle_search_tpu.transport import resilience
from distributed_oracle_search_tpu.transport.fifo import (
    RetryPolicy, clean_stale_answer_fifos, probe, send_with_retry,
)
from distributed_oracle_search_tpu.transport.wire import (
    HealthStatus, Request, RuntimeConfig, StatsRow,
)
from distributed_oracle_search_tpu.worker import server as server_mod
from distributed_oracle_search_tpu.worker.server import (
    FifoServer, stop_server,
)


# -------------------------------------------------------------- RetryPolicy

def test_retry_policy_backoff_is_capped_exponential_and_deterministic():
    p = RetryPolicy(retries=5, base_s=0.1, cap_s=0.4, jitter=0.0)
    assert [p.backoff_s(i) for i in range(4)] == [0.1, 0.2, 0.4, 0.4]
    pj = RetryPolicy(base_s=0.1, cap_s=10.0, jitter=0.5)
    a = pj.backoff_s(2, seed="answer.host3")
    b = pj.backoff_s(2, seed="answer.host3")
    assert a == b                       # crc32 seed: reruns identical
    assert 0.2 <= a <= 0.6              # raw 0.4 +- 50%
    assert pj.backoff_s(2, seed="answer.host4") != a


def test_retry_policy_from_env(monkeypatch):
    monkeypatch.setenv("DOS_RETRY_MAX", "3")
    monkeypatch.setenv("DOS_RETRY_BASE_S", "0.01")
    monkeypatch.setenv("DOS_RETRY_CAP_S", "0.05")
    monkeypatch.setenv("DOS_RETRY_JITTER", "0")
    p = RetryPolicy.from_env()
    assert (p.retries, p.base_s, p.cap_s, p.jitter) == (3, 0.01, 0.05, 0)
    monkeypatch.setenv("DOS_RETRY_MAX", "garbage")
    assert RetryPolicy.from_env().retries == 1      # default survives


def test_send_with_retry_uses_unique_answer_fifo_per_attempt(monkeypatch):
    """The stale-reply race fix: every attempt reads its own FIFO and the
    request carries that attempt's name, so a late reply to attempt N
    can never satisfy attempt N+1."""
    seen = []

    def fake_send(host, request, command_fifo, timeout=None, wid=None):
        seen.append(request.answerfifo)
        return (StatsRow.failed() if len(seen) < 3
                else StatsRow(plen=1))

    monkeypatch.setattr(fifo_mod, "send", fake_send)
    before = fifo_mod.M_RETRIES.value
    req = Request(RuntimeConfig(), "/nfs/q", "/nfs/answer.h0", "-")
    row = send_with_retry("localhost", req, "/tmp/w0.fifo",
                          policy=RetryPolicy(retries=3, base_s=0.0,
                                             jitter=0.0))
    assert row.ok
    assert seen == ["/nfs/answer.h0.a0", "/nfs/answer.h0.a1",
                    "/nfs/answer.h0.a2"]
    assert fifo_mod.M_RETRIES.value == before + 2


def test_stale_reply_race_end_to_end(tmp_path, monkeypatch):
    """A delayed worker reply outlives the head's first attempt; the
    retry must get a FRESH reply while the stale one dies in attempt 0's
    own FIFO. With a shared FIFO name the late reply would land in the
    retry's read instead."""
    faults.reset()
    monkeypatch.setenv("DOS_FAULTS", "delay;wid=0;delay=3.0;times=1")
    # the stale reply finds attempt 0's reader dead: drop it fast
    # instead of stalling the serve loop for the default 30s
    monkeypatch.setenv("DOS_REPLY_DEADLINE_S", "0.3")
    s = FifoServer.__new__(FifoServer)
    s.wid = 0
    s.command_fifo = str(tmp_path / "w0.fifo")
    th = threading.Thread(target=s.serve_forever, daemon=True)
    th.start()
    for _ in range(100):
        if os.path.exists(s.command_fifo):
            break
        time.sleep(0.02)
    replies_before = server_mod.M_REPLIES.value
    dropped_before = server_mod.M_DROPPED.value
    try:
        req = Request(RuntimeConfig(), "/no/such/queryfile",
                      str(tmp_path / "answer.h0"), "-")
        # attempt 0 times out at 2s (server sleeping 3s); the retry's
        # request is read after the sleep and answered immediately
        row = send_with_retry(
            "localhost", req, s.command_fifo, timeout=2.0,
            policy=RetryPolicy(retries=1, base_s=0.05, jitter=0.0))
        # bare server answers FAIL (no engine), via the FRESH attempt:
        # exactly one reply delivered (to attempt 1's FIFO) and exactly
        # one dropped (the stale one, into attempt 0's dead FIFO)
        assert not row.ok
        deadline = time.monotonic() + 5
        while (server_mod.M_REPLIES.value == replies_before
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert server_mod.M_REPLIES.value == replies_before + 1
        assert server_mod.M_DROPPED.value == dropped_before + 1
    finally:
        server_mod.stop_server(s.command_fifo)
        th.join(timeout=15)


# ------------------------------------------------------------------- probe

def test_probe_live_server_returns_health(tmp_path):
    s = FifoServer.__new__(FifoServer)
    s.wid = 3
    s.command_fifo = str(tmp_path / "w3.fifo")
    th = threading.Thread(target=s.serve_forever, daemon=True)
    th.start()
    for _ in range(100):
        if os.path.exists(s.command_fifo):
            break
        time.sleep(0.02)
    try:
        st = probe("localhost", 3, command_fifo=s.command_fifo,
                   nfs=str(tmp_path), timeout=5.0)
        assert st is not None and st.ok and st.wid == 3
        assert st.uptime_s >= 0.0
    finally:
        server_mod.stop_server(s.command_fifo)
        th.join(timeout=10)
    # probe cleaned its answer FIFO
    assert not [p for p in os.listdir(tmp_path)
                if p.startswith("answer.ping.")]


def test_probe_dead_server_fails_fast_no_fifo(tmp_path):
    before = fifo_mod.M_PROBE_FAILURES.value
    t0 = time.monotonic()
    st = probe("localhost", 9,
               command_fifo=str(tmp_path / "absent.fifo"),
               nfs=str(tmp_path), timeout=3.0)
    assert st is None
    assert time.monotonic() - t0 < 3.0           # [ -p ] guard, no wait
    assert fifo_mod.M_PROBE_FAILURES.value == before + 1


def test_probe_crashed_server_stale_fifo_bounded(tmp_path):
    """A hard crash leaves the command FIFO with no reader: the probe's
    write-open must time out instead of wedging like the failure it is
    detecting."""
    stale = str(tmp_path / "crashed.fifo")
    os.mkfifo(stale)
    t0 = time.monotonic()
    st = probe("localhost", 4, command_fifo=stale, nfs=str(tmp_path),
               timeout=2.0)
    assert st is None
    assert time.monotonic() - t0 < 8.0


# ---------------------------------------------------------- circuit breaker

class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


def test_breaker_opens_after_threshold_and_cooldown_half_opens():
    clk = FakeClock()
    opened = resilience.M_OPENED.value
    rejected = resilience.M_REJECTED.value
    br = resilience.CircuitBreaker(("h", 0), threshold=3, cooldown_s=5.0,
                                   clock=clk)
    for _ in range(2):
        assert br.allow()
        br.record(False)
    assert br.state == resilience.CLOSED
    assert br.allow()
    br.record(False)                              # 3rd consecutive
    assert br.state == resilience.OPEN
    assert resilience.M_OPENED.value == opened + 1
    assert not br.allow()                         # short-circuited
    assert resilience.M_REJECTED.value == rejected + 1
    clk.t += 5.1                                  # cooldown fallback
    assert br.allow()                             # the half-open trial
    assert br.state == resilience.HALF_OPEN
    assert not br.allow()                         # one trial at a time
    br.record(True)
    assert br.state == resilience.CLOSED
    assert br.allow()


def test_breaker_failed_trial_reopens():
    clk = FakeClock()
    br = resilience.CircuitBreaker(("h", 1), threshold=1, cooldown_s=2.0,
                                   clock=clk)
    assert br.allow()
    br.record(False)
    assert br.state == resilience.OPEN
    clk.t += 2.1
    assert br.allow()
    br.record(False)
    assert br.state == resilience.OPEN            # back to OPEN
    assert not br.allow()


def test_registry_background_probe_half_opens_and_shuts_down():
    """An OPEN breaker is healed by the registry's background probe
    (named dos-probe-*, joined by shutdown — the leak check in conftest
    would fail otherwise)."""
    healthy = threading.Event()

    def probe_fn(key):
        return HealthStatus(ok=True) if healthy.is_set() else None

    reg = resilience.BreakerRegistry(threshold=1, cooldown_s=0.05,
                                     probe_fn=probe_fn, enabled=True)
    key = ("localhost", 2)
    assert reg.allow(key)
    reg.record(key, False)                        # -> OPEN, probe starts
    assert reg.get(key).state == resilience.OPEN
    time.sleep(0.2)
    assert reg.get(key).state == resilience.OPEN  # probes keep failing
    healthy.set()
    deadline = time.monotonic() + 5
    while (reg.get(key).state != resilience.HALF_OPEN
           and time.monotonic() < deadline):
        time.sleep(0.02)
    assert reg.get(key).state == resilience.HALF_OPEN
    assert reg.allow(key)                         # the trial
    reg.record(key, True)
    assert reg.get(key).state == resilience.CLOSED
    reg.shutdown()


def test_registry_env_knobs_and_disable(monkeypatch):
    monkeypatch.setenv("DOS_CIRCUIT_THRESHOLD", "7")
    monkeypatch.setenv("DOS_CIRCUIT_COOLDOWN_S", "0.5")
    reg = resilience.BreakerRegistry()
    assert reg.threshold == 7 and reg.cooldown_s == 0.5
    monkeypatch.setenv("DOS_CIRCUIT_DISABLE", "1")
    reg = resilience.BreakerRegistry()
    key = ("h", 0)
    for _ in range(20):
        reg.record(key, False)
        assert reg.allow(key)                     # disabled: always allow
    reg.shutdown()


# ------------------------------------------------- stop_server / cleanup

def test_stop_server_does_not_wedge_on_dead_server(tmp_path):
    """The satellite fix: a leftover FIFO with no reader used to hang
    the caller forever in a blocking open."""
    stale = str(tmp_path / "dead.fifo")
    os.mkfifo(stale)
    t0 = time.monotonic()
    assert stop_server(stale, deadline_s=0.3) is False
    assert time.monotonic() - t0 < 2.0


def test_stop_server_missing_fifo_returns_false(tmp_path):
    assert stop_server(str(tmp_path / "never-existed.fifo")) is False


def test_stop_server_delivers_to_live_server(tmp_path):
    s = FifoServer.__new__(FifoServer)
    s.wid = 0
    s.command_fifo = str(tmp_path / "live.fifo")
    th = threading.Thread(target=s.serve_forever, daemon=True)
    th.start()
    for _ in range(100):
        if os.path.exists(s.command_fifo):
            break
        time.sleep(0.02)
    assert stop_server(s.command_fifo) is True
    th.join(timeout=10)
    assert not th.is_alive()


def test_clean_stale_answer_fifos(tmp_path):
    os.mkfifo(str(tmp_path / "answer.host0"))
    os.mkfifo(str(tmp_path / "answer.host1.a2"))
    with open(str(tmp_path / "answer.notafifo"), "w") as f:
        f.write("regular file, not ours to delete")
    with open(str(tmp_path / "query.host0"), "w") as f:
        f.write("1\n0 1\n")
    before = fifo_mod.M_STALE_CLEANED.value
    assert clean_stale_answer_fifos(str(tmp_path)) == 2
    assert sorted(os.listdir(tmp_path)) == ["answer.notafifo",
                                            "query.host0"]
    assert fifo_mod.M_STALE_CLEANED.value == before + 2
    assert clean_stale_answer_fifos(str(tmp_path)) == 0
