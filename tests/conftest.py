"""Test environment: force JAX onto 8 virtual CPU devices.

This is the framework's no-cluster analog of the reference's ``-t`` smoke
mode (N× ``localhost`` workers, reference ``README.md:29``): a single host
pretending to be an 8-shard mesh, per SURVEY.md §4. Must run before anything
imports jax.
"""

import os

# hard override: the host environment pins jax to the real TPU (axon
# platform, forced by a sitecustomize hook that calls
# jax.config.update("jax_platforms", ...) at interpreter start, trumping the
# JAX_PLATFORMS env var). Re-override via jax.config before any backend
# initializes; tests always run on the virtual 8-device CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")
try:
    # newer jax spells the device-count override as a config option; older
    # versions only honor the XLA_FLAGS form already set above
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass
assert len(jax.devices()) == 8, jax.devices()

import numpy as np
import pytest

from distributed_oracle_search_tpu.data import synth_city_graph, synth_scenario


@pytest.fixture(scope="session")
def toy_graph():
    """8x6 city grid — small enough for O(N^2) golden oracles."""
    return synth_city_graph(8, 6, seed=7)


@pytest.fixture(scope="session")
def toy_queries(toy_graph):
    return synth_scenario(toy_graph.n, 64, seed=11)
