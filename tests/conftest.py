"""Test environment: force JAX onto 8 virtual CPU devices.

This is the framework's no-cluster analog of the reference's ``-t`` smoke
mode (N× ``localhost`` workers, reference ``README.md:29``): a single host
pretending to be an 8-shard mesh, per SURVEY.md §4. Must run before anything
imports jax.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np
import pytest

from distributed_oracle_search_tpu.data import synth_city_graph, synth_scenario


@pytest.fixture(scope="session")
def toy_graph():
    """8x6 city grid — small enough for O(N^2) golden oracles."""
    return synth_city_graph(8, 6, seed=7)


@pytest.fixture(scope="session")
def toy_queries(toy_graph):
    return synth_scenario(toy_graph.n, 64, seed=11)
