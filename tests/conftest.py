"""Test environment: force JAX onto 8 virtual CPU devices.

This is the framework's no-cluster analog of the reference's ``-t`` smoke
mode (N× ``localhost`` workers, reference ``README.md:29``): a single host
pretending to be an 8-shard mesh, per SURVEY.md §4. Must run before anything
imports jax.
"""

import os

# hard override: the host environment pins jax to the real TPU (axon
# platform, forced by a sitecustomize hook that calls
# jax.config.update("jax_platforms", ...) at interpreter start, trumping the
# JAX_PLATFORMS env var). Re-override via jax.config before any backend
# initializes; tests always run on the virtual 8-device CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
# arm the runtime lock-order detector for the whole suite: every
# threaded serving/replication/obs test doubles as a lock-order
# regression check (utils.locks witness graph; cycles raise at the
# acquire that would make deadlock possible)
os.environ.setdefault("DOS_LOCK_CHECK", "1")
# pin the walk-kernel knob for tier-1: the XLA walk is the reference
# path every existing suite runs on, and the Pallas-fused kernel is
# exercised EXPLICITLY by tests/test_pallas_walk.py in interpret mode
# (it opts in per test). A hard override — not setdefault — so a
# container env carrying DOS_WALK_KERNEL=pallas can neither slow the
# whole suite to interpret speed nor let the parity suite silently
# stop comparing the two kernels against each other.
os.environ["DOS_WALK_KERNEL"] = "xla"
# same rule for the resident-codec knob: raw residency is the reference
# path every existing suite pins bit-identity against, and compressed
# residency is exercised EXPLICITLY by tests/test_compressed.py (it
# opts in per test). A container env carrying DOS_CPD_RESIDENT=rle
# must not silently flip every engine in the suite.
os.environ["DOS_CPD_RESIDENT"] = "raw"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")
try:
    # newer jax spells the device-count override as a config option; older
    # versions only honor the XLA_FLAGS form already set above
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass
assert len(jax.devices()) == 8, jax.devices()

import glob
import stat
import threading
import time

import numpy as np
import pytest

from distributed_oracle_search_tpu.data import synth_city_graph, synth_scenario
from distributed_oracle_search_tpu.obs import metrics as obs_metrics
from distributed_oracle_search_tpu.testing import faults
from distributed_oracle_search_tpu.utils import locks as dos_locks


@pytest.fixture(scope="session", autouse=True)
def _no_lock_order_cycles():
    """The witness graph must stay acyclic across the WHOLE run: in
    warn mode (or if a raise was swallowed by a worker thread) the
    session still fails with the recorded violation list."""
    yield
    assert dos_locks.violations() == [], dos_locks.violations()


@pytest.fixture(scope="session")
def toy_graph():
    """8x6 city grid — small enough for O(N^2) golden oracles."""
    return synth_city_graph(8, 6, seed=7)


@pytest.fixture(scope="session")
def toy_queries(toy_graph):
    return synth_scenario(toy_graph.n, 64, seed=11)


def _shared_dir_fifos() -> set:
    """FIFOs in /tmp matching the transport's naming conventions — the
    default shared dir, where a leak would poison later runs."""
    out = set()
    for pat in ("/tmp/worker*.fifo", "/tmp/answer.*"):
        for p in glob.glob(pat):
            try:
                if stat.S_ISFIFO(os.stat(p).st_mode):
                    out.add(p)
            except OSError:
                continue
    return out


@pytest.fixture(autouse=True)
def _no_leaked_fault_tolerance_resources():
    """Every test must clean up after the fault-tolerance layer: no
    ``dos-*`` supervisor/probe thread still alive, the supervisor gauge
    back at zero (checked via a metrics snapshot), no new FIFO left in
    the shared /tmp dir, and no armed fault injector bleeding into the
    next test."""
    fifos_before = _shared_dir_fifos()
    threads_before = {t.name for t in threading.enumerate()
                      if t.name.startswith("dos-")}
    yield
    faults.reset()
    # daemon probe threads notice shutdown on their next wait tick —
    # allow a short grace before calling a thread leaked
    deadline = time.monotonic() + 3.0
    leaked = []
    while time.monotonic() < deadline:
        leaked = [t.name for t in threading.enumerate()
                  if t.name.startswith("dos-") and t.is_alive()
                  and t.name not in threads_before]
        if not leaked:
            break
        time.sleep(0.05)
    assert not leaked, f"leaked supervisor/probe threads: {leaked}"
    snap = obs_metrics.REGISTRY.snapshot()
    alive = snap["gauges"].get("supervisor_workers_alive", 0)
    assert alive == 0, f"supervisor gauge reports {alive} workers alive"
    fifos_after = _shared_dir_fifos()
    assert fifos_after <= fifos_before, (
        f"leaked FIFOs in /tmp: {sorted(fifos_after - fifos_before)}")
