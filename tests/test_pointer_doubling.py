"""Pointer-doubling cost tables + path extraction.

The O(log L) "long-context" machinery: doubled tables must agree exactly
with the sequential walk on free-flow AND diffed weights, and extracted
path prefixes must match the CPU oracle's walk node-for-node.
"""

import numpy as np
import pytest

from distributed_oracle_search_tpu.data import synth_diff
from distributed_oracle_search_tpu.models import (
    first_move_matrix, table_search_walk,
)
from distributed_oracle_search_tpu.models.cpd import CPDOracle
from distributed_oracle_search_tpu.ops import (
    DeviceGraph, doubled_tables, extract_paths, lookup_tables,
)
from distributed_oracle_search_tpu.ops.pointer_doubling import (
    unpack_tables,
)
from distributed_oracle_search_tpu.parallel import DistributionController
from distributed_oracle_search_tpu.parallel.mesh import make_mesh

import jax.numpy as jnp


@pytest.fixture(scope="module")
def setup(toy_graph):
    g = toy_graph
    fm = first_move_matrix(g, np.arange(g.n))
    dg = DeviceGraph.from_graph(g)
    return g, fm, dg


def test_doubled_tables_match_walk_free_flow(setup):
    g, fm, dg = setup
    targets = jnp.arange(g.n, dtype=jnp.int32)
    c, p, f = unpack_tables(*doubled_tables(
        dg, jnp.asarray(fm), targets,
        jnp.asarray(g.padded_weights(), jnp.int32)))
    c, p, f = map(np.asarray, (c, p, f))
    fm_of = lambda x, t: fm[t, x]  # noqa: E731
    for t in range(0, g.n, 7):
        for s in range(0, g.n, 5):
            wc, wp, wf, _ = table_search_walk(g, fm_of, s, t)
            assert c[t, s] == wc and p[t, s] == wp and f[t, s] == wf


def test_doubled_tables_match_walk_diffed(setup):
    g, fm, dg = setup
    w = g.weights_with_diff(synth_diff(g, frac=0.3, seed=9))
    targets = jnp.arange(g.n, dtype=jnp.int32)
    c, p, f = unpack_tables(*doubled_tables(
        dg, jnp.asarray(fm), targets,
        jnp.asarray(g.padded_weights(w), jnp.int32)))
    c = np.asarray(c)
    fm_of = lambda x, t: fm[t, x]  # noqa: E731
    for t in range(0, g.n, 6):
        for s in range(0, g.n, 4):
            wc, _, _, _ = table_search_walk(g, fm_of, s, t, w_query=w)
            assert c[t, s] == wc


def test_doubled_tables_padding_rows(setup):
    g, fm, dg = setup
    targets = jnp.asarray([0, -1, 2], jnp.int32)
    c, p, f = unpack_tables(*doubled_tables(
        dg, jnp.asarray(fm[[0, 0, 2]]), targets,
        jnp.asarray(g.padded_weights(), jnp.int32)))
    assert not np.asarray(f)[1].any()  # padding row unfinished


def test_lookup_tables_roundtrip(setup):
    g, fm, dg = setup
    targets = jnp.arange(g.n, dtype=jnp.int32)
    tables = doubled_tables(dg, jnp.asarray(fm), targets,
                            jnp.asarray(g.padded_weights(), jnp.int32))
    rows = jnp.asarray([3, 8], jnp.int32)
    s = jnp.asarray([1, 40], jnp.int32)
    c, p, f = lookup_tables(*tables, rows, s)
    assert np.asarray(f).all()
    assert np.asarray(c)[0] == np.asarray(tables[0])[3, 1]


def test_oracle_query_table_matches_query(toy_graph, toy_queries):
    """End-to-end sharded: prepared tables == walked answers, free-flow
    and diffed."""
    dc = DistributionController("tpu", None, 4, toy_graph.n)
    oracle = CPDOracle(toy_graph, dc, mesh=make_mesh(n_workers=4)).build()
    w = toy_graph.weights_with_diff(synth_diff(toy_graph, frac=0.2,
                                               seed=17))
    for w_query in (None, w):
        tables = oracle.prepare_weights(w_query)
        c1, p1, f1 = oracle.query(toy_queries, w_query=w_query)
        c2, p2, f2 = oracle.query_table(tables, toy_queries)
        assert (c1 == c2).all() and (p1 == p2).all() and (f1 == f2).all()
        assert f2.all()


def test_doubled_tables_multi_matches_singles(setup):
    """Fused multi-diff tables: cost plane d == a single-diff
    doubled_tables run on diff d; plen/finished shared."""
    from distributed_oracle_search_tpu.data import synth_diff
    from distributed_oracle_search_tpu.ops.pointer_doubling import (
        doubled_tables_multi, lookup_tables_multi,
    )

    g, fm, dg = setup
    targets = jnp.arange(g.n, dtype=jnp.int32)
    w_list = [None,
              g.weights_with_diff(synth_diff(g, frac=0.3, seed=41)),
              g.weights_with_diff(synth_diff(g, frac=0.5, seed=42))]
    w_pads = jnp.asarray(np.stack([
        g.padded_weights(g.w if w is None else w) for w in w_list]),
        jnp.int32)
    costs, pp = doubled_tables_multi(dg, jnp.asarray(fm), targets, w_pads)
    assert costs.shape == (g.n, g.n, 3)
    for di, w in enumerate(w_list):
        c1, p1 = doubled_tables(
            dg, jnp.asarray(fm), targets,
            jnp.asarray(g.padded_weights(w), jnp.int32))
        np.testing.assert_array_equal(np.asarray(costs[..., di]),
                                      np.asarray(c1))
        np.testing.assert_array_equal(np.asarray(pp), np.asarray(p1))
    # lookup agrees with the single-diff lookup per plane, incl. padding
    rows = jnp.asarray([3, 8, 0], jnp.int32)
    s = jnp.asarray([1, 40, 0], jnp.int32)
    valid = jnp.asarray([True, True, False])
    cm, pm, fmm = lookup_tables_multi(costs, pp, rows, s, valid)
    for di, w in enumerate(w_list):
        c1t = doubled_tables(
            dg, jnp.asarray(fm), targets,
            jnp.asarray(g.padded_weights(w), jnp.int32))
        c1, p1, f1 = lookup_tables(*c1t, rows, s, valid)
        np.testing.assert_array_equal(np.asarray(cm[di]), np.asarray(c1))
        np.testing.assert_array_equal(np.asarray(pm), np.asarray(p1))
        np.testing.assert_array_equal(np.asarray(fmm), np.asarray(f1))


def test_oracle_query_table_multi_matches_query_table(toy_graph,
                                                      toy_queries,
                                                      monkeypatch):
    """End-to-end sharded: fused multi-diff tables == per-diff prepared
    tables == the walk, with the budget gate scaling by D."""
    import pytest

    dc = DistributionController("tpu", None, 4, toy_graph.n)
    oracle = CPDOracle(toy_graph, dc, mesh=make_mesh(n_workers=4)).build()
    w = toy_graph.weights_with_diff(synth_diff(toy_graph, frac=0.2,
                                               seed=18))
    w_list = [None, w]
    tables = oracle.prepare_weights_multi(w_list, chunk=16)
    cm, pm, fmm = oracle.query_table_multi(tables, toy_queries)
    assert fmm.all()
    for di, wq in enumerate(w_list):
        c1, p1, f1 = oracle.query(toy_queries, w_query=wq)
        assert (cm[di] == c1).all() and (pm == p1).all()
    with pytest.raises(ValueError, match="at least one"):
        oracle.prepare_weights_multi([])
    monkeypatch.setenv("DOS_TABLE_BUDGET_GB", "0.000001")
    with pytest.raises(ValueError, match="fused tables"):
        oracle.prepare_weights_multi(w_list)


def test_extract_paths_match_cpu_walk(setup):
    g, fm, dg = setup
    rng = np.random.default_rng(23)
    s = rng.integers(0, g.n, 16)
    t = rng.integers(0, g.n, 16)
    k = 10
    nodes, plen = extract_paths(
        dg, jnp.asarray(fm), jnp.asarray(t, jnp.int32),
        jnp.asarray(s, jnp.int32), jnp.asarray(t, jnp.int32), k)
    nodes, plen = np.asarray(nodes), np.asarray(plen)
    fm_of = lambda x, tt: fm[tt, x]  # noqa: E731
    for q in range(16):
        _, wp, _, path = table_search_walk(g, fm_of, int(s[q]), int(t[q]),
                                           k_moves=k)
        assert plen[q] == wp
        assert list(nodes[q][:wp + 1]) == path[:wp + 1]
        # after the walk ends, the last node repeats
        assert (nodes[q][wp:] == nodes[q][wp]).all()


def test_prepare_weights_budget_gate(toy_graph, monkeypatch):
    """Oversized table requests must refuse with the math, not fault."""
    dc = DistributionController("tpu", None, 4, toy_graph.n)
    oracle = CPDOracle(toy_graph, dc, mesh=make_mesh(n_workers=4)).build()
    monkeypatch.setattr(CPDOracle, "TABLE_BUDGET", 10)   # 10 bytes
    with pytest.raises(ValueError, match="GB/device budget"):
        oracle.prepare_weights()
    assert oracle.table_memory_bytes() > 10
