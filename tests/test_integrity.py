"""Answer-integrity plane: resident-table scrubbing, sampled
dual-execution audit, answer fingerprints, and divergence quarantine.

Non-slow: fingerprint byte-layout stability and the verify points
(results sidecar, in-process ``_fp_guard``, cache hit re-check), the
scrubber's detect→heal→rebind mechanics (resident rot, disk rot,
budgeted cursors, the ``corrupt-resident`` fault point end to end),
the audit sampler's deterministic cadence and lane choice (replica /
reference / recompute, queue-full drop), the ``DivergenceWatch`` →
quarantine → scrub-now → readmit control arm (executed and dry-run),
the ``dos-make-cpds --scrub`` cadence exit codes, and the obs/bench
key pins. The full corruption chaos drill (both fault points under a
live ControlDaemon) stays behind ``slow``.
"""

import os
import threading
import time
import types

import numpy as np
import pytest

from distributed_oracle_search_tpu.cli import make_cpds
from distributed_oracle_search_tpu.control.daemon import (
    ControlDaemon, maybe_daemon,
)
from distributed_oracle_search_tpu.control.actuators import Actuators
from distributed_oracle_search_tpu.control.config import ControlConfig
from distributed_oracle_search_tpu.control.policy import DivergenceWatch
from distributed_oracle_search_tpu.data import (
    ensure_synth_dataset, read_scen,
)
from distributed_oracle_search_tpu.data.graph import Graph
from distributed_oracle_search_tpu.integrity import IntegrityConfig
from distributed_oracle_search_tpu.integrity.audit import (
    AnswerAuditor, choose_audit_lane, make_reference_fn,
)
from distributed_oracle_search_tpu.integrity.fingerprint import (
    FingerprintError, answer_fingerprint, value_fingerprint,
)
from distributed_oracle_search_tpu.integrity.scrub import (
    TableScrubber, _rebind, scrub_engine_table,
)
from distributed_oracle_search_tpu.models.cpd import (
    build_worker_shard, shard_block_name, write_index_manifest,
)
from distributed_oracle_search_tpu.obs import fleet as obs_fleet
from distributed_oracle_search_tpu.obs import metrics as obs_metrics
from distributed_oracle_search_tpu.obs import recorder as obs_recorder
from distributed_oracle_search_tpu.parallel.partition import (
    DistributionController,
)
from distributed_oracle_search_tpu.serving import (
    EngineDispatcher, HedgeConfig, ResultCache, ServeConfig,
    ServingFrontend,
)
from distributed_oracle_search_tpu.serving.dispatch import (
    DispatchError, _fp_guard,
)
from distributed_oracle_search_tpu.testing import faults
from distributed_oracle_search_tpu.transport.resilience import (
    BreakerRegistry,
)
from distributed_oracle_search_tpu.transport.wire import (
    RuntimeConfig, read_results_file, write_results_file,
)
from distributed_oracle_search_tpu.utils.config import ClusterConfig
from distributed_oracle_search_tpu.worker.build import main as build_main
from distributed_oracle_search_tpu.worker.engine import ShardEngine

pytestmark = pytest.mark.integrity

N_WORKERS = 4
BLOCK_SIZE = 4


def _counter(name: str) -> float:
    return obs_metrics.REGISTRY.snapshot()["counters"].get(name, 0)


def _build_all(graph, dc, outdir):
    for wid in range(dc.maxworker):
        build_worker_shard(graph, dc, wid, outdir)
    write_index_manifest(outdir, dc)


@pytest.fixture()
def toy_dc(toy_graph):
    return DistributionController("tpu", N_WORKERS, N_WORKERS,
                                  toy_graph.n, block_size=BLOCK_SIZE)


@pytest.fixture()
def toy_engine(tmp_path, toy_graph, toy_dc):
    outdir = str(tmp_path / "idx")
    _build_all(toy_graph, toy_dc, outdir)
    return ShardEngine(toy_graph, toy_dc, 0, outdir), outdir


def _rot_resident(eng):
    """Flip row 0 of the RESIDENT table only (disk stays clean) —
    exactly what the ``corrupt-resident`` fault point does post-load."""
    clean = np.array(np.asarray(eng.fm), np.int8, copy=True)
    bad = clean.copy()
    bad[0, :] = np.where(bad[0, :] <= 0, 1, 0)
    eng.fm = bad
    return clean


# ---------------------------------------------------------- fingerprints

def test_answer_fingerprint_dtype_stable():
    """The canonical byte layout is dtype- and container-independent:
    every transport fingerprints the same bytes."""
    fp = answer_fingerprint([3, 0, 7], [2, 0, 4], [True, False, True])
    assert fp == answer_fingerprint(
        np.array([3, 0, 7], np.int32), np.array([2, 0, 4], np.int64),
        np.array([1, 0, 1], np.uint8))
    assert fp != answer_fingerprint([3, 0, 7], [2, 0, 4],
                                    [True, False, False])
    assert value_fingerprint((3, 2, True)) == answer_fingerprint(
        [3], [2], [True])


def test_results_file_fingerprint_round_trip(tmp_path):
    path = str(tmp_path / "results")
    cost = np.array([5, 9], np.int64)
    plen = np.array([2, 3], np.int64)
    fin = np.array([True, True])
    write_results_file(path, cost, plen, fin,
                       fp=answer_fingerprint(cost, plen, fin))
    c, p, f = read_results_file(path)
    np.testing.assert_array_equal(c, cost)
    np.testing.assert_array_equal(p, plen)
    # a tampered answer row fails typed, and books the counter
    lines = open(path).read().splitlines()
    lines[1] = "6 2 1"                       # cost 5 -> 6
    open(path, "w").write("\n".join(lines) + "\n")
    m0 = _counter("answer_fp_mismatch_total")
    with pytest.raises(FingerprintError):
        read_results_file(path)
    assert _counter("answer_fp_mismatch_total") - m0 == 1


def test_results_file_without_fp_stays_legacy(tmp_path):
    """No ``fp=`` token -> no verification: a tampered legacy sidecar
    still parses (pre-integrity behavior, byte for byte)."""
    path = str(tmp_path / "results")
    write_results_file(path, [5], [2], [True])
    assert "fp=" not in open(path).readline()
    lines = open(path).read().splitlines()
    lines[1] = "6 2 1"
    open(path, "w").write("\n".join(lines) + "\n")
    c, _, _ = read_results_file(path)
    assert c.tolist() == [6]


def test_fp_guard_catches_injected_corruption(monkeypatch):
    monkeypatch.setenv("DOS_FAULTS", "corrupt-answer;times=1")
    faults.reset()
    cost = np.arange(4, dtype=np.int64)
    plen = np.ones(4, np.int64)
    fin = np.ones(4, bool)
    m0 = _counter("answer_fp_mismatch_total")
    with pytest.raises(DispatchError, match="fingerprint"):
        _fp_guard(0, cost, plen, fin, RuntimeConfig(answer_fp=True))
    assert _counter("answer_fp_mismatch_total") - m0 == 1
    # the injection is consumed: the retry lane verifies clean
    c2, p2, f2 = _fp_guard(0, cost, plen, fin,
                           RuntimeConfig(answer_fp=True))
    np.testing.assert_array_equal(c2, cost)


def test_fp_guard_off_is_identity_and_consumes_nothing(monkeypatch):
    monkeypatch.setenv("DOS_FAULTS", "corrupt-answer;times=1")
    faults.reset()
    cost = np.arange(3, dtype=np.int64)
    plen = np.ones(3, np.int64)
    fin = np.ones(3, bool)
    out = _fp_guard(0, cost, plen, fin, RuntimeConfig())
    assert out[0] is cost and out[1] is plen and out[2] is fin
    # the armed fault was NOT consumed by the disabled guard
    assert faults.inject("corrupt-answer", 0) is not None


# ---------------------------------------------------------- cache checks

def test_cache_fingerprint_drops_rotted_entry():
    cache = ResultCache(1 << 20, fingerprint=True)
    key = (3, 9, "-", (), 0, 0)
    cache.put(key, (7, 2, True))
    assert cache.get(key) == (7, 2, True)
    m0 = _counter("cache_fingerprint_mismatch_total")
    with cache._lock:
        cache._od[key] = (8, 2, True)       # in-memory rot
    assert cache.get(key) is None           # dropped, booked as a miss
    assert _counter("cache_fingerprint_mismatch_total") - m0 == 1
    assert cache.fp_mismatches == 1
    assert len(cache) == 0                  # the entry is gone
    # the recompute path re-populates and hits again
    cache.put(key, (7, 2, True))
    assert cache.get(key) == (7, 2, True)


def test_cache_without_fingerprint_stays_legacy():
    cache = ResultCache(1 << 20)
    key = (3, 9, "-", (), 0, 0)
    cache.put(key, (7, 2, True))
    with cache._lock:
        cache._od[key] = (8, 2, True)
    assert cache.get(key) == (8, 2, True)   # served as-is (no check)
    assert cache.fp_mismatches == 0


# -------------------------------------------------------------- scrubber

def test_scrub_clean_pass_checks_everything(toy_engine):
    eng, outdir = toy_engine
    report, cur = scrub_engine_table(eng, outdir, eng.fm, None)
    assert cur == (0, 0)
    assert report["checked"] == 3           # 12 owned rows / block 4
    assert not report["corrupt"] and not report["healed"]
    assert not report["rebound"] and not report["errors"]


def test_scrub_detects_resident_rot_and_rebinds(tmp_path, toy_engine):
    eng, outdir = toy_engine
    clean = _rot_resident(eng)
    rec = obs_recorder.FlightRecorder(str(tmp_path / "tape"),
                                      flush_every=1)
    obs_recorder.set_recorder(rec)
    c0 = _counter("scrub_blocks_corrupt_total")
    try:
        report, cur = scrub_engine_table(eng, outdir, eng.fm, None)
    finally:
        obs_recorder.set_recorder(None)
        rec.close()
    assert report["corrupt"] == [shard_block_name(0, 0, 0)]
    assert report["rebound"] and cur == (0, 0)
    # the rebind republished the verified disk truth
    np.testing.assert_array_equal(np.asarray(eng.fm, np.int8), clean)
    assert _counter("scrub_blocks_corrupt_total") - c0 == 1
    events = [r for r in obs_recorder.replay(str(tmp_path / "tape"))
              if r.get("rec") == "event" and r["kind"] == "scrub_corrupt"]
    assert len(events) == 1
    assert events[0]["shard"] == 0
    assert events[0]["file"] == shard_block_name(0, 0, 0)


def test_scrub_heals_disk_rot_resident_stays_authoritative(toy_engine):
    eng, outdir = toy_engine
    resident = np.array(np.asarray(eng.fm), np.int8, copy=True)
    victim = shard_block_name(0, 1, 0)
    with open(os.path.join(outdir, victim), "r+b") as f:
        f.seek(130)
        f.write(b"\x7f" * 4)
    report, _ = scrub_engine_table(eng, outdir, eng.fm, None)
    assert report["healed"] == [victim]
    assert not report["corrupt"] and not report["rebound"]
    np.testing.assert_array_equal(np.asarray(eng.fm, np.int8), resident)
    # the healed file verifies on the next pass
    report2, _ = scrub_engine_table(eng, outdir, eng.fm, None)
    assert report2["checked"] == 3 and not report2["healed"]
    assert not report2["errors"]


def test_scrub_budget_cursor_resumes_and_wraps(toy_engine):
    eng, outdir = toy_engine
    report, cur = scrub_engine_table(eng, outdir, eng.fm, None,
                                     budget=1)
    assert report["checked"] == 1 and cur == (1, BLOCK_SIZE)
    report, cur = scrub_engine_table(eng, outdir, eng.fm, None,
                                     budget=1, cursor=cur)
    assert report["checked"] == 1 and cur == (2, 2 * BLOCK_SIZE)
    report, cur = scrub_engine_table(eng, outdir, eng.fm, None,
                                     budget=1, cursor=cur)
    assert report["checked"] == 1 and cur == (0, 0)     # wrapped


def test_corrupt_resident_fault_point_end_to_end(tmp_path, toy_graph,
                                                 toy_dc, monkeypatch):
    """The ``corrupt-resident`` fault point plants rot the digest-
    verified load cannot see; the scrubber is the ONLY defense that
    catches it — and after rebind the engine answers match a clean
    engine bit for bit."""
    outdir = str(tmp_path / "idx")
    _build_all(toy_graph, toy_dc, outdir)
    clean_eng = ShardEngine(toy_graph, toy_dc, 0, outdir)
    monkeypatch.setenv("DOS_FAULTS", "corrupt-resident;wid=0;times=1")
    faults.reset()
    eng = ShardEngine(toy_graph, toy_dc, 0, outdir)
    owned = toy_dc.owned(0)
    queries = np.array([[int(owned[-1]), int(owned[0])],
                        [0, int(owned[0])]], np.int64)
    want = clean_eng.answer(queries, RuntimeConfig())
    got_bad = eng.answer(queries, RuntimeConfig())
    assert (np.asarray(got_bad[0]) != np.asarray(want[0])).any()
    scr = TableScrubber(lambda: [eng, clean_eng], interval_s=3600.0)
    reports = scr.run_pass()
    assert scr.corrupt_blocks == 1          # only the rotted engine
    assert sum(r["rebound"] for r in reports) == 1
    got = eng.answer(queries, RuntimeConfig())
    for a, b in zip(got[:3], want[:3]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_table_scrubber_thread_scrub_now_and_statusz(toy_engine):
    eng, _ = toy_engine
    p0 = _counter("scrub_passes_total")
    scr = TableScrubber(lambda: [eng], interval_s=3600.0)
    scr.start()
    try:
        scr.scrub_now()                     # wake well before interval
        deadline = time.monotonic() + 10
        while scr.passes == 0 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert scr.passes >= 1
        st = scr.statusz()
        assert st["corrupt_blocks"] == 0 and st["healed_blocks"] == 0
        assert st["last"][0]["shard"] == 0
        assert st["last"][0]["checked"] == 3
    finally:
        scr.stop()
    assert _counter("scrub_passes_total") - p0 >= 1
    assert "dos-scrub" not in [t.name for t in threading.enumerate()
                               if t.is_alive()]


def test_scrubber_skips_astar_and_unloaded_engines(toy_engine):
    eng, _ = toy_engine
    no_fm = types.SimpleNamespace(alg="table-search", fm=None)
    astar = types.SimpleNamespace(alg="astar", fm=object())
    scr = TableScrubber(lambda: [no_fm, astar, eng], interval_s=3600.0)
    reports = scr.run_pass()
    assert [r["shard"] for r in reports] == [0]


def test_rebind_loses_to_newer_promotion(toy_engine):
    """A rebind racing a newer promotion must not clobber it: the
    epoch check under ``_promote_lock`` refuses the stale swap."""
    eng, _ = toy_engine
    table = np.asarray(eng.fm)
    eng._fm_promoted = (7, table)
    assert not _rebind(eng, 5)              # 5 lost the race to 7
    assert eng._fm_promoted == (7, table)


# ----------------------------------------------------------------- audit

class _EchoDispatcher:
    """Audit-lane stub: records the call, returns what the maker says
    (defaults to echoing cost = |s - t| like the gateway stubs)."""

    def __init__(self, fn=None):
        self.fn = fn
        self.calls = []

    def answer_batch(self, wid, queries, rconf, diff, via=None):
        q = np.asarray(queries)
        self.calls.append((int(wid), via, rconf, diff))
        if self.fn is not None:
            return self.fn(q)
        return (np.abs(q[:, 0] - q[:, 1]).astype(np.int64),
                np.ones(len(q), np.int64), np.ones(len(q), bool))


def _served(q):
    q = np.asarray(q)
    return (np.abs(q[:, 0] - q[:, 1]).astype(np.int64),
            np.ones(len(q), np.int64), np.ones(len(q), bool))


def test_choose_audit_lane_preference_order():
    lane, why = choose_audit_lane((0, 1), 0, 8, have_reference=True,
                                  max_reference=64)
    assert lane == "replica" and "candidate 1" in why
    lane, why = choose_audit_lane((0,), 0, 8, have_reference=True,
                                  max_reference=64)
    assert lane == "reference"
    lane, why = choose_audit_lane((0,), 0, 100, have_reference=True,
                                  max_reference=64)
    assert lane == "recompute"
    lane, why = choose_audit_lane((0,), 0, 8, have_reference=False,
                                  max_reference=64)
    assert lane == "recompute" and "no reference fn" in why


def test_audit_sampling_is_deterministic():
    """DOS_AUDIT_RATE=250 audits EXACTLY every 4th eligible batch (an
    accumulator, no RNG); deadline-bounded batches are never sampled."""
    aud = AnswerAuditor(_EchoDispatcher(), 250)
    try:
        q = np.array([[3, 9]], np.int64)
        c, p, f = _served(q)
        got = [aud.maybe_submit(0, 0, (0,), q, RuntimeConfig(), "-",
                                c, p, f) for _ in range(8)]
        assert got == [False, False, False, True] * 2
        # config.time != 0 -> never eligible, accumulator untouched
        assert not aud.maybe_submit(0, 0, (0,), q,
                                    RuntimeConfig(time=5), "-", c, p, f)
    finally:
        aud.stop()
    assert "dos-audit" not in [t.name for t in threading.enumerate()
                               if t.is_alive()]


def test_audit_rate_zero_never_starts_a_thread():
    aud = AnswerAuditor(_EchoDispatcher(), 0)
    q = np.array([[3, 9]], np.int64)
    c, p, f = _served(q)
    assert not aud.maybe_submit(0, 0, (0,), q, RuntimeConfig(), "-",
                                c, p, f)
    assert "dos-audit" not in [t.name for t in threading.enumerate()
                               if t.is_alive()]
    aud.stop()                              # harmless no-op


def test_audit_replica_lane_detects_divergence(tmp_path):
    """The replica lane disagrees with the served answers: the
    divergence books the counter, the per-shard tally, and a recorder
    event carrying the lane-choice provenance."""
    disp = _EchoDispatcher(fn=lambda q: (
        np.abs(q[:, 0] - q[:, 1]).astype(np.int64) + 1,    # diverges
        np.ones(len(q), np.int64), np.ones(len(q), bool)))
    rec = obs_recorder.FlightRecorder(str(tmp_path / "tape"),
                                      flush_every=1)
    obs_recorder.set_recorder(rec)
    d0 = _counter("audit_divergence_total")
    a0 = _counter("audit_batches_total")
    aud = AnswerAuditor(disp, 1000)
    try:
        q = np.array([[3, 9], [1, 8]], np.int64)
        c, p, f = _served(q)
        assert aud.maybe_submit(0, 0, (0, 1), q, RuntimeConfig(), "-",
                                c, p, f)
        deadline = time.monotonic() + 10
        while aud.audited == 0 and time.monotonic() < deadline:
            time.sleep(0.02)
    finally:
        aud.stop()
        obs_recorder.set_recorder(None)
        rec.close()
    assert _counter("audit_batches_total") - a0 == 1
    assert _counter("audit_divergence_total") - d0 == 1
    assert aud.snapshot() == {0: 1}
    st = aud.statusz()
    assert st["audited"] == 1 and st["divergent"] == {"0": 1}
    # the lane went to the replica, uncached (no L2 self-echo)
    wid, via, rconf, _ = disp.calls[0]
    assert (wid, via) == (0, 1) and rconf.no_cache
    events = [r for r in obs_recorder.replay(str(tmp_path / "tape"))
              if r.get("rec") == "event"
              and r["kind"] == "audit_divergence"]
    assert len(events) == 1
    assert events[0]["lane"] == "replica"
    assert events[0]["mismatches"] == 2 and events[0]["nq"] == 2


def test_audit_reference_and_recompute_lanes():
    disp = _EchoDispatcher()
    ref_calls = []

    def ref_fn(queries, config, diff):
        ref_calls.append(len(queries))
        return _served(queries)

    aud = AnswerAuditor(disp, 1000, reference_fn=ref_fn,
                        max_reference=2)
    try:
        # small single-candidate batch -> the CPU reference oracle
        q = np.array([[3, 9]], np.int64)
        c, p, f = _served(q)
        assert aud.maybe_submit(0, 0, (0,), q, RuntimeConfig(), "-",
                                c, p, f)
        # big single-candidate batch -> uncached recompute on via
        q2 = np.array([[3, 9], [1, 8], [2, 7]], np.int64)
        c2, p2, f2 = _served(q2)
        assert aud.maybe_submit(0, 0, (0,), q2, RuntimeConfig(), "-",
                                c2, p2, f2)
        deadline = time.monotonic() + 10
        while aud.audited < 2 and time.monotonic() < deadline:
            time.sleep(0.02)
    finally:
        aud.stop()
    assert ref_calls == [1]
    assert len(disp.calls) == 1             # only the recompute lane
    wid, via, rconf, _ = disp.calls[0]
    assert (wid, via) == (0, 0) and rconf.no_cache
    assert aud.snapshot() == {}             # both lanes agreed


def test_audit_queue_full_drops_never_blocks():
    release = threading.Event()

    def blocked(q):
        release.wait(30.0)
        return _served(q)

    aud = AnswerAuditor(_EchoDispatcher(fn=blocked), 1000, queue_max=1)
    try:
        q = np.array([[3, 9]], np.int64)
        c, p, f = _served(q)
        assert aud.maybe_submit(0, 0, (0, 1), q, RuntimeConfig(), "-",
                                c, p, f)
        # wait until the worker picked job 1 up and is blocked in it
        deadline = time.monotonic() + 10
        while aud._q.qsize() and time.monotonic() < deadline:
            time.sleep(0.02)
        assert aud.maybe_submit(0, 0, (0, 1), q, RuntimeConfig(), "-",
                                c, p, f)               # fills the queue
        d0 = _counter("audit_dropped_total")
        t0 = time.monotonic()
        assert not aud.maybe_submit(0, 0, (0, 1), q, RuntimeConfig(),
                                    "-", c, p, f)      # dropped
        assert time.monotonic() - t0 < 1.0             # no backpressure
        assert _counter("audit_dropped_total") - d0 == 1
        assert aud.dropped == 1
    finally:
        release.set()
        aud.stop()


def test_reference_oracle_matches_engine(toy_engine, toy_graph,
                                         toy_dc):
    eng, _ = toy_engine
    owned = toy_dc.owned(0)
    queries = np.array([[0, int(owned[0])],
                        [int(owned[-1]), int(owned[1])],
                        [int(owned[0]), int(owned[0])]], np.int64)
    ref = make_reference_fn(toy_graph)
    c, p, f = ref(queries, RuntimeConfig(), "-")
    want = eng.answer(queries, RuntimeConfig())
    np.testing.assert_array_equal(c, np.asarray(want[0]))
    np.testing.assert_array_equal(p, np.asarray(want[1]))
    np.testing.assert_array_equal(f, np.asarray(want[2]))


# ---------------------------------------------------------------- config

def test_integrity_config_defaults_off(monkeypatch):
    for k in ("DOS_SCRUB_INTERVAL_S", "DOS_SCRUB_BLOCKS_PER_PASS",
              "DOS_AUDIT_RATE", "DOS_AUDIT_MAX_REFERENCE",
              "DOS_ANSWER_FP"):
        monkeypatch.delenv(k, raising=False)
    cfg = IntegrityConfig.from_env()
    assert cfg == IntegrityConfig()
    assert not cfg.any_enabled


def test_integrity_config_from_env_and_degrade(monkeypatch):
    monkeypatch.setenv("DOS_SCRUB_INTERVAL_S", "30")
    monkeypatch.setenv("DOS_AUDIT_RATE", "10")
    monkeypatch.setenv("DOS_ANSWER_FP", "1")
    cfg = IntegrityConfig.from_env()
    assert cfg.scrub_interval_s == 30.0 and cfg.audit_rate == 10
    assert cfg.answer_fp and cfg.any_enabled
    # an impossible combination degrades to ALL defaults, not a crash
    monkeypatch.setenv("DOS_AUDIT_RATE", "2000")
    assert IntegrityConfig.from_env() == IntegrityConfig()
    with pytest.raises(ValueError):
        IntegrityConfig(audit_rate=-1).validate()


# ---------------------------------------------------- divergence control

def _sig(div):
    return types.SimpleNamespace(audit_divergent=dict(div))


def test_divergence_watch_acts_on_deltas_with_cooldown():
    w = DivergenceWatch(cooldown_s=10.0)
    out = w.decide(_sig({0: 1}), 100.0)
    assert [(d[0], d[1]) for d in out] == [("divergence_quarantine", 0)]
    assert "1 audit divergence" in out[0][2]
    # same cumulative count: no fresh evidence, no decision
    assert w.decide(_sig({0: 1}), 101.0) == []
    # fresh divergence mid-cooldown is NOT swallowed: _seen does not
    # advance, so it re-fires once the cooldown opens
    assert w.decide(_sig({0: 3}), 105.0) == []
    out = w.decide(_sig({0: 3}), 111.0)
    assert len(out) == 1 and out[0][1] == 0
    assert "2 audit divergence(s) (3 cumulative)" in out[0][2]


def _icfg(**kw):
    kw.setdefault("enabled", True)
    kw.setdefault("hold_ticks", 1)
    kw.setdefault("cooldown_s", 0.0)
    kw.setdefault("clean_probes", 1)
    return ControlConfig(**kw)


class _StubAuditor:
    def __init__(self):
        self.div = {}

    def snapshot(self):
        return dict(self.div)


def test_daemon_divergence_quarantine_scrub_then_readmit(tmp_path):
    """The control arm end to end: an audit divergence force-opens the
    shard's breaker, triggers scrub-now, and the shard earns its way
    back through the SAME probation loop — causal chain on tape."""
    rec = obs_recorder.FlightRecorder(str(tmp_path / "tape"),
                                      flush_every=1)
    obs_recorder.set_recorder(rec)
    reg = BreakerRegistry(threshold=3, cooldown_s=600.0, enabled=True)
    aud = _StubAuditor()
    scrubbed = []
    probe_ok = {"v": False}
    d = ControlDaemon(_icfg(), registry=reg, breaker_key=lambda w: w,
                      integrity=aud, scrub_fn=scrubbed.append,
                      probe_fn=lambda w: probe_ok["v"])
    q0 = _counter("control_divergence_quarantines_total")
    try:
        d.tick(now=100.0)
        assert d.quarantine.quarantined() == []        # nothing yet
        aud.div[1] = 1
        d.tick(now=101.0)
        assert d.quarantine.quarantined() == [1]
        assert not reg.allow(1)                        # routed around
        assert scrubbed == [1]                         # scrub-now fired
        assert (_counter("control_divergence_quarantines_total")
                - q0 == 1)
        d.tick(now=102.0)                              # probe fails
        assert d.quarantine.quarantined() == [1]
        probe_ok["v"] = True
        d.tick(now=103.0)                              # clean probe
        assert d.quarantine.quarantined() == []
        assert reg.allow(1)                            # released
    finally:
        reg.shutdown()
        obs_recorder.set_recorder(None)
        rec.close()
    kinds = [r["kind"] for r in obs_recorder.replay(str(tmp_path / "tape"))
             if r.get("rec") == "event"]
    assert kinds.index("control_divergence_quarantine") \
        < kinds.index("control_readmit")


def test_daemon_divergence_dry_run_books_without_acting():
    reg = BreakerRegistry(threshold=3, cooldown_s=600.0, enabled=True)
    aud = _StubAuditor()
    scrubbed = []
    d = ControlDaemon(_icfg(dry_run=True), registry=reg,
                      breaker_key=lambda w: w, integrity=aud,
                      scrub_fn=scrubbed.append,
                      probe_fn=lambda w: True)
    q0 = _counter("control_divergence_quarantines_total")
    try:
        aud.div[0] = 2
        d.tick(now=100.0)
        assert d.quarantine.quarantined() == []        # never entered
        assert reg.allow(0) and scrubbed == []         # nothing acted
        assert (_counter("control_divergence_quarantines_total")
                - q0 == 0)
        assert d.last_action.startswith(
            "divergence_quarantine(dry-run)")
    finally:
        reg.shutdown()


def test_actuator_divergence_quarantine_wiring():
    with pytest.raises(RuntimeError, match="registry"):
        Actuators().divergence_quarantine(0, "why")
    reg = BreakerRegistry(threshold=3, cooldown_s=600.0, enabled=True)
    try:
        def bad_scrub(shard):
            raise RuntimeError("scrubber wedged")

        act = Actuators(registry=reg, scrub_fn=bad_scrub)
        act.divergence_quarantine(2, "audit said so")
        assert not reg.allow(2)        # the breaker pin survived the
    finally:                           # scrub hiccup (best-effort half)
        reg.shutdown()


def test_maybe_daemon_wires_integrity_providers(monkeypatch):
    aud = _StubAuditor()
    fn = lambda shard: None  # noqa: E731
    monkeypatch.delenv("DOS_CONTROL", raising=False)
    assert maybe_daemon(integrity=aud, scrub_fn=fn) is None
    monkeypatch.setenv("DOS_CONTROL", "1")
    monkeypatch.setenv("DOS_CONTROL_INTERVAL_S", "60")
    d = maybe_daemon(integrity=aud, scrub_fn=fn)
    try:
        assert d is not None
        assert d.signals.integrity is aud
        assert d.actuators.scrub_fn is fn
    finally:
        d.stop()


def test_signal_reader_degrades_on_broken_auditor():
    class _Broken:
        def snapshot(self):
            raise RuntimeError("boom")

    d = ControlDaemon(_icfg(), integrity=_Broken(),
                      probe_fn=lambda w: True)
    d.tick(now=100.0)                       # reads degrade, no crash
    assert d.quarantine.quarantined() == []


# ------------------------------------------------- dos-make-cpds --scrub

def test_run_scrub_keeps_worst_exit_code(monkeypatch):
    seen = []
    seq = [4, 0, 0]
    monkeypatch.setattr(make_cpds, "run_verify",
                        lambda conf: seen.append(1) or seq.pop(0))
    args = types.SimpleNamespace(scrub_passes=3, scrub_interval=0.0)
    assert make_cpds.run_scrub(None, args) == 4     # rot seen once is
    assert len(seen) == 3                           # rot, healed or not


def test_make_cpds_scrub_exit_codes(tmp_path, toy_graph, toy_dc):
    outdir = str(tmp_path / "idx")
    _build_all(toy_graph, toy_dc, outdir)
    # run_verify counts nodes off the xy file
    toy_xy = str(tmp_path / "toy.xy")
    from distributed_oracle_search_tpu.data.formats import write_xy
    write_xy(toy_xy, toy_graph.xs, toy_graph.ys, toy_graph.src,
             toy_graph.dst, toy_graph.w)
    conf = ClusterConfig(
        workers=["localhost"] * N_WORKERS, partmethod="tpu",
        partkey=N_WORKERS, outdir=outdir, xy_file=toy_xy,
        nfs=str(tmp_path),
    ).validate()
    args = types.SimpleNamespace(scrub_passes=1, scrub_interval=0.0)
    assert make_cpds.run_scrub(conf, args) == 0         # clean
    victim = os.path.join(outdir, shard_block_name(1, 0, 0))
    os.unlink(victim)
    assert make_cpds.run_scrub(conf, args) == 3         # degraded
    open(os.path.join(outdir, "index.json"), "w").write("{")
    assert make_cpds.run_scrub(conf, args) == 4         # corrupt


def test_make_cpds_scrub_args_parse():
    from distributed_oracle_search_tpu.cli.args import parse_args
    args = parse_args([], prog="make_cpds")
    assert args.scrub is False
    assert args.scrub_interval == 60.0 and args.scrub_passes == 1
    args = parse_args(["--scrub", "--scrub-interval", "0.5",
                       "--scrub-passes", "0"], prog="make_cpds")
    assert args.scrub and args.scrub_passes == 0
    assert args.scrub_interval == 0.5


# ------------------------------------------------------------- obs pins

def test_fault_points_include_corruption_pair():
    assert "corrupt-resident" in faults.POINTS
    assert "corrupt-answer" in faults.POINTS
    rules = faults.parse_faults(
        "corrupt-resident;wid=0;times=1,corrupt-answer;times=2")
    assert [r.point for r in rules] == ["corrupt-resident",
                                       "corrupt-answer"]
    with pytest.raises(ValueError):
        faults.parse_faults("corrupt-everything")


def test_obs_metric_map_covers_integrity_family():
    import distributed_oracle_search_tpu.obs as obs

    for name in ("scrub_blocks_checked_total", "scrub_blocks_corrupt_total",
                 "scrub_passes_total", "scrub_pass_seconds",
                 "audit_batches_total", "audit_divergence_total",
                 "audit_dropped_total", "audit_lane_seconds",
                 "answer_fp_mismatch_total",
                 "cache_fingerprint_mismatch_total",
                 "control_divergence_quarantines_total"):
        assert name in obs.__doc__, name


def test_bench_directions_and_tolerances_cover_integrity_family():
    for k in ("integrity_audit_divergence",
              "integrity_wrong_answers_served",
              "integrity_audit_overhead_frac",
              "integrity_scrub_overhead_frac",
              "integrity_detect_seconds"):
        assert obs_fleet._KEY_DIRECTIONS.get(k) == "lower", k
        assert k in obs_fleet._KEY_TOLERANCES, k
    for k in ("integrity_base_queries_per_sec",
              "integrity_audit1_queries_per_sec",
              "integrity_audit10_queries_per_sec",
              "integrity_scrub_queries_per_sec"):
        assert obs_fleet._KEY_DIRECTIONS.get(k) == "higher", k
        assert k in obs_fleet._KEY_TOLERANCES, k
    # correctness counters regress at ZERO tolerance: one wrong answer
    # or one divergence is a failed diff, not noise
    assert obs_fleet._KEY_TOLERANCES["integrity_audit_divergence"] == 0.0
    assert obs_fleet._KEY_TOLERANCES[
        "integrity_wrong_answers_served"] == 0.0


# ------------------------------------------------- chaos drill (slow)

@pytest.mark.slow
def test_corruption_chaos_drill_zero_corrupt_answers(tmp_path_factory,
                                                     tmp_path,
                                                     monkeypatch):
    """The acceptance drill: ``corrupt-resident`` + ``corrupt-answer``
    under a live ControlDaemon. The audit's replica lane detects the
    resident rot, the divergence quarantine pulls the shard (clients
    fail over to the clean replica), scrub-now heals the table, the
    probation loop re-admits — and the final answers are bit-identical
    to the fault-free run, with the whole causal chain on the flight
    recorder. The wire-rot half (``corrupt-answer``) is caught
    synchronously by the fingerprint guard: the corrupted batch is
    retried on the replica and a corrupt answer NEVER reaches a
    client."""
    datadir = str(tmp_path_factory.mktemp("chaos-data"))
    paths = ensure_synth_dataset(datadir, width=8, height=6,
                                 n_queries=32, seed=11)
    outdir = os.path.join(datadir, "index")
    for wid in range(2):
        build_main(["--input", paths["xy"], "--partmethod", "mod",
                    "--partkey", "2", "--workerid", str(wid),
                    "--maxworker", "2", "--outdir", outdir,
                    "--replication", "2"])
    g = Graph.from_xy(paths["xy"])
    dc = DistributionController("mod", 2, 2, g.n, replication=2)
    write_index_manifest(outdir, dc)
    conf = ClusterConfig(
        workers=["localhost"] * 2, partmethod="mod", partkey=2,
        outdir=outdir, xy_file=paths["xy"], scenfile=paths["scen"],
        nfs=datadir, replication=2,
    ).validate()
    owned0 = dc.owned(0)
    t_rot = int(owned0[0])          # the row corrupt-resident flips
    pool = [(int(s), int(t)) for s, t in read_scen(paths["scen"])[:16]]
    pool += [(int(s), t_rot) for s in (1, 5, 9, int(owned0[-1]))]
    sconf = ServeConfig(max_wait_ms=1.0, cache_bytes=0)
    rconf = RuntimeConfig(answer_fp=True)

    # ---- fault-free truth run
    fe_t = ServingFrontend(dc, EngineDispatcher(conf, graph=g, dc=dc),
                           sconf=sconf, rconf=rconf,
                           hconf=HedgeConfig(enabled=False))
    fe_t.start()
    try:
        truth = {q: fe_t.query(*q, timeout=60) for q in pool}
        assert all(r.ok for r in truth.values())
    finally:
        fe_t.stop()
    truth = {q: (r.cost, r.plen, r.finished) for q, r in truth.items()}

    # ---- armed run: shard 0's primary resident table rots at load
    monkeypatch.setenv("DOS_FAULTS", "corrupt-resident;wid=0;times=1")
    faults.reset()
    rec = obs_recorder.FlightRecorder(str(tmp_path / "tape"),
                                      flush_every=1)
    obs_recorder.set_recorder(rec)
    disp = EngineDispatcher(conf, graph=g, dc=dc)
    reg = BreakerRegistry(threshold=3, cooldown_s=600.0, enabled=True)
    fe = ServingFrontend(dc, disp, sconf=sconf, rconf=rconf,
                         registry=reg, breaker_key=lambda w: w,
                         hconf=HedgeConfig(enabled=False))
    auditor = AnswerAuditor(disp, 1000,
                            reference_fn=make_reference_fn(g))
    fe.auditor = auditor
    scrubber = TableScrubber(lambda: list(disp._engines.values()),
                             interval_s=3600.0)
    fe.scrubber = scrubber
    # scrub-now runs synchronously inside the actuator: re-admission
    # probes can only pass AFTER the heal had its say
    daemon = ControlDaemon(
        _icfg(interval_s=0.05), frontend=fe, registry=reg,
        breaker_key=lambda w: w, integrity=auditor,
        scrub_fn=lambda s: scrubber.run_pass(shards={s}, budget=0),
        probe_fn=lambda w: True).start()
    fe.start()
    d0 = _counter("audit_divergence_total")
    q0 = _counter("control_divergence_quarantines_total")
    try:
        # phase A: drive traffic through the rotted row until the loop
        # detects, quarantines, heals and re-admits
        for q in pool:
            fe.query(*q, timeout=60)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            healed = scrubber.corrupt_blocks >= 1
            calm = (auditor._q.qsize() == 0
                    and not daemon.quarantine.quarantined()
                    and reg.allow(0))
            if healed and calm \
                    and _counter("audit_divergence_total") > d0:
                break
            for q in pool[-4:]:             # keep the rot observable
                fe.query(*q, timeout=60)
            time.sleep(0.1)
        assert _counter("audit_divergence_total") - d0 >= 1
        assert (_counter("control_divergence_quarantines_total")
                - q0 >= 1)
        assert auditor.snapshot().get(0, 0) >= 1
        assert scrubber.corrupt_blocks >= 1     # the heal really ran
        # detected -> quarantined -> healed -> re-admitted: the final
        # sweep is bit-identical to the fault-free run
        final = {q: fe.query(*q, timeout=60) for q in pool}
        assert all(r.ok for r in final.values())
        assert {q: (r.cost, r.plen, r.finished)
                for q, r in final.items()} == truth

        # phase B: wire rot. Stop the auditor first so the injection
        # is deterministically consumed by the SERVING dispatch.
        auditor.stop()
        monkeypatch.setenv("DOS_FAULTS", "corrupt-answer;times=1")
        faults.reset()
        m0 = _counter("answer_fp_mismatch_total")
        f0 = _counter("failover_total")
        wired = {q: fe.query(*q, timeout=60) for q in pool}
        assert all(r.ok for r in wired.values())
        assert {q: (r.cost, r.plen, r.finished)
                for q, r in wired.items()} == truth
        assert _counter("answer_fp_mismatch_total") - m0 >= 1
        assert _counter("failover_total") - f0 >= 1
    finally:
        daemon.stop()
        fe.stop()
        auditor.stop()
        scrubber.stop()
        reg.shutdown()
        obs_recorder.set_recorder(None)
        rec.close()
        monkeypatch.delenv("DOS_FAULTS", raising=False)
        faults.reset()
    # the causal chain on tape: fault fired -> audit caught it ->
    # scrub healed inside the quarantine actuator -> shard re-admitted
    kinds = [r["kind"] for r in obs_recorder.replay(str(tmp_path / "tape"))
             if r.get("rec") == "event"]
    for kind in ("fault", "audit_divergence", "scrub_corrupt",
                 "control_divergence_quarantine", "control_readmit"):
        assert kind in kinds, kind
    assert kinds.index("fault") < kinds.index("audit_divergence")
    assert (kinds.index("audit_divergence")
            < kinds.index("scrub_corrupt")
            < kinds.index("control_readmit"))
    assert (kinds.index("control_divergence_quarantine")
            < kinds.index("control_readmit"))
    text = obs_recorder.render_timeline(
        obs_recorder.replay(str(tmp_path / "tape")))
    assert "audit_divergence" in text and "scrub_corrupt" in text
