"""Gateway tier: binary protocol codecs, the accept loop + DosClient
end to end across all four families, credit-window backpressure,
malformed-frame hygiene, the worker-side L2 cache across diff-epoch
swaps and membership commits, the kill-one-frontend drill, and the
control/obs satellites (credit occupancy signal, fleet columns, bench
key pins).
"""

import os
import socket
import threading
import time
import types

import numpy as np
import pytest

from distributed_oracle_search_tpu.data import ensure_synth_dataset, read_scen
from distributed_oracle_search_tpu.data.formats import write_diff
from distributed_oracle_search_tpu.data.graph import Graph
from distributed_oracle_search_tpu.gateway import (
    DosClient, GatewayBusy, GatewayConfig, GatewayError, GatewayServer,
    GatewayTier, GATEWAY_SCHEMA_VERSION, GatewayProtocolError,
    GatewaySchemaError,
)
from distributed_oracle_search_tpu.gateway import protocol
from distributed_oracle_search_tpu.models.cpd import write_index_manifest
from distributed_oracle_search_tpu.obs import metrics as obs_metrics
from distributed_oracle_search_tpu.parallel import membership
from distributed_oracle_search_tpu.parallel.partition import (
    DistributionController,
)
from distributed_oracle_search_tpu.serving import (
    CallableDispatcher, EngineDispatcher, ServeConfig, ServingFrontend,
)
from distributed_oracle_search_tpu.traffic import QueryFamilies
from distributed_oracle_search_tpu.transport.frames import (
    Frame, FrameReader, FrameWriter, TransportError,
)
from distributed_oracle_search_tpu.transport.wire import RuntimeConfig
from distributed_oracle_search_tpu.utils.config import ClusterConfig
from distributed_oracle_search_tpu.worker.build import main as build_main
from distributed_oracle_search_tpu.worker.server import FifoServer

pytestmark = pytest.mark.gateway


def _counter(name: str) -> float:
    return obs_metrics.REGISTRY.snapshot()["counters"].get(name, 0)


# ------------------------------------------------------------- fixtures

@pytest.fixture(scope="module")
def gw_world(tmp_path_factory):
    """One-worker world with a built CPD index (the traffic_world
    pattern, single shard keeps it quick)."""
    datadir = str(tmp_path_factory.mktemp("gw-data"))
    paths = ensure_synth_dataset(datadir, width=10, height=8,
                                 n_queries=64, seed=51)
    conf = ClusterConfig(
        workers=["localhost"], partmethod="mod", partkey=1,
        outdir=os.path.join(datadir, "index"),
        xy_file=paths["xy"], scenfile=paths["scen"],
        diffs=["-", paths["diff"]], nfs=datadir,
    ).validate()
    build_main(["--input", conf.xy_file, "--partmethod",
                conf.partmethod, "--partkey", str(conf.partkey),
                "--workerid", "0", "--maxworker", "1",
                "--outdir", conf.outdir])
    g = Graph.from_xy(conf.xy_file)
    dc = DistributionController("mod", 1, 1, g.n)
    write_index_manifest(conf.outdir, dc)
    queries = read_scen(conf.scenfile)
    dispatcher = EngineDispatcher(conf, graph=g, dc=dc)
    return conf, g, dc, queries, dispatcher


def _frontend(dc, dispatcher, **kw):
    sconf = ServeConfig(**{"queue_depth": 1024, "max_wait_ms": 1.0,
                           "cache_bytes": 0, **kw}).validate()
    fe = ServingFrontend(dc, dispatcher, sconf=sconf)
    fe.start()
    return fe


def _gconf(tmp_path, **kw):
    return GatewayConfig(**{"replicas": 1,
                            "socket_dir": str(tmp_path),
                            "credit": 32,
                            "deadline_ms": 60_000.0, **kw}).validate()


# ------------------------------------------------------ protocol codecs

def test_protocol_pair_roundtrip():
    header, arrays = protocol.encode_pairs(7, [(1, 2), (3, 4)],
                                           deadline_ms=500.0, epoch=2)
    fam, payload = protocol.parse_query_frame(
        Frame("q", header, arrays))
    assert fam == "pair"
    assert payload.tolist() == [[1, 2], [3, 4]]
    assert protocol.frame_id(Frame("q", header, arrays)) == 7


def test_protocol_mat_alt_rev_roundtrip():
    h, a = protocol.encode_mat(1, 5, [7, 9, 11])
    fam, (s, targets) = protocol.parse_query_frame(Frame("q", h, a))
    assert (fam, s, targets.tolist()) == ("mat", 5, [7, 9, 11])
    h, a = protocol.encode_alt(2, 5, 9, 3)
    assert protocol.parse_query_frame(Frame("q", h, a)) == (
        "alt", (5, 9, 3))
    h, a = protocol.encode_pairs(3, [(5, 9)], family="rev")
    fam, payload = protocol.parse_query_frame(Frame("q", h, a))
    assert fam == "rev" and payload.tolist() == [[5, 9]]


def test_protocol_unknown_keys_tolerated():
    header, arrays = protocol.encode_pairs(1, [(1, 2)])
    header["shiny_future_field"] = {"nested": True}
    fam, _payload = protocol.parse_query_frame(
        Frame("q", header, arrays))
    assert fam == "pair"


def test_protocol_malformed_raises_typed():
    good_h, good_a = protocol.encode_pairs(1, [(1, 2)])
    bad = [
        Frame("q", {**good_h, "family": "zorp"}, good_a),
        Frame("q", good_h, []),                      # missing payload
        Frame("q", good_h, [np.zeros((2, 3), np.int64)]),  # bad shape
        Frame("q", {"kind": "q", "family": "mat", "id": 1},
              [np.zeros(0, np.int64)]),              # empty targets
        Frame("q", {"kind": "q", "family": "alt", "id": 1}, []),
    ]
    for fr in bad:
        with pytest.raises(GatewayProtocolError):
            protocol.parse_query_frame(fr)
    with pytest.raises(GatewayProtocolError):
        protocol.encode_pairs(1, [1, 2, 3])


def test_hello_gate_newer_tolerate_older():
    protocol.check_hello({"gv": GATEWAY_SCHEMA_VERSION})
    protocol.check_hello({"gv": 0, "unknown": 1})    # older + extras ok
    protocol.check_hello({})                         # no gv = oldest
    with pytest.raises(GatewaySchemaError):
        protocol.check_hello({"gv": GATEWAY_SCHEMA_VERSION + 1})
    fr = Frame("q", {"kind": "q", "family": "pair",
                     "gv": GATEWAY_SCHEMA_VERSION + 1}, [])
    with pytest.raises(GatewaySchemaError):
        protocol.parse_query_frame(fr)


def test_gateway_config_env_degrades(monkeypatch):
    monkeypatch.setenv("DOS_GATEWAY_REPLICAS", "-3")
    monkeypatch.setenv("DOS_GATEWAY_CREDIT", "not-a-number")
    monkeypatch.setenv("DOS_GATEWAY_L2_BYTES", "4096")
    gc = GatewayConfig.from_env()
    assert gc.replicas == GatewayConfig.replicas     # invalid → default
    assert gc.credit == GatewayConfig.credit         # unparseable
    assert gc.l2_bytes == 4096
    assert GatewayConfig.from_env(replicas=5).replicas == 5


# --------------------------------------------------------- server + client

def test_gateway_end_to_end_families(gw_world, tmp_path):
    """All four families over the wire, answers matching the direct
    frontend/planner results; the reply stamps replica identity."""
    conf, g, dc, queries, dispatcher = gw_world
    fe = _frontend(dc, dispatcher)
    fam = QueryFamilies(fe, graph=g)
    srv = GatewayServer(fe, families=fam, fid=0,
                        gconf=_gconf(tmp_path)).start()
    client = None
    try:
        client = DosClient(srv.socket_path)
        assert client.frontend == 0
        pairs = [(int(s), int(t)) for s, t in queries[:8]]
        rows = client.query_batch(pairs, timeout=60.0)
        direct = [fe.submit(s, t).result(60.0) for s, t in pairs]
        assert [(st, c, p, f) for st, c, p, f, _ in rows] == \
            [(r.status, r.cost, r.plen, r.finished) for r in direct]
        s, t = pairs[0]
        # rev == the direct reverse result, labeled with (s, t)
        rrow = client.reverse(s, t, timeout=60.0)
        rres = fam.reverse(s, t).result(60.0).result
        assert rrow[:4] == (rres.status, rres.cost, rres.plen,
                            rres.finished)
        # mat row pinned element-wise against the planner
        targets = [int(q[1]) for q in queries[:6]]
        costs = client.matrix(s, targets, timeout=60.0)
        assert costs == list(fam.matrix(s, targets).result(60.0).costs)
        # alt: ascending (cost, via) alternatives
        alts = client.alternatives(s, t, 3, timeout=60.0)
        assert alts == list(
            fam.alternatives(s, t, 3).result(60.0).alternatives)
        # liveness + statusz surface
        health = client.ping()
        assert health["ok"] and health["frontend"] == 0
        st = srv.statusz()
        assert st["frontend"] == 0 and st["served"] >= 4
    finally:
        if client is not None:
            client.close()
        srv.stop()
        fe.stop()


def test_gateway_malformed_frame_answers_typed_err(gw_world, tmp_path):
    """Satellite pin: a malformed client frame answers a typed err
    frame (never a torn connection), books
    gateway_frames_malformed_total, and the connection keeps serving."""
    conf, g, dc, queries, dispatcher = gw_world
    fe = _frontend(dc, dispatcher)
    srv = GatewayServer(fe, fid=0, gconf=_gconf(tmp_path)).start()
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    try:
        sock.connect(srv.socket_path)
        reader, writer = FrameReader(sock), FrameWriter(sock)
        assert reader.read().kind == "hello"
        m0 = _counter("gateway_frames_malformed_total")
        h, a = protocol.encode_pairs(4, [(1, 2)])
        writer.send({**h, "family": "zorp"}, a)
        err = reader.read()
        assert err.kind == "err" and "zorp" in err.header["error"]
        assert protocol.frame_id(err) == 4
        assert _counter("gateway_frames_malformed_total") - m0 == 1
        # same connection still serves after the typed refusal
        s, t = int(queries[0][0]), int(queries[0][1])
        h, a = protocol.encode_pairs(5, [(s, t)])
        writer.send(h, a)
        reply = reader.read()
        assert reply.kind == "r" and reply.header["status"] == ["OK"]
        assert srv.statusz()["malformed"] == 1
    finally:
        sock.close()
        srv.stop()
        fe.stop()


def test_gateway_busy_at_credit_window(tmp_path):
    """Query frames past the advertised credit window answer an
    explicit busy frame; the admitted ones still complete."""
    release = threading.Event()
    n = 64

    def slow(wid, q, rconf, diff):
        release.wait(30.0)
        q = np.asarray(q)
        return (np.abs(q[:, 0] - q[:, 1]).astype(np.int64),
                np.ones(len(q), np.int64), np.ones(len(q), bool))

    dc = DistributionController("mod", 1, 1, n)
    fe = _frontend(dc, CallableDispatcher(slow))
    srv = GatewayServer(fe, fid=0,
                        gconf=_gconf(tmp_path, credit=2)).start()
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    try:
        sock.connect(srv.socket_path)
        reader, writer = FrameReader(sock), FrameWriter(sock)
        assert int(reader.read().header["credit"]) == 2
        b0 = _counter("gateway_busy_total")
        for fid in range(3):
            h, a = protocol.encode_pairs(fid, [(1, 2)])
            writer.send(h, a)
        release.set()
        kinds = {}
        for _ in range(3):
            fr = reader.read()
            kinds[protocol.frame_id(fr)] = fr.kind
        assert kinds[0] == "r" and kinds[1] == "r"
        assert kinds[2] == "busy"          # third frame over the window
        assert _counter("gateway_busy_total") - b0 == 1
    finally:
        release.set()
        sock.close()
        srv.stop()
        fe.stop()


def test_client_gates_newer_gateway_schema(tmp_path):
    """DosClient refuses a gateway whose hello advertises a NEWER
    schema (gate-newer both directions)."""
    path = str(tmp_path / "fake.sock")
    lsock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    lsock.bind(path)
    lsock.listen(1)

    def fake_gateway():
        conn, _ = lsock.accept()
        FrameWriter(conn).send(
            {"kind": "hello", "gv": GATEWAY_SCHEMA_VERSION + 1,
             "frontend": 0, "credit": 4})
        time.sleep(0.5)
        conn.close()

    th = threading.Thread(target=fake_gateway, daemon=True)
    th.start()
    with pytest.raises(GatewaySchemaError):
        DosClient(path)
    th.join(timeout=5.0)
    lsock.close()


def test_kill_one_frontend_drill(tmp_path):
    """Two replicas, one killed mid-run: every ACCEPTED request is
    answered (the dying replica drains its in-flight frames), and the
    survivor absorbs the rerouted traffic."""
    n = 64

    def answer(wid, q, rconf, diff):
        q = np.asarray(q)
        return (np.abs(q[:, 0] - q[:, 1]).astype(np.int64),
                np.ones(len(q), np.int64), np.ones(len(q), bool))

    dc = DistributionController("mod", 1, 1, n)
    fes = [_frontend(dc, CallableDispatcher(answer)) for _ in range(2)]
    tier = GatewayTier([(fe, None) for fe in fes],
                       gconf=_gconf(tmp_path, replicas=2)).start()
    clients = [DosClient(ep) for ep in tier.endpoints]
    ok_rows = 0
    want = 0
    pool = [[(i % 11 + 1, (i * 7) % 13 + 1) for i in range(8)]
            for _ in range(6)]
    try:
        for batch in pool[:2]:           # both replicas take traffic
            for c in clients:
                rows = c.query_batch(batch, timeout=30.0)
                want += len(batch)
                ok_rows += sum(r[0] == "OK" for r in rows)
        tier.servers[0].stop()           # kill replica 0
        for batch in pool[2:]:
            try:
                rows = clients[0].query_batch(batch, timeout=5.0)
            except (TransportError, GatewayBusy, GatewayError,
                    TimeoutError, OSError):
                # the dead replica refuses cleanly; the client fails
                # over to the survivor — the request is NOT lost
                rows = clients[1].query_batch(batch, timeout=30.0)
            want += len(batch)
            ok_rows += sum(r[0] == "OK" for r in rows)
        assert ok_rows == want           # zero lost accepted requests
        assert tier.statusz()["replicas"] == 2
    finally:
        for c in clients:
            c.close()
        tier.stop()
        for fe in fes:
            fe.stop()


def test_l1_cache_visible_in_statusz(gw_world, tmp_path):
    conf, g, dc, queries, dispatcher = gw_world
    fe = _frontend(dc, dispatcher, cache_bytes=1 << 20)
    srv = GatewayServer(fe, fid=3, gconf=_gconf(tmp_path)).start()
    client = None
    try:
        client = DosClient(srv.socket_path)
        s, t = int(queries[0][0]), int(queries[0][1])
        first = client.query(s, t, timeout=60.0)
        again = client.query(s, t, timeout=60.0)
        assert first[1:4] == again[1:4]
        assert not first[4] and again[4]          # cached flag rides
        st = srv.statusz()
        assert st["l1_hits"] >= 1 and st["l1_hit_rate"] > 0.0
        tier_view = GatewayTier([(fe, None)],
                                gconf=_gconf(tmp_path)).statusz()
        assert tier_view["l1_hit_rate"] >= 0.0
        assert "0" in tier_view["frontends"]
    finally:
        if client is not None:
            client.close()
        srv.stop()
        fe.stop()


# ----------------------------------------------------- worker L2 cache

@pytest.fixture()
def l2_server(gw_world, tmp_path, monkeypatch):
    conf, g, dc, queries, dispatcher = gw_world
    monkeypatch.setenv("DOS_GATEWAY_L2_BYTES", str(1 << 20))
    srv = FifoServer(conf, 0,
                     command_fifo=str(tmp_path / "w0.fifo"))
    assert srv.l2.enabled
    return srv, g, queries


def test_l2_disabled_by_default_keeps_legacy_worker(gw_world, tmp_path,
                                                    monkeypatch):
    """Satellite pin: with DOS_GATEWAY_* unset the worker carries no
    L2 — answer path and statusz are byte-identical pre-gateway."""
    conf, g, dc, queries, dispatcher = gw_world
    monkeypatch.delenv("DOS_GATEWAY_L2_BYTES", raising=False)
    srv = FifoServer(conf, 0, command_fifo=str(tmp_path / "w0.fifo"))
    assert not srv.l2.enabled
    assert "l2" not in srv.statusz()
    rconf = RuntimeConfig()
    h0 = _counter("worker_l2_hits_total")
    m0 = _counter("worker_l2_misses_total")
    c1, p1, f1, _s, _paths = srv.answer_queries(queries[:8], rconf, "-")
    c2, p2, f2, _s, _paths = srv.answer_queries(queries[:8], rconf, "-")
    assert np.array_equal(c1, c2) and np.array_equal(p1, p2)
    assert _counter("worker_l2_hits_total") == h0
    assert _counter("worker_l2_misses_total") == m0


def test_l2_hits_before_kernel(l2_server):
    srv, g, queries = l2_server
    rconf = RuntimeConfig()
    h0 = _counter("worker_l2_hits_total")
    m0 = _counter("worker_l2_misses_total")
    c1, p1, f1, _s, _paths = srv.answer_queries(queries[:8], rconf, "-")
    assert _counter("worker_l2_misses_total") - m0 == 8
    c2, p2, f2, _s, _paths = srv.answer_queries(queries[:8], rconf, "-")
    assert _counter("worker_l2_hits_total") - h0 == 8
    assert np.array_equal(c1, c2) and np.array_equal(p1, p2)
    assert np.array_equal(f1, f2)
    st = srv.statusz()["l2"]
    assert st["entries"] == 8 and st["hits"] >= 8
    # a partial batch: 4 cached + 4 new merge back in query order
    c3, p3, _f, _s, _paths = srv.answer_queries(queries[4:12], rconf,
                                                "-")
    ref_c, ref_p, _rf, _rs, _rp = FifoServer.answer_queries(
        srv, queries[4:12], RuntimeConfig(hscale=rconf.hscale), "-")
    assert np.array_equal(c3, ref_c) and np.array_equal(p3, ref_p)


def test_l2_sig_fabricated_paths_match_engine(l2_server):
    """A sig-requesting caller gets a paths row fabricated from the
    stored signature on a hit — same node set, same move count — or
    the conservative moves=-1 sentinel, never garbage."""
    srv, g, queries = l2_server
    rconf = RuntimeConfig(sig_k=8)
    _c, plen, _f, _s, paths1 = srv.answer_queries(queries[:6], rconf,
                                                  "-")
    _c, _p, _f, _s, paths2 = srv.answer_queries(queries[:6], rconf,
                                                "-")
    assert paths1 is not None and paths2 is not None
    nodes1, moves1 = paths1
    nodes2, moves2 = paths2
    for i in range(6):
        if moves2[i] < 0:
            continue                     # conservative sentinel is ok
        assert moves2[i] == moves1[i]
        assert (set(nodes2[i, :moves2[i] + 1].tolist())
                == set(nodes1[i, :moves1[i] + 1].tolist()))


def test_l2_two_swap_never_serves_stale_cost(l2_server, tmp_path):
    """The PR 9 scoped-invalidation suite at the worker: across TWO
    diff-epoch swaps, an entry whose cached walk touches an updated
    edge always recomputes, a provably-clean survivor re-keys and
    hits — and every answer equals the kernel's own under the active
    fusion."""
    srv, g, queries = l2_server
    srv.traffic = types.SimpleNamespace(scoped_max=10_000)
    srv._l2_prev = (0, "-")
    rconf0 = RuntimeConfig(sig_k=8, diff_epoch=0)
    cost0, _p, fin0, _s, paths = srv.answer_queries(
        queries[:16], rconf0, "-")
    nodes, moves = paths
    # pick A, B: finished walks with disjoint path-node sets, so the
    # swap's affected edge (on A's walk) provably misses B's
    cand = [i for i in range(16) if fin0[i] and moves[i] >= 1]
    a = cand[0]
    a_nodes = set(nodes[a, :moves[a] + 1].tolist())
    b = next(i for i in cand[1:]
             if not (set(nodes[i, :moves[i] + 1].tolist()) & a_nodes))
    b_nodes = set(nodes[b, :moves[b] + 1].tolist())
    edge1 = (int(nodes[a, 0]), int(nodes[a, 1]))    # on A's walk

    fused = {}                           # fused spool is CUMULATIVE

    def swap(depoch, edge, bump):
        fused[edge] = bump
        diff = str(tmp_path / f"fused{depoch}.diff")
        es = list(fused.items())
        write_diff(diff, np.array([e[0][0] for e in es]),
                   np.array([e[0][1] for e in es]),
                   np.array([e[1] for e in es]))
        srv._l2_on_swap(depoch, diff, frozenset({edge}))
        return diff

    diff1 = swap(1, edge1, 10_000)
    rconf1 = RuntimeConfig(sig_k=8, diff_epoch=1)
    h0 = _counter("worker_l2_hits_total")
    got_c, got_p, _f, _s, _paths = srv.answer_queries(
        queries[:16][[a, b]], rconf1, diff1)
    # B survived the swap re-keyed (1 hit), A was dropped and re-ran
    assert _counter("worker_l2_hits_total") - h0 == 1
    ref_c, ref_p, _rf, _rs = srv.engine.answer(
        queries[:16][[a, b]], RuntimeConfig(sig_k=8, diff_epoch=1),
        diff1)
    assert got_c.tolist() == ref_c.tolist()
    assert got_p.tolist() == ref_p.tolist()
    assert got_c[0] != cost0[a]          # the bump priced A's walk up
    assert got_c[1] == cost0[b]          # B untouched by the swap
    # second swap: now B's walk is hit; A's epoch-1 entry must survive
    edge2 = (int(nodes[b, 0]), int(nodes[b, 1]))
    diff2 = swap(2, edge2, 20_000)
    assert srv._l2_prev == (2, diff2)
    rconf2 = RuntimeConfig(sig_k=8, diff_epoch=2)
    h1 = _counter("worker_l2_hits_total")
    got2_c, got2_p, _f, _s, _paths = srv.answer_queries(
        queries[:16][[a, b]], rconf2, diff2)
    assert _counter("worker_l2_hits_total") - h1 == 1   # A re-keyed
    ref2_c, ref2_p, _rf, _rs = srv.engine.answer(
        queries[:16][[a, b]], RuntimeConfig(sig_k=8, diff_epoch=2),
        diff2)
    assert got2_c.tolist() == ref2_c.tolist()
    assert got2_p.tolist() == ref2_p.tolist()
    assert got2_c[1] != cost0[b]         # B re-priced under fusion 2
    # stale-cost regression: nothing ever answered an old epoch's cost
    assert (a_nodes & b_nodes) == set()


def test_l2_flushes_on_membership_commit(l2_server, gw_world):
    """Mid-reshard drill: a committed membership epoch makes every L2
    key unreachable — the cache flushes instead of pinning dead
    entries, and post-commit answers recompute under the new epoch."""
    srv, g, queries = l2_server
    conf = gw_world[0]
    rconf = RuntimeConfig()
    srv.answer_queries(queries[:8], rconf, "-")
    assert len(srv.l2) == 8
    try:
        membership.save_state(conf.outdir, membership.MembershipState(
            epoch=1, workers=["localhost"], owners=[0]))
        srv._refresh_membership()
        assert srv.epoch == 1
        assert len(srv.l2) == 0
        m0 = _counter("worker_l2_misses_total")
        c1, p1, _f, _s, _paths = srv.answer_queries(
            queries[:8], RuntimeConfig(epoch=1), "-")
        assert _counter("worker_l2_misses_total") - m0 == 8
        ref_c, ref_p, _rf, _rs = srv.engine.answer(
            queries[:8], RuntimeConfig(epoch=1), "-")
        assert np.array_equal(c1, ref_c)
        assert np.array_equal(p1, ref_p)
    finally:
        os.remove(membership.state_path(conf.outdir))


def test_l2_bypassed_for_extraction_batches(l2_server):
    """Extraction batches need REAL per-move path prefixes — the L2
    must not intercept them."""
    srv, g, queries = l2_server
    rconf = RuntimeConfig(extract=True, k_moves=4)
    h0 = _counter("worker_l2_hits_total")
    m0 = _counter("worker_l2_misses_total")
    srv.answer_queries(queries[:4], rconf, "-")
    srv.answer_queries(queries[:4], rconf, "-")
    assert _counter("worker_l2_hits_total") == h0
    assert _counter("worker_l2_misses_total") == m0


# --------------------------------------------- control-plane satellite

def test_signal_reader_credit_occupancy():
    from distributed_oracle_search_tpu.control.signals import (
        SignalReader,
    )

    fe = types.SimpleNamespace(statusz=lambda: {
        "transport": {"mode": "rpc", "connections": {
            "0": {"occupancy": 0.25}, "1": {"occupancy": 0.875}}},
        "shards": {},
    })
    sig = SignalReader(frontend=fe).read(now=1.0)
    assert sig.credit_occupancy == {0: 0.25, 1: 0.875}
    assert sig.credit_frac == 0.875
    # a pre-gateway frontend statusz (no transport section) reads clean
    bare = types.SimpleNamespace(statusz=lambda: {"shards": {}})
    sig = SignalReader(frontend=bare).read(now=1.0)
    assert sig.credit_occupancy == {} and sig.credit_frac == 0.0


def test_repair_scaler_trips_on_credit_occupancy():
    from distributed_oracle_search_tpu.control.policy import (
        RepairScaler,
    )
    from distributed_oracle_search_tpu.control.signals import (
        ControlSignals,
    )

    rs = RepairScaler(starve_frac=0.8, hot_frac=0.9, clear_frac=0.5,
                      hold_ticks=2, cooldown_s=0.0)
    # full credit windows with EMPTY frontend queues (the streaming
    # fleet's starvation shape: queues live in the worker)
    sig = ControlSignals(now=0.0, credit_occupancy={0: 0.95},
                         credit_frac=0.95)
    assert rs.decide(sig, 1.0) == []
    assert rs.decide(sig, 2.0) == [("scale_advise",)]
    # neither sensor reporting = no evidence; the rule holds state
    idle = ControlSignals(now=0.0)
    assert rs.decide(idle, 3.0) == []


# --------------------------------------------------- obs-plane satellite

def test_fleet_columns_render_gateway_and_blanks():
    from distributed_oracle_search_tpu.obs import fleet as obs_fleet

    tier_row = obs_fleet._summarize({
        "gateway": {"replicas": 2, "clients": 5, "l1_hit_rate": 0.42},
    })
    assert tier_row["gw"] == "x2" and tier_row["clients"] == 5
    assert tier_row["l1 hit"] == 0.42
    replica_row = obs_fleet._summarize({
        "gateway": {"frontend": 1, "clients": 2, "l1_hit_rate": 0.5},
    })
    assert replica_row["gw"] == "f1"
    worker_row = obs_fleet._summarize({
        "worker": {"batches": 3, "l2": {"hit_rate": 0.75,
                                        "entries": 10}},
    })
    assert worker_row["l2 hit"] == 0.75
    # pre-gateway statusz renders blanks, never a crash
    old = obs_fleet._summarize({"worker": {"batches": 3}})
    assert "gw" not in old and "l2 hit" not in old
    weird = obs_fleet._summarize({
        "gateway": {"replicas": True, "clients": "many",
                    "l1_hit_rate": None},
        "worker": {"l2": {"hit_rate": "hot"}},
    })
    assert "gw" not in weird and "clients" not in weird
    assert "l1 hit" not in weird and "l2 hit" not in weird
    table = obs_fleet.render_top({
        "gw:1": {"gateway": {"replicas": 2, "clients": 5,
                             "l1_hit_rate": 0.42}},
        "old:2": {"worker": {"batches": 3}},
    })
    assert "x2" in table and "-" in table


def test_bench_gateway_keys_pinned():
    """The rush-hour bench keys carry a direction and a tolerance so
    regressions gate instead of drifting silently."""
    from distributed_oracle_search_tpu.obs import fleet as obs_fleet

    keys = {
        "gateway_aggregate_queries_per_sec": "higher",
        "gateway_single_head_queries_per_sec": "higher",
        "gateway_vs_single_head_ratio": "higher",
        "gateway_fairness_ratio": "lower",
        "gateway_answers_match": "higher",
        "gateway_fleet_cache_hit_rate": "higher",
        "gateway_single_head_cache_hit_rate": "higher",
    }
    for key, direction in keys.items():
        assert obs_fleet._KEY_DIRECTIONS.get(key) == direction, key
        assert key in obs_fleet._KEY_TOLERANCES, key
    assert obs_fleet._KEY_TOLERANCES["gateway_answers_match"] == 0.0
