"""Online serving layer: queues, micro-batching, cache, shed semantics.

The tier-1 smoke test is the acceptance gate: answers from the online
path must be identical to a batch campaign over the same queries on the
synth graph, overload must return ``BUSY`` (not a hang) when the queue
bound is hit, and on a skewed workload the cache-hit counter must move
and the micro-batcher must actually coalesce (mean dispatched batch
size > 1). The heavy open-loop Poisson latency drill stays behind
``slow``.
"""

import io
import os
import threading
import time

import numpy as np
import pytest

from distributed_oracle_search_tpu.data import ensure_synth_dataset, read_scen
from distributed_oracle_search_tpu.data.graph import Graph
from distributed_oracle_search_tpu.models.cpd import write_index_manifest
from distributed_oracle_search_tpu.obs import metrics as obs_metrics
from distributed_oracle_search_tpu.parallel.partition import (
    DistributionController,
)
from distributed_oracle_search_tpu.serving import (
    BUSY, CallableDispatcher, EngineDispatcher, FifoDispatcher, OK,
    ResultCache, ServeConfig, ServeRequest, ServingFrontend, ShardQueue,
    TIMEOUT, UNAVAILABLE, knob_fingerprint,
)
from distributed_oracle_search_tpu.serving import ingress
from distributed_oracle_search_tpu.transport import resilience
from distributed_oracle_search_tpu.transport.wire import RuntimeConfig
from distributed_oracle_search_tpu.utils.config import ClusterConfig
from distributed_oracle_search_tpu.worker import FifoServer, stop_server
from distributed_oracle_search_tpu.worker.build import main as build_main

pytestmark = pytest.mark.serve


# ------------------------------------------------------------- fixtures

@pytest.fixture(scope="module")
def serve_world(tmp_path_factory):
    """Small 2-shard world with a built CPD index (the test_drivers
    pattern): graph, controller, conf, and the scenario queries."""
    datadir = str(tmp_path_factory.mktemp("serve-data"))
    paths = ensure_synth_dataset(datadir, width=10, height=8,
                                 n_queries=96, seed=21)
    conf = ClusterConfig(
        workers=["localhost", "localhost"],
        partmethod="mod", partkey=2,
        outdir=os.path.join(datadir, "index"),
        xy_file=paths["xy"], scenfile=paths["scen"],
        diffs=["-", paths["diff"]],
        nfs=datadir,
    ).validate()
    for wid in range(conf.maxworker):
        build_main(["--input", conf.xy_file, "--partmethod",
                    conf.partmethod, "--partkey", str(conf.partkey),
                    "--workerid", str(wid),
                    "--maxworker", str(conf.maxworker),
                    "--outdir", conf.outdir])
    g = Graph.from_xy(conf.xy_file)
    dc = DistributionController(conf.partmethod, conf.partkey,
                                conf.maxworker, g.n)
    write_index_manifest(conf.outdir, dc)
    queries = read_scen(conf.scenfile)
    return conf, g, dc, queries


def _counter(name: str) -> float:
    return obs_metrics.REGISTRY.snapshot()["counters"].get(name, 0)


def _hist(name: str) -> dict:
    return obs_metrics.REGISTRY.snapshot()["histograms"][name]


def _mk_req(s, t, wid=0, deadline=None):
    return ServeRequest(s=s, t=t, wid=wid, key=(s, t, "-", ()),
                        t_submit=time.monotonic(), deadline=deadline)


# ----------------------------------------------------------- unit: knobs

def test_serve_config_env_and_overrides(monkeypatch):
    monkeypatch.setenv("DOS_SERVE_MAX_BATCH", "128")
    monkeypatch.setenv("DOS_SERVE_MAX_WAIT_MS", "2.5")
    monkeypatch.setenv("DOS_SERVE_QUEUE_DEPTH", "nonsense")  # degrades
    sc = ServeConfig.from_env(cache_bytes=0)
    assert sc.max_batch == 128
    assert sc.max_wait_ms == 2.5
    assert sc.queue_depth == ServeConfig.queue_depth
    assert sc.cache_bytes == 0


@pytest.mark.parametrize("bad", [
    dict(max_batch=0), dict(max_batch=48), dict(queue_depth=0),
    dict(deadline_ms=0), dict(cache_bytes=-1),
])
def test_serve_config_rejects_malformed(bad):
    with pytest.raises(ValueError):
        ServeConfig(**bad).validate()


# ----------------------------------------------------------- unit: cache

def test_result_cache_lru_eviction_and_counters():
    from distributed_oracle_search_tpu.serving.cache import ENTRY_BYTES

    cache = ResultCache(3 * ENTRY_BYTES)
    h0, m0, e0 = (_counter("serve_cache_hits_total"),
                  _counter("serve_cache_misses_total"),
                  _counter("serve_cache_evictions_total"))
    for i in range(4):
        cache.put((i, i, "-", ()), (i, 1, True))
    assert len(cache) == 3
    assert cache.get((0, 0, "-", ())) is None          # evicted (LRU)
    assert cache.get((3, 3, "-", ())) == (3, 1, True)
    # touching 1 makes 2 the LRU victim of the next insert
    assert cache.get((1, 1, "-", ())) is not None
    cache.put((9, 9, "-", ()), (9, 1, True))
    assert cache.get((2, 2, "-", ())) is None
    assert _counter("serve_cache_evictions_total") - e0 == 2
    assert _counter("serve_cache_hits_total") - h0 == 2
    assert _counter("serve_cache_misses_total") - m0 == 2


def test_result_cache_invalidate_by_diff_and_disabled():
    cache = ResultCache(1 << 20)
    cache.put((1, 2, "-", ()), (3, 1, True))
    cache.put((1, 2, "d1", ()), (5, 1, True))
    assert cache.invalidate("d1") == 1
    assert cache.get((1, 2, "-", ())) is not None
    assert cache.invalidate() == 1
    assert len(cache) == 0
    off = ResultCache(0)
    off.put((1, 2, "-", ()), (3, 1, True))
    assert off.get((1, 2, "-", ())) is None and not off.enabled


def test_knob_fingerprint_covers_answer_knobs():
    base = knob_fingerprint(RuntimeConfig())
    assert knob_fingerprint(RuntimeConfig(hscale=2.0)) != base
    assert knob_fingerprint(RuntimeConfig(k_moves=3)) != base
    assert knob_fingerprint(RuntimeConfig(time=10)) != base
    # presentation knobs stay out
    assert knob_fingerprint(RuntimeConfig(verbose=3, threads=7)) == base


# ----------------------------------------------------------- unit: queue

def test_shard_queue_bounded_and_never_blocks():
    q = ShardQueue(2)
    assert q.try_put(_mk_req(1, 2))
    assert q.try_put(_mk_req(3, 4))
    t0 = time.monotonic()
    assert not q.try_put(_mk_req(5, 6))        # full: immediate False
    assert time.monotonic() - t0 < 0.1
    q.close()
    assert not q.try_put(_mk_req(7, 8))        # closed: immediate False
    assert len(q.drain()) == 2


def test_shard_queue_batch_flush_on_size_and_wait():
    q = ShardQueue(64)
    stop = threading.Event()
    for i in range(5):
        q.try_put(_mk_req(i, i))
    # size flush: 4 of 5 immediately, no max_wait sleep
    t0 = time.monotonic()
    batch = q.get_batch(4, max_wait_s=5.0, stop=stop)
    assert len(batch) == 4 and time.monotonic() - t0 < 1.0
    # wait flush: the leftover flushes alone once max_wait expires
    batch = q.get_batch(4, max_wait_s=0.05, stop=stop)
    assert len(batch) == 1
    stop.set()
    assert q.get_batch(4, 0.01, stop) == []


# ------------------------------------------------- frontend: smoke gate

def test_online_answers_match_campaign_path(serve_world):
    """Acceptance smoke: frontend + in-process shard engines round-trip
    ~100 queries (some duplicated); answers are identical to the
    campaign path (``ShardEngine.answer`` over the grouped batch), the
    skewed repeats hit the cache, and the micro-batcher coalesces."""
    conf, g, dc, queries = serve_world
    base = queries[:64]
    rng = np.random.default_rng(5)
    # zipf-ish skew: repeats drawn heavily from the head of the pool
    reps = base[rng.zipf(1.5, size=40).clip(1, len(base)) - 1]
    workload = np.concatenate([base, reps])
    assert len(workload) >= 100

    rconf = RuntimeConfig()
    dispatcher = EngineDispatcher(conf, graph=g, dc=dc)
    sconf = ServeConfig(max_batch=32, max_wait_ms=50.0, queue_depth=256)
    fe = ServingFrontend(dc, dispatcher, sconf=sconf, rconf=rconf)
    fe.start()
    hits0 = _counter("serve_cache_hits_total")
    fill0 = _hist("serve_batch_fill")
    try:
        # phase 1: the unique pool, submitted back-to-back so batches
        # can form; phase 2: the skewed repeats (now cache-resident)
        futs = [fe.submit(s, t) for s, t in base]
        res = [f.result(30) for f in futs]
        futs2 = [fe.submit(s, t) for s, t in reps]
        res2 = [f.result(30) for f in futs2]
    finally:
        fe.stop()
    assert all(r.ok for r in res + res2)

    # golden: the campaign path over the same queries, grouped by owner
    cost = np.zeros(len(workload), np.int64)
    plen = np.zeros(len(workload), np.int64)
    fin = np.zeros(len(workload), bool)
    for wid, part in dc.group_queries(workload).items():
        mask = dc.worker_of(workload[:, 1]) == wid
        c, p, f, _ = dispatcher._engine_for(wid).answer(part, rconf)
        cost[mask], plen[mask], fin[mask] = c, p, f
    got = res + res2
    assert [r.cost for r in got] == cost.tolist()
    assert [r.plen for r in got] == plen.tolist()
    assert [r.finished for r in got] == fin.tolist()

    assert _counter("serve_cache_hits_total") - hits0 > 0
    assert any(r.cached for r in res2)
    fill1 = _hist("serve_batch_fill")
    n_batches = fill1["count"] - fill0["count"]
    assert n_batches > 0
    mean_fill = (fill1["sum"] - fill0["sum"]) / n_batches
    assert mean_fill > 1.0, f"micro-batcher never coalesced: {mean_fill}"


def test_overload_sheds_busy_immediately():
    """A full shard queue answers BUSY at once — the shed path must
    never hang the submitter behind a stuck shard."""
    dc = DistributionController("mod", 1, 1, 64)
    release = threading.Event()

    def slow(wid, q, rconf, diff):
        release.wait(10)
        n = len(q)
        return (np.zeros(n, np.int64), np.zeros(n, np.int64),
                np.ones(n, bool))

    sconf = ServeConfig(queue_depth=4, max_batch=2, max_wait_ms=1.0,
                        cache_bytes=0)
    fe = ServingFrontend(dc, CallableDispatcher(slow), sconf=sconf)
    fe.start()
    busy0 = _counter("serve_shed_busy_total")
    try:
        futs = [fe.submit(i, i + 1) for i in range(12)]
        t0 = time.monotonic()
        shed = [f for f in futs if f.done()
                and f.result(0).status == BUSY]
        # depth 4 + at most one forming/in-flight batch: most of the 12
        # must have shed, and instantly (no queue wait, no dispatch)
        assert len(shed) >= 4
        assert time.monotonic() - t0 < 1.0
        assert _counter("serve_shed_busy_total") - busy0 == len(shed)
    finally:
        release.set()
        fe.stop()
    # the admitted ones still terminate (drained on release)
    assert all(f.done() for f in futs)


def test_open_breaker_sheds_unavailable():
    dc = DistributionController("mod", 1, 1, 64)

    def never(wid, q, rconf, diff):  # pragma: no cover - breaker sheds
        raise AssertionError("dispatch must not run")

    registry = resilience.BreakerRegistry(threshold=1, cooldown_s=60.0,
                                          enabled=True)
    registry.record(0, ok=False)               # force breaker OPEN
    fe = ServingFrontend(dc, CallableDispatcher(never),
                         sconf=ServeConfig(cache_bytes=0),
                         registry=registry)
    fe.start()
    try:
        res = fe.query(1, 2, timeout=5)
        assert res.status == UNAVAILABLE and res.detail == "circuit-open"
    finally:
        fe.stop()
        registry.shutdown()


def test_dispatch_failure_records_breaker_and_errors():
    dc = DistributionController("mod", 1, 1, 64)

    def broken(wid, q, rconf, diff):
        raise RuntimeError("shard down")

    registry = resilience.BreakerRegistry(threshold=2, cooldown_s=60.0,
                                          enabled=True)
    fe = ServingFrontend(dc, CallableDispatcher(broken),
                         sconf=ServeConfig(max_wait_ms=1.0,
                                           cache_bytes=0),
                         registry=registry)
    fe.start()
    try:
        r1 = fe.query(1, 2, timeout=10)
        assert r1.status == "ERROR" and "shard down" in r1.detail
        r2 = fe.query(3, 4, timeout=10)
        assert r2.status == "ERROR"
        # two failed batches tripped the breaker: now shed, not dispatch
        r3 = fe.query(5, 6, timeout=10)
        assert r3.status == UNAVAILABLE
    finally:
        fe.stop()
        registry.shutdown()


def test_deadline_expires_queued_requests():
    dc = DistributionController("mod", 1, 1, 64)
    release = threading.Event()
    dispatched = []

    def gated(wid, q, rconf, diff):
        dispatched.append(np.array(q))
        release.wait(10)
        n = len(q)
        return (np.zeros(n, np.int64), np.zeros(n, np.int64),
                np.ones(n, bool))

    sconf = ServeConfig(max_batch=2, max_wait_ms=1.0, deadline_ms=200.0,
                        cache_bytes=0)
    fe = ServingFrontend(dc, CallableDispatcher(gated), sconf=sconf)
    fe.start()
    try:
        f1 = fe.submit(1, 2)                 # heads straight into flight
        for _ in range(100):
            if dispatched:
                break
            time.sleep(0.01)
        f2 = fe.submit(3, 4)                 # queues behind the gate
        time.sleep(0.4)                      # > deadline_ms
        release.set()
        assert f1.result(10).ok
        assert f2.result(10).status == TIMEOUT
    finally:
        release.set()
        fe.stop()


def test_diff_change_invalidates_cache(serve_world):
    conf, g, dc, queries = serve_world
    fe = ServingFrontend(dc, EngineDispatcher(conf, graph=g, dc=dc),
                         sconf=ServeConfig(max_wait_ms=1.0), diff="-")
    fe.start()
    try:
        s, t = map(int, queries[0])
        free = fe.query(s, t, timeout=30)
        assert free.ok
        assert fe.query(s, t, timeout=30).cached
        fe.set_diff(conf.diffs[1])
        perturbed = fe.query(s, t, timeout=30)
        assert perturbed.ok and not perturbed.cached
        # costs accumulate on perturbed weights (>= free flow)
        assert perturbed.cost >= free.cost
    finally:
        fe.stop()


# ------------------------------------------------------ wire: fifo path

def test_fifo_dispatcher_roundtrips_results(serve_world, tmp_path):
    """The host-backend dispatch: a resident FifoServer answers the
    stats line AND the per-query `.results` sidecar; answers match the
    in-process engines."""
    conf, g, dc, queries = serve_world
    fifo = str(tmp_path / "serve-worker1.fifo")
    server = FifoServer(conf, 1, command_fifo=fifo)
    th = threading.Thread(target=server.serve_forever, daemon=True)
    th.start()
    for _ in range(100):
        if os.path.exists(fifo):
            break
        time.sleep(0.02)
    else:
        pytest.fail("server fifo never appeared")
    try:
        import distributed_oracle_search_tpu.serving.dispatch as disp

        mine = queries[dc.worker_of(queries[:, 1]) == 1][:8]
        fd = FifoDispatcher(conf, timeout=60.0)
        orig = disp.command_fifo_path
        disp.command_fifo_path = lambda wid: fifo
        try:
            cost, plen, fin = fd.answer_batch(1, mine, RuntimeConfig(),
                                              "-")
        finally:
            disp.command_fifo_path = orig
        c2, p2, f2, _ = server.engine.answer(mine, RuntimeConfig())
        assert (cost == c2).all() and (plen == p2).all()
        assert (fin == f2).all()
    finally:
        stop_server(fifo)
        th.join(timeout=10)


# --------------------------------------------------------- line protocol

def test_line_protocol_stream(serve_world):
    conf, g, dc, queries = serve_world
    fe = ServingFrontend(dc, EngineDispatcher(conf, graph=g, dc=dc),
                         sconf=ServeConfig(max_wait_ms=5.0))
    fe.start()
    try:
        s0, t0 = map(int, queries[0])
        s1, t1 = map(int, queries[1])
        rfile = io.StringIO(
            f"{s0} {t0}\n"
            "# a comment\n"
            "\n"
            f"{s1} {t1}\n"
            "not a query\n"
            f"{s0} {t0}\n"
            "quit\n"
            f"{s1} {t1}\n")          # after quit: ignored
        wfile = io.StringIO()
        n = ingress.serve_stream(fe, rfile, wfile)
    finally:
        fe.stop()
    assert n == 3
    lines = wfile.getvalue().strip().splitlines()
    assert len(lines) == 4                    # 3 queries + 1 malformed
    assert lines[0].startswith(f"OK {s0} {t0} ")
    assert lines[1].startswith(f"OK {s1} {t1} ")
    assert lines[2].startswith("ERROR -1 -1 malformed-line")
    # the repeat answers identically whether it was batched with the
    # first ask (engine dedup) or served from the cache
    assert lines[3].split()[:6] == lines[0].split()[:6]


# ---------------------------------------------------- slow: poisson drill

@pytest.mark.slow
def test_poisson_open_loop_latency_drill(serve_world):
    """Open-loop Poisson load against the in-process shards: every
    request terminates, tail latency is measurable, the batcher
    coalesces under pressure, and sheds (if any) are explicit."""
    conf, g, dc, queries = serve_world
    dispatcher = EngineDispatcher(conf, graph=g, dc=dc)
    rconf = RuntimeConfig()
    # warm every power-of-two program the load can hit, off the clock
    # (XLA compiles mid-drill would back the queue up past any deadline)
    for wid in range(dc.maxworker):
        own = dc.owned(wid)
        for b in (1, 2, 4, 8, 16, 32, 64):
            t = np.resize(own, b)
            s = (t + np.arange(b) + 1) % g.n     # distinct (s, t) pairs
            dispatcher.answer_batch(
                wid, np.stack([s, t], axis=1), rconf, "-")
    fe = ServingFrontend(dc, dispatcher,
                         sconf=ServeConfig(max_batch=64, max_wait_ms=2.0,
                                           queue_depth=512,
                                           deadline_ms=60_000.0))
    fe.start()
    try:
        rng = np.random.default_rng(11)
        n = 2000
        pool = queries[rng.zipf(1.4, size=n).clip(1, len(queries)) - 1]
        gaps = rng.exponential(1.0 / 4000.0, size=n)   # ~4k rps offered
        t0 = time.monotonic()
        arrivals = t0 + np.cumsum(gaps)
        futs = []
        for (s, t), at in zip(pool, arrivals):
            now = time.monotonic()
            if at > now:
                time.sleep(at - now)
            futs.append(fe.submit(int(s), int(t)))
        res = [f.result(60) for f in futs]
        lat = np.array([r.t_done for r in res]) - arrivals
        assert all(r.status in (OK, BUSY) for r in res)
        n_ok = sum(r.ok for r in res)
        assert n_ok > 0.5 * n
        p99 = float(np.percentile(lat[[r.ok for r in res]], 99))
        assert 0 < p99 < 60.0
        fill = _hist("serve_batch_fill")
        assert fill["sum"] / max(fill["count"], 1) > 1.0
    finally:
        fe.stop()
