"""A*-family: optimality, telemetry, engine wiring, native parity.

The hscale/fscale weighted-A* family implied by the reference's knobs
(reference ``args.py:30-57``) with the priority-queue counter vocabulary of
its response schema (``process_query.py:198-213``).
"""

import os
import shutil
import subprocess
import time

import numpy as np
import pytest

from distributed_oracle_search_tpu.cli import process_query as pq
from distributed_oracle_search_tpu.cli.args import parse_args
from distributed_oracle_search_tpu.data import (
    Graph, ensure_synth_dataset, read_scen, synth_scenario,
)
from distributed_oracle_search_tpu.models import (
    AstarStats, astar, dijkstra, min_cost_per_unit,
)
from distributed_oracle_search_tpu.models.reference import dist_to_target


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    datadir = str(tmp_path_factory.mktemp("adata"))
    return ensure_synth_dataset(datadir, width=9, height=7, n_queries=48,
                                seed=41)


@pytest.fixture(scope="module")
def graph(dataset):
    return Graph.from_xy(dataset["xy"])


def test_astar_optimal_at_hscale_1(graph):
    """hscale=1 euclidean×min-cost-per-unit is admissible -> optimal."""
    qs = synth_scenario(graph.n, 40, seed=42)
    for s, t in qs:
        cost, plen, fin = astar(graph, int(s), int(t))
        assert fin
        assert cost == dijkstra(graph, int(s))[int(t)]
        assert plen > 0


def test_astar_counters_live(graph):
    st = AstarStats()
    astar(graph, 0, graph.n - 1, stats=st)
    assert st.n_expanded > 0
    assert st.n_inserted > st.n_expanded * 0  # pushes happened
    assert st.n_touched >= st.n_expanded      # every expansion touches edges
    assert st.finished == 1


def test_astar_hscale_inflation_reduces_expansions(graph):
    s, t = 0, graph.n - 1
    st1, st3 = AstarStats(), AstarStats()
    c1, _, _ = astar(graph, s, t, hscale=1.0, stats=st1)
    c3, _, _ = astar(graph, s, t, hscale=3.0, stats=st3)
    assert st3.n_expanded <= st1.n_expanded   # greedier -> fewer pops
    assert c3 >= c1                           # possibly suboptimal


def test_astar_diffed_weights(graph, dataset):
    from distributed_oracle_search_tpu.data import read_diff
    w = graph.weights_with_diff(read_diff(dataset["diff"]))
    s, t = 1, graph.n - 2
    cost, _, fin = astar(graph, s, t, w)
    assert fin
    assert cost == dijkstra(graph, s, w)[t]


def test_min_cost_per_unit_admissible(graph):
    cpu = min_cost_per_unit(graph)
    assert cpu > 0
    dx = graph.xs[graph.src] - graph.xs[graph.dst]
    dy = graph.ys[graph.src] - graph.ys[graph.dst]
    assert (graph.w >= cpu * np.hypot(dx, dy) - 1e-6).all()


def test_shard_engine_astar(dataset, graph, tmp_path):
    """ShardEngine(alg=astar): optimal costs + full counters on the wire
    row; no CPD shard required."""
    from distributed_oracle_search_tpu.parallel.partition import (
        DistributionController,
    )
    from distributed_oracle_search_tpu.worker import ShardEngine

    dc = DistributionController("mod", 1, 1, graph.n)
    eng = ShardEngine(graph, dc, wid=0, outdir=str(tmp_path), alg="astar")
    queries = read_scen(dataset["scen"])[:12]
    args = parse_args(["--h-scale", "1.0"])
    cost, plen, fin, stats = eng.answer(queries, pq.runtime_config(args))
    assert fin.all() and stats.finished == len(queries)
    assert stats.n_expanded > 0 and stats.n_inserted > 0
    for (s, t), c in zip(queries, cost):
        assert c == dist_to_target(graph, int(t))[int(s)]


@pytest.mark.skipif(shutil.which("g++") is None, reason="no C++ toolchain")
def test_native_astar_counter_parity(dataset, graph, tmp_path):
    """Native --alg astar and the Python A* agree on finished counts and
    produce comparable telemetry on the same batch."""
    from distributed_oracle_search_tpu.transport.fifo import send
    from distributed_oracle_search_tpu.transport.wire import (
        Request, RuntimeConfig, write_query_file,
    )

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    subprocess.run(["make", "-C", os.path.join(repo, "native"), "fast",
                    "-j4"], check=True, capture_output=True)
    fifo_auto = os.path.join(repo, "native", "build", "fast", "bin",
                             "fifo_auto")
    fifo = str(tmp_path / "na.fifo")
    proc = subprocess.Popen(
        [fifo_auto, "--input", dataset["xy"], "--partmethod", "mod",
         "--partkey", "1", "--workerid", "0", "--maxworker", "1",
         "--outdir", str(tmp_path), "--alg", "astar", "--fifo", fifo],
        stderr=subprocess.DEVNULL)
    try:
        deadline = time.time() + 15
        while not os.path.exists(fifo):
            assert time.time() < deadline
            time.sleep(0.05)
        queries = read_scen(dataset["scen"])[:12]
        qfile = str(tmp_path / "q")
        write_query_file(qfile, queries)
        req = Request(RuntimeConfig(hscale=1.0), qfile,
                      str(tmp_path / "a.fifo"))
        row = send("localhost", req, fifo, timeout=60)
        assert row.ok and row.finished == len(queries)

        # python side, same batch
        from distributed_oracle_search_tpu.models import AstarStats
        st = AstarStats()
        for s, t in queries:
            astar(graph, int(s), int(t), stats=st)
        assert st.finished == row.finished
        assert st.plen == row.plen       # both optimal & same tie landscape
    finally:
        with open(fifo, "w") as fh:
            fh.write("__DOS_STOP__\n")
        proc.wait(timeout=10)


def test_astar_fscale_correct_under_inflation(graph):
    """fscale prunes only pops beyond (1+fscale)x the incumbent — results
    stay finished and no worse than the unpruned inflated search."""
    qs = synth_scenario(graph.n, 20, seed=44)
    for s, t in qs:
        c_plain, _, f_plain = astar(graph, int(s), int(t), hscale=3.0)
        c_pruned, _, f_pruned = astar(graph, int(s), int(t), hscale=3.0,
                                      fscale=0.1)
        assert f_plain and f_pruned
        opt = dijkstra(graph, int(s))[int(t)]
        assert c_pruned >= opt
        # pruning cannot make the answer worse than the admissible bound
        assert c_pruned <= (1.0 + 0.1) * c_plain + 1
