"""Compressed-residency parity suite (DOS_CPD_RESIDENT, models.resident).

The compressed-resident CPD tier must be invisible in the answers:
every codec (pack4 / rle / auto) must produce BIT-identical results to
the raw-resident engine across both walk kernels (XLA and the Pallas
kernel's decompress-on-tile path in interpret mode), every mesh lane
count, diffed weights, and the awkward queries (s==t, duplicates,
unreachable); on disk the codec containers must ride the ordinary
digest/ledger/verify/heal/delta machinery unchanged. Degrades (codec
not viable) book a counter and serve raw — never a fault.
"""

import glob
import json
import os
import time

import jax
import numpy as np
import pytest

from distributed_oracle_search_tpu.data import (
    synth_diff, synth_scenario,
)
from distributed_oracle_search_tpu.data.formats import write_diff
from distributed_oracle_search_tpu.data.graph import Graph
from distributed_oracle_search_tpu.models import resident
from distributed_oracle_search_tpu.models.cpd import (
    CPDOracle, build_worker_shard, delta_build_index, read_manifest,
    verify_exit_code, verify_index, write_index_manifest,
)
from distributed_oracle_search_tpu.obs import fleet
from distributed_oracle_search_tpu.obs import metrics as obs_metrics
from distributed_oracle_search_tpu.parallel.partition import (
    DistributionController,
)
from distributed_oracle_search_tpu.transport.wire import RuntimeConfig
from distributed_oracle_search_tpu.utils.atomicio import (
    sweep_stale_artifacts,
)
from distributed_oracle_search_tpu.worker.engine import (
    ShardEngine, load_shard_rows,
)

pytestmark = pytest.mark.compressed

CODECS = ("pack4", "rle", "auto")


def _counter(name: str) -> int:
    return int(obs_metrics.REGISTRY.snapshot()["counters"].get(name, 0))


def _structured_fm(r: int = 600, n: int = 300, seed: int = 0):
    """A run-coherent [r, n] int8 table (the target-axis coherence real
    CPD shards have) with slots 0..5 and -1 holes."""
    rng = np.random.default_rng(seed)
    base = rng.integers(-1, 6, size=(1, n), dtype=np.int64)
    fm = np.repeat(base, r, axis=0).astype(np.int8)
    flip = rng.random(fm.shape) < 0.03
    fm[flip] = rng.integers(-1, 6, size=int(flip.sum()))
    return fm


# ------------------------------------------------------ codec units

def test_resident_choice_knob(monkeypatch):
    monkeypatch.delenv("DOS_CPD_RESIDENT", raising=False)
    assert resident.resident_choice() == "raw"
    for raw, want in (("rle", "rle"), ("PACK4", "pack4"),
                      ("auto", "auto"), ("bogus", "raw"), ("", "raw")):
        monkeypatch.setenv("DOS_CPD_RESIDENT", raw)
        assert resident.resident_choice() == want, raw


def test_rle_group_knob(monkeypatch):
    monkeypatch.delenv("DOS_CPD_RLE_GROUP", raising=False)
    assert resident.rle_group_rows() == resident._RLE_GROUP_DEFAULT
    monkeypatch.setenv("DOS_CPD_RLE_GROUP", "128")
    assert resident.rle_group_rows() == 128
    for bad in ("0", "1", "999999", "nope"):
        monkeypatch.setenv("DOS_CPD_RLE_GROUP", bad)
        assert resident.rle_group_rows() == resident._RLE_GROUP_DEFAULT


def test_pack4_roundtrip_and_escape_refusal():
    fm = _structured_fm()
    packed = resident.encode_pack4(fm)
    assert packed is not None
    tbl = resident.CompressedFM("pack4", fm.shape, {"packed": packed})
    rows = np.r_[0:7, 593:600, 41]
    got = np.asarray(tbl.decompress_rows(np.asarray(rows, np.int32)))
    np.testing.assert_array_equal(got, fm[rows])
    # a single slot >= 14 (the wire format's escape regime) refuses —
    # the resident codec has no scatter pass to apply escapes with
    esc = fm.copy()
    esc[3, 5] = 14
    assert resident.encode_pack4(esc) is None


@pytest.mark.parametrize("group", (64, 100, 4096))
def test_rle_roundtrip_groups(group):
    """Multi-group, partial-last-group, odd-width tables all decode
    bit-identically (device search decode AND host container decode)."""
    fm = _structured_fm(r=597, n=299, seed=2)
    enc = resident.encode_rle(fm, group=group)
    assert enc is not None
    starts, vals, offsets, g = enc
    tbl = resident.CompressedFM(
        "rle", fm.shape,
        {"starts": starts, "vals": vals, "offsets": offsets},
        group=g, steps=resident._rle_steps(offsets))
    got = np.asarray(tbl.decompress_rows(
        np.arange(fm.shape[0], dtype=np.int32)))
    np.testing.assert_array_equal(got, fm)
    # arbitrary (repeating) row subsets too — the batch shape
    rows = np.array([0, 0, 17, 596, 64, 63, 100, 596], np.int32)
    np.testing.assert_array_equal(
        np.asarray(tbl.decompress_rows(rows)), fm[rows])


def test_rle_incompressible_returns_none():
    rng = np.random.default_rng(1)
    junk = rng.integers(-1, 14, size=(128, 129)).astype(np.int8)
    assert resident.encode_rle(junk) is None
    assert resident.encode_block(junk, "rle") is None


def test_make_resident_degrade_books_counter():
    """A requested codec that is not viable serves raw and books the
    degrade counter — never a fault."""
    rng = np.random.default_rng(1)
    junk = rng.integers(-1, 30, size=(128, 129)).astype(np.int8)
    before = _counter("cpd_resident_degraded_total")
    tbl, used = resident.make_resident(junk, codec="auto")
    assert used == "raw"
    assert _counter("cpd_resident_degraded_total") == before + 1
    np.testing.assert_array_equal(np.asarray(tbl), junk)


def test_container_roundtrip_and_torn_payloads():
    fm = _structured_fm()
    for codec in ("rle", "pack4"):
        payload, used = resident.encode_block(fm, codec)
        assert used == codec
        assert resident.is_container(payload)
        assert resident.block_codec(payload) == codec
        np.testing.assert_array_equal(
            resident.decode_block_rows(payload), fm)
        assert payload.nbytes < fm.nbytes
    # raw blocks pass through untouched
    assert not resident.is_container(fm)
    np.testing.assert_array_equal(resident.maybe_decode_rows(fm), fm)
    # a truncated container raises ValueError (callers book corrupt)
    payload, _ = resident.encode_block(fm, "rle")
    with pytest.raises(ValueError):
        resident.decode_block_rows(payload[:len(payload) // 2])
    # a foreign uint8 array is not a container
    assert not resident.is_container(
        np.zeros(64, np.uint8))


def test_pallas_fits_accounts_compressed_tile(monkeypatch):
    """The VMEM-fit check models the pack4 working set honestly: the
    staged tile HALVES (nibble rows — the HBM-traffic win) but the
    on-chip unpack holds an extra int32 temp, so the pack4 working set
    is strictly LARGER than raw's — a budget between the two admits
    raw and degrades pack4, naming the codec in the reason."""
    from distributed_oracle_search_tpu.ops.pallas_walk import (
        pallas_walk_fits,
    )

    n, k, m, q = 40_000, 4, 120_000, 4096
    # this shape needs ~238 MB raw / ~355 MB pack4 (qb=1024 buckets);
    # a budget between the two separates the codecs
    monkeypatch.setenv("DOS_WALK_VMEM_MB", "300")
    ok_raw, _ = pallas_walk_fits(n, k, m, q, codec="raw")
    ok_p4, why_p4 = pallas_walk_fits(n, k, m, q, codec="pack4")
    assert ok_raw
    assert not ok_p4 and "pack4" in why_p4 and "VMEM budget" in why_p4
    monkeypatch.setenv("DOS_WALK_VMEM_MB", "100")
    ok_raw, why_raw = pallas_walk_fits(n, k, m, q, codec="raw")
    assert not ok_raw and "VMEM budget" in why_raw


# ----------------------------------------------------- engine parity

@pytest.fixture(scope="module")
def dc1(toy_graph):
    # small blocks: the disk suite needs MULTI-block indexes so one
    # corrupt container degrades (exit 3) instead of killing the index
    return DistributionController("tpu", None, 1, toy_graph.n,
                                  block_size=16)


@pytest.fixture(scope="module")
def shard_dir(toy_graph, dc1, tmp_path_factory):
    d = str(tmp_path_factory.mktemp("comp-shard"))
    build_worker_shard(toy_graph, dc1, 0, d, chunk=16)
    write_index_manifest(d, dc1)
    return d


@pytest.fixture(scope="module")
def diff_file(toy_graph, tmp_path_factory):
    d = tmp_path_factory.mktemp("comp-diff")
    path = str(d / "t.diff")
    write_diff(path, *synth_diff(toy_graph, frac=0.3, seed=3))
    return path


@pytest.fixture(scope="module")
def walk_queries(toy_graph, toy_queries):
    """Scenario plus the awkward rows: zero-length (s==t) and
    duplicate pairs — dedup/unsort must survive the row remap."""
    q = np.asarray(toy_queries, np.int64)
    extra = np.array([[3, 3], [0, 0], q[0].tolist(), q[0].tolist(),
                      q[5].tolist()], np.int64)
    return np.concatenate([q, extra], axis=0)


@pytest.fixture(scope="module")
def baseline(toy_graph, dc1, shard_dir, walk_queries, diff_file):
    """Raw-resident engine answers: free-flow and diffed."""
    eng = ShardEngine(toy_graph, dc1, 0, shard_dir)
    assert eng.resident_codec == "raw"    # conftest pins the knob
    rc = RuntimeConfig()
    free = eng.answer(walk_queries, rc)[:3]
    diffed = eng.answer(walk_queries, rc, diff_file)[:3]
    return free, diffed


def _codec_engine(monkeypatch, codec, *args, **kwargs):
    monkeypatch.setenv("DOS_CPD_RESIDENT", codec)
    eng = ShardEngine(*args, **kwargs)
    if codec in ("pack4", "rle"):
        # both codecs are viable on the toy shard; the engine must not
        # have silently degraded or the parity below proves nothing
        assert eng.resident_codec == codec
        assert 0 < eng.resident_bytes < eng.fm.shape[0] * eng.fm.shape[1]
    return eng


@pytest.mark.parametrize("codec", CODECS)
@pytest.mark.parametrize("kernel", ("xla", "pallas"))
def test_walk_parity(monkeypatch, toy_graph, dc1, shard_dir,
                     walk_queries, diff_file, baseline, codec, kernel):
    """Compressed residency bit-identical to raw: free-flow AND
    diffed, duplicates and s==t included, both walk kernels (pallas in
    interpret mode — pack4 exercises decompress-on-tile)."""
    monkeypatch.setenv("DOS_WALK_KERNEL", kernel)
    eng = _codec_engine(monkeypatch, codec, toy_graph, dc1, 0,
                        shard_dir)
    rc = RuntimeConfig()
    before = _counter("walk_compressed_batches_total")
    for want, diff in zip(baseline, ("-", diff_file)):
        got = eng.answer(walk_queries, rc, diff)[:3]
        for a, b in zip(want, got):
            np.testing.assert_array_equal(a, b)
    assert _counter("walk_compressed_batches_total") == before + 2


@pytest.mark.parametrize("lanes", (1, 2, 4, 8))
def test_walk_parity_mesh_lanes(monkeypatch, toy_graph, dc1, shard_dir,
                                walk_queries, diff_file, baseline,
                                lanes):
    """Every mesh lane count serves from compressed residency through
    the XLA decompress path, bit-identically."""
    monkeypatch.setenv("DOS_MESH_DEVICES", str(lanes))
    eng = _codec_engine(monkeypatch, "rle", toy_graph, dc1, 0,
                        shard_dir)
    assert eng.n_lanes == lanes
    rc = RuntimeConfig()
    for want, diff in zip(baseline, ("-", diff_file)):
        got = eng.answer(walk_queries, rc, diff)[:3]
        for a, b in zip(want, got):
            np.testing.assert_array_equal(a, b)


def test_walk_parity_unreachable(monkeypatch, tmp_path):
    """Unreachable targets (-1 rows on a disconnected graph) decode
    and answer identically to raw."""
    # two disconnected 2-cliques: 0-1 and 2-3
    src = np.array([0, 1, 2, 3])
    dst = np.array([1, 0, 3, 2])
    w = np.array([5, 5, 7, 7])
    xs = np.array([0, 1, 10, 11])
    ys = np.zeros(4, np.int64)
    g = Graph(xs, ys, src, dst, w)
    dc = DistributionController("tpu", None, 1, g.n)
    d = str(tmp_path / "disc")
    build_worker_shard(g, dc, 0, d, chunk=4)
    q = np.array([[0, 1], [0, 3], [2, 1], [3, 2], [1, 1]], np.int64)
    rc = RuntimeConfig()
    monkeypatch.delenv("DOS_CPD_RESIDENT", raising=False)
    want = ShardEngine(g, dc, 0, d).answer(q, rc)[:3]
    assert not np.asarray(want[2])[[1, 2]].any()   # cross-clique fails
    eng = _codec_engine(monkeypatch, "pack4", g, dc, 0, d)
    for a, b in zip(want, eng.answer(q, rc)[:3]):
        np.testing.assert_array_equal(a, b)


def test_chunked_deadline_under_compression(monkeypatch, toy_graph, dc1,
                                            shard_dir, walk_queries,
                                            diff_file):
    """The ns-budget chunked path slices the remapped rows into the
    SAME decompressed block; a generous budget answers everything,
    bit-identical to raw."""
    base = ShardEngine(toy_graph, dc1, 0, shard_dir)
    eng = _codec_engine(monkeypatch, "rle", toy_graph, dc1, 0,
                        shard_dir)
    base.astar_chunk = eng.astar_chunk = 16       # force chunking
    rc = RuntimeConfig(time=10**13)
    for a, b in zip(base.answer(walk_queries, rc, diff_file)[:3],
                    eng.answer(walk_queries, rc, diff_file)[:3]):
        np.testing.assert_array_equal(a, b)


def test_extract_and_sig_under_compression(monkeypatch, toy_graph, dc1,
                                           shard_dir, walk_queries):
    """--extract prefixes and sig_k signatures extract from the
    decompressed rows, unchanged (pack4 too: extraction opts out of
    the on-tile path and decompresses)."""
    base = ShardEngine(toy_graph, dc1, 0, shard_dir)
    for codec in ("rle", "pack4"):
        eng = _codec_engine(monkeypatch, codec, toy_graph, dc1, 0,
                            shard_dir)
        for rc in (RuntimeConfig(extract=True, k_moves=6),
                   RuntimeConfig(sig_k=4)):
            for a, b in zip(base.answer(walk_queries, rc)[:3],
                            eng.answer(walk_queries, rc)[:3]):
                np.testing.assert_array_equal(a, b)
            np.testing.assert_array_equal(base.last_paths[0],
                                          eng.last_paths[0])
            np.testing.assert_array_equal(base.last_paths[1],
                                          eng.last_paths[1])


def test_replica_lane_placement(monkeypatch, toy_graph, dc1, shard_dir,
                                walk_queries, baseline):
    """A replica engine's COMPRESSED arrays pin to its mesh lane
    device (the PR 13 placement), answers unchanged."""
    monkeypatch.setenv("DOS_MESH_DEVICES", "4")
    monkeypatch.setenv("DOS_CPD_RESIDENT", "rle")
    eng = ShardEngine(toy_graph, dc1, 0, shard_dir, replica=2)
    assert eng.resident_codec == "rle"
    for arr in eng.fm.arrays.values():
        assert set(arr.devices()) == {jax.devices()[2 % 4]}
    rc = RuntimeConfig()
    for a, b in zip(baseline[0], eng.answer(walk_queries, rc)[:3]):
        np.testing.assert_array_equal(a, b)


def test_decompress_metrics_move(monkeypatch, toy_graph, dc1, shard_dir,
                                 walk_queries):
    """cpd_decompress_seconds observes per batch; the resident gauge
    reports the compressed bytes; raw engines move neither."""
    def _snap():
        s = obs_metrics.REGISTRY.snapshot()
        return (s["histograms"].get("cpd_decompress_seconds",
                                    {}).get("count", 0),
                s["gauges"].get("cpd_resident_bytes", 0))

    eng = _codec_engine(monkeypatch, "rle", toy_graph, dc1, 0,
                        shard_dir)
    n0, gauge = _snap()
    assert gauge == eng.resident_bytes
    eng.answer(walk_queries, RuntimeConfig())
    assert _snap()[0] == n0 + 1
    monkeypatch.setenv("DOS_CPD_RESIDENT", "raw")
    raw_eng = ShardEngine(toy_graph, dc1, 0, shard_dir)
    n1, _ = _snap()
    raw_eng.answer(walk_queries, RuntimeConfig())
    assert _snap()[0] == n1


# ------------------------------------------------- on-disk containers

@pytest.fixture(scope="module")
def comp_index(toy_graph, dc1, tmp_path_factory):
    """A pack4-compressed on-disk index (pack4 is always viable on the
    toy shard; rle legitimately degrades at this tiny scale)."""
    d = str(tmp_path_factory.mktemp("comp-disk"))
    build_worker_shard(toy_graph, dc1, 0, d, chunk=16, codec="pack4")
    write_index_manifest(d, dc1)
    return d


def test_compressed_index_manifest_and_bytes(toy_graph, dc1, shard_dir,
                                             comp_index):
    man = read_manifest(comp_index)
    assert all(m.get("codec") == "pack4"
               for m in man["blocks"].values())
    for f in man["files"]:
        raw = np.load(os.path.join(shard_dir, f))
        comp = np.load(os.path.join(comp_index, f))
        assert resident.is_container(comp)
        assert comp.nbytes < raw.nbytes
        np.testing.assert_array_equal(
            resident.decode_block_rows(comp), raw)


def test_verify_checks_compressed_blocks(toy_graph, dc1, comp_index):
    rep = verify_index(comp_index, dc1)
    assert verify_exit_code(rep) == 0 and rep["ok"] == rep["total"]
    # a codec/manifest mismatch is corrupt even when the digest is
    # refreshed to match: swap a raw payload in and re-digest
    man = read_manifest(comp_index)
    f0 = man["files"][0]
    from distributed_oracle_search_tpu.models.cpd import check_block

    status, reason = check_block(
        os.path.join(comp_index, f0), {"codec": "rle"})
    assert status == "corrupt" and "codec" in reason


def test_compressed_index_serves_and_heals(monkeypatch, toy_graph, dc1,
                                           shard_dir, comp_index,
                                           walk_queries, baseline,
                                           tmp_path):
    """Engine + oracle load the compressed index transparently; a torn
    container is quarantined and healed back COMPRESSED (the manifest
    owns the block's codec, not the process env)."""
    monkeypatch.delenv("DOS_CPD_RESIDENT", raising=False)
    eng = ShardEngine(toy_graph, dc1, 0, comp_index)
    rc = RuntimeConfig()
    for a, b in zip(baseline[0], eng.answer(walk_queries, rc)[:3]):
        np.testing.assert_array_equal(a, b)
    CPDOracle(toy_graph, dc1).load(comp_index)
    # tear one container mid-payload
    man = read_manifest(comp_index)
    f0 = man["files"][0]
    p0 = os.path.join(comp_index, f0)
    data = open(p0, "rb").read()
    with open(p0, "wb") as f:
        f.write(data[:len(data) // 2])
    assert verify_exit_code(verify_index(comp_index, dc1)) == 3
    before = _counter("cpd_blocks_rebuilt_total")
    rows = load_shard_rows(comp_index, 0, dc=dc1, graph=toy_graph)
    assert _counter("cpd_blocks_rebuilt_total") == before + 1
    np.testing.assert_array_equal(
        rows, load_shard_rows(shard_dir, 0))
    assert verify_exit_code(verify_index(comp_index, dc1)) == 0
    assert resident.is_container(np.load(p0))
    assert read_manifest(comp_index)["blocks"][f0].get(
        "codec") == "pack4"


def test_replica_copy_ships_container(toy_graph, comp_index):
    """copy_replica_blocks moves the compressed container verbatim —
    the smaller anti-entropy/catch-up payload the membership plane
    wants — and journals its codec."""
    from distributed_oracle_search_tpu.models.cpd import (
        BuildLedger, copy_replica_blocks, shard_block_name,
    )

    dcr = DistributionController("tpu", None, 1, toy_graph.n,
                                 replication=1)
    copy_replica_blocks(dcr, 0, 1, comp_index)
    prim = np.load(os.path.join(comp_index, shard_block_name(0, 0)))
    rep = np.load(os.path.join(comp_index,
                               shard_block_name(0, 0, 1)))
    np.testing.assert_array_equal(np.asarray(prim), np.asarray(rep))
    assert resident.is_container(rep)
    ent = BuildLedger(comp_index, 0, 1).entries()[
        shard_block_name(0, 0, 1)]
    assert ent.get("codec") == "pack4"


def test_encode_block_auto_picks_smaller():
    """On-disk `auto` applies the SAME pick-smaller rule as
    make_resident: short-run tables where pack4 beats rle must not
    persist the larger rle payload (review regression)."""
    rng = np.random.default_rng(7)
    base = rng.integers(-1, 6, size=(1, 64), dtype=np.int64)
    fm = np.repeat(base, 1200, axis=0).astype(np.int8)
    flip = rng.random(fm.shape) < 0.12          # run length ~4-5
    fm[flip] = rng.integers(-1, 6, size=int(flip.sum()))
    rle_enc = resident.encode_rle(fm)
    p4 = resident.encode_pack4(fm)
    assert rle_enc is not None and p4 is not None
    assert sum(a.nbytes for a in rle_enc[:3]) > p4.nbytes
    payload, used = resident.encode_block(fm, "auto")
    assert used == "pack4"
    _, resident_used = resident.make_resident(fm, codec="auto")
    assert resident_used == used


def test_streamed_decoded_cache_is_bounded(toy_graph, tmp_path):
    """Decoded compressed blocks live in a small LRU, not the
    unbounded handle cache — streamed serving of a compressed index
    must keep its bounded-working-set contract (review regression)."""
    from distributed_oracle_search_tpu.models.streamed import (
        StreamedCPDOracle,
    )

    dcs = DistributionController("tpu", None, 1, toy_graph.n,
                                 block_size=8)
    d = str(tmp_path / "sm")
    build_worker_shard(toy_graph, dcs, 0, d, chunk=8, codec="pack4")
    write_index_manifest(d, dcs)
    st = StreamedCPDOracle(toy_graph, dcs, d, row_chunk=8,
                           cache_bytes=0)
    n_blocks = -(-dcs.n_owned(0) // dcs.block_size)
    assert n_blocks > st._DECODED_KEEP          # the bound can bite
    for bid in range(n_blocks):
        blk = st._block(0, bid)
        assert blk.dtype == np.int8 and blk.ndim == 2
    assert len(st._decoded) == st._DECODED_KEEP
    # recency refresh: a cached block re-touched stays resident
    st._block(0, n_blocks - 1)
    assert (0, n_blocks - 1) in st._decoded


def test_replica_recompute_keeps_primary_codec(toy_graph, tmp_path):
    """A replica recomputed from the graph (primary unreachable —
    separate filesystems) uses the PRIMARY's codec, so its digest can
    converge with the anti-entropy cross-check (review regression)."""
    from distributed_oracle_search_tpu.models.cpd import (
        _primary_codec, build_replica_shards, shard_block_name,
    )

    dcr = DistributionController("tpu", None, 2, toy_graph.n,
                                 replication=2)
    d = str(tmp_path / "repl")
    for wid in range(2):
        build_worker_shard(toy_graph, dcr, wid, d, chunk=16,
                           codec="pack4")
    assert _primary_codec(d, 0) == "pack4"
    # make shard 0's primary unreachable (its ledger survives: that is
    # what records the codec a recompute must match)
    for p in glob.glob(os.path.join(d, "cpd-w00000-b*.npy")):
        os.remove(p)
    build_replica_shards(toy_graph, dcr, 1, d, chunk=16)
    rep = np.load(os.path.join(d, shard_block_name(0, 0, 1)))
    assert resident.is_container(rep)
    assert resident.block_codec(rep) == "pack4"


def test_streamed_oracle_reads_compressed_blocks(toy_graph, dc1,
                                                 comp_index,
                                                 toy_queries):
    """The streamed serving path decodes container blocks on first
    touch — answers identical to the resident oracle's."""
    from distributed_oracle_search_tpu.models.streamed import (
        StreamedCPDOracle,
    )

    st = StreamedCPDOracle(toy_graph, dc1, comp_index, row_chunk=16,
                           cache_bytes=0)
    c, p, f = st.query(np.asarray(toy_queries, np.int64))
    oracle = CPDOracle(toy_graph, dc1).load(comp_index)
    c2, p2, f2 = oracle.query(np.asarray(toy_queries, np.int64))
    np.testing.assert_array_equal(c, c2)
    np.testing.assert_array_equal(p, p2)
    np.testing.assert_array_equal(f, f2)


# -------------------------------------------------- delta on compressed

@pytest.fixture(scope="module")
def delta_city(tmp_path_factory):
    """A 432-node city with a pack4-compressed index and a corner
    hotspot diff whose dirty cone leaves most rows clean."""
    from distributed_oracle_search_tpu.data import synth_city_graph

    g = synth_city_graph(24, 18, seed=3)
    dc = DistributionController("div", g.n, 1, g.n, block_size=64)
    d = str(tmp_path_factory.mktemp("comp-delta"))
    build_worker_shard(g, dc, 0, d, chunk=64, codec="pack4")
    write_index_manifest(d, dc)
    return g, dc, d


def test_delta_empty_copies_containers(delta_city):
    """An empty delta byte-copies every compressed block verbatim into
    the epoch index (codec journaled, digests cross-checked)."""
    g, dc, d = delta_city
    fused = os.path.join(d, "fused-e000001.diff")
    eid = np.array([0])
    write_diff(fused, g.src[eid], g.dst[eid],
               g.w[eid].astype(np.int64))      # same weight: no change
    rep = delta_build_index(g, dc, d, fused)
    assert rep["blocks_skipped"] == 7 and rep["rows_recomputed"] == 0
    man = read_manifest(d)
    for f in man["files"]:
        np.testing.assert_array_equal(
            np.load(os.path.join(rep["outdir"], f)),
            np.load(os.path.join(d, f)))
    eman = read_manifest(rep["outdir"])
    assert all(m.get("codec") == "pack4"
               for m in eman["blocks"].values())


def test_delta_splice_on_compressed_index(delta_city, tmp_path):
    """A real retime splices through decode -> row splice ->
    re-encode: the epoch index stays compressed and decodes
    bit-identical to a from-scratch RAW build on the retimed graph."""
    g, dc, d = delta_city
    cand = np.nonzero((g.src > g.n - 30) & (g.dst > g.n - 30))[0][:1]
    fused = os.path.join(d, "fused-e000002.diff")
    write_diff(fused, g.src[cand], g.dst[cand],
               g.w[cand].astype(np.int64) * 3)
    rep = delta_build_index(g, dc, d, fused)
    assert not rep["degraded_full"]
    assert 0 < rep["rows_recomputed"] < g.n
    g2 = Graph(g.xs, g.ys, g.src, g.dst, g.weights_with_diff(fused))
    full = str(tmp_path / "full")
    build_worker_shard(g2, dc, 0, full, chunk=64)           # raw
    for f in read_manifest(d)["files"]:
        ed = np.load(os.path.join(rep["outdir"], f))
        assert resident.is_container(ed), f
        np.testing.assert_array_equal(
            resident.decode_block_rows(ed),
            np.load(os.path.join(full, f)))
    assert verify_exit_code(verify_index(rep["outdir"])) == 0


# ------------------------------------------------------ debris sweep

def test_sweep_covers_compressed_debris(tmp_path):
    """Tmp debris of compressed block writes (and persisted rle
    sidecars) matches the existing stale-artifact sweep patterns."""
    d = str(tmp_path)
    old = time.time() - 120
    debris = [
        os.path.join(d, "cpd-w00000-b00003.npy.tmp.1234"),
        os.path.join(d, "rle-w00000-r000000000-c512.npz.77.tmp.npz"),
    ]
    keep = os.path.join(d, "cpd-w00000-b00003.npy")
    for p in debris + [keep]:
        with open(p, "wb") as f:
            f.write(b"x")
        os.utime(p, (old, old))
    swept = sweep_stale_artifacts(d)
    assert swept == 2
    assert not any(os.path.exists(p) for p in debris)
    assert os.path.exists(keep)


# ---------------------------------------------------- gates & registry

def test_bench_diff_compressed_directions():
    """The compressed key family's directions are explicit, pinned."""
    for key in ("cpd_resident_bytes_ratio",
                "compressed_walk_queries_per_sec",
                "compressed_raw_walk_queries_per_sec",
                "compressed_vs_raw_walk_ratio"):
        assert fleet._KEY_DIRECTIONS[key] == "higher", key
    assert fleet._KEY_DIRECTIONS[
        "compressed_decompress_seconds"] == "lower"
    assert fleet._KEY_TOLERANCES["cpd_resident_bytes_ratio"] == 0.15


def test_bench_diff_gates_compressed_regression(tmp_path):
    """End-to-end through compare_bench: a ratio drop and a decompress
    blow-up both gate."""
    def _rec(name, headline):
        p = tmp_path / name
        p.write_text(json.dumps(
            {"parsed": {"metric": "m", "value": 1.0,
                        "headline": headline}}))
        return str(p)

    old = _rec("BENCH_r01.json",
               {"cpd_resident_bytes_ratio": 8.0,
                "compressed_decompress_seconds": 0.01})
    new = _rec("BENCH_r02.json",
               {"cpd_resident_bytes_ratio": 4.0,
                "compressed_decompress_seconds": 0.05})
    out = fleet.compare_bench(old, new)
    bad = {e["key"] for e in out["regressions"]}
    assert bad == {"cpd_resident_bytes_ratio",
                   "compressed_decompress_seconds"}


def test_metrics_registered_in_obs_map():
    """New series documented in the obs/__init__ metric map (the
    dos-lint metric-registry contract)."""
    import distributed_oracle_search_tpu.obs as obs

    for name in ("cpd_resident_bytes", "cpd_resident_degraded_total",
                 "cpd_decompress_seconds",
                 "walk_compressed_batches_total"):
        assert name in obs.__doc__, name


def test_statusz_reports_resident(monkeypatch, toy_graph, dc1,
                                  shard_dir):
    """The worker statusz payload carries the resident codec + bytes
    (engine-side attributes the server copies)."""
    eng = _codec_engine(monkeypatch, "rle", toy_graph, dc1, 0,
                        shard_dir)
    assert eng.resident_codec == "rle"
    assert eng.resident_bytes == eng.fm.nbytes


def test_stale_crossref_fixed():
    """Satellite pin: ops/pallas_walk.py no longer points the loader
    seam at the pre-re-anchor 'ROADMAP item 3'."""
    import distributed_oracle_search_tpu.ops.pallas_walk as pw

    src = open(pw.__file__.rstrip("c")).read()
    assert "ROADMAP item 3" not in src
    assert "ROADMAP item 1" in src
