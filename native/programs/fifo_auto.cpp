// fifo_auto — resident query server (native).
//
// Role + protocol parity with reference C3 (SURVEY.md §2.2; launched at
// reference make_fifos.py:21): load the graph, the first diff, and this
// worker's CPD shard; create the command FIFO and block on it. Per
// request: parse the 2-line config (JSON knobs + "queryfile answerfifo
// difffile"), read the query file, answer every (s,t) by table-search
// (OpenMP over queries), write ONE CSV stats line to the answer FIFO.
// Stays resident across requests.
//
//   fifo_auto --input <xy> [<diff>] --partmethod M --partkey K...
//             --workerid W --maxworker N --outdir <idxdir>
//             --alg table-search|astar [--compress] [--fifo <path>]
//
// --alg astar serves the hscale/fscale weighted-A* family (the knobs the
// reference exposes, args.py:30-57) straight off the graph — no CPD
// needed — emitting the full priority-queue telemetry.
//
// Speaks the same wire as the Python worker/server.py, including the
// __DOS_STOP__ shutdown token and the FAIL failure sentinel, so the head
// drivers cannot tell the two apart. --compress keeps the shard
// run-length-encoded in memory (the reference's CPD compression trade).

#include <omp.h>

#include <cerrno>
#include <cstring>
#include <fstream>
#include <map>
#include <sstream>
#include <string>
#include <sys/stat.h>
#include <sys/time.h>
#include <unistd.h>
#include <vector>

#include "../src/cpd.hpp"
#include "../src/distribution_controller.hpp"
#include "../src/graph.hpp"
#include "../src/search.hpp"

using namespace dos;

static double now_s() {
    struct timeval tv;
    gettimeofday(&tv, nullptr);
    return tv.tv_sec + tv.tv_usec * 1e-6;
}

// minimal flat-JSON number/bool extraction for the runtime-config line
// (wire schema: transport/wire.py RuntimeConfig)
static double json_num(const std::string& j, const std::string& key,
                       double dflt) {
    auto p = j.find("\"" + key + "\"");
    if (p == std::string::npos) return dflt;
    p = j.find(':', p);
    if (p == std::string::npos) return dflt;
    ++p;
    while (p < j.size() && (j[p] == ' ' || j[p] == '\t')) ++p;
    if (!j.compare(p, 4, "true")) return 1;
    if (!j.compare(p, 5, "false")) return 0;
    try {
        return std::stod(j.substr(p));
    } catch (...) { return dflt; }
}

struct Server {
    Graph g;
    DistributionController dc;
    CpdShard shard;
    int64_t wid;
    std::string fifo_path;
    std::string alg;  // table-search | astar
    std::map<std::string, std::vector<int32_t>> weight_cache;

    Server(Graph gg, DistributionController dcc, CpdShard sh, int64_t w,
           std::string fifo, std::string algo)
        : g(std::move(gg)), dc(std::move(dcc)), shard(std::move(sh)),
          wid(w), fifo_path(std::move(fifo)), alg(std::move(algo)) {}

    std::vector<int32_t> scratch_weights;  // no_cache loads live here

    const std::vector<int32_t>& weights_for(const std::string& diff,
                                            bool no_cache) {
        if (no_cache) {  // python engine parity: clear AND don't cache
            weight_cache.clear();
            scratch_weights = weights_with_diff(g, diff);
            return scratch_weights;
        }
        auto it = weight_cache.find(diff);
        if (it != weight_cache.end()) return it->second;
        return weight_cache.emplace(diff, weights_with_diff(g, diff))
            .first->second;
    }

    std::string handle(const std::string& cfg_json,
                       const std::string& queryfile,
                       const std::string& difffile) {
        double t0 = now_s();
        int64_t k_moves = int64_t(json_num(cfg_json, "k_moves", -1));
        int threads = int(json_num(cfg_json, "threads", 0));
        bool no_cache = json_num(cfg_json, "no_cache", 0) != 0;
        int64_t itrs = std::max<int64_t>(1, int64_t(json_num(cfg_json, "itrs", 1)));
        double hscale = json_num(cfg_json, "hscale", 1.0);
        double fscale = json_num(cfg_json, "fscale", 0.0);
        const std::vector<int32_t>& wq = weights_for(difffile, no_cache);
        auto queries = load_query_file(queryfile);
        // routing invariant (same loud failure as the Python ShardEngine):
        // every query's target must be owned by this worker, and both
        // endpoints must be in range (a corrupt query file must answer
        // FAIL, not index out of bounds)
        for (auto& [s, t] : queries) {
            if (s < 0 || s >= dc.nodenum)
                die("query source " + std::to_string(s) + " out of range");
            if (t < 0 || t >= dc.nodenum || dc.wid_of[t] != wid)
                die("routing invariant violated: query targets node " +
                    std::to_string(t) + " not owned by worker " +
                    std::to_string(wid));
        }
        double t1 = now_s();

        bool use_astar = alg == "astar";
        double cpu = use_astar ? min_cost_per_unit(g, wq) : 0.0;
        SearchStats total;
        if (threads > 0) omp_set_num_threads(threads);
        for (int64_t it = 0; it < itrs; ++it) {
            SearchStats round;
#pragma omp parallel
            {
                SearchStats local;
#pragma omp for schedule(dynamic, 64)
                for (size_t q = 0; q < queries.size(); ++q) {
                    auto [s, t] = queries[q];
                    if (use_astar) {
                        astar(g, s, t, wq, hscale, fscale, local, cpu);
                        continue;
                    }
                    int64_t row = dc.owned_idx[t];
                    auto fm = [&](int64_t x) {
                        return shard.first_move(row, x);
                    };
                    QueryResult r = table_search(g, fm, s, t, wq, k_moves);
                    local.n_expanded += r.plen;
                    local.n_touched += 1;
                    local.plen += r.plen;
                    local.finished += r.finished ? 1 : 0;
                }
#pragma omp critical
                round += local;
            }
            total = round;  // last iteration wins (wire parity with python)
        }
        double t2 = now_s();
        char buf[256];
        std::snprintf(buf, sizeof buf,
                      "%ld,%ld,%ld,%ld,%ld,%ld,%ld,%.9f,%.9f,%.9f",
                      total.n_expanded, total.n_inserted, total.n_touched,
                      total.n_updated, total.n_surplus, total.plen,
                      total.finished, t1 - t0, t2 - t1, t2 - t0);
        return buf;
    }

    [[noreturn]] void serve() {
        ::unlink(fifo_path.c_str());
        if (::mkfifo(fifo_path.c_str(), 0666) != 0)
            die("mkfifo " + fifo_path + ": " + std::strerror(errno));
        std::fprintf(stderr, "fifo_auto: worker %ld serving on %s\n", wid,
                     fifo_path.c_str());
        while (true) {
            std::ifstream f(fifo_path);  // blocking-open rendezvous
            std::stringstream ss;
            ss << f.rdbuf();
            std::string text = ss.str();
            if (text.find("__DOS_STOP__") != std::string::npos) {
                ::unlink(fifo_path.c_str());
                std::exit(0);
            }
            auto nl = text.find('\n');
            if (nl == std::string::npos) continue;
            std::string cfg = text.substr(0, nl);
            std::istringstream l2(text.substr(nl + 1));
            std::string queryfile, answerfifo, difffile;
            l2 >> queryfile >> answerfifo >> difffile;
            if (answerfifo.empty()) continue;
            std::string reply;
            try {
                reply = handle(cfg, queryfile, difffile);
            } catch (...) {
                reply = "FAIL";  // never leave the head blocked
            }
            std::ofstream out(answerfifo);
            out << reply << "\n";
        }
    }
};

static int real_main(int argc, char** argv) {
    std::string input, diff = "-", partmethod, outdir = ".", alg =
        "table-search", fifo;
    std::vector<int64_t> partkey;
    int64_t workerid = -1, maxworker = -1,
            block_size = DEFAULT_BLOCK_SIZE;
    bool compress = false;
    for (int i = 1; i < argc; ++i) {
        std::string a = argv[i];
        auto next = [&]() -> std::string {
            if (i + 1 >= argc) die("missing value for " + a);
            return argv[++i];
        };
        if (a == "--input") {
            input = next();
            if (i + 1 < argc && argv[i + 1][0] != '-') diff = argv[++i];
            else if (i + 1 < argc && std::strcmp(argv[i + 1], "-") == 0)
                diff = argv[++i];
        } else if (a == "--partmethod") partmethod = next();
        else if (a == "--partkey") {
            while (i + 1 < argc && (argv[i + 1][0] != '-' ||
                                    std::isdigit(argv[i + 1][1])))
                partkey.push_back(std::stoll(argv[++i]));
        } else if (a == "--workerid") workerid = std::stoll(next());
        else if (a == "--maxworker") maxworker = std::stoll(next());
        else if (a == "--outdir") outdir = next();
        else if (a == "--alg") alg = next();
        else if (a == "--block-size") block_size = std::stoll(next());
        else if (a == "--compress") compress = true;
        else if (a == "--fifo") fifo = next();
        else die("unknown flag " + a);
    }
    if (input.empty() || partmethod.empty() || workerid < 0 || maxworker <= 0)
        die("usage: fifo_auto --input XY [DIFF] --partmethod M --partkey K "
            "--workerid W --maxworker N --outdir D --alg table-search");
    if (alg != "table-search" && alg != "astar")
        die("--alg must be table-search (reference make_fifos.py:20) or "
            "astar (this framework's hscale/fscale family)");
    if (partkey.empty()) partkey.push_back(1);
    if (fifo.empty())
        fifo = "/tmp/worker" + std::to_string(workerid) + ".fifo";

    Graph g = load_xy(input);
    DistributionController dc(partmethod, partkey, maxworker, g.n,
                              block_size);
    // astar needs no first-move table; table-search loads its CPD shard
    CpdShard shard;
    if (alg == "table-search")
        shard = CpdShard::load(outdir, workerid, dc.n_owned(workerid),
                               block_size, compress);
    Server server(std::move(g), std::move(dc), std::move(shard), workerid,
                  fifo, alg);
    // preload the first diff like the reference server (make_fifos.py:18)
    server.weights_for(diff, false);
    server.serve();
}

int main(int argc, char** argv) {
    return run_main([&] { return real_main(argc, argv); });
}
