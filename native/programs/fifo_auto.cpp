// fifo_auto — resident query server (native).
//
// Role + protocol parity with reference C3 (SURVEY.md §2.2; launched at
// reference make_fifos.py:21): load the graph, the first diff, and this
// worker's CPD shard; create the command FIFO and block on it. Per
// request: parse the 2-line config (JSON knobs + "queryfile answerfifo
// difffile"), read the query file, answer every (s,t) by table-search
// (OpenMP over queries), write ONE CSV stats line to the answer FIFO.
// Stays resident across requests.
//
//   fifo_auto --input <xy> [<diff>] --partmethod M --partkey K...
//             --workerid W --maxworker N --outdir <idxdir>
//             --alg table-search|astar|ch [--compress] [--fifo <path>]
//
// --alg astar serves the hscale/fscale weighted-A* family (the knobs the
// reference exposes, args.py:30-57) straight off the graph — no CPD
// needed — emitting the full priority-queue telemetry.
//
// --alg ch serves contraction-hierarchy queries (the congestion-free
// family of the reference's TODO, reference README.md:133): the hierarchy
// is built once at startup on FREE-FLOW weights; per-request diffs are
// ignored with a warning (a diff would invalidate the shortcuts).
//
// Speaks the same wire as the Python worker/server.py, including the
// __DOS_STOP__ shutdown token and the FAIL failure sentinel, so the head
// drivers cannot tell the two apart. --compress keeps the shard
// run-length-encoded in memory (the reference's CPD compression trade).

#include <csignal>
#include <fcntl.h>
#include <omp.h>
#include <poll.h>

#include <cctype>
#include <cerrno>
#include <cstring>
#include <fstream>
#include <map>
#include <memory>
#include <sstream>
#include <string>
#include <sys/stat.h>
#include <sys/time.h>
#include <unistd.h>
#include <vector>

#include "../src/ch.hpp"
#include "../src/cpd.hpp"
#include "../src/distribution_controller.hpp"
#include "../src/graph.hpp"
#include "../src/search.hpp"

using namespace dos;

static double now_s() {
    struct timeval tv;
    gettimeofday(&tv, nullptr);
    return tv.tv_sec + tv.tv_usec * 1e-6;
}

// ---- flat-JSON tokenizer for the runtime-config line (wire schema:
// transport/wire.py RuntimeConfig). A real (if small) parser: strings are
// skipped with escape handling, nested containers are skipped balanced,
// numbers accept sign/decimal/exponent — so a key name appearing inside a
// string value, or a string-typed knob, can never corrupt the numbers.
// Values surface as doubles (true=1, false=0, null/strings absent).
namespace flatjson {

static void skip_ws(const std::string& j, size_t& p) {
    while (p < j.size() && std::isspace(static_cast<unsigned char>(j[p])))
        ++p;
}

static bool parse_string(const std::string& j, size_t& p,
                         std::string* out) {
    if (p >= j.size() || j[p] != '"') return false;
    ++p;
    std::string s;
    while (p < j.size() && j[p] != '"') {
        if (j[p] == '\\' && p + 1 < j.size()) { s += j[p + 1]; p += 2; }
        else s += j[p++];
    }
    if (p >= j.size()) return false;
    ++p;  // closing quote
    if (out) *out = s;
    return true;
}

static bool skip_container(const std::string& j, size_t& p) {
    char open = j[p], close = open == '{' ? '}' : ']';
    int depth = 0;
    while (p < j.size()) {
        if (j[p] == '"') { if (!parse_string(j, p, nullptr)) return false; continue; }
        if (j[p] == open) ++depth;
        else if (j[p] == close && --depth == 0) { ++p; return true; }
        ++p;
    }
    return false;
}

// parse one top-level JSON object into key -> numeric value
static std::map<std::string, double> parse(const std::string& j) {
    std::map<std::string, double> out;
    size_t p = 0;
    skip_ws(j, p);
    if (p >= j.size() || j[p] != '{') return out;
    ++p;
    while (true) {
        skip_ws(j, p);
        if (p < j.size() && j[p] == '}') break;
        std::string key;
        if (!parse_string(j, p, &key)) break;
        skip_ws(j, p);
        if (p >= j.size() || j[p] != ':') break;
        ++p;
        skip_ws(j, p);
        if (p >= j.size()) break;
        if (j[p] == '"') {                       // string value: skip
            if (!parse_string(j, p, nullptr)) break;
        } else if (j[p] == '{' || j[p] == '[') { // nested: skip balanced
            if (!skip_container(j, p)) break;
        } else if (!j.compare(p, 4, "true")) { out[key] = 1; p += 4; }
        else if (!j.compare(p, 5, "false")) { out[key] = 0; p += 5; }
        else if (!j.compare(p, 4, "null")) { p += 4; }
        else {                                   // number
            size_t q = p;
            while (q < j.size() && (std::isdigit(
                       static_cast<unsigned char>(j[q])) || j[q] == '-' ||
                   j[q] == '+' || j[q] == '.' || j[q] == 'e' || j[q] == 'E'))
                ++q;
            try { out[key] = std::stod(j.substr(p, q - p)); } catch (...) {}
            p = q;
        }
        skip_ws(j, p);
        if (p < j.size() && j[p] == ',') { ++p; continue; }
        break;
    }
    return out;
}

static double get(const std::map<std::string, double>& m,
                  const std::string& key, double dflt) {
    auto it = m.find(key);
    return it == m.end() ? dflt : it->second;
}

}  // namespace flatjson

struct Server {
    Graph g;
    DistributionController dc;
    CpdShard shard;
    int64_t wid;
    std::string fifo_path;
    std::string alg;  // table-search | astar | ch
    CH ch_idx;        // built at startup when alg == "ch" (free flow)
    std::map<std::string, std::vector<int32_t>> weight_cache;

    Server(Graph gg, DistributionController dcc, CpdShard sh, int64_t w,
           std::string fifo, std::string algo)
        : g(std::move(gg)), dc(std::move(dcc)), shard(std::move(sh)),
          wid(w), fifo_path(std::move(fifo)), alg(std::move(algo)) {}

    std::vector<int32_t> scratch_weights;  // no_cache loads live here

    const std::vector<int32_t>& weights_for(const std::string& diff,
                                            bool no_cache) {
        if (no_cache) {  // python engine parity: clear AND don't cache
            weight_cache.clear();
            scratch_weights = weights_with_diff(g, diff);
            return scratch_weights;
        }
        auto it = weight_cache.find(diff);
        if (it != weight_cache.end()) return it->second;
        return weight_cache.emplace(diff, weights_with_diff(g, diff))
            .first->second;
    }

    std::string handle(const std::string& cfg_json,
                       const std::string& queryfile,
                       const std::string& difffile) {
        double t0 = now_s();
        auto cfg = flatjson::parse(cfg_json);
        int64_t k_moves = int64_t(flatjson::get(cfg, "k_moves", -1));
        int threads = int(flatjson::get(cfg, "threads", 0));
        if (flatjson::get(cfg, "thread_alloc", 0) != 0) {
            // receiver-thread pinning (reference args.py:164-169) has no
            // analog in this engine's batch model; say so rather than
            // silently ignoring the knob
            static bool warned = false;
            if (!warned) {
                std::fprintf(stderr,
                             "fifo_auto: thread_alloc is not supported "
                             "by this engine (ignored)\n");
                warned = true;
            }
        }
        bool no_cache = flatjson::get(cfg, "no_cache", 0) != 0;
        int64_t itrs =
            std::max<int64_t>(1, int64_t(flatjson::get(cfg, "itrs", 1)));
        double hscale = flatjson::get(cfg, "hscale", 1.0);
        double fscale = flatjson::get(cfg, "fscale", 0.0);
        // ns budget on the itrs repetition loop (wire parity with the
        // Python ShardEngine: worker/engine.py deadline semantics)
        double time_ns = flatjson::get(cfg, "time", 0);
        bool extract = flatjson::get(cfg, "extract", 0) != 0 && k_moves > 0
                       && alg == "table-search";
        const std::vector<int32_t>& wq = weights_for(difffile, no_cache);
        auto queries = load_query_file(queryfile);
        // routing invariant (same loud failure as the Python ShardEngine):
        // every query's target must be owned by this worker, and both
        // endpoints must be in range (a corrupt query file must answer
        // FAIL, not index out of bounds)
        for (auto& [s, t] : queries) {
            if (s < 0 || s >= dc.nodenum)
                die("query source " + std::to_string(s) + " out of range");
            if (t < 0 || t >= dc.nodenum || dc.wid_of[t] != wid)
                die("routing invariant violated: query targets node " +
                    std::to_string(t) + " not owned by worker " +
                    std::to_string(wid));
        }
        double t1 = now_s();

        bool use_astar = alg == "astar";
        bool use_ch = alg == "ch";
        if (use_ch && difffile != "-")
            std::fprintf(stderr,
                         "fifo_auto: --alg ch is congestion-free; ignoring "
                         "diff %s (answers are free-flow)\n",
                         difffile.c_str());
        double cpu = use_astar ? min_cost_per_unit(g, wq) : 0.0;
        SearchStats total;
        if (threads > 0) omp_set_num_threads(threads);
        double deadline = time_ns > 0 ? t1 + time_ns * 1e-9 : 0.0;
        for (int64_t it = 0; it < itrs; ++it) {
            SearchStats round;
#pragma omp parallel
            {
                SearchStats local;
                // per-thread CH search context: stamped arrays allocated
                // once per batch, each query then costs O(settled)
                std::unique_ptr<CHSearch> chs;
                if (use_ch) chs = std::make_unique<CHSearch>(ch_idx);
#pragma omp for schedule(dynamic, 64)
                for (size_t q = 0; q < queries.size(); ++q) {
                    // ns budget truncates INSIDE the batch (reference
                    // semantics: the time limit cuts searches short in
                    // the engine, reference args.py:30-57): queries
                    // past the deadline stay unanswered and the
                    // `finished` count comes back partial. Query 0
                    // always runs — an expired budget still yields a
                    // minimal answer (same rule as the A* chunk path).
                    // Table-search still counts the query as touched
                    // (= received), matching the Python engine's
                    // n_touched = batch size under truncation.
                    if (q > 0 && deadline > 0 && now_s() > deadline) {
                        if (!use_astar && !use_ch) local.n_touched += 1;
                        continue;
                    }
                    auto [s, t] = queries[q];
                    if (use_astar) {
                        astar(g, s, t, wq, hscale, fscale, local, cpu);
                        continue;
                    }
                    if (use_ch) {
                        chs->query(s, t, local);
                        continue;
                    }
                    int64_t row = dc.owned_idx[t];
                    auto fm = [&](int64_t x) {
                        return shard.first_move(row, x);
                    };
                    QueryResult r = table_search(g, fm, s, t, wq, k_moves);
                    local.n_expanded += r.plen;
                    local.n_touched += 1;
                    local.plen += r.plen;
                    local.finished += r.finished ? 1 : 0;
                }
#pragma omp critical
                round += local;
            }
            total = round;  // last iteration wins (wire parity with python)
            if (deadline > 0 && now_s() > deadline) break;
        }
        if (extract) {
            // wire extension (transport/wire.py paths_file_for): first
            // k_moves path nodes per query into <queryfile>.paths —
            // "Q k" header, then "<moves> n0 ... nk" per query, last
            // node repeated once the path ends
            std::ofstream pf(queryfile + ".paths");
            pf << queries.size() << " " << k_moves << "\n";
            for (auto& [s, t] : queries) {
                int64_t row = dc.owned_idx[t];
                int64_t x = s, moves = 0;
                std::vector<int64_t> nodes{x};
                for (int64_t k = 0; k < k_moves && x != t; ++k) {
                    int8_t slot = shard.first_move(row, x);
                    if (slot < 0) break;
                    x = g.dst[g.out_edge_at(x, slot)];
                    nodes.push_back(x);
                    ++moves;
                }
                pf << moves;
                for (int64_t k = 0; k <= k_moves; ++k)
                    pf << " "
                       << nodes[size_t(std::min<int64_t>(
                              k, int64_t(nodes.size()) - 1))];
                pf << "\n";
            }
        }
        double t2 = now_s();
        char buf[256];
        std::snprintf(buf, sizeof buf,
                      "%ld,%ld,%ld,%ld,%ld,%ld,%ld,%.9f,%.9f,%.9f",
                      total.n_expanded, total.n_inserted, total.n_touched,
                      total.n_updated, total.n_surplus, total.plen,
                      total.finished, t1 - t0, t2 - t1, t2 - t0);
        return buf;
    }

    // line-buffered reader over the persistent command-FIFO fd
    std::string fifo_pending;

    // next newline-terminated line; timeout_ms < 0 waits forever. The
    // deadline is ABSOLUTE (poll gets the remaining time, not a fresh
    // window): a byte-trickling writer that keeps waking poll without
    // completing a line cannot hold a half-frame wait open forever.
    // Returns false on timeout (line untouched).
    bool next_line(int fd, std::string* line, int timeout_ms = -1) {
        size_t nl;
        double give_up = timeout_ms >= 0 ? now_s() + timeout_ms / 1000.0
                                         : 0.0;
        while ((nl = fifo_pending.find('\n')) == std::string::npos) {
            if (timeout_ms >= 0) {
                double rem_s = give_up - now_s();
                if (rem_s <= 0) return false;
                struct pollfd p{fd, POLLIN, 0};
                int r = ::poll(&p, 1, int(rem_s * 1000) + 1);
                if (r == 0) return false;
                if (r < 0 && errno != EINTR)
                    die(std::string("poll ") + fifo_path + ": " +
                        std::strerror(errno));
                if (r < 0) continue;
            }
            char buf[4096];
            ssize_t k = ::read(fd, buf, sizeof buf);
            if (k < 0) {
                if (errno == EINTR) continue;
                die(std::string("read ") + fifo_path + ": " +
                    std::strerror(errno));
            }
            if (k == 0) { ::usleep(10 * 1000); continue; }  // defensive
            fifo_pending.append(buf, size_t(k));
        }
        *line = fifo_pending.substr(0, nl);
        fifo_pending.erase(0, nl + 1);
        return true;
    }

    // best effort: find an answer-FIFO path among a garbage line's
    // tokens and send the FAIL sentinel so a stranded head never blocks
    void answer_malformed(const std::string& line) {
        std::istringstream ss(line);
        std::string tok;
        while (ss >> tok) {
            struct stat st;
            if (::stat(tok.c_str(), &st) == 0 && S_ISFIFO(st.st_mode)) {
                int fd = ::open(tok.c_str(), O_WRONLY | O_NONBLOCK);
                if (fd < 0) {
                    // give a just-arriving reader a moment, then drop
                    for (int i = 0; i < 40 && fd < 0; ++i) {
                        ::usleep(50 * 1000);
                        fd = ::open(tok.c_str(), O_WRONLY | O_NONBLOCK);
                    }
                }
                if (fd >= 0) {
                    ::fcntl(fd, F_SETFL,
                            ::fcntl(fd, F_GETFL) & ~O_NONBLOCK);
                    const char* fail = "FAIL\n";
                    ssize_t n = ::write(fd, fail, 5);
                    (void)n;
                    ::close(fd);
                }
                return;
            }
        }
    }

    [[noreturn]] void serve() {
        ::unlink(fifo_path.c_str());
        if (::mkfifo(fifo_path.c_str(), 0666) != 0)
            die("mkfifo " + fifo_path + ": " + std::strerror(errno));
        std::fprintf(stderr, "fifo_auto: worker %ld serving on %s\n", wid,
                     fifo_path.c_str());
        // PERSISTENT read session, O_RDWR: our own write end keeps the
        // pipe alive, so read() never sees EOF and requests from
        // back-to-back writers queue in the pipe buffer instead of
        // coalescing into a dying open-to-EOF session (the reference's
        // documented FIFO race, reference README.md:125-127 — a second
        // writer's request used to be appended to the first writer's
        // session and silently discarded, deadlocking that writer on its
        // answer FIFO). Frames are newline-delimited, exactly 2 lines
        // per request; writes <= PIPE_BUF (4 KiB) are atomic so frames
        // cannot interleave even with concurrent writers.
        int cfd = ::open(fifo_path.c_str(), O_RDWR);
        if (cfd < 0)
            die("open " + fifo_path + ": " + std::strerror(errno));
        while (true) {
            std::string line1, line2;
            next_line(cfd, &line1);
            if (line1.find("__DOS_STOP__") != std::string::npos) {
                ::unlink(fifo_path.c_str());
                std::exit(0);
            }
            size_t first = line1.find_first_not_of(" \t\r");
            if (first == std::string::npos)
                continue;
            if (line1[first] != '{') {
                // frame starts are self-identifying: a config line is
                // always a JSON object, a paths line never is. A stray
                // non-JSON line is garbage — handle it standalone so it
                // can NEVER pair with (and eat) the next writer's config
                // line; best-effort FAIL any FIFO it names
                std::fprintf(stderr, "fifo_auto: stray non-frame line: "
                             "%s\n", line1.c_str());
                answer_malformed(line1);
                continue;
            }
            // a legit writer ships both lines in ONE atomic write, so
            // line 2 is already in the pipe; bound the wait so a
            // config-only garbage frame cannot desync the stream
            if (!next_line(cfd, &line2, 2000)) {
                std::fprintf(stderr,
                             "fifo_auto: half frame (no line 2): %s\n",
                             line1.c_str());
                continue;
            }
            if (line2.find("__DOS_STOP__") != std::string::npos) {
                // a stop chasing a truncated request must still win
                ::unlink(fifo_path.c_str());
                std::exit(0);
            }
            size_t f2 = line2.find_first_not_of(" \t\r");
            if (f2 != std::string::npos && line2[f2] == '{') {
                // a config line where the paths line belongs: the
                // previous writer truncated. Push it back to start the
                // next frame instead of corrupting two requests
                std::fprintf(stderr, "fifo_auto: config-only half frame: "
                             "%s\n", line1.c_str());
                fifo_pending = line2 + "\n" + fifo_pending;
                continue;
            }
            std::string cfg = line1;
            std::istringstream l2(line2);
            std::string queryfile, answerfifo, difffile;
            l2 >> queryfile >> answerfifo >> difffile;
            if (answerfifo.empty()) continue;
            std::string reply;
            try {
                reply = handle(cfg, queryfile, difffile);
            } catch (...) {
                reply = "FAIL";  // never leave the head blocked
            }
            reply += "\n";
            // non-blocking open with a bounded deadline: if the head died
            // before opening its `cat <answer>` reader, a blocking open
            // would wedge this worker for every future request. Drop the
            // reply (and log) if no reader appears in time
            // (DOS_REPLY_DEADLINE_S env overrides, for fast tests).
            static const double reply_deadline_s = [] {
                const char* e = std::getenv("DOS_REPLY_DEADLINE_S");
                if (!e || !*e) return 30.0;
                char* end = nullptr;
                double v = std::strtod(e, &end);
                // malformed value falls back instead of becoming a 0s
                // deadline that drops every reply
                return (end && *end == '\0' && v > 0) ? v : 30.0;
            }();
            double give_up = now_s() + reply_deadline_s;
            int fd = -1;
            while (fd < 0 && now_s() < give_up) {
                fd = ::open(answerfifo.c_str(), O_WRONLY | O_NONBLOCK);
                if (fd < 0) {
                    if (errno != ENXIO && errno != ENOENT) break;
                    ::usleep(50 * 1000);
                }
            }
            if (fd < 0) {
                std::fprintf(stderr,
                             "fifo_auto: no reader on %s within %.0fs; "
                             "dropping reply\n", answerfifo.c_str(),
                             reply_deadline_s);
                continue;
            }
            // reader present: clear O_NONBLOCK so the write itself blocks
            // normally (a FIFO write after open may still fill the pipe)
            ::fcntl(fd, F_SETFL, ::fcntl(fd, F_GETFL) & ~O_NONBLOCK);
            size_t off = 0;
            while (off < reply.size()) {
                ssize_t k = ::write(fd, reply.data() + off,
                                    reply.size() - off);
                if (k <= 0) break;
                off += size_t(k);
            }
            ::close(fd);
        }
    }
};

static int real_main(int argc, char** argv) {
    // a reply/FAIL write to an answer FIFO whose reader vanished between
    // our open() and write() must error with EPIPE, not kill the server
    ::signal(SIGPIPE, SIG_IGN);
    std::string input, diff = "-", partmethod, outdir = ".", alg =
        "table-search", fifo;
    std::vector<int64_t> partkey;
    int64_t workerid = -1, maxworker = -1,
            block_size = DEFAULT_BLOCK_SIZE;
    bool compress = false;
    for (int i = 1; i < argc; ++i) {
        std::string a = argv[i];
        auto next = [&]() -> std::string {
            if (i + 1 >= argc) die("missing value for " + a);
            return argv[++i];
        };
        if (a == "--input") {
            input = next();
            // optional diff operand: anything that is not a known flag —
            // "--input g.xy -my-diff" must treat "-my-diff" as the diff
            // path, not choke on the leading dash
            if (i + 1 < argc && std::strncmp(argv[i + 1], "--", 2) != 0)
                diff = argv[++i];
        } else if (a == "--partmethod") partmethod = next();
        else if (a == "--partkey") {
            while (i + 1 < argc && (argv[i + 1][0] != '-' ||
                                    std::isdigit(argv[i + 1][1])))
                partkey.push_back(std::stoll(argv[++i]));
        } else if (a == "--workerid") workerid = std::stoll(next());
        else if (a == "--maxworker") maxworker = std::stoll(next());
        else if (a == "--outdir") outdir = next();
        else if (a == "--alg") alg = next();
        else if (a == "--block-size") block_size = std::stoll(next());
        else if (a == "--compress") compress = true;
        else if (a == "--fifo") fifo = next();
        else die("unknown flag " + a);
    }
    if (input.empty() || partmethod.empty() || workerid < 0 || maxworker <= 0)
        die("usage: fifo_auto --input XY [DIFF] --partmethod M --partkey K "
            "--workerid W --maxworker N --outdir D --alg table-search");
    if (alg != "table-search" && alg != "astar" && alg != "ch")
        die("--alg must be table-search (reference make_fifos.py:20), "
            "astar (the hscale/fscale family), or ch (congestion-free "
            "contraction hierarchies)");
    if (partkey.empty()) partkey.push_back(1);
    if (fifo.empty())
        fifo = "/tmp/worker" + std::to_string(workerid) + ".fifo";

    Graph g = load_xy(input);
    DistributionController dc(partmethod, partkey, maxworker, g.n,
                              block_size);
    // astar/ch need no first-move table; table-search loads its CPD shard
    CpdShard shard;
    if (alg == "table-search")
        shard = CpdShard::load(outdir, workerid, dc.n_owned(workerid),
                               block_size, compress);
    Server server(std::move(g), std::move(dc), std::move(shard), workerid,
                  fifo, alg);
    if (alg == "ch") {
        double tb = now_s();
        server.ch_idx.build(server.g, server.g.w);
        std::fprintf(stderr,
                     "fifo_auto: CH built in %.2fs (%ld shortcuts)\n",
                     now_s() - tb, server.ch_idx.n_shortcuts);
    }
    // preload the first diff like the reference server (make_fifos.py:18)
    server.weights_for(diff, false);
    server.serve();
}

int main(int argc, char** argv) {
    return run_main([&] { return real_main(argc, argv); });
}
