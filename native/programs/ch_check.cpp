// ch_check — contraction-hierarchy self-verification harness.
//
// The FIFO stats wire carries only aggregate counters (reference
// process_query.py:198-213), so CH cost correctness is proven here
// instead: build the hierarchy for an .xy graph, then for every query in a
// .scen file compare CH's cost against plain Dijkstra (A* with hscale=0 —
// a zero heuristic IS Dijkstra) on the same weights. Exits non-zero on the
// first mismatch; prints one summary line on success. Driven by
// tests/test_native.py.
//
//   ch_check <graph.xy> <queries.scen> [witness_budget]

#include <cstdio>
#include <cstdlib>
#include <ctime>
#include <string>
#include <utility>
#include <vector>

#include "../src/ch.hpp"
#include "../src/graph.hpp"
#include "../src/search.hpp"

using namespace dos;

static double now_monotonic() {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return ts.tv_sec + ts.tv_nsec * 1e-9;
}

static int real_main(int argc, char** argv) {
    if (argc < 3) die("usage: ch_check <graph.xy> <queries.scen> [budget]");
    Graph g = load_xy(argv[1]);
    auto queries = load_scen(argv[2]);
    int64_t budget = argc > 3 ? std::atoll(argv[3]) : 64;

    double t0 = now_monotonic();
    CH ch;
    ch.build(g, g.w, budget);
    double t_build = now_monotonic() - t0;

    SearchStats ch_stats, dij_stats;
    CHSearch search(ch);
    int64_t checked = 0;
    t0 = now_monotonic();
    for (auto& [s, t] : queries) {
        QueryResult r = search.query(s, t, ch_stats);
        QueryResult golden = astar(g, s, t, g.w, /*hscale=*/0.0,
                                   /*fscale=*/0.0, dij_stats, /*cpu=*/0.0);
        if (r.finished != golden.finished || r.cost != golden.cost) {
            std::fprintf(stderr,
                         "MISMATCH s=%ld t=%ld ch=(%ld fin=%d) "
                         "dijkstra=(%ld fin=%d)\n",
                         s, t, r.cost, int(r.finished), golden.cost,
                         int(golden.finished));
            return 1;
        }
        ++checked;
    }
    double t_query = now_monotonic() - t0;
    std::printf("CH_OK n=%ld m=%ld shortcuts=%ld queries=%ld "
                "build_s=%.3f ch_expanded=%ld dijkstra_expanded=%ld "
                "query_s=%.3f\n",
                g.n, g.m, ch.n_shortcuts, checked, t_build,
                ch_stats.n_expanded, dij_stats.n_expanded, t_query);
    return 0;
}

int main(int argc, char** argv) {
    return run_main([&] { return real_main(argc, argv); });
}
