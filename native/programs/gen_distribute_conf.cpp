// gen_distribute_conf — partition oracle (native).
//
// CLI + wire parity with reference C2 (SURVEY.md §2.2; invoked at reference
// process_query.py:46):
//   gen_distribute_conf --nodenum N --maxworker W --partmethod M
//                       --partkey K...
// Stdout: header line + one CSV row per node: node,wid,bid,bidx.
// Pure function of its flags; must agree byte-for-byte with the Python
// cli.gen_distribute_conf (tests cross-check).

#include <string>
#include <vector>

#include "../src/distribution_controller.hpp"

using namespace dos;

static int real_main(int argc, char** argv) {
    int64_t nodenum = -1, maxworker = -1;
    std::string partmethod;
    std::vector<int64_t> partkey;
    for (int i = 1; i < argc; ++i) {
        std::string a = argv[i];
        auto next = [&]() -> std::string {
            if (i + 1 >= argc) die("missing value for " + a);
            return argv[++i];
        };
        if (a == "--nodenum") nodenum = std::stoll(next());
        else if (a == "--maxworker") maxworker = std::stoll(next());
        else if (a == "--partmethod") partmethod = next();
        else if (a == "--partkey") {
            while (i + 1 < argc && argv[i + 1][0] != '-')
                partkey.push_back(std::stoll(argv[++i]));
        } else die("unknown flag " + a);
    }
    if (nodenum < 0 || maxworker <= 0 || partmethod.empty())
        die("usage: gen_distribute_conf --nodenum N --maxworker W "
            "--partmethod M --partkey K...");
    if (partkey.empty()) partkey.push_back(1);
    DistributionController dc(partmethod, partkey, maxworker, nodenum);
    dc.print_conf(stdout);
    return 0;
}

int main(int argc, char** argv) {
    return run_main([&] { return real_main(argc, argv); });
}
