// make_cpd_auto — per-worker CPD builder (native).
//
// CLI parity with reference C1 (SURVEY.md §2.2; invoked at reference
// make_cpds.py:20):
//   make_cpd_auto --input <xy> --partmethod <div|mod|alloc|tpu>
//                 --partkey <int...> --workerid <w> --maxworker <n>
//                 [--outdir <dir>] [--block-size <b>] [--no-resume]
//
// One reverse-Dijkstra sweep per owned target, OpenMP over all cores
// (reference README.md:95), emitting the same cpd-w*-b*.npy block files as
// the Python builder (worker/build.py) — the two backends' indexes are
// interchangeable. Re-running skips blocks already on disk.

#include <omp.h>

#include <cstring>
#include <string>
#include <sys/stat.h>
#include <vector>

#include "../src/cpd.hpp"
#include "../src/distribution_controller.hpp"
#include "../src/graph.hpp"

using namespace dos;

static bool file_exists(const std::string& p) {
    struct stat st;
    return ::stat(p.c_str(), &st) == 0;
}

static int real_main(int argc, char** argv) {
    std::string input, partmethod, outdir;
    std::vector<int64_t> partkey;
    int64_t workerid = -1, maxworker = -1,
            block_size = DEFAULT_BLOCK_SIZE;
    bool resume = true;
    for (int i = 1; i < argc; ++i) {
        std::string a = argv[i];
        auto next = [&]() -> std::string {
            if (i + 1 >= argc) die("missing value for " + a);
            return argv[++i];
        };
        if (a == "--input") input = next();
        else if (a == "--partmethod" || a == "--partition")
            partmethod = next();
        else if (a == "--partkey") {
            while (i + 1 < argc && argv[i + 1][0] != '-')
                partkey.push_back(std::stoll(argv[++i]));
        } else if (a == "--workerid") workerid = std::stoll(next());
        else if (a == "--maxworker") maxworker = std::stoll(next());
        else if (a == "--outdir") outdir = next();
        else if (a == "--block-size") block_size = std::stoll(next());
        else if (a == "--no-resume") resume = false;
        else die("unknown flag " + a);
    }
    if (input.empty() || partmethod.empty() || workerid < 0 || maxworker <= 0)
        die("usage: make_cpd_auto --input XY --partmethod M --partkey K "
            "--workerid W --maxworker N [--outdir D]");
    if (outdir.empty()) {  // default: the input's directory (README.md:93)
        auto slash = input.find_last_of('/');
        outdir = slash == std::string::npos ? "." : input.substr(0, slash);
    }
    if (partkey.empty()) partkey.push_back(1);

    ::mkdir(outdir.c_str(), 0777);  // single level, EEXIST is fine

    Graph g = load_xy(input);
    DistributionController dc(partmethod, partkey, maxworker, g.n,
                              block_size);
    std::vector<int64_t> owned = dc.owned(workerid);
    int64_t n_blocks =
        (static_cast<int64_t>(owned.size()) + block_size - 1) / block_size;

    std::vector<int64_t> todo;
    for (int64_t bid = 0; bid < n_blocks; ++bid)
        if (!resume || !file_exists(outdir + "/" + block_name(workerid, bid)))
            todo.push_back(bid);

    int64_t written = 0;
    for (int64_t bid : todo) {
        int64_t r0 = bid * block_size;
        int64_t rows =
            std::min(block_size, static_cast<int64_t>(owned.size()) - r0);
        Int8Matrix blk;
        blk.rows = rows;
        blk.cols = g.n;
        blk.data.resize(rows * g.n);
#pragma omp parallel
        {
            std::vector<int64_t> dist;  // per-thread scratch
#pragma omp for schedule(dynamic)
            for (int64_t r = 0; r < rows; ++r) {
                int64_t target = owned[r0 + r];
                dist_to_target(g, target, g.w, dist);
                first_move_row(g, target, g.w, dist, &blk.data[r * g.n]);
            }
        }
        npy_write_i8(outdir + "/" + block_name(workerid, bid), blk);
        ++written;
    }
    std::printf("worker %ld: %ld block(s) -> %s\n", workerid, written,
                outdir.c_str());
    return 0;
}

int main(int argc, char** argv) {
    return run_main([&] { return real_main(argc, argv); });
}
