// Shared basics for the native engine.
//
// TPU-native framework's host-side C++ engine: plays the role warthog plays
// in the reference (SURVEY.md §2.2 C5) — CPU correctness oracle and
// host-mode worker compute. Semantics are kept in lock-step with the
// Python/JAX side (models/reference.py, ops/): int32 weights, INF = 1e9
// (INF + INF fits int32), first-move = out-edge slot ordered by ascending
// edge id, ties to the smallest slot.
#pragma once

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <stdexcept>
#include <string>

namespace dos {

constexpr int32_t INF = 1000000000;  // matches data/graph.py INF

// Throws rather than exits so a resident server can answer FAIL and stay
// up; program main()s catch at top level and exit 1.
[[noreturn]] inline void die(const std::string& msg) {
    throw std::runtime_error(msg);
}

template <typename F>
int run_main(F&& body) {
    try {
        return body();
    } catch (const std::exception& e) {
        std::fprintf(stderr, "error: %s\n", e.what());
        return 1;
    }
}

}  // namespace dos
