// Road-graph container + file formats (.xy / .scen / .diff).
//
// Format parity with the Python side (data/formats.py docstring grammar);
// semantic parity with data/graph.py: CSR by src with edge ids ascending
// within each node (file order == ascending edge id), so "out-edge slot k
// of node u" means the same thing to this engine, the CPU oracle, and the
// JAX kernels — first-move tables are interchangeable byte-for-byte.
#pragma once

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

#include "common.hpp"

namespace dos {

struct Graph {
    int64_t n = 0, m = 0;
    std::vector<int64_t> xs, ys;          // node coordinates
    std::vector<int32_t> src, dst, w;     // edges, file order
    std::vector<int64_t> out_ptr;         // CSR by src (eids ascending)
    std::vector<int32_t> out_eid;
    std::vector<int64_t> in_ptr;          // CSR by dst
    std::vector<int32_t> in_eid;

    int32_t out_degree(int64_t u) const {
        return static_cast<int32_t>(out_ptr[u + 1] - out_ptr[u]);
    }
    // slot k of u: k-th out-edge in ascending edge-id order
    int32_t out_edge_at(int64_t u, int32_t slot) const {
        return out_eid[out_ptr[u] + slot];
    }

    void build_csr() {
        out_ptr.assign(n + 1, 0);
        in_ptr.assign(n + 1, 0);
        for (int64_t e = 0; e < m; ++e) {
            out_ptr[src[e] + 1]++;
            in_ptr[dst[e] + 1]++;
        }
        for (int64_t i = 0; i < n; ++i) {
            out_ptr[i + 1] += out_ptr[i];
            in_ptr[i + 1] += in_ptr[i];
        }
        out_eid.resize(m);
        in_eid.resize(m);
        std::vector<int64_t> oc(out_ptr.begin(), out_ptr.end() - 1);
        std::vector<int64_t> ic(in_ptr.begin(), in_ptr.end() - 1);
        for (int64_t e = 0; e < m; ++e) {  // file order => ascending eid
            out_eid[oc[src[e]]++] = static_cast<int32_t>(e);
            in_eid[ic[dst[e]]++] = static_cast<int32_t>(e);
        }
    }

    int64_t edge_id(int64_t u, int64_t v) const {
        for (int64_t p = out_ptr[u]; p < out_ptr[u + 1]; ++p)
            if (dst[out_eid[p]] == v) return out_eid[p];
        return -1;
    }
};

// xy grammar (data/formats.py): 3 header lines, then
// "p <n> <m> 0", n "v x y" lines, m "e src dst w" lines.
inline Graph load_xy(const std::string& path) {
    FILE* f = std::fopen(path.c_str(), "r");
    if (!f) die("cannot open xy file " + path);
    char line[256];
    Graph g;
    int64_t nv = 0, ne = 0;
    // scan for the 'p' line (4th line; node count = 2nd token —
    // the structural fact the reference driver relies on,
    // reference process_query.py:126-130)
    while (std::fgets(line, sizeof line, f)) {
        if (line[0] == 'p') {
            if (std::sscanf(line, "p %ld %ld", &nv, &ne) != 2)
                die(path + ": bad p line");
            break;
        }
    }
    if (!nv) die(path + ": no p line");
    g.n = nv;
    g.m = ne;
    g.xs.resize(nv);
    g.ys.resize(nv);
    g.src.reserve(ne);
    g.dst.reserve(ne);
    g.w.reserve(ne);
    int64_t vi = 0;
    while (std::fgets(line, sizeof line, f)) {
        if (line[0] == 'v') {
            long x, y;
            if (std::sscanf(line, "v %ld %ld", &x, &y) != 2)
                die(path + ": bad v line");
            if (vi >= nv) die(path + ": too many v lines");
            g.xs[vi] = x;
            g.ys[vi] = y;
            ++vi;
        } else if (line[0] == 'e') {
            long a, b, ww;
            if (std::sscanf(line, "e %ld %ld %ld", &a, &b, &ww) != 3)
                die(path + ": bad e line");
            g.src.push_back(static_cast<int32_t>(a));
            g.dst.push_back(static_cast<int32_t>(b));
            g.w.push_back(static_cast<int32_t>(ww));
        }
    }
    std::fclose(f);
    if (vi != nv || static_cast<int64_t>(g.src.size()) != ne)
        die(path + ": node/edge count mismatch with p line");
    g.build_csr();
    return g;
}

// scen grammar: 'q <s> <t>' per query line.
inline std::vector<std::pair<int64_t, int64_t>>
load_scen(const std::string& path) {
    FILE* f = std::fopen(path.c_str(), "r");
    if (!f) die("cannot open scen file " + path);
    char line[256];
    std::vector<std::pair<int64_t, int64_t>> out;
    while (std::fgets(line, sizeof line, f)) {
        if (line[0] == 'q') {
            long s, t;
            if (std::sscanf(line, "q %ld %ld", &s, &t) == 2)
                out.emplace_back(s, t);
        }
    }
    std::fclose(f);
    return out;
}

// query-file format (wire): first line = count, then "s t" per line
// (reference process_query.py:93-96).
inline std::vector<std::pair<int64_t, int64_t>>
load_query_file(const std::string& path) {
    FILE* f = std::fopen(path.c_str(), "r");
    if (!f) die("cannot open query file " + path);
    long count = 0;
    if (std::fscanf(f, "%ld", &count) != 1)
        die(path + ": missing count line");
    std::vector<std::pair<int64_t, int64_t>> out;
    out.reserve(count);
    for (long i = 0; i < count; ++i) {
        long s, t;
        if (std::fscanf(f, "%ld %ld", &s, &t) != 2)
            die(path + ": truncated query file");
        out.emplace_back(s, t);
    }
    std::fclose(f);
    return out;
}

// diff grammar: 'd <count>' then '<src> <dst> <new_w>' lines; applied to
// query-time weights only (reference semantics, SURVEY.md §0).
inline std::vector<int32_t> weights_with_diff(const Graph& g,
                                              const std::string& path) {
    std::vector<int32_t> w = g.w;
    if (path == "-" || path.empty()) return w;
    FILE* f = std::fopen(path.c_str(), "r");
    if (!f) die("cannot open diff file " + path);
    char line[256];
    while (std::fgets(line, sizeof line, f)) {
        if (line[0] == 'd' || line[0] == 'c') continue;
        long a, b, nw;
        if (std::sscanf(line, "%ld %ld %ld", &a, &b, &nw) == 3) {
            int64_t e = g.edge_id(a, b);
            if (e < 0) die(path + ": diff names absent edge");
            w[e] = static_cast<int32_t>(nw);
        }
    }
    std::fclose(f);
    return w;
}

}  // namespace dos
