// Contraction hierarchies: the congestion-free query family the reference
// lists as a TODO it never built (reference README.md:133 "congestion-free
// algorithms: CH, CPD extractions"; SURVEY.md §2.2 C5).
//
// Classic directed CH:
//  * preprocessing contracts nodes in importance order (lazy heap over
//    edge-difference + deleted-neighbour + level), inserting a shortcut
//    u->x for every in/out pair the contracted node v uniquely serves —
//    "uniquely" established by a budget-limited witness Dijkstra; a failed
//    (budget-exhausted) witness search conservatively inserts the shortcut,
//    which can never make queries wrong, only the hierarchy denser;
//  * a query is a bidirectional Dijkstra where both sides only climb the
//    hierarchy (forward over up-edges from s, backward over reverse
//    up-edges from t), meeting at the lowest-cost peak.
//
// Every CH edge carries the number of ORIGINAL edges it stands for
// (shortcut hops = sum of its two parents), so plen comes out of the query
// without unpacking shortcuts. Telemetry uses the same SearchStats
// vocabulary as the A* family (reference process_query.py:198-213).
//
// CH answers on FREE-FLOW weights only: the hierarchy is built for one
// weight function, and a congestion diff would invalidate both the witness
// proofs and the shortcut weights — exactly why the reference files CH
// under "congestion-free".
#pragma once

#include <algorithm>
#include <cstdint>
#include <queue>
#include <vector>

#include "common.hpp"
#include "graph.hpp"
#include "search.hpp"

namespace dos {

struct CH {
    // one record per CH edge (original or shortcut)
    struct Edge {
        int32_t to;
        int32_t w;
        int32_t hops;  // original edges represented
    };

    int64_t n = 0;
    int64_t n_shortcuts = 0;
    std::vector<int32_t> rank;            // contraction order, 0 = first
    // upward search graphs (CSR): fwd = edges u->x with rank[x] > rank[u];
    // bwd at x = reverse edges u->x with rank[u] > rank[x]
    std::vector<int64_t> fwd_ptr, bwd_ptr;
    std::vector<Edge> fwd, bwd;

    void build(const Graph& g, const std::vector<int32_t>& w,
               int64_t witness_budget = 64);
};

// Per-thread query context over a built CH. The O(n) arrays are allocated
// once and reset by timestamp, and the meet scan walks only the forward
// search's touched list — each query costs O(settled log settled), not
// O(n) (the hierarchy's whole point). One instance per OMP thread; the CH
// itself stays shared and immutable.
struct CHSearch {
    const CH* ch;
    std::vector<int64_t> df, db;
    std::vector<int32_t> hf, hb;
    std::vector<int32_t> sf, sb;  // stamps: entry valid iff == cur
    std::vector<int64_t> touched_f;
    int32_t cur = 0;

    explicit CHSearch(const CH& c)
        : ch(&c), df(c.n), db(c.n), hf(c.n), hb(c.n), sf(c.n, -1),
          sb(c.n, -1) {}

    QueryResult query(int64_t s, int64_t t, SearchStats& stats) {
        ++cur;
        touched_f.clear();
        using QE = std::pair<int64_t, int64_t>;
        auto climb = [&](int64_t src, const std::vector<int64_t>& ptr,
                         const std::vector<CH::Edge>& edges,
                         std::vector<int64_t>& dist,
                         std::vector<int32_t>& hops,
                         std::vector<int32_t>& stamp, bool record) {
            std::priority_queue<QE, std::vector<QE>, std::greater<QE>> pq;
            stamp[src] = cur;
            dist[src] = 0;
            hops[src] = 0;
            if (record) touched_f.push_back(src);
            pq.emplace(0, src);
            stats.n_inserted++;
            while (!pq.empty()) {
                auto [d, u] = pq.top();
                pq.pop();
                if (d > dist[u]) { stats.n_surplus++; continue; }
                stats.n_expanded++;
                for (int64_t p = ptr[u]; p < ptr[u + 1]; ++p) {
                    const CH::Edge& e = edges[p];
                    stats.n_touched++;
                    int64_t nd = d + e.w;
                    bool seen = stamp[e.to] == cur;
                    if (!seen || nd < dist[e.to]) {
                        if (seen) {
                            stats.n_updated++;
                        } else {
                            stamp[e.to] = cur;
                            if (record) touched_f.push_back(e.to);
                        }
                        dist[e.to] = nd;
                        hops[e.to] = hops[u] + e.hops;
                        pq.emplace(nd, e.to);
                        stats.n_inserted++;
                    }
                }
            }
        };
        climb(s, ch->fwd_ptr, ch->fwd, df, hf, sf, true);
        climb(t, ch->bwd_ptr, ch->bwd, db, hb, sb, false);

        QueryResult r;
        int64_t best = INF, best_hops = 0;
        for (int64_t v : touched_f)
            if (sb[v] == cur && df[v] + db[v] < best) {
                best = df[v] + db[v];
                best_hops = hf[v] + hb[v];
            }
        r.finished = best < INF;
        r.cost = r.finished ? best : 0;
        r.plen = r.finished ? best_hops : 0;
        stats.plen += r.plen;
        stats.finished += r.finished ? 1 : 0;
        return r;
    }
};

namespace ch_detail {

// dynamic adjacency used only during contraction: per active node, the
// current out/in edges among still-active nodes (originals + shortcuts)
struct DynEdge {
    int32_t other;
    int32_t w;
    int32_t hops;
};

// limited Dijkstra from src among active nodes, excluding `skip`; stops
// when `target_bound` settled or expansions exceed budget. Returns
// dist[x] for x in `targets` (INF when not settled cheaply).
struct WitnessSearch {
    std::vector<int64_t> dist;
    std::vector<int32_t> stamp;
    int32_t cur = 0;

    void init(int64_t n) {
        dist.assign(n, INF);
        stamp.assign(n, -1);
    }

    int64_t get(int64_t x) const { return stamp[x] == cur ? dist[x] : INF; }

    void run(const std::vector<std::vector<DynEdge>>& out,
             const std::vector<char>& active, int64_t src, int64_t skip,
             int64_t cost_cap, int64_t budget) {
        ++cur;
        using QE = std::pair<int64_t, int64_t>;
        std::priority_queue<QE, std::vector<QE>, std::greater<QE>> pq;
        stamp[src] = cur;
        dist[src] = 0;
        pq.emplace(0, src);
        int64_t expansions = 0;
        while (!pq.empty() && expansions < budget) {
            auto [d, u] = pq.top();
            pq.pop();
            if (d > get(u)) continue;
            if (d > cost_cap) break;  // nothing cheaper left to prove
            ++expansions;
            for (const DynEdge& e : out[u]) {
                int64_t v = e.other;
                if (v == skip || !active[v]) continue;
                int64_t nd = d + e.w;
                if (nd < get(v)) {
                    stamp[v] = cur;
                    dist[v] = nd;
                    pq.emplace(nd, v);
                }
            }
        }
    }
};

inline void add_or_relax(std::vector<DynEdge>& edges, int32_t other,
                         int32_t w, int32_t hops) {
    for (DynEdge& e : edges)
        if (e.other == other) {
            if (w < e.w) { e.w = w; e.hops = hops; }
            return;
        }
    edges.push_back({other, w, hops});
}

}  // namespace ch_detail

inline void CH::build(const Graph& g, const std::vector<int32_t>& w,
                      int64_t witness_budget) {
    using namespace ch_detail;
    n = g.n;
    n_shortcuts = 0;
    std::vector<std::vector<DynEdge>> out(n), in(n);
    for (int64_t e = 0; e < g.m; ++e) {
        if (g.src[e] == g.dst[e]) continue;  // self-loops never help
        add_or_relax(out[g.src[e]], int32_t(g.dst[e]), w[e], 1);
        add_or_relax(in[g.dst[e]], int32_t(g.src[e]), w[e], 1);
    }
    // permanent record of every CH edge (originals deduped to min weight
    // + shortcuts as they are created)
    std::vector<std::vector<DynEdge>> all_out(n);
    for (int64_t u = 0; u < n; ++u) all_out[u] = out[u];

    std::vector<char> active(n, 1);
    std::vector<int32_t> deleted_nbrs(n, 0);
    std::vector<int32_t> level(n, 0);
    rank.assign(n, 0);
    WitnessSearch ws;
    ws.init(n);

    // simulate contraction of v: count needed shortcuts (and optionally
    // materialize them). Returns #shortcuts.
    auto contract = [&](int64_t v, bool commit) -> int64_t {
        int64_t added = 0;
        for (const DynEdge& ein : in[v]) {
            int64_t u = ein.other;
            if (!active[u] || u == v) continue;
            // one witness search from u covers every out-target of v
            int64_t cap = 0;
            for (const DynEdge& eout : out[v])
                if (active[eout.other] && eout.other != v)
                    cap = std::max(cap, int64_t(ein.w) + eout.w);
            ws.run(out, active, u, v, cap, witness_budget);
            for (const DynEdge& eout : out[v]) {
                int64_t x = eout.other;
                if (!active[x] || x == v || x == u) continue;
                int64_t via = int64_t(ein.w) + eout.w;
                if (ws.get(x) <= via) continue;  // witness proves v useless
                ++added;
                if (commit) {
                    int32_t hops = ein.hops + eout.hops;
                    add_or_relax(out[u], int32_t(x), int32_t(via), hops);
                    add_or_relax(in[x], int32_t(u), int32_t(via), hops);
                    add_or_relax(all_out[u], int32_t(x), int32_t(via), hops);
                    ++n_shortcuts;
                }
            }
        }
        return added;
    };

    auto degree = [&](int64_t v) -> int64_t {
        int64_t d = 0;
        for (const DynEdge& e : out[v]) d += active[e.other] && e.other != v;
        for (const DynEdge& e : in[v]) d += active[e.other] && e.other != v;
        return d;
    };
    auto priority = [&](int64_t v) -> int64_t {
        return contract(v, false) - degree(v) + 2 * deleted_nbrs[v]
               + level[v];
    };

    using QE = std::pair<int64_t, int64_t>;  // (priority, node)
    std::priority_queue<QE, std::vector<QE>, std::greater<QE>> pq;
    for (int64_t v = 0; v < n; ++v) pq.emplace(priority(v), v);

    int32_t next_rank = 0;
    while (!pq.empty()) {
        auto [p, v] = pq.top();
        pq.pop();
        if (!active[v]) continue;
        int64_t pnow = priority(v);  // lazy: re-check against current graph
        if (!pq.empty() && pnow > pq.top().first) {
            pq.emplace(pnow, v);
            continue;
        }
        contract(v, true);
        active[v] = 0;
        rank[v] = next_rank++;
        for (const DynEdge& e : out[v])
            if (active[e.other]) {
                deleted_nbrs[e.other]++;
                level[e.other] = std::max(level[e.other], level[v] + 1);
            }
        for (const DynEdge& e : in[v])
            if (active[e.other]) {
                deleted_nbrs[e.other]++;
                level[e.other] = std::max(level[e.other], level[v] + 1);
            }
    }

    // freeze the upward CSRs from the full edge record
    fwd_ptr.assign(n + 1, 0);
    bwd_ptr.assign(n + 1, 0);
    for (int64_t u = 0; u < n; ++u)
        for (const DynEdge& e : all_out[u]) {
            if (rank[e.other] > rank[u]) fwd_ptr[u + 1]++;
            else bwd_ptr[e.other + 1]++;
        }
    for (int64_t i = 0; i < n; ++i) {
        fwd_ptr[i + 1] += fwd_ptr[i];
        bwd_ptr[i + 1] += bwd_ptr[i];
    }
    fwd.resize(fwd_ptr[n]);
    bwd.resize(bwd_ptr[n]);
    std::vector<int64_t> fc(fwd_ptr.begin(), fwd_ptr.end() - 1);
    std::vector<int64_t> bc(bwd_ptr.begin(), bwd_ptr.end() - 1);
    for (int64_t u = 0; u < n; ++u)
        for (const DynEdge& e : all_out[u]) {
            if (rank[e.other] > rank[u])
                fwd[fc[u]++] = {e.other, e.w, e.hops};
            else
                bwd[bc[e.other]++] = {int32_t(u), e.w, e.hops};
        }
}

}  // namespace dos
