// Node→worker partition policy: the native distribution controller.
//
// Role parity with the reference's src/util/distribution_controller.h
// (SURVEY.md §2.2 C4): the single source of truth shared by the CPD
// builder, the query servers, and the router, so build-time sharding and
// query-time routing stay consistent. Semantics mirror
// parallel/partition.py exactly (the two are cross-checked by tests):
//   div:   wid = node / partkey
//   mod:   wid = node % partkey
//   alloc: wid = first i with bounds[i] > node (ascending bounds)
//   tpu:   wid = node / ceil(nodenum / maxworker)
// bid/bidx: each worker's owned nodes ascending, split into blocks of
// block_size; bid*block_size+bidx = dense row in the worker's CPD shard.
#pragma once

#include <cstdint>
#include <string>
#include <vector>

#include "common.hpp"

namespace dos {

constexpr int64_t DEFAULT_BLOCK_SIZE = 1 << 14;  // parallel/partition.py parity

struct DistributionController {
    std::string partmethod;
    std::vector<int64_t> partkey;  // 1 value, or per-worker alloc bounds
    int64_t maxworker = 1;
    int64_t nodenum = 0;
    int64_t block_size = DEFAULT_BLOCK_SIZE;

    std::vector<int32_t> wid_of;     // [n]
    std::vector<int64_t> owned_idx;  // [n] dense row within owner's shard
    std::vector<int64_t> counts;     // [w]

    DistributionController(std::string method, std::vector<int64_t> key,
                           int64_t maxw, int64_t n,
                           int64_t bs = DEFAULT_BLOCK_SIZE)
        : partmethod(std::move(method)), partkey(std::move(key)),
          maxworker(maxw), nodenum(n), block_size(bs) {
        wid_of.resize(n);
        owned_idx.resize(n);
        counts.assign(maxworker, 0);
        int64_t chunk = (n + maxworker - 1) / maxworker;
        for (int64_t node = 0; node < n; ++node) {
            int64_t w;
            if (partmethod == "div") w = node / partkey.at(0);
            else if (partmethod == "mod") w = node % partkey.at(0);
            else if (partmethod == "tpu") w = node / (chunk ? chunk : 1);
            else if (partmethod == "alloc") {
                w = 0;
                while (w < static_cast<int64_t>(partkey.size()) &&
                       partkey[w] <= node)
                    ++w;
            } else die("unknown partmethod " + partmethod);
            if (w < 0 || w >= maxworker)
                die("node maps outside maxworker (partmethod=" +
                    partmethod + ")");
            wid_of[node] = static_cast<int32_t>(w);
            owned_idx[node] = counts[w]++;  // nodes ascend => owned ascend
        }
    }

    int64_t n_owned(int64_t w) const { return counts[w]; }

    std::vector<int64_t> owned(int64_t w) const {
        std::vector<int64_t> out;
        out.reserve(counts[w]);
        for (int64_t node = 0; node < nodenum; ++node)
            if (wid_of[node] == w) out.push_back(node);
        return out;
    }

    // the gen_distribute_conf wire format: header + node,wid,bid,bidx rows
    // (parsed by the reference driver at process_query.py:50-53)
    void print_conf(FILE* f) const {
        std::fprintf(f, "node,wid,bid,bidx\n");
        for (int64_t node = 0; node < nodenum; ++node)
            std::fprintf(f, "%ld,%d,%ld,%ld\n", node, wid_of[node],
                         owned_idx[node] / block_size,
                         owned_idx[node] % block_size);
    }
};

}  // namespace dos
