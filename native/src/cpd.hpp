// CPD construction and shard container.
//
// The native build path: one reverse-Dijkstra sweep per owned target (the
// reference's approach, README.md:95 — contrast the JAX path's batched
// min-plus iteration in ops/bellman_ford.py), then first-move extraction
// with the shared tie-break rule (smallest slot). Produces the same int8
// [rows, N] block files as the Python side (npy.hpp), so indexes are
// interchangeable.
//
// In memory a shard can be kept raw (row-major int8, O(rows*N)) or
// run-length compressed (the reference's trade: CPD first-move rows are
// long runs — row storage drops ~50-100x on road networks at the cost of a
// binary search per lookup; SURVEY.md §7 notes this is wrong for TPU but
// right for a CPU resident server).
#pragma once

#include <algorithm>
#include <cstdint>
#include <limits>
#include <queue>
#include <vector>

#include "common.hpp"
#include "graph.hpp"
#include "npy.hpp"

namespace dos {

// d(x -> target) for all x: Dijkstra over in-edges from target.
inline void dist_to_target(const Graph& g, int64_t target,
                           const std::vector<int32_t>& w,
                           std::vector<int64_t>& dist /* [n], scratch */) {
    dist.assign(g.n, INF);
    dist[target] = 0;
    using QE = std::pair<int64_t, int64_t>;  // (dist, node)
    std::priority_queue<QE, std::vector<QE>, std::greater<QE>> pq;
    pq.emplace(0, target);
    while (!pq.empty()) {
        auto [d, v] = pq.top();
        pq.pop();
        if (d > dist[v]) continue;
        for (int64_t p = g.in_ptr[v]; p < g.in_ptr[v + 1]; ++p) {
            int32_t e = g.in_eid[p];
            int64_t u = g.src[e];
            int64_t nd = d + w[e];
            if (nd < dist[u]) {
                dist[u] = nd;
                pq.emplace(nd, u);
            }
        }
    }
}

// first-move row: slot k of x minimizing w[eid(x,k)] + d(nbr -> target);
// first minimal slot wins (models/reference.py first_move_to_target parity)
inline void first_move_row(const Graph& g, int64_t target,
                           const std::vector<int32_t>& w,
                           const std::vector<int64_t>& dist,
                           int8_t* row /* [n] */) {
    for (int64_t x = 0; x < g.n; ++x) {
        if (x == target) { row[x] = -1; continue; }
        int64_t best = INF;
        int8_t best_slot = -1;
        int32_t deg = g.out_degree(x);
        for (int32_t k = 0; k < deg; ++k) {
            int32_t e = g.out_edge_at(x, k);
            int64_t cand = w[e] + dist[g.dst[e]];
            if (cand < best) { best = cand; best_slot = static_cast<int8_t>(k); }
        }
        row[x] = best >= INF ? int8_t(-1) : best_slot;
    }
}

inline std::string block_name(int64_t wid, int64_t bid) {
    char buf[64];
    std::snprintf(buf, sizeof buf, "cpd-w%05ld-b%05ld.npy", wid, bid);
    return buf;
}

// ------------------------------------------------------ run-length rows

struct RleRow {
    // runs[i] = (start column, move); row value at c = move of the last
    // run with start <= c
    std::vector<std::pair<int32_t, int8_t>> runs;

    static RleRow encode(const int8_t* row, int64_t n) {
        RleRow r;
        for (int64_t c = 0; c < n; ++c)
            if (c == 0 || row[c] != row[c - 1])
                r.runs.emplace_back(static_cast<int32_t>(c), row[c]);
        return r;
    }

    int8_t lookup(int64_t c) const {
        auto it = std::upper_bound(
            runs.begin(), runs.end(),
            std::make_pair(static_cast<int32_t>(c),
                           std::numeric_limits<int8_t>::max()));
        return (--it)->second;
    }
};

// A worker's resident CPD shard: rows indexed by owned index of the target.
struct CpdShard {
    int64_t n = 0;          // columns (graph nodes)
    bool compressed = false;
    Int8Matrix raw;                 // when !compressed
    std::vector<RleRow> rle;        // when compressed

    int8_t first_move(int64_t row, int64_t x) const {
        return compressed ? rle[row].lookup(x) : raw.at(row, x);
    }

    // load all of a worker's block files from outdir (ascending bid)
    static CpdShard load(const std::string& outdir, int64_t wid,
                         int64_t n_owned, int64_t block_size,
                         bool compress) {
        CpdShard s;
        s.compressed = compress;
        int64_t n_blocks = (n_owned + block_size - 1) / block_size;
        int64_t row0 = 0;
        for (int64_t bid = 0; bid < n_blocks; ++bid) {
            Int8Matrix blk = npy_read_i8(outdir + "/" + block_name(wid, bid));
            if (s.n == 0) s.n = blk.cols;
            if (blk.cols != s.n) die("inconsistent CPD block width");
            if (compress) {
                for (int64_t r = 0; r < blk.rows; ++r)
                    s.rle.push_back(
                        RleRow::encode(&blk.data[r * blk.cols], blk.cols));
            } else {
                if (row0 == 0) {
                    s.raw.rows = n_owned;
                    s.raw.cols = blk.cols;
                    s.raw.data.resize(n_owned * blk.cols);
                }
                std::copy(blk.data.begin(), blk.data.end(),
                          s.raw.data.begin() + row0 * blk.cols);
            }
            row0 += blk.rows;
        }
        if (row0 != n_owned) die("CPD shard rows != owned node count");
        return s;
    }
};

}  // namespace dos
