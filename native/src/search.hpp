// Query-time algorithms: table-search and weighted A*.
//
// table-search (the distributed demo path, reference make_fifos.py:20):
// iterated first-move lookup from s toward t, cost accumulated on the
// possibly congestion-perturbed query weights while moves follow the
// free-flow table (SURVEY.md §0). Counter/timer vocabulary matches the
// response schema (reference process_query.py:198-213).
//
// A* (the hscale/fscale family implied by the reference's knobs,
// args.py:30-57): point-to-point weighted A* on the query-time weights
// with h = euclidean distance scaled by the graph's minimum cost-per-unit
// (admissible for hscale <= 1). f = g + hscale * h; hscale > 1 trades
// optimality for speed, fscale > 0 additionally prunes nodes whose f
// exceeds (1 + fscale) * best-known goal cost. Emits the classic
// priority-queue telemetry: n_expanded / n_inserted / n_touched /
// n_updated / n_surplus.
#pragma once

#include <cmath>
#include <cstdint>
#include <functional>
#include <queue>
#include <vector>

#include "common.hpp"
#include "graph.hpp"

namespace dos {

struct SearchStats {
    int64_t n_expanded = 0, n_inserted = 0, n_touched = 0, n_updated = 0,
            n_surplus = 0;
    int64_t plen = 0;
    int64_t finished = 0;

    void operator+=(const SearchStats& o) {
        n_expanded += o.n_expanded;
        n_inserted += o.n_inserted;
        n_touched += o.n_touched;
        n_updated += o.n_updated;
        n_surplus += o.n_surplus;
        plen += o.plen;
        finished += o.finished;
    }
};

struct QueryResult {
    int64_t cost = 0;
    int64_t plen = 0;
    bool finished = false;
};

// fm(x) -> slot toward the fixed target of this query
inline QueryResult table_search(const Graph& g,
                                const std::function<int8_t(int64_t)>& fm,
                                int64_t s, int64_t t,
                                const std::vector<int32_t>& w_query,
                                int64_t k_moves = -1) {
    QueryResult r;
    int64_t x = s;
    int64_t limit = k_moves < 0 ? g.n : k_moves;
    while (x != t && r.plen < limit) {
        int8_t slot = fm(x);
        if (slot < 0) break;
        int32_t e = g.out_edge_at(x, slot);
        r.cost += w_query[e];
        x = g.dst[e];
        ++r.plen;
    }
    r.finished = (x == t);
    return r;
}

// cost-per-coordinate-unit lower bound for the euclidean heuristic
inline double min_cost_per_unit(const Graph& g,
                                const std::vector<int32_t>& w) {
    double best = 1e300;
    for (int64_t e = 0; e < g.m; ++e) {
        double dx = double(g.xs[g.src[e]] - g.xs[g.dst[e]]);
        double dy = double(g.ys[g.src[e]] - g.ys[g.dst[e]]);
        double len = std::sqrt(dx * dx + dy * dy);
        if (len > 0) best = std::min(best, double(w[e]) / len);
    }
    return best == 1e300 ? 0.0 : best;
}

inline QueryResult astar(const Graph& g, int64_t s, int64_t t,
                         const std::vector<int32_t>& w_query,
                         double hscale, double fscale, SearchStats& stats,
                         double cpu /* precomputed min_cost_per_unit */) {
    auto h = [&](int64_t x) -> int64_t {
        double dx = double(g.xs[x] - g.xs[t]);
        double dy = double(g.ys[x] - g.ys[t]);
        return int64_t(std::sqrt(dx * dx + dy * dy) * cpu * hscale);
    };
    std::vector<int64_t> gcost(g.n, INF);
    std::vector<int64_t> parent_edge(g.n, -1);
    using QE = std::pair<int64_t, int64_t>;  // (f, node)
    std::priority_queue<QE, std::vector<QE>, std::greater<QE>> open;
    gcost[s] = 0;
    open.emplace(h(s), s);
    stats.n_inserted++;
    int64_t goal_cost = INF;
    while (!open.empty()) {
        auto [f, u] = open.top();
        open.pop();
        if (f > gcost[u] + h(u)) { stats.n_surplus++; continue; }
        if (u == t) { goal_cost = gcost[u]; break; }
        // fscale prune against the incumbent: gcost[t] is live as soon as
        // any relaxation reaches t, before t is ever popped
        if (fscale > 0 && gcost[t] < INF &&
            f > int64_t((1.0 + fscale) * double(gcost[t]))) {
            stats.n_surplus++;
            continue;
        }
        stats.n_expanded++;
        for (int64_t p = g.out_ptr[u]; p < g.out_ptr[u + 1]; ++p) {
            int32_t e = g.out_eid[p];
            int64_t v = g.dst[e];
            stats.n_touched++;
            int64_t ng = gcost[u] + w_query[e];
            if (ng < gcost[v]) {
                if (gcost[v] < INF) stats.n_updated++;
                gcost[v] = ng;
                parent_edge[v] = e;
                open.emplace(ng + h(v), v);
                stats.n_inserted++;
            }
        }
    }
    QueryResult r;
    r.finished = goal_cost < INF;
    r.cost = r.finished ? goal_cost : 0;
    if (r.finished)
        for (int64_t x = t; x != s; x = g.src[parent_edge[x]]) ++r.plen;
    stats.plen += r.plen;
    stats.finished += r.finished ? 1 : 0;
    return r;
}

}  // namespace dos
