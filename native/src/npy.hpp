// Minimal .npy v1.0 reader/writer for 2-D int8 arrays.
//
// CPD block files (cpd-w*-b*.npy) are shared between the Python/JAX side
// (numpy.save in models/cpd.py) and this engine: an index built by either
// side serves on the other. Only the |i1 dtype, C-order, 2-D case is
// supported — exactly what a first-move block is.
#pragma once

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include "common.hpp"

namespace dos {

struct Int8Matrix {
    int64_t rows = 0, cols = 0;
    std::vector<int8_t> data;  // row-major
    int8_t at(int64_t r, int64_t c) const { return data[r * cols + c]; }
};

inline void npy_write_i8_fd(FILE* f, const Int8Matrix& m) {
    std::string header = "{'descr': '|i1', 'fortran_order': False, "
                         "'shape': (" + std::to_string(m.rows) + ", " +
                         std::to_string(m.cols) + "), }";
    size_t base = 6 + 2 + 2;
    size_t total = base + header.size() + 1;
    size_t pad = (64 - total % 64) % 64;
    header.append(pad, ' ');
    header.push_back('\n');
    const unsigned char magic[8] = {0x93, 'N', 'U', 'M', 'P', 'Y', 1, 0};
    std::fwrite(magic, 1, 8, f);
    uint16_t hlen = static_cast<uint16_t>(header.size());
    std::fwrite(&hlen, 2, 1, f);
    std::fwrite(header.data(), 1, header.size(), f);
    std::fwrite(m.data.data(), 1, m.data.size(), f);
}

// Atomic write: temp file + rename, so a build killed mid-write never
// leaves a truncated block that a later resume would treat as complete.
inline void npy_write_i8(const std::string& path, const Int8Matrix& m) {
    std::string tmp = path + ".tmp";
    FILE* f = std::fopen(tmp.c_str(), "wb");
    if (!f) die("cannot write " + tmp);
    npy_write_i8_fd(f, m);
    bool ok = std::fflush(f) == 0;
    ok = (std::fclose(f) == 0) && ok;
    if (!ok || std::rename(tmp.c_str(), path.c_str()) != 0)
        die("cannot finalize " + path);
}


inline Int8Matrix npy_read_i8(const std::string& path) {
    FILE* f = std::fopen(path.c_str(), "rb");
    if (!f) die("cannot read " + path);
    unsigned char magic[8];
    if (std::fread(magic, 1, 8, f) != 8 || std::memcmp(magic, "\x93NUMPY", 6))
        die(path + ": not a .npy file");
    uint32_t hlen = 0;
    if (magic[6] == 1) {  // v1.0: 2-byte little-endian header length
        uint16_t h16;
        if (std::fread(&h16, 2, 1, f) != 1) die(path + ": truncated header");
        hlen = h16;
    } else {              // v2.0+: 4-byte
        if (std::fread(&hlen, 4, 1, f) != 1) die(path + ": truncated header");
    }
    std::string header(hlen, '\0');
    if (std::fread(header.data(), 1, hlen, f) != hlen)
        die(path + ": truncated header");
    if (header.find("'|i1'") == std::string::npos &&
        header.find("\"|i1\"") == std::string::npos)
        die(path + ": expected int8 (|i1) dtype");
    if (header.find("False") == std::string::npos)
        die(path + ": fortran_order arrays unsupported");
    size_t sp = header.find("'shape':");
    if (sp == std::string::npos) die(path + ": no shape in header");
    sp = header.find('(', sp);
    size_t ep = header.find(')', sp);
    std::string shape = header.substr(sp + 1, ep - sp - 1);
    Int8Matrix m;
    // a space in the scanf format matches any run of whitespace, so this
    // accepts "60,80", "60, 80", "60 , 80", ...
    if (std::sscanf(shape.c_str(), "%ld , %ld", &m.rows, &m.cols) != 2)
        die(path + ": unsupported shape '" + shape + "' (need 2-D)");
    m.data.resize(static_cast<size_t>(m.rows) * m.cols);
    if (std::fread(m.data.data(), 1, m.data.size(), f) != m.data.size())
        die(path + ": truncated data");
    std::fclose(f);
    return m;
}

}  // namespace dos
