"""Node→worker partitioning: the ``DistributionController``.

Role parity: the reference keeps its partition policy in a C++ header
(``src/util/distribution_controller.h``, reference ``README.md:31-34,75-80``)
exposed to Python only through the ``gen_distribute_conf`` binary, whose
stdout — a header line plus one ``node,wid,bid,bidx`` CSV row per node — is
parsed by the driver (reference ``process_query.py:46-53``). Passing the same
``(partmethod, partkey, workerid, maxworker)`` quadruple to the CPD builder,
the query servers, and the router is how build-time sharding and query-time
routing stay consistent.

Here the controller is a pure, vectorized Python function of
``(nodenum, maxworker, partmethod, partkey)`` — no subprocess hop — and it is
the exact seam where ``partmethod="tpu"`` lands: TPU partitions are
contiguous node chunks that map 1:1 onto ``jax.sharding.Mesh`` shards, so a
sharded ``[targets, N]`` first-move array indexed by *global target id* is
automatically laid out with each worker's rows on its own device.

Partition semantics (executable spec: reference ``offline.py:50-63``;
README.md:31-33):

* ``div``:   ``wid = node // partkey``
* ``mod``:   ``wid = node %  partkey``
* ``alloc``: ``wid = first i such that partkey[i] > node`` (partkey is a list
             of ascending exclusive upper bounds, one per worker)
* ``tpu``:   ``wid = node // ceil(nodenum / maxworker)`` — contiguous chunks
             sized to the mesh (partkey ignored)

Block structure: each worker's owned nodes, in ascending order, are split
into blocks of ``block_size``; ``bid`` is the block id and ``bidx`` the index
within the block (the reference's CPD builder emits one file per block:
``README.md:92``, and ``bid``/``bidx`` appear in ``gen_distribute_conf``
output). ``bid * block_size + bidx`` is the node's dense **owned index** —
its row in the worker's CPD shard.
"""

from __future__ import annotations

import numpy as np

DEFAULT_BLOCK_SIZE = 1 << 14


class DistributionController:
    def __init__(self, partmethod: str, partkey, maxworker: int,
                 nodenum: int, block_size: int = DEFAULT_BLOCK_SIZE):
        self.partmethod = partmethod
        self.partkey = partkey
        self.maxworker = int(maxworker)
        self.nodenum = int(nodenum)
        self.block_size = int(block_size)
        if self.maxworker <= 0:
            raise ValueError("maxworker must be positive")
        self._wid = self._assign_all()
        # dense owned index per node: position within its owner's ascending
        # owned-node list. Vectorized: stable argsort by (wid, node).
        order = np.argsort(self._wid, kind="stable")
        owned_idx = np.empty(self.nodenum, np.int64)
        counts = np.bincount(self._wid, minlength=self.maxworker)
        starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
        owned_idx[order] = np.arange(self.nodenum) - np.repeat(starts, counts)
        self._owned_idx = owned_idx
        self._counts = counts

    # ------------------------------------------------------------ policy
    def _assign_all(self) -> np.ndarray:
        nodes = np.arange(self.nodenum, dtype=np.int64)
        m = self.partmethod
        if m == "div":
            wid = nodes // int(self.partkey)
        elif m == "mod":
            wid = nodes % int(self.partkey)
        elif m == "alloc":
            bounds = np.asarray(self.partkey, np.int64)
            if np.any(np.diff(bounds) <= 0):
                raise ValueError("alloc bounds must be strictly ascending")
            wid = np.searchsorted(bounds, nodes, side="right")
        elif m == "tpu":
            chunk = -(-self.nodenum // self.maxworker)  # ceil div
            wid = nodes // chunk
        else:
            raise ValueError(f"unknown partmethod {m!r}")
        if self.nodenum and (wid.min() < 0 or wid.max() >= self.maxworker):
            raise ValueError(
                f"partmethod={m} partkey={self.partkey} maps some node to "
                f"worker {int(wid.max())} but maxworker={self.maxworker}")
        return wid.astype(np.int64)

    # ------------------------------------------------------------ queries
    def worker_of(self, nodes) -> np.ndarray:
        """wid for each node (vectorized)."""
        return self._wid[np.asarray(nodes, np.int64)]

    def owned_index_of(self, nodes) -> np.ndarray:
        """Dense row index of each node within its owner's CPD shard."""
        return self._owned_idx[np.asarray(nodes, np.int64)]

    def owned(self, wid: int) -> np.ndarray:
        """Ascending node ids owned by ``wid``."""
        return np.nonzero(self._wid == wid)[0].astype(np.int64)

    def n_owned(self, wid: int) -> int:
        return int(self._counts[wid])

    @property
    def max_owned(self) -> int:
        """Largest shard size — the padded per-device row count in TPU mode."""
        return int(self._counts.max()) if self.nodenum else 0

    def table(self) -> np.ndarray:
        """int64 [N, 4] rows of (node, wid, bid, bidx) — the
        ``gen_distribute_conf`` payload."""
        nodes = np.arange(self.nodenum, dtype=np.int64)
        bid = self._owned_idx // self.block_size
        bidx = self._owned_idx % self.block_size
        return np.stack([nodes, self._wid, bid, bidx], axis=1)

    def format_conf(self) -> str:
        """The wire format the reference driver parses: one header line, then
        ``node,wid,bid,bidx`` per node (reference ``process_query.py:50-53``)."""
        rows = self.table()
        lines = ["node,wid,bid,bidx"]
        lines += [f"{a},{b},{c},{d}" for a, b, c, d in rows]
        return "\n".join(lines)

    # ------------------------------------------------------------ routing
    def group_queries(self, queries: np.ndarray, active_worker: int = -1):
        """Group (s, t) queries by the worker owning the **target** node — the
        system invariant (reference ``process_query.py:56-57``).

        Returns ``{wid: int64 [q, 2] array}`` with empty groups omitted, like
        the reference's parts list skips empty workers
        (``process_query.py:62``). ``active_worker`` restricts to one worker
        (the ``-w`` flag), -1 = all.
        """
        queries = np.asarray(queries, np.int64)
        wids = self.worker_of(queries[:, 1])
        groups = {}
        for wid in range(self.maxworker):
            if active_worker != -1 and wid != active_worker:
                continue
            mask = wids == wid
            if mask.any():
                groups[wid] = queries[mask]
        return groups
