"""Node→worker partitioning: the ``DistributionController``.

Role parity: the reference keeps its partition policy in a C++ header
(``src/util/distribution_controller.h``, reference ``README.md:31-34,75-80``)
exposed to Python only through the ``gen_distribute_conf`` binary, whose
stdout — a header line plus one ``node,wid,bid,bidx`` CSV row per node — is
parsed by the driver (reference ``process_query.py:46-53``). Passing the same
``(partmethod, partkey, workerid, maxworker)`` quadruple to the CPD builder,
the query servers, and the router is how build-time sharding and query-time
routing stay consistent.

Here the controller is a pure, vectorized Python function of
``(nodenum, maxworker, partmethod, partkey)`` — no subprocess hop — and it is
the exact seam where ``partmethod="tpu"`` lands: TPU partitions are
contiguous node chunks that map 1:1 onto ``jax.sharding.Mesh`` shards, so a
sharded ``[targets, N]`` first-move array indexed by *global target id* is
automatically laid out with each worker's rows on its own device.

Partition semantics (executable spec: reference ``offline.py:50-63``;
README.md:31-33):

* ``div``:   ``wid = node // partkey``
* ``mod``:   ``wid = node %  partkey``
* ``alloc``: ``wid = first i such that partkey[i] > node`` (partkey is a list
             of ascending exclusive upper bounds, one per worker)
* ``tpu``:   ``wid = node // ceil(nodenum / maxworker)`` — contiguous chunks
             sized to the mesh (partkey ignored)

Block structure: each worker's owned nodes, in ascending order, are split
into blocks of ``block_size``; ``bid`` is the block id and ``bidx`` the index
within the block (the reference's CPD builder emits one file per block:
``README.md:92``, and ``bid``/``bidx`` appear in ``gen_distribute_conf``
output). ``bid * block_size + bidx`` is the node's dense **owned index** —
its row in the worker's CPD shard.

Replication (``replication`` / ``DOS_REPLICATION``, default 1): replica
rank ``r`` of every node owned by worker ``w`` lives on worker
``(w + r) % maxworker`` — chained declustering, a pure function of the
primary partition table, so every head and worker derives the identical
replica map from the same quadruple with no extra coordination. Rank 0
is the primary; :meth:`DistributionController.replica_workers` is the
failover order the head walks when a primary is dead, and
:meth:`DistributionController.replica_shards` is the set of shards a
worker must hold rows for. ``replication=1`` is byte-for-byte today's
behavior everywhere (placement, wire format, artifacts).

Elastic membership (``epoch`` / ``owners``): the node→**shard** map
above is fixed at build time (shard count = ``maxworker``), but the
shard→**worker** assignment is versioned. ``owners[s]`` names the
worker currently hosting shard ``s`` (identity by default — shard s on
worker s, today's behavior byte-for-byte), and ``epoch`` is the
monotonically increasing version of that assignment, bumped atomically
by the reconfiguration controller (``parallel.membership``) whenever a
worker joins or leaves. Every routing surface (``replica_workers``,
``group_queries``'s dead-remap, the serving frontend's candidate sets)
maps shard ids through ``owners``, so a committed epoch bump flips
traffic without touching the partition quadruple or the on-disk block
files. ``format_conf`` appends ``epoch``/``owner`` columns only for
non-default assignments — legacy epoch-0 identity tables stay
byte-identical on the wire, and ``parse_conf`` reads the columns by
header name under the same unknown-column-tolerant compat contract as
the ``rep<r>`` columns.
"""

from __future__ import annotations

import numpy as np

DEFAULT_BLOCK_SIZE = 1 << 14

#: the replica bucket :meth:`DistributionController.group_queries`
#: returns queries under when EVERY replica of their target shard is in
#: the caller's dead set — the caller must shed these UNAVAILABLE
#: immediately instead of routing (or hanging on) a dead worker
UNROUTABLE = -1


class DistributionController:
    def __init__(self, partmethod: str, partkey, maxworker: int,
                 nodenum: int, block_size: int = DEFAULT_BLOCK_SIZE,
                 replication: int = 1, epoch: int = 0, owners=None):
        self.partmethod = partmethod
        self.partkey = partkey
        self.maxworker = int(maxworker)
        self.nodenum = int(nodenum)
        self.block_size = int(block_size)
        self.replication = int(replication)
        self.epoch = int(epoch)
        if self.maxworker <= 0:
            raise ValueError("maxworker must be positive")
        if not 1 <= self.replication <= self.maxworker:
            raise ValueError(
                f"replication {self.replication} not in [1, "
                f"maxworker={self.maxworker}]: every replica rank must "
                "land on a distinct worker")
        if self.epoch < 0:
            raise ValueError(f"epoch must be >= 0, got {self.epoch}")
        if owners is None:
            self.owners = np.arange(self.maxworker, dtype=np.int64)
        else:
            self.owners = np.asarray(owners, np.int64)
            if self.owners.shape != (self.maxworker,):
                raise ValueError(
                    f"owners must name one worker per shard "
                    f"(maxworker={self.maxworker}), got shape "
                    f"{self.owners.shape}")
            if self.nodenum and self.owners.min() < 0:
                raise ValueError("owners must be non-negative worker ids")
        #: identity assignment = the pre-elastic fleet, byte-for-byte
        self._identity_owners = bool(
            (self.owners == np.arange(self.maxworker)).all())
        self._wid = self._assign_all()
        # dense owned index per node: position within its owner's ascending
        # owned-node list. Vectorized: stable argsort by (wid, node).
        order = np.argsort(self._wid, kind="stable")
        owned_idx = np.empty(self.nodenum, np.int64)
        counts = np.bincount(self._wid, minlength=self.maxworker)
        starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
        owned_idx[order] = np.arange(self.nodenum) - np.repeat(starts, counts)
        self._owned_idx = owned_idx
        self._counts = counts

    # ------------------------------------------------------------ policy
    def _assign_all(self) -> np.ndarray:
        nodes = np.arange(self.nodenum, dtype=np.int64)
        m = self.partmethod
        if m == "div":
            wid = nodes // int(self.partkey)
        elif m == "mod":
            wid = nodes % int(self.partkey)
        elif m == "alloc":
            bounds = np.asarray(self.partkey, np.int64)
            if np.any(np.diff(bounds) <= 0):
                raise ValueError("alloc bounds must be strictly ascending")
            wid = np.searchsorted(bounds, nodes, side="right")
        elif m == "tpu":
            chunk = -(-self.nodenum // self.maxworker)  # ceil div
            wid = nodes // chunk
        else:
            raise ValueError(f"unknown partmethod {m!r}")
        if self.nodenum and (wid.min() < 0 or wid.max() >= self.maxworker):
            raise ValueError(
                f"partmethod={m} partkey={self.partkey} maps some node to "
                f"worker {int(wid.max())} but maxworker={self.maxworker}")
        return wid.astype(np.int64)

    # ------------------------------------------------------------ queries
    def worker_of(self, nodes) -> np.ndarray:
        """wid for each node (vectorized)."""
        return self._wid[np.asarray(nodes, np.int64)]

    def owned_index_of(self, nodes) -> np.ndarray:
        """Dense row index of each node within its owner's CPD shard."""
        return self._owned_idx[np.asarray(nodes, np.int64)]

    def owned(self, wid: int) -> np.ndarray:
        """Ascending node ids owned by ``wid``."""
        return np.nonzero(self._wid == wid)[0].astype(np.int64)

    def n_owned(self, wid: int) -> int:
        return int(self._counts[wid])

    @property
    def max_owned(self) -> int:
        """Largest shard size — the padded per-device row count in TPU mode."""
        return int(self._counts.max()) if self.nodenum else 0

    # ---------------------------------------------------------- replicas
    def owner_of(self, shard: int) -> int:
        """The worker currently hosting shard ``shard``'s primary rows
        (identity — shard s on worker s — unless a membership epoch
        reassigned it)."""
        return int(self.owners[int(shard)])

    def _chain_shards(self, wid: int) -> list[int]:
        """Shard ids in shard ``wid``'s replica chain, rank order."""
        return [(int(wid) + r) % self.maxworker
                for r in range(self.replication)]

    def replica_workers(self, wid: int) -> list[int]:
        """Workers hosting shard ``wid``'s rows, in failover order:
        rank 0 the shard's owner (worker ``wid`` itself under the
        identity assignment), rank r the owner of chain slot
        ``(wid + r) % maxworker``. Length == ``replication``."""
        if self._identity_owners:
            return self._chain_shards(wid)
        return [self.owner_of(s) for s in self._chain_shards(wid)]

    def replica_shards(self, wid: int) -> list[int]:
        """Shards worker ``wid`` hosts rows for: the shard(s) it owns
        (rank 0) plus the shard whose rank-r chain slot it owns. The
        inverse of :meth:`replica_workers` (identity assignment: its
        own shard plus ``(wid - r) % maxworker``)."""
        if self._identity_owners and int(wid) < self.maxworker:
            # the fast path only holds for in-range ids: a fresh
            # joiner (wid >= maxworker) hosts nothing under identity —
            # the modulo would wrongly claim another worker's shard
            return [(int(wid) - r) % self.maxworker
                    for r in range(self.replication)]
        out = []
        for shard in range(self.maxworker):
            if int(wid) in self.replica_workers(shard):
                out.append(shard)
        return out

    def replica_rank(self, shard: int, host: int) -> int:
        """The replica rank with which worker ``host`` holds ``shard``'s
        rows (0 = primary/owner). Raises ``ValueError`` when ``host`` is
        not in the shard's replica set."""
        if self._identity_owners and int(host) < self.maxworker:
            r = (int(host) - int(shard)) % self.maxworker
            if r >= self.replication:
                raise ValueError(
                    f"worker {host} holds no replica of shard {shard} "
                    f"(replication={self.replication})")
            return r
        hosts = self.replica_workers(shard)
        if int(host) not in hosts:
            raise ValueError(
                f"worker {host} holds no replica of shard {shard} "
                f"(hosts: {hosts})")
        return hosts.index(int(host))

    def table(self) -> np.ndarray:
        """int64 [N, 4] rows of (node, wid, bid, bidx) — the
        ``gen_distribute_conf`` payload."""
        nodes = np.arange(self.nodenum, dtype=np.int64)
        bid = self._owned_idx // self.block_size
        bidx = self._owned_idx % self.block_size
        return np.stack([nodes, self._wid, bid, bidx], axis=1)

    def replica_table(self) -> np.ndarray:
        """int64 [N, replication-1]: column r-1 is the worker hosting
        replica rank r of each node. Empty (0 columns) at R=1."""
        cols = [self.owners[(self._wid + r) % self.maxworker]
                for r in range(1, self.replication)]
        if not cols:
            return np.zeros((self.nodenum, 0), np.int64)
        return np.stack(cols, axis=1)

    def format_conf(self) -> str:
        """The wire format the reference driver parses: one header line,
        then ``node,wid,bid,bidx`` per node (reference
        ``process_query.py:50-53``). With replication, ``rep<r>`` columns
        (the rank-r replica's worker) append on the right; an elastic
        table (``epoch > 0`` or a non-identity assignment) additionally
        appends ``epoch`` (the table's version, constant per row) and
        ``owner`` (the worker hosting the node's shard) columns — same
        compat contract as the wire codecs: readers take columns by
        header name and tolerate unknown ones, so an R=1 consumer
        reading the first four columns of an elastic table still routes
        on the primary shard, and epoch-0 identity R=1 output is
        byte-identical to the legacy format."""
        rows = self.table()
        rep = self.replica_table()
        elastic = self.epoch > 0 or not self._identity_owners
        header = "node,wid,bid,bidx" + "".join(
            f",rep{r}" for r in range(1, self.replication))
        if elastic:
            header += ",epoch,owner"
        lines = [header]
        if elastic:
            own = self.owners[self._wid]
            lines += [",".join(map(str, [*row, *reps, self.epoch, o]))
                      for row, reps, o in zip(rows, rep, own)]
        else:
            lines += [",".join(map(str, [*row, *reps]))
                      for row, reps in zip(rows, rep)]
        return "\n".join(lines)

    # ------------------------------------------------------------ routing
    def group_queries(self, queries: np.ndarray, active_worker: int = -1,
                      dead=()):
        """Group (s, t) queries by the worker owning the **target** node — the
        system invariant (reference ``process_query.py:56-57``).

        Returns ``{wid: int64 [q, 2] array}`` with empty groups omitted, like
        the reference's parts list skips empty workers
        (``process_query.py:62``). ``active_worker`` restricts to one worker
        (the ``-w`` flag), -1 = all.

        ``dead``: worker ids known down. Each query routes to the FIRST
        live worker in its target shard's replica chain
        (:meth:`replica_workers`); queries whose every replica is dead
        come back under the :data:`UNROUTABLE` key so the caller can
        shed them immediately instead of hanging on a dead worker. With
        ``dead`` empty (the default) routing is identical to the
        pre-replication behavior regardless of ``replication``.
        """
        queries = np.asarray(queries, np.int64)
        wids = self.worker_of(queries[:, 1])
        dead = set(int(d) for d in dead)
        if dead:
            # remap each primary wid to its first live replica host
            # (UNROUTABLE when the whole chain is dead) — one pass over
            # the W shard ids, then a vectorized gather
            remap = np.empty(self.maxworker, np.int64)
            for shard in range(self.maxworker):
                remap[shard] = next(
                    (h for h in self.replica_workers(shard)
                     if h not in dead), UNROUTABLE)
            wids = remap[wids]
        groups = {}
        # bucket over the ids actually PRESENT (ascending, UNROUTABLE
        # first — np.unique sorts, so iteration order matches the old
        # range(maxworker) walk exactly): a dead-remap through an
        # elastic owner table can name a joined worker past maxworker,
        # and a fixed range would silently drop its queries
        for wid in (int(w) for w in np.unique(wids)):
            if active_worker != -1 and wid != active_worker \
                    and wid != UNROUTABLE:
                continue
            groups[wid] = queries[wids == wid]
        return groups


def parse_conf(text: str) -> dict:
    """Parse :meth:`DistributionController.format_conf` output back into
    arrays — the consumer half of the ``gen_distribute_conf`` wire.

    Columns are taken BY HEADER NAME with unknown columns tolerated
    (the wire codecs' compat contract): a legacy R=1 table (no ``rep*``
    columns) parses with ``replication == 1``, an R>1 table parsed by
    old code that only reads the first four columns still routes on the
    primary, and future columns cannot break this parser. Elastic
    tables add ``epoch`` (constant table version; an epoch-less legacy
    conf parses as epoch 0) and ``owner`` (the worker hosting each
    node's shard; absent = the shard id itself) columns.

    Returns ``{"node", "wid", "bid", "bidx", "owner": int64 [N];
    "replicas": int64 [N, R-1]; "replication": R; "epoch": int}``.
    """
    lines = [ln for ln in text.strip().split("\n") if ln.strip()]
    if not lines:
        raise ValueError("empty distribute conf")
    header = [h.strip() for h in lines[0].split(",")]
    for required in ("node", "wid", "bid", "bidx"):
        if required not in header:
            raise ValueError(
                f"distribute conf header is missing {required!r}: "
                f"{lines[0]!r}")
    rep_cols = sorted(
        (h for h in header if h.startswith("rep") and h[3:].isdigit()),
        key=lambda h: int(h[3:]))
    ranks = [int(h[3:]) for h in rep_cols]
    if ranks != list(range(1, len(ranks) + 1)):
        raise ValueError(f"replica columns are not ranks 1..R-1: "
                         f"{rep_cols}")
    idx = {h: i for i, h in enumerate(header)}
    parsed = []
    for ln in lines[1:]:
        vals = ln.split(",")
        if len(vals) < len(header):
            raise ValueError(f"row has {len(vals)} columns, header "
                             f"names {len(header)}: {ln!r}")
        parsed.append([int(v) for v in vals[:len(header)]])
    rows = np.asarray(parsed, np.int64).reshape(len(lines) - 1,
                                                len(header))
    out = {k: rows[:, idx[k]] for k in ("node", "wid", "bid", "bidx")}
    out["replicas"] = (rows[:, [idx[c] for c in rep_cols]]
                       if rep_cols
                       else np.zeros((len(rows), 0), np.int64))
    out["replication"] = len(rep_cols) + 1
    if "epoch" in idx:
        epochs = np.unique(rows[:, idx["epoch"]])
        if len(epochs) > 1:
            raise ValueError(
                f"distribute conf mixes epochs {epochs.tolist()} — a "
                "table is one atomic assignment version")
        out["epoch"] = int(epochs[0]) if len(epochs) else 0
    else:
        out["epoch"] = 0          # legacy epoch-less conf
    out["owner"] = (rows[:, idx["owner"]] if "owner" in idx
                    else out["wid"].copy())
    return out
