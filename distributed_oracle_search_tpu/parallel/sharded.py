"""Sharded CPD build and sharded query execution.

The two distributed phases of the system, on a device mesh:

* **Build** (reference: per-worker ``make_cpd_auto`` processes launched over
  ssh/tmux, SURVEY.md §3.1): every mesh shard computes first-move rows for
  the targets it owns, in parallel, with zero cross-shard traffic — the
  batch axis of the min-plus iteration is sharded over ``worker``, the graph
  is replicated, and GSPMD keeps each row's computation on its row's device.
  The only collective is the all-reduce of the convergence flag inside the
  Bellman-Ford ``while_loop``.

* **Query** (reference: per-worker FIFO round-trips driven by a head-node
  thread pool, SURVEY.md §3.3): queries arrive pre-routed ``[D, W, Q]`` (row
  w = queries whose target w owns, the invariant of
  ``process_query.py:56-57``), an optional leading data axis splits the
  batch, and each shard walks its own queries against its own fm rows via
  ``shard_map`` — explicitly no resharding of the fm table.

Compiled programs are cached at module level, keyed on (mesh, static
shape knobs): a resident server calls these thousands of times, and an
eagerly re-traced shard_map would pay a device round-trip per while_loop
iteration — catastrophic over a remote-TPU link.

Padding convention: rectangular arrays everywhere; targets pad with -1,
queries pad with ``valid=False`` rows. Padding is computed-but-masked, the
usual SPMD trade.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import DeviceGraph, table_search_batch
from .mesh import WORKER_AXIS, DATA_AXIS, LANE_AXIS, replicated

# jax moved shard_map to the top-level namespace after 0.4.x; older
# releases only ship the experimental spelling, whose replication
# checker cannot handle the relaxation while_loops (check_rep=False is
# the documented workaround and a no-op for correctness here: every
# out_spec names the worker axis explicitly)
if hasattr(jax, "shard_map"):
    _shard_map = jax.shard_map
else:  # pragma: no cover - exercised only on older jax
    from jax.experimental.shard_map import shard_map as _xshard_map

    def _shard_map(f, **kwargs):
        kwargs.setdefault("check_rep", False)
        return _xshard_map(f, **kwargs)


def pad_targets(controller, dtype=np.int32) -> np.ndarray:
    """[W, R] owned targets per worker, -1-padded to the max shard size."""
    w = controller.maxworker
    r = max(controller.max_owned, 1)
    out = np.full((w, r), -1, dtype)
    for wid in range(w):
        owned = controller.owned(wid)
        out[wid, :len(owned)] = owned
    return out


# --------------------------------------------------------------------- build

@functools.lru_cache(maxsize=None)
def _build_fn(mesh: Mesh, n_workers: int, max_iters: int,
              with_dists: bool, kind: str = "ell",
              kernel_sig: tuple | None = None,
              axis: str = WORKER_AXIS):
    """One compiled sharded builder for all three relaxation kernels.

    ``kind`` selects the distance stage: ``"sweep"`` (fast-sweeping grid
    scans, sig ``(h, w, shifts, n_left)``), ``"shift"`` (gather-free shift
    relaxation, sig ``(shifts, n, k_left)``), ``"frontier"``
    (delta-stepping queue, sig ``(n, f, delta, s_unroll)``),
    ``"ellsplit"`` or ``"ell"`` (padded-ELL gather, no sig). Extra kernel
    operands arrive replicated. Everything else — shardings, target
    layout, first-move extraction, with_dists outputs — is shared, so
    the paths cannot drift.

    Runs under ``shard_map`` so each shard's relaxation ``while_loop``
    converges on its OWN flag — no per-sweep all-reduce, no
    slowest-shard coupling (a GSPMD-jit build had a single global loop:
    every shard swept until the last one converged, which is why the
    round-2 weak-scaling bench REGRESSED with worker count).
    """
    from ..ops.bellman_ford import dist_to_targets, first_move_from_dist
    from ..ops.ell_split import _ellsplit_dist_fn
    from ..ops.frontier_relax import _frontier_dist_fn
    from ..ops.grid_sweep import _sweep_dist_fn
    from ..ops.shift_relax import _dist_fn

    frontier = False
    if kind == "sweep":
        n_kernel_ops = 8
        kernel_dist = _sweep_dist_fn(*kernel_sig, max_iters)
    elif kind == "shift":
        n_kernel_ops = 3
        kernel_dist = _dist_fn(*kernel_sig, max_iters)
    elif kind == "ellsplit":
        n_kernel_ops = 5
        kernel_dist = _ellsplit_dist_fn(*kernel_sig, max_iters)
    elif kind == "frontier":
        # frontier consumes the DeviceGraph arrays too (sig carries the
        # queue knobs); only in_nbr is an extra operand
        n_kernel_ops = 1
        frontier = True
        kernel_dist = _frontier_dist_fn(*kernel_sig, max_iters)
    else:
        n_kernel_ops = 0
        kernel_dist = None

    def _local(dg, *ops_and_tgt):
        # local blocks: tgt [B, 1] (this shard's column); graph + kernel
        # operands replicated
        *kernel_ops, tgt_b1 = ops_and_tgt
        tgts = tgt_b1.reshape(-1)
        if frontier:
            dist = kernel_dist(dg.out_nbr, dg.out_eid, dg.w_pad,
                               *kernel_ops, tgts)
        elif kernel_dist is not None:
            dist = kernel_dist(*kernel_ops, tgts)
        else:
            dist = dist_to_targets(dg, tgts, max_iters=max_iters)
        fm = first_move_from_dist(dg, tgts, dist)
        if with_dists:
            return fm[None], dist[None]
        return fm[None]

    out_spec = P(axis, None, None)
    sm = _shard_map(
        _local, mesh=mesh,
        in_specs=(P(), *([P()] * n_kernel_ops), P(None, axis)),
        out_specs=(out_spec, out_spec) if with_dists else out_spec,
    )
    return jax.jit(sm)


def build_fm_sharded(dg: DeviceGraph, targets_wr: np.ndarray,
                     mesh: Mesh, chunk: int = 0,
                     max_iters: int = 0, with_dists: bool = False,
                     kernel=None, axis: str = WORKER_AXIS):
    """Build the full sharded CPD: int8 [W, R, N], axis 0 on ``worker``.

    ``chunk`` bounds per-device live distance rows (0 = whole shard at
    once): the host loops over column-chunks of ``targets_wr`` so each
    device only ever materializes ``[chunk, N]`` int32 distances, then
    concatenates the int8 results — the memory staging the reference gets
    from per-block CPD files (``README.md:92``).

    ``with_dists=True`` additionally returns the converged distance table
    int32 [W, R, N] (4x the fm memory): free-flow queries then need no
    walk at all — one gather answers d(s→t) (SURVEY.md §5: "distance-only
    answers need no extraction").

    ``kernel``: optional ``(kind, structure)`` from
    ``models.cpd.pick_build_kernel`` — selects the fast-sweeping /
    shift / ELL distance stage (default ELL).

    ``axis``: the mesh axis the target rows shard over — the campaign
    mesh's ``worker`` axis by default, or a worker-local mesh's
    ``lane`` axis (:func:`build_fm_lanes`): the per-target computation
    is axis-agnostic, only the sharding spec names change.
    """
    w, r = targets_wr.shape
    if mesh.shape[axis] != w:
        raise ValueError(
            f"targets rows ({w}) != mesh {axis} axis "
            f"({mesh.shape[axis]})")
    kind, st = kernel if kernel is not None else ("ell", None)
    if kind == "sweep":
        fn = _build_fn(mesh, w, max_iters, with_dists, kind="sweep",
                       kernel_sig=(st.height, st.width, st.shifts,
                                   st.n_left), axis=axis)
        build = lambda dg_, t_: fn(  # noqa: E731
            dg_, st.wl, st.wr, st.wd, st.wu, st.w_shift, st.src_left,
            st.dst_left, st.w_left, t_)
    elif kind == "shift":
        fn = _build_fn(mesh, w, max_iters, with_dists, kind="shift",
                       kernel_sig=(st.shifts, st.n, st.k_left),
                       axis=axis)
        build = lambda dg_, t_: fn(  # noqa: E731
            dg_, st.w_shift, st.nbr_left, st.w_left, t_)
    elif kind == "ellsplit":
        fn = _build_fn(mesh, w, max_iters, with_dists, kind="ellsplit",
                       kernel_sig=(st.n, st.k0, len(st.u_ov)),
                       axis=axis)
        build = lambda dg_, t_: fn(  # noqa: E731
            dg_, st.nbr0, st.w0, st.u_ov, st.v_ov, st.w_ov, t_)
    elif kind == "frontier":
        fn = _build_fn(mesh, w, max_iters, with_dists, kind="frontier",
                       kernel_sig=(st.n, st.f, st.delta, st.s_unroll),
                       axis=axis)
        build = lambda dg_, t_: fn(dg_, st.in_nbr, t_)  # noqa: E731
    else:
        build = _build_fn(mesh, w, max_iters, with_dists, axis=axis)
    if chunk <= 0 or chunk >= r:
        chunks = [targets_wr]
    else:
        # equal chunk sizes (pad the target list) so every chunk hits the
        # same compiled program
        pad = (-r) % chunk
        if pad:
            targets_wr = np.concatenate(
                [targets_wr, np.full((w, pad), -1, targets_wr.dtype)], axis=1)
        chunks = [targets_wr[:, i:i + chunk]
                  for i in range(0, targets_wr.shape[1], chunk)]
    parts = [build(dg, jnp.asarray(c.T)) for c in chunks]
    if with_dists:
        fms, dists = zip(*parts)
        fm = fms[0] if len(fms) == 1 else jnp.concatenate(fms, axis=1)
        dist = (dists[0] if len(dists) == 1
                else jnp.concatenate(dists, axis=1))
        return fm[:, :r], dist[:, :r]
    fm = parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=1)
    return fm[:, :r]


# ------------------------------------------------------- worker lanes
#
# Worker-LOCAL multi-device execution (the ``lane`` axis,
# ``parallel.mesh.make_worker_mesh``): one worker process drives several
# devices, splitting its own batches/build chunks across them. Unlike
# the campaign mesh above, nothing here crosses shards — the fm rows
# are this ONE shard's and replicate over lanes; only the query/target
# axis splits. Every lane function is bit-identical to its
# single-device twin (per-query/per-target computations are
# independent; tests/test_mesh.py pins 1/2/4/8 lanes).

def build_fm_lanes(dg: DeviceGraph, pad: np.ndarray, mesh: Mesh,
                   kind: str, structure, max_iters: int = 0):
    """One build chunk's target pad (int32 ``[C]``, -1-padded) computed
    across the worker's lanes: lane l builds the contiguous rows
    ``pad[l*C/L:(l+1)*C/L]``. Returns the async device fm block
    ``[C, N]`` in original target order — the same contract as the
    single-device chunk compute, so the pipelined build's stager/flush
    machinery is unchanged. ``C`` must divide by the lane count
    (callers gate; pads are fixed pow2-friendly shapes)."""
    lanes = mesh.shape[LANE_AXIS]
    c = int(np.asarray(pad).shape[0])
    targets_lr = np.asarray(pad, np.int32).reshape(lanes, c // lanes)
    fm = build_fm_sharded(dg, targets_lr, mesh, chunk=0,
                          max_iters=max_iters,
                          kernel=(kind, structure), axis=LANE_AXIS)
    return fm.reshape(c, -1)


@functools.lru_cache(maxsize=None)
def _lane_walk_fn(mesh: Mesh, max_steps: int, k_moves: int,
                  kernel: str):
    """One compiled lane-split walk: queries ``[L, Qb]`` sharded over
    ``lane``, the shard's fm replicated. ``kernel`` joins the cache key
    exactly like ``_query_fn``'s — each lane runs its bucket subset
    through the Pallas or XLA walk unchanged."""
    q2 = P(LANE_AXIS, None)

    def _local(dg, fm, rows, s, t, valid, w_pad):
        shape = s.shape
        if kernel == "pallas":
            from ..ops.pallas_walk import pallas_walk_batch as walk
        else:
            walk = table_search_batch
        cost, plen, fin = walk(
            dg, fm, rows.reshape(-1), s.reshape(-1), t.reshape(-1),
            w_pad, valid=valid.reshape(-1), k_moves=k_moves,
            max_steps=max_steps)
        return (cost.reshape(shape), plen.reshape(shape),
                fin.reshape(shape))

    sm = _shard_map(
        _local, mesh=mesh,
        in_specs=(P(), P(), q2, q2, q2, q2, P()),
        out_specs=(q2, q2, q2),
    )
    return jax.jit(sm)


def lane_walk_program(dg: DeviceGraph, fm, t_rows, s, t, valid, w_pad,
                      mesh: Mesh, k_moves: int = -1,
                      max_steps: int = 0, kernel: str = "xla"):
    """``(jitted_fn, operands)`` of one lane-split walk call — the same
    cached jit :func:`walk_lanes` dispatches, with the flat ``[Q]``
    query arrays reshaped to ``[L, Q/L]`` and lane-sharded exactly as
    it ships them. Split out so the engine's AOT cost capture lowers
    the program the mesh path ACTUALLY ran (an XLA cache hit), instead
    of going dark under lanes."""
    lanes = mesh.shape[LANE_AXIS]
    q = int(np.asarray(s).shape[0])
    qs = NamedSharding(mesh, P(LANE_AXIS, None))
    packed = tuple(np.asarray(a).reshape(lanes, q // lanes)
                   for a in (t_rows, s, t, valid))
    # ONE device_put for the whole pack (same rationale as
    # query_sharded: each separate transfer pays a fixed round trip)
    args = jax.device_put(packed, qs)
    fn = _lane_walk_fn(mesh, max_steps, int(k_moves), str(kernel))
    return fn, (dg, fm, *args, w_pad)


def walk_lanes(dg: DeviceGraph, fm, t_rows, s, t, valid, w_pad,
               mesh: Mesh, k_moves: int = -1, max_steps: int = 0,
               kernel: str = "xla"):
    """Split one worker's walk batch across its lanes.

    Flat ``[Q]`` inputs (the engine's est-sorted, pow2-padded batch);
    ``Q`` must divide by the lane count (the engine gates). Lane l
    walks the contiguous slice ``[l*Q/L, (l+1)*Q/L)`` — contiguous in
    the sorted order, so each lane's auto-bucketing
    (``pick_buckets``) sees the same monotone length profile the
    single-device kernel does, and results are bucket-invariant
    (pinned), hence bit-identical after the flat reshape back.
    Returns ``(cost, plen, finished)`` flat ``[Q]`` device arrays."""
    q = int(np.asarray(s).shape[0])
    fn, ops = lane_walk_program(dg, fm, t_rows, s, t, valid, w_pad,
                                mesh, k_moves=k_moves,
                                max_steps=max_steps, kernel=kernel)
    cost, plen, fin = fn(*ops)
    return cost.reshape(q), plen.reshape(q), fin.reshape(q)


@functools.lru_cache(maxsize=None)
def _mat_fn(mesh: Mesh, k_out: int, max_steps: int):
    """One-to-many ETA row with the JOIN ON MESH: each shard walks its
    routed slice, scatters its answers into a dense ``[k_out]`` row at
    the slot positions the router assigned, and a ``psum`` over both
    mesh axes assembles the complete row as a collective — no head-side
    fan-out/join, no per-target result plumbing."""
    q3 = P(DATA_AXIS, WORKER_AXIS, None)

    def _local(dg, fm_local, rows, s, t, valid, slots, w_pad):
        v = valid.reshape(-1)
        cost, _plen, fin = table_search_batch(
            dg, fm_local[0], rows.reshape(-1), s.reshape(-1),
            t.reshape(-1), w_pad, valid=v, k_moves=-1,
            max_steps=max_steps)
        # scatter-add into [k_out + 1]: pad slots dump into the extra
        # slot; every real target index lives in exactly ONE (d, w, q)
        # slot fleet-wide, so the psum is a disjoint union, not a sum
        idx = jnp.where(v, slots.reshape(-1), k_out)
        row_c = jnp.zeros(k_out + 1, jnp.int32).at[idx].add(
            jnp.where(v, cost, 0))
        row_f = jnp.zeros(k_out + 1, jnp.int32).at[idx].add(
            fin.astype(jnp.int32))
        row_c = jax.lax.psum(row_c, (DATA_AXIS, WORKER_AXIS))
        row_f = jax.lax.psum(row_f, (DATA_AXIS, WORKER_AXIS))
        return row_c[:k_out], row_f[:k_out] > 0

    sm = _shard_map(
        _local, mesh=mesh,
        in_specs=(P(), P(WORKER_AXIS, None, None), q3, q3, q3, q3, q3,
                  P()),
        out_specs=(P(), P()),
    )
    return jax.jit(sm)


def query_mat_sharded(dg: DeviceGraph, fm_wrn, t_rows, s, t, valid,
                      slots, w_pad, mesh: Mesh, k_out: int,
                      max_steps: int = 0):
    """Answer one ``mat`` family row (one source, ``k_out`` targets)
    with on-mesh collectives: routed ``[D, W, Q]`` inputs as in
    :func:`query_sharded` plus ``slots`` (each routed slot's position
    in the output row, -1 on padding). Returns ``(cost [k_out] int32,
    finished [k_out] bool)`` — already in target order, replicated, so
    the host reads one device and does no join at all."""
    qs = NamedSharding(mesh, P(DATA_AXIS, WORKER_AXIS, None))
    args = jax.device_put((t_rows, s, t, valid, slots), qs)
    fn = _mat_fn(mesh, int(k_out), max_steps)
    return fn(dg, fm_wrn, *args, jnp.asarray(w_pad))


# ----------------------------------------------------------- cost tables

@functools.lru_cache(maxsize=None)
def _tables_fn(mesh: Mesh, max_len: int):
    from ..ops.pointer_doubling import doubled_tables

    def _local(dg, fm_local, tgt_local, w_pad):
        # local blocks: fm [1, R, N], tgt [1, R]
        return doubled_tables(dg, fm_local[0], tgt_local[0], w_pad,
                              max_len=max_len)

    sm = _shard_map(
        _local, mesh=mesh,
        in_specs=(P(), P(WORKER_AXIS, None, None), P(WORKER_AXIS, None),
                  P()),
        out_specs=(P(WORKER_AXIS, None), P(WORKER_AXIS, None)),
    )

    def _wrap(dg, fm_wrn, tgt_wr, w_pad):
        c, p = sm(dg, fm_wrn, tgt_wr, w_pad)
        # shard_map emits [W*R, N] (axis-0 concat of local [R, N]); restore
        # the worker axis
        w = fm_wrn.shape[0]
        return c.reshape(w, -1, dg.n), p.reshape(w, -1, dg.n)

    return jax.jit(_wrap)


def build_tables_sharded(dg: DeviceGraph, fm_wrn: jax.Array,
                         targets_wr: np.ndarray, w_query_pad, mesh: Mesh,
                         max_len: int = 0):
    """Pointer-doubling cost/plen/finished tables, one shard per worker
    (each worker doubles only its own rows — zero cross-shard traffic)."""
    tgt = jax.device_put(
        jnp.asarray(targets_wr, jnp.int32),
        NamedSharding(mesh, P(WORKER_AXIS, None)))
    fn = _tables_fn(mesh, max_len)
    return fn(dg, fm_wrn, tgt, jnp.asarray(w_query_pad))


@functools.lru_cache(maxsize=None)
def _tables_multi_fn(mesh: Mesh, max_len: int):
    from ..ops.pointer_doubling import doubled_tables_multi

    def _local(dg, fm_local, tgt_local, w_pads):
        # local blocks: fm [1, R, N], tgt [1, R]; w_pads replicated
        return doubled_tables_multi(dg, fm_local[0], tgt_local[0],
                                    w_pads, max_len=max_len)

    sm = _shard_map(
        _local, mesh=mesh,
        in_specs=(P(), P(WORKER_AXIS, None, None), P(WORKER_AXIS, None),
                  P()),
        out_specs=(P(WORKER_AXIS, None, None), P(WORKER_AXIS, None)),
    )

    def _wrap(dg, fm_wrn, tgt_wr, w_pads):
        c, p = sm(dg, fm_wrn, tgt_wr, w_pads)
        # shard_map emits [W*R, N, D] / [W*R, N]; restore the worker axis
        w = fm_wrn.shape[0]
        return (c.reshape(w, -1, dg.n, c.shape[-1]),
                p.reshape(w, -1, dg.n))

    return jax.jit(_wrap)


def build_tables_multi_sharded(dg: DeviceGraph, fm_wrn: jax.Array,
                               targets_wr: np.ndarray, w_pads,
                               mesh: Mesh, max_len: int = 0):
    """Fused multi-diff pointer-doubling tables, one shard per worker.

    ``w_pads`` int32 [D, M+1]. Returns ``(costs [W, R, N, D],
    plen_packed [W, R, N])`` — D diffs' tables for ~one prepare's
    gather traffic (``ops.pointer_doubling.doubled_tables_multi``).
    """
    tgt = jax.device_put(
        jnp.asarray(targets_wr, jnp.int32),
        NamedSharding(mesh, P(WORKER_AXIS, None)))
    fn = _tables_multi_fn(mesh, max_len)
    return fn(dg, fm_wrn, tgt, jnp.asarray(w_pads, jnp.int32))


@functools.lru_cache(maxsize=None)
def _query_table_multi_fn(mesh: Mesh, d: int):
    from ..ops.pointer_doubling import lookup_tables_multi

    q3 = P(DATA_AXIS, WORKER_AXIS, None)

    def _local(costs, plen_packed, rows, s, valid):
        shape = s.shape
        c, p, f = lookup_tables_multi(costs[0], plen_packed[0],
                                      rows.reshape(-1), s.reshape(-1),
                                      valid.reshape(-1))
        return (c.reshape(d, *shape), p.reshape(shape), f.reshape(shape))

    sm = _shard_map(
        _local, mesh=mesh,
        in_specs=(P(WORKER_AXIS, None, None, None),
                  P(WORKER_AXIS, None, None), q3, q3, q3),
        out_specs=(P(None, DATA_AXIS, WORKER_AXIS, None), q3, q3))
    return jax.jit(sm)


def query_tables_multi_sharded(tables, t_rows, s, valid, mesh: Mesh):
    """Answer routed [Dg, W, Q] queries from fused multi-diff tables."""
    costs, plen_packed = tables
    qs = NamedSharding(mesh, P(DATA_AXIS, WORKER_AXIS, None))
    rows_d, s_d, v_d = jax.device_put((t_rows, s, valid), qs)
    fn = _query_table_multi_fn(mesh, int(costs.shape[-1]))
    return fn(costs, plen_packed, rows_d, s_d, v_d)


@functools.lru_cache(maxsize=None)
def _query_table_fn(mesh: Mesh):
    from ..ops.pointer_doubling import lookup_tables

    q3 = P(DATA_AXIS, WORKER_AXIS, None)

    def _local(cost, plen_packed, rows, s, valid):
        shape = s.shape
        c, p, f = lookup_tables(cost[0], plen_packed[0],
                                rows.reshape(-1), s.reshape(-1),
                                valid.reshape(-1))
        return c.reshape(shape), p.reshape(shape), f.reshape(shape)

    t3 = P(WORKER_AXIS, None, None)
    sm = _shard_map(_local, mesh=mesh,
                       in_specs=(t3, t3, q3, q3, q3),
                       out_specs=(q3, q3, q3))
    return jax.jit(sm)


def query_tables_sharded(tables, t_rows, s, valid, mesh: Mesh):
    """Answer routed [D, W, Q] queries from prepared cost tables."""
    cost, plen_packed = tables
    qs = NamedSharding(mesh, P(DATA_AXIS, WORKER_AXIS, None))
    rows_d, s_d, v_d = jax.device_put((t_rows, s, valid), qs)
    return _query_table_fn(mesh)(cost, plen_packed, rows_d, s_d, v_d)


# --------------------------------------------------------------------- paths

@functools.lru_cache(maxsize=None)
def _paths_fn(mesh: Mesh, k: int):
    from ..ops.table_search import extract_paths

    q3 = P(DATA_AXIS, WORKER_AXIS, None)

    def _local(dg, fm_local, rows, s, t):
        shape = s.shape
        nodes, plen = extract_paths(dg, fm_local[0], rows.reshape(-1),
                                    s.reshape(-1), t.reshape(-1), k=k)
        return (nodes.reshape(*shape, k + 1), plen.reshape(shape))

    sm = _shard_map(
        _local, mesh=mesh,
        in_specs=(P(), P(WORKER_AXIS, None, None), q3, q3, q3),
        out_specs=(P(DATA_AXIS, WORKER_AXIS, None, None), q3),
    )
    return jax.jit(sm)


def query_paths_sharded(dg: DeviceGraph, fm_wrn: jax.Array,
                        t_rows: np.ndarray, s: np.ndarray, t: np.ndarray,
                        mesh: Mesh, k: int):
    """Materialize k-move path prefixes for routed [D, W, Q] queries.

    Returns ``(nodes [D, W, Q, k+1], moves [D, W, Q])`` — each shard scans
    only its own queries against its own fm rows (the reference's
    ``--k-moves`` extraction, reference ``args.py:31-36``, batched).
    """
    qs = NamedSharding(mesh, P(DATA_AXIS, WORKER_AXIS, None))
    args = jax.device_put((t_rows, s, t), qs)
    return _paths_fn(mesh, k)(dg, fm_wrn, *args)


# --------------------------------------------------------------------- query

@functools.lru_cache(maxsize=None)
def _query_dist_fn(mesh: Mesh):
    q3 = P(DATA_AXIS, WORKER_AXIS, None)

    def _local(dist_local, rows, s):
        # dist_local [1, R, N]; rows/s [D/|data|, 1, Q]
        shape = s.shape
        cost = dist_local[0][rows.reshape(-1), s.reshape(-1)]
        return cost.reshape(shape)

    sm = _shard_map(_local, mesh=mesh,
                       in_specs=(P(WORKER_AXIS, None, None), q3, q3),
                       out_specs=q3)
    return jax.jit(sm)


def query_dist_sharded(dist_wrn: jax.Array, t_rows: np.ndarray,
                       s: np.ndarray, mesh: Mesh) -> jax.Array:
    """Free-flow fast path: d(s → t) by one sharded gather, no walk.

    Inputs ``[D, W, Q]`` as in :func:`query_sharded`; returns cost
    ``[D, W, Q]`` (INF where unreachable).
    """
    qs = NamedSharding(mesh, P(DATA_AXIS, WORKER_AXIS, None))
    rows_d, s_d = jax.device_put((t_rows, s), qs)
    return _query_dist_fn(mesh)(dist_wrn, rows_d, s_d)


@functools.lru_cache(maxsize=None)
def _query_fn(mesh: Mesh, max_steps: int, k_moves: int = -1,
              kernel: str = "xla"):
    q3 = P(DATA_AXIS, WORKER_AXIS, None)

    def _local(dg, fm_local, rows, s, t, valid, w_pad):
        # local blocks: fm [1, R, N]; queries [D/|data|, 1, Q].
        # k_moves is part of THIS function's cache key (a per-campaign
        # constant), so the kernel sees a Python int and its static
        # no-budget specialization applies — a traced k_moves operand
        # would force the per-step budget compare back in. `kernel`
        # joins the key the same way: "pallas" swaps in the fused walk
        # (ops.pallas_walk, bit-identical answers) per shard
        fm2 = fm_local[0]
        shape = s.shape
        if kernel == "pallas":
            from ..ops.pallas_walk import pallas_walk_batch as walk
        else:
            walk = table_search_batch
        cost, plen, fin = walk(
            dg, fm2, rows.reshape(-1), s.reshape(-1), t.reshape(-1), w_pad,
            valid=valid.reshape(-1), k_moves=k_moves, max_steps=max_steps)
        return (cost.reshape(shape), plen.reshape(shape), fin.reshape(shape))

    sm = _shard_map(
        _local, mesh=mesh,
        in_specs=(P(), P(WORKER_AXIS, None, None), q3, q3, q3, q3, P()),
        out_specs=(q3, q3, q3),
    )
    return jax.jit(sm)


@functools.lru_cache(maxsize=None)
def _query_multi_fn(mesh: Mesh, max_steps: int, d: int):
    from ..ops.table_search import table_search_multi

    q3 = P(DATA_AXIS, WORKER_AXIS, None)

    def _local(dg, fm_local, rows, s, t, valid, w_pads):
        fm2 = fm_local[0]
        shape = s.shape
        cost, plen, fin = table_search_multi(
            dg, fm2, rows.reshape(-1), s.reshape(-1), t.reshape(-1),
            w_pads, valid=valid.reshape(-1), max_steps=max_steps)
        return (cost.reshape(d, *shape), plen.reshape(shape),
                fin.reshape(shape))

    sm = _shard_map(
        _local, mesh=mesh,
        in_specs=(P(), P(WORKER_AXIS, None, None), q3, q3, q3, q3, P()),
        out_specs=(P(None, DATA_AXIS, WORKER_AXIS, None), q3, q3),
    )
    return jax.jit(sm)


def query_multi_sharded(dg: DeviceGraph, fm_wrn: jax.Array,
                        t_rows: np.ndarray, s: np.ndarray, t: np.ndarray,
                        valid: np.ndarray, w_pads, mesh: Mesh,
                        max_steps: int = 0):
    """Fused multi-diff campaign on the mesh: one walk, D cost sets.

    ``w_pads`` int32 [D, M+1] (one padded weight row per diff). Returns
    ``(cost [D, Dg, W, Q], plen [Dg, W, Q], finished [Dg, W, Q])`` for
    routed ``[Dg, W, Q]`` batches — trajectories are diff-independent,
    so plen/finished are shared (``ops.table_search.table_search_multi``).
    """
    qs = NamedSharding(mesh, P(DATA_AXIS, WORKER_AXIS, None))
    args = jax.device_put((t_rows, s, t, valid), qs)
    w = jnp.asarray(w_pads, jnp.int32)
    fn = _query_multi_fn(mesh, max_steps, int(w.shape[0]))
    return fn(dg, fm_wrn, *args, w)


def query_sharded(dg: DeviceGraph, fm_wrn: jax.Array,
                  t_rows: np.ndarray, s: np.ndarray, t: np.ndarray,
                  valid: np.ndarray, w_query_pad, mesh: Mesh,
                  k_moves: int = -1, max_steps: int = 0,
                  kernel: str = "xla"):
    """Answer routed query batches on the mesh.

    Inputs are ``[D, W, Q]`` (data axis × worker axis × padded queries):
    ``t_rows`` = local fm row of each query's target, ``valid`` masks
    padding. Returns ``(cost, plen, finished)`` each ``[D, W, Q]``.
    ``kernel``: ``"xla"`` (the reference walk) or ``"pallas"`` (the
    fused kernel, ``ops.pallas_walk``) — callers resolve the
    ``DOS_WALK_KERNEL`` knob, this layer just compiles what it is told.
    """
    qs = NamedSharding(mesh, P(DATA_AXIS, WORKER_AXIS, None))
    # ONE device_put for the whole query pack: each separate transfer
    # costs a fixed round trip (~25-90 ms over a tunneled TPU link);
    # and never jnp.asarray first — that is a second, default-device
    # transfer before the resharding copy
    args = jax.device_put((t_rows, s, t, valid), qs)
    fn = _query_fn(mesh, max_steps, int(k_moves), str(kernel))
    return fn(dg, fm_wrn, *args, jnp.asarray(w_query_pad))
