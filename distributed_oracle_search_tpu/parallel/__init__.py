from .partition import DistributionController

__all__ = ["DistributionController"]
