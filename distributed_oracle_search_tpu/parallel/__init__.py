from .partition import DistributionController, UNROUTABLE, parse_conf

__all__ = ["DistributionController", "UNROUTABLE", "parse_conf"]
