"""Multi-host (multi-process) mesh support.

The reference scales across hosts with ssh + NFS + FIFOs; the TPU-native
equivalent is multi-controller JAX: every host runs the same program,
``jax.distributed.initialize`` wires them into one runtime, and the worker
mesh simply spans all processes' devices — GSPMD then routes collectives
over ICI within a slice and DCN across hosts (SURVEY.md §5 "distributed
communication backend", build plan stage 6).

Cluster-conf integration: a ``multihost`` object in the conf JSON::

    "multihost": {"coordinator": "10.0.0.1:8476",
                  "num_processes": 4}        # process_id from env/flag

Call :func:`initialize_from_conf` before any jax API touches a backend.
On TPU pods, all three values can usually be omitted (auto-detected from
the TPU environment). The same machinery runs on CPU processes (used by
the multi-process test), so the multi-host path is testable on one
machine without a pod.

Caveats worth knowing (multi-controller JAX semantics):

* every process must execute the same jitted computations in the same
  order;
* host numpy inputs fed through ``device_put`` with a global
  ``NamedSharding`` must be identical on all processes (they are here:
  graph, targets, and routed query batches are deterministic functions of
  shared inputs);
* pulling a globally-sharded result back to one host needs an allgather —
  use :func:`gather_to_host`.
"""

from __future__ import annotations

import os

from ..utils.env import env_str
from ..utils.log import get_logger

log = get_logger(__name__)


def initialize(coordinator: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None,
               local_device_ids=None,
               cpu_devices_per_process: int | None = None) -> None:
    """Thin, idempotent wrapper over ``jax.distributed.initialize``.

    ``cpu_devices_per_process``: for CPU-backed multi-process runs (tests,
    pods-without-TPUs) force the CPU platform with that many virtual
    devices and gloo collectives — must be called before any backend
    initializes.
    """
    import jax

    if getattr(initialize, "_done", False):
        return
    if cpu_devices_per_process is not None:
        jax.config.update("jax_platforms", "cpu")
        try:
            jax.config.update("jax_num_cpu_devices",
                              int(cpu_devices_per_process))
        except AttributeError:
            # older jax: only the env-flag spelling exists; honored as
            # long as no backend has initialized yet
            import re

            flags = os.environ.get("XLA_FLAGS", "")
            have = re.search(
                r"--xla_force_host_platform_device_count=(\d+)", flags)
            if have is None:
                os.environ["XLA_FLAGS"] = (
                    flags + " --xla_force_host_platform_device_count="
                    + str(int(cpu_devices_per_process))).strip()
            elif int(have.group(1)) != int(cpu_devices_per_process):
                log.warning(
                    "XLA_FLAGS already pins %s device(s), differing "
                    "from cpu_devices_per_process=%d; keeping the "
                    "existing flag", have.group(1),
                    int(cpu_devices_per_process))
        try:
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
        except AttributeError:
            pass
    kwargs = {}
    if coordinator is not None:
        kwargs["coordinator_address"] = coordinator
    if num_processes is not None:
        kwargs["num_processes"] = int(num_processes)
    if process_id is not None:
        kwargs["process_id"] = int(process_id)
    if local_device_ids is not None:
        kwargs["local_device_ids"] = local_device_ids
    jax.distributed.initialize(**kwargs)
    initialize._done = True  # type: ignore[attr-defined]
    log.info("multihost: process %d/%d up, %d global devices",
             jax.process_index(), jax.process_count(), len(jax.devices()))


def initialize_from_conf(conf) -> bool:
    """Initialize from a ClusterConfig-style object / dict. Returns True
    when multi-host mode was configured. ``process_id`` comes from the
    conf, ``$DOS_PROCESS_ID``, or TPU auto-detection, in that order."""
    mh = getattr(conf, "multihost", None)
    if mh is None and isinstance(conf, dict):
        mh = conf.get("multihost")
    if not mh:
        return False
    pid = mh.get("process_id", env_str("DOS_PROCESS_ID"))
    cpus = mh.get("cpu_devices_per_process")  # CPU-backed pods / tests
    initialize(coordinator=mh.get("coordinator"),
               num_processes=mh.get("num_processes"),
               process_id=None if pid is None else int(pid),
               cpu_devices_per_process=None if cpus is None else int(cpus))
    return True


def _runtime_active() -> bool:
    """True when a multi-controller JAX runtime is up — via this wrapper
    or initialized outside it (direct ``jax.distributed.initialize``, TPU
    pod auto-init). Never triggers backend init itself."""
    if getattr(initialize, "_done", False):
        return True
    try:
        from jax._src import distributed as _jdist

        return _jdist.global_state.client is not None
    except (ImportError, AttributeError):  # private API moved: assume
        return False                       # single-controller


def process_info() -> tuple[int, int]:
    """``(process_index, process_count)`` — ``(0, 1)`` on any
    single-controller run (same guard rationale as :func:`is_primary`)."""
    if _runtime_active():
        import jax

        return jax.process_index(), jax.process_count()
    return 0, 1


def barrier(name: str) -> None:
    """Cross-process rendezvous (no-op single-controller): every process
    must reach it before any proceeds — e.g. all block files written
    before one process writes the index manifest."""
    if _runtime_active():
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)


def is_primary() -> bool:
    """True on the process that should write shared artifacts (process 0),
    and on any single-controller run. Only consults the JAX process index
    when multi-host mode was actually initialized — a run that never
    configured ``multihost`` is always primary (a stray ``$DOS_PROCESS_ID``
    in the shell must not silently suppress campaign output)."""
    return process_info()[0] == 0


def gather_to_host(x):
    """Allgather a globally-sharded array to replicated numpy on every
    process (wraps ``multihost_utils.process_allgather``)."""
    from jax.experimental import multihost_utils

    return multihost_utils.process_allgather(x, tiled=True)
