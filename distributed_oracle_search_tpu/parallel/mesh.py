"""Device-mesh construction and sharding specs.

The TPU replacement for the reference's cluster topology: where the
reference's ``workers`` list is ssh hostnames and its "communication
backend" is ssh + tmux + NFS + named FIFOs (SURVEY.md §5), here a worker is
a mesh shard and every exchange is an XLA collective over ICI/DCN inserted
by GSPMD. One mesh axis — ``"worker"`` — carries the index sharding (the
system's model-parallel axis: CPD rows live where their targets are owned);
an optional leading ``"data"`` axis replicates the CPD and splits query
batches (pure data parallelism) for meshes larger than the worker count.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

WORKER_AXIS = "worker"
DATA_AXIS = "data"


def make_mesh(n_workers: int | None = None, n_data: int = 1,
              devices=None) -> Mesh:
    """Build a ``(data, worker)`` mesh.

    ``n_workers`` defaults to all available devices (with ``n_data=1``).
    Total devices used = ``n_data * n_workers``.
    """
    devices = jax.devices() if devices is None else devices
    if n_workers is None:
        n_workers = len(devices) // n_data
    need = n_data * n_workers
    if need > len(devices):
        raise ValueError(
            f"mesh ({n_data}x{n_workers}) needs {need} devices, "
            f"have {len(devices)}")
    dev = np.asarray(devices[:need]).reshape(n_data, n_workers)
    return Mesh(dev, (DATA_AXIS, WORKER_AXIS))


def mesh_from_config(conf) -> Mesh:
    """Build the campaign mesh from a :class:`~..utils.config.ClusterConfig`.

    ``mesh_shape``/``mesh_axes`` (optional config keys) pin the exact
    layout — e.g. ``[2, 4]`` with ``["data", "worker"]`` — with the
    worker axis required to equal ``maxworker`` (one shard per worker,
    the partmethod=tpu invariant). Absent, the default is
    ``(1, maxworker)``.
    """
    if conf.mesh_shape is None:
        return make_mesh(n_workers=conf.maxworker)
    axes = (list(conf.mesh_axes) if conf.mesh_axes is not None
            else [DATA_AXIS, WORKER_AXIS][-len(conf.mesh_shape):])
    if len(axes) != len(conf.mesh_shape):
        raise ValueError(
            f"mesh_axes {axes} and mesh_shape {list(conf.mesh_shape)} "
            "must have the same length")
    if sorted(axes) != sorted([DATA_AXIS, WORKER_AXIS])[:len(axes)] and \
            axes != [WORKER_AXIS]:
        raise ValueError(
            f"mesh_axes must be drawn from "
            f"['{DATA_AXIS}', '{WORKER_AXIS}'], got {axes}")
    shape = dict(zip(axes, conf.mesh_shape))
    n_workers = shape.get(WORKER_AXIS, conf.maxworker)
    if n_workers != conf.maxworker:
        raise ValueError(
            f"mesh_shape worker axis {n_workers} != maxworker "
            f"{conf.maxworker}; partmethod=tpu requires one mesh shard "
            "per worker")
    return make_mesh(n_workers=n_workers,
                     n_data=shape.get(DATA_AXIS, 1))


def worker_sharding(mesh: Mesh, rank: int = 1) -> NamedSharding:
    """Shard axis 0 over workers, replicate everything else (CPD layout)."""
    return NamedSharding(mesh, P(WORKER_AXIS, *([None] * (rank - 1))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def query_sharding(mesh: Mesh, rank: int = 2) -> NamedSharding:
    """Queries: [data, worker, ...] — batch split over data, routed rows on
    the worker axis."""
    return NamedSharding(mesh, P(DATA_AXIS, WORKER_AXIS, *([None] * (rank - 2))))
