"""Device-mesh construction and sharding specs.

The TPU replacement for the reference's cluster topology: where the
reference's ``workers`` list is ssh hostnames and its "communication
backend" is ssh + tmux + NFS + named FIFOs (SURVEY.md §5), here a worker is
a mesh shard and every exchange is an XLA collective over ICI/DCN inserted
by GSPMD. One mesh axis — ``"worker"`` — carries the index sharding (the
system's model-parallel axis: CPD rows live where their targets are owned);
an optional leading ``"data"`` axis replicates the CPD and splits query
batches (pure data parallelism) for meshes larger than the worker count.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils.env import env_cast
from ..utils.log import get_logger

log = get_logger(__name__)

WORKER_AXIS = "worker"
DATA_AXIS = "data"
#: the WORKER-LOCAL axis: one worker process driving several devices.
#: Orthogonal to the campaign mesh's (data, worker) axes — a lane mesh
#: never crosses workers, it splits ONE worker's batches/build chunks
#: over the devices that worker owns.
LANE_AXIS = "lane"


def make_mesh(n_workers: int | None = None, n_data: int = 1,
              devices=None) -> Mesh:
    """Build a ``(data, worker)`` mesh.

    ``n_workers`` defaults to all available devices (with ``n_data=1``).
    Total devices used = ``n_data * n_workers``.
    """
    devices = jax.devices() if devices is None else devices
    if n_workers is None:
        n_workers = len(devices) // n_data
    need = n_data * n_workers
    if need > len(devices):
        raise ValueError(
            f"mesh ({n_data}x{n_workers}) needs {need} devices, "
            f"have {len(devices)}")
    dev = np.asarray(devices[:need]).reshape(n_data, n_workers)
    return Mesh(dev, (DATA_AXIS, WORKER_AXIS))


def mesh_from_config(conf) -> Mesh:
    """Build the campaign mesh from a :class:`~..utils.config.ClusterConfig`.

    ``mesh_shape``/``mesh_axes`` (optional config keys) pin the exact
    layout — e.g. ``[2, 4]`` with ``["data", "worker"]`` — with the
    worker axis required to equal ``maxworker`` (one shard per worker,
    the partmethod=tpu invariant). Absent, the default is
    ``(1, maxworker)``.
    """
    if conf.mesh_shape is None:
        return make_mesh(n_workers=conf.maxworker)
    axes = (list(conf.mesh_axes) if conf.mesh_axes is not None
            else [DATA_AXIS, WORKER_AXIS][-len(conf.mesh_shape):])
    if len(axes) != len(conf.mesh_shape):
        raise ValueError(
            f"mesh_axes {axes} and mesh_shape {list(conf.mesh_shape)} "
            "must have the same length")
    if sorted(axes) != sorted([DATA_AXIS, WORKER_AXIS])[:len(axes)] and \
            axes != [WORKER_AXIS]:
        raise ValueError(
            f"mesh_axes must be drawn from "
            f"['{DATA_AXIS}', '{WORKER_AXIS}'], got {axes}")
    shape = dict(zip(axes, conf.mesh_shape))
    n_workers = shape.get(WORKER_AXIS, conf.maxworker)
    if n_workers != conf.maxworker:
        raise ValueError(
            f"mesh_shape worker axis {n_workers} != maxworker "
            f"{conf.maxworker}; partmethod=tpu requires one mesh shard "
            "per worker")
    return make_mesh(n_workers=n_workers,
                     n_data=shape.get(DATA_AXIS, 1))


def mesh_devices(avail: int | None = None) -> int:
    """Resolve the ``DOS_MESH_DEVICES`` knob: how many local devices one
    worker drives. 1 (the default — unset, malformed, or non-positive)
    is the legacy single-device engine, byte-identical behavior.

    The resolved count is floored to a power of two (batch pads and
    build chunks are pow2, so only pow2 lane counts split them evenly)
    and clamped to the devices actually present — an 8-lane config on a
    4-device host degrades with a log line, never a crash."""
    n = env_cast("DOS_MESH_DEVICES", 1, int)
    if n <= 1:
        return 1
    have = len(jax.devices()) if avail is None else int(avail)
    if n > have:
        log.warning("DOS_MESH_DEVICES=%d but only %d device(s) present; "
                    "clamping", n, have)
        n = have
    floored = 1 << (max(n, 1).bit_length() - 1)
    if floored != n:
        log.warning("DOS_MESH_DEVICES=%d is not a power of two; using "
                    "%d lanes (pow2 splits keep padded batches even)",
                    n, floored)
    return max(floored, 1)


def make_worker_mesh(n_lanes: int | None = None,
                     devices=None) -> Mesh | None:
    """The worker-LOCAL sub-mesh: a 1-D ``(lane,)`` mesh over the first
    ``n_lanes`` devices this process owns. ``n_lanes=None`` resolves
    ``DOS_MESH_DEVICES``; a resolved count of 1 returns ``None`` — the
    single-device legacy path, so callers gate mesh execution on the
    return value and an unset knob stays byte-identical."""
    devices = jax.devices() if devices is None else list(devices)
    if n_lanes is None:
        n_lanes = mesh_devices(avail=len(devices))
    if n_lanes <= 1:
        return None
    if n_lanes > len(devices):
        raise ValueError(
            f"worker mesh needs {n_lanes} devices, have {len(devices)}")
    return Mesh(np.asarray(devices[:n_lanes]), (LANE_AXIS,))


def lane_sharding(mesh: Mesh, rank: int = 1) -> NamedSharding:
    """Shard axis 0 over the worker's lanes, replicate the rest."""
    return NamedSharding(mesh, P(LANE_AXIS, *([None] * (rank - 1))))


def worker_sharding(mesh: Mesh, rank: int = 1) -> NamedSharding:
    """Shard axis 0 over workers, replicate everything else (CPD layout)."""
    return NamedSharding(mesh, P(WORKER_AXIS, *([None] * (rank - 1))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def query_sharding(mesh: Mesh, rank: int = 2) -> NamedSharding:
    """Queries: [data, worker, ...] — batch split over data, routed rows on
    the worker axis."""
    return NamedSharding(mesh, P(DATA_AXIS, WORKER_AXIS, *([None] * (rank - 2))))
