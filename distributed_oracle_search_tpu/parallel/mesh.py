"""Device-mesh construction and sharding specs.

The TPU replacement for the reference's cluster topology: where the
reference's ``workers`` list is ssh hostnames and its "communication
backend" is ssh + tmux + NFS + named FIFOs (SURVEY.md §5), here a worker is
a mesh shard and every exchange is an XLA collective over ICI/DCN inserted
by GSPMD. One mesh axis — ``"worker"`` — carries the index sharding (the
system's model-parallel axis: CPD rows live where their targets are owned);
an optional leading ``"data"`` axis replicates the CPD and splits query
batches (pure data parallelism) for meshes larger than the worker count.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

WORKER_AXIS = "worker"
DATA_AXIS = "data"


def make_mesh(n_workers: int | None = None, n_data: int = 1,
              devices=None) -> Mesh:
    """Build a ``(data, worker)`` mesh.

    ``n_workers`` defaults to all available devices (with ``n_data=1``).
    Total devices used = ``n_data * n_workers``.
    """
    devices = jax.devices() if devices is None else devices
    if n_workers is None:
        n_workers = len(devices) // n_data
    need = n_data * n_workers
    if need > len(devices):
        raise ValueError(
            f"mesh ({n_data}x{n_workers}) needs {need} devices, "
            f"have {len(devices)}")
    dev = np.asarray(devices[:need]).reshape(n_data, n_workers)
    return Mesh(dev, (DATA_AXIS, WORKER_AXIS))


def worker_sharding(mesh: Mesh, rank: int = 1) -> NamedSharding:
    """Shard axis 0 over workers, replicate everything else (CPD layout)."""
    return NamedSharding(mesh, P(WORKER_AXIS, *([None] * (rank - 1))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def query_sharding(mesh: Mesh, rank: int = 2) -> NamedSharding:
    """Queries: [data, worker, ...] — batch split over data, routed rows on
    the worker axis."""
    return NamedSharding(mesh, P(DATA_AXIS, WORKER_AXIS, *([None] * (rank - 2))))
