"""Elastic fleet membership: the reconfiguration controller.

The partition quadruple fixes the node→**shard** map at build time
(``parallel.partition``); this module makes the shard→**worker**
assignment a first-class, versioned, observable state machine instead
of a frozen conf file. One durable artifact — ``membership.json`` in
the index directory, written atomically (``utils.atomicio``) — holds
the fleet's current **epoch** (monotonically increasing assignment
version), the worker roster, the shard→owner table, and (while a
reconfiguration is in flight) the migration record. Every head, worker,
and serving frontend derives its routing from the same file, and the
epoch rides the wire (``RuntimeConfig.epoch``) so a worker can refuse a
request routed under a NEWER table than it has seen — the codecs'
version-gate contract (tolerate older, gate only on newer) applied to
routing state.

A reconfiguration is a three-step state machine, crash-resumable at
every step because each step is one atomic ``membership.json`` write:

1. **begin** — the migration record (which shards move where, target
   epoch) lands in the state file. Routing does not change yet: the
   migration opens the **dual-read window**, during which the campaign
   head and the serving frontend route a moving shard's reads to BOTH
   candidate owners via the replica failover chain — the OLD owner
   first (authoritative), the adopter next — so no query is shed while
   ownership is in flight.
2. **catch_up** — the adopter materializes each moving shard's rows by
   digest-verifying the on-disk block set and healing anything bad
   through the shared copy/heal path (``models.cpd.adopt_shard_blocks``
   → ``heal_block``: copy from a digest-valid replica set, recompute
   from the graph as a last resort). Progress is journaled per shard
   into the migration record (and the underlying heal path journals
   per block into the build ledgers), so a controller killed mid
   catch-up resumes exactly where it died — the ``kill-during-reshard``
   fault point lives between shard moves.
3. **commit** — one atomic write updates the owner table, bumps the
   epoch, and clears the migration record. Routing flips the instant
   the rename lands; a worker that has not re-read the file yet simply
   keeps serving (older epochs are always served) until a newer-epoch
   request prompts it to refresh.

**Join** moves a balanced slice of shards onto the new worker; **leave**
is the inverse — every shard the leaver owns transfers to the next live
host in its replica chain first (a worker that already holds the rows),
falling back to round-robin over the remaining roster, after which the
leaver drains and exits 0 (``WorkerSupervisor.remove_worker``).

Env knobs (``utils.env`` policy): ``DOS_MEMBERSHIP_VERIFY`` (default
on — re-verify every moved shard's block digests immediately before
commit; off trusts the catch-up journal), ``DOS_MEMBERSHIP_MAX_MOVES``
(cap shards moved by one join rebalance; 0 = balanced share).
"""

from __future__ import annotations

import dataclasses
import json
import os
import time

import numpy as np

from ..obs import metrics as obs_metrics
from ..testing import faults
from ..utils.atomicio import atomic_write_json
from ..utils.env import env_cast, env_flag
from ..utils.log import get_logger
from ..utils.timer import Timer
from .partition import DistributionController

log = get_logger(__name__)

#: the durable assignment artifact, next to ``index.json``
STATE_FILE = "membership.json"

#: membership.json schema version — same compat contract as the index
#: manifest: unknown keys tolerated, only NEWER versions rejected
MEMBERSHIP_VERSION = 1

G_EPOCH = obs_metrics.gauge(
    "reshard_epoch",
    "committed partition-table epoch (0 = the static pre-elastic fleet)")
M_MIGRATIONS = obs_metrics.counter(
    "reshard_migrations_total",
    "reconfigurations begun (join + leave; commits and aborts both "
    "start here)")
M_SHARDS_MOVED = obs_metrics.counter(
    "reshard_shards_moved_total",
    "shard ownership transfers committed by epoch bumps")
M_ABORTED = obs_metrics.counter(
    "reshard_aborted_total",
    "migration windows explicitly aborted (owner table unchanged)")
M_LEAVE_REFUSED = obs_metrics.counter(
    "reshard_leave_refused_total",
    "leave plans refused because a shard had no live replica-chain "
    "adopter (R=1 sole owner) — refusing beats stranding it mid-window")
H_CATCHUP = obs_metrics.histogram(
    "reshard_catchup_seconds",
    "per-shard adopter catch-up: digest-verify + heal/copy of one "
    "moving shard's block set")


@dataclasses.dataclass
class Migration:
    """One in-flight reconfiguration (the dual-read window record)."""

    epoch: int                       # epoch this migration commits
    kind: str                        # "join" | "leave"
    worker: int                      # joining/leaving worker id
    #: ownership transfers: ``[shard, from_worker, to_worker]`` rows
    moves: list = dataclasses.field(default_factory=list)
    #: shards whose adopter catch-up is journaled complete
    done: list = dataclasses.field(default_factory=list)
    #: join only: the joiner's ssh host, recorded by the plan so
    #: ``begin`` rosters the host the plan was made for (an explicit
    #: ``begin(host=...)`` still wins)
    host: str | None = None

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "Migration":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})

    def target_of(self, shard: int) -> int | None:
        for s, _frm, to in self.moves:
            if s == shard:
                return int(to)
        return None


@dataclasses.dataclass
class MembershipState:
    """The durable content of ``membership.json``.

    Same compat contract as the wire codecs and the index manifest:
    ``from_dict`` filters unknown keys (future fields cannot break this
    reader), and only a file whose ``version`` is NEWER than this code
    rejects — it may have changed the meaning of keys we would silently
    misread into wrong routing."""

    epoch: int = 0
    workers: list = dataclasses.field(default_factory=list)
    owners: list = dataclasses.field(default_factory=list)
    migration: dict | None = None
    version: int = MEMBERSHIP_VERSION

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        if d["migration"] is None:
            del d["migration"]
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "MembershipState":
        version = int(d.get("version", 1))
        if version > MEMBERSHIP_VERSION:
            raise ValueError(
                f"membership state has schema v{version}; this build "
                f"reads up to v{MEMBERSHIP_VERSION} — upgrade the "
                "serving code (unknown keys are tolerated, newer major "
                "versions are not)")
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})

    @property
    def live_migration(self) -> Migration | None:
        return (Migration.from_dict(self.migration)
                if self.migration else None)


def state_path(outdir: str) -> str:
    return os.path.join(outdir, STATE_FILE)


def load_state(outdir: str) -> MembershipState | None:
    """The on-disk assignment, or None for a static (pre-elastic)
    fleet. The file is only ever written atomically, so a readable file
    is a complete one; an unparsable file raises — serving under a
    routing table we cannot read is worse than failing loudly."""
    path = state_path(outdir)
    try:
        with open(path) as f:
            raw = json.load(f)
    except FileNotFoundError:
        return None
    except (OSError, ValueError) as e:
        raise ValueError(f"unreadable membership state {path}: {e}")
    return MembershipState.from_dict(raw)


def save_state(outdir: str, state: MembershipState) -> None:
    atomic_write_json(state_path(outdir), state.to_dict())


def current_epoch(outdir: str) -> int:
    """The committed epoch (0 when no membership state exists)."""
    state = load_state(outdir)
    return state.epoch if state is not None else 0


def apply_state(dc: DistributionController,
                state: MembershipState | None) -> DistributionController:
    """A controller carrying ``state``'s epoch + owner assignment (the
    same partition quadruple — node→shard routing never changes)."""
    if state is None:
        return dc
    owners = (np.asarray(state.owners, np.int64) if state.owners
              else None)
    return DistributionController(
        dc.partmethod, dc.partkey, dc.maxworker, dc.nodenum,
        block_size=dc.block_size, replication=dc.replication,
        epoch=state.epoch, owners=owners)


def route_candidates(state: MembershipState | None,
                     dc: DistributionController, shard: int) -> list[int]:
    """The worker ids to try for ``shard``'s batch, failover order.

    Steady state: the shard's replica chain (owner first). During a
    migration window that moves this shard: the OLD owner stays
    authoritative (first), the adopter rides second — the dual-read
    rule — and the replica chain follows, deduped. No query is shed
    during handoff: the chain is walked by ``send_failover`` exactly
    like a replica chain, because it is one."""
    chain = list(dc.replica_workers(shard))
    mig = state.live_migration if state is not None else None
    if mig is not None:
        target = mig.target_of(int(shard))
        if target is not None and target not in chain[:1]:
            chain = [chain[0], target] + chain[1:]
    out: list[int] = []
    for c in chain:
        if c not in out:
            out.append(int(c))
    return out


def hosted_shards(state: MembershipState | None,
                  dc: DistributionController, wid: int) -> set[int]:
    """Every shard worker ``wid`` may legitimately answer batches for:
    its owned/replica chain slots, plus any shard it is mid-adopting
    (the dual-read window routes reads there before the epoch
    commits)."""
    out = {int(s) for s in dc.replica_shards(wid)}
    mig = state.live_migration if state is not None else None
    if mig is not None:
        out |= {int(s) for s, _frm, to in mig.moves if int(to) == wid}
    return out


class MembershipController:
    """Drives join/leave reconfigurations over one index directory.

    The controller is head-side tooling: it plans the ownership
    transfers, opens the dual-read window, runs (or resumes) the
    adopter catch-up, and commits the epoch bump. Workers and serving
    frontends only ever READ the state file."""

    def __init__(self, conf, dc: DistributionController,
                 outdir: str | None = None, graph=None):
        self.conf = conf
        self.outdir = outdir if outdir is not None else conf.outdir
        self._graph = graph
        state = load_state(self.outdir)
        if state is None:
            state = MembershipState(
                epoch=dc.epoch, workers=list(conf.workers),
                owners=[dc.owner_of(s) for s in range(dc.maxworker)])
        self.base_dc = dc
        self.state = state
        #: bumped at every state mutation point; dc_view snapshots it
        #: so a concurrent reader can never pin a stale controller past
        #: the next call (a plain None sentinel could be re-populated
        #: from pre-mutation state AFTER the mutator cleared it)
        self._state_gen = 0
        self._dc_cache: tuple | None = None     # (gen, controller)
        self._last_refresh = time.monotonic()
        G_EPOCH.set(state.epoch)

    #: how stale a SERVING process's view of membership.json may get —
    #: the dual-read window makes a commit visible lag harmless (old
    #: routing keeps working), so a coarse re-read bound suffices
    REFRESH_INTERVAL_S = 1.0

    # ----------------------------------------------------------- views
    @property
    def epoch(self) -> int:
        return self.state.epoch

    def dc_view(self) -> DistributionController:
        """A controller reflecting the current committed assignment.
        Cached per state generation (every mutation point bumps
        ``_state_gen``) — the serving admission hot path asks for this
        per request, and rebuilding a controller re-runs the O(N)
        node-assignment over the whole graph. The generation is read
        BEFORE the state: a racing mutator at worst leaves one stale
        entry that the next call's generation mismatch recomputes."""
        gen = self._state_gen
        cache = self._dc_cache
        if cache is None or cache[0] != gen:
            cache = (gen, apply_state(self.base_dc, self.state))
            self._dc_cache = cache
        return cache[1]

    def _invalidate_dc(self) -> None:
        """Every state mutation point comes through here: the bumped
        generation is what makes the dc_view cache safe against the
        reader-preempted-across-a-mutation race the ctor describes."""
        self._state_gen += 1
        self._dc_cache = None

    def refresh(self) -> MembershipState:
        """Re-read the durable state (another controller process may
        have committed since). An OLDER epoch never applies — epochs
        are monotone, so a lagging read (NFS attribute cache, an
        operator restoring a stale file) must not roll routing back to
        a drained owner; same-epoch content still applies (``begin``
        opens the window without a bump). The worker side
        (``FifoServer._refresh_membership``) enforces the same rule."""
        self._last_refresh = time.monotonic()
        state = load_state(self.outdir)
        if state is not None:
            if state.epoch < self.state.epoch:
                log.warning(
                    "membership refresh read epoch %d behind the "
                    "current %d; ignoring the stale state",
                    state.epoch, self.state.epoch)
                return self.state
            if state.to_dict() == self.state.to_dict():
                # unchanged (steady state, once per refresh interval):
                # keep the dc_view cache — invalidating would re-run
                # the O(N) node assignment on the admission hot path
                return self.state
            self.state = state
            self._invalidate_dc()
            G_EPOCH.set(state.epoch)
        return self.state

    def _maybe_refresh(self) -> None:
        """Throttled :meth:`refresh` for read paths: a serving frontend
        holding this controller must observe commits made by OTHER
        processes (the campaign-style re-read, amortized), without
        paying a file read per batch."""
        if time.monotonic() - self._last_refresh >= \
                self.REFRESH_INTERVAL_S:
            try:
                self.refresh()
            except ValueError as e:
                log.error("membership refresh failed: %s (keeping "
                          "the current table)", e)

    def candidates_for(self, shard: int) -> list[int]:
        self._maybe_refresh()
        return route_candidates(self.state, self.dc_view(), shard)

    def host_of(self, via: int) -> str:
        """ssh host of worker ``via`` from the LIVE roster — a joined
        worker's id is past the static conf's list, and a FIFO
        dispatcher must still be able to name its host."""
        ws = self.state.workers or list(self.conf.workers)
        return ws[via] if via < len(ws) else ws[via % len(ws)]

    def statusz(self) -> dict:
        """The ``/statusz`` section: epoch, roster, owner table, and —
        during a window — the migration record."""
        out = {
            "epoch": self.state.epoch,
            "workers": list(self.state.workers),
            "owners": [int(o) for o in self.state.owners],
        }
        mig = self.state.live_migration
        if mig is not None:
            out["migration"] = mig.to_dict()
        return out

    def graph(self):
        if self._graph is None:
            from ..data.graph import Graph

            self._graph = Graph.from_xy(self.conf.xy_file)
        return self._graph

    # -------------------------------------------------------- planning
    def _owners(self) -> list[int]:
        dc = self.base_dc
        return ([int(o) for o in self.state.owners] if self.state.owners
                else [dc.owner_of(s) for s in range(dc.maxworker)])

    def plan_join(self, host: str) -> Migration:
        """Rebalance onto a new worker: it receives its balanced share
        of shards (``W // (live_owners + 1)``, at least 1), taken from
        the most-loaded current owners first (deterministic: stable by
        shard id). The divisor counts workers that OWN shards, not
        roster slots — roster entries are positional and never pruned
        on leave, so a departed worker must not dilute the share.
        ``DOS_MEMBERSHIP_MAX_MOVES`` caps the transfer."""
        owners = self._owners()
        w = len(owners)
        new_wid = len(self.state.workers)
        share = max(1, w // (len(set(owners)) + 1))
        cap = env_cast("DOS_MEMBERSHIP_MAX_MOVES", 0, int)
        if cap > 0:
            share = min(share, cap)
        load: dict[int, list[int]] = {}
        for shard, owner in enumerate(owners):
            load.setdefault(owner, []).append(shard)
        moves: list[list[int]] = []
        while len(moves) < share:
            donor = max(load, key=lambda o: (len(load[o]), -o))
            if len(load[donor]) <= 1 and len(moves):
                break           # never strip a worker bare mid-join
            shard = load[donor].pop(0)
            moves.append([shard, donor, new_wid])
        return Migration(epoch=self.state.epoch + 1, kind="join",
                         worker=new_wid, moves=moves, host=host)

    def plan_leave(self, wid: int, live=None) -> Migration:
        """Transfer every shard ``wid`` owns before it drains:
        ownership goes to the next host in the shard's replica chain
        that is not the leaver (a worker already holding the rows — the
        cheapest adopter), falling back to round-robin over the workers
        that still OWN shards when the whole chain is the leaver. The
        fallback pool is ownership-derived, not the roster: roster
        entries are never pruned on leave (worker ids are positional),
        so a previously-departed worker still has a roster slot — and
        committing a shard onto a drained host would make it
        permanently unroutable.

        ``live`` (optional set of worker ids known to be serving)
        restricts adopters: the control daemon removing a dead worker
        must not move its shards onto another sick one. When filtering
        leaves a shard with NO adopter at all (R=1 sole owner and no
        live peer owns anything), the plan **refuses** — a per-shard
        diagnostic plus ``reshard_leave_refused_total`` — instead of
        opening a dual-read window that could never drain. ``live=None``
        preserves the pre-control behavior bit-for-bit."""
        owners = self._owners()
        dc = self.dc_view()
        remaining = sorted(set(owners) - {int(wid)})
        if live is not None:
            live = {int(w) for w in live}
            remaining = [w for w in remaining if w in live]
        if not remaining:
            if live is not None:
                M_LEAVE_REFUSED.inc()
            raise ValueError("cannot remove the last shard-owning "
                             "worker")
        moves: list[list[int]] = []
        stranded: list[str] = []
        rr = 0
        for shard, owner in enumerate(owners):
            if owner != int(wid):
                continue
            chain = [h for h in dc.replica_workers(shard)
                     if h != int(wid)]
            if live is not None:
                # the leaver is presumed dead: the adopter must ALREADY
                # hold the rows (be a live replica-chain host) because
                # catch-up cannot copy from a corpse. Round-robin onto
                # a non-replica is only safe on the legacy live=None
                # path, where the leaver itself serves the catch-up.
                alive_chain = [h for h in chain if h in live]
                if not alive_chain:
                    stranded.append(
                        f"shard {shard}: replica chain {chain or '[]'} "
                        f"has no live host (sole owner at R="
                        f"{int(dc.replication)})")
                    continue
                moves.append([shard, owner, int(alive_chain[0])])
                continue
            target = next(iter(chain), None)
            if target is None:
                target = remaining[rr % len(remaining)]
                rr += 1
            moves.append([shard, owner, int(target)])
        if stranded:
            M_LEAVE_REFUSED.inc()
            raise ValueError(
                f"refusing leave of worker {int(wid)}: "
                + "; ".join(stranded))
        return Migration(epoch=self.state.epoch + 1, kind="leave",
                         worker=int(wid), moves=moves)

    # --------------------------------------------------- state machine
    def begin(self, migration: Migration, host: str | None = None
              ) -> Migration:
        """Open the dual-read window: persist the migration record (one
        atomic write). A join also extends the roster so routing can
        name the new worker; ownership does NOT change yet."""
        if self.state.migration is not None:
            raise ValueError(
                "a migration is already in flight "
                f"(target epoch {self.state.live_migration.epoch}); "
                "resume or abort it first")
        if migration.epoch != self.state.epoch + 1:
            raise ValueError(
                f"migration targets epoch {migration.epoch}, current "
                f"is {self.state.epoch} — plans do not skip epochs")
        if migration.kind == "join":
            if host is None:
                host = migration.host
            self.state.workers = list(self.state.workers) + [
                host if host is not None else f"worker:{migration.worker}"]
        self.state.owners = self._owners()
        self.state.migration = migration.to_dict()
        self._invalidate_dc()
        save_state(self.outdir, self.state)
        M_MIGRATIONS.inc()
        log.info("membership: %s of worker %d begun (epoch %d -> %d, "
                 "%d shard move(s))", migration.kind, migration.worker,
                 self.state.epoch, migration.epoch, len(migration.moves))
        return migration

    def catch_up(self, migration: Migration | None = None) -> Migration:
        """Adopter catch-up, resumable: every move not yet journaled
        ``done`` digest-verifies (and heals) the shard's block set,
        then the journal line lands in one atomic state write. The
        ``kill-during-reshard`` fault point fires between shard moves —
        a controller killed here resumes with the journal intact."""
        from ..models.cpd import adopt_shard_blocks

        mig = (migration if migration is not None
               else self.state.live_migration)
        if mig is None:
            raise ValueError("no migration in flight to catch up")
        dc = self.dc_view()
        for shard, _frm, to in mig.moves:
            if shard in mig.done:
                continue
            with Timer() as t:
                report = adopt_shard_blocks(self.graph(), dc, int(shard),
                                            self.outdir)
            H_CATCHUP.observe(t.interval)
            log.info("membership: worker %d caught up shard %d "
                     "(%d block(s), %d healed, %.3fs)", to, shard,
                     report["blocks"], len(report["healed"]), t.interval)
            mig.done.append(int(shard))
            self.state.migration = mig.to_dict()
            save_state(self.outdir, self.state)
            rule = faults.inject("kill-during-reshard")
            if rule is not None:
                log.error("fault: dying between reshard catch-up moves")
                if rule.mode == "exit":
                    os._exit(faults.KILL_EXIT_CODE)
                raise RuntimeError("kill-during-reshard fault injected")
        return mig

    def commit(self, migration: Migration | None = None
               ) -> MembershipState:
        """The epoch bump: one atomic ``membership.json`` write flips
        ownership and closes the window. Refuses while any move's
        catch-up is unjournaled; ``DOS_MEMBERSHIP_VERIFY=1`` (default)
        additionally re-checks every moved shard's block digests right
        before the flip — an adopter that rotted between catch-up and
        commit must not take ownership of rows it cannot serve."""
        mig = (migration if migration is not None
               else self.state.live_migration)
        if mig is None:
            raise ValueError("no migration in flight to commit")
        pending = [s for s, _f, _t in mig.moves if s not in mig.done]
        if pending:
            raise ValueError(
                f"cannot commit epoch {mig.epoch}: shards {pending} "
                "have not finished adopter catch-up")
        if env_flag("DOS_MEMBERSHIP_VERIFY", True):
            self._verify_moves(mig)
        owners = self._owners()
        for shard, _frm, to in mig.moves:
            owners[int(shard)] = int(to)
        self.state.owners = owners
        self.state.epoch = mig.epoch
        self.state.migration = None
        self._invalidate_dc()
        save_state(self.outdir, self.state)
        M_SHARDS_MOVED.inc(len(mig.moves))
        G_EPOCH.set(self.state.epoch)
        log.info("membership: epoch %d committed (%s of worker %d, %d "
                 "shard move(s))", self.state.epoch, mig.kind,
                 mig.worker, len(mig.moves))
        return self.state

    def _verify_moves(self, mig: Migration) -> None:
        from ..models.cpd import (
            check_block, read_manifest, shard_block_name,
        )

        try:
            manifest = read_manifest(self.outdir)
        except (OSError, ValueError):
            manifest = None
        blocks_meta = (manifest or {}).get("blocks", {})
        dc = self.base_dc
        bad = []
        for shard, _frm, _to in mig.moves:
            n_blocks = (dc.n_owned(int(shard)) + dc.block_size - 1
                        ) // dc.block_size
            for bid in range(n_blocks):
                fname = shard_block_name(int(shard), bid)
                status, reason = check_block(
                    os.path.join(self.outdir, fname),
                    blocks_meta.get(fname))
                if status not in ("ok", "unverified"):
                    bad.append((fname, status, reason))
        if bad:
            raise ValueError(
                f"pre-commit verify failed for epoch {mig.epoch}: "
                + "; ".join(f"{f} is {s} ({r})" for f, s, r in bad))

    def abort(self, migration: Migration | None = None
              ) -> MembershipState:
        """Close the window without the bump: ownership unchanged, the
        migration record cleared (and, for a join, the provisional
        roster entry dropped). Adopted blocks stay on disk — they are
        digest-valid copies of rows the fleet already serves, and the
        next begin/catch-up reuses them for free."""
        mig = (migration if migration is not None
               else self.state.live_migration)
        if mig is None:
            raise ValueError("no migration in flight to abort")
        if (mig.kind == "join"
                and mig.worker == len(self.state.workers) - 1):
            self.state.workers = list(self.state.workers)[:-1]
        self.state.migration = None
        self._invalidate_dc()
        save_state(self.outdir, self.state)
        M_ABORTED.inc()
        log.warning("membership: %s of worker %d aborted (epoch stays "
                    "%d)", mig.kind, mig.worker, self.state.epoch)
        return self.state

    # ----------------------------------------------------- convenience
    def join(self, host: str) -> MembershipState:
        """Plan + begin + catch up + commit one worker join."""
        mig = self.begin(self.plan_join(host))
        self.catch_up(mig)
        return self.commit(mig)

    def leave(self, wid: int, live=None) -> MembershipState:
        """Plan + begin + catch up + commit one worker leave. The
        caller drains the worker AFTER the commit (its shards have new
        owners by then; in-flight batches it already read are answered
        before the stop token wins — drain-free by construction).
        ``live`` restricts adopters to known-serving workers (see
        :meth:`plan_leave`)."""
        mig = self.begin(self.plan_leave(wid, live=live))
        self.catch_up(mig)
        return self.commit(mig)

    def resume(self) -> MembershipState | None:
        """Finish a migration a crashed controller left in flight
        (catch-up journal intact → only the missing tail re-runs).
        Returns the committed state, or None when nothing was in
        flight."""
        mig = self.state.live_migration
        if mig is None:
            return None
        log.info("membership: resuming %s of worker %d toward epoch %d "
                 "(%d/%d shard(s) already caught up)", mig.kind,
                 mig.worker, mig.epoch, len(mig.done), len(mig.moves))
        self.catch_up(mig)
        return self.commit(mig)
