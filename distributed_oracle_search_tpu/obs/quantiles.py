"""Sliding-window streaming quantiles with trace exemplars.

The registry's histograms (``obs.metrics``) are process-lifetime
cumulative — perfect for offline snapshots, useless for "what is p99
*right now*" on a resident ``dos-serve``: after a day of traffic one
slow minute vanishes into millions of old samples. This module is the
live half: a :class:`SlidingQuantiles` keeps a ring of rotating time
buckets (window/bucket granularity, default 60 s over 6 buckets), and
quantile reads sort only the samples that fell inside the window — the
scrape endpoint (``obs.http``) exposes them as
``<name>_window{quantile="0.99"}`` gauges next to the cumulative
histogram series.

**Exemplars:** every window additionally remembers the single
worst-case observation it saw and the ``trace_id`` that observation was
stamped with (the same id ``obs.trace`` propagates over the wire and
into Perfetto sidecars). A bad p99 on the scrape is therefore one copy-
paste away from its timeline: open the merged trace and search for the
exemplar's id. Observations without an id still count toward the
quantiles; they just can't win the exemplar slot while an identified
observation is worse-or-equal-visible (an id-less worst is kept too —
better an anonymous exemplar than none).

Cost discipline: ``observe`` is a lock + list append (bounded by
reservoir sampling at ``max_samples`` per bucket), cheap enough to run
unconditionally next to the histogram's ``observe`` on the serve hot
path. Sorting happens only on read (scrape/statusz cadence, not request
cadence).

Instrumented names (the standing windows every process feeds):
``serve_request_seconds`` (frontend end-to-end),
``serve_dispatch_seconds`` (frontend dispatch lanes, hedges included),
``worker_search_seconds`` (engine steady-state search).

Env knobs: ``DOS_OBS_WINDOW_S`` (window length, default 60),
``DOS_OBS_WINDOW_BUCKETS`` (rotation granularity, default 6).
"""

from __future__ import annotations

import math
import random
import time

from ..utils.env import env_cast
from ..utils.locks import OrderedLock

#: the quantiles every window reports (scrape + statusz)
DEFAULT_QUANTILES = (0.5, 0.95, 0.99)


class _Bucket:
    """One rotation slot: samples + the worst observation seen."""

    __slots__ = ("epoch", "samples", "seen", "worst", "worst_trace")

    def __init__(self):
        self.reset(-1)

    def reset(self, epoch: int) -> None:
        self.epoch = epoch
        self.samples: list[float] = []
        self.seen = 0
        self.worst = None
        self.worst_trace = ""


class SlidingQuantiles:
    """Streaming quantiles over the last ``window_s`` seconds.

    A ring of ``buckets`` time slots, each ``window_s / buckets`` wide;
    an ``observe`` lands in the slot of its epoch (stale slots are
    recycled in place, so rotation is O(1) and needs no timer thread).
    Reads collect every in-window slot's samples and answer
    nearest-rank quantiles; with more than ``max_samples`` observations
    per slot, reservoir sampling keeps an unbiased subset (the exemplar
    is exact regardless — the worst observation always wins its slot).
    """

    def __init__(self, window_s: float = 60.0, buckets: int = 6,
                 max_samples: int = 512, clock=time.monotonic):
        if window_s <= 0 or buckets <= 0 or max_samples <= 0:
            raise ValueError("window_s, buckets, max_samples must be > 0")
        self.window_s = float(window_s)
        self.n_buckets = int(buckets)
        self.bucket_s = self.window_s / self.n_buckets
        self.max_samples = int(max_samples)
        self.clock = clock
        self._ring = [_Bucket() for _ in range(self.n_buckets)]
        self._rng = random.Random(0x0b5)
        self._lock = OrderedLock("quantiles.SlidingQuantiles")

    # ------------------------------------------------------------ write
    def observe(self, v: float, trace_id: str | None = None,
                now: float | None = None) -> None:
        now = self.clock() if now is None else now
        epoch = int(now // self.bucket_s)
        with self._lock:
            b = self._ring[epoch % self.n_buckets]
            if b.epoch != epoch:
                b.reset(epoch)
            b.seen += 1
            if len(b.samples) < self.max_samples:
                b.samples.append(v)
            else:
                # reservoir: every observation keeps an equal chance of
                # being in the retained subset
                i = self._rng.randrange(b.seen)
                if i < self.max_samples:
                    b.samples[i] = v
            if b.worst is None or v > b.worst or (
                    v == b.worst and trace_id and not b.worst_trace):
                b.worst = v
                b.worst_trace = trace_id or ""

    # ------------------------------------------------------------- read
    def _live_locked(self, now: float) -> list[_Bucket]:
        epoch = int(now // self.bucket_s)
        lo = epoch - self.n_buckets + 1
        return [b for b in self._ring if lo <= b.epoch <= epoch]

    def count(self, now: float | None = None) -> int:
        now = self.clock() if now is None else now
        with self._lock:
            return sum(b.seen for b in self._live_locked(now))

    def quantiles(self, qs=DEFAULT_QUANTILES,
                  now: float | None = None) -> dict[float, float] | None:
        """Nearest-rank quantiles over the window; None when empty."""
        now = self.clock() if now is None else now
        with self._lock:
            data = [v for b in self._live_locked(now) for v in b.samples]
        if not data:
            return None
        data.sort()
        n = len(data)
        out = {}
        for q in qs:
            # nearest-rank: ceil(q*n) - 1
            idx = max(0, min(n - 1, math.ceil(q * n) - 1))
            out[q] = data[idx]
        return out

    def worst(self, now: float | None = None):
        """``(value, trace_id)`` of the window's worst observation, or
        None when the window is empty. The trace_id may be ``""`` when
        the worst observation carried none."""
        now = self.clock() if now is None else now
        with self._lock:
            live = [b for b in self._live_locked(now)
                    if b.worst is not None]
            if not live:
                return None
            b = max(live, key=lambda b: b.worst)
            return (b.worst, b.worst_trace)

    def snapshot(self, now: float | None = None) -> dict:
        now = self.clock() if now is None else now
        qs = self.quantiles(now=now)
        w = self.worst(now=now)
        out = {
            "window_s": self.window_s,
            "count": self.count(now=now),
            "quantiles": ({f"p{int(q * 100)}": v for q, v in qs.items()}
                          if qs else {}),
        }
        if w is not None:
            out["worst"] = {"value": w[0], "trace_id": w[1]}
        return out


class QuantileWindows:
    """Name-keyed registry of :class:`SlidingQuantiles` — the live-
    quantile analog of :class:`~.metrics.MetricsRegistry`. Windows are
    get-or-create so instrumented modules can observe without
    declaring; the scrape endpoint renders every window that has ever
    observed."""

    def __init__(self, window_s: float | None = None,
                 buckets: int | None = None, max_samples: int = 512,
                 clock=time.monotonic):
        self.window_s = (window_s if window_s is not None
                         else env_cast("DOS_OBS_WINDOW_S", 60.0, float))
        self.buckets = (buckets if buckets is not None
                        else env_cast("DOS_OBS_WINDOW_BUCKETS", 6, int))
        self.max_samples = max_samples
        self.clock = clock
        self._windows: dict[str, SlidingQuantiles] = {}
        self._lock = OrderedLock("quantiles.QuantileWindows")

    def window(self, name: str) -> SlidingQuantiles:
        with self._lock:
            w = self._windows.get(name)
            if w is None:
                w = SlidingQuantiles(self.window_s, self.buckets,
                                     self.max_samples, clock=self.clock)
                self._windows[name] = w
            return w

    def observe(self, name: str, v: float,
                trace_id: str | None = None) -> None:
        self.window(name).observe(v, trace_id=trace_id)

    def snapshot(self) -> dict:
        with self._lock:
            windows = dict(self._windows)
        return {name: w.snapshot() for name, w in sorted(windows.items())}

    def to_prometheus(self) -> str:
        """Live-quantile gauges: ``<name>_window{quantile="0.99"}``
        samples plus a ``<name>_window_worst`` exemplar sample whose
        ``trace_id`` label links the worst observation to its Perfetto
        timeline, and a ``<name>_window_count`` volume gauge."""
        with self._lock:
            windows = dict(self._windows)
        lines = []
        for name, w in sorted(windows.items()):
            qs = w.quantiles()
            lines.append(f"# TYPE {name}_window gauge")
            lines.append(
                f"# HELP {name}_window live quantiles over the last "
                f"{w.window_s:g}s")
            if qs:
                for q, v in sorted(qs.items()):
                    lines.append(
                        f'{name}_window{{quantile="{q:g}"}} {v:.9g}')
            lines.append(f"{name}_window_count {w.count()}")
            worst = w.worst()
            if worst is not None:
                v, tid = worst
                lines.append(
                    f'{name}_window_worst{{trace_id="{tid}"}} {v:.9g}')
        return "\n".join(lines) + ("\n" if lines else "")

    def reset(self) -> None:
        """Drop every window (tests only)."""
        with self._lock:
            self._windows.clear()


#: process-wide default windows — instrumented modules and the scrape
#: endpoint share it unless a test injects its own
WINDOWS = QuantileWindows()


def observe(name: str, v: float, trace_id: str | None = None) -> None:
    WINDOWS.observe(name, v, trace_id=trace_id)
