"""Black-box flight recorder: a bounded on-disk ring of what happened.

Metrics answer "how much"; traces answer "where did one batch go"; what
an incident review needs first is *what happened, in order* — the fault
fired, the burn alert tripped, the breaker opened, the supervisor
respawned. This module is that tape:

* a module-level **event bus** (:func:`emit`) every subsystem posts
  structured events to — epoch swaps (``serving.frontend``), breaker
  transitions (``transport.resilience``), respawns
  (``worker.supervisor``), membership commits, BUSY storms and SLO
  alert flips (``obs.slo`` / telemetry ingest), fault-harness fires
  (``testing.faults``). Events are plain dicts ``{"ts", "kind", ...}``
  — unknown fields are the reader's to ignore, the annotation contract
  of every other codec here;
* a bounded in-memory ring of recent events (:func:`drain_pending`) the
  telemetry publisher drains into its ticks, so a *worker's* events
  reach the head's tape even across a process boundary;
* :class:`FlightRecorder` — the on-disk ring: JSONL segments
  (``rec-<seq>.jsonl``) written atomically (``utils.atomicio``), rotated
  at ``DOS_RECORDER_SEGMENT_BYTES`` and capped at
  ``DOS_RECORDER_BYTES`` total (oldest segments deleted first — a
  flight recorder overwrites its own tail, it never fills a disk);
* :func:`replay` — read the ring back into one timestamp-ordered
  timeline, skipping a torn tail line (a crash mid-flush must not make
  the tape unreadable; that is the tape's whole job), and
  :func:`render_timeline` — the ``dos-obs replay`` text view, with
  Perfetto trace events merged in by ``trace_id``.

Env knobs: ``DOS_RECORDER_BYTES`` (ring budget, default 4 MiB),
``DOS_RECORDER_SEGMENT_BYTES`` (rotation size, default 64 KiB),
``DOS_RECORDER_FLUSH_EVERY`` (records buffered between disk flushes,
default 16).
"""

from __future__ import annotations

import collections
import glob
import json
import os
import re
import time

from ..utils.atomicio import atomic_replace_bytes, atomic_write_bytes
from ..utils.env import env_cast
from ..utils.locks import OrderedLock
from ..utils.log import get_logger
from . import metrics as obs_metrics

log = get_logger(__name__)

M_EVENTS = obs_metrics.counter(
    "recorder_events_total", "structured events posted to the bus")
M_RECORDS = obs_metrics.counter(
    "recorder_records_total", "records written to the on-disk ring")
M_SEGMENTS = obs_metrics.counter(
    "recorder_segments_total", "ring segments finalized (rotations)")
M_TORN = obs_metrics.counter(
    "recorder_torn_lines_total",
    "torn tail lines skipped while replaying the ring")
G_BYTES = obs_metrics.gauge(
    "recorder_ring_bytes", "bytes currently held by the on-disk ring")

#: segment filename pattern — the seq number orders the ring on disk
_SEG_RE = re.compile(r"rec-(\d{8})\.jsonl$")


# ------------------------------------------------------------- event bus

#: recent events awaiting a telemetry tick (bounded: an idle publisher
#: must not grow memory; the tape on disk is the durable copy)
_PENDING_MAX = 256
_pending: collections.deque = collections.deque(maxlen=_PENDING_MAX)
_pending_lock = OrderedLock("recorder.pending")

_recorder: "FlightRecorder | None" = None


def set_recorder(rec: "FlightRecorder | None") -> None:
    """Install the process's on-disk recorder (None detaches). Events
    emitted before a recorder exists still reach the pending ring."""
    global _recorder
    _recorder = rec


def get_recorder() -> "FlightRecorder | None":
    return _recorder


def emit(kind: str, ts: float | None = None, **fields) -> dict:
    """Post one structured event to the bus: it lands in the pending
    ring (for the next telemetry tick) and, when an on-disk recorder is
    installed, on the tape. Cheap and never raises — instrumentation
    must not add failure modes to the paths it watches."""
    ev = {"ts": float(ts if ts is not None else time.time()),
          "kind": str(kind)}
    ev.update(fields)
    M_EVENTS.inc()
    with _pending_lock:
        _pending.append(ev)
    rec = _recorder
    if rec is not None:
        try:
            rec.record_event(ev)
        except Exception as e:  # noqa: BLE001 — a full disk must not
            # crash the breaker/supervisor path that emitted
            log.warning("flight recorder write failed: %s", e)
    return ev


def drain_pending() -> list[dict]:
    """Take (and clear) the pending events — the telemetry publisher's
    per-tick drain."""
    with _pending_lock:
        out = list(_pending)
        _pending.clear()
    return out


# ------------------------------------------------------------- the tape

class FlightRecorder:
    """Bounded on-disk ring of telemetry ticks + structured events.

    Records buffer in memory and flush as atomic segment rewrites
    (``atomic_replace_bytes`` — transient-by-design: the ring
    overwrites itself, fsync durability buys nothing here) every
    ``flush_every`` records; a finalized segment gets the durable
    ``atomic_write_bytes`` treatment once, at rotation. A crash loses
    at most the unflushed buffer — and :func:`replay` skips a torn
    tail line, so a crash mid-rename never makes the tape unreadable.
    """

    def __init__(self, dirname: str, max_bytes: int | None = None,
                 segment_bytes: int | None = None,
                 flush_every: int | None = None, clock=time.time):
        self.dirname = dirname
        self.max_bytes = int(max_bytes if max_bytes is not None else
                             env_cast("DOS_RECORDER_BYTES", 4 << 20, int))
        self.segment_bytes = int(
            segment_bytes if segment_bytes is not None else
            env_cast("DOS_RECORDER_SEGMENT_BYTES", 64 << 10, int))
        self.flush_every = int(
            flush_every if flush_every is not None else
            env_cast("DOS_RECORDER_FLUSH_EVERY", 16, int))
        self.clock = clock
        os.makedirs(dirname, exist_ok=True)
        self._lock = OrderedLock("recorder.FlightRecorder")
        existing = self._segments()
        self._seq = (self._seg_seq(existing[-1]) + 1) if existing else 0
        self._lines: list[str] = []     # current segment, in memory
        self._cur_bytes = 0
        self._unflushed = 0
        self._records = 0
        G_BYTES.set(self._disk_bytes())

    # ------------------------------------------------------------ layout
    def _segments(self) -> list[str]:
        paths = glob.glob(os.path.join(self.dirname, "rec-*.jsonl"))
        return sorted(p for p in paths if _SEG_RE.search(p))

    @staticmethod
    def _seg_seq(path: str) -> int:
        m = _SEG_RE.search(path)
        return int(m.group(1)) if m else -1

    def _seg_path(self, seq: int) -> str:
        return os.path.join(self.dirname, f"rec-{seq:08d}.jsonl")

    def _disk_bytes(self) -> int:
        total = 0
        for p in self._segments():
            try:
                total += os.path.getsize(p)
            except OSError:
                pass
        return total

    # ------------------------------------------------------------- write
    def record_event(self, ev: dict) -> None:
        self._record({"rec": "event", **ev})

    def record_tick(self, tick: dict) -> None:
        """One telemetry tick on the tape — the window snapshots are
        dropped (the timeseries store is their home; the tape keeps the
        tick's identity, counters and events for replay context)."""
        slim = {k: v for k, v in tick.items() if k != "windows"}
        self._record({"rec": "tick",
                      "ts": float(tick.get("ts") or self.clock()),
                      **slim})

    def _record(self, rec: dict) -> None:
        line = json.dumps(rec, sort_keys=True, default=str) + "\n"
        with self._lock:
            self._lines.append(line)
            self._cur_bytes += len(line)
            self._unflushed += 1
            self._records += 1
            M_RECORDS.inc()
            if self._cur_bytes >= self.segment_bytes:
                self._rotate_locked()
            elif self._unflushed >= self.flush_every:
                self._flush_locked(durable=False)

    def _flush_locked(self, durable: bool) -> None:
        if not self._lines:
            return
        data = "".join(self._lines).encode()
        write = atomic_write_bytes if durable else atomic_replace_bytes
        write(self._seg_path(self._seq), data)
        self._unflushed = 0
        G_BYTES.set(self._disk_bytes())

    def _rotate_locked(self) -> None:
        self._flush_locked(durable=True)
        M_SEGMENTS.inc()
        self._seq += 1
        self._lines = []
        self._cur_bytes = 0
        # ring bound: oldest segments fall off first
        segs = self._segments()
        total = self._disk_bytes()
        while segs and total > self.max_bytes:
            victim = segs.pop(0)
            try:
                total -= os.path.getsize(victim)
                os.remove(victim)
                log.info("flight recorder ring: dropped %s",
                         os.path.basename(victim))
            except OSError as e:
                log.warning("cannot drop ring segment %s: %s", victim, e)
                break
        G_BYTES.set(max(total, 0))

    def flush(self) -> None:
        with self._lock:
            self._flush_locked(durable=False)

    def close(self) -> None:
        with self._lock:
            self._flush_locked(durable=True)

    # ------------------------------------------------------------ status
    def statusz(self) -> dict:
        with self._lock:
            segs = self._segments()
            return {"dir": self.dirname,
                    "segments": len(segs) + (1 if self._lines else 0),
                    "records": self._records,
                    "bytes": self._disk_bytes(),
                    "max_bytes": self.max_bytes,
                    "seq": self._seq}


# ------------------------------------------------------------- replay

def segment_paths(dirname: str) -> list[str]:
    """The ring's segment files, oldest first."""
    paths = glob.glob(os.path.join(dirname, "rec-*.jsonl"))
    return sorted(p for p in paths if _SEG_RE.search(p))


def replay(dirname: str, since: float | None = None,
           until: float | None = None) -> list[dict]:
    """Read the ring back as one timestamp-ordered record list. A torn
    tail line (crash mid-flush) is skipped and counted; an undecodable
    line mid-segment raises — that is corruption, not a torn tail, and
    must not silently vanish from an incident review."""
    records: list[dict] = []
    for path in segment_paths(dirname):
        with open(path, "rb") as f:
            raw = f.read()
        lines = raw.split(b"\n")
        if lines and lines[-1] == b"":
            lines.pop()
        for i, line in enumerate(lines):
            try:
                rec = json.loads(line)
            except ValueError:
                if i == len(lines) - 1:
                    M_TORN.inc()
                    log.warning("replay: skipping torn tail line in %s",
                                os.path.basename(path))
                    continue
                raise ValueError(
                    f"{path}: undecodable record mid-segment "
                    f"(line {i + 1})")
            if not isinstance(rec, dict):
                continue
            ts = rec.get("ts")
            if not isinstance(ts, (int, float)):
                continue
            if since is not None and ts < since:
                continue
            if until is not None and ts > until:
                continue
            records.append(rec)
    records.sort(key=lambda r: r.get("ts", 0.0))
    return records


def _trace_entries(trace_paths, trace_ids: set) -> list[dict]:
    """Perfetto events from ``merge_traces``-style inputs whose
    ``trace_id`` matches a record on the tape, as timeline rows
    (trace ``ts`` is wall-clock microseconds)."""
    from .fleet import _events_of
    out = []
    for path in trace_paths:
        paths = (sorted(glob.glob(os.path.join(path, "*.trace")))
                 if os.path.isdir(path) else [path])
        for p in paths:
            for ev in _events_of(p):
                if not isinstance(ev, dict):
                    continue
                tid = (ev.get("args") or {}).get("trace_id", "")
                if not tid or tid not in trace_ids:
                    continue
                ts = ev.get("ts")
                if not isinstance(ts, (int, float)):
                    continue
                out.append({"rec": "span", "ts": ts / 1e6,
                            "kind": ev.get("name", "span"),
                            "trace_id": tid,
                            "dur_ms": round(
                                float(ev.get("dur", 0)) / 1e3, 3)})
    return out


def render_timeline(records: list[dict],
                    trace_paths: list[str] | None = None) -> str:
    """The ``dos-obs replay`` text view: one line per record, relative
    timestamps, event fields inline. With ``trace_paths``, Perfetto
    spans whose ``trace_id`` appears on the tape are merged in — the
    incident's batches next to the incident's events."""
    rows = list(records)
    if trace_paths:
        ids = {r["trace_id"] for r in rows
               if isinstance(r.get("trace_id"), str) and r["trace_id"]}
        rows.extend(_trace_entries(trace_paths, ids))
        rows.sort(key=lambda r: r.get("ts", 0.0))
    if not rows:
        return "(empty tape)"
    t0 = rows[0].get("ts", 0.0)
    lines = []
    skip = ("ts", "rec", "kind")
    for r in rows:
        rec = r.get("rec", "event")
        kind = r.get("kind", r.get("source", "?"))
        rest = " ".join(f"{k}={r[k]}" for k in sorted(r)
                        if k not in skip and not isinstance(
                            r[k], (dict, list)))
        lines.append(f"+{r.get('ts', t0) - t0:9.3f}s  "
                     f"{rec:5s} {kind:18s} {rest}".rstrip())
    return "\n".join(lines)
