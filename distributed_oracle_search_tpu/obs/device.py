"""Per-compiled-program XLA cost/memory capture.

ROADMAP item 1 (the Pallas walk kernel) needs *measured* per-program
FLOPs, bytes-accessed, and HBM footprints before anyone can claim a
kernel closed the roofline gap — a wall-clock number alone cannot say
whether the walk is bandwidth-bound or issue-bound. This module records
XLA's own analyses for the programs the engine actually runs:

* :func:`analyze` — AOT-lower a jitted callable with concrete args and
  read ``cost_analysis()`` (FLOPs, bytes accessed) plus — after an AOT
  ``compile()`` — ``memory_analysis()`` (argument/output/temp bytes;
  their sum is the program's HBM footprint). Returns a plain dict, or
  None when the backend exposes neither (host CPU exposes costs but may
  return no memory stats; both absences degrade, never raise).
* :func:`capture` — :func:`analyze` + record under a program key.
  ``worker.engine`` calls it once per entry of its existing compiled-
  program cache (the ``_jit_seen`` keys), so a resident worker
  accumulates exactly one entry per distinct program, and the capture
  cost (one re-lower; the compile hits XLA's cache) is paid once,
  off the steady-state path.

The store exports three ways: :func:`snapshot` (JSON — ``bench.py``
embeds it in ``BENCH_DETAIL.json`` and derives achieved-vs-peak
gather-bandwidth rooflines), :func:`to_prometheus` (labeled gauges on
the ``/metrics`` scrape), and the ``device_programs_analyzed`` registry
gauge (the fleet aggregator's cheap cardinality signal).

``DOS_DEVICE_COSTS=0`` disables capture entirely (the engine then skips
even the key lookup).
"""

from __future__ import annotations

import os
import threading

from ..utils.env import env_flag
from ..utils.log import get_logger
from . import metrics as obs_metrics

log = get_logger(__name__)

G_PROGRAMS = obs_metrics.gauge(
    "device_programs_analyzed",
    "compiled programs with a captured XLA cost/memory analysis")

_COSTS: dict[str, dict] = {}
_lock = threading.Lock()

#: memory_analysis attributes summed into the HBM footprint
_MEM_FIELDS = ("argument_size_in_bytes", "output_size_in_bytes",
               "temp_size_in_bytes")


def enabled() -> bool:
    return env_flag("DOS_DEVICE_COSTS", True)


def analyze(fn, *args, **kwargs) -> dict | None:
    """XLA cost + memory analysis of ``fn(*args, **kwargs)``.

    ``fn`` must be a ``jax.jit`` wrapper (it has ``.lower``); a bare
    callable is jitted first. Any failure — old jaxlib without the AOT
    API, a backend refusing analysis, a donation mismatch — returns
    None with a debug log, never an exception into the serving path.
    """
    try:
        if not hasattr(fn, "lower"):
            import jax
            fn = jax.jit(fn)
        lowered = fn.lower(*args, **kwargs)
        out: dict = {}
        try:
            cost = lowered.cost_analysis()
            if isinstance(cost, (list, tuple)):   # per-device lists on
                cost = cost[0] if cost else {}    # some jax versions
            if cost:
                out["flops"] = float(cost.get("flops", 0.0))
                out["bytes_accessed"] = float(
                    cost.get("bytes accessed", 0.0))
        except Exception as e:  # noqa: BLE001 — degrade per analysis
            log.debug("cost_analysis unavailable: %s", e)
        try:
            mem = lowered.compile().memory_analysis()
            if mem is not None:
                for f in _MEM_FIELDS:
                    out[f.replace("_size_in_bytes", "_bytes")] = int(
                        getattr(mem, f, 0))
                out["hbm_bytes"] = sum(
                    int(getattr(mem, f, 0)) for f in _MEM_FIELDS)
                out["generated_code_bytes"] = int(
                    getattr(mem, "generated_code_size_in_bytes", 0))
        except Exception as e:  # noqa: BLE001
            log.debug("memory_analysis unavailable: %s", e)
        return out or None
    except Exception as e:  # noqa: BLE001 — capture is advisory
        log.debug("program analysis failed: %s", e)
        return None


def capture(key, fn, *args, **kwargs) -> dict | None:
    """Analyze once per ``key`` and record the result. Returns the
    stored entry (existing or new), or None when disabled/failed."""
    if not enabled():
        return None
    skey = key if isinstance(key, str) else repr(key)
    with _lock:
        if skey in _COSTS:
            return _COSTS[skey]
    entry = analyze(fn, *args, **kwargs)
    if entry is None:
        return None
    with _lock:
        _COSTS.setdefault(skey, entry)
        G_PROGRAMS.set(len(_COSTS))
        return _COSTS[skey]


def derive_bandwidth(entry: dict | None, seconds: float,
                     peak_gbps: float | None) -> dict | None:
    """Fold a measured wall-clock into a captured analysis: achieved
    GB/s off ``bytes_accessed`` plus the utilization fraction against a
    calibrated HBM peak. The roofline arithmetic the bench used to
    inline for the XLA walk, shared here so the fused Pallas kernel's
    capture derives the SAME figures (kernel-vs-kernel comparisons must
    not differ in the denominator math). Mutates and returns ``entry``;
    None in (no analysis / no timing) degrades to None out."""
    if not entry or seconds <= 0 or "bytes_accessed" not in entry:
        return entry
    gbps = entry["bytes_accessed"] / seconds / 1e9
    entry["achieved_gbps"] = round(gbps, 2)
    if peak_gbps and peak_gbps > 0:
        entry["hbm_bw_utilization"] = round(gbps / peak_gbps, 4)
    return entry


def record(key, entry: dict) -> None:
    """Store an externally computed analysis under ``key`` (bench uses
    this for programs it lowers itself)."""
    with _lock:
        _COSTS[key if isinstance(key, str) else repr(key)] = dict(entry)
        G_PROGRAMS.set(len(_COSTS))


def snapshot() -> dict:
    """``{program_key: {flops, bytes_accessed, hbm_bytes, ...}}``."""
    with _lock:
        return {k: dict(v) for k, v in sorted(_COSTS.items())}


def to_prometheus() -> str:
    """Labeled per-program gauges for the scrape endpoint."""
    with _lock:
        costs = {k: dict(v) for k, v in sorted(_COSTS.items())}
    if not costs:
        return ""
    lines = []
    for field, help_ in (
            ("flops", "XLA cost_analysis FLOPs per program execution"),
            ("bytes_accessed", "XLA cost_analysis bytes accessed"),
            ("hbm_bytes", "argument+output+temp device memory")):
        samples = [(k, v[field]) for k, v in costs.items()
                   if field in v]
        if not samples:
            continue
        lines.append(f"# TYPE device_program_{field} gauge")
        lines.append(f"# HELP device_program_{field} {help_}")
        for key, val in samples:
            esc = key.replace("\\", "\\\\").replace('"', '\\"')
            lines.append(
                f'device_program_{field}{{program="{esc}"}} {val:.10g}')
    return "\n".join(lines) + ("\n" if lines else "")


def reset() -> None:
    """Drop every captured program (tests only)."""
    with _lock:
        _COSTS.clear()
        G_PROGRAMS.set(0)
